"""Continuous batching — arrival-driven serving over the cached forward.

The r2 serving stack is batch-static: every sequence in a
``greedy_generate`` call starts and ends together.  Real serving is
arrival-driven; the structural piece this module adds (VERDICT r2 next
item #7) is the SLOT engine:

- the KV cache is ``n_slots`` independent batch rows with PER-SLOT
  positions — a slot is admitted, decodes, retires, and is re-admitted
  without disturbing its neighbors;
- an arriving request is prefilled at batch 1 (prompt right-padded to a
  compile bucket) and its K/V panel is scattered into a free slot's
  rows — admission never re-traces the decode executable;
- decode advances ALL slots in one executable with per-row positions:
  rope takes a [B, 1] position matrix, the cache write is a vmapped
  ``dynamic_update_slice`` (one row offset per slot, lowered to a
  scatter), and the causal/unwritten mask compares each row's own
  position;
- host interaction is STRIDE-amortized: ``lax.scan`` runs N decode
  steps per dispatch and the host fetches one [stride, B] token block
  — under the async TPU tunnel a per-step fetch costs ~100× the step
  itself (the r2 speculative host loop measured exactly that), and
  even locally it serializes dispatch.  Admission/retirement granularity
  is the stride.

On the paged pool two serving fast paths ride the page tables (the
r6 tentpole):

- REFCOUNTED PREFIX CACHING (``prefix_cache=True``): prompt
  page-blocks are chain-hashed at submit; admission aliases matching
  read-only pages under a refcount instead of re-prefilling and
  re-storing them, so N-way shared-prefix traffic pays prefill once
  and holds one copy of the shared pages.  A page frees only on
  last-owner release; registered pages are retained at refcount 0
  (LRU-reclaimed under pool pressure) so later same-prefix requests
  still hit.  The pool invariant generalizes: a page may have MANY
  owners, but free ∪ allocated still partitions {1..total_pages}
  exactly, and refcount == owner count at every tick.
- CHUNKED PREFILL (``chunked_prefill=True``): long prompts admit as
  page-aligned chunks written straight into the slot's pool pages and
  interleaved with decode ticks — history attention runs through the
  paged kernel, the chunk's own keys attend exactly (causal
  partials), and the two merge as flash-decoding partials — so a
  full-wave ``[k, bucket]`` prefill never stalls every active decode
  slot for a whole forward.  Per-tick decode stall is tracked
  (``stall_ms``; ``serve_decode_stall_ms`` in a passed registry).

The paged engine is MESH-NATIVE (the r7 tentpole): pass
``mesh=make_serve_mesh(tp)`` and every executable runs under
``shard_map`` on a ``("tp",)`` mesh — the page pool and both paged
kernels shard over KV heads (per-chip pools hold Hkv/tp heads; the
per-head attention math is embarrassingly parallel, so the kernels
run unchanged on local shapes), weights split megatron-style with a
per-layer psum and one lm_head all-gather per token pick, while page
tables, refcounts, the prefix registry, and all per-slot host vectors
stay REPLICATED — the admission/eviction/chunking logic above is
sharding-oblivious and tokens are bit-identical to the unsharded
engine.  dp scale-out is :class:`DataParallelServePool`: independent
engine replicas behind one admission queue, no cross-replica
collective ever.

The serving stack is CHAOS-HARDENED (the r9 tentpole): every
``_Request`` keeps its prompt + accepted tokens host-side, so any
fault resolves to bit-exact greedy REPLAY (prompt + accepted, the
remaining budget — prefix-cache-accelerated when the original pages
are registered).  The engine defends itself per tick: non-finite
logits quarantine the offending SLOT (never the batch), a watchdog
(``tick_deadline_s``) declares a stalled replica dead instead of
letting ``drain()`` wedge, unfittable admissions are SHED (failed
loudly) instead of deadlocking the FIFO queue, and repeated
zero-acceptance verify ticks degrade γ→0 engine-wide.
:class:`DataParallelServePool` adds replica failover: a dead replica's
resident requests replay onto healthy replicas with exactly-once
completion, driven either by the engine raising
:class:`~kubegpu_tpu.obs.chaos.ReplicaDeadError` or by a control-plane
gang eviction observed on the apiserver watch stream
(``watch_health``).  ``obs/chaos.py`` injects all of these faults
deterministically from a seed.

Correctness contract: slots are independent batch rows — a request's
attention/FFN math never mixes with its neighbors'.  Tokens are
bit-identical to a solo ``greedy_generate`` at the tested
configurations (f32, small slot counts, asserted with staggered
arrivals); at other batch sizes XLA may choose different reduction
orders, which can flip a near-degenerate argmax tie (observed once at
n_slots=4 on an untrained f32 model — the same chunked-vs-stepwise
caveat spec decoding documents).  Right-pad garbage is never
attended: pad rows sit at positions ≥ the row's true length, the
per-row mask hides ``k_pos > q_pos``, and generation overwrites each
row before its position becomes visible (the same
overwrite-before-attend invariant the speculative verifier relies
on).
"""

from __future__ import annotations

import functools
import hashlib
import time
from collections import deque
from dataclasses import dataclass, field, replace

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kubegpu_tpu.models.decode import (
    _attend_buffer_partials,
    _attn_finish,
    _dense_ffn,
    _project_qkv,
    init_kv_cache,
)
from kubegpu_tpu.models.llama import LlamaConfig, _rmsnorm
from kubegpu_tpu.obs.chaos import (
    DispatchFailure,
    ReplicaDeadError,
    TickStallError,
)
from kubegpu_tpu.obs.cost import CostLedger
from kubegpu_tpu.ops.flash_attention import NEG_INF
from kubegpu_tpu.parallel.sharding import donating_jit


# ---------------------------------------------------------------------------
# Buffer donation (the HBM-lean serving contract)
# ---------------------------------------------------------------------------
# Every executable donates the arguments the engine rebinds from its
# outputs each dispatch — the page pool / dense cache AND the per-slot
# device mirrors — so XLA aliases output buffers onto input buffers
# instead of holding both live (2× steady-state KV HBM without it).
# These tables are the single source of truth: the engine fns wrap
# through donating_jit with exactly these names, donation_report()
# verifies the compiled input_output_aliases cover them, and the
# cb_hbm_donation bench A/Bs them off via the ``donate`` knob.

PAGED_DONATED = {
    "decode_block": ("pool", "tokens", "pos"),
    "prefill_wave": (),
    "adopt_wave": ("pool", "first_toks", "tokens", "pos", "temps"),
    "prefill_chunk": ("pool",),
    "activate_slot": ("first_toks", "tokens", "pos", "temps"),
    "verify_block": ("pool", "tokens", "pos"),
    "decode_fused": ("pool", "tokens", "pos"),
    "verify_fused": ("pool", "tokens", "pos"),
    # migration: export keeps the source pool live (the exporting
    # engine serves on); import donates ONLY the pool — the uploaded
    # chain leaves are shaped [L, max_pages, ...], not pool-shaped,
    # so they can never alias a pool output
    "export_chain": (),
    "import_chain": ("pool",),
}

DENSE_DONATED = {
    "decode_block": ("cache", "tokens", "pos"),
    "prefill_wave": (),
    "adopt_wave": ("cache", "first_toks", "tokens", "pos", "temps"),
}


# ---------------------------------------------------------------------------
# Per-row-position forward (the continuous-batching decode step)
# ---------------------------------------------------------------------------

def _attend_rows_buffered(q: jax.Array, ck: jax.Array, cv: jax.Array,
                          bk: jax.Array, bv: jax.Array,
                          flush_pos: jax.Array, j: jax.Array) -> jax.Array:
    """Grouped cached attention with PER-ROW positions over a dense
    cache PLUS the in-block write buffer.

    q: [B, Hq, 1, D]; cache [B, Hkv, S, D], valid where
    ``k_pos < flush_pos[b]`` (everything flushed before this block);
    buffer [B, Hkv, stride, D] holding this block's keys, valid at
    buffer index ``j' <= j`` (the SHARED in-block step — buffer entry
    j' is row b's logical position ``flush_pos[b] + j'``).  Softmax is
    permutation-invariant over the key set, so splitting the keys
    between cache and buffer changes nothing semantically; the point is
    that buffer writes land at the shared index j (one
    dynamic_update_slice, no scatter)."""
    b, hq, t, d = q.shape
    hkv, s = ck.shape[1], ck.shape[2]
    stride = bk.shape[2]
    qg = q.reshape(b, hkv, hq // hkv, t, d)
    scale = d ** -0.5
    sc = jnp.einsum("bkgtd,bksd->bkgts", qg, ck,
                    preferred_element_type=jnp.float32)
    sb = jnp.einsum("bkgtd,bksd->bkgts", qg, bk,
                    preferred_element_type=jnp.float32)
    scores = jnp.concatenate([sc, sb], axis=-1) * scale
    k_pos = jnp.arange(s)
    mask = jnp.concatenate(
        [k_pos[None, :] < flush_pos[:, None],              # [B, S]
         jnp.broadcast_to(jnp.arange(stride)[None, :] <= j,
                          (b, stride))], axis=-1)
    scores = jnp.where(mask[:, None, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = (jnp.einsum("bkgts,bksd->bkgtd", probs[..., :s], cv,
                      preferred_element_type=jnp.float32)
           + jnp.einsum("bkgts,bksd->bkgtd", probs[..., s:], bv,
                        preferred_element_type=jnp.float32))
    return out.reshape(b, hq, t, d).astype(q.dtype)


def _row_step_buffered(params: dict, tokens: jax.Array, cache: dict,
                       buf: dict, flush_pos: jax.Array, pos: jax.Array,
                       j: jax.Array, cfg: LlamaConfig, ffn=None
                       ) -> tuple[jax.Array, dict]:
    """One decode step for every slot at its OWN position, writing new
    K/V into the block buffer at the SHARED index ``j`` instead of
    scattering into the cache at per-row offsets.

    The r3 engine's vmapped per-slot ``dynamic_update_slice`` lowered
    to a scatter that cost 21% of the step (1.56 vs 1.23 ms measured,
    BASELINE.md r3); the buffer write is a plain shared-offset update,
    and the scatter happens ONCE per stride-block at flush time.
    tokens: [B]; pos: [B] each row's global position (rope);
    flush_pos: [B] positions at block start (cache validity).
    Returns (next-token logits [B, V] f32, updated buffer)."""
    if ffn is None:
        ffn = lambda x_, lp_: _dense_ffn(x_, lp_, cfg)   # noqa: E731
    x = jnp.take(params["embed"], tokens, axis=0)[:, None, :]   # [B,1,D]
    positions = pos[:, None]                                    # [B,1]

    def layer(x, xs):
        lp, ck, cv, bk, bv = xs
        h = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q, k, v = _project_qkv(h, lp, cfg, positions)   # [B,H,1,D]
        bk = lax.dynamic_update_slice(bk, k.astype(bk.dtype),
                                      (0, 0, j, 0))
        bv = lax.dynamic_update_slice(bv, v.astype(bv.dtype),
                                      (0, 0, j, 0))
        o = _attend_rows_buffered(q, ck, cv, bk, bv, flush_pos, j)
        return _attn_finish(x, o, lp, cfg, ffn), (bk, bv)

    x, (bk_new, bv_new) = lax.scan(
        layer, x, (params["layers"], cache["k"], cache["v"],
                   buf["k"], buf["v"]))
    x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits[:, 0], {"k": bk_new, "v": bv_new}


# NB: _attend_buffer_partials lives in decode.py (the beam-on-pages
# path shares it); imported with the other decode internals above.


def _paged_row_step(params: dict, tokens: jax.Array, pool: dict,
                    pt: jax.Array, tvec: jax.Array, tpad: jax.Array,
                    d0: jax.Array, buf: dict, pos: jax.Array,
                    j: jax.Array, cfg: LlamaConfig, interpret: bool,
                    ffn=None, tp_axis: str | None = None,
                    collect_mass: bool = False):
    """One decode step for every slot against the PAGED pool: flushed
    history via the pallas paged-attention kernel (reads only the pages
    each row actually holds), this block's keys via the write buffer,
    combined with the flash-decoding logsumexp merge.  Layers scan over
    (params, buffer, layer index); the pool rides as a loop-invariant
    closure so nothing pool-sized is ever sliced or copied.

    ``tp_axis`` (inside a shard_map over that mesh axis): ``cfg`` is the
    LOCAL config, the pool/buffer hold this chip's KV heads, the paged
    kernel walks only the local head shard, per-layer partial
    projections psum over the axis, and the lm_head's local vocab shard
    all-gathers so the returned logits are FULL [B, V] on every chip
    (token selection must be replicated — the picked token feeds the
    next step's embedding on all chips)."""
    from kubegpu_tpu.ops.paged_attention import (
        merge_partials,
        paged_attention,
    )
    if ffn is None:
        ffn = lambda x_, lp_: _dense_ffn(x_, lp_, cfg,   # noqa: E731
                                         tp_axis=tp_axis)
    x = jnp.take(params["embed"], tokens, axis=0)[:, None, :]   # [B,1,D]
    positions = pos[:, None]
    pool_k, pool_v = pool["k"], pool["v"]
    k_scale = pool.get("k_scale")
    v_scale = pool.get("v_scale")

    def layer(x, xs):
        lp, bk, bv, li = xs
        h = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q, k, v = _project_qkv(h, lp, cfg, positions)   # [B,H,1,D]
        bk = lax.dynamic_update_slice(bk, k.astype(bk.dtype),
                                      (0, 0, j, 0))
        bv = lax.dynamic_update_slice(bv, v.astype(bv.dtype),
                                      (0, 0, j, 0))
        parts = paged_attention(
            q[:, :, 0, :], pool_k, pool_v, pt, li, tvec, tpad, d0,
            k_scale=k_scale, v_scale=v_scale, interpret=interpret,
            collect_mass=collect_mass)
        o_p, m_p, l_p = parts[0], parts[1], parts[2]
        o_b, m_b, l_b = _attend_buffer_partials(q, bk, bv, j)
        o = merge_partials(o_p, m_p, l_p, o_b, m_b, l_b)
        o = o[:, :, None, :].astype(x.dtype)            # [B,Hq,1,D]
        ys = (bk, bv, parts[3]) if collect_mass else (bk, bv)
        return _attn_finish(x, o, lp, cfg, ffn, tp_axis=tp_axis), ys

    lidx = jnp.arange(cfg.n_layers, dtype=jnp.int32)
    x, ys = lax.scan(
        layer, x, (params["layers"], buf["k"], buf["v"], lidx))
    bk_new, bv_new = ys[0], ys[1]
    x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"]).astype(jnp.float32)[:, 0]
    if tp_axis is not None:
        logits = lax.all_gather(logits, tp_axis, axis=-1, tiled=True)
    buf_new = {"k": bk_new, "v": bv_new}
    if collect_mass:
        # mean over layers: ys[2] is [L, B, max_pages] per-page mass
        return logits, buf_new, jnp.mean(ys[2], axis=0)
    return logits, buf_new


def _flush_buffer_paged(pool: dict, buf: dict, pt: jax.Array,
                        tpad: jax.Array, d0: jax.Array,
                        page_size: int) -> dict:
    """Scatter the block buffer into each row's CURRENT decode page.
    pool [L, n_pages, Hkv, P, D]; buf [L, B, Hkv, stride, D].  The
    decode region is page-aligned and stride divides P, so a block
    never splits a page.  Retired/never-admitted rows carry a zeroed
    page-table row, so their garbage lands in trash page 0 (never
    allocated); the page INDEX clamp keeps their stale positions from
    indexing past the table."""
    n_slots = buf["k"].shape[1]
    max_pages = pt.shape[1]
    phys0 = tpad + d0
    pidx = jnp.clip(phys0 // page_size, 0, max_pages - 1)
    page = jnp.take_along_axis(pt, pidx[:, None], axis=1)[:, 0]   # [B]
    off = phys0 % page_size

    quant = "k_scale" in pool
    q4 = quant and pool["k"].dtype == jnp.uint8
    if q4:
        # packed int4 with per-group scales: group size is recoverable
        # from the pool layout (P / scale lanes), and the engine
        # guarantees kv_group | stride and page-aligned decode starts,
        # so every block write is group-aligned — quantization never
        # straddles a write boundary (that alignment is what keeps q4
        # writes exactly-once under chaos replay)
        from kubegpu_tpu.ops.kvquant import quantize_groups_q4
        gq = page_size // pool["k_scale"].shape[3]
        kq, ksc = quantize_groups_q4(
            buf["k"].reshape((-1,) + buf["k"].shape[2:]), gq)
        vq, vsc = quantize_groups_q4(
            buf["v"].reshape((-1,) + buf["v"].shape[2:]), gq)
        sshape = buf["k"].shape[:-1][:-1] + (buf["k"].shape[3] // gq,)
        qbuf = {"k": kq.reshape(buf["k"].shape[:-1] + (kq.shape[-1],)),
                "v": vq.reshape(buf["v"].shape[:-1] + (vq.shape[-1],)),
                "k_scale": ksc.reshape(sshape),
                "v_scale": vsc.reshape(sshape)}
    elif quant:
        # ONE vectorized quantize of the whole buffer; the per-slot
        # loop below only scatters (a review catch: quantizing inside
        # the sequential loop serialized n_slots quantize ops on the
        # hot decode path)
        from kubegpu_tpu.models.decode import _quantize_rows
        kq, ksc = _quantize_rows(
            buf["k"].reshape((-1,) + buf["k"].shape[2:]))
        vq, vsc = _quantize_rows(
            buf["v"].reshape((-1,) + buf["v"].shape[2:]))
        qbuf = {"k": kq.reshape(buf["k"].shape),
                "v": vq.reshape(buf["v"].shape),
                "k_scale": ksc.reshape(buf["k"].shape[:-1]),
                "v_scale": vsc.reshape(buf["v"].shape[:-1])}

    def write_row(b, pool_st):
        # [L, 1, Hkv, stride, D] → pool at (layer *, page, head *, off, *)
        start = (0, page[b], 0, off[b], 0)
        if quant:
            pk, pv, pks, pvs = pool_st
            s4 = (0, page[b], 0, off[b] // gq if q4 else off[b])
            pk = lax.dynamic_update_slice(
                pk, lax.dynamic_slice_in_dim(qbuf["k"], b, 1, axis=1),
                start)
            pv = lax.dynamic_update_slice(
                pv, lax.dynamic_slice_in_dim(qbuf["v"], b, 1, axis=1),
                start)
            pks = lax.dynamic_update_slice(
                pks, lax.dynamic_slice_in_dim(qbuf["k_scale"], b, 1,
                                              axis=1), s4)
            pvs = lax.dynamic_update_slice(
                pvs, lax.dynamic_slice_in_dim(qbuf["v_scale"], b, 1,
                                              axis=1), s4)
            return pk, pv, pks, pvs
        pk, pv = pool_st
        seg_k = lax.dynamic_slice_in_dim(buf["k"], b, 1, axis=1)
        seg_v = lax.dynamic_slice_in_dim(buf["v"], b, 1, axis=1)
        pk = lax.dynamic_update_slice(pk, seg_k.astype(pk.dtype), start)
        pv = lax.dynamic_update_slice(pv, seg_v.astype(pv.dtype), start)
        return pk, pv

    if quant:
        pk, pv, pks, pvs = lax.fori_loop(
            0, n_slots, write_row,
            (pool["k"], pool["v"], pool["k_scale"], pool["v_scale"]))
        return {"k": pk, "v": pv, "k_scale": pks, "v_scale": pvs}
    pk, pv = lax.fori_loop(
        0, n_slots, write_row, (pool["k"], pool["v"]))
    return {"k": pk, "v": pv}


def _flush_buffer(cache: dict, buf: dict, flush_pos: jax.Array) -> dict:
    """Scatter the block buffer into the dense cache — the ONE per-row
    write of a stride-block.  cache [L, B, Hkv, S, D]; buf
    [L, B, Hkv, stride, D]; row b's segment lands at ``flush_pos[b]``."""

    def write_seg(c, seg, p):     # [Hkv, S, D] ← [Hkv, stride, D] at p
        return lax.dynamic_update_slice(c, seg.astype(c.dtype),
                                        (0, p, 0))

    write = jax.vmap(jax.vmap(write_seg, in_axes=(0, 0, 0)),
                     in_axes=(0, 0, None))          # over L, then B
    return {"k": write(cache["k"], buf["k"], flush_pos),
            "v": write(cache["v"], buf["v"], flush_pos)}


@functools.lru_cache(maxsize=32)
def _engine_fns(cfg: LlamaConfig, n_slots: int, max_len: int,
                stride: int, top_k: int = 0, sampling: bool = False,
                ffn_factory=None, ffn_cfg=None, donate: bool = True):
    """Jitted engine pieces, cached per static signature.  ``top_k``
    is the engine-wide truncation for sampled slots (static: per-slot
    k would be shape-dynamic); per-REQUEST temperature rides a [B]
    vector — 0 means greedy for that slot.  ``sampling`` is STATIC:
    a greedy-only engine traces pure argmax steps — temps is a
    runtime input, so XLA could never dead-code the full-vocab
    categorical draw out of the hot scan on its own.
    ``ffn_factory(ffn_cfg)`` (hashable pair, same contract as
    decode.generate) swaps the feed-forward sublayer — the MoE family
    serves through this engine with its routed-expert FFN."""
    ffn = ffn_factory(ffn_cfg) if ffn_factory is not None else None

    def _pick(logits, temps, k_):
        return _pick_token(logits, temps, k_, top_k, sampling)

    def _don(name):
        return DENSE_DONATED[name] if donate else ()

    @functools.partial(donating_jit, donate=_don("decode_block"))
    def decode_block(params, cache, tokens, pos, active, temps,
                     base_key, tick):
        """``stride`` decode steps for all slots in ONE dispatch.
        Per-slot greedy/sampled feedback; inactive slots hold position
        (their garbage output is never emitted and their rows never
        advance).  New K/V rides the write buffer at the shared step
        index and is flushed to the cache once at block end — the
        per-row scatter is paid 1/stride as often as the r3 engine
        paid it.  The cache is DONATED (the engine rebinds it every
        tick; without donation the flush copies the whole cache).  The
        tick folds into the key INSIDE the jit (an eager fold_in would
        cost dispatches on an engine built to avoid them).  Returns
        (token block [stride, B], last tokens, pos', cache)."""
        keys = jax.random.split(
            jax.random.fold_in(jax.random.fold_in(base_key, 0), tick),
            stride)
        flush_pos = pos                     # block-start positions [B]
        shape = cache["k"].shape            # [L, B, Hkv, S, D]
        buf = {n: jnp.zeros(shape[:3] + (stride,) + shape[4:],
                            cache[n].dtype) for n in ("k", "v")}
        bad0 = jnp.zeros(tokens.shape, bool)

        def step(carry, xs):
            tokens, pos, buf, bad = carry
            j, k_ = xs
            logits, buf = _row_step_buffered(
                params, tokens, cache, buf, flush_pos, pos, j, cfg,
                ffn=ffn)
            # invalid-logit self-defense: a row whose logits went
            # non-finite (NaN weights/KV, kernel fault) is flagged so
            # the host quarantines THAT slot instead of letting the
            # garbage argmax masquerade as a token
            bad = bad | jnp.any(~jnp.isfinite(logits), axis=-1)
            nxt = _pick(logits, temps, k_).astype(tokens.dtype)
            nxt = jnp.where(active, nxt, tokens)
            pos = jnp.where(active, pos + 1, pos)
            return (nxt, pos, buf, bad), nxt

        (tokens, pos, buf, bad), block = lax.scan(
            step, (tokens, pos, buf, bad0), (jnp.arange(stride), keys))
        cache = _flush_buffer(cache, buf, flush_pos)
        return block, tokens, pos, cache, bad.astype(jnp.int32)

    @donating_jit
    def prefill_wave(params, padded_prompts, true_lens, temps_w,
                     base_key, rid0):
        """Batch-k prefill on right-padded prompts [k, bucket] (the
        padded SHAPE — both k and bucket — keys the compile cache).
        Returns (first tokens [k], batch-k cache); each row's first
        token is picked at ITS true last prompt position (pad logits
        ignored), greedy or sampled per-row.  The wave's first rid
        folds into the key inside the jit (separate domain from the
        block keys via the leading 1); rows draw independently from
        the one key via the batched categorical."""
        from kubegpu_tpu.models.decode import _forward_with_cache
        k = padded_prompts.shape[0]
        cache_w = init_kv_cache(cfg, k, max_len)
        logits, cache_w = _forward_with_cache(
            params, padded_prompts, cache_w, jnp.int32(0), cfg, ffn=ffn)
        last = jnp.take_along_axis(
            logits, (true_lens - 1)[:, None, None], axis=1)[:, 0]
        key = jax.random.fold_in(jax.random.fold_in(base_key, 1), rid0)
        return _pick(last, temps_w, key).astype(jnp.int32), cache_w

    @functools.partial(donating_jit, donate=_don("adopt_wave"),
                       static=("k",))
    def adopt_wave(cache, cache_w, slots, firsts, plens, temps_w,
                   first_toks, tokens, pos, temps, k):
        """Admit a whole wave in ONE dispatch: scatter the batch-k
        cache's rows into (possibly non-contiguous) slots and update
        every per-slot device vector.  (Eager ``.at[].set`` ops per
        admission each cost a dispatch — under the tunnel that
        overhead rivaled the decode itself.)  The big cache is DONATED
        — an r4 on-chip measurement caught each un-donated adoption
        copying the whole cache (~3 s of a 16-request drain)."""
        for i in range(k):   # k is static: unrolled slice-updates
            cache = jax.tree.map(
                lambda big, w: lax.dynamic_update_slice(
                    big, lax.dynamic_slice_in_dim(
                        w, i, 1, axis=1).astype(big.dtype),
                    (0, slots[i], 0, 0, 0)),
                cache, cache_w)
            first_toks = lax.dynamic_update_slice(
                first_toks, firsts[i:i + 1], (slots[i],))
            tokens = lax.dynamic_update_slice(
                tokens, firsts[i:i + 1], (slots[i],))
            pos = lax.dynamic_update_slice(
                pos, plens[i:i + 1], (slots[i],))
            temps = lax.dynamic_update_slice(
                temps, temps_w[i:i + 1], (slots[i],))
        return cache, first_toks, tokens, pos, temps

    return decode_block, prefill_wave, adopt_wave


def _gamma_from_accept(ema: np.ndarray, gamma: int) -> np.ndarray:
    """Adaptive per-slot draft depth: map the rolling acceptance EMA
    monotonically onto [0, γ] (``floor(ema·(γ+1))`` clipped).  A slot
    at γ_b = 0 degrades to exactly one full-model token per tick —
    today's non-speculative path, per slot — while the EMA keeps
    updating from the UNCAPPED match length, so a slot whose text turns
    draft-friendly recovers its depth.  The batched draft runs in
    lockstep, so the cap governs acceptance depth (how far pos and the
    rollback window advance per tick), not draft compute."""
    return np.clip(np.floor(ema * (gamma + 1)).astype(np.int32),
                   0, gamma)


def _pick_token(logits, temps, k_, top_k: int, sampling: bool):
    """Per-slot greedy/sampled selection shared by both engine modes."""
    greedy = jnp.argmax(logits, axis=-1)
    if not sampling:
        return greedy
    from kubegpu_tpu.models.decode import _sample_token
    sampled = _sample_token(logits, k_, temps[:, None],
                            jnp.float32(1.0), top_k, nucleus=False)
    return jnp.where(temps > 0, sampled, greedy)


def _serve_param_specs(quant_weights: bool):
    """Per-leaf PartitionSpec tree for the tensor-parallel serving
    engine (Llama decode weights; megatron column/row split over the
    ``tp`` mesh axis).  The embedding is REPLICATED — decode looks it
    up with ``take`` once per step, and a vocab-sharded table would
    force the one-hot-matmul path for a [B] gather.  ``quant_weights``
    mirrors the tree onto QTensor leaves: a per-output-channel scale
    shards WITH its values on a column split and stays replicated on a
    row split (its channel dim is the unsharded output)."""
    from jax.sharding import PartitionSpec as P

    def col(n_dims=3):
        v = P(*([None] * (n_dims - 1) + ["tp"]))
        if not quant_weights:
            return v
        from kubegpu_tpu.models.quant import QTensor
        return QTensor(v, v)

    def row():
        v = P(None, "tp", None)
        if not quant_weights:
            return v
        from kubegpu_tpu.models.quant import QTensor
        return QTensor(v, P(None, None, None))

    return {
        "embed": P(None, None),
        "layers": {
            "attn_norm": P(None, None),
            "wq": col(), "wk": col(), "wv": col(),
            "wo": row(),
            "mlp_norm": P(None, None),
            "w_gate": col(), "w_up": col(),
            "w_down": row(),
        },
        "final_norm": P(None),
        "lm_head": col(2),
    }


def make_serve_mesh(tp: int, devices=None):
    """A 1-axis ``("tp",)`` serving mesh over ``tp`` devices (defaults
    to the first tp local devices).  dp scale-out does NOT live on this
    mesh — dp replicas are fully independent engines behind one
    admission queue (:class:`DataParallelServePool`), each pinned to
    its own tp-submesh; there is no cross-replica collective to
    express.  tp=1 over one device is valid and pins a replica."""
    import numpy as _np
    devs = list(devices if devices is not None else jax.devices()[:tp])
    if len(devs) != tp:
        raise ValueError(f"need {tp} devices for tp={tp}, got {len(devs)}")
    from jax.sharding import Mesh
    return Mesh(_np.array(devs), ("tp",))


@functools.lru_cache(maxsize=32)
def _paged_engine_fns(cfg: LlamaConfig, n_slots: int, max_pages: int,
                      page_size: int, stride: int, top_k: int = 0,
                      sampling: bool = False, interpret: bool = False,
                      kv_int8: bool = False, kv_bits: int = 16,
                      kv_group: int = 0, evict_mass: bool = False,
                      ffn_factory=None,
                      ffn_cfg=None, mesh=None,
                      quant_weights: bool = False,
                      spec_gamma: int = 0, draft_layers: int = 0,
                      fused_k: int = 0, eos_id: int = -1,
                      donate: bool = True):
    """Jitted engine pieces for the PAGED cache mode: the KV history
    lives in a page pool [L, n_pages, Hkv, P, D] shared by all slots
    (page 0 is a trash page, never allocated), addressed through a
    host-managed per-slot page table uploaded with each block dispatch.
    Same write-buffer structure as the dense mode; the flushed history
    is read by the pallas paged-attention kernel, which only fetches
    the pages a row actually holds.  ``ffn_factory(ffn_cfg)`` swaps the
    feed-forward sublayer (MoE serves through the pool this way).

    ``mesh`` (a ``("tp",)`` Mesh from :func:`make_serve_mesh`) makes
    every executable MESH-NATIVE via ``jax.shard_map``: the pool and
    both paged-attention kernel variants shard over KV heads (each
    chip's pool holds Hkv/tp heads and its kernel walks only those),
    weights split megatron-style (qkv/gate/up column-sharded, wo/down
    row-sharded with a per-layer psum, lm_head vocab-sharded with an
    all-gather before token selection), and page tables + every
    per-slot host vector stay REPLICATED — admission, prefix caching,
    LRU eviction, and chunked prefill are sharding-oblivious.
    ``quant_weights`` keys the per-leaf spec tree for QTensor params
    (it only matters when mesh is set).

    ``fused_k > 1`` additionally builds ``decode_fused`` (and, with
    spec decoding on, ``verify_fused``): K complete engine ticks inside
    one ``lax.scan``, one host fetch for the whole block.  Each inner
    tick is the UNMODIFIED single-tick body, so a fused block is
    bit-exact vs K dispatches of it by construction; what the fusion
    adds is the on-device lane freeze — a per-slot validity mask that
    retires a lane mid-block when it exhausts its token ``budget``,
    emits ``eos_id``, would flush past its page allocation ``cap``
    (the stall flag the host reads back), or trips the non-finite
    quarantine flag.  ``eos_id < 0`` disables the EOS freeze.

    ``kv_bits = 4`` (with ``kv_group`` tokens per scale group) selects
    the PACKED int4 pool format (ISSUE 15): uint8 value leaves hold
    two nibbles per byte and every write path quantizes per group
    through :mod:`kubegpu_tpu.ops.kvquant` — the same module the int8
    paths rate through.  ``evict_mass`` makes ``decode_block`` emit a
    fourth output, the per-page attention-mass accumulator harvested
    from the paged kernel ([B, max_pages]) — the signal for the
    engine's low-attention-mass page eviction (mesh=None only: mass
    over a head shard is chip-local, not replicated)."""
    if mesh is not None and ffn_factory is not None:
        raise ValueError(
            "tensor-parallel serving supports the dense Llama family "
            "only (MoE scales out on dp replicas)")
    q4 = kv_bits == 4
    quant = kv_int8 or q4
    if kv_int8 and q4:
        raise ValueError("kv_int8 and kv_bits=4 are exclusive")
    if evict_mass and mesh is not None:
        raise ValueError("attention-mass harvest requires mesh=None")
    if evict_mass and (spec_gamma or fused_k > 1):
        raise ValueError(
            "attention-mass harvest rides the plain K=1 decode block "
            "(spec/fused ticks have no single per-page mass signal)")
    tp = int(mesh.shape["tp"]) if mesh is not None else 1
    tp_axis = "tp" if mesh is not None else None
    lcfg = cfg if tp == 1 else replace(
        cfg, n_heads=cfg.n_heads // tp, n_kv_heads=cfg.n_kv_heads // tp,
        d_ff=cfg.d_ff // tp, head_dim_override=cfg.head_dim)
    if ffn_factory is not None:
        ffn = ffn_factory(ffn_cfg)
    else:
        ffn = lambda x_, lp_: _dense_ffn(x_, lp_, lcfg,   # noqa: E731
                                         tp_axis=tp_axis)

    def _pick(logits, temps, k_):
        return _pick_token(logits, temps, k_, top_k, sampling)

    def don(name):
        return PAGED_DONATED[name] if donate else ()

    def _block_body(params, pool, pt, tvec, tpad, tokens, pos, active,
                    temps, base_key, tick):
        """``stride`` decode steps against the paged pool in ONE
        dispatch.  ``tvec``/``tpad``: per-row prompt length and
        (page-aligned) decode-region start; flushed decode count is
        ``pos - tvec`` for active rows and pinned to 0 for inactive
        ones (their page-table rows are zeroed at retirement, so
        nothing they touch is live).  The pool is donated: the engine
        rebinds it every tick, and without donation every flush would
        copy the whole pool."""
        keys = jax.random.split(
            jax.random.fold_in(jax.random.fold_in(base_key, 0), tick),
            stride)
        d0 = jnp.where(active, pos - tvec, 0)
        shape = pool["k"].shape            # [L, n_pages, Hkv, P, D]
        # the write buffer stays in the MODEL dtype regardless of the
        # pool's (int8/int4 pools quantize at flush, not at write — the
        # in-block keys are attended exactly; a packed-int4 pool's last
        # dim is D/2, so the buffer sizes off the config, not the pool)
        buf = {n: jnp.zeros((shape[0], n_slots, shape[2], stride,
                             lcfg.head_dim), lcfg.jdtype)
               for n in ("k", "v")}
        bad0 = jnp.zeros(tokens.shape, bool)
        macc0 = jnp.zeros((n_slots, max_pages), jnp.float32)

        def step(carry, xs):
            tokens, pos, buf, bad, macc = carry
            j, k_ = xs
            if evict_mass:
                logits, buf, pmass = _paged_row_step(
                    params, tokens, pool, pt, tvec, tpad, d0, buf,
                    pos, j, lcfg, interpret, ffn=ffn, tp_axis=tp_axis,
                    collect_mass=True)
                macc = macc + pmass
            else:
                logits, buf = _paged_row_step(
                    params, tokens, pool, pt, tvec, tpad, d0, buf,
                    pos, j, lcfg, interpret, ffn=ffn, tp_axis=tp_axis)
            # per-slot invalid-logit flag (slots are independent rows,
            # so a poisoned page NaNs exactly one row's logits — the
            # host quarantines that slot, never the batch)
            bad = bad | jnp.any(~jnp.isfinite(logits), axis=-1)
            nxt = _pick(logits, temps, k_).astype(tokens.dtype)
            nxt = jnp.where(active, nxt, tokens)
            pos = jnp.where(active, pos + 1, pos)
            return (nxt, pos, buf, bad, macc), nxt

        (tokens, pos, buf, bad, macc), block = lax.scan(
            step, (tokens, pos, buf, bad0, macc0),
            (jnp.arange(stride), keys))
        pool = _flush_buffer_paged(pool, buf, pt, tpad, d0, page_size)
        outs = (block, tokens, pos, pool, bad.astype(jnp.int32))
        if evict_mass:
            # mean per-page attention mass over the block's steps —
            # the eviction signal the host EMAs into _page_mass
            outs = outs + (macc / stride,)
        return outs

    def _pw_body(params, padded_prompts, true_lens, temps_w,
                 base_key, rid0):
        """Batch-k prefill producing a DENSE [L, k, Hkv, bucket, D]
        panel (bucket is a multiple of the page size) for page-wise
        adoption.  First-token selection identical to the dense mode.
        Under tp the panel holds local heads and the lm_head's vocab
        shard gathers AFTER last-position selection ([k, V/tp] rows,
        not [k, bucket, V/tp] tensors, cross the axis)."""
        from kubegpu_tpu.models.decode import _forward_with_cache
        k = padded_prompts.shape[0]
        bucket = padded_prompts.shape[1]
        cache_w = init_kv_cache(lcfg, k, bucket)
        logits, cache_w = _forward_with_cache(
            params, padded_prompts, cache_w, jnp.int32(0), lcfg,
            ffn=ffn, tp_axis=tp_axis)
        last = jnp.take_along_axis(
            logits, (true_lens - 1)[:, None, None], axis=1)[:, 0]
        if tp_axis is not None:
            last = lax.all_gather(last, tp_axis, axis=-1, tiled=True)
        key = jax.random.fold_in(jax.random.fold_in(base_key, 1), rid0)
        return _pick(last, temps_w, key).astype(jnp.int32), cache_w

    def _adopt_body(pool, cache_w, page_dst, slots, firsts, plens,
                    temps_w, first_toks, tokens, pos, temps, k):
        """Admit a wave: copy each row's prompt panel page-by-page into
        its allocated pool pages (``page_dst`` [k, bucket/P] pool page
        ids) and update the per-slot device vectors.  k and the page
        count are static — unrolled slice updates, in-place on the
        donated pool."""
        bucket = cache_w["k"].shape[3]
        n_pages_row = bucket // page_size
        if kv_int8:
            from kubegpu_tpu.models.decode import _quantize_rows
            kq, ksc = _quantize_rows(
                cache_w["k"].reshape((-1,) + cache_w["k"].shape[2:]))
            vq, vsc = _quantize_rows(
                cache_w["v"].reshape((-1,) + cache_w["v"].shape[2:]))
            cache_q = {
                "k": kq.reshape(cache_w["k"].shape),
                "v": vq.reshape(cache_w["v"].shape),
                "k_scale": ksc.reshape(cache_w["k"].shape[:-1]),
                "v_scale": vsc.reshape(cache_w["v"].shape[:-1]),
            }
        elif q4:
            # per-group int4 over the whole panel at once (the bucket
            # is a page multiple and kv_group | page_size, so groups
            # never straddle the per-page copies below)
            from kubegpu_tpu.ops.kvquant import quantize_groups_q4
            kq, ksc = quantize_groups_q4(cache_w["k"], kv_group)
            vq, vsc = quantize_groups_q4(cache_w["v"], kv_group)
            cache_q = {"k": kq, "v": vq, "k_scale": ksc, "v_scale": vsc}
        for i in range(k):
            for pi in range(n_pages_row):
                sl = (slice(None), slice(i, i + 1), slice(None),
                      slice(pi * page_size, (pi + 1) * page_size))
                start = (0, page_dst[i, pi], 0, 0, 0)
                if quant:
                    gsz = 1 if kv_int8 else kv_group
                    ssl = (slice(None), slice(i, i + 1), slice(None),
                           slice(pi * page_size // gsz,
                                 (pi + 1) * page_size // gsz))
                    pool = {
                        "k": lax.dynamic_update_slice(
                            pool["k"], cache_q["k"][sl], start),
                        "v": lax.dynamic_update_slice(
                            pool["v"], cache_q["v"][sl], start),
                        "k_scale": lax.dynamic_update_slice(
                            pool["k_scale"], cache_q["k_scale"][ssl],
                            start[:-1]),
                        "v_scale": lax.dynamic_update_slice(
                            pool["v_scale"], cache_q["v_scale"][ssl],
                            start[:-1]),
                    }
                else:
                    src_k = cache_w["k"][sl]
                    src_v = cache_w["v"][sl]
                    pool = {
                        "k": lax.dynamic_update_slice(
                            pool["k"], src_k.astype(pool["k"].dtype), start),
                        "v": lax.dynamic_update_slice(
                            pool["v"], src_v.astype(pool["v"].dtype), start),
                    }
            first_toks = lax.dynamic_update_slice(
                first_toks, firsts[i:i + 1], (slots[i],))
            tokens = lax.dynamic_update_slice(
                tokens, firsts[i:i + 1], (slots[i],))
            pos = lax.dynamic_update_slice(
                pos, plens[i:i + 1], (slots[i],))
            temps = lax.dynamic_update_slice(
                temps, temps_w[i:i + 1], (slots[i],))
        return pool, first_toks, tokens, pos, temps

    def _chunk_body(params, pool, chunk, pt_row, s, tlen, temps1,
                    base_key, rid):
        """Process one page-aligned PROMPT CHUNK of a single slot
        directly against the pool: chunk tokens [1, C] at global
        positions [s, s+C), K/V written straight into the slot's pool
        pages (no dense prefill panel, no adopt copy), attention =
        paged kernel over the already-written history [0, s) merged
        with the chunk's own causal partials (the flash-decoding
        split the PLD verify path uses — decode.py's
        ``_paged_chunk_forward`` — generalized to page-table-indirect
        writes).  This is BOTH halves of the serving fast path:

        - chunked prefill: a long prompt admits as ceil(t/C) of these
          interleaved with decode ticks instead of one full-wave
          forward that stalls every active slot;
        - prefix caching: a request whose leading pages alias cached
          pages starts its chunks at the first non-cached page — the
          aliased history is read through the page table like any
          other flushed K/V, so shared-prefix traffic pays prefill
          only for its tail.

        ``s`` must be page-aligned and C a page multiple; the final
        chunk right-pads past ``tlen`` (pad K/V lands at phys >= tlen
        inside owned pages — invalid region, never attended; in-chunk
        pad keys are only attended by pad queries under the causal
        mask).  Returns (picked token [1] — the request's FIRST token,
        meaningful only on the chunk containing position tlen-1 —
        and the updated pool)."""
        from kubegpu_tpu.models.decode import (
            _chunk_causal_partials,
            _quantize_rows,
        )
        from kubegpu_tpu.ops.kvquant import quantize_groups_q4
        from kubegpu_tpu.ops.paged_attention import (
            fold_chunk_queries,
            merge_partials,
            paged_attention,
        )
        c = chunk.shape[1]
        c_pages = c // page_size
        hd = lcfg.head_dim
        x = jnp.take(params["embed"], chunk, axis=0)          # [1, C, D]
        q_pos = s + jnp.arange(c)
        positions = jnp.broadcast_to(q_pos[None, :], (1, c))
        page_base = s // page_size
        svec = jnp.full((1,), s, jnp.int32)
        zeros1 = jnp.zeros((1,), jnp.int32)

        def layer(x, xs):
            if quant:
                lp, pk, pv, pks, pvs = xs
            else:
                lp, pk, pv = xs      # per-layer [n_pages, Hkv, P, D]
            h = _rmsnorm(x, lp["attn_norm"], lcfg.norm_eps)
            q, k, v = _project_qkv(h, lp, lcfg, positions)  # [1,H,C,D]
            if kv_int8:
                kq, ksc = _quantize_rows(k)
                vq, vsc = _quantize_rows(v)
            elif q4:
                # chunks are page-aligned and kv_group | page_size, so
                # per-group quantization of the whole chunk never
                # straddles the per-page writes below
                kq, ksc = quantize_groups_q4(k, kv_group)
                vq, vsc = quantize_groups_q4(v, kv_group)
            for j in range(c_pages):
                pid = pt_row[0, page_base + j]
                sl = (slice(None), slice(None),
                      slice(j * page_size, (j + 1) * page_size))
                if quant:
                    gsz = 1 if kv_int8 else kv_group
                    ssl = (slice(None), slice(None),
                           slice(j * page_size // gsz,
                                 (j + 1) * page_size // gsz))
                    pk = lax.dynamic_update_slice(
                        pk, kq[sl], (pid, 0, 0, 0))
                    pv = lax.dynamic_update_slice(
                        pv, vq[sl], (pid, 0, 0, 0))
                    pks = lax.dynamic_update_slice(
                        pks, ksc[ssl], (pid, 0, 0))
                    pvs = lax.dynamic_update_slice(
                        pvs, vsc[ssl], (pid, 0, 0))
                else:
                    pk = lax.dynamic_update_slice(
                        pk, k[sl].astype(pk.dtype), (pid, 0, 0, 0))
                    pv = lax.dynamic_update_slice(
                        pv, v[sl].astype(pv.dtype), (pid, 0, 0, 0))
            # chunk queries fold into the paged kernel's group dim
            # ((hkv, g, c)-major, matching _chunk_causal_partials)
            qflat = fold_chunk_queries(q)
            o_p, m_p, l_p = paged_attention(
                qflat, pk[None], pv[None], pt_row, jnp.int32(0),
                svec, svec, zeros1,
                k_scale=pks[None] if quant else None,
                v_scale=pvs[None] if quant else None,
                interpret=interpret)
            # the chunk's own keys attend EXACTLY (unquantized), the
            # same write-buffer-is-exact contract the decode block has
            o_c, m_c, l_c = _chunk_causal_partials(q, k, v)
            o = merge_partials(o_p, m_p, l_p, o_c, m_c, l_c)
            o = o.reshape(1, lcfg.n_heads, c, hd).astype(x.dtype)
            new = (pk, pv, pks, pvs) if quant else (pk, pv)
            return _attn_finish(x, o, lp, lcfg, ffn,
                                tp_axis=tp_axis), new

        if quant:
            xs = (params["layers"], pool["k"], pool["v"],
                  pool["k_scale"], pool["v_scale"])
            x, (pk_new, pv_new, pks_new, pvs_new) = lax.scan(
                layer, x, xs)
            pool = {"k": pk_new, "v": pv_new,
                    "k_scale": pks_new, "v_scale": pvs_new}
        else:
            x, (pk_new, pv_new) = lax.scan(
                layer, x, (params["layers"], pool["k"], pool["v"]))
            pool = {"k": pk_new, "v": pv_new}
        x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
        # lm_head only at the last VALID position (a full [C, vocab]
        # logits matmul per chunk would out-cost the chunk itself);
        # non-final chunks read a clamped garbage index and the token
        # is discarded host-side
        idx = jnp.clip(tlen - s - 1, 0, c - 1)                # [1]
        h_last = jnp.take_along_axis(x, idx[:, None, None], axis=1)
        logits = (h_last[:, 0] @ params["lm_head"]).astype(jnp.float32)
        if tp_axis is not None:
            logits = lax.all_gather(logits, tp_axis, axis=-1,
                                    tiled=True)
        key = jax.random.fold_in(jax.random.fold_in(base_key, 1), rid)
        tok = _pick(logits, temps1, key).astype(jnp.int32)
        return tok, pool

    @functools.partial(donating_jit, donate=don("activate_slot"))
    def activate_slot(first_toks, tokens, pos, temps, slot, tok,
                      plen, temp):
        """Flip a chunk-prefilled slot live in ONE dispatch (the
        chunk-path analog of adopt_wave's vector updates).  Pure
        replicated vector math — needs no shard_map even under tp
        (every input is replicated; jit runs it SPMD on the mesh).
        The four slot mirrors are donated: the engine rebinds all of
        them from the outputs."""
        first_toks = lax.dynamic_update_slice(first_toks, tok, (slot,))
        tokens = lax.dynamic_update_slice(tokens, tok, (slot,))
        pos = lax.dynamic_update_slice(pos, plen, (slot,))
        temps = lax.dynamic_update_slice(temps, temp, (slot,))
        return first_toks, tokens, pos, temps

    # -- page migration (disaggregated serving): export gathers one  --
    # -- request's page chain out of the pool, import scatters it    --
    # -- into another engine's pool.  page_ids is ALWAYS a fixed     --
    # -- int32[max_pages] vector (padded with trash-page zeros) so   --
    # -- each direction lowers to exactly ONE census signature.      --

    def _export_body(pool, page_ids):
        """Gather a page chain for migration.  The chain carries every
        pool leaf — int8 values AND their QTensor scales — so the
        importing engine resumes from bit-identical pool bytes.  Pool
        is NOT donated: the exporting engine keeps serving from it."""
        from kubegpu_tpu.ops.paged_attention import gather_pages
        return gather_pages(pool, page_ids)

    def _import_body(pool, chain, page_dst):
        """Scatter a migrated chain into freshly allocated pages.  The
        pool is donated (the engine rebinds it); the chain leaves are
        NOT — they are differently shaped host uploads and cannot
        alias pool outputs."""
        from kubegpu_tpu.ops.paged_attention import scatter_pages
        return scatter_pages(pool, chain, page_dst)

    # -- speculative tick (spec_gamma > 0): batched early-exit self- --
    # -- draft + ONE full-model verify over [n_slots, γ+1] positions --
    _spec_body = None
    if spec_gamma:
        import dataclasses as _dc

        gamma = spec_gamma
        dcfg = _dc.replace(lcfg, n_layers=draft_layers)

        def _verify_fwd(params, chunk, pool, pt, tvec, tpad, d0, pos):
            """Full-model verify forward over C = γ+1 positions for
            EVERY slot against the page pool: per-row positions
            ``pos[b] .. pos[b]+γ``, history (prompt + flushed decode)
            through the paged kernel with the chunk queries folded into
            the group dim (:func:`fold_chunk_queries` — all C queries
            of a row share one validity window), in-chunk causality
            exact via ``_chunk_causal_partials``, flash-decoding merge
            — the same composition ``prefill_chunk`` uses, batched and
            page-table-indirect.

            The chunk's fresh K/V lands in the pool through each row's
            page TABLE at phys ``[t_pad+d, t_pad+d+γ]`` — a 2-page
            read-modify-write window per row (pages of a slot's decode
            region are private, so windows never collide; inactive or
            overrun rows resolve to trash page 0).  Rejected entries
            need no physical rollback: the next tick's ``d`` simply
            doesn't cover them (invalid ⇒ never attended) and the next
            verify overwrites them in place — the engine's standing
            overwrite-before-attend contract.  Returns (logits
            [B, C, V] f32 — full vocab on every chip under tp — and
            the updated pool)."""
            from kubegpu_tpu.models.decode import (
                _chunk_causal_partials,
                _quantize_rows,
            )
            from kubegpu_tpu.ops.kvquant import (
                dequantize_q4,
                quantize_groups_q4,
            )
            from kubegpu_tpu.ops.paged_attention import (
                fold_chunk_queries,
                merge_partials,
                paged_attention,
            )
            b, c = chunk.shape
            hkv = lcfg.n_kv_heads
            hd = lcfg.head_dim
            p = page_size
            x = jnp.take(params["embed"], chunk, axis=0)    # [B, C, D]
            positions = pos[:, None] + jnp.arange(c)[None, :]
            phys0 = tpad + d0
            p0 = jnp.clip(phys0 // p, 0, max_pages - 1)
            p1 = jnp.clip(p0 + 1, 0, max_pages - 1)
            off = phys0 % p
            pid0 = jnp.take_along_axis(pt, p0[:, None], axis=1)[:, 0]
            pid1 = jnp.take_along_axis(pt, p1[:, None], axis=1)[:, 0]

            def put_win(pw, seg, r):
                """Place row r's [Hkv, C, ...] segment at its offset
                inside the 2-page window (pid0[r], pid1[r]) of a
                [n_pages, Hkv, P, ...] pool leaf.  pid1 writes back
                FIRST: at the table edge p1 clamps onto p0 and the
                first-half update must win."""
                tail = pw.shape[3:]          # (D,) for values, () scales
                w0 = lax.dynamic_slice(
                    pw, (pid0[r], 0, 0) + (0,) * len(tail),
                    (1, hkv, p) + tail)
                w1 = lax.dynamic_slice(
                    pw, (pid1[r], 0, 0) + (0,) * len(tail),
                    (1, hkv, p) + tail)
                axes = (1, 0, 2) + tuple(range(3, 3 + len(tail)))
                win = jnp.concatenate([w0, w1], axis=0) \
                    .transpose(axes).reshape((hkv, 2 * p) + tail)
                win = lax.dynamic_update_slice(
                    win, seg.astype(win.dtype),
                    (0, off[r]) + (0,) * len(tail))
                win = win.reshape((hkv, 2, p) + tail).transpose(axes)
                pw = lax.dynamic_update_slice(
                    pw, win[1:2], (pid1[r], 0, 0) + (0,) * len(tail))
                return lax.dynamic_update_slice(
                    pw, win[0:1], (pid0[r], 0, 0) + (0,) * len(tail))

            def put_win_q4(pw, pws, seg, r):
                """int4 twin of put_win, jointly over a packed value
                leaf [n_pages, Hkv, P, D/2] and its group-scale leaf
                [n_pages, Hkv, P/g]: dequantize row r's 2-page window,
                splice the f32 segment at its (possibly group-
                unaligned) offset, requantize the WHOLE window per
                group.  Groups already at full int4 range requantize to
                the same bytes, so the verify overwrite stays
                idempotent after the first pass.  Same pid1-first
                clamp-edge rule as put_win."""
                gq = kv_group
                w0 = lax.dynamic_slice(pw, (pid0[r], 0, 0, 0),
                                       (1, hkv, p, hd // 2))
                w1 = lax.dynamic_slice(pw, (pid1[r], 0, 0, 0),
                                       (1, hkv, p, hd // 2))
                s0 = lax.dynamic_slice(pws, (pid0[r], 0, 0),
                                       (1, hkv, p // gq))
                s1 = lax.dynamic_slice(pws, (pid1[r], 0, 0),
                                       (1, hkv, p // gq))
                win = jnp.concatenate([w0, w1], axis=0) \
                    .transpose(1, 0, 2, 3).reshape(hkv, 2 * p, hd // 2)
                sc = jnp.concatenate([s0, s1], axis=0) \
                    .transpose(1, 0, 2).reshape(hkv, 2 * p // gq)
                vals = dequantize_q4(win, sc, gq)
                vals = lax.dynamic_update_slice(
                    vals, seg.astype(vals.dtype), (0, off[r], 0))
                wq, wsc = quantize_groups_q4(vals, gq)
                wq = wq.reshape(hkv, 2, p, hd // 2) \
                    .transpose(1, 0, 2, 3)
                wsc = wsc.reshape(hkv, 2, p // gq).transpose(1, 0, 2)
                pw = lax.dynamic_update_slice(
                    pw, wq[1:2], (pid1[r], 0, 0, 0))
                pw = lax.dynamic_update_slice(
                    pw, wq[0:1], (pid0[r], 0, 0, 0))
                pws = lax.dynamic_update_slice(
                    pws, wsc[1:2], (pid1[r], 0, 0))
                pws = lax.dynamic_update_slice(
                    pws, wsc[0:1], (pid0[r], 0, 0))
                return pw, pws

            def layer(x, xs):
                if quant:
                    lp, pk, pv, pks, pvs = xs
                else:
                    lp, pk, pv = xs
                h = _rmsnorm(x, lp["attn_norm"], lcfg.norm_eps)
                q, k, v = _project_qkv(h, lp, lcfg, positions)
                if kv_int8:
                    kq, ksc = _quantize_rows(k)
                    vq, vsc = _quantize_rows(v)

                def wrow(r, st):
                    if kv_int8:
                        pk, pv, pks, pvs = st
                        return (put_win(pk, kq[r], r),
                                put_win(pv, vq[r], r),
                                put_win(pks, ksc[r], r),
                                put_win(pvs, vsc[r], r))
                    if q4:
                        pk, pv, pks, pvs = st
                        pk, pks = put_win_q4(pk, pks, k[r], r)
                        pv, pvs = put_win_q4(pv, pvs, v[r], r)
                        return (pk, pv, pks, pvs)
                    pk, pv = st
                    return put_win(pk, k[r], r), put_win(pv, v[r], r)

                st = (pk, pv, pks, pvs) if quant else (pk, pv)
                st = lax.fori_loop(0, n_slots, wrow, st)
                # validity stops at d0, so the kernel never reads the
                # entries just written — the chunk's own keys attend
                # exactly (unquantized) through the causal partials
                o_p, m_p, l_p = paged_attention(
                    fold_chunk_queries(q), st[0][None], st[1][None],
                    pt, jnp.int32(0), tvec, tpad, d0,
                    k_scale=st[2][None] if quant else None,
                    v_scale=st[3][None] if quant else None,
                    interpret=interpret)
                o_c, m_c, l_c = _chunk_causal_partials(q, k, v)
                o = merge_partials(o_p, m_p, l_p, o_c, m_c, l_c)
                o = o.reshape(b, lcfg.n_heads, c, hd).astype(x.dtype)
                return _attn_finish(x, o, lp, lcfg, ffn,
                                    tp_axis=tp_axis), st

            if quant:
                xs = (params["layers"], pool["k"], pool["v"],
                      pool["k_scale"], pool["v_scale"])
                x, (pk, pv, pks, pvs) = lax.scan(layer, x, xs)
                pool = {"k": pk, "v": pv,
                        "k_scale": pks, "v_scale": pvs}
            else:
                x, (pk, pv) = lax.scan(
                    layer, x, (params["layers"], pool["k"], pool["v"]))
                pool = {"k": pk, "v": pv}
            x = _rmsnorm(x, params["final_norm"], lcfg.norm_eps)
            # the verify NEEDS every position's argmax — the [B, C, V]
            # matmul is the price of multi-token acceptance (C is γ+1,
            # not a prompt)
            logits = (x @ params["lm_head"]).astype(jnp.float32)
            if tp_axis is not None:
                logits = lax.all_gather(logits, tp_axis, axis=-1,
                                        tiled=True)
            return logits, pool

        def _spec_tick_body(params, dparams, pool, pt, tvec, tpad,
                            tokens, pos, active, gcap):
            """One SPECULATIVE engine tick, in one dispatch: the first
            ``draft_layers`` (``dparams`` — a :func:`draft_view`, NOT
            extra weights) autoregressively propose γ tokens per slot,
            then ONE verify forward scores all [B, γ+1] positions and
            per-slot acceptance keeps each slot's longest full-model-
            agreed prefix plus the always-valid correction token.

            The draft needs NO cache of its own: layer i < draft_layers
            of the early-exit draft computes exactly the full model's
            layer-i K/V, so the draft reads the SHARED pool history and
            keeps only this tick's proposals in a γ-wide write buffer
            (``_paged_row_step`` — the decode block's own step — drives
            it with ``dcfg``).  ``gcap`` [B] is the per-slot adaptive γ
            cap from rolling acceptance; a capped/failed slot still
            emits 1 full-model token per tick — today's path, per slot.
            Emitted tokens are the FULL model's argmax by construction;
            the draft only ever decides how many land per dispatch.

            Returns (emit [B, γ+1] — accepted drafts then the
            correction, tail filler; take [B] accepted-draft counts;
            matched [B] uncapped match lengths for the host's rolling
            acceptance; tokens'; pos'; pool')."""
            from kubegpu_tpu.models.decode import spec_acceptance
            d0 = jnp.where(active, pos - tvec, 0)
            shape = pool["k"].shape
            dbuf = {n: jnp.zeros((draft_layers, n_slots, shape[2],
                                  gamma, lcfg.head_dim), lcfg.jdtype)
                    for n in ("k", "v")}

            def dstep(carry, i):
                tok, dbuf = carry
                dlogits, dbuf = _paged_row_step(
                    dparams, tok, pool, pt, tvec, tpad, d0, dbuf,
                    pos + i, i, dcfg, interpret, tp_axis=tp_axis)
                nxt = jnp.argmax(dlogits, axis=-1).astype(tok.dtype)
                return (nxt, dbuf), nxt

            (_, _), drafted = lax.scan(dstep, (tokens, dbuf),
                                       jnp.arange(gamma))
            drafted = drafted.swapaxes(0, 1)                 # [B, γ]
            chunk = jnp.concatenate([tokens[:, None], drafted], axis=1)
            vlogits, pool = _verify_fwd(params, chunk, pool, pt, tvec,
                                        tpad, d0, pos)
            # invalid-logit flag over every verify position: a slot
            # whose verify went non-finite emits garbage acceptance —
            # the host quarantines it before its tokens count
            badv = jnp.any(~jnp.isfinite(vlogits), axis=(1, 2))
            f = jnp.argmax(vlogits, axis=-1).astype(jnp.int32)
            matched, take = spec_acceptance(drafted, f, gcap)
            corr = jnp.take_along_axis(f, take[:, None], axis=1)[:, 0]
            padded = jnp.concatenate([drafted, drafted[:, -1:]], axis=1)
            emit = jnp.where(
                jnp.arange(gamma + 1)[None, :] < take[:, None],
                padded, corr[:, None]).astype(tokens.dtype)
            take = jnp.where(active, take, 0)
            matched = jnp.where(active, matched, 0)
            tokens = jnp.where(active, corr.astype(tokens.dtype),
                               tokens)
            pos = jnp.where(active, pos + take + 1, pos)
            return emit, take, matched, badv.astype(jnp.int32), \
                tokens, pos, pool

        _spec_body = _spec_tick_body

    # -- fused multi-tick decode (fused_k > 1): K complete ticks in ----
    # -- one lax.scan — one host round-trip per BLOCK, not per tick ----
    _fused_body = None
    _fused_spec_body = None
    if fused_k > 1:

        def _fused_body(params, pool, pt, tvec, tpad, tokens, pos,
                        active, temps, budget, cap, base_key, tick0):
            """``fused_k`` decode ticks back-to-back on device.  Each
            inner tick IS ``_block_body`` with the same key schedule
            (``tick0 + tk`` reproduces the K=1 fold-in sequence), so
            the token stream is bit-exact vs K separate dispatches.
            The carry holds the lane freeze: ``emitted`` counts tokens
            laid down per slot this block, ``dead`` latches EOS / non-
            finite lanes, and a lane whose next flush would pass its
            page allocation ``cap`` raises ``stall`` instead of
            writing into pages it doesn't own.  A frozen lane runs
            with ``act=False`` exactly like a retired K=1 slot: its
            tokens/pos hold, its d0 pins to 0, and whatever its flush
            lane writes at offset 0 is never attended (the host
            retires or quarantines every frozen lane when it consumes
            the block, so the clobbered page is never live again)."""

            def one_tick(carry, tk):
                pool, tokens, pos, emitted, stall, dead = carry
                act = active & (emitted < budget) & ~dead
                overrun = act & (pos - tvec + stride > cap)
                stall = stall | overrun
                act = act & ~overrun
                block, tokens, pos, pool, bad = _block_body(
                    params, pool, pt, tvec, tpad, tokens, pos, act,
                    temps, base_key, tick0 + tk)
                if eos_id >= 0:
                    dead = dead | (act & jnp.any(block == eos_id,
                                                 axis=0))
                dead = dead | (bad > 0)
                emitted = emitted + jnp.where(act, stride, 0)
                return (pool, tokens, pos, emitted, stall, dead), \
                    (block, bad)

            zeros = jnp.zeros(tokens.shape, jnp.int32)
            falses = jnp.zeros(tokens.shape, bool)
            (pool, tokens, pos, _, stall, _), (blocks, bads) = lax.scan(
                one_tick, (pool, tokens, pos, zeros, falses, falses),
                jnp.arange(fused_k, dtype=jnp.int32))
            return blocks, tokens, pos, pool, bads, \
                stall.astype(jnp.int32)

        if _spec_body is not None:

            def _fused_spec_body(params, dparams, pool, pt, tvec,
                                 tpad, tokens, pos, active, budget,
                                 cap, gcap):
                """Fused SPECULATIVE ticks: same lane freeze as
                ``_fused_body`` around the unmodified spec tick.  The
                budget/EOS checks count what a tick actually lands
                (``take + 1``), and the overrun guard reserves the
                worst case γ+1 so a stalled lane never opens its
                2-page verify window past its allocation."""
                gamma_ = spec_gamma

                def one_tick(carry, tk):
                    pool, tokens, pos, emitted, stall, dead = carry
                    act = active & (emitted < budget) & ~dead
                    overrun = act & (pos - tvec + gamma_ + 1 > cap)
                    stall = stall | overrun
                    act = act & ~overrun
                    emit, take, matched, badv, tokens, pos, pool = \
                        _spec_body(params, dparams, pool, pt, tvec,
                                   tpad, tokens, pos, act, gcap)
                    if eos_id >= 0:
                        idx = jnp.arange(gamma_ + 1)[None, :]
                        hit = jnp.any((emit == eos_id)
                                      & (idx <= take[:, None]), axis=1)
                        dead = dead | (act & hit)
                    dead = dead | (badv > 0)
                    emitted = emitted + jnp.where(act, take + 1, 0)
                    return (pool, tokens, pos, emitted, stall, dead), \
                        (emit, take, matched, badv)

                zeros = jnp.zeros(tokens.shape, jnp.int32)
                falses = jnp.zeros(tokens.shape, bool)
                (pool, tokens, pos, _, stall, _), \
                    (emits, takes, matcheds, badvs) = lax.scan(
                        one_tick,
                        (pool, tokens, pos, zeros, falses, falses),
                        jnp.arange(fused_k, dtype=jnp.int32))
                return emits, takes, matcheds, badvs, tokens, pos, \
                    pool, stall.astype(jnp.int32)

    if mesh is None:
        decode_block = donating_jit(_block_body,
                                    donate=don("decode_block"))
        prefill_wave = donating_jit(_pw_body)
        adopt_wave = donating_jit(_adopt_body,
                                  donate=don("adopt_wave"),
                                  static=("k",))
        prefill_chunk = donating_jit(_chunk_body,
                                     donate=don("prefill_chunk"))
        verify_block = (donating_jit(_spec_body,
                                     donate=don("verify_block"))
                        if _spec_body is not None else None)
        decode_fused = (donating_jit(_fused_body,
                                     donate=don("decode_fused"))
                        if _fused_body is not None else None)
        verify_fused = (donating_jit(_fused_spec_body,
                                     donate=don("verify_fused"))
                        if _fused_spec_body is not None else None)
        export_chain = donating_jit(_export_body,
                                    donate=don("export_chain"))
        import_chain = donating_jit(_import_body,
                                    donate=don("import_chain"))
        return decode_block, prefill_wave, adopt_wave, prefill_chunk, \
            activate_slot, verify_block, decode_fused, verify_fused, \
            export_chain, import_chain

    # -- mesh-native wrapping (shard_map over the tp axis) --------------
    # donating_jit composes the shard_map (replication checking off:
    # pallas_call has no replication rule; every replicated output here
    # is replicated by construction — identical math on identical
    # operands, post-all-gather) with the donation the engine's rebind
    # contract expects; the pool's shards alias in place per chip.
    from jax.sharding import PartitionSpec as P

    rep = P()
    kvspec = P(None, None, "tp", None, None)
    pool_spec = {"k": kvspec, "v": kvspec}
    if quant:
        pool_spec.update(k_scale=P(None, None, "tp", None),
                         v_scale=P(None, None, "tp", None))
    cache_spec = {"k": kvspec, "v": kvspec}   # prefill panel: model dtype
    pspec = _serve_param_specs(quant_weights)

    decode_block = donating_jit(
        _block_body, donate=don("decode_block"), mesh=mesh,
        in_specs=(pspec, pool_spec) + (rep,) * 9,
        out_specs=(rep, rep, rep, pool_spec, rep))

    prefill_wave = donating_jit(
        _pw_body, mesh=mesh, in_specs=(pspec,) + (rep,) * 5,
        out_specs=(rep, cache_spec))

    adopt_wave = donating_jit(
        _adopt_body, donate=don("adopt_wave"), static=("k",),
        mesh=mesh, in_specs=(pool_spec, cache_spec) + (rep,) * 9,
        out_specs=(pool_spec,) + (rep,) * 4)

    prefill_chunk = donating_jit(
        _chunk_body, donate=don("prefill_chunk"), mesh=mesh,
        in_specs=(pspec, pool_spec) + (rep,) * 7,
        out_specs=(rep, pool_spec))

    verify_block = None
    if _spec_body is not None:
        # the draft weights shard under the SAME per-leaf spec tree as
        # the full model (a draft_view shares/slices the same leaves);
        # everything else replicates like the decode block's inputs
        verify_block = donating_jit(
            _spec_body, donate=don("verify_block"), mesh=mesh,
            in_specs=(pspec, pspec, pool_spec) + (rep,) * 7,
            out_specs=(rep,) * 6 + (pool_spec,))

    decode_fused = None
    verify_fused = None
    if _fused_body is not None:
        decode_fused = donating_jit(
            _fused_body, donate=don("decode_fused"), mesh=mesh,
            in_specs=(pspec, pool_spec) + (rep,) * 11,
            out_specs=(rep, rep, rep, pool_spec, rep, rep))
    if _fused_spec_body is not None:
        verify_fused = donating_jit(
            _fused_spec_body, donate=don("verify_fused"), mesh=mesh,
            in_specs=(pspec, pspec, pool_spec) + (rep,) * 9,
            out_specs=(rep,) * 6 + (pool_spec, rep))

    # migration executables: the chain gathers/scatters per-chip head
    # shards exactly like the pool it came from, so a chain leaf
    # inherits the pool's spec
    export_chain = donating_jit(
        _export_body, donate=don("export_chain"), mesh=mesh,
        in_specs=(pool_spec, rep), out_specs=pool_spec)

    import_chain = donating_jit(
        _import_body, donate=don("import_chain"), mesh=mesh,
        in_specs=(pool_spec, pool_spec, rep), out_specs=pool_spec)

    return decode_block, prefill_wave, adopt_wave, prefill_chunk, \
        activate_slot, verify_block, decode_fused, verify_fused, \
        export_chain, import_chain


# ---------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------

_ACCT_CAP = 32768   # per-tick accounting window (entries per list)


def _trim_acct(xs: list) -> None:
    """Eviction sweep for the host-side per-tick accounting lists
    (``stall_ms``, ``wave_sizes``, ``_tick_log``, …): once a list
    exceeds ``_ACCT_CAP`` drop the oldest half, so an engine serving
    indefinitely holds a bounded recent window — the summaries the
    benches read are over recent ticks either way.  Amortized O(1);
    smoke runs never reach the cap, so their numbers are unchanged."""
    if len(xs) > _ACCT_CAP:
        del xs[:len(xs) - _ACCT_CAP // 2]


class _AdmissionQueue(deque):
    """The engine's admission queue with an INCREMENTAL queued-prompt-
    token total: every mutation the engine performs (append at submit,
    popleft at admission, ``del q[i]`` at cancel/deadline-prune, the
    sorted rebuild in ``_sort_queue``) keeps :attr:`prompt_tokens`
    equal to ``sum(r.prompt_len for r, _ in q)``, so the pool router's
    prefill-backlog tiebreak reads one attribute instead of scanning
    arbitrarily deep queues per submit — routing stays O(replicas).
    Items are the engine's ``(request, padded_prompt)`` pairs."""

    def __init__(self, items=()):
        super().__init__()
        self.prompt_tokens = 0
        for item in items:
            self.append(item)

    def append(self, item) -> None:
        super().append(item)
        self.prompt_tokens += item[0].prompt_len

    def appendleft(self, item) -> None:
        super().appendleft(item)
        self.prompt_tokens += item[0].prompt_len

    def extend(self, items) -> None:
        for item in items:
            self.append(item)

    def popleft(self):
        item = super().popleft()
        self.prompt_tokens -= item[0].prompt_len
        return item

    def pop(self):
        item = super().pop()
        self.prompt_tokens -= item[0].prompt_len
        return item

    def remove(self, item) -> None:
        super().remove(item)
        self.prompt_tokens -= item[0].prompt_len

    def clear(self) -> None:
        super().clear()
        self.prompt_tokens = 0

    def __delitem__(self, i) -> None:
        self.prompt_tokens -= self[i][0].prompt_len
        super().__delitem__(i)


def _chain_digest(chain: dict, t: int) -> str:
    """Content hash of an exported page chain (every leaf — int8
    values AND scales — plus the prompt length).  The importing engine
    recomputes and compares before touching its pool, so a corrupted
    or torn transfer fails loudly instead of decoding garbage."""
    h = hashlib.sha256(str(t).encode())
    for name in sorted(chain):
        a = np.ascontiguousarray(chain[name])
        h.update(name.encode())
        h.update(str(a.shape).encode())
        h.update(str(a.dtype).encode())
        h.update(a.tobytes())
    return h.hexdigest()


@dataclass
class _Request:
    rid: int
    prompt_len: int
    max_new_tokens: int
    temperature: float = 0.0     # 0 = greedy
    tokens: list[int] = field(default_factory=list)   # generated so far
    done: bool = False
    # chain hashes of the request's CACHEABLE prompt page-blocks
    # (key i covers tokens [0, (i+1)*P) — a registry hit at i implies
    # the whole prefix up to that page boundary matches); computed at
    # submit, empty unless the engine runs prefix caching
    prefix_keys: tuple = ()
    # -- durability (ISSUE 4): the prompt lives HOST-side for the
    # request's whole lifetime so quarantine/failover can replay it as
    # prompt + accepted tokens (greedy replay is bit-exact — the
    # accepted prefix conditions the same continuation).  ``admit_len``
    # is the CURRENT admission's true prompt length: the original
    # prompt at first admission, prompt + accepted at a replay.
    prompt: object = None               # np.ndarray, set at submit
    admit_len: int = 0
    retries: int = 0                    # quarantine/replay attempts
    not_before_tick: int = 0            # backoff gate for replays
    deadline: float | None = None       # time.monotonic() cutoff
    error: str | None = None            # set when the request FAILED
    # -- SLO-guarded admission (ISSUE 13) -------------------------
    # ``tier`` orders admission strictly (0 = most critical); within
    # a tier the queue is EDF on ``deadline_tick`` (a step-count
    # cutoff — deterministic, unlike the wall-clock ``deadline``,
    # which prunes but never reorders).  ``seq`` is the engine-wide
    # enqueue sequence that makes the sort stable-FIFO within
    # (tier, deadline) — replays and parked resumes re-draw it, so a
    # re-queued request never jumps its tier-mates.
    tier: int = 0
    tenant: str = ""                    # quota bucket ("" = unmetered)
    seq: int = 0
    deadline_tick: int | None = None    # _step_count cutoff
    preemptions: int = 0                # park/resume cycles survived
    resuming: bool = False              # parked; next admit = resume
    # engine-tick lifecycle stamps (the load harness's deterministic
    # SLO clock: TTFT = first - submit, decode rate from finish)
    submit_tick: int = -1
    first_tick: int = -1
    finish_tick: int = -1

    @property
    def remaining_new(self) -> int:
        """Tokens still owed: the budget minus what already landed
        (non-zero ``tokens`` at admission means this is a replay)."""
        return self.max_new_tokens - len(self.tokens)


class ContinuousBatcher:
    """Slot-based continuous-batching engine.

    ``submit()`` enqueues a request (greedy by default; a positive
    ``temperature`` samples that request with the engine's static
    ``top_k`` truncation, deterministically per ``seed``); ``step()``
    admits pending requests into free slots (batch-1 prefill + cache
    scatter), runs ONE stride-block of decode steps for every slot,
    and returns the requests that finished.  ``prompt_buckets`` are
    the padded prompt lengths prefill compiles for (one executable per
    bucket).

    Paged-mode fast paths (see module docstring): ``prefix_cache``
    aliases shared prompt pages under a refcount; ``chunked_prefill``
    splits long-prompt admission into ``prefill_chunk``-token
    page-aligned chunks interleaved with decode ticks (default chunk:
    two pages).  ``metrics`` (a MetricsRegistry) receives the per-tick
    ``serve_decode_stall_ms`` histogram when provided.

    ``spec_gamma > 0`` (paged, greedy, dense-Llama) turns every decode
    tick into a SPECULATIVE tick: a batched early-exit self-draft (the
    first ``draft_layers`` of the same weights, sliced once at
    construction) proposes γ tokens per slot, one full-model verify
    forward scores all [n_slots, γ+1] positions against the page pool,
    and each slot banks its longest full-model-agreed prefix plus the
    always-valid correction — up to γ+1 tokens per host sync instead
    of 1 per slot-step, at ~(draft_layers/n_layers)·γ extra compute.
    Rejected tokens roll back by VALIDITY (their pool entries are never
    attended and the next tick overwrites them); ``spec_adaptive``
    drives a per-slot γ cap from rolling acceptance.  Composes with
    prefix caching, chunked prefill, and tp meshes; emitted tokens are
    the full model's argmax by construction, so γ=0 and γ>0 engines
    agree token-for-token (greedy, same weights).

    ``collect_overlap=True`` double-buffers the steady state: tick N+1
    dispatches before tick N's host readout, hiding the fetch wall
    behind device compute (``serve_collect_overlap_ms``)."""

    def __init__(self, params: dict, cfg, n_slots: int = 8,
                 max_len: int | None = None, stride: int = 16,
                 prompt_buckets: tuple[int, ...] = (128, 512, 1024),
                 sampling: bool = False, top_k: int = 0, seed: int = 0,
                 max_wave: int = 8, paged: bool = False,
                 page_size: int = 128, total_pages: int | None = None,
                 kv_int8: bool = False, kv_bits: int | None = None,
                 kv_group: int | None = None,
                 evict_policy: str | None = None,
                 evict_param: float | None = None,
                 prefix_cache: bool = False,
                 chunked_prefill: bool = False,
                 prefill_chunk: int | None = None,
                 metrics=None, mesh=None,
                 spec_gamma: int = 0, draft_layers: int | None = None,
                 spec_adaptive: bool = True,
                 collect_overlap: bool = False,
                 chaos=None, tick_deadline_s: float | None = None,
                 max_retries: int = 2,
                 spec_degrade_after: int | None = None,
                 debug_invariants: bool = False,
                 tracer=None, trace_ctx=None,
                 fused_ticks: int = 1, eos_id: int | None = None,
                 donate: bool = True,
                 tenant_quotas: dict | None = None):
        # model families: a MoEConfig serves through the same engine —
        # its Llama backbone drives attention/cache shapes, the routed
        # expert FFN rides the engine's ffn hook (VERDICT r4 weak #6:
        # non-flagship families were stuck on the dense per-slot cache)
        ffn_factory = ffn_cfg = None
        if not isinstance(cfg, LlamaConfig) and hasattr(cfg, "base"):
            from kubegpu_tpu.models.moe import MoEConfig, _moe_decode_ffn
            if isinstance(cfg, MoEConfig):
                ffn_factory, ffn_cfg = _moe_decode_ffn, cfg
                cfg = cfg.base
            else:
                raise TypeError(
                    f"unsupported engine config {type(cfg).__name__}")
        if not 0 <= top_k <= cfg.vocab_size:
            raise ValueError(
                f"top_k {top_k} not in [0, vocab_size={cfg.vocab_size}]")
        self.sampling = sampling
        # -- batched speculative decoding (spec_gamma > 0): per tick a
        # batched early-exit self-draft (first ``draft_layers`` of the
        # SAME weights) proposes γ tokens per slot and ONE full-model
        # verify forward scores all [n_slots, γ+1] positions, with
        # per-slot acceptance + adaptive γ.  γ=0 IS today's engine —
        # the decode-block path, bit for bit.
        self.spec_gamma = int(spec_gamma)
        self.draft_layers = 0
        if self.spec_gamma:
            if not paged:
                raise ValueError(
                    "speculative serving (spec_gamma > 0) requires "
                    "paged=True — the draft reads the shared page pool "
                    "(its layer-i K/V IS the full model's) and the "
                    "verify writes through the page tables")
            if sampling:
                raise ValueError(
                    "speculative serving is greedy-only (acceptance "
                    "compares argmaxes); build a sampling=False engine "
                    "or set spec_gamma=0")
            if ffn_factory is not None:
                raise ValueError(
                    "speculative serving supports the dense Llama "
                    "family only (the draft_view slice has no story "
                    "for routed experts)")
            if self.spec_gamma + 1 > page_size:
                raise ValueError(
                    f"spec_gamma {self.spec_gamma} + 1 must be <= "
                    f"page_size {page_size} (the verify writes a "
                    "2-page window)")
            self.draft_layers = (draft_layers if draft_layers is not None
                                 else max(1, cfg.n_layers // 4))
            if not 1 <= self.draft_layers <= cfg.n_layers:
                raise ValueError(
                    f"draft_layers {self.draft_layers} not in "
                    f"[1, {cfg.n_layers}]")
        self.spec_adaptive = bool(spec_adaptive)
        self.collect_overlap = bool(collect_overlap)
        # -- fused multi-tick decode (fused_ticks > 1): when no
        # admission / chunk / replay work is pending, dispatch K
        # complete ticks as ONE executable and reconcile host
        # bookkeeping once per block — the per-tick host round-trip
        # (launch + readout under the TPU tunnel) is the paged
        # engine's steady-state ceiling, and fusing amortizes it K×.
        self.fused_ticks = int(fused_ticks)
        if self.fused_ticks < 1:
            raise ValueError(f"fused_ticks {fused_ticks} must be >= 1")
        if self.fused_ticks > 1 and not paged:
            raise ValueError(
                "fused_ticks > 1 requires paged=True — the fused block "
                "advances page-pool state on device; the dense slot "
                "cache has no multi-tick story")
        self.eos_id = eos_id
        # -- tensor-parallel serving (the mesh-native paged engine) ----
        # ``mesh`` is a ("tp",) Mesh (make_serve_mesh); the page pool
        # and both paged-attention kernels shard over KV heads, host
        # state stays replicated.  Validated HERE so a bad degree fails
        # at construction, not mid-trace.
        self.mesh = mesh
        self.tp = 1
        if mesh is not None:
            if not paged:
                raise ValueError(
                    "mesh (tensor-parallel) serving requires "
                    "paged=True — the sharded engine is the page-pool "
                    "engine; the dense slot cache has no mesh story")
            if ffn_factory is not None:
                raise ValueError(
                    "tensor-parallel serving supports the dense Llama "
                    "family only; MoE scales out on dp replicas "
                    "(DataParallelServePool)")
            if tuple(mesh.axis_names) != ("tp",):
                raise ValueError(
                    f"serving mesh must have exactly the ('tp',) axis, "
                    f"got {mesh.axis_names} — dp replicas are separate "
                    "engines (DataParallelServePool)")
            self.tp = int(mesh.shape["tp"])
            for name, val in (("n_kv_heads", cfg.n_kv_heads),
                              ("n_heads", cfg.n_heads),
                              ("d_ff", cfg.d_ff),
                              ("vocab_size", cfg.vocab_size)):
                if val % self.tp:
                    raise ValueError(
                        f"tp={self.tp} must divide cfg.{name}={val} "
                        "(KV heads shard the pool; q heads/d_ff/vocab "
                        "shard the weights)")
        # Wave-size cap, DEFAULT 8.  The r3 A/B was inconclusive
        # (tunnel weather swung 5x between windows); the r4 in-window
        # chained measurement settled it: at flagship shapes a k=8
        # wave costs 3.66 ms/request (prefill 3.37 + adopt 0.29)
        # vs 4.04 (1.86 + 2.17) at k=1 — 0.91x, the adopt's fixed
        # per-dispatch cost amortizing — plus 2 dispatches per wave
        # instead of 2k.  Each wave still holds a [k, bucket] prefill
        # panel transient; cap at 1 on HBM-critical configs.
        self.max_wave = max(1, max_wave)
        self.params = params
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_len = max_len or cfg.max_seq_len
        self.stride = stride
        self.prompt_buckets = tuple(sorted(prompt_buckets))
        if self.prompt_buckets[-1] >= self.max_len:
            raise ValueError("largest prompt bucket must be < max_len")
        self.paged = paged
        if kv_int8 and not paged:
            raise ValueError(
                "kv_int8=True requires paged=True (the dense engine's "
                "int8 cache is the static decode path's kv_int8)")
        # -- KV bit-width (ISSUE 15): ``kv_bits`` generalizes kv_int8.
        # 16 = model dtype, 8 = the int8 per-token pool (alias for
        # kv_int8=True), 4 = PACKED int4 pages with one f32 scale per
        # ``kv_group`` tokens.  The group must divide both stride and
        # page_size so every non-speculative pool write lands
        # group-aligned — that alignment is what keeps int4 writes
        # deterministic and exactly-once under chaos replay.
        if kv_bits is None:
            kv_bits = 8 if kv_int8 else 16
        if kv_bits not in (16, 8, 4):
            raise ValueError(f"kv_bits {kv_bits} not in (16, 8, 4)")
        if kv_bits == 8:
            if not paged:
                raise ValueError("kv_bits=8 requires paged=True")
            kv_int8 = True
        if kv_bits == 4:
            if kv_int8:
                raise ValueError(
                    "kv_int8=True and kv_bits=4 are exclusive — pick "
                    "one pool quantization")
            if not paged:
                raise ValueError(
                    "kv_bits=4 requires paged=True (the packed int4 "
                    "format is a page-pool layout)")
            if cfg.head_dim % 2:
                raise ValueError(
                    f"kv_bits=4 needs an even head_dim, got "
                    f"{cfg.head_dim} (two channels pack per byte)")
            kv_group = int(kv_group) if kv_group else stride
            if stride % kv_group or page_size % kv_group:
                raise ValueError(
                    f"kv_group {kv_group} must divide both stride "
                    f"{stride} and page_size {page_size} (group-"
                    "aligned writes are the exactly-once contract)")
        else:
            if kv_group:
                raise ValueError("kv_group only applies to kv_bits=4")
            kv_group = 0
        self.kv_bits = int(kv_bits)
        self.kv_group = int(kv_group)
        # -- attention-aware page eviction (ISSUE 15) ------------------
        # ``evict_policy``: "window" drops prompt pages wholly below
        # the trailing ``evict_param``-token window; "mass" drops the
        # lowest attention-mass prompt pages (EMA of the per-page mass
        # the decode kernel harvests) once their mass falls below
        # ``evict_param``.  Both release pages through the standing
        # refcount machinery and punch a page-id-0 HOLE in the slot's
        # table row — the kernel's validity mask skips holes.
        if evict_policy is not None:
            if evict_policy not in ("window", "mass"):
                raise ValueError(
                    f"evict_policy {evict_policy!r} not in "
                    "('window', 'mass')")
            if not paged:
                raise ValueError("evict_policy requires paged=True")
            if mesh is not None:
                raise ValueError(
                    "evict_policy requires mesh=None (the mass signal "
                    "is a chip-local head-shard statistic)")
            if spec_gamma or fused_ticks > 1:
                raise ValueError(
                    "evict_policy rides the plain K=1 decode path "
                    "(spec/fused blocks have no per-tick mass signal)")
            if evict_param is None:
                evict_param = (2.0 * page_size
                               if evict_policy == "window" else 0.02)
        self.evict_policy = evict_policy
        self.evict_param = float(evict_param or 0.0)
        if (prefix_cache or chunked_prefill) and not paged:
            raise ValueError(
                "prefix_cache / chunked_prefill require paged=True — "
                "both are page-pool structural levers (aliased pages, "
                "page-aligned chunk writes)")
        if paged:
            from kubegpu_tpu.ops.paged_attention import page_table_size
            if page_size % stride:
                raise ValueError(
                    f"page_size {page_size} must be a multiple of "
                    f"stride {stride} (block flushes must not split a "
                    "page)")
            if any(b % page_size for b in self.prompt_buckets):
                raise ValueError(
                    f"prompt buckets {self.prompt_buckets} must be "
                    f"multiples of page_size {page_size}")
            self.page_size = page_size
            # a row's physical span: its bucket (the page-aligned
            # prompt region, which may exceed the true prompt length)
            # + its decode region; bucket_max + max_len bounds any row
            self.max_pages = page_table_size(
                self.prompt_buckets[-1] + self.max_len, page_size)
            # pool page 0 is TRASH: retired rows' page tables zero out,
            # so their per-block garbage flush lands somewhere no live
            # row reads.  Capacity is set INDEPENDENTLY of n_slots —
            # the dense mode's n_slots x max_len HBM bound is gone.
            self.total_pages = (total_pages if total_pages is not None
                                else n_slots * self.max_pages)
            interpret = jax.devices()[0].platform == "cpu"
            quant_weights = False
            if mesh is not None:
                from kubegpu_tpu.models.quant import QTensor
                quant_weights = any(
                    isinstance(leaf, QTensor) for leaf in jax.tree.leaves(
                        params,
                        is_leaf=lambda x: isinstance(x, QTensor)))
            self._fns = _paged_engine_fns(
                cfg, n_slots, self.max_pages, page_size, stride, top_k,
                sampling, interpret, kv_int8,
                kv_bits=self.kv_bits, kv_group=self.kv_group,
                evict_mass=(evict_policy == "mass"),
                ffn_factory=ffn_factory, ffn_cfg=ffn_cfg, mesh=mesh,
                quant_weights=quant_weights,
                spec_gamma=self.spec_gamma,
                draft_layers=self.draft_layers,
                fused_k=(self.fused_ticks if self.fused_ticks > 1
                         else 0),
                eos_id=-1 if eos_id is None else int(eos_id),
                donate=bool(donate))
            shape = (cfg.n_layers, self.total_pages + 1, cfg.n_kv_heads,
                     page_size, cfg.head_dim)
            if kv_int8:
                # int8 pages with per-token f32 scales — the cache
                # streams at half the bytes (the dense engine's r2
                # wide-batch lever, now paged); scales init to 1 so
                # unwritten entries dequantize to exact zero
                self.pool = {"k": jnp.zeros(shape, jnp.int8),
                             "v": jnp.zeros(shape, jnp.int8),
                             "k_scale": jnp.ones(shape[:-1], jnp.float32),
                             "v_scale": jnp.ones(shape[:-1], jnp.float32)}
            elif self.kv_bits == 4:
                # packed int4: two channels per byte, one f32 scale
                # per kv_group tokens.  Q4_ZERO_BYTE puts both nibbles
                # at the bias so an unwritten page dequantizes to
                # exact zero under ANY scale — the int4 twin of the
                # int8 pool's scale-1 init.
                from kubegpu_tpu.ops.kvquant import Q4_ZERO_BYTE
                pshape = shape[:-1] + (cfg.head_dim // 2,)
                sshape = shape[:-1][:-1] + (page_size // self.kv_group,)
                self.pool = {
                    "k": jnp.full(pshape, Q4_ZERO_BYTE, jnp.uint8),
                    "v": jnp.full(pshape, Q4_ZERO_BYTE, jnp.uint8),
                    "k_scale": jnp.ones(sshape, jnp.float32),
                    "v_scale": jnp.ones(sshape, jnp.float32)}
            else:
                self.pool = {"k": jnp.zeros(shape, cfg.jdtype),
                             "v": jnp.zeros(shape, cfg.jdtype)}
            if mesh is not None:
                # shard ONCE at construction: the pool over KV heads,
                # the weights megatron-style per _serve_param_specs.
                # Every per-call executable then sees inputs already
                # laid out per its in_specs — no per-tick resharding.
                from jax.sharding import PartitionSpec as _P

                from kubegpu_tpu.parallel.sharding import device_put_tree
                kv = _P(None, None, "tp", None, None)
                sc = _P(None, None, "tp", None)
                self.pool = device_put_tree(
                    mesh, self.pool,
                    {k: (sc if k.endswith("_scale") else kv)
                     for k in self.pool})
                self.params = device_put_tree(
                    mesh, params, _serve_param_specs(quant_weights))
            # the draft view is sliced ONCE per engine (the r5 bench
            # docstring's warning — per-call slicing re-copies the
            # draft fraction of the weights every tick) and, under tp,
            # re-laid-out per the SAME _serve_param_specs so the
            # verify executable's in_specs see it pre-sharded
            self._draft_params = None
            if self.spec_gamma:
                from kubegpu_tpu.models.decode import draft_view
                dview = draft_view(self.params, self.draft_layers)
                if mesh is not None:
                    dview = device_put_tree(
                        mesh, dview, _serve_param_specs(quant_weights))
                self._draft_params = dview
            self._free_pages = list(range(1, self.total_pages + 1))
            self._pt = np.zeros((n_slots, self.max_pages), np.int32)
            self._tvec = np.zeros((n_slots,), np.int32)
            self._tpad = np.zeros((n_slots,), np.int32)
            self._slot_pages: dict[int, list[int]] = {}
            # -- refcounted pool bookkeeping (prefix caching) ---------
            # _page_refs holds EVERY allocated page: value = number of
            # slots whose table references it (aliased prompt pages
            # carry > 1).  A page drops to 0 on last-owner release; if
            # it is REGISTERED in the prefix cache it is retained at
            # ref 0 (reclaimable — _alloc_pages evicts LRU ref-0 cached
            # pages under pressure), otherwise it returns to the free
            # list immediately.  free ∪ _page_refs.keys() partitions
            # {1..total_pages} exactly at every tick.
            self.prefix_cache_enabled = bool(prefix_cache)
            self.chunked_prefill = bool(chunked_prefill)
            self.prefill_chunk = prefill_chunk or 2 * page_size
            if self.prefill_chunk % page_size:
                raise ValueError(
                    f"prefill_chunk {self.prefill_chunk} must be a "
                    f"multiple of page_size {page_size} (chunks write "
                    "whole pages)")
            self._page_refs: dict[int, int] = {}
            from collections import OrderedDict
            self._prefix_cache: "OrderedDict[int, int]" = OrderedDict()
            self._page_key: dict[int, int] = {}   # page → registry key
            # slot → in-flight chunked-prefill state
            self._prefilling: dict[int, dict] = {}
            # device-resident copies, re-uploaded only when admission/
            # retirement actually mutates them — uploading three arrays
            # per tick measured ~ms each of dispatch latency under the
            # TPU tunnel (steady-state decode ticks touch none of them)
            self._tables_dirty = True
            self._pt_dev = self._tvec_dev = self._tpad_dev = None
            # which slots changed since the last upload: small admit/
            # release churn patches device rows in place (.at[s].set)
            # instead of re-uploading whole tables; None = everything
            # (first upload, or more churn than patching is worth)
            self._dirty_slots: set[int] | None = None
            # per-slot decode CAPACITY (positions its page allocation
            # holds past t_pad) — the fused block's on-device stall
            # bound; maintained wherever _slot_pages/_tpad are
            self._cap = np.zeros((n_slots,), np.int32)
            self._cap_dev = None
            # per-(slot, page-index) EMA of the decode kernel's
            # attention-mass harvest + the device array holding the
            # not-yet-fetched mass of the in-flight block (read in
            # _maybe_evict AFTER the tick's main sync, so it costs no
            # extra device round trip)
            self._page_mass = np.zeros((n_slots, self.max_pages))
            self._mass_pending = None
        else:
            self._fns = _engine_fns(cfg, n_slots, self.max_len, stride,
                                    top_k, sampling,
                                    ffn_factory=ffn_factory,
                                    ffn_cfg=ffn_cfg,
                                    donate=bool(donate))
            self.cache = init_kv_cache(cfg, n_slots, self.max_len)
            self.prefix_cache_enabled = False
            self.chunked_prefill = False
            self._prefilling = {}
            self._draft_params = None
        self.tokens = jnp.zeros((n_slots,), jnp.int32)
        self.pos = jnp.zeros((n_slots,), jnp.int32)
        self.temps = jnp.zeros((n_slots,), jnp.float32)
        # deterministic sampling: prefill keys derive from the rid,
        # block keys from the tick counter — no device-side key state
        self._base_key = jax.random.PRNGKey(seed)
        self._tick = 0
        # the active mask lives HOST-side (numpy) and uploads with the
        # block dispatch — mutating it at retirement must not cost a
        # device op per request
        self.active = np.zeros((n_slots,), bool)
        # device mirror of the active mask, re-uploaded only when a
        # host mutation flips a bit (the K=1 path re-uploaded it every
        # tick); all writes go through _set_active
        self._active_dev = None
        self._active_dirty = True
        # per-slot prefill-produced first token, kept ON DEVICE until
        # the next tick's single fused fetch — admissions must add zero
        # host round trips (under the TPU tunnel one fetch costs ~100
        # decode steps; the naive per-admission int() sync dominated
        # the first on-chip measurement)
        self.first_toks = jnp.zeros((n_slots,), jnp.int32)
        self._donate = bool(donate)
        if mesh is not None:
            # replicate the slot mirrors ONCE: a donating executable
            # can only alias an input already laid out like its
            # output — an uncommitted single-device mirror would be
            # resharded at dispatch (a copy) and its donation
            # silently dropped
            from jax.sharding import PartitionSpec as _P

            from kubegpu_tpu.parallel.sharding import device_put_tree
            (self.tokens, self.pos, self.temps,
             self.first_toks) = device_put_tree(
                mesh, (self.tokens, self.pos, self.temps,
                       self.first_toks), (_P(),) * 4)
        # live-byte accounting + donated-handle hygiene (HBM-lean
        # serving): around each donating dispatch the engine
        # snapshots the handles it is about to donate, samples how
        # many pool/mirror bytes are REALLY live right after
        # (donation-on: inputs already deleted, 1x the pool;
        # donation-off: input and output both live, 2x), and — the
        # debug guard — force-deletes any stale input handle a
        # backend left undeleted, so a leaked reference fails loudly
        # (RuntimeError: Array has been deleted) instead of silently
        # pinning pool-sized garbage
        from kubegpu_tpu.obs.metrics import LiveBytesTracker
        self.hbm = LiveBytesTracker(metrics)
        self.slot_req: dict[int, _Request] = {}
        self.queue: _AdmissionQueue = _AdmissionQueue()
        self._inflight: jax.Array | None = None   # fused (block, firsts)
        self._next_rid = 0
        # generated-token bookkeeping (totals; the bench's numerator)
        self.emitted_tokens = 0      # all generated tokens (incl. the
        #                              prefill-produced first token)
        self._decode_tokens = 0      # tokens produced BY decode steps
        self.slot_steps = 0          # decode slot-steps spent
        self.prefill_waves = 0       # admission waves dispatched
        self.wave_sizes: list[int] = []   # k of each dispatched wave
        self.wave_log: list[tuple[int, int]] = []   # (k, bucket)
        # serving fast-path accounting (the prefix-cache bench's
        # numerators): prompt tokens actually prefilled vs saved by
        # page aliasing, and how many pool pages were aliased instead
        # of allocated+rewritten
        self.prefill_tokens = 0
        self.prefill_tokens_saved = 0
        self.pages_aliased = 0
        self.prefix_hits = 0         # admissions that aliased >= 1 page
        self.chunks_run = 0          # prefill chunks dispatched
        # KV compression & eviction accounting (ISSUE 15): pages the
        # eviction policy released, and the bench-measured quality
        # delta vs a bf16 reference (note_kv_quality sets it)
        self.pages_evicted = 0
        self.kv_quality_delta = 0.0
        if metrics is not None:
            metrics.set_gauge("serve_kv_bits",
                              self.kv_bits if paged else 16)
        # per-tick decode stall: host wall of the tick's admission +
        # prefill-chunk work (a lower-bound proxy under async dispatch;
        # the bench computes the device-anchored version from
        # _tick_log).  Exposed through obs/metrics when a registry is
        # passed (histogram "serve_decode_stall_ms").
        self.stall_ms: list[float] = []
        self._tick_log: list[dict] = []   # per tick: admission work
        self._tick_work: list = []
        self._metrics = metrics
        # chip-tick cost attribution (ISSUE 20): each dispatched tick
        # charges tp × fused-k chip-ticks to the resident slots'
        # (tenant, tier) keys, pro-rata by work units — prefill slots
        # weigh the prompt tokens they prefilled this tick
        # (_tick_prefill_tokens, filled at wave/chunk time), decode
        # slots one unit each.  busy_ticks counts the device ticks
        # independently, so the exact conservation law
        # (Σ attributed == tp × busy_ticks) is checkable from outside
        # the ledger.
        self.cost = CostLedger()
        self.busy_ticks = 0
        self._tick_prefill_tokens: dict[int, int] = {}
        # -- speculative accounting (per-slot adaptive γ + the bench's
        # acceptance numerators).  ``_gcap`` is the per-slot cap the
        # next verify tick applies; ``_accept_ema`` the rolling match
        # fraction driving it (reset optimistic at admission so a new
        # request starts at full γ).  ``_spec_active`` snapshots the
        # active mask AT DISPATCH so collect attributes stats to the
        # slots that actually drafted.
        self._gcap = np.full((n_slots,), self.spec_gamma, np.int32)
        self._gcap_dev = None
        self._gcap_last: np.ndarray | None = None
        self._accept_ema = np.ones((n_slots,), np.float64)
        self._spec_active: np.ndarray | None = None
        self.spec_ticks = 0
        self.spec_drafts_proposed = 0
        self.spec_drafts_accepted = 0
        # -- double-buffered collect (collect_overlap=True): host wall
        # spent inside the tick-N readout while tick N+1 was already
        # computing — the latency the overlap hides (exported as the
        # ``serve_collect_overlap_ms`` histogram via ``metrics``)
        self.overlap_ms: list[float] = []
        # -- fault injection + self-defense (ISSUE 4 tentpole) --------
        # ``chaos``: a ChaosInjector consulted at every dispatch;
        # ``tick_deadline_s``: watchdog — a tick whose wall time
        # exceeds it declares this replica STALLED (TickStallError, a
        # ReplicaDeadError: a replica that stalls once can wedge
        # drain() forever, so policy is failover, not waiting);
        # ``max_retries`` bounds per-request quarantine/replay cycles;
        # ``spec_degrade_after``: N consecutive verify ticks with ZERO
        # accepted drafts across every active slot degrade the engine
        # to γ=0 (the plain decode-block path — bit-exact, since the
        # spec engine only ever amortizes dispatches);
        # ``debug_invariants`` runs the page-leak detector every tick.
        self.chaos = chaos
        self.tick_deadline_s = tick_deadline_s
        self.max_retries = int(max_retries)
        self.spec_degrade_after = spec_degrade_after
        self.debug_invariants = bool(debug_invariants)
        self.dead: str | None = None      # death reason, once dead
        self.spec_degraded = False
        self._spec_reject_streak = 0
        self.slots_quarantined = 0
        self.requests_retried = 0
        self.requests_shed = 0
        self.dispatch_failures = 0
        # -- SLO-guarded admission (ISSUE 13) -------------------------
        # ``_tier_mode`` flips on at the first submit carrying a tier
        # > 0 or a tick deadline; until then the queue is plain FIFO
        # and every pre-existing schedule is bit-identical.  Tenant
        # quotas bound IN-FLIGHT (queued + resident) requests per
        # tenant — an over-quota submit is shed at the door, before
        # any prefill work.
        self._seq = 0
        self._tier_mode = False
        self.tenant_quotas = dict(tenant_quotas or {})
        self._tenant_load: dict[str, int] = {}
        self._rid_tenant: dict[int, str] = {}
        self.requests_preempted = 0
        self.requests_resumed = 0
        self.deadline_misses = 0
        self.shed_by_reason: dict[str, int] = {}
        self.replay_ms: list[float] = []
        self._jseed = seed
        # step counter for replay backoff: advances every step() even
        # when nothing dispatches (self._tick does not — an idle
        # engine would never clear a replay's backoff gate)
        self._step_count = 0
        # slots admitted whose prefill-produced first token has not
        # been consumed yet (replaces the r3 ``not req.tokens`` test,
        # which a replayed request — non-empty tokens — would break)
        self._await_first: set[int] = set()
        # shed/cancelled requests surfaced by the next step()'s return
        self._failed: list[_Request] = []
        # requests that FINISHED in the same step() that killed the
        # replica — the pool harvests these at failover so a completed
        # request is never replayed (exactly-once)
        self._orphans: list[_Request] = []
        self._inflight_spec = False       # layout of the in-flight fetch
        # -- page migration (disaggregated serving) -------------------
        # ``_migrate_out``: rids whose page chain must be exported at
        # retirement (the prefill-specialist contract); ``_exports``:
        # finished exports keyed by rid, held host-side until the pool
        # pops them with take_export() — host numpy, so they survive
        # this replica's death and a mid-migration kill replays
        # exactly-once from the stash.
        self._migrate_out: set[int] = set()
        self._exports: dict[int, dict] = {}
        self.chains_exported = 0
        self.chains_imported = 0
        self.pages_migrated_out = 0
        self.pages_migrated_in = 0
        # -- fused-block accounting (ISSUE 8) -------------------------
        # ``_inflight_kind``/``_inflight_k`` pin the LAYOUT of the
        # in-flight fetch ("block" | "spec" | "fused" | "fused_spec")
        # so collect routes it correctly even when the overlap path
        # has already dispatched the next (possibly different-kind)
        # tick; ``_fused_budget`` snapshots the per-slot token budget
        # the device froze lanes against, so consume can replay the
        # freeze deterministically host-side.
        self._inflight_kind = "block"
        self._inflight_k = 1
        self._fused_budget: np.ndarray | None = None
        self.fused_dispatches = 0     # fused blocks dispatched
        self.fused_ticks_run = 0      # device ticks covered by them
        self.fused_stalls = 0         # lanes frozen by the page cap
        self.fused_block_ms: list[float] = []   # sync wall per block
        self.host_overhead_ms: list[float] = []  # per step(): wall - sync
        self._sync_ms_last = 0.0
        # -- request tracing + tick profiler (ISSUE 6) ----------------
        # ``tracer``: an obs.spans.Tracer; ``trace_ctx``: the decoded
        # KUBETPU_TRACE_CONTEXT SpanContext (the crishim.inject span),
        # so engine spans join the scheduler's trace.  Every traced
        # site is a single ``is not None`` branch and no traced value
        # feeds device math — tokens are bit-exact traced/untraced
        # (asserted by the cb_trace_overhead bench row).  The anchor
        # span roots the engine's tree even with no inbound context.
        self._tracer = tracer
        self._trace_parent = trace_ctx
        self._engine_anchor = None
        if tracer is not None:
            with tracer.span("engine.start", parent=trace_ctx,
                             attrs={"n_slots": n_slots, "paged": paged,
                                    "tp": self.tp,
                                    "spec_gamma": self.spec_gamma}) as sp:
                self._engine_anchor = sp.context
        self._req_spans: dict[int, object] = {}   # rid → open Span
        self._submit_ts: dict[int, float] = {}    # rid → submit wall
        self._submit_tick: dict[int, int] = {}    # rid → submit tick
        self._first_tok_ts: dict[int, float] = {}  # rid → TTFT wall

    def warmup(self) -> None:
        """Compile every executable this engine can hit — the decode
        block and each power-of-two wave size per prompt bucket —
        WITHOUT touching engine state (all calls are functional and
        their outputs are discarded; counters stay at zero).  Benches
        and serving pods call this before the timed window: the first
        full-slot wave otherwise compiles a [n_slots, bucket] prefill
        mid-measurement (observed eating ~95% of a flagship run)."""
        decode_block, prefill_wave, adopt_wave = self._fns[:3]
        outs = []
        # Every executable DONATES its big KV argument AND the slot
        # mirrors it rebinds, so warmup chains scratch copies of ALL
        # of them through the calls and never touches the live state
        # (donating a live array would invalidate the engine).
        scratch = jax.tree.map(
            jnp.zeros_like, self.pool if self.paged else self.cache)
        sft = jnp.zeros_like(self.first_toks)
        stok = jnp.zeros_like(self.tokens)
        spos = jnp.zeros_like(self.pos)
        stmp = jnp.zeros_like(self.temps)

        def adopt(scratch, sft, stok, spos, stmp, cache_w, k, bucket,
                  firsts, lens, temps):
            common = (jnp.arange(k, dtype=jnp.int32), firsts, lens,
                      temps, sft, stok, spos, stmp, k)
            if self.paged:
                page_dst = jnp.zeros(
                    (k, bucket // self.page_size), jnp.int32)
                return adopt_wave(scratch, cache_w, page_dst, *common)
            return adopt_wave(scratch, cache_w, *common)

        def block(scratch, stok, spos, stmp):
            if self.paged and self.spec_gamma:
                # the spec engine never dispatches the decode block —
                # its hot executable is the verify tick
                out = self._fns[5](
                    self.params, self._draft_params, scratch,
                    jnp.asarray(self._pt), jnp.asarray(self._tvec),
                    jnp.asarray(self._tpad), stok, spos,
                    jnp.asarray(self.active), jnp.asarray(self._gcap))
                return out[0], out[6], out[4], out[5]
            if self.paged:
                out = decode_block(
                    self.params, scratch, jnp.asarray(self._pt),
                    jnp.asarray(self._tvec), jnp.asarray(self._tpad),
                    stok, spos, jnp.asarray(self.active),
                    stmp, self._base_key, jnp.int32(0))
            else:
                out = decode_block(
                    self.params, scratch, stok, spos,
                    jnp.asarray(self.active), stmp,
                    self._base_key, jnp.int32(0))
            return out[0], out[3], out[1], out[2]

        for bucket in self.prompt_buckets:
            k = 1
            while k <= min(self.n_slots, self.max_wave):
                padded = jnp.zeros((k, bucket), jnp.int32)
                lens = jnp.ones((k,), jnp.int32)
                temps = jnp.zeros((k,), jnp.float32)
                firsts, cache_w = prefill_wave(
                    self.params, padded, lens, temps,
                    self._base_key, jnp.int32(0))
                scratch, sft, stok, spos, stmp = adopt(
                    scratch, sft, stok, spos, stmp, cache_w, k,
                    bucket, firsts, lens, temps)
                outs.append(firsts)
                k *= 2
        if self.paged and (self.prefix_cache_enabled
                           or self.chunked_prefill):
            ck = jnp.zeros((1, self.prefill_chunk), jnp.int32)
            ptr = jnp.zeros((1, self.max_pages), jnp.int32)
            tok, scratch = self._fns[3](
                self.params, scratch, ck, ptr, jnp.int32(0),
                jnp.ones((1,), jnp.int32), jnp.zeros((1,), jnp.float32),
                self._base_key, jnp.int32(0))
            outs.append(tok)
        if self.paged:
            # migration executables (gather a zero chain out of the
            # scratch pool and scatter it straight back — trash-page
            # indices only, so the scratch stays all-zero)
            zids = jnp.zeros((self.max_pages,), jnp.int32)
            chain = self._fns[8](scratch, zids)
            scratch = self._fns[9](scratch, chain, zids)
            outs.append(chain["k"])
        blk, scratch, stok, spos = block(scratch, stok, spos, stmp)
        outs.append(blk)
        if self.paged and self.fused_ticks > 1:
            # fused executables (zero budget/cap: every lane frozen —
            # compile is shape-driven, the math never runs hot here)
            zb = jnp.zeros((self.n_slots,), jnp.int32)
            zpt = jnp.zeros((self.n_slots, self.max_pages), jnp.int32)
            if self._fns[7] is not None:
                out = self._fns[7](
                    self.params, self._draft_params, scratch, zpt, zb,
                    zb, stok, spos, jnp.asarray(self.active), zb, zb,
                    jnp.asarray(self._gcap))
                outs.append(out[0])
                scratch = out[6]
                stok, spos = out[4], out[5]
            if self._fns[6] is not None:
                out = self._fns[6](
                    self.params, scratch, zpt, zb, zb, stok, spos,
                    jnp.asarray(self.active), stmp, zb, zb,
                    self._base_key, jnp.int32(0))
                outs.append(out[0])
                scratch = out[3]
                stok, spos = out[1], out[2]
        for o in outs:   # block until every compile finished
            np.asarray(o)

    # -- donated-handle hygiene + HBM accounting ------------------------

    def _state_handles(self) -> list:
        """Every device handle the donating executables may consume:
        the page pool / dense cache leaves plus the four slot
        mirrors.  Int8 pools contribute their scale leaves here like
        any other — values and scales alias (and are accounted)
        together."""
        hs = list(jax.tree.leaves(self.pool if self.paged
                                  else self.cache))
        hs += [self.first_toks, self.tokens, self.pos, self.temps]
        return hs

    def _pre_dispatch(self) -> list:
        """Snapshot the donated-state handles ahead of a dispatch."""
        return self._state_handles()

    def _post_dispatch(self, old: list) -> None:
        """Enforce the donation contract after a rebind and account
        live bytes.  ``live`` counts the rebound state plus any OLD
        handle not yet released — with donation on, jit deletes the
        donated inputs at dispatch, so live is ~1× the pool; with it
        off, input and output coexist (~2×; the bench row's A/B).
        The debug-guard half: any stale donated handle a backend
        left undeleted is deleted HERE, so code that squirreled away
        a pre-dispatch reference fails loudly on its next read
        (``RuntimeError: Array has been deleted``) instead of
        silently pinning pool-sized garbage in HBM."""
        new = self._state_handles()
        new_ids = {id(h) for h in new}
        live = sum(h.nbytes for h in new)
        stale = [h for h in old
                 if id(h) not in new_ids and not h.is_deleted()]
        live += sum(h.nbytes for h in stale)
        self.hbm.sample(live)
        if self._donate:
            for h in stale:
                h.delete()

    # -- submission -----------------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               temperature: float = 0.0,
               deadline_s: float | None = None,
               migrate_out: bool = False, tier: int = 0,
               tenant: str = "",
               deadline_ticks: int | None = None) -> int:
        """Enqueue a request.  ``prompt``: 1-D int sequence;
        ``temperature`` 0 decodes greedily, > 0 samples;
        ``deadline_s`` (optional) cancels the request if it has not
        completed that many seconds from now (it returns FAILED with
        ``error='deadline exceeded'`` — partial tokens preserved).
        ``migrate_out`` marks the request for page-chain export at
        retirement (the prefill-specialist leg of disaggregated
        serving): its pool pages are gathered host-side just before
        release and published via :meth:`take_export`.

        SLO-guarded admission (ISSUE 13): ``tier`` is the priority
        tier (0 = most critical; admission is strict across tiers and
        EDF within one), ``tenant`` the quota bucket (an over-quota
        submit is shed at the door with a ``quota``-tagged reason,
        surfaced FAILED by the next step()), ``deadline_ticks`` a
        deterministic step-count deadline that both prunes the
        request before prefill once expired AND orders it within its
        tier (the wall-clock ``deadline_s`` only prunes — wall time
        is weather, so it never drives the schedule)."""
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if tier < 0:
            raise ValueError(f"tier must be >= 0, got {tier}")
        if deadline_ticks is not None and deadline_ticks < 1:
            raise ValueError(
                f"deadline_ticks must be >= 1, got {deadline_ticks}")
        if migrate_out and not self.paged:
            raise ValueError(
                "migrate_out needs the paged pool (page chains are "
                "the migration transfer unit)")
        if temperature < 0:
            raise ValueError(
                f"temperature must be >= 0, got {temperature}")
        if temperature > 0 and not self.sampling:
            raise ValueError(
                "temperature > 0 needs a sampling-enabled engine "
                "(ContinuousBatcher(..., sampling=True)) — greedy-only "
                "engines compile argmax-only decode steps")
        prompt_np = np.asarray(prompt, np.int32)
        prompt = jnp.asarray(prompt_np)
        t = int(prompt.shape[0])
        if t < 1:
            # an empty prompt would index prefill logits at -1, which
            # dynamic_index clamps to 0 — silent garbage, not an error
            raise ValueError("prompt must have at least one token")
        bucket = next((b for b in self.prompt_buckets if b >= t), None)
        if bucket is None:
            raise ValueError(
                f"prompt length {t} exceeds largest bucket "
                f"{self.prompt_buckets[-1]}")
        # overhang: how far past the last consumed token the engine may
        # physically write (a full stride block, or a verify tick's
        # γ+1-wide slab — whichever path this engine runs)
        overhang = max(self.stride, self.spec_gamma + 1
                       if self.spec_gamma else 0)
        if t + max_new_tokens + overhang > self.max_len:
            raise ValueError(
                f"prompt {t} + max_new {max_new_tokens} + overhang "
                f"{overhang} (stride/γ+1) > max_len {self.max_len}")
        if self.paged:
            need = self._pages_needed(max_new_tokens, bucket)
            if need > self.total_pages:
                # an unfittable request would park at the queue front
                # and stall FIFO admission forever — reject at submit
                raise ValueError(
                    f"request needs {need} pages (bucket {bucket} + "
                    f"{max_new_tokens} new tokens) but the pool has "
                    f"only {self.total_pages}")
        padded = jnp.zeros((1, bucket), jnp.int32).at[0, :t].set(prompt)
        keys: tuple = ()
        if self.paged and self.prefix_cache_enabled:
            # chain hashes over whole leading page-blocks; the page
            # holding token t-1 is never cacheable (its logits seed the
            # first generated token, and it may be partial)
            n_cacheable = (t - 1) // self.page_size
            keys = tuple(
                hash(prompt_np[:(i + 1) * self.page_size].tobytes())
                for i in range(n_cacheable))
        req = _Request(rid=self._next_rid, prompt_len=t,
                       max_new_tokens=max_new_tokens,
                       temperature=float(temperature),
                       prefix_keys=keys, prompt=prompt_np,
                       admit_len=t, tier=int(tier), tenant=str(tenant),
                       deadline=(time.monotonic() + deadline_s
                                 if deadline_s is not None else None),
                       deadline_tick=(self._step_count + deadline_ticks
                                      if deadline_ticks is not None
                                      else None))
        self._next_rid += 1
        req.submit_tick = self._tick
        if tier > 0 or deadline_ticks is not None:
            self._tier_mode = True
        if self._tracer is not None or self._metrics is not None:
            self._submit_ts[req.rid] = time.perf_counter()
            self._submit_tick[req.rid] = self._tick
        if self._tracer is not None:
            sp = self._tracer.start_span(
                "request", parent=self._engine_anchor,
                attrs={"rid": req.rid, "prompt_len": t,
                       "max_new_tokens": max_new_tokens,
                       "tier": int(tier)})
            self._req_spans[req.rid] = sp
        quota = self.tenant_quotas.get(req.tenant) if req.tenant else None
        if (quota is not None
                and self._tenant_load.get(req.tenant, 0) >= quota):
            # over-quota: rejected at the door — never queued, never
            # prefilled; surfaced FAILED by the next step() return
            self._shed(req, f"tenant {req.tenant!r} over quota "
                       f"({quota} in flight)", reason="quota")
            return req.rid
        if req.tenant:
            self._rid_tenant[req.rid] = req.tenant
            self._tenant_load[req.tenant] = \
                self._tenant_load.get(req.tenant, 0) + 1
        if migrate_out:
            self._migrate_out.add(req.rid)
        req.seq = self._seq
        self._seq += 1
        self.queue.append((req, padded))
        return req.rid

    # -- the engine tick ------------------------------------------------

    def _pages_needed(self, max_new_tokens: int, bucket: int) -> int:
        """Pool pages a request occupies for its whole lifetime: its
        prompt bucket plus the decode extent its blocks will flush
        (full stride blocks, so garbage tails are still owned pages).
        A speculative engine's decode extent is ``max_new + γ`` instead
        — each verify tick writes a γ+1 slab whose rejected tail may
        overhang the accepted frontier by up to γ positions."""
        if self.spec_gamma:
            dec_pages = -(-(max_new_tokens + self.spec_gamma)
                          // self.page_size)
        else:
            blocks = -(-(max_new_tokens - 1) // self.stride)
            dec_pages = -(-(blocks * self.stride) // self.page_size)
        return bucket // self.page_size + dec_pages

    # -- refcounted page allocation (prefix caching) --------------------

    def _prefix_hit_run(self, req: _Request) -> int:
        """Longest run of leading cacheable pages present in the
        registry.  Contiguity from page 0 is required: LRU eviction
        drops single pages, so key i alone does not imply keys < i."""
        if not self.prefix_cache_enabled:
            return 0
        h = 0
        for key in req.prefix_keys:
            if key not in self._prefix_cache:
                break
            h += 1
        return h

    def _available_pages(self) -> int:
        """Pages an admission can claim: the free list plus cached
        pages no slot currently references (LRU-reclaimable)."""
        cached_free = sum(
            1 for p in self._prefix_cache.values()
            if self._page_refs.get(p, 0) == 0)
        return len(self._free_pages) + cached_free

    def _alloc_pages(self, n: int) -> list[int]:
        """Claim n pages at refcount 1, evicting LRU unreferenced
        cached pages when the free list runs dry (the admission gates
        guarantee availability — exhaustion here is a bug)."""
        out: list[int] = []
        for _ in range(n):
            if self._free_pages:
                p = self._free_pages.pop()
            else:
                p = self._evict_cached_page()
            self._page_refs[p] = 1
            out.append(p)
        return out

    def _evict_cached_page(self) -> int:
        for key, p in list(self._prefix_cache.items()):   # LRU first
            if self._page_refs.get(p, 0) == 0:
                del self._prefix_cache[key]
                del self._page_key[p]
                del self._page_refs[p]
                return p
        raise RuntimeError(
            "page pool exhausted past the admission gate")

    def _alias_pages(self, req: _Request, hits: int) -> list[int]:
        """Take shared references on the request's cached prefix pages
        (and refresh their LRU position)."""
        pages: list[int] = []
        for key in req.prefix_keys[:hits]:
            p = self._prefix_cache[key]
            self._prefix_cache.move_to_end(key)
            self._page_refs[p] += 1
            pages.append(p)
        return pages

    def _register_prefix(self, req: _Request, pages: list[int]) -> None:
        """Publish a finished prefill's cacheable prompt pages.  First
        writer wins per key; a page aliased FROM the registry is
        already present under the same chain key and is skipped."""
        if not self.prefix_cache_enabled:
            return
        for key, p in zip(req.prefix_keys, pages):
            if key in self._prefix_cache or p in self._page_key:
                continue
            self._prefix_cache[key] = p
            self._page_key[p] = key

    def _shed(self, req: _Request, why: str,
              reason: str = "pressure") -> None:
        """Graceful degradation: fail ONE admission instead of letting
        it deadlock the FIFO queue (it is surfaced as a FAILED request
        by the next step() return, never silently dropped).
        ``reason`` tags the shed cause — ``pressure`` (pool/bucket
        exhaustion), ``quota`` (tenant over its in-flight cap),
        ``deadline`` (pruned from the queue before prefill) — so the
        breakdown separates overload policy from capacity faults."""
        req.done = True
        req.error = why
        self.requests_shed += 1
        # ktp: allow(KTP005) keyed by the 3 fixed reason strings
        self.shed_by_reason[reason] = \
            self.shed_by_reason.get(reason, 0) + 1
        if self._metrics is not None:
            self._metrics.inc("serve_requests_shed")
            self._metrics.inc("serve_requests_shed" + f"_{reason}")
            if self._tier_mode:
                self._metrics.inc("serve_requests_shed"
                                  + f"_t{req.tier}")
        self._failed.append(req)
        self._finish_request_trace(req)

    def _note_resume(self, req: _Request, slot: int) -> None:
        """A parked (preempted) request just re-entered a slot: its
        replay prefill of prompt + accepted tokens is the bit-exact
        greedy resume.  Counted once per park/resume cycle."""
        if not req.resuming:
            return
        req.resuming = False
        self.requests_resumed += 1
        if self._metrics is not None:
            self._metrics.inc("serve_requests_resumed")
        if self._tracer is not None:
            self._tracer.instant(
                "request.resume", self._req_spans.get(req.rid),
                attrs={"rid": req.rid, "slot": slot,
                       "tier": req.tier,
                       "preemptions": req.preemptions})

    def _sort_queue(self) -> None:
        """Tier-strict, EDF-within-tier admission order: sort the
        queue by (tier, deadline_tick, seq).  Strict across tiers —
        a tier-k request never admits while a tier-(k-1) request is
        admittable; EDF within a tier on the DETERMINISTIC tick
        deadline (requests without one sort after every request with
        one, in FIFO ``seq`` order, so the untiered engine's schedule
        is exactly the FIFO it always was).  The wall-clock
        ``deadline_s`` never participates: wall time is weather and
        must not drive the schedule the deterministic twins gate."""
        if len(self.queue) > 1:
            self.queue = _AdmissionQueue(sorted(
                self.queue,
                key=lambda e: (e[0].tier,
                               e[0].deadline_tick
                               if e[0].deadline_tick is not None
                               else float("inf"),
                               e[0].seq)))

    # -- request tracing hooks (ISSUE 6) --------------------------------
    # Callers gate on ``self._tracer is not None or self._metrics is
    # not None`` so the untraced, unmetered engine pays nothing.

    def _trace_admit(self, req: _Request, slot: int, how: str) -> None:
        """Queue wait ends here: the moment the request owns a slot."""
        now = time.perf_counter()
        t_sub = self._submit_ts.get(req.rid)
        wait_ms = (now - t_sub) * 1e3 if t_sub is not None else None
        if wait_ms is not None and self._metrics is not None:
            self._metrics.observe("serve_queue_wait_ms", wait_ms)
        # tick-denominated twin: engine service rounds spent queued.
        # Wall clocks are weather on a loaded host; the tick count is
        # a pure function of the admission schedule, so CPU smoke
        # benches gate on THIS and leave the ms tails to hardware.
        k_sub = self._submit_tick.get(req.rid)
        if k_sub is not None and self._metrics is not None:
            self._metrics.observe("serve_queue_wait_ticks",
                                  float(self._tick - k_sub))
            if self._tier_mode:
                # per-tier twin (``_t<k>`` suffix): the degradation
                # story in one histogram family — under overload the
                # low tiers absorb the queueing, the top tier doesn't
                self._metrics.observe("serve_queue_wait_ticks"
                                      + f"_t{req.tier}",
                                      float(self._tick - k_sub))
        if self._tracer is None:
            return
        sp = self._req_spans.get(req.rid)
        if sp is not None and wait_ms is not None:
            sp.set_attr("queue_wait_ms", round(wait_ms, 3))
        self._tracer.instant(
            "request.admit", sp, attrs={"rid": req.rid, "slot": slot,
                                        "how": how})

    def _trace_first_token(self, req: _Request) -> None:
        """TTFT: first generated token consumed on the host."""
        if req.first_tick < 0:
            req.first_tick = self._tick
        if req.rid in self._first_tok_ts:
            return   # replayed request — TTFT already stamped
        now = time.perf_counter()
        self._first_tok_ts[req.rid] = now
        t_sub = self._submit_ts.get(req.rid)
        if t_sub is None:
            return
        ttft = (now - t_sub) * 1e3
        if self._metrics is not None:
            self._metrics.observe("serve_ttft_ms", ttft)
            k_sub = self._submit_tick.get(req.rid)
            if k_sub is not None:
                self._metrics.observe("serve_ttft_ticks",
                                      float(self._tick - k_sub))
        sp = self._req_spans.get(req.rid)
        if sp is not None:
            sp.set_attr("ttft_ms", round(ttft, 3))

    def _finish_request_trace(self, req: _Request) -> None:
        """Close the request span (idempotent — pops its state) with
        TTFT / per-output-token time attributes; called wherever a
        request reaches a terminal state (retire/shed/cancel/fail)."""
        if req.finish_tick < 0:
            req.finish_tick = self._tick
        ten = self._rid_tenant.pop(req.rid, None)
        if ten is not None:
            # terminal = the tenant's in-flight quota slot frees (this
            # pop makes the release exactly-once across re-entries);
            # idle tenants evict so the dict stays bounded by the
            # live tenant set
            left = max(0, self._tenant_load.get(ten, 1) - 1)
            if left:
                self._tenant_load[ten] = left
            else:
                self._tenant_load.pop(ten, None)
        t_first = self._first_tok_ts.pop(req.rid, None)
        self._submit_ts.pop(req.rid, None)
        self._submit_tick.pop(req.rid, None)
        sp = self._req_spans.pop(req.rid, None)
        if sp is None and (self._metrics is None or t_first is None):
            return
        now = time.perf_counter()
        tok_ms = None
        if t_first is not None and len(req.tokens) > 1:
            tok_ms = (now - t_first) * 1e3 / (len(req.tokens) - 1)
            if self._metrics is not None:
                self._metrics.observe("serve_token_ms", tok_ms)
        if sp is not None:
            sp.set_attr("tokens", len(req.tokens))
            if tok_ms is not None:
                sp.set_attr("token_ms", round(tok_ms, 4))
            if req.error is not None:
                sp.set_attr("error", req.error)
            sp.end(now)

    def _trace_tick(self, t_tick: float, t_col: float, t_adm: float,
                    stall: float, t_d0: float,
                    n_finished: int) -> None:
        """Tick-level profiler: one ``engine.tick`` span per step with
        collect / admit / dispatch-or-verify phase children, rebuilt
        from the phase timestamps the engine measures anyway (so the
        profiler adds bookkeeping, not timing)."""
        tr = self._tracer
        now = time.perf_counter()
        tick = tr.add_span(
            "engine.tick", t_tick, now, parent=self._engine_anchor,
            attrs={"tick": self._tick - 1, "spec": self._inflight_spec,
                   "fused_k": self._inflight_k,
                   "slots": len(self.slot_req)}).context
        tr.add_span("engine.collect", t_tick, t_col, parent=tick,
                    attrs={"finished": n_finished})
        tr.add_span("engine.admit", t_adm, t_adm + stall / 1e3,
                    parent=tick, attrs={"work": len(self._tick_work)})
        tr.add_span("engine.verify" if self._inflight_spec
                    else "engine.dispatch", t_d0, now, parent=tick)

    def _admit(self) -> None:
        from kubegpu_tpu.ops.paged_attention import decode_capacity
        prefill_wave, adopt_wave = self._fns[1], self._fns[2]
        free = deque(s for s in range(self.n_slots)
                     if s not in self.slot_req)
        if self._tier_mode:
            # tier-strict + EDF admission order (FIFO until the first
            # tiered submit — the sort key degenerates to ``seq``)
            self._sort_queue()
            if self.queue and not free:
                # slot pressure: the most critical queued request
                # outranks a resident lower-tier decoder — park the
                # lowest-priority victim(s) and admit into its slot
                req0, p0 = self.queue[0]
                if req0.not_before_tick <= self._step_count:
                    need = 0
                    if self.paged:
                        need = (self._pages_needed(req0.remaining_new,
                                                   p0.shape[1])
                                - self._prefix_hit_run(req0))
                    free.extend(sorted(
                        self._maybe_preempt(req0, need,
                                            need_slot=True)))
        while free and self.queue:
            req0, p0 = self.queue[0]
            if req0.not_before_tick > self._step_count:
                # replay backoff gate: a quarantined request waits out
                # its jittered backoff at the queue front (FIFO is
                # preserved; the delay is a few ticks)
                break
            if self.paged:
                # page-admission gate: the queue FRONT must fit (FIFO
                # is preserved — nothing jumps a request that is only
                # waiting for pages).  Aliased prefix pages don't count
                # against the ask, and unreferenced cached pages count
                # as reclaimable capacity.
                hits0 = self._prefix_hit_run(req0)
                need0 = self._pages_needed(req0.remaining_new,
                                           p0.shape[1])
                if need0 - hits0 > self.total_pages:
                    # pool-exhaustion backpressure: this admission can
                    # NEVER fit (even with every page free) — a replay
                    # whose prompt grew past the pool.  Shed it instead
                    # of deadlocking the queue behind it.
                    self.queue.popleft()
                    self._shed(req0, f"shed: needs {need0 - hits0} "
                               f"pages, pool has {self.total_pages}")
                    continue
                if (need0 - hits0) > self._available_pages():
                    if self._tier_mode:
                        # page pressure: park lower-priority decoders
                        # before making a critical admission wait
                        freed = self._maybe_preempt(
                            req0, need0 - hits0, need_slot=False)
                        if freed:
                            free.extend(sorted(freed))
                            # parked victims re-entered the queue —
                            # restore tier order before re-evaluating
                            self._sort_queue()
                            continue
                    break
                # prefix-aliased tails and long prompts (chunked mode)
                # admit per-slot through the chunk path — no wave
                if hits0 or (self.chunked_prefill
                             and p0.shape[1] > self.prefill_chunk):
                    self._admit_chunked(free.popleft(), hits0)
                    continue
            # WAVE admission: consecutive queue-front requests sharing
            # one prompt bucket prefill as a single [k, bucket] batch
            # (one prefill + one adopt dispatch instead of 2k, and the
            # batched prompt matmuls beat k batch-1 passes).  k rounds
            # down to a power of two so the per-(k, bucket) executable
            # count stays at log2(n_slots) per bucket; FIFO order is
            # preserved — a different-bucket request at the front just
            # bounds this wave, never gets jumped.
            bucket = self.queue[0][1].shape[1]
            n_same = 1
            # with prefix caching on, the wave stops before (a) a
            # request that can already alias the registry and (b) a
            # request sharing its leading page with an EARLIER wave
            # member — both should alias instead of re-prefilling, and
            # registration happens right after this wave adopts
            seen_lead = ({self.queue[0][0].prefix_keys[0]}
                         if self.prefix_cache_enabled
                         and self.queue[0][0].prefix_keys else set())
            for r, p in list(self.queue)[1:min(len(self.queue),
                                               len(free))]:
                if p.shape[1] != bucket:
                    break
                if self.prefix_cache_enabled and r.prefix_keys:
                    if self._prefix_hit_run(r) \
                            or r.prefix_keys[0] in seen_lead:
                        break
                    seen_lead.add(r.prefix_keys[0])
                n_same += 1
            k = 1
            while k * 2 <= min(n_same, len(free), self.max_wave):
                k *= 2
            if self.paged:
                # shrink the wave until its TOTAL page need fits (the
                # front alone was already checked, so k >= 1 survives)
                while k > 1 and sum(
                        self._pages_needed(r.remaining_new, bucket)
                        for r, _ in list(self.queue)[:k]
                        ) > self._available_pages():
                    k //= 2
            wave = [self.queue.popleft() for _ in range(k)]
            slots = [free.popleft() for _ in range(k)]
            padded = jnp.concatenate([p for _, p in wave], axis=0)
            true_lens = jnp.asarray(
                [r.admit_len for r, _ in wave], jnp.int32)
            temps_w = jnp.asarray(
                [r.temperature for r, _ in wave], jnp.float32)
            firsts, cache_w = prefill_wave(
                self.params, padded, true_lens, temps_w,
                self._base_key, jnp.int32(wave[0][0].rid))
            self.prefill_waves += 1
            self.wave_sizes.append(k)
            # two dispatches per WAVE, zero host fetches: first-token
            # values reach req.tokens at the next tick's fused fetch
            if self.paged:
                n_prompt_pages = bucket // self.page_size
                page_dst = np.zeros((k, n_prompt_pages), np.int32)
                for i, (slot, (req, _)) in enumerate(zip(slots, wave)):
                    need = self._pages_needed(req.remaining_new, bucket)
                    pages = self._alloc_pages(need)
                    self._slot_pages[slot] = pages
                    self._pt[slot, :] = 0
                    self._pt[slot, :need] = pages
                    self._tvec[slot] = req.admit_len
                    self._tpad[slot] = bucket
                    self._cap[slot] = decode_capacity(
                        need, bucket, self.page_size)
                    self._mark_tables_dirty(slot)
                    page_dst[i] = pages[:n_prompt_pages]
                held = self._pre_dispatch()
                (self.pool, self.first_toks, self.tokens,
                 self.pos, self.temps) = adopt_wave(
                    self.pool, cache_w, jnp.asarray(page_dst),
                    jnp.asarray(slots, jnp.int32), firsts, true_lens,
                    temps_w, self.first_toks, self.tokens, self.pos,
                    self.temps, k)
                self._post_dispatch(held)
            else:
                held = self._pre_dispatch()
                (self.cache, self.first_toks, self.tokens,
                 self.pos, self.temps) = adopt_wave(
                    self.cache, cache_w, jnp.asarray(slots, jnp.int32),
                    firsts, true_lens, temps_w, self.first_toks,
                    self.tokens, self.pos, self.temps, k)
                self._post_dispatch(held)
            self.wave_log.append((k, bucket))
            self._tick_work.append(("wave", k, bucket))
            self.prefill_tokens += sum(r.admit_len for r, _ in wave)
            for slot, (req, _) in zip(slots, wave):
                remaining = req.remaining_new
                self._set_active(slot, remaining > 1)
                self.slot_req[slot] = req
                self._tick_prefill_tokens[slot] = req.admit_len
                self._await_first.add(slot)
                self.emitted_tokens += 1
                self._note_resume(req, slot)
                if remaining <= 1:
                    req.done = True
            if self._tracer is not None or self._metrics is not None:
                for slot, (req, _) in zip(slots, wave):
                    self._trace_admit(req, slot, "wave")
            if self.paged and self.prefix_cache_enabled:
                # the adopt dispatch above is ordered before any later
                # read, so the pages are publishable immediately — the
                # NEXT iteration of this loop can already alias them
                for slot, (req, _) in zip(slots, wave):
                    self._register_prefix(req, self._slot_pages[slot])

    def _admit_chunked(self, slot: int, hits: int) -> None:
        """Admit the queue-front request onto ``slot`` WITHOUT a
        prefill wave: alias its cached prefix pages, allocate the
        rest, and queue page-aligned prefill chunks that run
        interleaved with decode ticks (so a long prompt never stalls
        every active slot for one full-wave forward).  The slot stays
        inactive until the final chunk lands; the decode block's
        output for it is discarded and its per-block garbage flush
        targets its own first decode page, which the first REAL flush
        overwrites before any position there becomes valid."""
        from kubegpu_tpu.ops.paged_attention import decode_capacity
        req, padded = self.queue.popleft()
        bucket = padded.shape[1]
        need = self._pages_needed(req.remaining_new, bucket)
        aliased = self._alias_pages(req, hits)
        pages = aliased + self._alloc_pages(need - hits)
        self._slot_pages[slot] = pages
        self._pt[slot, :] = 0
        self._pt[slot, :need] = pages
        self._tvec[slot] = req.admit_len
        self._tpad[slot] = bucket
        self._cap[slot] = decode_capacity(need, bucket, self.page_size)
        self._mark_tables_dirty(slot)
        if hits:
            self.prefix_hits += 1
            self.pages_aliased += hits
            self.prefill_tokens_saved += hits * self.page_size
        # right-extend by one chunk so the final dynamic slice never
        # clamps (its pad pages spill into the slot's OWN decode pages
        # — overwritten by the first real flush before becoming valid)
        self._prefilling[slot] = {
            "req": req,
            "padded": jnp.pad(padded, ((0, 0), (0, self.prefill_chunk))),
            "next": hits * self.page_size,
        }
        self.slot_req[slot] = req
        self._set_active(slot, False)
        self._note_resume(req, slot)
        if self._tracer is not None or self._metrics is not None:
            self._trace_admit(req, slot, "chunk")

    def _run_prefill_chunks(self) -> None:
        """One prefill chunk per prefilling slot per tick."""
        if not self._prefilling:
            return
        prefill_chunk, activate_slot = self._fns[3], self._fns[4]
        self._sync_tables()
        for slot in sorted(self._prefilling):
            st = self._prefilling[slot]
            req = st["req"]
            t, c, start = req.admit_len, self.prefill_chunk, st["next"]
            chunk = lax.dynamic_slice_in_dim(st["padded"], start, c,
                                             axis=1)
            pt_row = lax.dynamic_slice_in_dim(self._pt_dev, slot, 1,
                                              axis=0)
            held = self._pre_dispatch()
            tok, self.pool = prefill_chunk(
                self.params, self.pool, chunk, pt_row, jnp.int32(start),
                jnp.full((1,), t, jnp.int32),
                jnp.full((1,), req.temperature, jnp.float32),
                self._base_key, jnp.int32(req.rid))
            self._post_dispatch(held)
            self.chunks_run += 1
            self._tick_work.append(("chunk", c))
            if self._tracer is not None:
                self._tracer.instant(
                    "request.prefill_chunk", self._req_spans.get(req.rid),
                    attrs={"rid": req.rid, "slot": slot, "start": start,
                           "chunk": c})
            self.prefill_tokens += min(t - start, c)
            self._tick_prefill_tokens[slot] = (
                self._tick_prefill_tokens.get(slot, 0)
                + min(t - start, c))
            st["next"] = start + c
            if st["next"] >= t:
                # final chunk (it held position t-1): go live
                held = self._pre_dispatch()
                (self.first_toks, self.tokens, self.pos,
                 self.temps) = activate_slot(
                    self.first_toks, self.tokens, self.pos, self.temps,
                    jnp.int32(slot), tok,
                    jnp.full((1,), t, jnp.int32),
                    jnp.full((1,), req.temperature, jnp.float32))
                self._post_dispatch(held)
                del self._prefilling[slot]
                self._register_prefix(req, self._slot_pages[slot])
                remaining = req.remaining_new
                self._set_active(slot, remaining > 1)
                self._await_first.add(slot)
                self.emitted_tokens += 1
                if remaining <= 1:
                    req.done = True

    # -- fault injection + self-defense (ISSUE 4) -----------------------

    def _die(self, reason: str) -> None:
        """Mark this replica dead and raise; every later step()
        re-raises.  Host-side request state (slot_req/queue/tokens)
        stays intact — the pool's failover path harvests it."""
        self.dead = reason
        if self._metrics is not None:
            self._metrics.inc("serve_replica_deaths")
        raise ReplicaDeadError(reason)

    def _chaos_gate(self) -> None:
        """Apply every chaos event due at this tick, BEFORE the real
        dispatch mutates state (so a failed dispatch retries the exact
        same functional call)."""
        if self.chaos is None:
            return
        due = self.chaos.take(self._tick)
        for i, ev in enumerate(due):
            if ev.kind == "kill_replica":
                self._die(f"chaos: replica killed at tick {self._tick}")
            elif ev.kind == "stall_tick":
                time.sleep(ev.stall_s)
            elif ev.kind == "nan_logits":
                if not self._poison_one_slot():
                    self.chaos.defer(ev, self._tick + 1)
            elif ev.kind == "fail_dispatch":
                for rest in due[i + 1:]:
                    self.chaos.defer(rest, self._tick)
                raise DispatchFailure(
                    f"chaos: dispatch failed at tick {self._tick}")

    def poison_slot(self, slot: int) -> None:
        """Chaos hook: NaN one slot's K/V history (paged: its first
        decode page — never prefix-registered, so the poison cannot be
        aliased into another request; dense: its cache row).  The
        slot's next logits go non-finite while its neighbors stay
        exact — slots are independent batch rows."""
        if self.paged:
            pid = int(self._pt[slot,
                               int(self._tpad[slot]) // self.page_size])
            leaf = "k_scale" if "k_scale" in self.pool else "k"
            self.pool[leaf] = self.pool[leaf].at[:, pid].set(jnp.nan)
        else:
            self.cache["k"] = self.cache["k"].at[:, slot].set(jnp.nan)

    def _poison_one_slot(self) -> bool:
        """Poison the lowest eligible slot (active, past its first
        decode flush so the paged kernel actually reads the poisoned
        page); False defers the event to the next tick."""
        for slot in sorted(self.slot_req):
            if slot in self._prefilling or not self.active[slot]:
                continue
            if self.paged:
                flushed = int(np.asarray(self.pos)[slot]) \
                    - int(self._tvec[slot])
                if flushed < 1:
                    continue
            self.poison_slot(slot)
            return True
        return False

    def _backoff_ticks(self, req: _Request) -> int:
        """Exponential backoff in ticks with deterministic per-(rid,
        attempt) jitter — retries spread out instead of thundering
        back into the same admission window."""
        base = min(1 << (req.retries - 1), 8)
        j = int(np.random.default_rng(
            abs(hash((self._jseed, req.rid, req.retries)))
        ).integers(0, base + 1))
        return base + j

    def _replay(self, req: _Request, why: str) -> None:
        """Re-admit a faulted request: replay prompt = original prompt
        + accepted tokens, budget = what is still owed.  Greedy replay
        is BIT-EXACT (the accepted prefix conditions the same
        continuation), and with prefix caching on the original
        prompt's registered pages make the replay prefill mostly
        aliasing.  Bounded by ``max_retries`` with jittered
        exponential backoff; an unfittable replay is shed, never
        parked."""
        req.retries += 1
        if req.retries > self.max_retries:
            req.done = True
            req.error = f"failed after {req.retries - 1} retries: {why}"
            self._failed.append(req)
            self._finish_request_trace(req)
            return
        if self._tracer is not None:
            self._tracer.instant(
                "request.replay", self._req_spans.get(req.rid),
                attrs={"rid": req.rid, "retries": req.retries,
                       "why": why})
        req.not_before_tick = self._step_count \
            + self._backoff_ticks(req)
        if not self._requeue_host(req, "replay"):
            return
        self.requests_retried += 1
        if self._metrics is not None:
            self._metrics.inc("serve_requests_retried")

    def _requeue_host(self, req: _Request, what: str) -> bool:
        """Rebuild a host-side re-admission (prompt + accepted tokens,
        fresh bucket / prefix keys / enqueue seq) and put it back on
        the queue.  Shared by quarantine/failover replays and by
        preemption parking — both resume through the same bit-exact
        greedy path.  False = the grown prompt no longer fits any
        bucket (shed, never parked at the queue front)."""
        replay = (np.concatenate([req.prompt,
                                  np.asarray(req.tokens, np.int32)])
                  if req.tokens else req.prompt)
        t = int(replay.shape[0])
        bucket = next((b for b in self.prompt_buckets if b >= t), None)
        if bucket is None:
            self._shed(req, f"{what} prompt {t} exceeds largest "
                       f"bucket {self.prompt_buckets[-1]}")
            return False
        keys: tuple = ()
        if self.paged and self.prefix_cache_enabled:
            n_cacheable = (t - 1) // self.page_size
            keys = tuple(
                hash(replay[:(i + 1) * self.page_size].tobytes())
                for i in range(n_cacheable))
        req.prefix_keys = keys
        req.admit_len = t
        padded = jnp.zeros((1, bucket), jnp.int32) \
            .at[0, :t].set(jnp.asarray(replay))
        req.seq = self._seq
        self._seq += 1
        self.queue.append((req, padded))
        return True

    # -- low-priority decode preemption (ISSUE 13) ----------------------

    def _preempt_slot(self, slot: int, req: _Request) -> None:
        """Park a lower-priority DECODING request host-side so its
        slot and pool pages serve a more critical admission: release
        the pages, requeue prompt + accepted tokens.  The resume is
        the engine's standing bit-exact greedy replay (the accepted
        prefix conditions the identical continuation, prefix-cache
        accelerated), so preemption is exactly-once and
        token-identical to an unpreempted run.  Unlike quarantine it
        consumes NO retry budget — being outranked is policy, not a
        fault.  ``not_before_tick`` defers the resume one step so a
        park can never bounce straight back into the slot it just
        vacated ahead of the request it was preempted for."""
        self.requests_preempted += 1
        req.preemptions += 1
        if self._metrics is not None:
            self._metrics.inc("serve_requests_preempted")
            self._metrics.inc("serve_requests_preempted"
                              + f"_t{req.tier}")
        if self._tracer is not None:
            self._tracer.instant(
                "request.preempt", self._req_spans.get(req.rid),
                attrs={"rid": req.rid, "slot": slot, "tier": req.tier,
                       "tokens": len(req.tokens)})
        del self.slot_req[slot]
        self._set_active(slot, False)
        self._await_first.discard(slot)
        self._release_pages(slot)
        if self.spec_gamma:
            self._accept_ema[slot] = 1.0
            self._gcap[slot] = self.spec_gamma
        req.resuming = True
        req.not_before_tick = max(req.not_before_tick,
                                  self._step_count + 1)
        self._requeue_host(req, "parked")

    def _maybe_preempt(self, req0: _Request, need_pages: int,
                       need_slot: bool) -> list[int]:
        """Free capacity for ``req0`` by preempting strictly
        lower-priority decoding slots (lowest tier first, newest
        first within a tier — the work discarded is the least
        critical and the least sunk).  Victims must be greedy (a
        sampled resume is not bit-exact), fully admitted (not
        chunk-prefilling, not awaiting their first token — their
        accounting has no in-flight remainder), not a migrate-out
        leg, and REPLAYABLE: the grown prompt (prompt + accepted
        tokens) must still fit the largest bucket, else parking
        would silently convert a healthy request into a shed.
        Returns the freed slot ids; empty when no eligible victim
        exists or preempting ALL of them still could not fit the
        ask (then nobody is parked in vain)."""
        victims = sorted(
            ((s, r) for s, r in self.slot_req.items()
             if r.tier > req0.tier and not r.done
             and s not in self._prefilling
             and s not in self._await_first
             and r.temperature == 0.0
             and r.rid not in self._migrate_out
             and int(r.prompt.shape[0]) + len(r.tokens)
             <= self.prompt_buckets[-1]),
            key=lambda sr: (-sr[1].tier, -sr[1].seq))
        if not victims:
            return []
        if self.paged and need_pages > self._available_pages() + sum(
                sum(1 for p in self._slot_pages.get(s, ()) if p)
                for s, _ in victims):
            return []
        freed: list[int] = []
        for s, r in victims:
            fits = (not self.paged
                    or need_pages <= self._available_pages())
            if fits and (freed or not need_slot):
                break
            self._preempt_slot(s, r)
            freed.append(s)
        return freed

    def _quarantine(self, slot: int, req: _Request) -> None:
        """Invalid-logit self-defense: pull the offending slot out of
        the batch (its math never mixed with its neighbors'), drop the
        poisoned tick's tokens, release its pages, and replay the
        request from its last good token."""
        self.slots_quarantined += 1
        if self._metrics is not None:
            self._metrics.inc("serve_slots_quarantined")
        if self._tracer is not None:
            self._tracer.instant(
                "request.quarantine", self._req_spans.get(req.rid),
                attrs={"rid": req.rid, "slot": slot})
        del self.slot_req[slot]
        self._set_active(slot, False)
        self._prefilling.pop(slot, None)
        self._await_first.discard(slot)
        self._release_pages(slot)
        if self.spec_gamma:
            self._accept_ema[slot] = 1.0
            self._gcap[slot] = self.spec_gamma
        self._replay(req, "non-finite logits quarantined")

    def _cancel_req(self, req: _Request, why: str) -> None:
        """Remove a request from wherever it lives (queue, slot,
        chunk-prefill) and mark it failed with its partial tokens."""
        req.done = True
        req.error = why
        self._finish_request_trace(req)
        for i, (r, _) in enumerate(self.queue):
            if r.rid == req.rid:
                del self.queue[i]
                break
        for slot, r in list(self.slot_req.items()):
            if r.rid == req.rid:
                del self.slot_req[slot]
                self._set_active(slot, False)
                self._prefilling.pop(slot, None)
                self._await_first.discard(slot)
                self._release_pages(slot)
                if self.spec_gamma:
                    self._accept_ema[slot] = 1.0
                    self._gcap[slot] = self.spec_gamma
                break

    def cancel(self, rid: int, reason: str = "canceled"):
        """Cancel a queued or resident request.  Returns the request
        (done, ``error`` set, partial tokens preserved) or None if the
        rid is unknown/already finished.  The canceled request is
        returned HERE, not from a later step()."""
        for r, _ in self.queue:
            if r.rid == rid:
                self._cancel_req(r, reason)
                return r
        for r in self.slot_req.values():
            if r.rid == rid:
                self._cancel_req(r, reason)
                return r
        return None

    def _expire_deadlines(self, finished: list) -> None:
        """Cancel requests whose per-request deadline passed; they
        surface as FAILED in this step's return.  Runs BEFORE
        admission, so a QUEUED expiry is pruned without ever burning
        prefill work — those count as ``deadline``-tagged sheds,
        distinct from the pressure sheds (and from resident expiries,
        which cancel mid-decode with their partial tokens).  Both the
        wall-clock ``deadline_s`` and the deterministic
        ``deadline_ticks`` cutoffs expire here."""
        reqs = [r for r, _ in self.queue] + list(self.slot_req.values())
        if not any(r.deadline is not None or r.deadline_tick is not None
                   for r in reqs):
            return
        now = time.monotonic()

        def _expired(r: _Request) -> bool:
            return ((r.deadline is not None and now > r.deadline)
                    or (r.deadline_tick is not None
                        and self._step_count > r.deadline_tick))

        for req, _ in [e for e in self.queue if _expired(e[0])]:
            self._note_deadline_miss(req)
            for i, (q, _) in enumerate(self.queue):
                if q.rid == req.rid:
                    del self.queue[i]
                    break
            # pruned pre-prefill: shed (reason-tagged), surfaced by
            # this step's return via the _failed drain
            self._shed(req, "deadline exceeded", reason="deadline")
        for req in [r for r in self.slot_req.values() if _expired(r)]:
            self._note_deadline_miss(req)
            self._cancel_req(req, "deadline exceeded")
            finished.append(req)

    def _note_deadline_miss(self, req: _Request) -> None:
        self.deadline_misses += 1
        if self._metrics is not None:
            self._metrics.inc("serve_deadline_miss")
            if self._tier_mode:
                self._metrics.inc("serve_deadline_miss"
                                  + f"_t{req.tier}")

    def take_orphans(self) -> list[_Request]:
        """Requests that FINISHED in the very step() that killed this
        replica — the failover path collects them so a completed
        request is never replayed (exactly-once completion)."""
        out, self._orphans = self._orphans, []
        return out

    def _watchdog(self, t0: float, finished: list) -> None:
        """Tick watchdog: a tick whose wall time blew the deadline
        marks this replica STALLED.  Post-hoc by construction (a hung
        device sync cannot be interrupted in-thread), but that is
        exactly the drain()-wedging failure mode — policy is failover,
        not waiting."""
        if self.tick_deadline_s is None or self.dead is not None:
            return
        dt = time.perf_counter() - t0
        if dt > self.tick_deadline_s:
            self._orphans.extend(finished)
            self.dead = (f"watchdog: tick {self._tick - 1} took "
                         f"{dt * 1e3:.0f} ms > deadline "
                         f"{self.tick_deadline_s * 1e3:.0f} ms")
            if self._metrics is not None:
                self._metrics.inc("serve_tick_stalls")
            raise TickStallError(self.dead)

    def _dispatch_with_retry(self) -> None:
        """Bounded in-place retry on transient dispatch failures (the
        chaos gate raises BEFORE the functional dispatch mutates
        state, so a retry re-runs identical math); repeated failure
        escalates to replica death."""
        for _ in range(3):
            try:
                return self._dispatch_tick()
            except DispatchFailure:
                self.dispatch_failures += 1
                if self._metrics is not None:
                    self._metrics.inc("serve_dispatch_failures")
        self._die("dispatch failed 3 times in a row")

    def _charge_chip_ticks(self) -> None:
        """Attribute the chip-ticks of the dispatch that just went out
        — ``_inflight_k`` device ticks × ``tp`` chips — to the
        resident slots' (tenant, tier) keys (ISSUE 20).  Pro-rata by
        work units: a prefilling slot weighs the prompt tokens it
        prefilled this tick, a decoding slot one unit.  Called right
        after a successful dispatch, so ``_inflight_k`` is the block
        the device is actually computing."""
        if not self.slot_req:
            return
        k = max(1, int(self._inflight_k or 1))
        self.busy_ticks += k
        entries = [(req.tenant, req.tier,
                    self._tick_prefill_tokens.get(slot, 0) or 1)
                   for slot, req in sorted(self.slot_req.items())]
        self.cost.charge(entries, max(1, int(self.tp or 1)) * k)
        self._tick_prefill_tokens.clear()

    # -- device-resident slot-state mirrors (ISSUE 8 satellite) ---------
    # Page tables, length scalars, capacity, the active mask, and the
    # spec γ caps used to re-upload from numpy on EVERY dispatch; each
    # now lives on device and re-uploads only when a host mutation
    # actually changed it (steady-state decode ticks touch none).

    def _mark_tables_dirty(self, slot: int) -> None:
        """Record that ``slot``'s table row / length scalars changed.
        Small churn patches device rows in place at the next sync;
        more than a couple of dirty rows falls back to a full upload
        (None = everything dirty)."""
        self._tables_dirty = True
        if self._dirty_slots is not None:
            self._dirty_slots.add(slot)
            if len(self._dirty_slots) > 2:
                self._dirty_slots = None

    def _sync_tables(self) -> None:
        """Bring the device mirrors of ``_pt``/``_tvec``/``_tpad``/
        ``_cap`` current.  No-op on clean tables."""
        if not self._tables_dirty:
            return
        ds = self._dirty_slots
        if ds and self._pt_dev is not None:
            for s in ds:
                self._pt_dev = self._pt_dev.at[s].set(
                    jnp.asarray(self._pt[s]))
                self._tvec_dev = self._tvec_dev.at[s].set(
                    int(self._tvec[s]))
                self._tpad_dev = self._tpad_dev.at[s].set(
                    int(self._tpad[s]))
                self._cap_dev = self._cap_dev.at[s].set(
                    int(self._cap[s]))
        else:
            self._pt_dev = jnp.asarray(self._pt)
            self._tvec_dev = jnp.asarray(self._tvec)
            self._tpad_dev = jnp.asarray(self._tpad)
            self._cap_dev = jnp.asarray(self._cap)
        self._tables_dirty = False
        self._dirty_slots = set()

    def _set_active(self, slot: int, val: bool) -> None:
        if bool(self.active[slot]) != bool(val):
            self.active[slot] = val
            self._active_dirty = True

    def _active_mask(self):
        if self._active_dirty or self._active_dev is None:
            self._active_dev = jnp.asarray(self.active)
            self._active_dirty = False
        return self._active_dev

    def _gcap_mask(self):
        if (self._gcap_dev is None or self._gcap_last is None
                or not np.array_equal(self._gcap, self._gcap_last)):
            self._gcap_dev = jnp.asarray(self._gcap)
            self._gcap_last = self._gcap.copy()
        return self._gcap_dev

    # -- fused multi-tick dispatch (ISSUE 8 tentpole) -------------------

    def _check_eos(self, req: _Request) -> bool:
        """Trim ``req.tokens`` at its first EOS; True = finished."""
        from kubegpu_tpu.models.decode import truncate_at_eos
        return truncate_at_eos(req.tokens, self.eos_id)

    def _fused_k_now(self) -> int:
        """How many ticks the next dispatch may fuse.  K > 1 only in
        the steady state: fusing across an admission / chunk / replay
        boundary would run new work K-1 ticks late, so any pending
        host work drops to the single-tick path."""
        if (self.fused_ticks <= 1 or not self.paged or self.queue
                or self._prefilling or not self.slot_req):
            return 1
        if self.spec_gamma and not self.spec_degraded:
            return self.fused_ticks if self._fns[7] is not None else 1
        return self.fused_ticks if self._fns[6] is not None else 1

    def _dispatch_fused(self, k: int) -> None:
        """Dispatch ONE fused executable covering ``k`` complete
        ticks.  The per-slot token budget (what each request still
        owes, minus its pending first token) freezes a lane the tick
        it is satisfied, so the host consumes exactly the tokens K
        single dispatches would have produced; ``_fused_budget`` keeps
        the numpy snapshot so consume can replay the freeze."""
        budget = np.zeros((self.n_slots,), np.int32)
        for slot, req in self.slot_req.items():
            want = req.max_new_tokens - len(req.tokens)
            if slot in self._await_first:
                want -= 1
            budget[slot] = max(want, 0)
        self._fused_budget = budget
        budget_dev = jnp.asarray(budget)
        held = self._pre_dispatch()
        if self.spec_gamma and not self.spec_degraded:
            (emit, take, matched, badv, self.tokens, self.pos,
             self.pool, stall) = self._fns[7](
                self.params, self._draft_params, self.pool,
                self._pt_dev, self._tvec_dev, self._tpad_dev,
                self.tokens, self.pos, self._active_mask(),
                budget_dev, self._cap_dev, self._gcap_mask())
            self._spec_active = self.active.copy()
            self._inflight_spec = True
            self._inflight_kind = "fused_spec"
            self._inflight = jnp.concatenate(
                [emit.reshape(-1), take.reshape(-1),
                 matched.reshape(-1), badv.reshape(-1), stall,
                 self.first_toks])
        else:
            (blocks, self.tokens, self.pos, self.pool, bads,
             stall) = self._fns[6](
                self.params, self.pool, self._pt_dev, self._tvec_dev,
                self._tpad_dev, self.tokens, self.pos,
                self._active_mask(), self.temps, budget_dev,
                self._cap_dev, self._base_key, jnp.int32(self._tick))
            self._inflight_spec = False
            self._inflight_kind = "fused"
            self._inflight = jnp.concatenate(
                [blocks.reshape(-1), bads.reshape(-1), stall,
                 self.first_toks])
        self._post_dispatch(held)
        self._inflight_k = k
        self.fused_dispatches += 1
        self.fused_ticks_run += k
        self._tick += k

    def _dispatch_tick(self) -> None:
        """Dispatch the next decode work for the CURRENT slot state —
        a stride decode block, a speculative verify tick (spec_gamma
        > 0, not degraded), or a FUSED K-tick block when the engine is
        in steady state (fused_ticks > 1, nothing pending host-side) —
        and fuse the in-flight host fetch (token slab + per-slot
        bad-logit flags + per-slot accounting + every pending first
        token)."""
        if self.dead is not None:
            raise ReplicaDeadError(self.dead)
        self._chaos_gate()
        if self.paged:
            self._sync_tables()
        k = self._fused_k_now()
        if k > 1:
            self._dispatch_fused(k)
            return
        held = self._pre_dispatch()
        if self.paged and self.spec_gamma and not self.spec_degraded:
            (emit, take, matched, badv, self.tokens, self.pos,
             self.pool) = self._fns[5](
                self.params, self._draft_params, self.pool,
                self._pt_dev, self._tvec_dev, self._tpad_dev,
                self.tokens, self.pos, self._active_mask(),
                self._gcap_mask())
            self._spec_active = self.active.copy()
            self._inflight_spec = True
            self._inflight_kind = "spec"
            self._inflight = jnp.concatenate(
                [emit.reshape(-1), take, matched, badv,
                 self.first_toks])
        elif self.paged:
            outs = self._fns[0](
                self.params, self.pool, self._pt_dev,
                self._tvec_dev, self._tpad_dev,
                self.tokens, self.pos, self._active_mask(),
                self.temps, self._base_key, jnp.int32(self._tick))
            if self.evict_policy == "mass":
                (block, self.tokens, self.pos, self.pool, bad,
                 self._mass_pending) = outs
            else:
                block, self.tokens, self.pos, self.pool, bad = outs
            self._inflight_spec = False
            self._inflight_kind = "block"
            self._inflight = jnp.concatenate(
                [block.reshape(-1), bad, self.first_toks])
        else:
            block, self.tokens, self.pos, self.cache, bad = \
                self._fns[0](
                    self.params, self.cache, self.tokens, self.pos,
                    self._active_mask(), self.temps,
                    self._base_key, jnp.int32(self._tick))
            self._inflight_spec = False
            self._inflight_kind = "block"
            self._inflight = jnp.concatenate(
                [block.reshape(-1), bad, self.first_toks])
        self._post_dispatch(held)
        self._inflight_k = 1
        self._tick += 1

    def step(self) -> list[_Request]:
        """One engine tick: collect the previous tick's in-flight block,
        retire its finishers, admit into the freed slots, then dispatch
        the next block and return WITHOUT waiting for it.  One fused
        host round trip per tick (token block + every pending first
        token).  Because the dispatch is asynchronous, the block
        computes during whatever the caller does between ticks (e.g. an
        async server accepting submissions) — and since collection
        precedes dispatch, membership is always current: a finisher
        retires before the next block runs.  Returns the requests that
        FINISHED (from the block dispatched last tick).

        ``collect_overlap=True`` double-buffers the steady state: when
        there is nothing to admit (empty queue, no prefill chunks in
        flight), tick N+1 is dispatched BEFORE the host reads tick N's
        fused block, so the device computes through the readout instead
        of idling behind it (the readout wall is the hidden latency —
        ``serve_collect_overlap_ms``).  Dispatching on the pre-collect
        mask is safe by the engine's standing contracts: a slot that
        finished in tick N runs one garbage tick whose writes resolve
        to owned-or-trash pages and whose tokens the budget clamp
        discards; admission is deferred to the next step, so a freshly
        freed slot is never re-filled under an in-flight stale tick."""
        if self.dead is not None:
            raise ReplicaDeadError(self.dead)
        self._step_count += 1
        self._sync_ms_last = 0.0
        t_tick = time.perf_counter()
        if (self.collect_overlap and self._inflight is not None
                and not self.queue and not self._prefilling
                and self.slot_req):
            prev, prev_spec_active = self._inflight, self._spec_active
            prev_spec = self._inflight_spec
            prev_kind, prev_k = self._inflight_kind, self._inflight_k
            try:
                self._dispatch_with_retry()   # tick N+1, pre-sync
            except ReplicaDeadError:
                # the un-consumed tick N still holds real tokens —
                # account it so the failover path never loses them
                self._orphans.extend(
                    self._consume_any(np.asarray(prev),
                                      prev_spec_active, prev_kind,
                                      prev_k) + self._failed)
                self._failed.clear()
                raise
            self._charge_chip_ticks()
            t0 = time.perf_counter()
            fused = np.asarray(prev)       # overlapped host readout
            dt = (time.perf_counter() - t0) * 1e3
            self.overlap_ms.append(dt)
            if self._metrics is not None:
                self._metrics.observe("serve_collect_overlap_ms", dt)
            if prev_kind in ("fused", "fused_spec"):
                self.fused_block_ms.append(dt)
                if self._metrics is not None:
                    self._metrics.observe("serve_fused_block_ms", dt)
            finished = self._consume_any(fused, prev_spec_active,
                                         prev_kind, prev_k)
            if self._failed:
                finished.extend(self._failed)
                self._failed.clear()
            if self._tracer is not None:
                tick = self._tracer.add_span(
                    "engine.tick", t_tick, time.perf_counter(),
                    parent=self._engine_anchor,
                    attrs={"tick": self._tick - 1, "overlap": True,
                           "spec": prev_spec, "fused_k": prev_k,
                           "slots": len(self.slot_req)}).context
                self._tracer.add_span(
                    "engine.verify" if self._inflight_spec
                    else "engine.dispatch", t_tick, t0, parent=tick)
                self._tracer.add_span(
                    "engine.collect", t0, t0 + dt / 1e3, parent=tick,
                    attrs={"overlap_ms": round(dt, 3),
                           "finished": len(finished)})
            self._note_host_overhead(t_tick, dt)
            self._watchdog(t_tick, finished)
            return finished
        finished = self._collect()
        t_col = time.perf_counter() if self._tracer is not None else 0.0
        try:
            self._expire_deadlines(finished)
            t_adm = time.perf_counter()
            self._tick_work = []
            if self.paged and self.evict_policy is not None:
                self._maybe_evict()
            self._admit()
            if self.paged:
                self._run_prefill_chunks()
            # per-tick decode stall: the admission + chunk work decode
            # slots waited behind this tick (host wall — a lower bound
            # under async dispatch; the bench anchors it on chained
            # per-dispatch costs via _tick_log)
            stall = (time.perf_counter() - t_adm) * 1e3
            if self.slot_req:
                t_d0 = (time.perf_counter()
                        if self._tracer is not None else 0.0)
                self._dispatch_with_retry()
                self._charge_chip_ticks()
                self.stall_ms.append(stall)
                self._tick_log.append({"tick": self._tick - 1,
                                       "work": self._tick_work})
                # the histogram is a DECODE-stall: only ticks where a
                # decode-phase slot actually waited behind the admission
                # + chunk work count (a pure-prefill tick stalls nobody,
                # and on a role-split prefill replica every tick is
                # one).  A max_new_tokens == 1 request HAS no decode
                # phase — after its prefill chunk computes the single
                # token the slot only awaits readout, so it cannot be
                # stalled by chunk work either.
                if self._metrics is not None and any(
                        s not in self._prefilling
                        and self.slot_req[s].max_new_tokens > 1
                        for s in self.slot_req):
                    self._metrics.observe("serve_decode_stall_ms",
                                          stall)
                    # structural twin: HOW MANY admission/chunk work
                    # units the decode-phase slots waited behind this
                    # tick — 0 on a tick that interleaved nothing.
                    # Deterministic (pure schedule), so the CPU smoke
                    # A/B gates on this where the ms tail is weather.
                    self._metrics.observe("serve_decode_stall_work",
                                          float(len(self._tick_work)))
                if self._tracer is not None:
                    self._trace_tick(t_tick, t_col, t_adm, stall,
                                     t_d0, len(finished))
        except ReplicaDeadError:
            # requests that FINISHED this step must survive the death:
            # stash them for the pool's failover harvest (exactly-once)
            self._orphans.extend(finished + self._failed)
            self._failed.clear()
            raise
        if self._failed:
            finished.extend(self._failed)
            self._failed.clear()
        if self.debug_invariants:
            self.check_page_invariants()
        self._note_host_overhead(t_tick, self._sync_ms_last)
        self._watchdog(t_tick, finished)
        _trim_acct(self.stall_ms)
        _trim_acct(self.wave_sizes)
        _trim_acct(self.wave_log)
        _trim_acct(self.overlap_ms)
        _trim_acct(self.fused_block_ms)
        _trim_acct(self.host_overhead_ms)
        _trim_acct(self._tick_log)
        return finished

    def _note_host_overhead(self, t_tick: float,
                            sync_ms: float) -> None:
        """Per-step host overhead: wall time NOT spent in the device
        sync (dispatch bookkeeping, admission, consume) — the cost the
        fused path amortizes over K ticks.  Exposed as the
        ``serve_host_overhead_pct`` gauge and the per-step list the
        ``cb_fused_ticks`` bench reads."""
        wall = (time.perf_counter() - t_tick) * 1e3
        overhead = max(wall - min(sync_ms, wall), 0.0)
        self.host_overhead_ms.append(overhead)
        if self._metrics is not None and wall > 0:
            self._metrics.set_gauge(
                "serve_host_overhead_pct",
                round(100.0 * overhead / wall, 3))

    def _collect(self) -> list[_Request]:
        """Fetch + account the in-flight block, if any."""
        if self._inflight is None:
            return []
        t0 = time.perf_counter()
        fused = np.asarray(self._inflight)    # THE host sync
        self._sync_ms_last = (time.perf_counter() - t0) * 1e3
        spec_active, self._spec_active = self._spec_active, None
        kind, k = self._inflight_kind, self._inflight_k
        self._inflight = None
        if kind in ("fused", "fused_spec"):
            self.fused_block_ms.append(self._sync_ms_last)
            if self._metrics is not None:
                self._metrics.observe("serve_fused_block_ms",
                                      self._sync_ms_last)
        return self._consume_any(fused, spec_active, kind, k)

    def _consume_any(self, fused: np.ndarray,
                     spec_active: np.ndarray | None, kind: str,
                     k: int) -> list[_Request]:
        """Route a fetched slab to the consumer matching its LAYOUT
        (pinned at dispatch — the overlap path may have a different
        kind already in flight by the time this one is read)."""
        if kind in ("fused", "fused_spec"):
            return self._consume_fused(fused, k, spec_active,
                                       kind == "fused_spec")
        return self._consume(fused, spec_active, kind == "spec")

    def _retire(self, slot: int, req: _Request,
                finished: list[_Request]) -> None:
        if (req.rid in self._migrate_out and req.error is None
                and req.tokens):
            # export BEFORE the pages go back to the free list — the
            # gather must see this request's bytes, not a reuse
            self._export_chain_slot(slot, req)
        self._migrate_out.discard(req.rid)
        req.done = True
        finished.append(req)
        self._finish_request_trace(req)
        del self.slot_req[slot]
        self._set_active(slot, False)
        self._release_pages(slot)
        if self.spec_gamma:
            # the NEXT occupant starts optimistic — full γ until its
            # own rolling acceptance says otherwise
            self._accept_ema[slot] = 1.0
            self._gcap[slot] = self.spec_gamma

    # -- page-chain migration (disaggregated serving) -------------------

    def _export_chain_slot(self, slot: int, req: _Request) -> None:
        """Gather the retiring request's page chain host-side and
        stash it for :meth:`take_export`.  The chain covers the FULL
        page-aligned prompt region ``[0, tpad)`` — under the prefill
        contract (``max_new_tokens == 1``) nothing has flushed past it
        — so the importer resumes from bit-identical pool bytes.  The
        export is plain numpy: it survives this replica's death, which
        is what makes a mid-migration kill replay exactly-once."""
        n_chain = int(self._tpad[slot]) // self.page_size
        page_ids = np.zeros((self.max_pages,), np.int32)
        page_ids[:n_chain] = self._pt[slot, :n_chain]
        chain_dev = self._fns[8](self.pool, jnp.asarray(page_ids))
        chain = {name: np.ascontiguousarray(np.asarray(leaf)[:, :n_chain])
                 for name, leaf in chain_dev.items()}
        t = int(self._tvec[slot])
        self._exports[req.rid] = {
            "rid": req.rid, "t": t, "tpad": int(self._tpad[slot]),
            "pages": n_chain, "page_size": self.page_size,
            "prefix_keys": tuple(req.prefix_keys),
            "first_token": int(req.tokens[0]), "prompt": req.prompt,
            "chain": chain, "digest": _chain_digest(chain, t),
        }
        self.chains_exported += 1
        self.pages_migrated_out += n_chain

    def take_export(self, rid: int) -> dict | None:
        """Pop one finished export — exactly-once (a second call
        returns None).  Callable on a DEAD replica: the stash is
        host-side state, not device state."""
        return self._exports.pop(rid, None)

    def take_exports(self) -> dict[int, dict]:
        """Pop every finished export at once (census/test driver)."""
        out, self._exports = self._exports, {}
        return out

    def import_chain(self, export: dict, max_new_tokens: int,
                     temperature: float = 0.0, tier: int = 0,
                     tenant: str = "") -> int | None:
        """Adopt a migrated page chain: verify the digest, allocate
        pages, scatter the chain in, activate a slot mid-decode (the
        first generated token travels inside the export), and register
        the prompt pages in the prefix registry so later shared-prefix
        requests alias them for free.  Returns the LOCAL rid, or
        ``None`` when no slot/pages are free right now (the caller
        retries a later tick).  ``max_new_tokens`` is the TOTAL budget
        for this leg including the already-produced first token."""
        from kubegpu_tpu.ops.paged_attention import decode_capacity
        if not self.paged:
            raise ValueError("import_chain needs the paged pool")
        if self.dead is not None:
            raise ReplicaDeadError(f"replica dead: {self.dead}")
        if max_new_tokens < 2:
            raise ValueError(
                "import_chain needs max_new_tokens >= 2 — a satisfied "
                "request retires at its prefill replica")
        if temperature > 0 and not self.sampling:
            raise ValueError(
                "temperature > 0 needs a sampling-enabled engine")
        if int(export["page_size"]) != self.page_size:
            raise ValueError(
                f"page-size mismatch: chain {export['page_size']} vs "
                f"pool {self.page_size}")
        chain = export["chain"]
        t = int(export["t"])
        if _chain_digest(chain, t) != export["digest"]:
            raise ValueError(
                "chain digest mismatch — torn or corrupted transfer")
        bucket = int(export["tpad"])
        n_chain = int(export["pages"])
        overhang = max(self.stride, self.spec_gamma + 1
                       if self.spec_gamma else 0)
        if t + max_new_tokens + overhang > self.max_len:
            raise ValueError(
                f"prompt {t} + max_new {max_new_tokens} + overhang "
                f"{overhang} > max_len {self.max_len}")
        need = self._pages_needed(max_new_tokens, bucket)
        if need > self.total_pages:
            raise ValueError(
                f"import needs {need} pages but the pool has only "
                f"{self.total_pages}")
        slot = next((s for s in range(self.n_slots)
                     if s not in self.slot_req), None)
        if slot is None or self._available_pages() < need:
            return None
        req = _Request(rid=self._next_rid, prompt_len=t,
                       max_new_tokens=max_new_tokens,
                       temperature=float(temperature),
                       prefix_keys=tuple(export["prefix_keys"]),
                       prompt=np.asarray(export["prompt"], np.int32),
                       admit_len=t, tier=int(tier),
                       tenant=str(tenant))
        self._next_rid += 1
        req.submit_tick = self._tick
        req.seq = self._seq
        self._seq += 1
        if tier > 0:
            self._tier_mode = True
        req.tokens = [int(export["first_token"])]
        pages = self._alloc_pages(need)
        self._slot_pages[slot] = pages
        self._pt[slot, :] = 0
        self._pt[slot, :need] = pages
        self._tvec[slot] = t
        self._tpad[slot] = bucket
        self._cap[slot] = decode_capacity(need, bucket, self.page_size)
        self._mark_tables_dirty(slot)
        # pad the host chain back to the fixed [*, max_pages, ...]
        # upload shape — page_ids is always int32[max_pages], so each
        # migration direction lowers to exactly ONE census signature
        page_dst = np.zeros((self.max_pages,), np.int32)
        page_dst[:n_chain] = pages[:n_chain]
        chain_up = {}
        for name, a in chain.items():
            pad = [(0, 0)] * a.ndim
            pad[1] = (0, self.max_pages - a.shape[1])
            chain_up[name] = jnp.asarray(np.pad(a, pad))
        held = self._pre_dispatch()
        self.pool = self._fns[9](self.pool, chain_up,
                                 jnp.asarray(page_dst))
        (self.first_toks, self.tokens, self.pos,
         self.temps) = self._fns[4](
            self.first_toks, self.tokens, self.pos, self.temps,
            jnp.int32(slot),
            jnp.full((1,), req.tokens[0], jnp.int32),
            jnp.full((1,), t, jnp.int32),
            jnp.full((1,), req.temperature, jnp.float32))
        self._post_dispatch(held)
        self.slot_req[slot] = req
        self._register_prefix(req, pages)
        self._set_active(slot, True)
        # the first token was consumed (and TTFT stamped) at the
        # prefill replica — the slot is NOT awaiting a first token
        if self.spec_gamma:
            self._accept_ema[slot] = 1.0
            self._gcap[slot] = self.spec_gamma
        self.chains_imported += 1
        self.pages_migrated_in += n_chain
        return req.rid

    def _consume(self, fused: np.ndarray,
                 spec_active: np.ndarray | None,
                 spec: bool) -> list[_Request]:
        """Account one fetched fused block.  Non-spec layout:
        ``[stride·B token block, B bad flags, B first tokens]``.  Spec
        layout: ``[B·(γ+1) emit slab, B take, B matched, B bad flags,
        B first tokens]`` — each slot consumed ``take+1`` real tokens
        (accepted drafts + correction; the slab tail is filler),
        ``matched`` drives the per-slot rolling acceptance and
        adaptive γ.  ``spec`` is the layout of THIS fetch (a degraded
        engine mixes spec and block ticks).  A slot whose bad flag is
        set emitted non-finite logits: its tokens from this tick are
        discarded and the slot is quarantined + replayed."""
        finished: list[_Request] = []
        if spec:
            g, b = self.spec_gamma, self.n_slots
            nb = b * (g + 1)
            emit_np = fused[:nb].reshape(b, g + 1)
            take_np = fused[nb:nb + b]
            matched_np = fused[nb + b:nb + 2 * b]
            bad_np = fused[nb + 2 * b:nb + 3 * b]
            firsts_np = fused[nb + 3 * b:]
            self.slot_steps += (g + 1) * b
            self.spec_ticks += 1
            if spec_active is not None and spec_active.any():
                act = spec_active
                self.spec_drafts_proposed += g * int(act.sum())
                self.spec_drafts_accepted += int(take_np[act].sum())
                frac = matched_np[act] / g
                self._accept_ema[act] = (0.7 * self._accept_ema[act]
                                         + 0.3 * frac)
                if self.spec_adaptive:
                    self._gcap = _gamma_from_accept(
                        self._accept_ema, g)
                if self._metrics is not None:
                    for f_ in frac:
                        self._metrics.observe("serve_spec_accept",
                                              float(f_))
                    for t_ in take_np[act]:
                        self._metrics.observe(
                            "serve_spec_tokens_per_tick",
                            float(t_) + 1.0)
                # acceptance-anomaly degradation: N consecutive verify
                # ticks where NO active slot matched a single draft
                # means the draft is paying compute for nothing (or
                # worse, is corrupt) — fall back engine-wide to γ=0,
                # which IS the decode-block path, bit for bit
                if (self.spec_degrade_after is not None
                        and not self.spec_degraded):
                    if int(matched_np[act].sum()) == 0:
                        self._spec_reject_streak += 1
                    else:
                        self._spec_reject_streak = 0
                    if (self._spec_reject_streak
                            >= self.spec_degrade_after):
                        self.spec_degraded = True
                        if self._metrics is not None:
                            self._metrics.inc("serve_spec_degraded")
        else:
            nb = self.stride * self.n_slots
            block_np = fused[:nb].reshape(self.stride, self.n_slots)
            bad_np = fused[nb:nb + self.n_slots]
            firsts_np = fused[nb + self.n_slots:]
            self.slot_steps += self.stride * self.n_slots
        for slot, req in list(self.slot_req.items()):
            if slot in self._prefilling:
                continue   # still chunk-prefilling: nothing emitted yet
            if slot in self._await_first:
                # first token materializes on fetch (prefill-produced,
                # so it predates any poison in this decode tick)
                req.tokens.append(int(firsts_np[slot]))
                self._await_first.discard(slot)
                if (self._tracer is not None
                        or self._metrics is not None):
                    self._trace_first_token(req)
                if self._check_eos(req):
                    self._retire(slot, req, finished)
                    continue
            if req.done:   # single-token request: retires without decode
                self._retire(slot, req, finished)
                continue
            if bad_np[slot]:
                self._quarantine(slot, req)
                continue
            want = req.max_new_tokens - len(req.tokens)
            if spec:
                avail = (int(take_np[slot]) + 1
                         if spec_active is not None
                         and spec_active[slot] else 0)
                take = min(avail, want)
                req.tokens.extend(int(x) for x in emit_np[slot, :take])
            else:
                take = min(self.stride, want)
                req.tokens.extend(int(x) for x in block_np[:take, slot])
            self.emitted_tokens += take
            self._decode_tokens += take
            if (self._check_eos(req)
                    or len(req.tokens) >= req.max_new_tokens):
                self._retire(slot, req, finished)
        return finished

    def _consume_fused(self, fused: np.ndarray, k: int,
                       spec_active: np.ndarray | None,
                       spec: bool) -> list[_Request]:
        """Account one fetched FUSED block — K ticks' worth of state
        in one slab.  Non-spec layout: ``[K·stride·B token blocks,
        K·B bad flags, B stall flags, B first tokens]``; spec layout:
        ``[K·B·(γ+1) emit slabs, K·B take, K·B matched, K·B bad,
        B stall, B first tokens]``.  The per-tick loop below replays
        the device's lane freeze deterministically: a slot stops
        consuming the tick its budget is spent (BEFORE looking at any
        later bad flag — K=1 would have retired it and never seen
        one), is quarantined at its first bad tick, and retires at
        EOS/length exactly where K single ticks would have."""
        finished: list[_Request] = []
        b = self.n_slots
        if spec:
            g = self.spec_gamma
            ne = k * b * (g + 1)
            kb = k * b
            emit_np = fused[:ne].reshape(k, b, g + 1)
            take_np = fused[ne:ne + kb].reshape(k, b)
            matched_np = fused[ne + kb:ne + 2 * kb].reshape(k, b)
            bad_np = fused[ne + 2 * kb:ne + 3 * kb].reshape(k, b)
            stall_np = fused[ne + 3 * kb:ne + 3 * kb + b]
            firsts_np = fused[ne + 3 * kb + b:]
            self.slot_steps += k * (g + 1) * b
            self.spec_ticks += k
            self._spec_stats_fused(k, emit_np, take_np, matched_np,
                                   bad_np, spec_active)
        else:
            ns = k * self.stride * b
            block_np = fused[:ns].reshape(k, self.stride, b)
            bad_np = fused[ns:ns + k * b].reshape(k, b)
            stall_np = fused[ns + k * b:ns + k * b + b]
            firsts_np = fused[ns + k * b + b:]
            self.slot_steps += k * self.stride * b
        self.fused_stalls += int((stall_np != 0).sum())
        for slot, req in list(self.slot_req.items()):
            if slot in self._prefilling:
                continue
            if slot in self._await_first:
                req.tokens.append(int(firsts_np[slot]))
                self._await_first.discard(slot)
                if (self._tracer is not None
                        or self._metrics is not None):
                    self._trace_first_token(req)
                if self._check_eos(req):
                    self._retire(slot, req, finished)
                    continue
            if req.done:
                self._retire(slot, req, finished)
                continue
            quarantined = hit_eos = False
            for kk in range(k):
                want = req.max_new_tokens - len(req.tokens)
                if want <= 0:
                    break
                if bad_np[kk, slot]:
                    self._quarantine(slot, req)
                    quarantined = True
                    break
                if spec:
                    avail = (int(take_np[kk, slot]) + 1
                             if spec_active is not None
                             and spec_active[slot] else 0)
                    take = min(avail, want)
                    req.tokens.extend(
                        int(x) for x in emit_np[kk, slot, :take])
                else:
                    take = min(self.stride, want)
                    req.tokens.extend(
                        int(x) for x in block_np[kk, :take, slot])
                self.emitted_tokens += take
                self._decode_tokens += take
                if self._check_eos(req):
                    hit_eos = True
                    break
            if quarantined:
                continue
            if hit_eos or len(req.tokens) >= req.max_new_tokens:
                self._retire(slot, req, finished)
        return finished

    def _spec_stats_fused(self, k: int, emit_np: np.ndarray,
                          take_np: np.ndarray, matched_np: np.ndarray,
                          bad_np: np.ndarray,
                          spec_active: np.ndarray | None) -> None:
        """Speculative accounting for a fused block: replay the
        device's per-tick act mask host-side (budget / bad / EOS lane
        freezes — the same arithmetic ``_fused_spec_body`` ran) so
        EMA, acceptance metrics, and the degrade streak see exactly
        the ticks each slot actually drafted.  γ adaptation applies
        once per BLOCK (the device held ``gcap`` fixed across it)."""
        if spec_active is None or not spec_active.any():
            return
        g = self.spec_gamma
        budget = (self._fused_budget
                  if self._fused_budget is not None
                  else np.full((self.n_slots,), 1 << 30, np.int64))
        emitted = np.zeros((self.n_slots,), np.int64)
        dead = np.zeros((self.n_slots,), bool)
        for kk in range(k):
            act = spec_active & (emitted < budget) & ~dead
            if act.any():
                self.spec_drafts_proposed += g * int(act.sum())
                self.spec_drafts_accepted += int(
                    take_np[kk][act].sum())
                frac = matched_np[kk][act] / g
                self._accept_ema[act] = (0.7 * self._accept_ema[act]
                                         + 0.3 * frac)
                if self._metrics is not None:
                    for f_ in frac:
                        self._metrics.observe("serve_spec_accept",
                                              float(f_))
                    for t_ in take_np[kk][act]:
                        self._metrics.observe(
                            "serve_spec_tokens_per_tick",
                            float(t_) + 1.0)
                if (self.spec_degrade_after is not None
                        and not self.spec_degraded):
                    if int(matched_np[kk][act].sum()) == 0:
                        self._spec_reject_streak += 1
                    else:
                        self._spec_reject_streak = 0
                    if (self._spec_reject_streak
                            >= self.spec_degrade_after):
                        self.spec_degraded = True
                        if self._metrics is not None:
                            self._metrics.inc("serve_spec_degraded")
            if self.eos_id is not None:
                hit = ((emit_np[kk] == self.eos_id)
                       & (np.arange(g + 1)[None, :]
                          <= take_np[kk][:, None])).any(axis=1)
                dead = dead | (act & hit)
            emitted = emitted + np.where(act, take_np[kk] + 1, 0)
            dead = dead | (bad_np[kk] != 0)
        if self.spec_adaptive:
            self._gcap = _gamma_from_accept(self._accept_ema, g)

    def _release_pages(self, slot: int) -> None:
        """Paged retirement: drop one reference per page the slot
        holds and zero its table row + length scalars, so the slot's
        per-block garbage flush retargets trash page 0.  A page frees
        only on LAST-owner release (aliased prompt pages outlive any
        single sharer); a registered prefix page is retained at ref 0
        in the registry — reclaimable under pressure, instantly
        aliasable until then."""
        if not self.paged:
            return
        for p in self._slot_pages.pop(slot, []):
            if p == 0:
                continue          # eviction hole — already released
            self._page_refs[p] -= 1
            if self._page_refs[p] == 0 and p not in self._page_key:
                del self._page_refs[p]
                self._free_pages.append(p)
        self._pt[slot, :] = 0
        self._tvec[slot] = 0
        self._tpad[slot] = 0
        self._cap[slot] = 0
        if self.evict_policy is not None:
            self._page_mass[slot] = 0.0
        self._mark_tables_dirty(slot)

    # -- attention-aware page eviction (ISSUE 15 tentpole) --------------

    def _maybe_evict(self) -> None:
        """Drop cold PROMPT pages from fully-admitted decoding slots.

        ``window``: a prompt page wholly below the trailing
        ``evict_param``-token window of the prompt is dropped;
        ``mass``: the decode kernel's per-page attention-mass harvest
        (EMA 0.8/0.2 across ticks) marks pages whose mass fell below
        ``evict_param``.  Either way the page releases through the
        standing refcount machinery and its table entry becomes a
        page-id-0 HOLE the kernels' validity masks skip — positions
        keep their rope phases, the page just stops being attended
        (and its HBM goes back to the allocator).

        Safety rails: never the first prompt page (the attention
        sink), never a shared page (refcount > 1 — an aliased prefix
        is some other slot's live context), never a prefix-registered
        page, never a slot that is still prefilling / awaiting its
        first token / exporting a migration chain, and at least two
        real prompt pages always remain."""
        if self.evict_policy == "mass" and self._mass_pending is not None:
            # the block carrying this mass was synced in _collect, so
            # this fetch is a device->host copy of a READY array
            mass = np.asarray(self._mass_pending)
            self._mass_pending = None
            live = self.active & np.isfinite(mass).all(axis=1)
            self._page_mass[live] = (0.8 * self._page_mass[live]
                                     + 0.2 * mass[live])
        p = self.page_size
        for slot, req in list(self.slot_req.items()):
            if (slot in self._prefilling or slot in self._await_first
                    or req.rid in self._migrate_out
                    or not self.active[slot]):
                continue
            n_prompt = int(self._tpad[slot]) // p
            if n_prompt <= 2:
                continue
            row = self._pt[slot]
            live_idx = [pi for pi in range(n_prompt) if row[pi] != 0]
            if self.evict_policy == "window":
                t = int(self._tvec[slot])
                horizon = t - int(self.evict_param)
                cand = [pi for pi in live_idx
                        if pi >= 1 and (pi + 1) * p <= horizon]
            else:
                cand = sorted(
                    (pi for pi in live_idx
                     if pi >= 1
                     and self._page_mass[slot, pi] < self.evict_param),
                    key=lambda pi: self._page_mass[slot, pi])
            remaining = len(live_idx)
            for pi in cand:
                if remaining <= 2:
                    break
                page = int(row[pi])
                if (self._page_refs.get(page, 0) != 1
                        or page in self._page_key):
                    continue    # shared or prefix-retained: keep
                self._pt[slot, pi] = 0
                self._slot_pages[slot][pi] = 0
                del self._page_refs[page]
                self._free_pages.append(page)
                self._page_mass[slot, pi] = 0.0
                self._mark_tables_dirty(slot)
                self.pages_evicted += 1
                remaining -= 1
                if self._metrics is not None:
                    self._metrics.inc("serve_pages_evicted_total")

    def note_kv_quality(self, delta: float) -> None:
        """Record the measured KV-compression quality delta — the
        fraction of greedy tokens that diverge from a bf16 reference
        engine over the same workload.  The bench measures it (the
        engine cannot see its own counterfactual); the engine owns
        the ``serve_kv_quality_delta`` gauge."""
        self.kv_quality_delta = float(delta)
        if self._metrics is not None:
            self._metrics.set_gauge("serve_kv_quality_delta",
                                    round(float(delta), 6))

    def drain(self, max_ticks: int = 10_000) -> list[_Request]:
        """Run until queue and slots are empty; returns every finished
        request in completion order.  Exhausting ``max_ticks`` with
        work still in flight raises a DIAGNOSTIC error naming every
        stuck slot/request (instead of silently returning with work
        resident, which reads as 'lost requests' to the caller)."""
        out: list[_Request] = []
        for _ in range(max_ticks):
            if not self.queue and not self.slot_req:
                return out
            out.extend(self.step())
        raise RuntimeError(
            f"drain did not converge after {max_ticks} ticks; "
            f"stuck work: {self._drain_diagnosis()}")

    def _drain_diagnosis(self) -> str:
        """Who is stuck and why — the payload drain() raises with."""
        parts = []
        for slot in sorted(self.slot_req):
            req = self.slot_req[slot]
            state = ("prefilling" if slot in self._prefilling
                     else "active" if self.active[slot] else "inactive")
            parts.append(
                f"slot {slot}: rid={req.rid} {state} "
                f"tokens={len(req.tokens)}/{req.max_new_tokens} "
                f"retries={req.retries}")
        for req, _ in self.queue:
            parts.append(
                f"queued rid={req.rid} admit_len={req.admit_len} "
                f"not_before_tick={req.not_before_tick} "
                f"(engine step {self._step_count})")
        return "; ".join(parts) or "none visible (bookkeeping bug)"

    def check_page_invariants(self) -> None:
        """Page-leak detector (ISSUE 4 satellite; ``debug_invariants``
        runs it every tick, the test suites call it directly): every
        pool page must be exactly one of (a) free, (b) owned by a live
        slot (refcount == owner count), or (c) prefix-cache-retained
        at refcount 0 — and the three classes must partition
        {1..total_pages} with trash page 0 in none of them.  Raises
        RuntimeError on the first violation (explicit raises, not
        asserts, so ``python -O`` keeps the detector armed)."""
        if not self.paged:
            return

        def fail(msg: str) -> None:
            raise RuntimeError(f"page invariant violated: {msg}")

        allocated = set(self._page_refs)
        if 0 in allocated or 0 in self._page_key:
            fail("trash page 0 allocated or cached")
        if set(self._free_pages) & allocated:
            fail(f"pages both free and allocated: "
                 f"{sorted(set(self._free_pages) & allocated)}")
        universe = set(range(1, self.total_pages + 1))
        if set(self._free_pages) | allocated != universe:
            fail(f"leak/forgery: free∪allocated misses "
                 f"{sorted(universe - set(self._free_pages) - allocated)}"
                 f", extra "
                 f"{sorted((set(self._free_pages) | allocated) - universe)}")
        owners: dict[int, int] = {}
        for slot, pages in self._slot_pages.items():
            real = [p for p in pages if p]   # 0 = eviction hole
            if len(real) != len(set(real)):
                fail(f"slot {slot} references a page twice")
            for p in real:
                owners[p] = owners.get(p, 0) + 1
        for p in allocated:
            if self._page_refs[p] != owners.get(p, 0):
                fail(f"page {p}: refcount {self._page_refs[p]} != "
                     f"{owners.get(p, 0)} owners")
            if self._page_refs[p] == 0 and p not in self._page_key:
                fail(f"page {p} unreferenced but not prefix-retained "
                     "(leaked)")
        for p, key in self._page_key.items():
            if self._prefix_cache.get(key) != p:
                fail(f"page {p} registry back-pointer broken")
        for slot, pages in self._slot_pages.items():
            row = self._pt[slot]
            if list(row[:len(pages)]) != pages \
                    or not (row[len(pages):] == 0).all():
                fail(f"slot {slot} table row disagrees with its pages")
        for slot in range(self.n_slots):
            if slot not in self._slot_pages \
                    and not (self._pt[slot] == 0).all():
                fail(f"retired slot {slot} kept a live page table")

    @property
    def occupancy(self) -> float:
        """Fraction of decode slot-steps whose token was consumed by a
        request (the prefill-produced first token is throughput but not
        a decode step, so it does not count here)."""
        return (self._decode_tokens / self.slot_steps
                if self.slot_steps else 0.0)

    @property
    def spec_acceptance_rate(self) -> float:
        """Accepted draft tokens per proposal slot, over every verify
        tick's ACTIVE slots (the engine analog of ``spec_generate``'s
        acceptance_rate; 0.0 on a non-speculative engine)."""
        return (self.spec_drafts_accepted / self.spec_drafts_proposed
                if self.spec_drafts_proposed else 0.0)

    @property
    def spec_tokens_per_tick(self) -> float:
        """Mean tokens banked per slot per verify tick (accepted
        drafts + the correction) — the factor by which one host sync
        and one dispatch are amortized vs the γ=0 engine's single
        token per slot-step."""
        if not self.spec_drafts_proposed:
            return 0.0
        ticks_slots = self.spec_drafts_proposed / self.spec_gamma
        return 1.0 + self.spec_drafts_accepted / ticks_slots

    @property
    def hbm_pool_bytes(self) -> int:
        """Live pool/mirror bytes at the most recent dispatch boundary
        (``serve_hbm_pool_bytes``): ~1× the pool with donation on, ~2×
        with it off — the cb_hbm_donation bench's A/B numerator."""
        return self.hbm.live

    @property
    def hbm_peak_bytes(self) -> int:
        """Peak of :attr:`hbm_pool_bytes` over the engine's lifetime
        (``serve_hbm_peak_bytes``) — what capacity planning must
        budget for."""
        return self.hbm.peak


@dataclass
class _PoolEntry:
    """Host-side durability record for one pool request: everything
    needed to replay it on another replica after a fault."""
    rid: int
    prompt: np.ndarray
    max_new: int
    temperature: float
    deadline: float | None
    replica: int
    local: int                    # engine-local rid on `replica`
    prefix: list = field(default_factory=list)   # accepted tokens
    retries: int = 0              # failover replays consumed
    tier: int = 0                 # priority tier (survives failover)
    tenant: str = ""              # quota bucket (survives failover)


class DataParallelServePool:
    """dp INDEPENDENT engine replicas behind ONE admission queue — the
    scale-out half of mesh-native serving.  Each replica is a full
    :class:`ContinuousBatcher` pinned to its own ``tp``-device submesh
    (tp=1 pins a replica to a single chip); replicas share NOTHING on
    device — no collective crosses replica boundaries, which is exactly
    why serving dp splits across slices for free where training dp pays
    a gradient allreduce (the scheduler's serving axis weights encode
    the same fact).

    ``submit()`` routes each request to the least-loaded replica
    (queued + resident requests) at submit time — a static round-robin
    would let one long request skew a whole replica's queue.  Prefix
    caching is PER-REPLICA (pools don't alias across meshes), so
    shared-prefix traffic benefits most when the router keeps it
    together; the least-loaded policy is the throughput default.

    FAILOVER (ISSUE 4 tentpole): the pool keeps every request's prompt
    and accepted tokens HOST-side, so when a replica dies mid-tick
    (raises :class:`ReplicaDeadError` — a chaos kill, a watchdog
    stall, or a control-plane eviction observed via
    :meth:`observe_gang_eviction`/:meth:`watch_health`) the pool
    harvests the dead engine's resident requests and re-admits each
    survivor onto the least-loaded healthy replica as prompt +
    accepted tokens with the remaining budget — greedy replay is
    BIT-EXACT and prefix-cache-accelerated on the new replica.
    Completion is idempotent: requests that finished in the dying step
    are collected from the engine's orphan stash, never replayed.
    Replays are bounded per request (``max_replays``); a request that
    exceeds the bound — or whose ``deadline_s`` passes — surfaces as
    FAILED (``error`` set, partial tokens preserved) instead of
    wedging ``drain()``.  Metrics (when a registry is passed):
    ``serve_failover_total``, ``serve_replay_ms``,
    ``serve_requests_retried``."""

    def __init__(self, params: dict, cfg, dp: int = 1, tp: int = 1,
                 devices=None, metrics=None, max_replays: int = 2,
                 chaos=None, tracer=None, trace_ctx=None,
                 routing: str = "affinity", **engine_kw):
        devs = list(devices if devices is not None
                    else jax.devices()[:dp * tp])
        if len(devs) < dp * tp:
            raise ValueError(
                f"dp={dp} x tp={tp} needs {dp * tp} devices, "
                f"have {len(devs)}")
        if routing not in ("affinity", "least_loaded"):
            raise ValueError(
                f"routing must be 'affinity' or 'least_loaded', "
                f"got {routing!r}")
        engine_kw.setdefault("paged", True)
        chaos = chaos or {}
        self.dp, self.tp = dp, tp
        self.routing = routing
        # scale-up construction context: add_replica() builds a fresh
        # engine exactly the way __init__ built the originals
        self._params, self._cfg = params, cfg
        self._devs = devs
        self._chaos = chaos
        self._engine_kw = engine_kw
        self._trace_ctx = trace_ctx
        self._blocks = list(range(dp))    # replica → tp-device block
        self._metrics = metrics
        self._tracer = tracer
        # ONE shared tracer across replicas: a failed-over request's
        # replay spans land on the same timeline as its first life.
        # Engines come from _build_engine() — the single construction
        # seam shared with add_replica(), and the override point the
        # fleet harness uses to mount cost-model replicas under this
        # pool's unmodified routing/failover/autoscale logic.
        self.replicas = [self._build_engine(i) for i in range(dp)]
        self.max_replays = int(max_replays)
        # host-side durability: pool rid → (prompt, budget, accepted
        # prefix from prior incarnations, current placement)
        self._entries: dict[int, _PoolEntry] = {}
        self._local: dict[tuple[int, int], int] = {}  # (rep, lrid)→rid
        self._next_rid = 0
        self.dead_replicas: dict[int, str] = {}
        self.failovers = 0
        self.replay_ms: list[float] = []
        self.requests_retried = 0
        # control-plane glue: serving gang → replica index, plus
        # evictions observed (from a watch or an explicit call) that
        # the next step() turns into failovers
        self._gang_replica: dict[str, int] = {}
        self._pending_deaths: deque[tuple[int, str]] = deque()
        self._unsub = None
        # prefix-affinity routing (ISSUE 14): per-replica digest of
        # chain-hash keys resident (prefix registry) or inbound
        # (queued/slot-resident requests) — refreshed from truth every
        # step() and kept warm incrementally at submit.  Host-side
        # only: no digest ever touches a device buffer.
        self._digests: list[set] = [set() for _ in range(dp)]
        self.routing_affinity_hits = 0
        self.route_log: list[tuple[int, int, int]] = []  # (rid,rep,aff)
        # SLO-driven autoscaling surface (ISSUE 14): graceful retires
        # drain through the failover replay parking (bit-exact, and
        # never burning a request's bounded failover budget)
        self._pending_retire: deque[int] = deque()
        self.autoscale_events = 0
        self.drains = 0
        self.drain_replays = 0
        self.replicas_active_min = dp
        self.replicas_active_max = dp

    def _build_engine(self, i: int):
        """Build replica ``i``'s engine on its tp-device block.  The
        ONLY place an engine is constructed (``__init__`` and
        :meth:`add_replica` both route through here), so a subclass
        that overrides it — e.g. the fleet harness's simulated
        cost-model replica — inherits every routing / admission /
        failover / autoscale path above it unmodified."""
        b = self._blocks[i]
        tp = self.tp
        return ContinuousBatcher(
            self._params, self._cfg,
            mesh=make_serve_mesh(tp, self._devs[b * tp:(b + 1) * tp]),
            metrics=self._metrics, chaos=self._chaos.get(i),
            tracer=self._tracer, trace_ctx=self._trace_ctx,
            **self._engine_kw)

    def warmup(self) -> None:
        for eng in self.replicas:
            eng.warmup()

    def _load(self, eng: ContinuousBatcher) -> int:
        return len(eng.queue) + len(eng.slot_req)

    def _route_key(self, j: int):
        """Least-loaded routing key: request count, then QUEUED PROMPT
        TOKENS as the tiebreak (two replicas with equal request counts
        can hide very different prefill backlogs), then the index for
        determinism.  The token total is the admission queue's
        incrementally-maintained counter, so this stays O(1) per
        replica however deep the queue."""
        eng = self.replicas[j]
        return (self._load(eng), eng.queue.prompt_tokens, j)

    def _alive(self) -> list[int]:
        return [i for i in range(self.dp) if i not in self.dead_replicas]

    # -- prefix-affinity routing (ISSUE 14) -----------------------------

    def _chain_keys(self, prompt_np: np.ndarray) -> tuple:
        """Chain-hash keys of the prompt's leading whole pages — the
        SAME hash scheme the engine computes at submit, evaluated
        host-side by the router so it can score a replica's registry
        before placing the request."""
        eng = self.replicas[0]
        if not (eng.paged and eng.prefix_cache_enabled):
            return ()
        t = int(prompt_np.shape[0])
        n_cacheable = (t - 1) // eng.page_size
        return tuple(
            hash(prompt_np[:(i + 1) * eng.page_size].tobytes())
            for i in range(n_cacheable))

    def _affinity(self, j: int, keys: tuple) -> int:
        """Pages of this chain replica ``j`` already holds (or will —
        its digest includes inbound requests' keys): the longest
        CONTIGUOUS leading run, mirroring the engine's
        ``_prefix_hit_run`` — key i alone never aliases without keys
        < i."""
        d = self._digests[j]
        h = 0
        for key in keys:
            if key not in d:
                break
            h += 1
        return h

    def _route(self, candidates: list[int],
               prompt_np: np.ndarray) -> tuple[int, int]:
        """Pick a replica for ``prompt_np`` among ``candidates``;
        returns ``(replica, affinity_pages)``.  Affinity mode scores
        each candidate ``(load - affinity, load, queued_tokens, j)`` —
        a replica holding the prompt's chain wins unless its load
        penalty dominates.  ZERO affinity anywhere reduces the score
        to exactly the least-loaded key, so traffic with no shared
        prefixes routes bit-identically to the least-loaded policy."""
        if self.routing != "affinity":
            return min(candidates, key=self._route_key), 0
        keys = self._chain_keys(prompt_np)
        aff = ({j: self._affinity(j, keys) for j in candidates}
               if keys else {})
        if keys and any(aff.values()):
            i = min(candidates, key=lambda j: (
                self._load(self.replicas[j]) - aff[j],)
                + self._route_key(j))
            hit = aff[i]
        else:
            i = min(candidates, key=self._route_key)
            hit = 0
        if keys:
            # warm the digest with the keys just placed: a same-tick
            # burst of one prefix sticks together instead of
            # scattering before the registry has cached a page
            self._digests[i].update(keys)
        return i, hit

    def _record_route(self, rid: int, i: int, aff: int) -> None:
        self.route_log.append((rid, i, aff))
        _trim_acct(self.route_log)
        if aff > 0:
            self.routing_affinity_hits += 1
            if self._metrics is not None:
                self._metrics.inc("serve_routing_affinity_hits")
        if self._tracer is not None:
            sp = self._tracer.start_span(
                "request.route",
                parent=self.replicas[i]._engine_anchor,
                attrs={"rid": rid, "replica": i,
                       "affinity_pages": aff,
                       "load": self._load(self.replicas[i])})
            sp.end()

    def _refresh_digests(self) -> None:
        """Rebuild every live replica's digest from truth — registry
        keys plus queued/slot-resident requests' chain keys — on the
        step()/metric-echo path, so routing reads a tick-fresh digest
        (submit-time incremental adds cover the gap between ticks and
        any over-statement from LRU eviction self-heals here)."""
        for j, eng in enumerate(self.replicas):
            if j in self.dead_replicas:
                self._digests[j] = set()
                continue
            d = (set(eng._prefix_cache)
                 if eng.paged and eng.prefix_cache_enabled else set())
            for req in eng.slot_req.values():
                d.update(req.prefix_keys)
            for req, _ in eng.queue:
                d.update(req.prefix_keys)
            self._digests[j] = d

    @property
    def routing_affinity_hit_rate(self) -> float:
        """Fraction of routed submits (recent window) that landed on a
        replica already holding ≥1 page of the prompt's chain."""
        if not self.route_log:
            return 0.0
        return (sum(1 for _, _, a in self.route_log if a > 0)
                / len(self.route_log))

    # -- autoscaling surface (ISSUE 14) ---------------------------------

    def add_replica(self, gang: str | None = None) -> int:
        """Scale up: build one fresh replica on a free tp-device block
        (dead replicas' blocks are reused — their host-side entries
        replayed away at failover, their pools unreachable).  Binding
        ``gang`` links the new replica into the same health-watch
        eviction flow as the originals.  Returns the replica index."""
        tp = self.tp
        n_blocks = len(self._devs) // tp
        used = {self._blocks[j] for j in range(len(self.replicas))
                if j not in self.dead_replicas}
        free = [b for b in range(n_blocks) if b not in used]
        if not free:
            raise ValueError(
                f"no spare devices for a new replica: tp={tp}, "
                f"{len(self._devs)} devices, "
                f"{len(used)} blocks in use")
        b = free[0]
        i = len(self.replicas)
        # one entry per replica ever built — replica indices are stable
        # identities (dead ones keep their slot), so growth is bounded
        # by scale-up actions, not traffic
        # ktp: allow(KTP005) lifetime: one slot per replica identity
        self._blocks.append(b)
        eng = self._build_engine(i)
        self.replicas.append(eng)
        self._digests.append(set())
        self.dp = len(self.replicas)
        if gang is not None:
            self.bind_replica_gang(i, gang)
        self.autoscale_events += 1
        n = len(self._alive())
        self.replicas_active_max = max(self.replicas_active_max, n)
        if self._metrics is not None:
            self._metrics.inc("serve_autoscale_events")
            self._metrics.set_gauge("serve_replicas_active", float(n))
        if self._tracer is not None:
            sp = self._tracer.start_span(
                "pool.scale", parent=eng._engine_anchor,
                attrs={"direction": "up", "replica": i,
                       "replicas_active": n})
            sp.end()
        return i

    def retire_replica(self, i: int) -> None:
        """Graceful scale-down: mark replica ``i`` for drain.  The
        next step() parks its resident requests on the survivors via
        the bit-exact failover replay (prompt + accepted tokens,
        remaining budget) WITHOUT burning any request's bounded
        failover budget — exactly-once completion holds through a
        scale-down exactly as through a fault."""
        if not (0 <= i < self.dp):
            raise ValueError(f"no replica {i} (dp={self.dp})")
        if i in self.dead_replicas:
            raise ValueError(
                f"replica {i} is already dead: "
                f"{self.dead_replicas[i]}")
        if i in self._pending_retire:
            return
        survivors = [j for j in self._alive()
                     if j != i and j not in self._pending_retire]
        if not survivors:
            raise ValueError(
                "cannot retire the last healthy replica")
        self._pending_retire.append(i)

    def _scale_down(self, i: int, done: list) -> None:
        eng = self.replicas[i]
        sp = None
        if self._tracer is not None:
            sp = self._tracer.start_span(
                "pool.scale", parent=eng._engine_anchor,
                attrs={"direction": "down", "replica": i})
        eng.dead = "retired (scale-down)"
        before = self.drain_replays
        self._failover(i, "scale-down drain", done, drain=True)
        self.autoscale_events += 1
        n = len(self._alive())
        self.replicas_active_min = min(self.replicas_active_min, n)
        if self._metrics is not None:
            self._metrics.inc("serve_autoscale_events")
            self._metrics.set_gauge("serve_replicas_active", float(n))
        if sp is not None:
            sp.set_attr("replicas_active", n)
            sp.set_attr("drain_replays",
                        self.drain_replays - before)
            sp.end()

    def submit(self, prompt, max_new_tokens: int,
               temperature: float = 0.0,
               deadline_s: float | None = None, tier: int = 0,
               tenant: str = "") -> int:
        alive = self._alive()
        if not alive:
            raise ReplicaDeadError(
                "no healthy replicas left: "
                + "; ".join(f"replica {i}: {r}"
                            for i, r in self.dead_replicas.items()))
        prompt_np = np.asarray(prompt, np.int32)
        i, aff = self._route(alive, prompt_np)
        local = self.replicas[i].submit(prompt, max_new_tokens,
                                        temperature, tier=tier,
                                        tenant=tenant)
        rid = self._next_rid
        self._next_rid += 1
        self._entries[rid] = _PoolEntry(
            rid=rid, prompt=prompt_np,
            max_new=max_new_tokens, temperature=float(temperature),
            deadline=(time.monotonic() + deadline_s
                      if deadline_s is not None else None),
            replica=i, local=local, tier=int(tier),
            tenant=str(tenant))
        self._local[(i, local)] = rid
        self._record_route(rid, i, aff)
        return rid

    # -- control-plane integration ------------------------------------

    def bind_replica_gang(self, replica: int, gang: str) -> None:
        """Declare that ``replica`` is backed by serving gang ``gang``
        — the link the health controller's evictions resolve through."""
        self._gang_replica[gang] = replica

    def observe_gang_eviction(self, gang: str,
                              reason: str = "gang evicted") -> None:
        """A serving gang died in the control plane (the health
        controller evicted it).  The bound replica is marked for death;
        the next step() fails its requests over to healthy replicas."""
        i = self._gang_replica.pop(gang, None)   # gang is gone: unlink
        if i is not None and i not in self.dead_replicas:
            self._pending_deaths.append((i, f"{reason} (gang {gang})"))

    def watch_health(self, api) -> None:
        """Subscribe to the apiserver watch stream: a DELETED pod of a
        bound serving gang (the eviction's delete-and-recreate) marks
        that replica dead — the same event flow training recovery
        rides, now driving serving failover."""
        from kubegpu_tpu.kubemeta.codec import pod_gang_spec

        def _cb(ev) -> None:
            if ev.kind != "Pod" or ev.type != "DELETED":
                return
            gs = pod_gang_spec(ev.obj)
            if gs is not None and gs.name in self._gang_replica:
                self.observe_gang_eviction(gs.name, "pod evicted")

        self._unsub = api.watch(_cb)

    def close(self) -> None:
        if self._unsub is not None:
            self._unsub()
            self._unsub = None

    # -- failover -----------------------------------------------------

    def _fail_entry(self, e: "_PoolEntry", why: str,
                    done: list) -> None:
        r = _Request(rid=e.rid, prompt_len=int(e.prompt.shape[0]),
                     max_new_tokens=e.max_new,
                     temperature=e.temperature, prompt=e.prompt)
        r.tokens = list(e.prefix)
        r.done = True
        r.error = why
        self._entries.pop(e.rid, None)
        done.append(r)

    def _finish(self, replica: int, r: _Request, done: list) -> None:
        rid = self._local.pop((replica, r.rid), None)
        if rid is None:
            return   # idempotence: already completed/failed over
        e = self._entries.pop(rid, None)
        if e is not None and e.prefix:
            r.tokens = e.prefix + r.tokens
        r.rid = rid
        done.append(r)

    def _replay_submit(self, replay, remaining: int,
                       e: "_PoolEntry") -> tuple[int, int]:
        """Place one replay (prompt + accepted prefix, remaining
        budget) on a healthy replica; returns ``(replica, local_rid)``
        and lets the engine's ValueError propagate.  The routing hook
        the disaggregated pool overrides with role awareness."""
        j = min(self._alive(), key=self._route_key)
        return j, self.replicas[j].submit(replay, remaining,
                                          e.temperature, tier=e.tier,
                                          tenant=e.tenant)

    def _failover(self, i: int, reason: str, done: list,
                  drain: bool = False) -> None:
        """Re-admit every request resident on dead replica ``i`` onto
        healthy replicas via bit-exact greedy replay (prompt +
        accepted tokens, remaining budget).  ``drain=True`` is the
        GRACEFUL variant (scale-down): the same replay parking, but no
        failover counters and no ``retries`` bump — a retire must
        never spend a request's bounded fault budget or trip the
        failover alarms."""
        self.dead_replicas[i] = reason
        if drain:
            self.drains += 1
        else:
            self.failovers += 1
            if self._metrics is not None:
                self._metrics.inc("serve_failover_total")
        t0 = time.perf_counter()
        eng = self.replicas[i]
        fo_span = None
        if self._tracer is not None and not drain:
            fo_span = self._tracer.start_span(
                "pool.failover", parent=eng._engine_anchor,
                attrs={"replica": i, "reason": reason})
        # completed-but-unreturned finishers first (exactly-once)
        for r in eng.take_orphans():
            self._finish(i, r, done)
        resident: dict[int, _Request] = {}
        for req in list(eng.slot_req.values()) \
                + [r for r, _ in eng.queue]:
            resident[req.rid] = req
        alive = self._alive()
        n_replayed = 0
        for local in sorted(resident):
            req = resident[local]
            rid = self._local.pop((i, local), None)
            if rid is None:
                continue
            e = self._entries[rid]
            e.prefix = e.prefix + list(req.tokens)
            remaining = e.max_new - len(e.prefix)
            if remaining < 1:    # finished exactly at the fault
                r = _Request(rid=rid, prompt_len=int(e.prompt.shape[0]),
                             max_new_tokens=e.max_new,
                             temperature=e.temperature, prompt=e.prompt)
                r.tokens = list(e.prefix)
                r.done = True
                self._entries.pop(rid, None)
                done.append(r)
                continue
            if not drain:
                e.retries += 1
                if e.retries > self.max_replays:
                    self._fail_entry(
                        e, f"exceeded {self.max_replays} failovers "
                        f"(last: {reason})", done)
                    continue
            if not alive:
                self._fail_entry(
                    e, f"no healthy replicas left ({reason})", done)
                continue
            replay = (np.concatenate(
                [e.prompt, np.asarray(e.prefix, np.int32)])
                if e.prefix else e.prompt)
            try:
                j, new_local = self._replay_submit(replay, remaining, e)
            except ValueError as err:
                self._fail_entry(e, f"replay rejected: {err}", done)
                continue
            e.replica, e.local = j, new_local
            self._local[(j, new_local)] = rid
            n_replayed += 1
            if drain:
                self.drain_replays += 1
            else:
                self.requests_retried += 1
                if self._metrics is not None:
                    self._metrics.inc("serve_requests_retried")
        dt = (time.perf_counter() - t0) * 1e3
        if n_replayed or resident:
            self.replay_ms.append(dt)
            _trim_acct(self.replay_ms)
            if self._metrics is not None:
                self._metrics.observe("serve_replay_ms", dt)
        # the dead engine never steps again: its digest is gone and
        # its per-replica depth gauge must not linger on /metrics
        self._digests[i] = set()
        if self._metrics is not None:
            self._metrics.delete_gauge(
                "serve_replica_queue_depth" + f"_r{i}")
        if fo_span is not None:
            fo_span.set_attr("replayed", n_replayed)
            fo_span.set_attr("resident", len(resident))
            fo_span.end()

    def _expire_deadlines(self, done: list) -> None:
        if not any(e.deadline is not None
                   for e in self._entries.values()):
            return
        now = time.monotonic()
        for e in list(self._entries.values()):
            if e.deadline is None or now <= e.deadline:
                continue
            eng = self.replicas[e.replica]
            partial = None
            if e.replica not in self.dead_replicas:
                partial = eng.cancel(e.local, "deadline exceeded")
            self._local.pop((e.replica, e.local), None)
            if partial is not None and partial.tokens:
                e.prefix = e.prefix + list(partial.tokens)
            self._fail_entry(e, "deadline exceeded", done)

    def cancel(self, rid: int, reason: str = "canceled"):
        """Cancel a pool request wherever it lives; returns the failed
        request (partial tokens preserved) or None if unknown."""
        e = self._entries.get(rid)
        if e is None:
            return None
        if e.replica not in self.dead_replicas:
            partial = self.replicas[e.replica].cancel(e.local, reason)
            if partial is not None and partial.tokens:
                e.prefix = e.prefix + list(partial.tokens)
        self._local.pop((e.replica, e.local), None)
        sink: list = []
        self._fail_entry(e, reason, sink)
        return sink[0]

    def step(self) -> list[_Request]:
        done: list[_Request] = []
        # graceful retires drain BEFORE eviction-driven deaths: a
        # scale-down whose gang eviction also lands in
        # _pending_deaths must not double as a fault (the death is
        # skipped below because the replica is already dead)
        while self._pending_retire:
            i = self._pending_retire.popleft()
            if i in self.dead_replicas:
                continue
            self._scale_down(i, done)
        while self._pending_deaths:
            i, reason = self._pending_deaths.popleft()
            if i in self.dead_replicas:
                continue
            self.replicas[i].dead = reason   # engine refuses new work
            self._failover(i, reason, done)
        self._expire_deadlines(done)
        for i, eng in enumerate(self.replicas):
            if i in self.dead_replicas:
                continue
            try:
                rs = eng.step()
            except ReplicaDeadError as e:
                self._failover(i, str(e), done)
                continue
            for r in rs:
                self._finish(i, r, done)
        if self.routing == "affinity":
            self._refresh_digests()
        n_alive = len(self._alive())
        self.replicas_active_min = min(self.replicas_active_min,
                                       n_alive)
        self.replicas_active_max = max(self.replicas_active_max,
                                       n_alive)
        if self._metrics is not None:
            # per-replica queue depth (the router's own signal,
            # exported): one gauge per LIVE replica index.  Dead
            # replicas' gauges are deleted at failover/drain AND
            # re-deleted here at the harvest choke point — idempotent,
            # and it holds the no-stale-gauge invariant for any death
            # path that reaches dead_replicas without _failover's
            # cleanup (e.g. an engine declared dead between steps)
            for i in self.dead_replicas:
                self._metrics.delete_gauge(
                    "serve_replica_queue_depth" + f"_r{i}")
            for i, eng in enumerate(self.replicas):
                if i in self.dead_replicas:
                    continue
                self._metrics.set_gauge(
                    "serve_replica_queue_depth" + f"_r{i}",
                    float(len(eng.queue)))
            self._metrics.set_gauge("serve_replicas_active",
                                    float(n_alive))
            self._metrics.set_gauge(
                "serve_chip_ticks_total",
                float(sum(e.cost.busy_chip_ticks
                          for e in self.replicas)))
        return done

    def drain(self, max_ticks: int = 10_000) -> list[_Request]:
        out: list[_Request] = []
        for _ in range(max_ticks):
            if not self._entries and not self._pending_deaths \
                    and not self._pending_retire:
                return out
            out.extend(self.step())
        diag = "; ".join(
            f"replica {e.replica}{' (DEAD)' if e.replica in self.dead_replicas else ''}: "
            f"rid={rid} prefix={len(e.prefix)}/{e.max_new} "
            f"retries={e.retries}"
            for rid, e in sorted(self._entries.items()))
        raise RuntimeError(
            f"drain did not converge after {max_ticks} ticks; "
            f"stuck work: {diag or 'none visible (bookkeeping bug)'}")

    @property
    def emitted_tokens(self) -> int:
        return sum(e.emitted_tokens for e in self.replicas)

    @property
    def occupancy(self) -> float:
        steps = sum(e.slot_steps for e in self.replicas)
        toks = sum(e._decode_tokens for e in self.replicas)
        return toks / steps if steps else 0.0

    # aggregate accounting mirrors the single-engine surface so the
    # serve pod's metric echo works against either
    @property
    def prefill_waves(self) -> int:
        return sum(e.prefill_waves for e in self.replicas)

    @property
    def slot_steps(self) -> int:
        return sum(e.slot_steps for e in self.replicas)

    @property
    def stall_ms(self) -> list[float]:
        return [s for e in self.replicas for s in e.stall_ms]

    # robustness aggregates (the serve pod's failover metric echo)
    @property
    def slots_quarantined(self) -> int:
        return sum(e.slots_quarantined for e in self.replicas)

    @property
    def dispatch_failures(self) -> int:
        return sum(e.dispatch_failures for e in self.replicas)

    @property
    def requests_retried_total(self) -> int:
        """Pool-level failover replays + engine-level quarantine
        replays, combined."""
        return self.requests_retried + sum(
            e.requests_retried for e in self.replicas)

    # SLO-guarded admission aggregates (ISSUE 13): the overload
    # controls are per-engine; the pool sums them for the metric echo
    @property
    def requests_shed(self) -> int:
        return sum(e.requests_shed for e in self.replicas)

    @property
    def requests_preempted(self) -> int:
        return sum(e.requests_preempted for e in self.replicas)

    @property
    def requests_resumed(self) -> int:
        return sum(e.requests_resumed for e in self.replicas)

    @property
    def deadline_misses(self) -> int:
        return sum(e.deadline_misses for e in self.replicas)

    @property
    def shed_by_reason(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for e in self.replicas:
            for k, v in e.shed_by_reason.items():
                out[k] = out.get(k, 0) + v
        return out

    @property
    def spec_acceptance_rate(self) -> float:
        prop = sum(e.spec_drafts_proposed for e in self.replicas)
        acc = sum(e.spec_drafts_accepted for e in self.replicas)
        return acc / prop if prop else 0.0

    @property
    def spec_tokens_per_tick(self) -> float:
        gamma = self.replicas[0].spec_gamma
        if not gamma:
            return 0.0
        prop = sum(e.spec_drafts_proposed for e in self.replicas)
        acc = sum(e.spec_drafts_accepted for e in self.replicas)
        return 1.0 + acc / (prop / gamma) if prop else 0.0

    # HBM accounting aggregates (the donation layer's pool surface):
    # live bytes SUM across replicas (each holds its own pool), peak
    # likewise — a failover snapshot replays from host-side prompts,
    # so dead replicas' pools drop out of the sum with the replica
    @property
    def hbm_pool_bytes(self) -> int:
        return sum(e.hbm_pool_bytes for e in self.replicas
                   if e.dead is None)

    @property
    def hbm_peak_bytes(self) -> int:
        return sum(e.hbm_peak_bytes for e in self.replicas)

    # chip-tick cost aggregates (ISSUE 20): dead replicas KEEP their
    # ledgers — the chips they burned were real spend — so the
    # pool-wide sum conserves across failover, drain, and scale-down
    @property
    def cost(self) -> CostLedger:
        led = CostLedger()
        for e in self.replicas:
            led.merge(e.cost)
        return led

    @property
    def busy_ticks(self) -> int:
        return sum(e.busy_ticks for e in self.replicas)


class DisaggServePool(DataParallelServePool):
    """Disaggregated prefill/decode serving: ``prefill`` replicas are
    PREFILL SPECIALISTS (chunked prefill into page-aligned pool
    blocks, one generated token, never a steady-state decode tick) and
    ``decode`` replicas are DECODE SPECIALISTS (they adopt migrated
    page chains and only ever decode).  At equal chip count this cuts
    BOTH serving tails vs the symmetric pool: TTFT p99 (an arriving
    prompt never queues behind another replica's decode residents) and
    decode-stall p99 (a decoding slot never shares its engine with a
    prefill chunk).

    The MIGRATION PROTOCOL, request by request:

    1. ``submit`` routes the prompt to the least-loaded prefill
       replica as a ``max_new_tokens=1, migrate_out=True`` request —
       the prefill leg produces exactly the first token.
    2. At retirement — BEFORE its pages return to the free list — the
       prefill engine gathers the request's page chain (one fixed-
       shape ``export_chain`` dispatch; int8 scales travel with their
       values), slices it host-side, and stashes it with a sha256
       content digest, the prompt, its chain-hash prefix keys, and the
       first token.
    3. The pool pops the export (exactly-once) and hands it to the
       least-loaded decode replica: ``import_chain`` verifies the
       digest, allocates pages, scatters the chain in (one fixed-shape
       dispatch, pool donated), activates the slot mid-decode, and
       REGISTERS the prompt pages in its prefix registry — later
       shared-prefix requests on that replica alias the migrated pages
       for free.
    4. Decode proceeds from bit-identical pool bytes: greedy tokens
       are bit-exact vs the symmetric pool by construction.

    FAILOVER composes: exports are host-side numpy, so a prefill
    replica dying mid-migration still publishes its finished chains
    (harvested from the orphan stash), pre-export deaths replay the
    prompt onto a surviving prefill replica (prefix-cache
    accelerated), and a decode death replays prompt + accepted tokens
    through prefill again — each request exactly once, bit-exact.
    With every decode replica dead the pool degrades to symmetric
    serving on the prefill side (and vice versa)."""

    def __init__(self, params: dict, cfg, prefill: int = 1,
                 decode: int = 1, tp: int = 1, **kw):
        if prefill < 1 or decode < 1:
            raise ValueError(
                f"need at least one replica per role, got "
                f"prefill={prefill} decode={decode}")
        kw.setdefault("paged", True)
        super().__init__(params, cfg, dp=prefill + decode, tp=tp, **kw)
        self.n_prefill, self.n_decode = prefill, decode
        self.roles = ["prefill"] * prefill + ["decode"] * decode
        # (pool rid, export) pairs finished at a prefill replica and
        # awaiting decode capacity; drained every step, re-queued when
        # the decode side is momentarily full
        self._pending_migrations: deque = deque()
        self.migrations = 0
        self.migrated_pages = 0
        self.migration_ms: list[float] = []

    def _role_replicas(self, role: str, alive: list[int]) -> list[int]:
        return [i for i in alive if self.roles[i] == role]

    def add_replica(self, gang: str | None = None,
                    role: str = "decode") -> int:
        """Scale up one ROLE — the autoscaler grows the decode side
        (decode capacity is what queue-wait pressure starves first);
        prefill growth is the operator's call."""
        if role not in ("prefill", "decode"):
            raise ValueError(
                f"role must be 'prefill' or 'decode', got {role!r}")
        i = super().add_replica(gang)
        self.roles.append(role)
        if role == "prefill":
            self.n_prefill += 1
        else:
            self.n_decode += 1
        return i

    def submit(self, prompt, max_new_tokens: int,
               temperature: float = 0.0,
               deadline_s: float | None = None, tier: int = 0,
               tenant: str = "") -> int:
        alive = self._alive()
        if not alive:
            raise ReplicaDeadError(
                "no healthy replicas left: "
                + "; ".join(f"replica {i}: {r}"
                            for i, r in self.dead_replicas.items()))
        pref = self._role_replicas("prefill", alive)
        dec = self._role_replicas("decode", alive)
        prompt_np = np.asarray(prompt, np.int32)
        if pref and dec and max_new_tokens > 1:
            # the disaggregated fast path: prefill leg emits ONE token
            # — affinity scores the PREFILL role (that is where the
            # prompt's chain pages alias)
            i, aff = self._route(pref, prompt_np)
            local = self.replicas[i].submit(
                prompt, 1, temperature, migrate_out=True, tier=tier,
                tenant=tenant)
        elif pref and max_new_tokens == 1:
            # satisfied entirely by prefill — no migration needed
            i, aff = self._route(pref, prompt_np)
            local = self.replicas[i].submit(prompt, 1, temperature,
                                            tier=tier, tenant=tenant)
        else:
            # degraded: one whole role is dead — serve symmetrically
            # on whatever survives
            i, aff = self._route(alive, prompt_np)
            local = self.replicas[i].submit(prompt, max_new_tokens,
                                            temperature, tier=tier,
                                            tenant=tenant)
        rid = self._next_rid
        self._next_rid += 1
        self._entries[rid] = _PoolEntry(
            rid=rid, prompt=prompt_np,
            max_new=max_new_tokens, temperature=float(temperature),
            deadline=(time.monotonic() + deadline_s
                      if deadline_s is not None else None),
            replica=i, local=local, tier=int(tier),
            tenant=str(tenant))
        self._local[(i, local)] = rid
        self._record_route(rid, i, aff)
        return rid

    def _replay_submit(self, replay, remaining: int,
                       e: "_PoolEntry") -> tuple[int, int]:
        """Role-aware replay: unfinished work goes back through a
        prefill replica as a fresh migrate-out leg (prefix-cache
        accelerated re-prefill of prompt + accepted), falling back to
        symmetric placement when a whole role is dead."""
        alive = self._alive()
        pref = self._role_replicas("prefill", alive)
        dec = self._role_replicas("decode", alive)
        if pref and dec and remaining > 1:
            j = min(pref, key=self._route_key)
            return j, self.replicas[j].submit(
                replay, 1, e.temperature, migrate_out=True,
                tier=e.tier, tenant=e.tenant)
        j = min(alive, key=self._route_key)
        return j, self.replicas[j].submit(replay, remaining,
                                          e.temperature, tier=e.tier,
                                          tenant=e.tenant)

    def _finish(self, replica: int, r: _Request, done: list) -> None:
        """A finisher from a PREFILL replica whose pool budget is not
        yet satisfied is a migration hand-off, not a completion — pop
        its export and queue it for a decode replica.  Everything else
        (decode finishers, satisfied one-token requests, EOS at first
        token, failed requests) falls through to the base path."""
        rid = self._local.get((replica, r.rid))
        if (rid is not None and self.roles[replica] == "prefill"
                and r.error is None):
            e = self._entries[rid]
            eng = self.replicas[replica]
            exp = eng.take_export(r.rid)
            hit_eos = (eng.eos_id is not None and r.tokens
                       and r.tokens[-1] == eng.eos_id)
            needs_more = e.max_new > len(e.prefix) + len(r.tokens)
            if needs_more and not hit_eos:
                self._local.pop((replica, r.rid))
                if exp is not None:
                    # first token rides INSIDE the export — e.prefix
                    # stays as-is so the budget math stays exact
                    self._pending_migrations.append((rid, exp))
                else:
                    # no chain (e.g. a degraded-mode leg landed here):
                    # bank the tokens and replay the remainder
                    e.prefix = e.prefix + list(r.tokens)
                    remaining = e.max_new - len(e.prefix)
                    replay = np.concatenate(
                        [e.prompt, np.asarray(e.prefix, np.int32)])
                    try:
                        j, new_local = self._replay_submit(
                            replay, remaining, e)
                    except ValueError as err:
                        self._fail_entry(
                            e, f"replay rejected: {err}", done)
                        return
                    e.replica, e.local = j, new_local
                    self._local[(j, new_local)] = rid
                return
        super()._finish(replica, r, done)

    def _drain_migrations(self, done: list) -> None:
        """Hand every pending export to the least-loaded decode
        replica.  A full decode side defers the migration to the next
        step (the export is host memory — nothing on device waits); a
        dead decode side falls back to any healthy replica."""
        if not self._pending_migrations:
            return
        alive = self._alive()
        dec = self._role_replicas("decode", alive) or alive
        pending, self._pending_migrations = \
            self._pending_migrations, deque()
        for rid, exp in pending:
            e = self._entries.get(rid)
            if e is None:
                continue   # cancelled / deadline-expired in flight
            if not dec:
                self._fail_entry(
                    e, "no healthy replicas left for migration", done)
                continue
            j = min(dec, key=self._route_key)
            eng = self.replicas[j]
            remaining = e.max_new - len(e.prefix)
            sp = None
            if self._tracer is not None:
                sp = self._tracer.start_span(
                    "request.migrate", parent=eng._engine_anchor,
                    attrs={"rid": rid, "pages": exp["pages"],
                           "to_replica": j})
            t0 = time.perf_counter()
            try:
                local = eng.import_chain(exp, remaining, e.temperature,
                                         tier=e.tier, tenant=e.tenant)
            except ReplicaDeadError:
                self._pending_migrations.append((rid, exp))
                if sp is not None:
                    sp.set_attr("outcome", "replica_dead")
                    sp.end()
                continue
            except ValueError as err:
                self._fail_entry(e, f"migration rejected: {err}", done)
                if sp is not None:
                    sp.set_attr("outcome", "rejected")
                    sp.end()
                continue
            if local is None:
                # decode side momentarily out of slots/pages
                self._pending_migrations.append((rid, exp))
                if sp is not None:
                    sp.set_attr("outcome", "deferred")
                    sp.end()
                continue
            dt = (time.perf_counter() - t0) * 1e3
            self.migrations += 1
            self.migrated_pages += int(exp["pages"])
            self.migration_ms.append(dt)
            _trim_acct(self.migration_ms)
            if self._metrics is not None:
                self._metrics.inc("serve_migrated_pages_total",
                                  float(exp["pages"]))
                self._metrics.observe("serve_migration_ms", dt)
            if sp is not None:
                sp.set_attr("outcome", "migrated")
                sp.set_attr("ms", round(dt, 3))
                sp.end()
            e.replica, e.local = j, local
            self._local[(j, local)] = rid

    def step(self) -> list[_Request]:
        done = super().step()
        self._drain_migrations(done)
        return done

"""T5 encoder-decoder family: structure, masking, bucketing, GSPMD."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kubegpu_tpu.models.t5 import (
    T5Config,
    make_t5_train_step,
    rel_pos_bucket,
    seq2seq_loss,
    t5_encode,
    t5_forward,
    t5_init,
    t5_param_specs,
)


@pytest.fixture(scope="module")
def tiny():
    cfg = T5Config.tiny()
    params = t5_init(jax.random.PRNGKey(0), cfg)
    return cfg, params


def toks(key, b, t, vocab):
    return jax.random.randint(jax.random.PRNGKey(key), (b, t), 0, vocab)


class TestStructure:
    def test_forward_shapes_and_finite_loss(self, tiny):
        cfg, params = tiny
        enc = toks(1, 2, 12, cfg.vocab_size)
        dec = toks(2, 2, 8, cfg.vocab_size)
        logits = t5_forward(params, enc, dec, cfg)
        assert logits.shape == (2, 8, cfg.vocab_size)
        assert logits.dtype == jnp.float32
        loss = seq2seq_loss(params, enc, dec, cfg)
        assert np.isfinite(float(loss))

    def test_specs_cover_every_leaf(self, tiny):
        cfg, params = tiny
        specs = t5_param_specs(cfg)
        p_leaves = jax.tree.structure(params)
        s_leaves = jax.tree.structure(
            specs, is_leaf=lambda x: x is None or hasattr(x, "index"))
        assert p_leaves == s_leaves

    def test_decoder_is_causal(self, tiny):
        """Perturbing a later decoder token must not change earlier
        positions' logits."""
        cfg, params = tiny
        enc = toks(3, 1, 10, cfg.vocab_size)
        dec = toks(4, 1, 8, cfg.vocab_size)
        base = t5_forward(params, enc, dec, cfg)
        dec2 = dec.at[0, 6].set((dec[0, 6] + 1) % cfg.vocab_size)
        pert = t5_forward(params, enc, dec2, cfg)
        np.testing.assert_allclose(np.asarray(base[:, :6]),
                                   np.asarray(pert[:, :6]),
                                   atol=1e-5, rtol=1e-5)
        assert not np.allclose(np.asarray(base[:, 6:]),
                               np.asarray(pert[:, 6:]))

    def test_encoder_is_bidirectional_and_cross_attended(self, tiny):
        """Perturbing the LAST encoder token must change encoder states
        at EARLIER positions (bidirectional) and shift decoder logits
        everywhere (cross-attention is live)."""
        cfg, params = tiny
        enc = toks(5, 1, 10, cfg.vocab_size)
        dec = toks(6, 1, 6, cfg.vocab_size)
        e1 = t5_encode(params, enc, cfg)
        enc2 = enc.at[0, 9].set((enc[0, 9] + 1) % cfg.vocab_size)
        e2 = t5_encode(params, enc2, cfg)
        assert not np.allclose(np.asarray(e1[:, 0]), np.asarray(e2[:, 0]))
        d1 = t5_forward(params, enc, dec, cfg)
        d2 = t5_forward(params, enc2, dec, cfg)
        assert not np.allclose(np.asarray(d1[:, 0]), np.asarray(d2[:, 0]))


class TestRelPosBucket:
    def test_causal_buckets_past_only(self):
        rel = jnp.arange(-10, 11)
        b = rel_pos_bucket(rel, bidirectional=False, num_buckets=8,
                           max_dist=16)
        # future (rel > 0) clamps to bucket 0; past is monotone in |rel|
        assert (np.asarray(b[rel > 0]) == 0).all()
        past = np.asarray(b[rel < 0])[::-1]   # increasing distance
        assert (np.diff(past) >= 0).all()
        assert past.max() < 8

    def test_bidirectional_sign_split(self):
        rel = jnp.asarray([-5, -1, 0, 1, 5])
        b = np.asarray(rel_pos_bucket(rel, bidirectional=True,
                                      num_buckets=8, max_dist=16))
        assert (b[:2] < 4).all()      # past: low half
        assert b[2] == 0
        assert (b[3:] >= 4).all()     # future: high half
        assert b.max() < 8

    def test_distance_clamps_at_max(self):
        b = rel_pos_bucket(jnp.asarray([-1000]), bidirectional=False,
                           num_buckets=8, max_dist=16)
        assert int(b[0]) == 7


class TestTraining:
    def test_loss_decreases_on_memorization(self, tiny):
        cfg, _ = tiny
        params = t5_init(jax.random.PRNGKey(9), cfg)
        opt = optax.adam(3e-3)
        opt_state = opt.init(params)
        step = jax.jit(make_t5_train_step(cfg, opt))
        enc = toks(7, 4, 10, cfg.vocab_size)
        dec = toks(8, 4, 8, cfg.vocab_size)
        first = None
        for _ in range(8):
            params, opt_state, loss = step(params, opt_state, enc, dec)
            first = first if first is not None else float(loss)
        assert float(loss) < first

    def test_gspmd_dp_tp_mesh(self, tiny):
        """Sharded end-to-end on the 8-device CPU mesh (dp=2, tp=4):
        params on megatron specs, one jitted train step, finite loss."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from kubegpu_tpu.parallel import make_mesh, named_sharding_tree
        from kubegpu_tpu.parallel.sharding import fit_spec

        cfg, _ = tiny
        mesh = make_mesh({"dp": 2, "tp": 4})
        params = jax.device_put(
            t5_init(jax.random.PRNGKey(1), cfg),
            named_sharding_tree(mesh, t5_param_specs(cfg)))
        opt = optax.adamw(1e-3)
        opt_state = opt.init(params)
        step = jax.jit(make_t5_train_step(cfg, opt, mesh),
                       donate_argnums=(0, 1))
        sh = NamedSharding(mesh, fit_spec(mesh, P("dp", None)))
        enc = jax.device_put(toks(10, 4, 16, cfg.vocab_size), sh)
        dec = jax.device_put(toks(11, 4, 12, cfg.vocab_size), sh)
        params, opt_state, loss = step(params, opt_state, enc, dec)
        assert np.isfinite(float(loss))


class TestT5Serving:
    """Cached decode parity with the teacher-forced decoder — the same
    contract the Llama/MoE serving paths carry."""

    def test_decode_steps_match_teacher_forcing(self, tiny):
        from kubegpu_tpu.models.t5 import (
            t5_decode_step, t5_decode_train, t5_init_decode_state,
        )
        cfg, params = tiny
        enc = toks(20, 2, 10, cfg.vocab_size)
        dec = toks(21, 2, 8, cfg.vocab_size)
        enc_out = t5_encode(params, enc, cfg)
        ref = t5_decode_train(params, enc_out, dec, cfg)  # [B, 8, V]
        state = t5_init_decode_state(params, enc_out, cfg, max_len=8)
        for pos in range(8):
            logits, state = t5_decode_step(params, state, dec[:, pos],
                                           pos, cfg)
            np.testing.assert_allclose(
                np.asarray(logits), np.asarray(ref[:, pos]),
                atol=3e-4, rtol=3e-4, err_msg=f"position {pos}")

    def test_greedy_generate_matches_naive(self, tiny):
        from kubegpu_tpu.models.t5 import t5_greedy_generate
        cfg, params = tiny
        enc = toks(22, 2, 10, cfg.vocab_size)
        n = 5
        got = np.asarray(t5_greedy_generate(params, enc, n, cfg,
                                            start_token=0))
        # naive rollout: teacher-force the growing decoder sequence
        dec = jnp.zeros((2, 1), jnp.int32)   # start token 0
        for _ in range(n):
            logits = t5_forward(params, enc, dec, cfg)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            dec = jnp.concatenate([dec, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(got, np.asarray(dec[:, 1:]))

    def test_generate_validation(self, tiny):
        from kubegpu_tpu.models.t5 import t5_greedy_generate
        cfg, params = tiny
        enc = toks(23, 1, 6, cfg.vocab_size)
        with pytest.raises(ValueError, match="n_steps"):
            t5_greedy_generate(params, enc, 0, cfg)
        with pytest.raises(ValueError, match="max_len"):
            t5_greedy_generate(params, enc, 9, cfg, max_len=4)


class TestT5OnPages:
    """t5_greedy_generate_paged: the decoder self-attn cache lives in
    a page pool read by the BIASED paged-attention kernel (rel-pos
    buckets computed in-kernel); cross-attention stays dense.  Token
    parity with the dense implementation is exact at f32."""

    def test_matches_dense_generate(self):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from kubegpu_tpu.models.t5 import (
            T5Config, t5_greedy_generate, t5_greedy_generate_paged,
            t5_init,
        )
        cfg = T5Config.tiny()
        params = t5_init(jax.random.PRNGKey(5), cfg)
        enc = jnp.asarray(
            np.arange(2 * 9).reshape(2, 9) % cfg.vocab_size, jnp.int32)
        # 11 steps over page_size 4: two full pages flushed + a
        # partial third block — exercises pool reads AND buffer merge
        dense = t5_greedy_generate(params, enc, 11, cfg, max_len=16)
        paged = t5_greedy_generate_paged(params, enc, 11, cfg,
                                         page_size=4)
        np.testing.assert_array_equal(np.asarray(dense),
                                      np.asarray(paged))

    def test_single_block_no_flush(self):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from kubegpu_tpu.models.t5 import (
            T5Config, t5_greedy_generate, t5_greedy_generate_paged,
            t5_init,
        )
        cfg = T5Config.tiny()
        params = t5_init(jax.random.PRNGKey(6), cfg)
        enc = jnp.asarray(
            (np.arange(3 * 6).reshape(3, 6) * 5) % cfg.vocab_size,
            jnp.int32)
        dense = t5_greedy_generate(params, enc, 3, cfg, max_len=8)
        paged = t5_greedy_generate_paged(params, enc, 3, cfg,
                                         page_size=8)
        np.testing.assert_array_equal(np.asarray(dense),
                                      np.asarray(paged))

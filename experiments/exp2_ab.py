"""Same-window A/B of the exp2-folded softmax (VERDICT item #4: the
"exp-bound" bwd-kernel ceiling hypothesis).

Two protocols in one run, both interleaved inside one window (the
tunnel's cross-window variance measured 45% on sub-3ms kernels —
dkv_ab.py's finding — so only interleaved bursts can rank a ~few-%
transcendental change):

1. RAW KERNELS: forward, and the fused dq+dkv backward, exp on vs
   exp2-folded, alternating timing bursts, per-variant medians.
2. TRAIN STEP bracket (step_ab protocol): SOFTMAX_EXP2 0 → 1 → 0 on
   the flagship train step, reporting step ms + MFU per leg — the
   A...A bracket bounds window drift, and the middle leg is the
   hypothesis: if the bwd kernels are exp-bound, MFU moves; if the A/B
   is flat, the committed record says the transcendental is NOT the
   ceiling and the claim dies honestly.

Usage: exp2_ab.py [--kernels-only | --step-only]
"""

import importlib
import statistics
import sys
import time

sys.path.insert(0, "/root/repo")

import jax                                      # noqa: E402
import jax.numpy as jnp                         # noqa: E402
import numpy as np                              # noqa: E402

fa = importlib.import_module("kubegpu_tpu.ops.flash_attention")
RAW_BWD = fa.flash_attention_bwd.__wrapped__
RAW_FWD = fa.flash_attention.__wrapped__

B, HQ, HKV, T, D = 4, 16, 4, 2048, 128
DT = jnp.bfloat16
ITERS = 60
ROUNDS = 5


def fetch(x):
    return float(np.asarray(jax.device_get(jnp.ravel(x)[0])))


def kernel_ab():
    key = jax.random.PRNGKey(0)
    kq, kk, kv, kg = jax.random.split(key, 4)
    q = jax.random.normal(kq, (B, HQ, T, D), DT)
    k = jax.random.normal(kk, (B, HKV, T, D), DT)
    v = jax.random.normal(kv, (B, HKV, T, D), DT)
    g = jax.random.normal(kg, (B, HQ, T, D), DT)

    variants = {}
    for name, knob in (("exp", False), ("exp2", True)):
        fa.SOFTMAX_EXP2 = knob

        def mk():
            def fwd_run(q_):
                out, lse = RAW_FWD(q_, k, v, True, 512, 512, False,
                                   True)
                return (q_ + (out[0, 0, 0, 0]
                              + lse[0, 0, 0]).astype(q_.dtype)
                        * jnp.bfloat16(1e-8))

            def bwd_run(g_):
                out, lse = RAW_FWD(q, k, v, True, 512, 512, False,
                                   True)
                dq, dk, dv = RAW_BWD(q, k, v, out, lse, g_, True,
                                     512, 512, False)
                return (g_ + (dq[0, 0, 0, 0] + dk[0, 0, 0, 0]
                              + dv[0, 0, 0, 0]).astype(g_.dtype)
                        * jnp.bfloat16(1e-8))
            return jax.jit(fwd_run), jax.jit(bwd_run)

        try:
            ffn, bfn = mk()
            fetch(ffn(q))      # compile while the device queue is calm
            fetch(bfn(g))
            variants[name] = (ffn, bfn)
            print(f"compiled {name}", flush=True)
        except Exception as e:   # pragma: no cover - remote compile
            print(f"{name}: COMPILE FAILED {str(e)[:120]}", flush=True)
        finally:
            fa.SOFTMAX_EXP2 = True

    times = {n: {"fwd": [], "bwd": []} for n in variants}
    for _ in range(ROUNDS):
        for name, (ffn, bfn) in variants.items():
            st = q
            t0 = time.perf_counter()
            for _ in range(ITERS):
                st = ffn(st)
            fetch(st)
            times[name]["fwd"].append((time.perf_counter() - t0) / ITERS)
            st = g
            t0 = time.perf_counter()
            for _ in range(ITERS):
                st = bfn(st)
            fetch(st)
            times[name]["bwd"].append((time.perf_counter() - t0) / ITERS)
    for name, tt in times.items():
        for leg in ("fwd", "bwd"):
            med = statistics.median(tt[leg])
            print(f"{leg} {name}: median {med*1e3:7.3f} ms  "
                  f"(all: {[round(x*1e3, 3) for x in tt[leg]]})",
                  flush=True)
    if {"exp", "exp2"} <= set(times):
        for leg in ("fwd", "bwd"):
            a = statistics.median(times["exp"][leg])
            b = statistics.median(times["exp2"][leg])
            print(f"{leg} exp/exp2 ratio: {a / b:.4f} "
                  f"({'exp2 faster' if a > b else 'flat-or-slower'})",
                  flush=True)


def step_bracket():
    from experiments.step_ab import one_leg
    from kubegpu_tpu.benchmark import llama_bench_config
    cfg = llama_bench_config()
    for knob, value in (("SOFTMAX_EXP2", 0), ("SOFTMAX_EXP2", 1),
                        ("SOFTMAX_EXP2", 0)):
        one_leg(cfg, 4, 2048, knob, value)
    fa.SOFTMAX_EXP2 = True


def main():
    args = set(sys.argv[1:])
    if "--step-only" not in args:
        kernel_ab()
    if "--kernels-only" not in args:
        step_bracket()


if __name__ == "__main__":
    main()

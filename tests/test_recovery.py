"""Fault detection + elastic recovery (SURVEY.md §6): kill a host
mid-gang, fail a chip, flap a link — the gang gets evicted and
rescheduled onto a fresh healthy sub-mesh; freed chips are reusable;
state survives scheduler restarts (annotation truth)."""

import random

import pytest

from kubegpu_tpu.cluster import SimCluster, tpu_pod
from kubegpu_tpu.kubemeta import GangSpec, PodPhase
from kubegpu_tpu.kubemeta.codec import pod_allocation
from kubegpu_tpu.scheduler import DeviceScheduler, FaultRecoveryController
from kubegpu_tpu.tpuplugin.backend import MILLICHIPS_PER_CHIP


def submit_gang(cl, name, size, chips, axes=None):
    pods = []
    for i in range(size):
        pods.append(tpu_pod(f"{name}-{i}", chips=chips,
                            gang=GangSpec(name=name, size=size, index=i),
                            mesh_axes=axes, command=["noop"]))
    cl.submit(*pods)
    return [p.name for p in pods]


def allocated_coords(cl, names):
    out = {}
    for n in names:
        alloc = pod_allocation(cl.api.get("Pod", n))
        out[n] = [ch.coord for ch in alloc.chips] if alloc else None
    return out


class TestChipFailure:
    def test_failed_chip_evicts_and_reschedules_gang(self):
        cl = SimCluster(["v5e-16"])
        names = submit_gang(cl, "job", size=4, chips=2,
                            axes={"dp": 4, "tp": 2})
        result, started = cl.step()
        assert len(result.scheduled) == 4
        before = allocated_coords(cl, names)
        # fail one allocated chip on its node
        victim = cl.api.get("Pod", names[0])
        alloc = pod_allocation(victim)
        cl.fail_chip(alloc.node_name, alloc.chips[0].local_index)
        result, _ = cl.step()
        # gang was evicted and immediately rescheduled avoiding the chip
        after = allocated_coords(cl, names)
        assert all(v is not None for v in after.values())
        bad = alloc.chips[0].coord
        all_after = [c for chips in after.values() for c in chips]
        assert bad not in all_after
        assert sorted(all_after) != sorted(
            c for chips in before.values() for c in chips)
        # worker ids preserved (gang index order)
        for i, n in enumerate(names):
            assert pod_allocation(cl.api.get("Pod", n)).worker_id == i
        assert cl.metrics.counter("gangs_evicted") == 1
        cl.close()

    def test_same_node_replacement_restarts_container_fresh_env(self):
        """Regression: an evicted gang member re-bound to the SAME node
        must get a NEW container with the new allocation env — the old
        incarnation's container (stale chip set/coordinator) must die."""
        cl = SimCluster(["v5e-16"])
        names = submit_gang(cl, "job", size=4, chips=2,
                            axes={"dp": 4, "tp": 2})
        _, started1 = cl.step()
        envs1 = {h.pod_name: h.env for h in started1}
        victim = pod_allocation(cl.api.get("Pod", names[0]))
        cl.fail_chip(victim.node_name, victim.chips[0].local_index)
        _, started2 = cl.step()
        # every member restarted (all four gang workers), even ones whose
        # re-placement landed on the same node under the same name
        assert {h.pod_name for h in started2} == set(names)
        for h in started2:
            assert h.env["TPU_VISIBLE_CHIPS"] != ""
        # all pods progressed to RUNNING with the new incarnation
        for n in names:
            assert cl.pod_phase(n) == PodPhase.RUNNING
            alloc = pod_allocation(cl.api.get("Pod", n))
            agent = cl.agent_for(alloc.node_name)
            assert n in agent.handles
            new_chips = ",".join(str(c.local_index) for c in alloc.chips)
            assert agent.handles[n].env["TPU_VISIBLE_CHIPS"] == new_chips
        # old incarnation's env differed for at least the victim pod
        new_envs = {h.pod_name: h.env for h in started2}
        assert (new_envs[names[0]]["TPU_VISIBLE_CHIPS"]
                != envs1[names[0]]["TPU_VISIBLE_CHIPS"]
                or [c.coord for c in pod_allocation(
                    cl.api.get("Pod", names[0])).chips]
                != [c.coord for c in victim.chips])
        cl.close()

    def test_healed_chip_usable_again(self):
        cl = SimCluster(["v4-8"])
        node = cl.agents[0].node_name
        cl.fail_chip(node, 0)
        cl.submit(tpu_pod("big", chips=4, command=["noop"]))
        result, _ = cl.step()
        assert result.unschedulable == ["big"]
        cl.heal_chip(node, 0)
        result, _ = cl.step()
        assert result.scheduled == ["big"]
        cl.close()


class TestHostFailure:
    def test_host_death_reschedules_gang_to_other_slice(self):
        """Kill a host mid-gang (SURVEY.md §6): the whole gang restarts on
        healthy hardware — including members whose own host survived."""
        cl = SimCluster(["v5e-16", "v5e-16"])
        names = submit_gang(cl, "job", size=4, chips=4,
                            axes={"dp": 4, "tp": 4})
        result, started = cl.step()
        assert len(result.scheduled) == 4
        slice_before = pod_allocation(cl.api.get("Pod", names[0])).slice_id
        victim_node = pod_allocation(cl.api.get("Pod", names[0])).node_name
        cl.fail_host(victim_node)
        result, started = cl.step()
        after = allocated_coords(cl, names)
        assert all(v is not None for v in after.values())
        new_nodes = {pod_allocation(cl.api.get("Pod", n)).node_name
                     for n in names}
        assert victim_node not in new_nodes
        # v5e-16 minus one host can't fit 16 chips → other slice hosts it
        assert pod_allocation(
            cl.api.get("Pod", names[0])).slice_id != slice_before
        # fresh containers started for the restarted gang
        assert {h.pod_name for h in started} == set(names)
        cl.close()

    def test_whole_slice_death_still_evicts_gang(self):
        """Regression: a gang whose ENTIRE slice vanishes (single-host
        v4-8 dies) must still be seen by the recovery controller — sync()
        must not silently drop committed gangs with a missing slice,
        leaving zombie RUNNING pods bound to a dead node."""
        cl = SimCluster(["v4-8", "v5e-16"])
        names = submit_gang(cl, "job", size=4, chips=1)
        cl.step()
        sid = pod_allocation(cl.api.get("Pod", names[0])).slice_id
        assert sid.startswith("v4-8")
        cl.fail_host(pod_allocation(cl.api.get("Pod", names[0])).node_name)
        cl.step()
        assert cl.metrics.counter("gangs_evicted") == 1
        after = allocated_coords(cl, names)
        assert all(v is not None for v in after.values())
        assert pod_allocation(
            cl.api.get("Pod", names[0])).slice_id.startswith("v5e-16")
        cl.close()

    def test_single_slice_gang_pends_until_host_restored(self):
        cl = SimCluster(["v5e-16"])
        names = submit_gang(cl, "job", size=4, chips=4)
        cl.step()
        victim = pod_allocation(cl.api.get("Pod", names[0])).node_name
        cl.fail_host(victim)
        result, _ = cl.step()
        # 12 healthy chips < 16 asked: gang pends, does not half-place
        assert set(result.unschedulable) == set(names)
        assert all(cl.pod_phase(n) == PodPhase.PENDING for n in names)
        cl.restore_host(victim)
        result, _ = cl.step()
        assert len(result.scheduled) == 4
        cl.close()

    def test_dead_host_containers_killed_on_survivors(self):
        """Members on healthy hosts get torn down when the gang restarts
        (kubelet reconcile of deleted pods)."""
        cl = SimCluster(["v5e-16", "v5e-16"])
        names = submit_gang(cl, "job", size=4, chips=4)
        cl.step()
        nodes = {n: pod_allocation(cl.api.get("Pod", n)).node_name
                 for n in names}
        victim_node = nodes[names[0]]
        survivor_agents = {cl.agent_for(nd) for n, nd in nodes.items()
                           if nd != victim_node}
        assert any(a.handles for a in survivor_agents)
        cl.fail_host(victim_node)
        cl.step()
        for a in survivor_agents:
            for n in names:
                assert n not in a.handles or \
                    pod_allocation(cl.api.get("Pod", n)).node_name == a.node_name
        cl.close()


class TestLinkFailure:
    def test_new_allocations_avoid_bad_link(self):
        """A tp ring placed after a link flap must not ride the dead link
        as a collective hop."""
        cl = SimCluster(["v5e-16"])
        sid = cl.agents[0].backend.slice_id
        cl.fail_link((0, 0, 0), (1, 0, 0), slice_id=sid)
        names = submit_gang(cl, "job", size=2, chips=4,
                            axes={"tp": 8})
        result, _ = cl.step()
        assert len(result.scheduled) == 2
        # every consecutive tp-ring pair must avoid the dead link
        coords = []
        for n in names:
            coords.extend(pod_allocation(cl.api.get("Pod", n)).chips)
        order = [c.coord for c in coords]
        bad = ((0, 0, 0), (1, 0, 0))
        for i in range(len(order)):
            a, b = order[i], order[(i + 1) % len(order)]
            assert (min(a, b), max(a, b)) != bad
        cl.close()

    def test_link_failure_inside_allocation_triggers_recovery(self):
        cl = SimCluster(["v5e-16", "v5e-16"])
        names = submit_gang(cl, "job", size=4, chips=4,
                            axes={"dp": 4, "tp": 4})
        cl.step()
        before = allocated_coords(cl, names)
        chips = sorted({c for v in before.values() for c in v})
        # find an ICI link strictly inside the allocation
        topo = cl.scheduler.slices[
            pod_allocation(cl.api.get("Pod", names[0])).slice_id].topo
        link = None
        for a in chips:
            for b in chips:
                if a < b and topo.are_ici_adjacent(a, b):
                    link = (a, b)
                    break
            if link:
                break
        assert link is not None
        sid = pod_allocation(cl.api.get("Pod", names[0])).slice_id
        cl.fail_link(*link, slice_id=sid)
        cl.step()
        assert cl.metrics.counter("gangs_evicted") == 1
        after = allocated_coords(cl, names)
        assert all(v is not None for v in after.values())
        # healed link: next gang may use those chips again
        cl.heal_link(*link, slice_id=sid)
        cl.step()
        cl.close()


class TestCompletedMembers:
    def test_eviction_does_not_resurrect_completed_pod(self):
        """Regression: a SUCCEEDED gang member keeps its allocation
        annotation; a later fault on the gang must evict only LIVE
        members, not re-run the finished one."""
        cl = SimCluster(["v5e-16", "v5e-16"])
        names = submit_gang(cl, "job", size=4, chips=2)
        cl.step()
        # one member finishes early
        cl.api.set_pod_phase(names[3], PodPhase.SUCCEEDED, exit_code=0)
        victim = pod_allocation(cl.api.get("Pod", names[0]))
        cl.fail_host(victim.node_name)
        cl.step()
        done = cl.api.get("Pod", names[3])
        assert done.status.phase == PodPhase.SUCCEEDED  # untouched
        for n in names[:3]:
            assert cl.api.get("Pod", n).status.phase in (
                PodPhase.SCHEDULED, PodPhase.RUNNING, PodPhase.PENDING)
        cl.close()


class TestRestartRecovery:
    def test_fresh_scheduler_detects_fault_from_annotations(self):
        """Scheduler + recovery controller restart: all state (allocations,
        gang membership, health) rebuilds from annotations, and a fault
        injected while 'down' is detected on the first pass after restart."""
        cl = SimCluster(["v5e-16", "v4-8"])
        names = submit_gang(cl, "job", size=4, chips=2)
        cl.step()
        victim = pod_allocation(cl.api.get("Pod", names[0]))
        # replace scheduler+controller wholesale (process restart)
        cl.recovery.close()
        cl.scheduler = DeviceScheduler(
            cl.api, metrics=cl.metrics, trace=cl.trace,
            coordinator_port=9900)
        cl.recovery = FaultRecoveryController(cl.api, cl.scheduler)
        cl.fail_chip(victim.node_name, victim.chips[0].local_index)
        cl.step()
        after = allocated_coords(cl, names)
        assert all(v is not None for v in after.values())
        assert victim.chips[0].coord not in [
            c for v in after.values() for c in v]
        cl.close()


class TestNoDoubleBooking:
    def test_random_fault_storm_never_overbooks(self):
        """Property: arbitrary fault/heal/churn sequences keep every chip's
        occupancy within capacity and committed gangs disjoint."""
        rng = random.Random(7)
        cl = SimCluster(["v5e-16", "v4-8"])
        gang_i = 0
        live_nodes = [a.node_name for a in cl.agents]
        down = set()
        for step in range(40):
            op = rng.random()
            if op < 0.4:
                gang_i += 1
                submit_gang(cl, f"g{gang_i}", size=rng.choice([1, 2, 4]),
                            chips=rng.choice([1, 2, 4]))
            elif op < 0.6 and len(down) < len(live_nodes) - 1:
                n = rng.choice([x for x in live_nodes if x not in down])
                down.add(n)
                cl.fail_host(n)
            elif op < 0.8 and down:
                n = rng.choice(sorted(down))
                down.remove(n)
                cl.restore_host(n)
            else:
                running = [p for p in cl.api.list("Pod")
                           if p.status.phase != PodPhase.PENDING]
                if running:
                    victim = rng.choice(running)
                    try:
                        cl.api.delete("Pod", victim.name)
                    except Exception:
                        pass
            cl.step()
            # invariant: no chip over-allocated
            for st in cl.scheduler.slices.values():
                for coord, used in st.used_millichips.items():
                    assert 0 <= used <= MILLICHIPS_PER_CHIP, \
                        f"step {step}: chip {coord} at {used}"
            # invariant: committed gangs' whole-chip sets disjoint
            seen = {}
            for gang, asg in cl.scheduler._committed.items():
                for p in asg.pods:
                    for ch in p.chips:
                        if ch.millichips == MILLICHIPS_PER_CHIP:
                            key = (asg.slice_id, ch.coord)
                            assert key not in seen, \
                                f"step {step}: {key} in {gang} and {seen[key]}"
                            seen[key] = gang
        cl.close()

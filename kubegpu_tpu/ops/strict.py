"""Strict-mode fence for silent hot-path degradation.

Three rounds of misattributed MFU happened because the flagship train
step silently fell back from the pallas flash-attention kernel to O(T²)
XLA attention while every test stayed green (VERDICT r4 weak #3).  The
fence makes that regression class *fail* instead of merely warn:

- ``KUBETPU_REQUIRE_PALLAS=1`` in the environment (or
  :func:`require_pallas` toggled programmatically) turns every
  would-be-silent fallback — flash-attention block misalignment,
  paged→dense engine degradation — into a raised
  :class:`StrictFallbackError`.
- ``bench.py`` exports the flag for its whole run, and the serve-pod
  bench forwards it into the scheduled flagship pod's env, so a future
  shape/layout change that quietly de-optimizes a hot path aborts the
  bench instead of recording a plausible-but-wrong number.  (Tiny smoke
  configs run permissive: their prompt buckets legitimately don't align
  to pages.)

The flag is read at trace time (these decisions are static on shapes),
so flipping it mid-process affects new shapes only — jit caches keyed on
already-traced shapes keep their original behavior.  Use distinct shapes
per test when asserting both behaviors.
"""

from __future__ import annotations

import os

ENV_VAR = "KUBETPU_REQUIRE_PALLAS"


class StrictFallbackError(RuntimeError):
    """A hot path degraded (pallas→XLA, paged→dense) under strict mode."""


def require_pallas() -> bool:
    """True when silent fallbacks must raise (env-driven, read live)."""
    return os.environ.get(ENV_VAR, "") not in ("", "0")


def fallback(path: str, detail: str) -> None:
    """Record a hot-path fallback: raise under strict mode, else return
    so the caller can warn and degrade.  ``path`` names the hot path
    (e.g. ``flash_attention``), ``detail`` says why it degraded."""
    if require_pallas():
        raise StrictFallbackError(
            f"{ENV_VAR}=1 but {path} fell back: {detail}")

"""Structured JSON logging (SURVEY.md §6): machine-parseable lines from
the real scheduling path."""

import io
import json
import logging

from kubegpu_tpu.cluster import SimCluster, tpu_pod
from kubegpu_tpu.obs.logging import configure, get_logger


def drain(stream: io.StringIO) -> list[dict]:
    return [json.loads(line) for line in stream.getvalue().splitlines()]


class TestStructuredLogging:
    def test_json_lines_shape(self):
        stream = io.StringIO()
        handler = configure(logging.DEBUG, stream)
        try:
            log = get_logger("testcomp")
            log.info("hello", pod="p1", chips=4)
            log.warning("uh-oh", reason="why")
        finally:
            logging.getLogger("kubetpu").removeHandler(handler)
        events = drain(stream)
        assert events[0]["event"] == "hello"
        assert events[0]["component"] == "testcomp"
        assert events[0]["level"] == "info"
        assert events[0]["pod"] == "p1" and events[0]["chips"] == 4
        assert isinstance(events[0]["ts"], float)
        assert events[1]["level"] == "warning"

    def test_configure_idempotent(self):
        s1, s2 = io.StringIO(), io.StringIO()
        configure(logging.INFO, s1)
        handler = configure(logging.INFO, s2)  # replaces, no double lines
        try:
            get_logger("x").info("once")
        finally:
            logging.getLogger("kubetpu").removeHandler(handler)
        assert s1.getvalue() == ""
        assert len(drain(s2)) == 1

    def test_scheduler_path_emits_events(self):
        stream = io.StringIO()
        handler = configure(logging.INFO, stream)
        try:
            cl = SimCluster(["v4-8"])
            cl.submit(tpu_pod("p", chips=2, command=["x"]))
            cl.step()
            cl.close()
        finally:
            logging.getLogger("kubetpu").removeHandler(handler)
        events = drain(stream)
        kinds = {(e["component"], e["event"]) for e in events}
        assert ("scheduler", "schedule") in kinds
        assert ("crishim", "create_container") in kinds
        sched = next(e for e in events if e["event"] == "schedule")
        assert sched["gang"] == "default/p" and sched["pods"] == 1

    def test_silent_by_default(self, capsys):
        """No handler configured → nothing reaches stderr and nothing
        raises (library-friendly: logging is opt-in).  WARNING+ must not
        leak through logging.lastResort either (NullHandler in place)."""
        log = get_logger("quiet")
        log.info("nobody-listening", a=1)
        log.warning("still-nobody", b=2)
        log.error("even-errors", c=3)
        captured = capsys.readouterr()
        assert "still-nobody" not in captured.err
        assert "even-errors" not in captured.err

"""Backend interface + advertisement payload types.

Reference parity: ``types.DeviceManager`` (SURVEY.md §3 "Core types") —
``Start / Capacity / AllocateDevices``.  The advertisement payload here is
what the node advertiser patches onto the Node object (SURVEY.md §4.1),
replacing the reference's ``gpugrp`` hierarchical ResourceList with explicit
mesh metadata.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field

from kubegpu_tpu.topology.mesh import Coord

# A whole chip is 1000 millichips; fractional-chip co-tenancy (BASELINE
# config 5) bin-packs against this per-chip capacity vector (SURVEY.md §8
# "Fractional chips").
MILLICHIPS_PER_CHIP = 1000


@dataclass(frozen=True)
class ChipAdvertisement:
    """One local chip: its global mesh coordinate and capacity."""

    coord: Coord
    local_index: int  # index on this host (0..chips_per_host-1)
    millichips: int = MILLICHIPS_PER_CHIP
    hbm_gib: float = 16.0
    healthy: bool = True


@dataclass(frozen=True)
class NodeAdvertisement:
    """What one node (TPU host VM) advertises to the control plane.

    A multi-host slice is represented by N nodes sharing ``slice_id``; the
    scheduler reassembles the full mesh from their chips.  ``host_id`` is
    the host's deterministic rank within the slice — the source of
    TPU_WORKER_ID ordering (SURVEY.md §8 "Worker identity wiring").
    """

    node_name: str
    slice_id: str
    slice_type: str           # registry key, e.g. "v5e-16"
    host_id: int
    mesh_shape: Coord
    wrap: tuple[bool, bool, bool]
    host_block: Coord
    chips: tuple[ChipAdvertisement, ...] = field(default_factory=tuple)
    internal_ip: str = "127.0.0.1"
    # Failed ICI links incident to this host's chips, as normalized
    # (min(a,b), max(a,b)) coord pairs.  Both endpoints' hosts advertise a
    # shared link; the scheduler unions them per slice (SURVEY.md §6
    # failure-detection row: a bad link makes ring placements across it
    # score low and marks gangs straddling it for recovery).
    bad_links: tuple[tuple[Coord, Coord], ...] = field(default_factory=tuple)

    @property
    def num_chips(self) -> int:
        return len(self.chips)


class DeviceBackend(abc.ABC):
    """Vendor seam — the reference loaded this as ``nvidiagpuplugin.so``."""

    @abc.abstractmethod
    def discover(self) -> NodeAdvertisement:
        """Enumerate this host's chips + mesh position (NVML-equivalent)."""

    @abc.abstractmethod
    def allocate_env(
        self,
        chips: list[ChipAdvertisement],
        worker_id: int,
        num_workers: int,
        coordinator_address: str,
        worker_hostnames: list[str],
    ) -> dict[str, str]:
        """Environment to inject for a container granted ``chips``.

        The reference returned ``NVIDIA_VISIBLE_DEVICES=<uuids>`` + device
        nodes + driver mounts; the TPU equivalent is env-only (libtpu reads
        these at ``jax.distributed.initialize`` time).
        """

"""CPU-fallback MNIST in torch — BASELINE config 1 workload.

The reference's config names a TF MNIST job; TF isn't in this image, so
the 0-device CPU-fallback path is exercised with a torch-CPU trainer —
the point of config 1 is that a *non-TPU, non-JAX* workload schedules and
runs untouched (no TPU env, no device allocation).
"""

from __future__ import annotations

import os
import sys


def main() -> int:
    if os.environ.get("TPU_VISIBLE_CHIPS", ""):
        print("FAIL: CPU-fallback pod saw TPU chips", file=sys.stderr)
        return 2
    import torch

    torch.manual_seed(0)
    x = torch.randn(256, 784)
    y = torch.randint(0, 10, (256,))
    model = torch.nn.Sequential(
        torch.nn.Linear(784, 64), torch.nn.ReLU(), torch.nn.Linear(64, 10))
    opt = torch.optim.Adam(model.parameters(), lr=1e-3)
    loss_fn = torch.nn.CrossEntropyLoss()
    first = None
    for _ in range(20):
        opt.zero_grad()
        loss = loss_fn(model(x), y)
        loss.backward()
        opt.step()
        first = first if first is not None else float(loss.detach())
    print(f"mnist_torch: first_loss={first:.4f} last_loss={float(loss):.4f}")
    return 0 if float(loss) < first else 3


if __name__ == "__main__":
    sys.exit(main())

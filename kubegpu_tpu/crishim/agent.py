"""Node agent: advertiser + kubelet-ish pod lifecycle.

Reference parity: the ``kubeadvertise`` loop PATCHing the Node object
(SURVEY.md §4.1) plus the kubelet role in §4.3 (seeing pods bound to this
node and calling the CRI).  One agent per (simulated) TPU host VM.
"""

from __future__ import annotations

from kubegpu_tpu.crishim.runtime import ContainerHandle, ContainerRuntime
from kubegpu_tpu.crishim.shim import CriShim
from kubegpu_tpu.kubemeta import (
    FakeApiServer,
    Node,
    NotFound,
    ObjectMeta,
    PodPhase,
)
from kubegpu_tpu.kubemeta.codec import (
    DEVICE_INFO_KEY,
    node_advertisement_to_annotation,
)
from kubegpu_tpu.tpuplugin.backend import DeviceBackend


class NodeAgent:
    def __init__(self, api: FakeApiServer, backend: DeviceBackend,
                 runtime: ContainerRuntime):
        self.api = api
        self.backend = backend
        self.adv = backend.discover()
        self.node_name = self.adv.node_name
        self.runtime = runtime
        self.shim = CriShim(api, backend, self.node_name, runtime)
        self.handles: dict[str, ContainerHandle] = {}  # pod name → handle

    # -- advertisement (SURVEY.md §4.1) ---------------------------------

    def register(self) -> None:
        """Create the Node object if needed, then advertise capacity +
        topology as an annotation."""
        try:
            self.api.get("Node", self.node_name)
        except NotFound:
            self.api.create("Node", Node(
                metadata=ObjectMeta(name=self.node_name)))
        self.advertise()

    def advertise(self) -> None:
        self.adv = self.backend.discover()  # re-enumerate (health may change)
        self.api.patch_annotations(
            "Node", self.node_name,
            {DEVICE_INFO_KEY: node_advertisement_to_annotation(self.adv)})

    # -- pod lifecycle (SURVEY.md §4.3) ---------------------------------

    def run_once(self) -> list[ContainerHandle]:
        """Start containers for pods newly bound to this node."""
        started: list[ContainerHandle] = []
        for pod in self.api.list("Pod"):
            if (pod.spec.node_name == self.node_name
                    and pod.status.phase == PodPhase.SCHEDULED
                    and pod.name not in self.handles):
                handle = self.shim.create_container(pod)
                self.handles[pod.name] = handle
                self.api.set_pod_phase(pod.name, PodPhase.RUNNING,
                                       namespace=pod.metadata.namespace)
                started.append(handle)
        return started

    def reap(self, timeout: float | None = None) -> dict[str, int]:
        """Wait for running containers; report exit codes and update pod
        phases (Succeeded/Failed)."""
        results: dict[str, int] = {}
        for pod_name, handle in list(self.handles.items()):
            code = handle.wait(timeout=timeout)
            if code is None:
                continue
            results[pod_name] = code
            phase = PodPhase.SUCCEEDED if code == 0 else PodPhase.FAILED
            try:
                self.api.set_pod_phase(pod_name, phase,
                                       message=handle.stderr[-2000:] if code else "",
                                       exit_code=code)
            except NotFound:
                pass
            del self.handles[pod_name]
        return results

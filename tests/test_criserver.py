"""CRI wire boundary (SURVEY.md §4.3): the agent↔shim transport seam.

The reference's crishim was a gRPC CRI server on a unix socket that
kubelet called; these tests prove the simulated stack keeps that seam —
every container operation traverses the RuntimeService-shaped socket
protocol (``criserver.py``), with the server doing the reference's
CreateContainer flow (GET pod from apiserver → injection → forward to
the real runtime)."""

import sys

import pytest

from kubegpu_tpu.cluster import SimCluster, tpu_pod
from kubegpu_tpu.crishim import (
    CriClient,
    CriError,
    CriServer,
    FakeRuntime,
    RemoteCriShim,
)
from kubegpu_tpu.crishim.criserver import (
    CONTAINER_EXITED,
    POD_NAME_LABEL,
    POD_NAMESPACE_LABEL,
    POD_UID_LABEL,
)
from kubegpu_tpu.kubemeta import FakeApiServer, GangSpec, PodPhase
from kubegpu_tpu.kubemeta.codec import pod_allocation
from kubegpu_tpu.tpuplugin import MockBackend


@pytest.fixture(params=["json", "grpc-proto", "grpc-json"])
def served(request):
    """One v4-8 node's CRI server + a raw client, no scheduler — every
    protocol/image/shim test runs over ALL THREE transports: the JSON
    frame fallback, the runtime.v1 gRPC endpoint with PROTOBUF bodies
    (the kubelet-compatible default — VERDICT r4 missing #1), and the
    gRPC endpoint with JSON bodies (the r3 fallback)."""
    api = FakeApiServer()
    backend = MockBackend("v4-8")
    runtime = FakeRuntime()
    if request.param == "json":
        server = CriServer(api, backend, backend.discover().node_name,
                           runtime).start()
        client = CriClient(server.socket_path)
    else:
        # imported lazily so the JSON transport stays testable in an
        # environment without grpcio (it is the dependency-free fallback)
        grpcserver = pytest.importorskip("kubegpu_tpu.crishim.grpcserver")
        codec = request.param.split("-", 1)[1]
        server = grpcserver.GrpcCriServer(
            api, backend, backend.discover().node_name, runtime,
            codec=codec).start()
        client = grpcserver.GrpcCriClient(server.socket_path, codec=codec)
    yield api, backend, runtime, server, client
    client.close()
    server.close()


class TestProtocol:
    def test_version_handshake(self, served):
        _, backend, _, _, client = served
        out = client.call("Version")
        assert out["runtime_name"] == "kubetpu-crishim"
        assert out["runtime_api_version"] == "v1"
        assert out["node_name"] == backend.discover().node_name

    def test_unknown_method_is_in_band_error(self, served):
        *_, client = served
        with pytest.raises(CriError, match="unknown method"):
            client.call("ExecSync")
        # the connection survives the error
        assert client.call("Version")["runtime_name"] == "kubetpu-crishim"

    def test_unknown_container_id(self, served):
        *_, client = served
        with pytest.raises(CriError, match="no such container"):
            client.call("ContainerStatus", {"container_id": "nope"})

    def test_create_requires_pod_label(self, served):
        *_, client = served
        with pytest.raises(CriError, match=POD_NAME_LABEL):
            client.call("CreateContainer", {"config": {"labels": {}}})

    def test_create_missing_pod(self, served):
        *_, client = served
        with pytest.raises(CriError, match="not found"):
            client.call("CreateContainer", {"config": {"labels": {
                POD_NAME_LABEL: "ghost"}}})

    def test_uid_mismatch_rejects_stale_incarnation(self, served):
        api, *_, client = served
        api.create("Pod", tpu_pod("p", chips=0, command=["noop"]))
        with pytest.raises(CriError, match="stale incarnation"):
            client.call("CreateContainer", {"config": {"labels": {
                POD_NAME_LABEL: "p",
                POD_NAMESPACE_LABEL: "default",
                POD_UID_LABEL: "uid-of-a-dead-incarnation"}}})

    def test_create_status_list_remove_roundtrip(self, served):
        api, backend, runtime, server, client = served
        api.create("Pod", tpu_pod("p", chips=0, command=["noop"]))
        pod = api.get("Pod", "p")
        # kubelet's sequence: the image must be pulled before create
        image = pod.spec.containers[0].image
        client.call("PullImage", {"image": {"image": image}})
        out = client.call("CreateContainer", {"config": {
            "metadata": {"name": "main"},
            "image": {"image": image},
            "labels": {POD_NAME_LABEL: "p",
                       POD_NAMESPACE_LABEL: "default",
                       POD_UID_LABEL: pod.metadata.uid}}})
        cid = out["container_id"]
        # injection observable through the create info map
        assert out["info"]["env"]["TPU_VISIBLE_CHIPS"] == ""
        listed = client.call("ListContainers")["containers"]
        assert [c["id"] for c in listed] == [cid]
        st = client.call("ContainerStatus", {"container_id": cid})
        assert st["status"]["state"] == CONTAINER_EXITED  # FakeRuntime
        assert st["status"]["exit_code"] == 0
        client.call("RemoveContainer", {"container_id": cid})
        assert client.call("ListContainers")["containers"] == []


class TestImageService:
    """The ImageService half of the CRI contract (SURVEY.md §2 L2),
    served on the SAME socket as the RuntimeService — kubelet expects
    one endpoint for both."""

    def test_pull_status_list_remove(self, served):
        api, backend, runtime, server, client = served
        ref = "kubetpu/runtime:latest"
        assert client.call("ImageStatus",
                           {"image": {"image": ref}})["image"] is None
        out = client.call("PullImage", {"image": {"image": ref}})
        assert out["image_ref"].startswith("sha256:")
        st = client.call("ImageStatus", {"image": {"image": ref}})["image"]
        assert st["id"] == out["image_ref"]
        assert st["repo_tags"] == [ref]
        assert st["size"] > 0
        # idempotent re-pull keeps the same digest
        assert client.call("PullImage",
                           {"image": {"image": ref}})["image_ref"] \
            == out["image_ref"]
        client.call("PullImage", {"image": {"image": "other:v1"}})
        allimgs = client.call("ListImages")["images"]
        assert len(allimgs) == 2
        only = client.call("ListImages", {"filter": {
            "image": {"image": ref}}})["images"]
        assert [i["id"] for i in only] == [out["image_ref"]]
        fs = client.call("ImageFsInfo")["image_filesystems"][0]
        assert fs["inodes_used"]["value"] == 2
        assert fs["used_bytes"]["value"] > 0
        client.call("RemoveImage", {"image": {"image": ref}})
        assert client.call("ImageStatus",
                           {"image": {"image": ref}})["image"] is None
        client.call("RemoveImage", {"image": {"image": ref}})  # idempotent

    def test_create_requires_pulled_image(self, served):
        """kubelet's pull-serialize contract: CreateContainer with an
        unpulled image fails like a real runtime's 'image not found',
        and succeeds after PullImage."""
        api, backend, runtime, server, client = served
        api.create("Pod", tpu_pod("p", chips=0, command=["noop"]))
        pod = api.get("Pod", "p")
        req = {"config": {
            "metadata": {"name": "main"},
            "labels": {POD_NAME_LABEL: "p",
                       POD_NAMESPACE_LABEL: "default",
                       POD_UID_LABEL: pod.metadata.uid}}}
        with pytest.raises(CriError, match="not present"):
            client.call("CreateContainer", req)
        client.call("PullImage", {"image": {
            "image": pod.spec.containers[0].image}})
        out = client.call("CreateContainer", req)
        client.call("RemoveContainer",
                    {"container_id": out["container_id"]})


class TestRemoteShim:
    def test_injection_over_socket(self, served):
        """RemoteCriShim.create_container == in-process shim semantics,
        but the allocation env crosses the wire."""
        api, backend, runtime, server, client = served
        if isinstance(server, CriServer):
            shim = RemoteCriShim(server.socket_path)
        else:
            from kubegpu_tpu.crishim.grpcserver import GrpcRemoteCriShim
            shim = GrpcRemoteCriShim(server.socket_path,
                                     codec=server.codec)
        try:
            api.create("Pod", tpu_pod("p", chips=0, command=["noop"]))
            h = shim.create_container(api.get("Pod", "p"))
            assert h.env["TPU_VISIBLE_CHIPS"] == ""
            assert h.wait(timeout=1) == 0
            # the server-side runtime really got the forwarded call
            assert [c.pod_name for c in runtime.created] == ["p"]
        finally:
            shim.close()


class TestClusterOverWire:
    """SimCluster(wire_cri=True): the full §4.5 traversal with the CRI
    socket spliced between agent and shim on every node."""

    def test_single_chip_pod_full_path(self):
        cl = SimCluster(["v4-8"], wire_cri=True)
        try:
            cl.submit(tpu_pod("resnet", chips=1, command=["noop"]))
            result, started = cl.step()
            assert result.scheduled == ["resnet"]
            env = started[0].env
            assert len(env["TPU_VISIBLE_CHIPS"].split(",")) == 1
            assert env["TPU_WORKER_ID"] == "0"
            assert pod_allocation(cl.api.get("Pod", "resnet")) is not None
            assert cl.reap(timeout=1) == {"resnet": 0}
            assert cl.pod_phase("resnet") == PodPhase.SUCCEEDED
        finally:
            cl.close()

    def test_gang_over_wire(self):
        cl = SimCluster(["v4-8"], wire_cri=True)
        try:
            for i in range(4):
                cl.submit(tpu_pod(f"dp-{i}", chips=1, command=["noop"],
                                  gang=GangSpec(name="dp", size=4, index=i)))
            result, started = cl.step()
            assert len(result.scheduled) == 4
            envs = {h.pod_name: h.env for h in started}
            assert [envs[f"dp-{i}"]["TPU_WORKER_ID"] for i in range(4)] == \
                ["0", "1", "2", "3"]
            assert len({e["JAX_COORDINATOR_ADDRESS"]
                        for e in envs.values()}) == 1
        finally:
            cl.close()

    def test_host_failure_kills_over_wire(self):
        """agent.fail() → StopContainer RPCs; recovery reschedules."""
        cl = SimCluster(["v4-8", "v4-8"], wire_cri=True)
        try:
            cl.submit(tpu_pod("job", chips=1, command=["noop"]))
            _, started = cl.step()
            node = cl.api.get("Pod", "job").spec.node_name
            cl.fail_host(node)
            cl.step()  # recovery controller evicts + reschedules
            new_pod = cl.api.get("Pod", "job")
            assert new_pod.spec.node_name not in (None, node)
        finally:
            cl.close()

    def test_real_subprocess_metrics_harvested_over_wire(self):
        """A real child process's stdout metric line crosses the CRI
        socket (ContainerStatus info) and lands in metrics.snapshot()
        — north-star #2's transport, now wire-complete end to end."""
        cmd = [sys.executable, "-c",
               'print(\'{"metric": "allreduce_algo_bandwidth", '
               '"value": 21.0, "unit": "GB/s"}\')']
        cl = SimCluster(["v4-8"], wire_cri=True, real_processes=True)
        try:
            cl.submit(tpu_pod("bench", chips=0, command=cmd))
            cl.step()
            codes = cl.reap(timeout=30)
            assert codes == {"bench": 0}
            snap = cl.metrics.snapshot()
            assert snap["gauges"]["workload_allreduce_algo_bandwidth"] == 21.0
        finally:
            cl.close()

"""Llama-3-family decoder in pure JAX — the flagship pjit workload
(BASELINE config 4: Llama-3-8B on v5e-16/64).

TPU-first design choices:
- layers stored *stacked* (leading n_layers dim) and executed with
  ``lax.scan`` — one traced layer, O(1) compile time at any depth;
- bfloat16 params/activations, f32 for norms/softmax/logits;
- megatron-style sharding rules as a PartitionSpec tree (dp/fsdp batch,
  tp on head/ffn dims), applied by jit shardings + in-graph constraints;
- attention dispatches to the pallas flash kernel on TPU;
- optional ``jax.checkpoint`` per layer (remat) for long sequences;
- optional ring attention over the ``sp`` axis for sequence parallelism.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kubegpu_tpu.ops import attention
from kubegpu_tpu.parallel.sharding import constrain


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: str = "bfloat16"
    remat: bool = True
    attn_impl: str = "auto"   # auto | pallas | xla | ring
    # lax.scan unroll over the stacked layers: >1 lets XLA fuse/overlap
    # across layer boundaries at the cost of compile time (O(1) compile
    # was the reason for the scan; unroll trades some of it back)
    scan_unroll: int = 1
    # Pin head_dim independently of d_model/n_heads.  The tensor-
    # parallel serving engine derives a per-chip LOCAL config by
    # dividing the head counts by tp; head_dim must stay the physical
    # head width, not re-derive from the divided count.
    head_dim_override: int | None = None

    @property
    def head_dim(self) -> int:
        return self.head_dim_override or self.d_model // self.n_heads

    @classmethod
    def llama3_8b(cls) -> "LlamaConfig":
        return cls()

    @classmethod
    def tiny(cls, **kw) -> "LlamaConfig":
        """Test-scale config with the same structure."""
        base = cls(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                   n_kv_heads=2, d_ff=128, max_seq_len=128,
                   dtype="float32", remat=False, attn_impl="xla")
        return replace(base, **kw)

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------

def llama_init(key: jax.Array, cfg: LlamaConfig) -> dict:
    """Stacked-layer parameter pytree."""
    hd = cfg.head_dim
    k_emb, k_layers, k_out = jax.random.split(key, 3)

    def norm_init(shape):
        return jnp.ones(shape, cfg.jdtype)

    def dense_init(k, shape, scale_dim):
        return (jax.random.normal(k, shape, jnp.float32)
                * (scale_dim ** -0.5)).astype(cfg.jdtype)

    ks = jax.random.split(k_layers, 7)
    L = cfg.n_layers
    layers = {
        "attn_norm": norm_init((L, cfg.d_model)),
        "wq": dense_init(ks[0], (L, cfg.d_model, cfg.n_heads * hd),
                         cfg.d_model),
        "wk": dense_init(ks[1], (L, cfg.d_model, cfg.n_kv_heads * hd),
                         cfg.d_model),
        "wv": dense_init(ks[2], (L, cfg.d_model, cfg.n_kv_heads * hd),
                         cfg.d_model),
        "wo": dense_init(ks[3], (L, cfg.n_heads * hd, cfg.d_model),
                         cfg.n_heads * hd),
        "mlp_norm": norm_init((L, cfg.d_model)),
        "w_gate": dense_init(ks[4], (L, cfg.d_model, cfg.d_ff), cfg.d_model),
        "w_up": dense_init(ks[5], (L, cfg.d_model, cfg.d_ff), cfg.d_model),
        "w_down": dense_init(ks[6], (L, cfg.d_ff, cfg.d_model), cfg.d_ff),
    }
    return {
        "embed": dense_init(k_emb, (cfg.vocab_size, cfg.d_model), cfg.d_model),
        "layers": layers,
        "final_norm": norm_init((cfg.d_model,)),
        "lm_head": dense_init(k_out, (cfg.d_model, cfg.vocab_size),
                              cfg.d_model),
    }


def llama_param_specs(cfg: LlamaConfig) -> dict:
    """Megatron/GSPMD sharding rules (PartitionSpec tree, stacked-layer
    leading dim unsharded; ``fsdp`` shards the non-tp dim; norms are
    replicated).  Axes absent from the actual mesh are dropped by
    ``fit_spec`` at materialization."""
    return {
        "embed": P("tp", "fsdp"),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, "fsdp", "tp"),
            "wk": P(None, "fsdp", "tp"),
            "wv": P(None, "fsdp", "tp"),
            "wo": P(None, "tp", "fsdp"),
            "mlp_norm": P(None, None),
            "w_gate": P(None, "fsdp", "tp"),
            "w_up": P(None, "fsdp", "tp"),
            "w_down": P(None, "tp", "fsdp"),
        },
        "final_norm": P(None),
        "lm_head": P("fsdp", "tp"),
    }


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _rmsnorm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * rms).astype(x.dtype) * w


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """x: [B, T, H, D] — rotate pairs (d, d + D/2)."""
    d = x.shape[-1]
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[:, :, None, None].astype(jnp.float32) \
        * freqs[None, None, None, :]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def embed_lookup(embed: jax.Array, tokens: jax.Array,
                 mesh: Mesh | None) -> jax.Array:
    """Token-embedding lookup.  Single-device: a plain gather.  Under a
    sharded mesh, a one-hot contraction instead: SPMD cannot partition
    a gather from a (tp-vocab, fsdp-d) sharded table against
    (dp·fsdp, sp)-sharded indices — it falls back to "involuntary full
    rematerialization" (all-gathering the whole table per step; the
    spmd_partitioner.cc warnings in MULTICHIP_r02's tail).  The
    one-hot matmul partitions cleanly — contraction over the
    tp-sharded vocab dim becomes a local matmul + psum, and its
    transpose (the embedding gradient) is again a matmul, not a
    scatter-add.  Only meshes that actually shard the table (tp or
    fsdp > 1) pay the one-hot materialization; a dp-only mesh keeps
    the zero-comms gather.  Tokens are clipped like ``jnp.take``'s
    default mode so out-of-range ids behave identically on both
    paths (one_hot alone would silently embed them as zeros)."""
    sharded = mesh is not None and any(
        mesh.shape.get(a, 1) > 1 for a in ("tp", "fsdp"))
    if not sharded:
        return jnp.take(embed, tokens, axis=0)
    tokens = jnp.clip(tokens, 0, embed.shape[0] - 1)
    onehot = jax.nn.one_hot(tokens, embed.shape[0], dtype=embed.dtype)
    return onehot @ embed


def llama_forward(params: dict, tokens: jax.Array, cfg: LlamaConfig,
                  mesh: Mesh | None = None) -> jax.Array:
    """tokens [B, T] → logits [B, T, vocab] (f32).

    Batch is sharded on (dp, fsdp); hidden activations are constrained to
    tp on the head/ffn dim so XLA places the megatron allreduces; with
    ``attn_impl='ring'`` the sequence axis is sharded on sp and attention
    runs as a ppermute ring.
    """
    b, t = tokens.shape
    x = embed_lookup(params["embed"], tokens, mesh)
    x = constrain(x, mesh, ("dp", "fsdp"), "sp", None)
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    attend = select_attend(cfg, mesh)

    def layer(x, lp):
        x = attention_sublayer(x, lp, cfg, positions, attend, mesh)
        h = _rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        up = jax.nn.silu(h @ lp["w_gate"]) * (h @ lp["w_up"])
        up = constrain(up, mesh, ("dp", "fsdp"), "sp", "tp")
        x = x + (up @ lp["w_down"]).astype(x.dtype)
        x = constrain(x, mesh, ("dp", "fsdp"), "sp", None)
        return x, None

    layer_fn = jax.checkpoint(layer) if cfg.remat else layer
    x, _ = jax.lax.scan(layer_fn, x, params["layers"],
                        unroll=cfg.scan_unroll)
    x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return constrain(logits, mesh, ("dp", "fsdp"), "sp", "tp")


def _attn_impl(cfg: LlamaConfig) -> str:
    return cfg.attn_impl if cfg.attn_impl != "ring" else "auto"


def select_attend(cfg: LlamaConfig, mesh: Mesh | None):
    """The attention callable for this (config, mesh): the sp ring when
    requested and the mesh has an sp axis > 1, the flash/XLA kernel
    otherwise.  Shared by the Llama and MoE forwards."""
    if cfg.attn_impl == "ring" and mesh is not None \
            and "sp" in mesh.axis_names and mesh.shape["sp"] > 1:
        from kubegpu_tpu.parallel.ringattention import (
            make_sharded_ring_attention,
        )
        return _gqa_wrap(make_sharded_ring_attention(mesh), cfg)
    return lambda q, k, v: attention(q, k, v, causal=True,
                                     impl=_attn_impl(cfg))


def attention_sublayer(x: jax.Array, lp: dict, cfg: LlamaConfig,
                       positions: jax.Array, attend, mesh: Mesh | None
                       ) -> jax.Array:
    """norm → qkv → rope → attention → wo, with residual.  ``lp`` is one
    layer's (unstacked) parameter dict; shared by Llama and MoE layers."""
    b, t = x.shape[0], x.shape[1]
    hd = cfg.head_dim
    h = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
    q = (h @ lp["wq"]).reshape(b, t, cfg.n_heads, hd)
    k = (h @ lp["wk"]).reshape(b, t, cfg.n_kv_heads, hd)
    v = (h @ lp["wv"]).reshape(b, t, cfg.n_kv_heads, hd)
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    # [B, H, T, D] for the attention kernels
    o = attend(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
               v.transpose(0, 2, 1, 3))
    o = o.transpose(0, 2, 1, 3).reshape(b, t, cfg.n_heads * hd)
    o = constrain(o, mesh, ("dp", "fsdp"), "sp", "tp")
    return x + (o @ lp["wo"]).astype(x.dtype)


def _gqa_wrap(ring_fn, cfg: LlamaConfig):
    """Repeat kv heads before the ring (ring_attention wants Hq == Hkv)."""
    from kubegpu_tpu.ops.flash_attention import repeat_kv

    def attend(q, k, v):
        k, v = repeat_kv(q, k, v)
        return ring_fn(q, k, v)
    return attend


# ---------------------------------------------------------------------------
# Loss / train step builders (shared by workloads, bench, graft entry)
# ---------------------------------------------------------------------------

def next_token_loss(params: dict, tokens: jax.Array, cfg: LlamaConfig,
                    mesh: Mesh | None = None) -> jax.Array:
    """Causal LM loss: predict tokens[:, 1:] from tokens[:, :-1].

    The forward runs on ALL T tokens and the last position's logits
    are dropped, rather than slicing the input to T-1: causality makes
    the first T-1 positions' logits identical either way, but T-1
    (e.g. 2047) breaks every kernel/MXU tile alignment — the r4
    profiler trace caught the T=2047 forward silently falling back to
    O(T²)-materializing XLA attention for the entire train step."""
    logits = llama_forward(params, tokens, cfg, mesh)[:, :-1]
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -ll.mean()


def make_train_step(cfg, optimizer, mesh: Mesh | None = None,
                    loss_fn=None, accum_steps: int = 1):
    """(params, opt_state, tokens) → (params, opt_state, loss), undecorated
    (callers jit with their shardings).  ``loss_fn(params, tokens, cfg,
    mesh)`` defaults to the Llama next-token loss; the MoE step reuses
    this with its own loss.

    ``accum_steps > 1`` splits the batch into that many equal
    microbatches and accumulates their grads under ``lax.scan`` before
    ONE optimizer update — activation memory scales with the microbatch
    while the effective batch (and, for equal-size microbatches, the
    resulting update) stays that of the full batch.  Trades steps for
    HBM: the lever when a model fits but its activations don't."""
    import optax

    loss_fn = loss_fn if loss_fn is not None else next_token_loss
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    def step(params, opt_state, tokens):
        if accum_steps == 1:
            loss, grads = jax.value_and_grad(loss_fn)(
                params, tokens, cfg, mesh)
        else:
            b = tokens.shape[0]
            if b % accum_steps:
                raise ValueError(
                    f"batch {b} not divisible by accum_steps "
                    f"{accum_steps}")
            micro = tokens.reshape(accum_steps, b // accum_steps,
                                   *tokens.shape[1:])

            def acc(carry, mb):
                loss_sum, grad_sum = carry
                loss, grads = jax.value_and_grad(loss_fn)(
                    params, mb, cfg, mesh)
                return (loss_sum + loss,
                        jax.tree.map(jnp.add, grad_sum, grads)), None

            zeros = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params)
            (loss_sum, grad_sum), _ = jax.lax.scan(
                acc, (jnp.float32(0.0), zeros), micro)
            loss = loss_sum / accum_steps
            grads = jax.tree.map(
                lambda g, p: (g / accum_steps).astype(p.dtype),
                grad_sum, params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step

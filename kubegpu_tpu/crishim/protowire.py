"""Hand-rolled protobuf wire-format codec for the kubelet CRI messages.

VERDICT r4 missing #1: the gRPC CRI endpoint (``grpcserver.py``) spoke
real gRPC framing but carried JSON bodies — a stock kubelet marshals
``runtime.v1`` protobufs, so it could exchange *frames* but not
*messages*.  protoc is absent in this environment, but the proto wire
format is small and fully specified: varints, 3-bit wire-type tags, and
length-delimited fields.  This module implements exactly that subset —
enough for the ~12 request/response pairs the shim serves — as a
schema-driven encoder/decoder, and declares those message schemas with
the public ``k8s.io/cri-api`` ``runtime/v1/api.proto`` field numbers
(SURVEY.md §2 L2, §4.3; the reference mount is empty, so numbers follow
the public cri-api layout and are pinned by golden-bytes tests).

Wire-format rules implemented (proto3):
- varint fields (int32/int64/uint64/bool/enum): wire type 0; negative
  int32/int64 encode as 10-byte two's-complement varints;
- length-delimited (string/bytes/embedded message/map entry): wire
  type 2;
- repeated strings/messages: one tagged field per element;
- ``map<string,string>``: repeated entry message {key=1, value=2};
- proto3 presence: default-valued scalars are not emitted; absent
  singular message fields decode as ``None``; absent scalars decode to
  their defaults ("" / 0 / False), repeated → [], map → {};
- unknown fields are skipped by wire type (forward compatibility — a
  newer kubelet's extra fields must not break the shim).

KubeTPU extensions ride in the reserved-for-private range (field
numbers >= 1000): kubelet ignores unknown fields, so the endpoint stays
stock-compatible while our own client can still see e.g. the injected
env on CreateContainerResponse.  Structured values inside ``info`` maps
are JSON-encoded strings — the CRI's own convention for its verbose
info map.
"""

from __future__ import annotations

import json
from typing import Any

# -- primitive wire encoding ---------------------------------------------

_WT_VARINT = 0
_WT_I64 = 1
_WT_LEN = 2
_WT_I32 = 5

_U64_MASK = (1 << 64) - 1


def encode_varint(n: int) -> bytes:
    """Unsigned LEB128; negative ints are two's-complement 64-bit
    (proto's int32/int64 encoding — always 10 bytes when negative)."""
    n &= _U64_MASK
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decode_varint(data: bytes, pos: int) -> tuple[int, int]:
    """(value, new_pos); value is the raw unsigned 64-bit quantity."""
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result & _U64_MASK, pos
        shift += 7
        if shift >= 70:
            raise ValueError("varint too long")


def _signed(v: int) -> int:
    """Reinterpret an unsigned 64-bit varint as proto int64."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _tag(num: int, wt: int) -> bytes:
    return encode_varint((num << 3) | wt)


def _len_field(num: int, payload: bytes) -> bytes:
    return _tag(num, _WT_LEN) + encode_varint(len(payload)) + payload


# -- schema-driven message codec ------------------------------------------
#
# A message schema is {field_name: (field_number, kind, sub)} where kind:
#   "string" / "bytes"            length-delimited scalar
#   "int" / "bool"                varint scalar
#   "enum"                        varint via sub = {name: number} map
#   "message"                     embedded message, sub = schema
#   "rep_string" / "rep_message"  repeated
#   "map_str"                     map<string,string>
#   "map_json"                    map<string,string> with JSON-encoded
#                                 values (CRI verbose-info convention)


def encode_message(schema: dict, obj: dict | None) -> bytes:
    out = bytearray()
    obj = obj or {}
    for name, (num, kind, sub) in schema.items():
        val = obj.get(name)
        if val is None:
            continue
        if kind == "string":
            if val != "":
                out += _len_field(num, str(val).encode())
        elif kind == "bytes":
            if val:
                out += _len_field(num, bytes(val))
        elif kind == "int":
            if int(val):
                out += _tag(num, _WT_VARINT) + encode_varint(int(val))
        elif kind == "bool":
            if val:
                out += _tag(num, _WT_VARINT) + encode_varint(1)
        elif kind == "enum":
            n = sub[val] if isinstance(val, str) else int(val)
            if n:
                out += _tag(num, _WT_VARINT) + encode_varint(n)
        elif kind == "message":
            out += _len_field(num, encode_message(sub, val))
        elif kind == "rep_string":
            for item in val:
                out += _len_field(num, str(item).encode())
        elif kind == "rep_message":
            for item in val:
                out += _len_field(num, encode_message(sub, item))
        elif kind in ("map_str", "map_json"):
            for k in sorted(val):   # deterministic bytes (golden tests)
                v = val[k]
                vs = json.dumps(v) if kind == "map_json" else str(v)
                entry = (_len_field(1, str(k).encode())
                         + _len_field(2, vs.encode()))
                out += _len_field(num, entry)
        else:   # pragma: no cover — schema author error
            raise ValueError(f"unknown field kind {kind!r}")
    return bytes(out)


def _skip(data: bytes, pos: int, wt: int) -> int:
    if wt == _WT_VARINT:
        _, pos = decode_varint(data, pos)
        return pos
    if wt == _WT_I64:
        return pos + 8
    if wt == _WT_LEN:
        n, pos = decode_varint(data, pos)
        return pos + n
    if wt == _WT_I32:
        return pos + 4
    raise ValueError(f"unsupported wire type {wt}")


def decode_message(schema: dict, data: bytes) -> dict:
    """Decode ``data`` against ``schema``; returns a dict with every
    declared field materialized (proto3 defaults when absent; ``None``
    for absent singular messages) and unknown fields skipped."""
    by_num = {num: (name, kind, sub)
              for name, (num, kind, sub) in schema.items()}
    out: dict[str, Any] = {}
    for name, (num, kind, sub) in schema.items():
        if kind in ("rep_string", "rep_message"):
            out[name] = []
        elif kind in ("map_str", "map_json"):
            out[name] = {}
        elif kind == "message":
            out[name] = None
        elif kind == "string":
            out[name] = ""
        elif kind == "bytes":
            out[name] = b""
        elif kind == "bool":
            out[name] = False
        elif kind == "enum":
            out[name] = _enum_name(sub, 0)
        else:
            out[name] = 0
    pos = 0
    while pos < len(data):
        key, pos = decode_varint(data, pos)
        num, wt = key >> 3, key & 7
        entry = by_num.get(num)
        if entry is None:
            pos = _skip(data, pos, wt)
            continue
        name, kind, sub = entry
        if kind in ("string", "bytes", "message", "rep_string",
                    "rep_message", "map_str", "map_json"):
            if wt != _WT_LEN:
                raise ValueError(
                    f"field {name} expects length-delimited, got wt={wt}")
            n, pos = decode_varint(data, pos)
            payload = data[pos:pos + n]
            if len(payload) != n:
                raise ValueError(f"truncated field {name}")
            pos += n
            if kind == "string":
                out[name] = payload.decode()
            elif kind == "bytes":
                out[name] = payload
            elif kind == "message":
                out[name] = decode_message(sub, payload)
            elif kind == "rep_string":
                out[name].append(payload.decode())
            elif kind == "rep_message":
                out[name].append(decode_message(sub, payload))
            else:
                k, v = _decode_map_entry(payload)
                if kind == "map_json":
                    try:
                        v = json.loads(v)
                    except (json.JSONDecodeError, ValueError):
                        pass   # a foreign client may send raw strings
                out[name][k] = v
        else:
            if wt != _WT_VARINT:
                raise ValueError(
                    f"field {name} expects varint, got wt={wt}")
            raw, pos = decode_varint(data, pos)
            if kind == "bool":
                out[name] = bool(raw)
            elif kind == "enum":
                out[name] = _enum_name(sub, raw)
            else:
                out[name] = _signed(raw)
    return out


def _decode_map_entry(payload: bytes) -> tuple[str, str]:
    k = v = ""
    pos = 0
    while pos < len(payload):
        key, pos = decode_varint(payload, pos)
        num, wt = key >> 3, key & 7
        if wt != _WT_LEN:
            pos = _skip(payload, pos, wt)
            continue
        n, pos = decode_varint(payload, pos)
        s = payload[pos:pos + n].decode()
        pos += n
        if num == 1:
            k = s
        elif num == 2:
            v = s
    return k, v


def _enum_name(enum: dict, raw: int):
    for name, n in enum.items():
        if n == raw:
            return name
    return raw   # unknown enum value: surface the number


# -- runtime.v1 schemas ----------------------------------------------------
# Field numbers follow the public k8s.io/cri-api runtime/v1 api.proto;
# KubeTPU extension fields sit at >= 1000 (ignored by stock kubelets).

CONTAINER_STATE = {
    "CONTAINER_CREATED": 0,
    "CONTAINER_RUNNING": 1,
    "CONTAINER_EXITED": 2,
    "CONTAINER_UNKNOWN": 3,
}

_CONTAINER_METADATA = {
    "name": (1, "string", None),
    "attempt": (2, "int", None),
}

_IMAGE_SPEC = {
    "image": (1, "string", None),
    "annotations": (2, "map_str", None),
}

_KEY_VALUE = {
    "key": (1, "string", None),
    "value": (2, "string", None),
}

_CONTAINER_CONFIG = {
    "metadata": (1, "message", _CONTAINER_METADATA),
    "image": (2, "message", _IMAGE_SPEC),
    "command": (3, "rep_string", None),
    "args": (4, "rep_string", None),
    "working_dir": (5, "string", None),
    "envs": (6, "rep_message", _KEY_VALUE),
    "labels": (9, "map_str", None),
    "annotations": (10, "map_str", None),
}

_CONTAINER_STATUS = {
    "id": (1, "string", None),
    "metadata": (2, "message", _CONTAINER_METADATA),
    "state": (3, "enum", CONTAINER_STATE),
    "created_at": (4, "int", None),
    "started_at": (5, "int", None),
    "finished_at": (6, "int", None),
    "exit_code": (7, "int", None),
    "image": (8, "message", _IMAGE_SPEC),
    "image_ref": (9, "string", None),
    "reason": (10, "string", None),
    "message": (11, "string", None),
    "labels": (12, "map_str", None),
}

_CONTAINER = {
    "id": (1, "string", None),
    "pod_sandbox_id": (2, "string", None),
    "metadata": (3, "message", _CONTAINER_METADATA),
    "image": (4, "message", _IMAGE_SPEC),
    "image_ref": (5, "string", None),
    "state": (6, "enum", CONTAINER_STATE),
    "created_at": (7, "int", None),
    "labels": (8, "map_str", None),
    "annotations": (9, "map_str", None),
}

_IMAGE = {
    "id": (1, "string", None),
    "repo_tags": (2, "rep_string", None),
    "repo_digests": (3, "rep_string", None),
    "size": (4, "int", None),
}

_IMAGE_FILTER = {
    "image": (1, "message", _IMAGE_SPEC),
}

_CONTAINER_FILTER = {
    "id": (1, "string", None),
    "state": (2, "message", {"state": (1, "enum", CONTAINER_STATE)}),
    "pod_sandbox_id": (3, "string", None),
    "label_selector": (4, "map_str", None),
}

_UINT64_VALUE = {
    "value": (1, "int", None),
}

_FILESYSTEM_IDENTIFIER = {
    "mountpoint": (1, "string", None),
}

_FILESYSTEM_USAGE = {
    "timestamp": (1, "int", None),
    "fs_id": (2, "message", _FILESYSTEM_IDENTIFIER),
    "used_bytes": (3, "message", _UINT64_VALUE),
    "inodes_used": (4, "message", _UINT64_VALUE),
}

# method → (request schema, response schema)
MESSAGES: dict[str, tuple[dict, dict]] = {
    "Version": (
        {"version": (1, "string", None)},
        {"version": (1, "string", None),
         "runtime_name": (2, "string", None),
         "runtime_version": (3, "string", None),
         "runtime_api_version": (4, "string", None),
         # extension: which node this shim serves (tests/observability)
         "node_name": (1000, "string", None)},
    ),
    "CreateContainer": (
        {"pod_sandbox_id": (1, "string", None),
         "config": (2, "message", _CONTAINER_CONFIG)},
        {"container_id": (1, "string", None),
         # extension: the injected env + pid, JSON-valued info map
         # (the CRI verbose-info convention, private field range)
         "info": (1000, "map_json", None)},
    ),
    "StartContainer": (
        {"container_id": (1, "string", None)},
        {},
    ),
    "StopContainer": (
        {"container_id": (1, "string", None),
         "timeout": (2, "int", None)},
        {},
    ),
    "RemoveContainer": (
        {"container_id": (1, "string", None)},
        {},
    ),
    "ListContainers": (
        {"filter": (1, "message", _CONTAINER_FILTER)},
        {"containers": (1, "rep_message", _CONTAINER)},
    ),
    "ContainerStatus": (
        {"container_id": (1, "string", None),
         "verbose": (2, "bool", None)},
        {"status": (1, "message", _CONTAINER_STATUS),
         "info": (2, "map_json", None)},
    ),
    "PullImage": (
        {"image": (1, "message", _IMAGE_SPEC)},
        {"image_ref": (1, "string", None)},
    ),
    "ImageStatus": (
        {"image": (1, "message", _IMAGE_SPEC),
         "verbose": (2, "bool", None)},
        {"image": (1, "message", _IMAGE),
         "info": (2, "map_json", None)},
    ),
    "ListImages": (
        {"filter": (1, "message", _IMAGE_FILTER)},
        {"images": (1, "rep_message", _IMAGE)},
    ),
    "RemoveImage": (
        {"image": (1, "message", _IMAGE_SPEC)},
        {},
    ),
    "ImageFsInfo": (
        {},
        {"image_filesystems": (1, "rep_message", _FILESYSTEM_USAGE)},
    ),
}


def request_serializer(method: str):
    schema = MESSAGES[method][0]
    return lambda obj: encode_message(schema, obj)


def request_deserializer(method: str):
    schema = MESSAGES[method][0]
    return lambda data: decode_message(schema, data or b"")


def response_serializer(method: str):
    schema = MESSAGES[method][1]
    return lambda obj: encode_message(schema, obj)


def response_deserializer(method: str):
    schema = MESSAGES[method][1]
    return lambda data: decode_message(schema, data or b"")

"""ISSUE 20 flight recorder, paging half: multi-window burn-rate
rules (fast AND slow window must breach), hold-tick hysteresis,
cooldown, tick determinism, and the FlightRecorder controller that
bolts the whole loop onto ``run_load``/``run_fleet``.
"""

import pytest

from kubegpu_tpu.obs.alerts import (
    BURN,
    Alert,
    AlertEngine,
    AlertRule,
    FlightRecorder,
    default_rules,
)
from kubegpu_tpu.obs.metrics import MetricsRegistry
from kubegpu_tpu.obs.spans import Tracer
from kubegpu_tpu.obs.tsdb import SeriesStore


def _drive(engine, reg, store, ticks, failovers=()):
    fired = []
    for t in range(ticks):
        if t in failovers:
            reg.inc("serve_failover_total", 16)
        store.sample(t)
        fired.extend(engine.evaluate(t))
    return fired


def test_rule_validation():
    with pytest.raises(ValueError):
        AlertRule(name="x", series="s", kind="bogus")
    with pytest.raises(ValueError):
        AlertRule(name="x", series="s", fast_window=64, slow_window=8)
    with pytest.raises(ValueError):
        AlertRule(name="x", series="s", fast_window=0)


def test_healthy_run_fires_nothing():
    reg = MetricsRegistry()
    store = SeriesStore(reg)
    engine = AlertEngine(store, metrics=reg)
    assert _drive(engine, reg, store, 100) == []
    assert list(engine.alerts) == []


def test_failover_burst_pages_within_bound():
    reg = MetricsRegistry()
    store = SeriesStore(reg)
    engine = AlertEngine(store, metrics=reg)
    fired = _drive(engine, reg, store, 60, failovers={20})
    assert fired, "burst never paged"
    a = fired[0]
    assert a.rule == "alert_failover_burn"
    assert a.tick - 20 <= 16
    assert a.fast > a.slow > 0
    assert reg.snapshot()["counters"]["serve_alerts_fired"] == len(fired)


def test_both_windows_must_breach():
    # a burst INSIDE the fast window but too small for the slow
    # window's budget must not page: one failover in 64 ticks is
    # 1/64 ≈ 0.016 < slow_threshold 0.02
    reg = MetricsRegistry()
    store = SeriesStore(reg)
    engine = AlertEngine(store, metrics=reg)
    assert _drive(engine, reg, store, 60, failovers=()) == []
    reg2 = MetricsRegistry()
    store2 = SeriesStore(reg2)
    engine2 = AlertEngine(store2, metrics=reg2)
    fired = []
    for t in range(60):
        if t == 20:
            reg2.inc("serve_failover_total", 1)
        store2.sample(t)
        fired.extend(engine2.evaluate(t))
    assert fired == []


def test_hold_ticks_hysteresis():
    # hold_ticks=3: the breach must PERSIST three consecutive
    # evaluations before paging — a one-tick spike that decays out of
    # the fast window before the streak completes never fires
    rule = AlertRule(name="alert_failover_burn",
                     series="serve_failover_total",
                     fast_window=2, slow_window=4,
                     fast_threshold=4.0, slow_threshold=0.5,
                     hold_ticks=3)
    reg = MetricsRegistry()
    store = SeriesStore(reg)
    engine = AlertEngine(store, rules=[rule], metrics=reg)
    fired = []
    for t in range(10):
        if t == 2:
            reg.inc("serve_failover_total", 10)
        store.sample(t)
        fired.extend(engine.evaluate(t))
    # breach at t=2,3 only (fast window 2) — streak never reaches 3
    assert fired == []


def test_cooldown_suppresses_refires():
    rule = AlertRule(name="alert_failover_burn",
                     series="serve_failover_total",
                     fast_window=2, slow_window=4,
                     fast_threshold=0.5, slow_threshold=0.25,
                     hold_ticks=1, cooldown_ticks=20)
    reg = MetricsRegistry()
    store = SeriesStore(reg)
    engine = AlertEngine(store, rules=[rule], metrics=reg)
    fired = []
    for t in range(30):
        reg.inc("serve_failover_total", 5)   # permanently on fire
        store.sample(t)
        fired.extend(engine.evaluate(t))
    assert len(fired) == 2
    assert fired[1].tick - fired[0].tick >= 20


def test_burn_rule_measures_objective_shortfall():
    rule = AlertRule(name="alert_slo_burn",
                     series="serve_slo_attainment", kind=BURN,
                     objective=0.95, fast_window=4, slow_window=8,
                     fast_threshold=0.3, slow_threshold=0.2,
                     hold_ticks=2)
    reg = MetricsRegistry()
    store = SeriesStore(reg)
    engine = AlertEngine(store, rules=[rule], metrics=reg)
    fired = []
    for t in range(30):
        # attainment collapses to 0.5 at tick 10
        reg.set_gauge("serve_slo_attainment", 1.0 if t < 10 else 0.5)
        store.sample(t)
        fired.extend(engine.evaluate(t))
    assert fired and fired[0].rule == "alert_slo_burn"
    assert fired[0].tick >= 11   # hold_ticks=2 past the collapse
    # an EMPTY window measures 0 burn: missing data is not an incident
    empty = AlertEngine(SeriesStore(MetricsRegistry()), rules=[rule])
    assert empty._measure(rule) == (0.0, 0.0)


def test_alert_records_are_deterministic():
    def once():
        reg = MetricsRegistry()
        store = SeriesStore(reg)
        engine = AlertEngine(store, metrics=reg)
        return _drive(engine, reg, store, 80, failovers={20, 60})
    a, b = once(), once()
    assert a == b
    assert all(isinstance(x, Alert) for x in a)


def test_default_rules_cover_documented_names():
    from kubegpu_tpu.obs.metrics import documented_names
    docs = documented_names()["metrics"]
    for rule in default_rules():
        assert rule.name in docs, rule.name
        assert rule.series in docs, rule.series


def test_alert_log_bounded():
    rule = AlertRule(name="alert_failover_burn",
                     series="serve_failover_total",
                     fast_window=1, slow_window=1,
                     fast_threshold=0.5, slow_threshold=0.5,
                     hold_ticks=1, cooldown_ticks=0)
    reg = MetricsRegistry()
    store = SeriesStore(reg)
    engine = AlertEngine(store, rules=[rule], metrics=reg,
                         capacity=16)
    for t in range(100):
        reg.inc("serve_failover_total", 5)
        store.sample(t)
        engine.evaluate(t)
    assert len(engine.alerts) == 16


def test_flight_recorder_controller_contract():
    reg = MetricsRegistry()
    tracer = Tracer()
    inner_calls = []
    rec = FlightRecorder(reg, tracer=tracer,
                         inner=lambda t, s: inner_calls.append(t))
    for t in range(40):
        if t == 20:
            reg.inc("serve_failover_total", 16)
        rec(t, {"attainment": 1.0})
    assert inner_calls == list(range(40))       # chains the wrapped hook
    assert rec.alert_log() == [(21, "alert_failover_burn")]
    assert rec.ticks == 40
    assert rec.overhead_per_tick_s > 0.0
    # the attainment gauge was refreshed from the stats dict
    assert reg.snapshot()["gauges"]["serve_slo_attainment"] == 1.0
    # the firing landed on the span timeline as an alert.fired instant
    trace = tracer.to_chrome_trace()
    assert "alert.fired" in trace

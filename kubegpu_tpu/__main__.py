"""``python -m kubegpu_tpu`` → the kubetpu CLI."""

import sys

from kubegpu_tpu.cli import main

sys.exit(main())

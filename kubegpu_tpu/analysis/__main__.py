"""CLI: ``python -m kubegpu_tpu.analysis [--json] [--no-census]
[--lint-only] [--root DIR]``.

Exit status 0 when the repo is clean (blessed findings do not fail the
run — they are reported under ``"blessed"`` so the allowlist itself
stays reviewable), 1 when any unblessed violation is found, 2 on
usage errors.  ``make analyze`` is the canonical invocation.
"""

from __future__ import annotations

import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kubegpu_tpu.analysis",
        description="KTP-Audit: jaxpr auditor + repo lint engine")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable JSON report")
    ap.add_argument("--no-census", action="store_true",
                    help="skip the compile-signature census (the only "
                         "pass that compiles; the rest just trace)")
    ap.add_argument("--lint-only", action="store_true",
                    help="AST lints only — no jax import, no tracing")
    ap.add_argument("--root", default=None,
                    help="package root to lint (default: the installed "
                         "kubegpu_tpu package)")
    args = ap.parse_args(argv)

    if args.lint_only:
        import pathlib

        from .blessed import Blessings
        from .lint import lint_package
        from .report import Report
        root = pathlib.Path(args.root) if args.root else \
            pathlib.Path(__file__).resolve().parent.parent
        report = Report()
        report.extend(lint_package(root, Blessings.load()))
    else:
        from . import run_all
        report = run_all(root=args.root, census=not args.no_census)

    if args.json:
        print(report.to_json())
    else:
        print(report.render())
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())

"""Weight-only int8 serving: accuracy bounds, size halving, and drop-in
compatibility with the existing forward/decode paths."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubegpu_tpu.models import (
    LlamaConfig, greedy_generate, llama_forward, llama_init,
)
from kubegpu_tpu.models.quant import (
    QTensor,
    quantize,
    quantize_llama,
    tree_nbytes,
)


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny(n_layers=3, n_heads=4, n_kv_heads=2,
                           max_seq_len=64)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    return cfg, params


class TestQTensor:
    def test_roundtrip_error_bounded(self):
        w = jax.random.normal(jax.random.PRNGKey(1), (64, 32))
        q = quantize(w)
        err = jnp.abs(q.dequantize() - w)
        # symmetric int8: error <= scale/2 per channel
        assert float(jnp.max(err / q.scale)) <= 0.5 + 1e-6

    def test_matmul_matches_dequantized(self):
        w = jax.random.normal(jax.random.PRNGKey(2), (16, 24))
        x = jax.random.normal(jax.random.PRNGKey(3), (4, 16))
        q = quantize(w)
        np.testing.assert_allclose(np.asarray(x @ q),
                                   np.asarray(x @ q.dequantize()),
                                   atol=1e-5, rtol=1e-5)

    def test_stacked_matmul_outside_scan(self):
        """Advisor regression: [L, in, out] stacked weights used
        directly (outside lax.scan) must scale per layer, not collide
        the layer dim with the batch dim — including when B == L."""
        L, B, cin, cout = 3, 3, 8, 5   # B == L: the silent-mis-scale case
        w = jax.random.normal(jax.random.PRNGKey(5), (L, cin, cout))
        x = jax.random.normal(jax.random.PRNGKey(6), (B, cin))
        q = quantize(w, batch_dims=1)
        out = x @ q
        assert out.shape == (L, B, cout)
        np.testing.assert_allclose(
            np.asarray(out),
            np.asarray(jnp.einsum("bi,lio->lbo", x, q.dequantize())),
            atol=1e-4, rtol=1e-4)

    def test_1d_x_against_2d_and_stacked(self):
        """Review regression: a 1-D x has no batch dim, so the kept-dims
        scale must be squeezed or broadcasting resurrects the contracted
        slot ([out]*[1,out]→[1,out]; [L,out]*[L,1,out]→[L,L,out])."""
        x = jax.random.normal(jax.random.PRNGKey(7), (8,))
        w2 = jax.random.normal(jax.random.PRNGKey(8), (8, 5))
        q2 = quantize(w2)
        assert (x @ q2).shape == (5,)
        np.testing.assert_allclose(np.asarray(x @ q2),
                                   np.asarray(x @ q2.dequantize()),
                                   atol=1e-5, rtol=1e-5)
        w3 = jax.random.normal(jax.random.PRNGKey(9), (3, 8, 5))
        q3 = quantize(w3, batch_dims=1)
        assert (x @ q3).shape == (3, 5)
        np.testing.assert_allclose(
            np.asarray(x @ q3),
            np.asarray(jnp.einsum("i,lio->lo", x, q3.dequantize())),
            atol=1e-4, rtol=1e-4)

    def test_jit_and_pytree(self):
        w = jax.random.normal(jax.random.PRNGKey(4), (8, 8))
        q = quantize(w)
        leaves = jax.tree.leaves(q)
        assert len(leaves) == 2
        out = jax.jit(lambda x, qt: x @ qt)(jnp.ones((2, 8)), q)
        assert out.shape == (2, 8)


class TestQuantizedLlama:
    def test_halves_weight_bytes(self, tiny):
        cfg, params = tiny
        # compare against a bf16 deployment (the serving dtype)
        bf16 = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
        qparams = quantize_llama(bf16)
        assert tree_nbytes(qparams) < 0.62 * tree_nbytes(bf16)

    def test_forward_close_to_fp32(self, tiny):
        cfg, params = tiny
        tokens = (jnp.arange(2 * 12, dtype=jnp.int32).reshape(2, 12) * 7
                  ) % cfg.vocab_size
        ref = llama_forward(params, tokens, cfg)
        got = jax.jit(lambda p, t: llama_forward(p, t, cfg))(
            quantize_llama(params), tokens)
        ref_n = np.asarray(ref).ravel()
        got_n = np.asarray(got).ravel()
        cos = float(np.dot(ref_n, got_n)
                    / (np.linalg.norm(ref_n) * np.linalg.norm(got_n)))
        assert cos > 0.999, cos

    def test_greedy_generate_runs_quantized(self, tiny):
        """The KV-cache decode loop accepts the quantized tree as-is."""
        cfg, params = tiny
        prompt = (jnp.arange(2 * 5, dtype=jnp.int32).reshape(2, 5) * 3
                  ) % cfg.vocab_size
        toks_q = greedy_generate(quantize_llama(params), prompt, 6, cfg)
        assert toks_q.shape == (2, 6)
        toks_f = greedy_generate(params, prompt, 6, cfg)
        # int8 weights perturb logits; most greedy picks still agree
        agree = float((np.asarray(toks_q) == np.asarray(toks_f)).mean())
        assert agree >= 0.5, (toks_q, toks_f)


class TestStackedQTensor:
    def test_stacked_dequantize_broadcasts(self, tiny):
        """Review regression: layers leaves ([L, in, out] values with
        [L, 1, out] scales) must dequantize correctly outside lax.scan
        (export/debug paths), not crash or silently mis-scale."""
        cfg, params = tiny
        from kubegpu_tpu.models.quant import quantize_llama
        q = quantize_llama(params)["layers"]["wq"]
        d = q.dequantize()
        assert d.shape == params["layers"]["wq"].shape
        err = jnp.max(jnp.abs(d - params["layers"]["wq"])
                      / jnp.squeeze(q.scale, -2)[:, None, :])
        assert float(err) <= 0.5 + 1e-6


class TestQuantizedT5:
    def test_t5_quantized_serving(self):
        """quantize_t5 drops into encode + cached greedy decode
        unchanged — including the precomputed cross-K/V path — with
        halved matmul-weight bytes and bounded logit error."""
        from kubegpu_tpu.models.quant import quantize_t5, tree_nbytes
        from kubegpu_tpu.models.t5 import (
            T5Config,
            t5_encode,
            t5_greedy_generate,
            t5_init,
        )
        cfg = T5Config.tiny()
        params = t5_init(jax.random.PRNGKey(3), cfg)
        qparams = quantize_t5(params)
        assert tree_nbytes(qparams) < 0.62 * tree_nbytes(params)
        enc = jnp.asarray(
            np.arange(2 * 6).reshape(2, 6) % cfg.vocab_size, jnp.int32)
        full = t5_encode(params, enc, cfg)
        quant = t5_encode(qparams, enc, cfg)
        # int8 weight error compounds per layer but stays small
        assert float(jnp.mean(jnp.abs(full - quant))) < 0.1 * float(
            jnp.mean(jnp.abs(full)) + 1e-6)
        toks = t5_greedy_generate(qparams, enc, 5, cfg)
        assert toks.shape == (2, 5)
        assert bool(jnp.all((toks >= 0) & (toks < cfg.vocab_size)))


class TestQuantizedMoE:
    def test_moe_quantized_serving(self):
        """quantize_moe: expert weights carry per-(layer, expert,
        channel) scales so the vmap'd expert matmuls map values and
        scales in lockstep; routed decode runs quantized and the f32
        router stays untouched."""
        from kubegpu_tpu.models.moe import (
            MoEConfig,
            moe_forward,
            moe_greedy_generate,
            moe_init,
        )
        from kubegpu_tpu.models.quant import (
            QTensor,
            quantize_moe,
            tree_nbytes,
        )
        cfg = MoEConfig.tiny()
        params = moe_init(jax.random.PRNGKey(4), cfg)
        qparams = quantize_moe(params)
        assert tree_nbytes(qparams) < 0.62 * tree_nbytes(params)
        wg = qparams["layers"]["w_gate"]
        assert isinstance(wg, QTensor)
        # per-(layer, EXPERT, channel) scales: expert axis NOT reduced
        assert wg.scale.shape[:2] == wg.values.shape[:2]
        assert qparams["layers"]["w_router"].dtype == jnp.float32
        toks = jnp.asarray(
            np.arange(2 * 6).reshape(2, 6) % cfg.base.vocab_size,
            jnp.int32)
        full, _ = moe_forward(params, toks, cfg)
        quant, _ = moe_forward(qparams, toks, cfg)
        assert float(jnp.mean(jnp.abs(full - quant))) < 0.1 * float(
            jnp.mean(jnp.abs(full)) + 1e-6)
        gen = moe_greedy_generate(qparams, toks, 4, cfg,
                                  max_len=cfg.base.max_seq_len)
        assert gen.shape == (2, 4)

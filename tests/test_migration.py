"""Defragmentation via migratable gangs: the scheduler may relocate
checkpointed workloads to compact space — only under a joint plan that
proves the big gang fits AND every migrated gang re-places."""

from kubegpu_tpu.allocator import GangRequest
from kubegpu_tpu.cluster import SimCluster, tpu_pod
from kubegpu_tpu.kubemeta import GangSpec, PodPhase, pod_allocation
from kubegpu_tpu.tpuplugin.backend import MILLICHIPS_PER_CHIP


def block_origin(cl, name):
    alloc = pod_allocation(cl.api.get("Pod", name))
    return min(ch.coord for ch in alloc.chips)


class TestMigration:
    def _fragment_v5e16(self, cl):
        """Fill all four host blocks with migratable 4-chip pods, then
        complete the two on a DIAGONAL — the 8 free chips are left
        disconnected, so an 8-chip gang can't place without migration."""
        for n in "abcd":
            cl.submit(tpu_pod(n, chips=4, command=["x"], migratable=True))
        cl.step()
        origins = {n: block_origin(cl, n) for n in "abcd"}
        # find a diagonal pair of blocks (|dx| == |dy| == 2)
        names = list(origins)
        for i in range(4):
            for j in range(i + 1, 4):
                a, b = names[i], names[j]
                dx = abs(origins[a][0] - origins[b][0])
                dy = abs(origins[a][1] - origins[b][1])
                if dx == 2 and dy == 2:
                    for victim in (a, b):
                        cl.api.delete("Pod", victim)
                    return [n for n in names if n not in (a, b)]
        raise AssertionError(f"no diagonal pair in {origins}")

    def test_migration_compacts_disconnected_free_space(self):
        cl = SimCluster(["v5e-16"])
        survivors = self._fragment_v5e16(cl)
        # 8 chips free but in two diagonal (disconnected) blocks
        cl.submit(*[
            tpu_pod(f"big-{i}", chips=4,
                    gang=GangSpec(name="big", size=2, index=i),
                    command=["x"])
            for i in range(2)
        ])
        result, _ = cl.step()
        assert {"big-0", "big-1"} <= set(result.scheduled), result
        moved = [n for n in survivors
                 if cl.pod_phase(n) == PodPhase.PENDING]
        assert len(moved) == 1, moved   # minimal plan: one migrant
        assert cl.metrics.snapshot()["counters"]["gangs_migrated"] == 1.0
        # next pass: the migrant re-places in a freed diagonal block
        result, _ = cl.step()
        assert moved[0] in result.scheduled
        # no over-commitment anywhere
        for st in cl.scheduler.slices.values():
            for used in st.used_millichips.values():
                assert 0 <= used <= MILLICHIPS_PER_CHIP
        cl.close()

    def test_no_migration_without_opt_in(self):
        cl = SimCluster(["v5e-16"])
        for n in "abcd":
            cl.submit(tpu_pod(n, chips=4, command=["x"]))  # not migratable
        cl.step()
        origins = {n: block_origin(cl, n) for n in "abcd"}
        names = list(origins)
        done = False
        for i in range(4):
            for j in range(i + 1, 4):
                a, b = names[i], names[j]
                if not done and (
                        abs(origins[a][0] - origins[b][0]) == 2
                        and abs(origins[a][1] - origins[b][1]) == 2):
                    cl.api.delete("Pod", a)
                    cl.api.delete("Pod", b)
                    done = True
        assert done
        cl.submit(*[
            tpu_pod(f"big-{i}", chips=4,
                    gang=GangSpec(name="big", size=2, index=i),
                    command=["x"])
            for i in range(2)
        ])
        result, _ = cl.step()
        assert {"big-0", "big-1"} <= set(result.unschedulable)
        cl.close()

    def test_no_migration_that_strands_the_migrant(self):
        """If the migrated gang could not re-place anywhere, nobody
        moves (the joint-closure check)."""
        cl = SimCluster(["v4-8"])
        cl.submit(tpu_pod("mova", chips=2, command=["x"], migratable=True))
        cl.step()
        cl.submit(tpu_pod("big", chips=4, command=["x"]))
        result, _ = cl.step()
        assert "big" in result.unschedulable
        assert cl.pod_phase("mova") != PodPhase.PENDING
        cl.close()

    def test_migration_never_disturbs_higher_priority(self):
        """Planner-level: a migratable gang above the requester's
        priority is not a candidate; at equal priority it is."""
        cl = SimCluster(["v4-8", "v4-8"])
        cl.submit(tpu_pod("vip", chips=2, command=["x"], migratable=True,
                          priority=10))
        cl.step()
        # pin 2 chips of the OTHER slice (a tenant big can't displace,
        # but vip could co-tenant with)
        vip_slice = pod_allocation(cl.api.get("Pod", "vip")).slice_id
        other = next(st for sid, st in cl.scheduler.slices.items()
                     if sid != vip_slice)
        for ch in list(other.topo.chips)[:2]:
            other.used_millichips[ch.coord] = MILLICHIPS_PER_CHIP
        req = GangRequest("default/big", num_pods=1, chips_per_pod=4)
        assert cl.scheduler._plan_migration(req, priority=0) is None
        assert cl.scheduler._plan_migration(req, priority=10) \
            == ["default/vip"]
        cl.close()

    def test_migrant_keeps_queue_seniority(self):
        """Review regression: a migrated gang must not lose its FIFO
        position — a later-submitted equal-priority pod must not steal
        the home the migration plan proved for it."""
        cl = SimCluster(["v5e-16"])
        survivors = self._fragment_v5e16(cl)
        cl.submit(*[
            tpu_pod(f"big-{i}", chips=4,
                    gang=GangSpec(name="big", size=2, index=i),
                    command=["x"])
            for i in range(2)
        ])
        # a later rival wanting the same 4-chip block the mover needs
        cl.submit(tpu_pod("rival", chips=4, command=["x"]))
        result, _ = cl.step()
        assert {"big-0", "big-1"} <= set(result.scheduled)
        moved = [n for n in survivors
                 if cl.pod_phase(n) == PodPhase.PENDING]
        assert len(moved) == 1
        # next pass: the MOVER (senior) gets the freed block, not rival
        result, _ = cl.step()
        assert moved[0] in result.scheduled
        assert cl.pod_phase("rival") == PodPhase.PENDING
        cl.close()


class TestMigrationDebtPersistence:
    def test_debt_survives_scheduler_restart(self):
        """Advisor r1 regression: a scheduler restart between
        migration-eviction and re-placement must not drop the mover's
        home reservation — the debt persists as a pod annotation and
        rebuilds in sync(), so an equal-priority backfiller submitted
        after the restart cannot take the freed block."""
        cl = SimCluster(["v5e-16"])
        survivors = TestMigration()._fragment_v5e16(cl)
        cl.submit(*[
            tpu_pod(f"big-{i}", chips=4,
                    gang=GangSpec(name="big", size=2, index=i),
                    command=["x"])
            for i in range(2)
        ])
        result, _ = cl.step()
        assert {"big-0", "big-1"} <= set(result.scheduled)
        moved = [n for n in survivors
                 if cl.pod_phase(n) == PodPhase.PENDING]
        assert len(moved) == 1
        # restart: rebuild ALL scheduler state from annotation truth
        assert cl.scheduler._migration_debts   # in-memory before
        cl.scheduler.sync()
        assert list(cl.scheduler._migration_debts) == [
            f"default/{moved[0]}"]
        # an equal-priority 4-chip single arrives AFTER the restart; the
        # mover still wins its reserved home (queue seniority + debt)
        cl.submit(tpu_pod("thief", chips=4, command=["x"]))
        result, _ = cl.step()
        assert moved[0] in result.scheduled
        # debt repaid: annotation cleared, registry empty
        assert not cl.scheduler._migration_debts
        pod = cl.api.get("Pod", moved[0])
        from kubegpu_tpu.kubemeta.codec import MIGRATION_DEBT_KEY
        assert MIGRATION_DEBT_KEY not in pod.metadata.annotations
        cl.close()

"""Pipeline parallelism (GPipe over the ``pp`` axis) on the 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from kubegpu_tpu.models.llama import (
    LlamaConfig, llama_init, next_token_loss,
)
from kubegpu_tpu.parallel import make_mesh, make_pp_loss, make_pp_train_step
from kubegpu_tpu.parallel.pipeline import llama_pp_param_specs
from kubegpu_tpu.parallel.sharding import fit_spec, named_sharding_tree


def _setup(mesh, cfg, batch=8, seq=32, seed=0):
    params = llama_init(jax.random.PRNGKey(0), cfg)
    tokens = (np.random.RandomState(seed)
              .randint(0, cfg.vocab_size, (batch, seq + 1))
              .astype(np.int32))
    specs = named_sharding_tree(mesh, llama_pp_param_specs(cfg))
    p_sh = jax.device_put(params, specs)
    tok = jax.device_put(
        jnp.asarray(tokens),
        NamedSharding(mesh, fit_spec(mesh, P("dp", None))))
    return params, tokens, p_sh, tok


class TestPipelineLoss:
    def test_matches_reference_dp_pp_tp(self):
        """dp2 × pp2 × tp2: pipelined loss == plain next-token loss."""
        cfg = LlamaConfig.tiny(n_layers=4, n_heads=4, n_kv_heads=4)
        mesh = make_mesh({"dp": 2, "pp": 2, "tp": 2})
        params, tokens, p_sh, tok = _setup(mesh, cfg)
        ref = float(next_token_loss(params, jnp.asarray(tokens), cfg))
        got = float(jax.jit(make_pp_loss(cfg, mesh, 2))(p_sh, tok))
        assert got == pytest.approx(ref, abs=1e-5)

    def test_grads_match_reference(self):
        cfg = LlamaConfig.tiny(n_layers=4, n_heads=4, n_kv_heads=4)
        mesh = make_mesh({"dp": 2, "pp": 2, "tp": 2})
        params, tokens, p_sh, tok = _setup(mesh, cfg)
        g = jax.jit(jax.grad(make_pp_loss(cfg, mesh, 2)))(p_sh, tok)
        gref = jax.grad(
            lambda p: next_token_loss(p, jnp.asarray(tokens), cfg))(params)
        err = max(jax.tree.leaves(jax.tree.map(
            lambda a, b: float(jnp.max(jnp.abs(a - b))), g, gref)))
        assert err < 1e-5

    def test_pp_only_deep_pipeline(self):
        """pp8: every device is a stage; still exact."""
        cfg = LlamaConfig.tiny(n_layers=8, n_heads=4, n_kv_heads=4)
        mesh = make_mesh({"dp": 1, "pp": 8, "tp": 1})
        params, tokens, p_sh, tok = _setup(mesh, cfg, batch=4)
        ref = float(next_token_loss(params, jnp.asarray(tokens), cfg))
        got = float(jax.jit(make_pp_loss(cfg, mesh, 4))(p_sh, tok))
        assert got == pytest.approx(ref, abs=1e-5)

    def test_degenerate_single_stage(self):
        cfg = LlamaConfig.tiny(n_layers=2, n_heads=4, n_kv_heads=4)
        mesh = make_mesh({"dp": 8, "pp": 1, "tp": 1})
        params, tokens, p_sh, tok = _setup(mesh, cfg)
        ref = float(next_token_loss(params, jnp.asarray(tokens), cfg))
        got = float(jax.jit(make_pp_loss(cfg, mesh, 1))(p_sh, tok))
        assert got == pytest.approx(ref, abs=1e-5)

    def test_gqa_with_tp(self):
        """kv heads < q heads, both tp-sharded."""
        cfg = LlamaConfig.tiny(n_layers=2, n_heads=4, n_kv_heads=2)
        mesh = make_mesh({"dp": 2, "pp": 2, "tp": 2})
        params, tokens, p_sh, tok = _setup(mesh, cfg)
        ref = float(next_token_loss(params, jnp.asarray(tokens), cfg))
        got = float(jax.jit(make_pp_loss(cfg, mesh, 2))(p_sh, tok))
        assert got == pytest.approx(ref, abs=1e-5)

    def test_layers_not_divisible_raises(self):
        cfg = LlamaConfig.tiny(n_layers=3)
        mesh = make_mesh({"dp": 2, "pp": 2, "tp": 2})
        with pytest.raises(ValueError, match="n_layers"):
            make_pp_loss(cfg, mesh, 2)

    def test_mesh_without_pp_axis_raises(self):
        cfg = LlamaConfig.tiny(n_layers=2)
        mesh = make_mesh({"dp": 8})
        with pytest.raises(ValueError, match="pp"):
            make_pp_loss(cfg, mesh, 2)


class TestPipelineTrainStep:
    def test_loss_decreases(self):
        cfg = LlamaConfig.tiny(n_layers=4, n_heads=4, n_kv_heads=4)
        mesh = make_mesh({"dp": 2, "pp": 2, "tp": 2})
        _, _, p_sh, tok = _setup(mesh, cfg)
        opt = optax.adamw(3e-3)
        step = jax.jit(make_pp_train_step(cfg, opt, mesh, 2),
                       donate_argnums=(0, 1))
        opt_state = opt.init(p_sh)
        losses = []
        params = p_sh
        for _ in range(4):
            params, opt_state, loss = step(params, opt_state, tok)
            losses.append(float(loss))
        assert losses[-1] < losses[0]
        assert all(np.isfinite(l) for l in losses)

"""Example workloads — reference: ``example/`` pod specs (SURVEY.md §3).

``programs/`` are real JAX programs launched by the (simulated) runtime
with the injected TPU env; ``specs.py`` builds the five BASELINE.json
acceptance-config pod/gang specs that exercise the full stack.
"""

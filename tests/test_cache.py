"""WatchCachedApiClient — the scheduler's reflector (kubemeta/cache.py).

The consistency rules under test are the ones the wire deployment
depends on: reads served locally (zero HTTP per run_once), writes
visible to the very next read (read-your-writes), stale watch echoes
never rolling back local state, and reset ⇒ relist rebuilding."""

import time

from kubegpu_tpu.cluster import tpu_pod
from kubegpu_tpu.kubemeta import FakeApiServer, PodPhase
from kubegpu_tpu.kubemeta.apiserver_http import ApiServerHTTP, HttpApiClient
from kubegpu_tpu.kubemeta.cache import WatchCachedApiClient


class CountingApi(FakeApiServer):
    """FakeApiServer that counts list() calls (the reads the cache must
    absorb)."""

    def __init__(self):
        super().__init__()
        self.list_calls = 0

    def list(self, *a, **kw):
        self.list_calls += 1
        return super().list(*a, **kw)


class TestCacheReads:
    def test_reads_served_locally_after_seed(self):
        api = CountingApi()
        api.create("Pod", tpu_pod("a", chips=1, command=["x"]))
        cache = WatchCachedApiClient(api)
        seeded = api.list_calls          # the 3 seed lists
        assert [p.name for p in cache.list("Pod")] == ["a"]
        cache.get("Pod", "a")
        cache.list("Pod", phase=PodPhase.PENDING)
        assert api.list_calls == seeded, "reads leaked to the inner api"

    def test_watch_events_update_store(self):
        api = FakeApiServer()
        cache = WatchCachedApiClient(api)
        api.create("Pod", tpu_pod("late", chips=1, command=["x"]))
        assert [p.name for p in cache.list("Pod")] == ["late"]
        api.delete("Pod", "late")
        assert cache.list("Pod") == []

    def test_field_selector_parity_with_server(self):
        api = FakeApiServer()
        cache = WatchCachedApiClient(api)
        api.create("Pod", tpu_pod("p1", chips=1, command=["x"]))
        api.create("Pod", tpu_pod("p2", chips=1, command=["x"]))
        api.bind_pod("p1", "node-a")
        for kw in ({"phase": PodPhase.PENDING},
                   {"node_name": "node-a"},
                   {"phase": (PodPhase.PENDING, PodPhase.SCHEDULED)}):
            want = sorted(p.name for p in api.list("Pod", **kw))
            got = sorted(p.name for p in cache.list("Pod", **kw))
            assert got == want, kw

    def test_list_returns_clones(self):
        api = FakeApiServer()
        cache = WatchCachedApiClient(api)
        api.create("Pod", tpu_pod("p", chips=1, command=["x"]))
        cache.list("Pod")[0].metadata.annotations["mut"] = "ated"
        assert "mut" not in cache.list("Pod")[0].metadata.annotations


class TestCacheWrites:
    def test_read_your_writes_bind(self):
        """A bind through the cache is visible to the next local read
        even before the watch echo lands — the property that keeps a
        bound pod out of the scheduler's next PENDING scan."""
        api = FakeApiServer()
        cache = WatchCachedApiClient(api)
        cache.create("Pod", tpu_pod("p", chips=1, command=["x"]))
        cache.bind_pod("p", "node-a")
        got = cache.get("Pod", "p")
        assert got.spec.node_name == "node-a"
        assert got.status.phase == PodPhase.SCHEDULED
        assert cache.list("Pod", phase=PodPhase.PENDING) == []

    def test_read_your_writes_patch(self):
        api = FakeApiServer()
        cache = WatchCachedApiClient(api)
        cache.create("Pod", tpu_pod("p", chips=1, command=["x"]))
        cache.patch_annotations("Pod", "p", {"k": "v"})
        assert cache.get("Pod", "p").metadata.annotations["k"] == "v"

    def test_stale_echo_cannot_roll_back(self):
        """An event carrying an rv <= the cached one must be a no-op:
        the pre-write clone of our own write's echo must not undo a
        newer local write-through."""
        from kubegpu_tpu.kubemeta.controlplane import WatchEvent
        api = FakeApiServer()
        cache = WatchCachedApiClient(api)
        cache.create("Pod", tpu_pod("p", chips=1, command=["x"]))
        before = api.get("Pod", "p")        # clone at creation rv
        cache.patch_annotations("Pod", "p", {"k": "v"})
        # replay the pre-patch clone as if the watch delivered it late
        cache._on_event(WatchEvent("Pod", "MODIFIED", before))
        assert cache.get("Pod", "p").metadata.annotations.get("k") == "v"

    def test_relist_keeps_newer_writethrough(self):
        """_relist (reset recovery) must not clobber an entry whose
        write-through postdates the list snapshot."""
        api = FakeApiServer()
        cache = WatchCachedApiClient(api)
        cache.create("Pod", tpu_pod("p", chips=1, command=["x"]))
        stale_list = {o.metadata.namespace + "/" + o.metadata.name: o
                      for o in api.list("Pod")}
        cache.patch_annotations("Pod", "p", {"k": "v"})

        def stale(kind, *a, **kw):
            return list(stale_list.values()) if kind == "Pod" else []
        cache.inner = type("I", (), {"list": staticmethod(stale)})()
        try:
            cache._relist()
        finally:
            cache.inner = api
        assert cache.get("Pod", "p").metadata.annotations.get("k") == "v"


class TestCacheUnderChurn:
    def test_concurrent_writers_converge(self):
        """Stress: several threads churn pods (create/patch/bind/phase/
        delete) — some through the cache, some directly against the
        server (events-only visibility) — while readers hammer list().
        After quiescence the cache must be EXACTLY the server state:
        no ghosts (tombstone bugs), no losses (rollback bugs), no stale
        rows (rv-guard bugs)."""
        import threading

        from kubegpu_tpu.kubemeta import Conflict, NotFound

        api = FakeApiServer()
        cache = WatchCachedApiClient(api)
        n_threads, n_ops = 4, 120
        errs: list[Exception] = []

        def churn(tid: int, via_cache: bool):
            client = cache if via_cache else api
            # SHARED name pool (no tid): cache-side and direct-server
            # threads must contend on the same objects, or the
            # tombstone/recreate defenses in cache.delete are
            # structurally unreachable
            names = [f"p{(i + tid) % 7}" for i in range(n_ops)]
            try:
                for i, name in enumerate(names):
                    op = (i + tid) % 5
                    try:
                        if op == 0:
                            client.create("Pod", tpu_pod(
                                name, chips=1, command=["x"]))
                        elif op == 1:
                            client.patch_annotations(
                                "Pod", name, {"i": str(i)})
                        elif op == 2:
                            client.bind_pod(name, f"node-{tid}")
                        elif op == 3:
                            client.set_pod_phase(name, PodPhase.RUNNING)
                        else:
                            client.delete("Pod", name)
                    except (NotFound, Conflict):
                        pass   # expected inter-thread races
                    if i % 10 == 0:
                        cache.list("Pod", phase=PodPhase.PENDING)
            except Exception as e:  # pragma: no cover - failure path
                errs.append(e)

        threads = [threading.Thread(target=churn, args=(t, t % 2 == 0))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs
        # Quiesce: FakeApiServer._drain can strand an event when two
        # threads race the delivery lock at shutdown; one
        # single-threaded mutation now drains anything left (and its
        # own events deliver synchronously with no competing drainer).
        api.create("Pod", tpu_pod("flush", chips=0, command=["x"]))
        api.delete("Pod", "flush")
        want = {(p.metadata.namespace, p.name,
                 p.metadata.resource_version, p.status.phase,
                 p.spec.node_name)
                for p in api.list("Pod")}
        got = {(p.metadata.namespace, p.name,
                p.metadata.resource_version, p.status.phase,
                p.spec.node_name)
               for p in cache.list("Pod")}
        assert got == want
        assert not any(cache._tombstones.values()), "leaked tombstones"


class TestCacheOverHttp:
    def test_scheduler_reads_zero_http_lists(self):
        """DeviceScheduler over cache-over-HttpApiClient: after seeding,
        a full schedule pass issues NO HTTP list requests — the wire
        property VERDICT r2 missing-#1 demanded."""
        from kubegpu_tpu.crishim.agent import NodeAgent
        from kubegpu_tpu.crishim.runtime import FakeRuntime
        from kubegpu_tpu.scheduler import DeviceScheduler
        from kubegpu_tpu.tpuplugin import MockBackend

        api = FakeApiServer()
        srv = ApiServerHTTP(api).start()
        client = HttpApiClient(srv.address)
        try:
            backend = MockBackend("v4-8")
            agent = NodeAgent(api, backend, FakeRuntime())
            agent.register()

            cache = WatchCachedApiClient(client)
            calls = {"list": 0}
            real_call = client._call

            def counting_call(method, path, *a, **kw):
                if method == "GET" and path.startswith("/apis/") \
                        and "/" not in path[len("/apis/"):]:
                    calls["list"] += 1
                return real_call(method, path, *a, **kw)
            client._call = counting_call

            sched = DeviceScheduler(cache)
            after_init = calls["list"]
            api.create("Pod", tpu_pod("job", chips=1, command=["x"]))
            # Retry run_once until the watch has delivered everything the
            # pass needs (ADVICE r3: asserting after a single pass raced
            # watch delivery of related state under multi-file load).
            deadline = time.monotonic() + 5
            res = sched.run_once()
            while not res.scheduled and time.monotonic() < deadline:
                time.sleep(0.02)
                res = sched.run_once()
            assert res.scheduled == ["job"]
            assert calls["list"] == after_init, \
                "run_once issued HTTP list calls despite the cache"
            # the bind crossed the wire: the server saw it
            assert api.get("Pod", "job").status.phase == PodPhase.SCHEDULED
        finally:
            cache.close()
            client.close()
            srv.close()

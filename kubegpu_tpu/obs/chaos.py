"""Deterministic fault injection for the serving stack (ISSUE 4).

The training side already treats hardware loss as routine (the health
controller evicts and re-places whole gangs); this module gives the
SERVING stack the same discipline by making failures reproducible: a
:class:`ChaosInjector` is a seeded schedule of :class:`ChaosEvent`\\ s
that an engine consults at every tick boundary.  Four fault kinds cover
the failure modes production TPU serving actually sees:

- ``kill_replica`` — the whole engine dies mid-tick (host preemption,
  slice revocation).  The engine raises :class:`ReplicaDeadError`;
  :class:`~kubegpu_tpu.models.serve.DataParallelServePool` catches it
  and re-admits every resident request onto healthy replicas via
  prefix-cache-accelerated replay.
- ``fail_dispatch`` — ONE dispatch fails transiently
  (:class:`DispatchFailure`); the engine retries it in place (the
  dispatch is functional, so a retry re-runs identical math) and only
  escalates to replica death after repeated failures.
- ``nan_logits`` — a slot's pool pages are poisoned with NaN, so that
  slot's logits go non-finite while its neighbors stay exact (slots
  are independent batch rows).  The engine's per-tick invalid-logit
  detector quarantines the slot and replays its request instead of
  letting the poison ride the batch.
- ``stall_tick`` — the tick sleeps past the engine's watchdog deadline
  (``tick_deadline_s``); the watchdog declares the replica stalled
  (:class:`TickStallError`, a :class:`ReplicaDeadError`) and the pool
  fails over exactly as for a kill.

Determinism contract: an injector is a pure function of its events (or
of ``from_seed``'s arguments), and every downstream recovery action is
greedy-replay bit-exact — so a chaos run must emit EXACTLY the
fault-free run's tokens, which is what ``tests/test_serve_chaos.py``
and the ``cb_chaos`` bench row assert.

EVENT TABLE (ISSUE 19) — the ONE registry both injectors draw from;
the README chaos section mirrors this table verbatim:

====================  =======  ==========================================
kind                  scope    effect
====================  =======  ==========================================
``kill_replica``      engine   whole engine dies mid-tick
``fail_dispatch``     engine   one dispatch fails transiently, retried
``nan_logits``        engine   one slot's logits poisoned, quarantined
``stall_tick``        engine   tick sleeps past the watchdog deadline
``kill_domain``       domain   every replica in one failure domain
                               (slice/rack/zone) dies in the SAME tick;
                               watch evictions for the gangs are also
                               emitted (late/dup deliveries must no-op)
``evict_domain``      domain   control-plane eviction of a domain's
                               gangs, visible ONLY via the health watch
                               — a delayed delivery is a stale-read
                               window where routing still targets them
``watch_delay``       watch    deliveries issued in the window arrive
                               ``delay_ticks`` late
``watch_dup``         watch    each delivery in the window arrives
                               ``dup`` times
``watch_reorder``     watch    deliveries due the same tick flush in
                               reverse issue order
``watch_partition``   watch    the watch stream partitions: deliveries
                               buffer for ``duration_ticks`` (stale
                               reads), then flush on heal
====================  =======  ==========================================

Scopes: *engine* events are consumed by ``ContinuousBatcher`` (and the
fleet harness's simulated engines) at tick boundaries via
:class:`ChaosInjector`; *domain* and *watch* events are consumed by the
fleet harness's watch channel via :class:`DomainChaosInjector`.  Both
injectors share the determinism contract above: same seed ⇒ same
schedule ⇒ same recovery sequence, with per-request outcomes bit-exact
against a fault-free twin.
"""

from __future__ import annotations

from dataclasses import dataclass, field


class ChaosError(RuntimeError):
    """Base class for injected serving faults."""


class ReplicaDeadError(ChaosError):
    """The engine is dead (killed, or declared dead by its watchdog);
    every subsequent ``step()`` re-raises.  The pool's failover path
    catches this, harvests the engine's host-side request state, and
    replays survivors on healthy replicas."""


class TickStallError(ReplicaDeadError):
    """Watchdog verdict: a tick exceeded ``tick_deadline_s``.  A
    subclass of :class:`ReplicaDeadError` because the recovery policy
    is identical — a replica that can stall once can wedge ``drain()``
    forever, so the pool fails over rather than waiting."""


class DispatchFailure(ChaosError):
    """A single dispatch failed transiently; the engine retries the
    same dispatch (safe: dispatches are functional) with a bounded
    budget before escalating to replica death."""


KILL = "kill_replica"
FAIL_DISPATCH = "fail_dispatch"
NAN_LOGITS = "nan_logits"
STALL = "stall_tick"
KINDS = (KILL, FAIL_DISPATCH, NAN_LOGITS, STALL)

# -- failure-domain / watch-channel kinds (ISSUE 19) --------------------
DOMAIN_KILL = "kill_domain"
DOMAIN_EVICT = "evict_domain"
WATCH_DELAY = "watch_delay"
WATCH_DUP = "watch_dup"
WATCH_REORDER = "watch_reorder"
WATCH_PARTITION = "watch_partition"
DOMAIN_KINDS = (DOMAIN_KILL, DOMAIN_EVICT)
WATCH_KINDS = (WATCH_DELAY, WATCH_DUP, WATCH_REORDER, WATCH_PARTITION)

#: the shared event registry (kind → scope) both injectors validate
#: against — the docstring table and the README chaos section mirror it
EVENT_TABLE = {
    KILL: "engine", FAIL_DISPATCH: "engine",
    NAN_LOGITS: "engine", STALL: "engine",
    DOMAIN_KILL: "domain", DOMAIN_EVICT: "domain",
    WATCH_DELAY: "watch", WATCH_DUP: "watch",
    WATCH_REORDER: "watch", WATCH_PARTITION: "watch",
}


@dataclass(frozen=True)
class ChaosEvent:
    tick: int            # engine tick (dispatch counter) to fire at
    kind: str            # one of KINDS
    stall_s: float = 0.0  # sleep injected for STALL events


@dataclass
class ChaosInjector:
    """Seeded, replayable fault schedule for ONE engine.

    ``take(tick)`` pops every event due at or before ``tick`` (events
    fire once); ``defer(ev, tick)`` re-queues an event the engine could
    not apply yet (e.g. a NaN injection with no eligible slot).  The
    ``fired`` log is the audit trail the bench row reports."""

    events: list = field(default_factory=list)
    fired: list = field(default_factory=list)

    def __post_init__(self) -> None:
        for ev in self.events:
            if ev.kind not in KINDS:
                raise ValueError(f"unknown chaos kind {ev.kind!r}")
        self.events = sorted(self.events, key=lambda e: e.tick)

    @classmethod
    def from_seed(cls, seed: int, ticks: int,
                  kinds: tuple = KINDS,
                  n_events: int = 1,
                  stall_s: float = 0.0) -> "ChaosInjector":
        """Draw ``n_events`` events uniformly over ``[1, ticks]`` from a
        seeded generator — the scenario-matrix entry point (same seed ⇒
        same schedule ⇒ same recovery sequence)."""
        import numpy as np
        rng = np.random.default_rng(seed)
        evs = [ChaosEvent(tick=int(rng.integers(1, max(ticks, 2))),
                          kind=str(rng.choice(list(kinds))),
                          stall_s=stall_s)
               for _ in range(n_events)]
        return cls(events=evs)

    def take(self, tick: int) -> list:
        due = [e for e in self.events if e.tick <= tick]
        if due:
            self.events = [e for e in self.events if e.tick > tick]
            self.fired.extend(due)
        return due

    def defer(self, ev: ChaosEvent, tick: int) -> None:
        self.fired.remove(ev)
        self.events.append(ChaosEvent(tick=tick, kind=ev.kind,
                                      stall_s=ev.stall_s))
        self.events.sort(key=lambda e: e.tick)


@dataclass(frozen=True)
class DomainChaosEvent:
    """One correlated fault: a whole failure domain (slice/rack/zone)
    or the health-watch channel itself, at a fleet tick."""
    tick: int                 # fleet tick to fire at
    kind: str                 # one of DOMAIN_KINDS + WATCH_KINDS
    domain: str | None = None  # target domain (domain-scope kinds)
    delay_ticks: int = 0      # WATCH_DELAY: per-delivery lateness
    duration_ticks: int = 0   # window length for watch-scope kinds
    dup: int = 1              # WATCH_DUP: copies per delivery


@dataclass
class DomainChaosInjector:
    """Seeded, replayable CORRELATED-fault schedule for a fleet — the
    topology-aware sibling of :class:`ChaosInjector` (same event table,
    same determinism contract, domain/watch scope instead of engine
    scope).  ``take(tick)`` pops every event due at or before ``tick``;
    the fleet harness turns domain events into simultaneous replica
    deaths / gang evictions and watch events into delivery-channel
    weather (delay, duplication, reorder, partition)."""

    events: list = field(default_factory=list)
    fired: list = field(default_factory=list)

    def __post_init__(self) -> None:
        for ev in self.events:
            if ev.kind not in EVENT_TABLE:
                raise ValueError(f"unknown chaos kind {ev.kind!r}")
            if EVENT_TABLE[ev.kind] == "engine":
                raise ValueError(
                    f"{ev.kind!r} is engine-scope — schedule it on a "
                    f"per-replica ChaosInjector, not the domain one")
            if EVENT_TABLE[ev.kind] == "domain" and ev.domain is None:
                raise ValueError(f"{ev.kind!r} needs a target domain")
        self.events = sorted(self.events, key=lambda e: e.tick)

    @classmethod
    def from_seed(cls, seed: int, ticks: int, domains: tuple,
                  kinds: tuple = DOMAIN_KINDS + WATCH_KINDS,
                  n_events: int = 1,
                  delay_ticks: int = 2,
                  duration_ticks: int = 4,
                  dup: int = 2) -> "DomainChaosInjector":
        """Draw ``n_events`` correlated faults uniformly over
        ``[1, ticks]`` and uniformly over ``domains`` — a pure function
        of its arguments, exactly like :meth:`ChaosInjector.from_seed`."""
        import numpy as np
        rng = np.random.default_rng(seed)
        evs = []
        for _ in range(n_events):
            kind = str(rng.choice(list(kinds)))
            dom = (str(rng.choice(list(domains)))
                   if EVENT_TABLE[kind] == "domain" else None)
            evs.append(DomainChaosEvent(
                tick=int(rng.integers(1, max(ticks, 2))), kind=kind,
                domain=dom, delay_ticks=delay_ticks,
                duration_ticks=duration_ticks, dup=dup))
        return cls(events=evs)

    def take(self, tick: int) -> list:
        due = [e for e in self.events if e.tick <= tick]
        if due:
            self.events = [e for e in self.events if e.tick > tick]
            self.fired.extend(due)
        return due

"""Slice algebra: enumerate contiguous sub-tori and find free placements.

This is the TPU-native analogue of the reference's grouped-resource-tree
matching (SURVEY.md §3 ``grpalloc.PodFitsGroupConstraints``): where the
reference searched a hierarchy for a feasible group assignment, KubeTPU
searches the torus for a free contiguous sub-slice of the requested shape.
The hot-path version of this search lives in the C++ allocator core
(``kubegpu_tpu/allocator/csrc``); this module is the reference
implementation and the shape/placement vocabulary shared with it.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass

from kubegpu_tpu.topology.mesh import Coord, TpuTopology


@dataclass(frozen=True)
class Placement:
    """A concrete contiguous sub-slice: origin + shape → set of coords.

    ``coords`` are in row-major order of the *local* offset (z fastest),
    which downstream code relies on for deterministic worker ordering.
    """

    origin: Coord
    shape: Coord
    coords: tuple[Coord, ...]

    @property
    def num_chips(self) -> int:
        # len(coords), not the shape product: connected-set (non-
        # rectangular) placements carry a degenerate shape.
        return len(self.coords)


def subslice_shapes(n: int, mesh_shape: Coord) -> list[Coord]:
    """All (a,b,c) factorizations of ``n`` that fit inside ``mesh_shape``.

    Ordered best-first for ICI locality: prefer compact (near-cubical /
    near-square) shapes over skinny ones, since compact sub-tori minimize
    the surface area collectives must cross.  Mirrors how TPU pod
    allocators enumerate candidate slice shapes.
    """
    mx, my, mz = mesh_shape
    shapes: list[Coord] = []
    for a in range(1, min(n, mx) + 1):
        if n % a:
            continue
        rest = n // a
        for b in range(1, min(rest, my) + 1):
            if rest % b:
                continue
            c = rest // b
            if c <= mz:
                shapes.append((a, b, c))
    # Compactness = low max-dimension, then low surface-to-volume.
    def badness(s: Coord) -> tuple:
        a, b, c = s
        surface = a * b + b * c + a * c
        return (max(s), surface, s)
    return sorted(shapes, key=badness)


def _axis_origins(dim: int, size: int, wrap: bool) -> range:
    if wrap and dim > 2 and size < dim:
        return range(dim)  # wrapped placements are legal on a torus axis
    return range(dim - size + 1)


def enumerate_placements(topo: TpuTopology, shape: Coord) -> list[Placement]:
    """Every contiguous placement of ``shape`` within the topology.

    On wrapped axes, placements may wrap around; coordinates are reduced
    modulo the axis dimension.  Duplicate coord-sets that arise from full-
    axis spans are canonicalized away.
    """
    mx, my, mz = topo.spec.mesh_shape
    sx, sy, sz = shape
    if sx > mx or sy > my or sz > mz:
        return []
    out: list[Placement] = []
    seen: set[frozenset[Coord]] = set()
    wraps = topo.spec.wrap
    for ox in _axis_origins(mx, sx, wraps[0]):
        for oy in _axis_origins(my, sy, wraps[1]):
            for oz in _axis_origins(mz, sz, wraps[2]):
                coords = tuple(
                    ((ox + dx) % mx, (oy + dy) % my, (oz + dz) % mz)
                    for dx in range(sx)
                    for dy in range(sy)
                    for dz in range(sz)
                )
                key = frozenset(coords)
                if key in seen:
                    continue
                seen.add(key)
                out.append(Placement(origin=(ox, oy, oz), shape=shape,
                                     coords=coords))
    return out


def find_free_placements(
    topo: TpuTopology,
    occupied: set[Coord],
    shape: Coord,
    limit: int | None = None,
    mask=None,
) -> list[Placement]:
    """Free contiguous placements of ``shape`` given an occupancy set.

    This is the feasibility predicate behind the scheduler's ``/filter``
    verb (SURVEY.md §4.2).  ``limit`` caps the returned candidates so the
    prioritize step scores a bounded set.  ``mask`` is an optional
    prebuilt :func:`_native.occupancy_mask` for ``occupied`` (callers
    scanning many shapes against one occupancy build it once).
    """
    from kubegpu_tpu.allocator import _native

    native = _native.find_free_placements_native(topo, occupied, shape,
                                                 limit, mask=mask)
    if native is not None:
        return native
    out: list[Placement] = []
    for p in enumerate_placements(topo, shape):
        if not any(c in occupied for c in p.coords):
            out.append(p)
            if limit is not None and len(out) >= limit:
                break
    return out


def host_aligned(topo: TpuTopology, placement: Placement) -> bool:
    """True if the placement is a union of whole host blocks.

    Multi-host gangs want host-aligned slices so each pod maps to exactly
    one host's chips (TPU_WORKER_ID per host — SURVEY.md §8).
    """
    by_host: dict[int, int] = {}
    for c in placement.coords:
        hid = topo.chip_at(c).host_id
        by_host[hid] = by_host.get(hid, 0) + 1
    cph = topo.spec.chips_per_host
    return all(n == cph for n in by_host.values())


def partition_by_host(
    topo: TpuTopology, placement: Placement
) -> list[tuple[int, list[Coord]]]:
    """Group a placement's coords by owning host, ordered by host id.

    The ordering defines gang-member → host assignment and hence
    TPU_WORKER_ID: host order must match mesh-coordinate order or pjit
    layouts silently degrade (SURVEY.md §8 "Worker identity wiring").
    """
    by_host: dict[int, list[Coord]] = {}
    for c in placement.coords:
        by_host.setdefault(topo.chip_at(c).host_id, []).append(c)
    return sorted(by_host.items(), key=lambda kv: kv[0])


def fragmentation_score(topo: TpuTopology, occupied: set[Coord],
                        placement: Placement) -> float:
    """Packing heuristic: prefer placements hugging walls/occupied chips.

    Returns the fraction of the placement's *boundary* (neighbor slots
    outside the placement) that is either off-mesh or already occupied —
    higher means tighter packing, leaving larger free blocks for future
    gangs (the bin-packing pressure case, BASELINE config 5).
    """
    from kubegpu_tpu.allocator import _native

    native = _native.fragmentation_score_native(
        topo, occupied, placement.coords)
    if native is not None:
        return native
    return _fragmentation_score_py(topo, occupied, placement)


def fragmentation_scorer(topo: TpuTopology, occupied: set[Coord],
                         mask=None):
    """``placement -> score`` closure for scoring MANY placements
    against ONE occupancy set: the native path builds its O(chips)
    occupancy mask once instead of per call — the allocator's per-shape
    ranking loop scores every free placement, and the repeated mask
    build dominated 1024-chip decision times."""
    from kubegpu_tpu.allocator import _native

    native = _native.frag_scorer_native(topo, occupied, mask=mask)
    if native is not None:
        return lambda placement: native(placement.coords)
    return lambda placement: _fragmentation_score_py(
        topo, occupied, placement)


def _fragmentation_score_py(topo: TpuTopology, occupied: set[Coord],
                            placement: Placement) -> float:
    pset = set(placement.coords)
    boundary = 0
    blocked = 0
    for c in placement.coords:
        x, y, z = c
        for axis in range(3):
            dim = topo.spec.mesh_shape[axis]
            for delta in (-1, 1):
                n = list(c)
                n[axis] += delta
                if not (0 <= n[axis] < dim):
                    if topo.spec.wrap[axis] and dim > 2:
                        n[axis] %= dim
                    else:
                        boundary += 1
                        blocked += 1  # mesh wall: counts as packed-against
                        continue
                nc = (n[0], n[1], n[2])
                if nc in pset:
                    continue
                boundary += 1
                if nc in occupied:
                    blocked += 1
    return blocked / boundary if boundary else 1.0

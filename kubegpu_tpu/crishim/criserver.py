"""CRI-shaped wire boundary for the shim — reference: SURVEY.md §4.3.

The reference's crishim was a real gRPC server implementing the kubelet
CRI (``RuntimeService``/``ImageService``) on a unix socket; kubelet
never called the shim in-process.  This module restores that transport
seam in the simulated stack: a :class:`CriServer` listens on a unix
socket speaking length-prefixed JSON frames whose method names and
message shapes mirror the CRI RuntimeService (``Version``,
``CreateContainer``, ``StartContainer``, ``ContainerStatus``,
``StopContainer``, ``RemoveContainer``, ``ListContainers``) AND the
ImageService half (``PullImage``, ``ImageStatus``, ``ListImages``,
``RemoveImage``, ``ImageFsInfo``) on the same socket — the deployment
shape kubelet expects (one endpoint serving both services).  The image
store is per-node and passthrough-shaped: a pull registers the ref
under a deterministic digest (workload "images" here are the runtime
environment, not layer tarballs), and ``CreateContainer`` enforces
kubelet's pull-serialize contract — creating with an unpulled image is
an error, exactly as a real runtime reports ``image not found``.  A
:class:`RemoteCriShim` client gives
:class:`~kubegpu_tpu.crishim.agent.NodeAgent` the same
``create_container(pod) -> handle`` seam it has with the in-process
:class:`~kubegpu_tpu.crishim.shim.CriShim` — except every call
traverses the socket (pull → create → start), exactly as
kubelet→crishim did.

Wire format: 4-byte big-endian length prefix, then a UTF-8 JSON object
``{"method": str, "request": {...}}``; response frames are
``{"response": {...}}`` or ``{"error": str}``.  Connections are
persistent (many frames per connection), one server per node, mirroring
the one-crishim-per-node deployment of the reference.

Pod identity rides on the CRI container-config labels
(``io.kubernetes.pod.name`` / ``.namespace`` / ``.uid``) — the server
re-reads the Pod from the apiserver and verifies the uid, so a stale
kubelet asking for a dead incarnation gets an error instead of a
container wired to another pod's allocation (the same incarnation rule
the NodeAgent enforces in ``reconcile``).
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import socketserver
import struct
import tempfile
import threading
import time
import uuid

from kubegpu_tpu.crishim.runtime import ContainerHandle, ContainerRuntime
from kubegpu_tpu.crishim.shim import CriShim
from kubegpu_tpu.kubemeta import FakeApiServer, NotFound, Pod
from kubegpu_tpu.obs import get_logger
from kubegpu_tpu.tpuplugin.backend import DeviceBackend

log = get_logger("criserver")

RUNTIME_NAME = "kubetpu-crishim"
RUNTIME_API_VERSION = "v1"

# CRI ContainerState names (subset this runtime model can be in)
CONTAINER_RUNNING = "CONTAINER_RUNNING"
CONTAINER_EXITED = "CONTAINER_EXITED"

POD_NAME_LABEL = "io.kubernetes.pod.name"
POD_NAMESPACE_LABEL = "io.kubernetes.pod.namespace"
POD_UID_LABEL = "io.kubernetes.pod.uid"


class CriError(Exception):
    """Server-side verb failure carried back over the wire."""


# -- framing ------------------------------------------------------------

def send_frame(sock: socket.socket, obj: dict) -> None:
    body = json.dumps(obj).encode()
    sock.sendall(struct.pack(">I", len(body)) + body)


def recv_frame(sock: socket.socket) -> dict | None:
    """Read one frame; None on clean EOF at a frame boundary."""
    header = _recv_exact(sock, 4)
    if header is None:
        return None
    (n,) = struct.unpack(">I", header)
    body = _recv_exact(sock, n)
    if body is None:
        raise ConnectionError("socket closed mid-frame")
    return json.loads(body)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            if buf:
                raise ConnectionError("socket closed mid-frame")
            return None
        buf += chunk
    return buf


# -- server -------------------------------------------------------------

class CriVerbs:
    """The CRI verb core — RuntimeService + ImageService semantics for
    one node, transport-free.  :class:`CriServer` (length-prefixed JSON
    frames) and :class:`~kubegpu_tpu.crishim.grpcserver.GrpcCriServer`
    (real gRPC, the reference's actual transport — SURVEY.md §2 L2)
    both dispatch into this object, so the two wire formats can never
    diverge semantically."""

    def __init__(self, api: FakeApiServer, backend: DeviceBackend,
                 node_name: str, runtime: ContainerRuntime,
                 socket_path: str | None = None):
        self.api = api
        self.node_name = node_name
        self.runtime = runtime
        self.shim = CriShim(api, backend, node_name, runtime)
        self._tmpdir: str | None = None
        if socket_path is None:
            # unix socket paths cap at ~107 bytes; mkdtemp under /tmp stays
            # far below it regardless of the test runner's cwd
            self._tmpdir = tempfile.mkdtemp(prefix="kubetpu-cri-")
            socket_path = os.path.join(self._tmpdir, "cri.sock")
        self.socket_path = socket_path
        self._handles: dict[str, ContainerHandle] = {}
        # ImageService store: ref → image record (per-node, like a
        # node's containerd image store)
        self._images: dict[str, dict] = {}
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None

    # -- verbs ----------------------------------------------------------

    def _dispatch(self, method: str, request: dict) -> dict:
        handler = getattr(self, f"_verb_{method}", None)
        if handler is None:
            raise CriError(f"unknown method {method!r}")
        return handler(request)

    def _verb_Version(self, request: dict) -> dict:
        return {
            "runtime_name": RUNTIME_NAME,
            "runtime_api_version": RUNTIME_API_VERSION,
            "node_name": self.node_name,
        }

    def _verb_CreateContainer(self, request: dict) -> dict:
        config = request.get("config") or {}
        labels = config.get("labels") or {}
        pod_name = labels.get(POD_NAME_LABEL)
        namespace = labels.get(POD_NAMESPACE_LABEL, "default")
        uid = labels.get(POD_UID_LABEL)
        if not pod_name:
            raise CriError(f"config.labels missing {POD_NAME_LABEL}")
        # The reference's crishim fetched the pod (annotation) from the
        # apiserver at CreateContainer time — same here; the wire request
        # carries identity, not the allocation.
        try:
            pod: Pod = self.api.get("Pod", pod_name, namespace=namespace)
        except NotFound:
            raise CriError(f"pod {namespace}/{pod_name} not found") from None
        if uid and pod.metadata.uid != uid:
            raise CriError(
                f"pod {namespace}/{pod_name} uid mismatch: have "
                f"{pod.metadata.uid}, caller expects {uid} (stale incarnation)")
        container_name = (config.get("metadata") or {}).get("name")
        index = 0
        if container_name:
            names = [c.name for c in pod.spec.containers]
            if container_name not in names:
                raise CriError(
                    f"pod {pod_name} has no container {container_name!r}")
            index = names.index(container_name)
        # kubelet's pull-serialize contract: the image the container
        # will actually RUN (the pod spec's — what the shim consumes)
        # must be present before create; a differing client-supplied
        # config ref is a stale-manifest error, not a loophole
        ref = pod.spec.containers[index].image
        cfg_ref = (config.get("image") or {}).get("image")
        if cfg_ref and cfg_ref != ref:
            raise CriError(
                f"config image {cfg_ref!r} != pod spec image {ref!r}")
        with self._lock:
            present = ref in self._images
        if not present:
            raise CriError(
                f"image {ref!r} not present on node (PullImage first)")
        handle = self.shim.create_container(pod, container_index=index)
        container_id = uuid.uuid4().hex[:16]
        with self._lock:
            self._handles[container_id] = handle
        # info: CRI-style verbose map — the rewritten env, so callers
        # (and tests) can observe the injection without reaching into the
        # server process
        return {"container_id": container_id,
                "info": {"env": handle.env, "pid": handle.pid}}

    def _verb_StartContainer(self, request: dict) -> dict:
        # our runtimes launch at create time; the verb exists so callers
        # can speak the kubelet's create→start sequence unchanged
        self._handle_of(request)
        return {}

    def _verb_ContainerStatus(self, request: dict) -> dict:
        handle = self._handle_of(request)
        code = handle.wait(timeout=0.05)
        if code is None:
            state, info = CONTAINER_RUNNING, {}
        else:
            # exited: ship the collected output so the caller can harvest
            # workload metric lines (info mirrors CRI's verbose-info map)
            state = CONTAINER_EXITED
            info = {"stdout": handle.stdout, "stderr": handle.stderr}
        return {
            "status": {
                "id": request.get("container_id"),
                "metadata": {"name": handle.container_name},
                "state": state,
                "exit_code": code if code is not None else 0,
            },
            "info": info,
        }

    def _verb_StopContainer(self, request: dict) -> dict:
        self._handle_of(request).kill()
        return {}

    def _verb_RemoveContainer(self, request: dict) -> dict:
        cid = str(request.get("container_id") or "")
        with self._lock:
            handle = self._handles.pop(cid, None)
        if handle is not None and handle.exit_code is None:
            handle.kill()
        return {}

    def _verb_ListContainers(self, request: dict) -> dict:
        with self._lock:
            items = list(self._handles.items())
        out = []
        for cid, h in items:
            running = h.running()
            out.append({
                "id": cid,
                "metadata": {"name": h.container_name},
                "labels": {POD_NAME_LABEL: h.pod_name},
                "state": CONTAINER_RUNNING if running else CONTAINER_EXITED,
            })
        return {"containers": out}

    # -- ImageService verbs (same socket, kubelet's expected shape) ------

    @staticmethod
    def _image_ref(request: dict) -> str:
        ref = ((request.get("image") or {}).get("image") or "").strip()
        if not ref:
            raise CriError("empty image reference")
        return ref

    def _verb_PullImage(self, request: dict) -> dict:
        """Passthrough pull: register the ref under a deterministic
        digest.  Idempotent (a re-pull refreshes nothing — refs are
        content-stable here, as with tag-pinned digests)."""
        ref = self._image_ref(request)
        digest = "sha256:" + hashlib.sha256(ref.encode()).hexdigest()
        # strip an existing digest first ('app@sha256:…' keeps ':' in its
        # last path segment, which fooled the tag check — ADVICE r3), then
        # strip only a TAG (colon after the last '/'): a plain split(':')
        # would truncate registry-port refs like registry:5000/app:v1
        base = ref.split("@", 1)[0]
        repo = (base.rsplit(":", 1)[0]
                if ":" in base.rsplit("/", 1)[-1] else base)
        with self._lock:
            self._images.setdefault(ref, {
                "id": digest,
                "repo_tags": [ref],
                "repo_digests": [f"{repo}@{digest}"],
                # deterministic pseudo-size so ImageFsInfo sums move
                "size": int.from_bytes(
                    digest.encode()[7:11], "big") % (1 << 28),
                "pulled_at": time.time(),
            })
        log.info("pull_image", image=ref, node=self.node_name)
        return {"image_ref": digest}

    def _verb_ImageStatus(self, request: dict) -> dict:
        ref = self._image_ref(request)
        with self._lock:
            img = self._images.get(ref)
        if img is None:
            return {"image": None}   # CRI: absent image → null, not error
        return {"image": {k: img[k] for k in
                          ("id", "repo_tags", "repo_digests", "size")}}

    def _verb_ListImages(self, request: dict) -> dict:
        want = ((request.get("filter") or {}).get("image") or {}).get(
            "image")
        with self._lock:
            items = list(self._images.items())
        return {"images": [
            {k: img[k] for k in ("id", "repo_tags", "repo_digests",
                                 "size")}
            for ref, img in items if not want or ref == want]}

    def _verb_RemoveImage(self, request: dict) -> dict:
        ref = self._image_ref(request)
        with self._lock:
            self._images.pop(ref, None)   # CRI: remove is idempotent
        return {}

    def _verb_ImageFsInfo(self, request: dict) -> dict:
        with self._lock:
            used = sum(img["size"] for img in self._images.values())
            count = len(self._images)
        return {"image_filesystems": [{
            "timestamp": int(time.time() * 1e9),
            "fs_id": {"mountpoint": tempfile.gettempdir()},
            "used_bytes": {"value": used},
            "inodes_used": {"value": count},
        }]}

    def _handle_of(self, request: dict) -> ContainerHandle:
        cid = str(request.get("container_id") or "")
        with self._lock:
            handle = self._handles.get(cid)
        if handle is None:
            raise CriError(f"no such container {cid!r}")
        return handle

    def _cleanup_socket(self) -> None:
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass
        if self._tmpdir is not None:
            try:
                os.rmdir(self._tmpdir)
            except OSError:
                pass


class CriServer(CriVerbs):
    """RuntimeService-shaped server fronting the injection shim + the
    real runtime for one node, speaking length-prefixed JSON frames.
    ``start()`` binds the unix socket and serves in a daemon thread;
    ``close()`` shuts down and unlinks."""

    def __init__(self, api: FakeApiServer, backend: DeviceBackend,
                 node_name: str, runtime: ContainerRuntime,
                 socket_path: str | None = None):
        super().__init__(api, backend, node_name, runtime, socket_path)

        dispatch = self._dispatch

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                while True:
                    try:
                        frame = recv_frame(self.request)
                    except (ConnectionError, OSError):
                        return
                    if frame is None:
                        return
                    try:
                        out = dispatch(str(frame.get("method", "")),
                                       frame.get("request") or {})
                        reply = {"response": out}
                    except Exception as e:  # carried in-band, conn survives
                        reply = {"error": f"{type(e).__name__}: {e}"}
                    try:
                        send_frame(self.request, reply)
                    except (ConnectionError, OSError):
                        return

        class Server(socketserver.ThreadingUnixStreamServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server(self.socket_path, Handler)

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "CriServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()
        log.info("listening", socket=self.socket_path, node=self.node_name)
        return self

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._cleanup_socket()



# -- client -------------------------------------------------------------

class CriClient:
    """Thread-safe frame client: one persistent connection, calls
    serialized (the CRI is request/response; kubelet holds few conns)."""

    def __init__(self, socket_path: str, connect_timeout: float = 5.0):
        self.socket_path = socket_path
        self._lock = threading.Lock()
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        deadline = time.monotonic() + connect_timeout
        while True:
            try:
                self._sock.connect(socket_path)
                break
            except (FileNotFoundError, ConnectionRefusedError):
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.02)

    def call(self, method: str, request: dict | None = None) -> dict:
        with self._lock:
            send_frame(self._sock, {"method": method,
                                    "request": request or {}})
            reply = recv_frame(self._sock)
        if reply is None:
            raise ConnectionError("CRI server closed the connection")
        if "error" in reply:
            raise CriError(reply["error"])
        return reply.get("response") or {}

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class RemoteContainerHandle:
    """Client-side view of a container: the same wait/kill/stdout surface
    :class:`ContainerHandle` has, implemented via ContainerStatus /
    StopContainer RPCs.  Once the exit is observed the result is cached
    locally and the server-side entry is removed."""

    def __init__(self, client: CriClient, container_id: str,
                 pod_name: str, container_name: str,
                 env: dict[str, str] | None = None, pid: int | None = None):
        self._client = client
        self.container_id = container_id
        self.pod_name = pod_name
        self.container_name = container_name
        self.exit_code: int | None = None
        self.stdout: str = ""
        self.stderr: str = ""
        self.env = dict(env or {})  # the injected env, from create info
        self.pid = pid

    def wait(self, timeout: float | None = None) -> int | None:
        if self.exit_code is not None:
            return self.exit_code
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            out = self._client.call(
                "ContainerStatus", {"container_id": self.container_id})
            if out["status"]["state"] == CONTAINER_EXITED:
                self.exit_code = int(out["status"]["exit_code"])
                info = out.get("info") or {}
                self.stdout = info.get("stdout", "")
                self.stderr = info.get("stderr", "")
                self._client.call(
                    "RemoveContainer", {"container_id": self.container_id})
                return self.exit_code
            if deadline is not None and time.monotonic() >= deadline:
                return None
            time.sleep(0.02)

    def kill(self) -> None:
        if self.exit_code is not None:
            return
        try:
            self._client.call(
                "StopContainer", {"container_id": self.container_id})
            self.wait(timeout=10)
        except (CriError, ConnectionError):
            pass  # already removed / server gone — nothing left to stop


class RemoteCriShim:
    """Drop-in for :class:`CriShim` that traverses the unix socket: what
    the NodeAgent uses when the shim runs as a separate service (the
    reference's actual deployment shape)."""

    def __init__(self, socket_path: str):
        self.client = CriClient(socket_path)
        self.runtime_name = self.client.call("Version")["runtime_name"]

    def create_container(self, pod: Pod,
                         container_index: int = 0) -> RemoteContainerHandle:
        spec = pod.spec.containers[container_index]
        # kubelet's sequence: EnsureImageExists (PullImage) → create →
        # start — the create verb refuses unpulled images
        self.client.call("PullImage", {"image": {"image": spec.image}})
        out = self.client.call("CreateContainer", {
            "config": {
                "metadata": {"name": spec.name},
                "image": {"image": spec.image},
                "labels": {
                    POD_NAME_LABEL: pod.name,
                    POD_NAMESPACE_LABEL: pod.metadata.namespace,
                    POD_UID_LABEL: pod.metadata.uid,
                },
            },
        })
        cid = out["container_id"]
        self.client.call("StartContainer", {"container_id": cid})
        info = out.get("info") or {}
        return RemoteContainerHandle(self.client, cid, pod.name, spec.name,
                                     env=info.get("env"),
                                     pid=info.get("pid"))

    def close(self) -> None:
        self.client.close()

"""The gang allocator core — reference: ``grpalloc.PodFitsGroupConstraints``
+ ``ComputePodScore`` (SURVEY.md §3, §4.2 hot loop).

Semantics (reference parity, TPU-translated):
- *Fit*: can this gang's total chip ask be satisfied by a free contiguous
  sub-torus of some slice, partitioned into per-pod chunks that never span
  a host?  (Reference: grouped requests must land in one locality group.)
- *Score*: 0–10, combining honest ICI locality of the best logical order,
  packing tightness, and slice fill (bin-packing pressure, BASELINE
  config 5).  (Reference: prefer fewest groups spanned.)
- *Atomicity*: the assignment covers every pod of the gang or ``None`` —
  the all-or-nothing group allocation BASELINE extends to multi-pod gangs.

Fractional requests (millitpu < 1000) bin-pack onto partially-used chips
(best-fit-decreasing) and never block whole-chip slices unnecessarily.
"""

from __future__ import annotations

import heapq
import os
from dataclasses import dataclass, field

from kubegpu_tpu.kubemeta.codec import AllocatedChip, Allocation
from kubegpu_tpu.topology.mesh import Coord, TopologySpec, TpuTopology
from kubegpu_tpu.topology.slices import (
    Placement,
    find_free_placements,
    fragmentation_score,
    fragmentation_scorer,
    subslice_shapes,
)
from kubegpu_tpu.tpuplugin.backend import MILLICHIPS_PER_CHIP, NodeAdvertisement
from kubegpu_tpu.allocator.ordering import candidate_orders, evaluate_order

COORDINATOR_PORT = 8476


@dataclass
class GangRequest:
    """One gang's ask: N pods × (whole chips | millitpu fraction) each."""

    gang_name: str
    num_pods: int = 1
    chips_per_pod: int = 0
    millitpu_per_pod: int = 0
    hbm_gib_per_chip: float = 0.0  # min advertised HBM per allocated chip
    mesh_axes: dict[str, int] | None = None       # logical axes, ordered
    axis_weights: dict[str, float] | None = None  # relative collective bytes
    # permit splitting the gang across slices when no single slice fits:
    # the FIRST mesh axis (outermost, dp by convention) partitions across
    # slices, its crossing pairs riding DCN (counted non-local)
    allow_multislice: bool = False

    @property
    def total_chips(self) -> int:
        return self.num_pods * self.chips_per_pod

    def __post_init__(self) -> None:
        if self.num_pods < 1:
            raise ValueError("num_pods must be >= 1")
        if self.chips_per_pod < 0 or self.millitpu_per_pod < 0:
            raise ValueError("negative device request")
        if self.chips_per_pod and self.millitpu_per_pod:
            raise ValueError("gang mixes whole-chip and fractional asks")
        if self.millitpu_per_pod and self.num_pods != 1:
            raise ValueError("fractional requests are single-pod")
        if self.millitpu_per_pod >= MILLICHIPS_PER_CHIP:
            raise ValueError("millitpu >= 1000 must be a whole-chip ask")
        if self.mesh_axes:
            prod = 1
            for v in self.mesh_axes.values():
                prod *= v
            if prod != self.total_chips:
                raise ValueError(
                    f"mesh_axes {self.mesh_axes} product {prod} != "
                    f"total chips {self.total_chips}")


@dataclass
class PodAssignment:
    pod_index: int       # gang index == TPU_WORKER_ID
    node_name: str
    host_id: int
    chips: list[AllocatedChip] = field(default_factory=list)
    slice_id: str = ""   # "" → the gang's primary slice (single-slice gang)


@dataclass
class GangAssignment:
    slice_id: str        # primary slice (pods may override — multislice)
    pods: list[PodAssignment]
    locality: float
    score: float
    placement: Placement | None = None
    logical_order: list[Coord] = field(default_factory=list)

    def pod_slice(self, p: PodAssignment) -> str:
        return p.slice_id or self.slice_id

    @property
    def slice_ids(self) -> list[str]:
        """All slices touched, primary first, stable order."""
        out: list[str] = []
        for p in self.pods:
            sid = self.pod_slice(p)
            if sid not in out:
                out.append(sid)
        return out or [self.slice_id]

    def to_allocations(self, coordinator_address: str,
                       worker_hostnames: list[str]) -> list[Allocation]:
        return [
            Allocation(
                node_name=p.node_name,
                slice_id=self.pod_slice(p),
                chips=list(p.chips),
                worker_id=p.pod_index,
                num_workers=len(self.pods),
                coordinator_address=coordinator_address,
                worker_hostnames=worker_hostnames,
            )
            for p in self.pods
        ]


class SliceState:
    """Mutable occupancy of one slice, assembled from node advertisements.

    Reference parity: ``NodeInfo{Capacity, Allocatable, Used}`` (SURVEY.md
    §3) — except a TPU "allocatable unit" is a coord in a mesh shared by
    many nodes (hosts), so occupancy is per-coord millichips.
    """

    def __init__(self, slice_id: str, spec: TopologySpec):
        self.slice_id = slice_id
        self.spec = spec
        self.topo = TpuTopology.build(spec)
        self.node_of_host: dict[int, str] = {}
        self.ip_of_host: dict[int, str] = {}
        self.available: set[Coord] = set()     # advertised by some node
        self.unhealthy: set[Coord] = set()
        self.bad_links: set[tuple[Coord, Coord]] = set()  # normalized pairs
        self.local_index: dict[Coord, int] = {}
        self.used_millichips: dict[Coord, int] = {}
        self.hbm_gib: dict[Coord, float] = {}  # advertised HBM per chip

    @classmethod
    def from_advertisements(
        cls, advs: list[NodeAdvertisement]
    ) -> "SliceState":
        if not advs:
            raise ValueError("no advertisements")
        first = advs[0]
        if len({a.slice_id for a in advs}) != 1:
            raise ValueError("advertisements span slices")
        spec = TopologySpec(
            name=first.slice_type, generation=first.slice_type.split("-")[0],
            mesh_shape=first.mesh_shape, wrap=first.wrap,
            host_block=first.host_block)
        st = cls(first.slice_id, spec)
        for a in advs:
            st.node_of_host[a.host_id] = a.node_name
            st.ip_of_host[a.host_id] = a.internal_ip
            for c in a.chips:
                st.available.add(c.coord)
                st.local_index[c.coord] = c.local_index
                st.hbm_gib[c.coord] = c.hbm_gib
                if not c.healthy:
                    st.unhealthy.add(c.coord)
            for pair in a.bad_links:
                st.bad_links.add((min(pair), max(pair)))
        return st

    def clone(self) -> "SliceState":
        """Copy for what-if planning (preemption/backfill trials): mutable
        occupancy/health is copied, immutable topo/spec shared."""
        st = SliceState.__new__(SliceState)
        st.slice_id = self.slice_id
        st.spec = self.spec
        st.topo = self.topo
        st.node_of_host = dict(self.node_of_host)
        st.ip_of_host = dict(self.ip_of_host)
        st.available = set(self.available)
        st.unhealthy = set(self.unhealthy)
        st.bad_links = set(self.bad_links)
        st.local_index = dict(self.local_index)
        st.used_millichips = dict(self.used_millichips)
        st.hbm_gib = dict(self.hbm_gib)
        return st

    # -- occupancy -------------------------------------------------------

    def blocked_for_whole(self, min_hbm_gib: float = 0.0) -> set[Coord]:
        """Coords unusable for whole-chip placement: any current use,
        unhealthy, not advertised (host missing), or — with
        ``min_hbm_gib`` — advertising less HBM than the request needs
        (a chip the model doesn't fit on is no chip at all)."""
        blocked = {c for c, u in self.used_millichips.items() if u > 0}
        blocked |= self.unhealthy
        all_coords = {ch.coord for ch in self.topo.chips}
        blocked |= all_coords - self.available
        if min_hbm_gib > 0:
            blocked |= {c for c in self.available
                        if self.hbm_gib.get(c, 0.0) < min_hbm_gib}
        return blocked

    def free_millichips(self, coord: Coord) -> int:
        if coord not in self.available or coord in self.unhealthy:
            return 0
        return MILLICHIPS_PER_CHIP - self.used_millichips.get(coord, 0)

    def take(self, chips: list[AllocatedChip]) -> None:
        for ch in chips:
            newu = self.used_millichips.get(ch.coord, 0) + ch.millichips
            if newu > MILLICHIPS_PER_CHIP:
                raise ValueError(f"chip {ch.coord} over-allocated: {newu}")
            self.used_millichips[ch.coord] = newu

    def release(self, chips: list[AllocatedChip]) -> None:
        for ch in chips:
            cur = self.used_millichips.get(ch.coord, 0) - ch.millichips
            if cur < 0:
                raise ValueError(f"chip {ch.coord} over-released")
            self.used_millichips[ch.coord] = cur

    def restricted_to_node(self, node_name: str) -> "SliceState":
        """A view of this slice where only ``node_name``'s chips are
        available — the per-node feasibility check the extender /filter
        verb needs (a candidate node can only contribute its own chips)."""
        host_ids = {h for h, n in self.node_of_host.items() if n == node_name}
        view = SliceState(self.slice_id, self.spec)
        view.node_of_host = dict(self.node_of_host)
        view.ip_of_host = dict(self.ip_of_host)
        node_coords = {self.topo.chips[i].coord
                       for h in host_ids
                       for i in self.topo.hosts[h].chip_indices}
        view.available = self.available & node_coords
        view.unhealthy = set(self.unhealthy)
        view.bad_links = set(self.bad_links)
        view.local_index = dict(self.local_index)
        view.used_millichips = dict(self.used_millichips)
        view.hbm_gib = dict(self.hbm_gib)
        return view

    def fill_fraction(self) -> float:
        cap = len(self.available) * MILLICHIPS_PER_CHIP
        if not cap:
            return 1.0
        return sum(self.used_millichips.values()) / cap

    def _alloc_chip(self, coord: Coord, millichips: int) -> AllocatedChip:
        return AllocatedChip(coord=coord,
                             local_index=self.local_index[coord],
                             millichips=millichips)


# ---------------------------------------------------------------------------
# Ordering helpers specific to gang chunking
# ---------------------------------------------------------------------------

def _gilbert2d(w: int, h: int):
    """Generalized Hilbert curve over a w×h grid: yields (x, y) visiting
    every cell with consecutive cells adjacent and strong locality at all
    scales — consecutive groups of blocks stay compact, which is what lets
    a tp ring spanning several host blocks close into a physical cycle."""
    def gen(x, y, ax, ay, bx, by):
        wl = abs(ax + ay)
        hl = abs(bx + by)
        dax, day = (ax > 0) - (ax < 0), (ay > 0) - (ay < 0)
        dbx, dby = (bx > 0) - (bx < 0), (by > 0) - (by < 0)
        if hl == 1:
            for _ in range(wl):
                yield (x, y)
                x, y = x + dax, y + day
            return
        if wl == 1:
            for _ in range(hl):
                yield (x, y)
                x, y = x + dbx, y + dby
            return
        ax2, ay2 = ax // 2, ay // 2
        bx2, by2 = bx // 2, by // 2
        w2 = abs(ax2 + ay2)
        h2 = abs(bx2 + by2)
        if 2 * wl > 3 * hl:
            if w2 % 2 and wl > 2:
                ax2, ay2 = ax2 + dax, ay2 + day
            yield from gen(x, y, ax2, ay2, bx, by)
            yield from gen(x + ax2, y + ay2, ax - ax2, ay - ay2, bx, by)
        else:
            if h2 % 2 and hl > 2:
                bx2, by2 = bx2 + dbx, by2 + dby
            yield from gen(x, y, bx2, by2, ax2, ay2)
            yield from gen(x + bx2, y + by2, ax, ay, bx - bx2, by - by2)
            yield from gen(x + (ax - dax) + (bx2 - dbx),
                           y + (ay - day) + (by2 - dby),
                           -bx2, -by2, -(ax - ax2), -(ay - ay2))
    if w >= h:
        yield from gen(0, 0, w, 0, 0, h)
    else:
        yield from gen(0, 0, 0, h, w, 0)


def _block_cycle_options(coords: list[Coord]) -> list[list[Coord]]:
    """All oriented Hamiltonian walks of one host block that downstream
    chunking may use (2x2 blocks: 4 rotations × 2 directions of the cycle)."""
    if len(coords) == 4:
        s = sorted(coords)
        base = [s[0], s[1], s[3], s[2]]  # the 2x2 cycle
        outs = []
        for rot in range(4):
            r = base[rot:] + base[:rot]
            outs.append(r)
            outs.append([r[0]] + list(reversed(r[1:])))
        return outs
    return [sorted(coords)]


def _dist(a: Coord, b: Coord) -> int:
    return sum(abs(a[i] - b[i]) for i in range(3))


def _orient_rings(blocks: list[list[Coord]], close: bool = False) -> list[Coord]:
    """Chain per-block chip cycles by dynamic programming: choose each
    block's orientation so entry chips sit next to the previous block's
    exit chip (Viterbi over ≤8 orientations/block).  With ``close``, also
    optimize the wrap transition last-exit → first-entry, turning the whole
    sequence into a physical cycle — what lets a collective ring spanning
    several host blocks run at 100% ICI locality on an unwrapped mesh."""
    options = [_block_cycle_options(b) for b in blocks]
    if len(blocks) == 1:
        return list(options[0][0])
    from kubegpu_tpu.allocator import _native

    native = _native.orient_rings_native(options, close)
    if native is not None:
        return native

    def trans_cost(prev_opt: list[Coord], nxt_opt: list[Coord]) -> int:
        d = _dist(prev_opt[-1], nxt_opt[0])
        return 0 if d == 1 else d

    best_total, best_path = None, None
    starts = options[0] if close else options[0][:1]
    for start in starts:
        # cost[j] = best cost ending with option j of current block
        cost = {0: 0}
        back: list[dict[int, int]] = []
        prev_opts = [start]
        for i in range(1, len(blocks)):
            ncost: dict[int, int] = {}
            nback: dict[int, int] = {}
            for j, opt in enumerate(options[i]):
                bestc, bestj = None, None
                for pj, pcost in cost.items():
                    c = pcost + trans_cost(prev_opts[pj], opt)
                    if bestc is None or c < bestc:
                        bestc, bestj = c, pj
                ncost[j] = bestc
                nback[j] = bestj
            back.append(nback)
            cost = ncost
            prev_opts = options[i]
        for j, c in cost.items():
            total = c
            if close:
                total += trans_cost(options[-1][j], start)
            if best_total is None or total < best_total:
                # backtrack
                path = [j]
                for nb in reversed(back):
                    path.append(nb[path[-1]])
                path.reverse()
                chosen = [start] + [options[i][path[i]]
                                    for i in range(1, len(blocks))]
                best_total, best_path = total, chosen
    out: list[Coord] = []
    for opt in best_path:
        out.extend(opt)
    return out


def _block_sequences(topo: TpuTopology,
                     placement: Placement) -> list[list[list[Coord]]]:
    """Orderings of the placement's host blocks: snake (two axes) and
    generalized-Hilbert traversals of the block grid."""
    by_host: dict[int, list[Coord]] = {}
    for c in placement.coords:
        by_host.setdefault(topo.chip_at(c).host_id, []).append(c)
    entries = [(topo.hosts[h].block_origin, coords)
               for h, coords in by_host.items()]
    seqs: list[list[list[Coord]]] = []
    for major in (0, 1):
        minor = 1 - major
        majors = sorted({o[major] for o, _ in entries})
        seq: list[list[Coord]] = []
        for i, m in enumerate(majors):
            line = [e for e in entries if e[0][major] == m]
            line.sort(key=lambda e: e[0][minor])
            if i % 2 == 1:
                line.reverse()
            seq.extend(blk for _, blk in line)
        seqs.append(seq)
    origins = sorted({o for o, _ in entries})
    bxs = sorted({o[0] for o in origins})
    bys = sorted({o[1] for o in origins})
    if len(origins) == len(bxs) * len(bys) and len(origins) > 2:
        by_origin = {o: blk for o, blk in entries}
        seq = []
        for gx, gy in _gilbert2d(len(bxs), len(bys)):
            key = (bxs[gx], bys[gy], origins[0][2])
            if key not in by_origin:
                seq = []
                break
            seq.append(by_origin[key])
        if seq:
            seqs.append(seq)
    return seqs


_block_orders_memo: dict = {}


def _block_orders(topo: TpuTopology, placement: Placement,
                  ring_span: int | None = None) -> list[list[Coord]]:
    """Memoizing wrapper over :func:`_block_orders_uncached` — pure
    geometry, so results are shared across slices of the same topology
    shape and across scheduling passes (the same placements recur
    constantly under churn).  Callers must not mutate the returned
    orders.  The native-path flag is part of the key so the parity tests
    compare real computations, not cache hits."""
    key = (topo.spec.name, topo.spec.mesh_shape, topo.spec.wrap,
           topo.spec.host_block, placement, ring_span,
           bool(os.environ.get("KUBETPU_NO_NATIVE")))
    hit = _block_orders_memo.get(key)
    if hit is None:
        hit = _block_orders_uncached(topo, placement, ring_span)
        if len(_block_orders_memo) >= 8192:
            _block_orders_memo.clear()
        _block_orders_memo[key] = hit
    return hit


def _block_orders_uncached(topo: TpuTopology, placement: Placement,
                           ring_span: int | None = None
                           ) -> list[list[Coord]]:
    """Chip orders built from block sequences.  With ``ring_span`` (chips
    in the workload's fastest logical axis), blocks are grouped so each
    ring's span of blocks is closed into a physical cycle — e.g. a tp=16
    ring over four 2x2 host blocks becomes a 16-chip ICI cycle."""
    orders: list[list[Coord]] = []
    seen: set[tuple] = set()

    def add(o: list[Coord] | None) -> None:
        if o is not None and tuple(o) not in seen:
            seen.add(tuple(o))
            orders.append(o)

    for seq in _block_sequences(topo, placement):
        add(_orient_rings(seq, close=len(seq) > 2))
        if not ring_span:
            continue
        cph = len(seq[0])
        if ring_span == cph and len(seq) >= 2:
            # fast axis = one host block: align the per-block cycles so
            # the NEXT axis's position-wise pairs ride ICI too
            add(_align_units([_block_cycle_options(b)[0] for b in seq],
                             step=1))
            continue
        span_blocks = ring_span // cph if ring_span % cph == 0 else 0
        if span_blocks > 1 and len(seq) % span_blocks == 0:
            # fast axis spans several blocks: close each group's ring once,
            # reuse the oriented groups both concatenated and aligned
            units = [_orient_rings(seq[g:g + span_blocks], close=True)
                     for g in range(0, len(seq), span_blocks)]
            add([c for u in units for c in u])
            if len(units) >= 2:
                add(_align_units(units, step=cph))
    return orders


def _cycle_variants(cycle: list[Coord], step: int) -> list[list[Coord]]:
    """Rotations (by multiples of ``step``, preserving chunk boundaries)
    and reversals of a chip cycle — the orientation freedom of one ring."""
    n = len(cycle)
    outs = []
    for r in range(0, n, max(step, 1)):
        rot = cycle[r:] + cycle[:r]
        outs.append(rot)
        outs.append(list(reversed(rot)))
    return outs


def _align_units(units: list[list[Coord]], step: int) -> list[Coord] | None:
    """Choose an orientation per ring so POSITION-WISE pairs between
    consecutive rings (and last→first) maximize ICI adjacency.

    This is the second-axis problem the global-ring orders can't solve:
    with pods pinned to host blocks, the fastest logical axis rides each
    block's internal cycle, while the next axis pairs chip *i* of ring k
    with chip *i* of ring k+1 — a dp/fsdp gradient ring across blocks.
    Viterbi over ≤2n orientations per ring; unit 0 is fixed to identity or
    reversal WLOG (a global rotation applied to every ring preserves all
    pairwise gains, intra-ring rings, and chunk boundaries).
    """
    if len(units) < 2 or len({len(u) for u in units}) != 1:
        return None
    from kubegpu_tpu.allocator import _native

    options = [_cycle_variants(u, step) for u in units]
    native = _native.align_units_native(options)
    if native is not None:
        return native

    def gain(a: list[Coord], b: list[Coord]) -> int:
        return sum(1 for p, q in zip(a, b) if _dist(p, q) == 1)

    best_total, best_seq = -1, None
    for start in options[0][:2]:  # identity + reversal (see docstring)
        score = {j: gain(start, opt) for j, opt in enumerate(options[1])}
        back: list[dict[int, int]] = []
        for i in range(2, len(units)):
            nscore: dict[int, int] = {}
            nback: dict[int, int] = {}
            for j, opt in enumerate(options[i]):
                bj, bs = None, -1
                for pj, ps in score.items():
                    s = ps + gain(options[i - 1][pj], opt)
                    if s > bs:
                        bs, bj = s, pj
                nscore[j] = bs
                nback[j] = bj
            back.append(nback)
            score = nscore
        for j, s in score.items():
            total = s + gain(options[-1][j], start)  # close the loop
            if total > best_total:
                path = [j]
                for nb in reversed(back):
                    path.append(nb[path[-1]])
                path.reverse()
                seq = list(start)
                for i, pj in enumerate(path, start=1):
                    seq.extend(options[i][pj])
                best_total, best_seq = total, seq
    return best_seq


def _multislice_locality(parts: list[tuple[SliceState, list[Coord]]],
                         axes: dict[str, int],
                         axis_weights: dict[str, float] | None) -> float:
    """Weighted ICI locality of a multislice logical order: ring pairs
    inside one part score against that part's torus (bad links included);
    pairs spanning parts ride DCN and count non-local.  Coord spaces
    collide across slices, so coords are disambiguated with a part tag
    and the shared ring enumeration is reused."""
    from kubegpu_tpu.topology.locality import traffic_pairs_for_mesh_axes

    tagged = [(pi,) + c for pi, (_, o) in enumerate(parts) for c in o]
    tm = traffic_pairs_for_mesh_axes(tagged, axes, axis_weights)
    total_w = local_w = 0.0
    for (a, b), w in tm.pairs.items():
        total_w += w
        if a[0] != b[0]:
            continue   # DCN crossing
        st, _ = parts[a[0]]
        ca, cb = a[1:], b[1:]
        if (st.topo.are_ici_adjacent(ca, cb)
                and (min(ca, cb), max(ca, cb)) not in st.bad_links):
            local_w += w
    return local_w / total_w if total_w else 1.0


def _chunks_host_local(topo: TpuTopology, order: list[Coord], c: int) -> bool:
    for i in range(0, len(order), c):
        hosts = {topo.chip_at(x).host_id for x in order[i:i + c]}
        if len(hosts) != 1:
            return False
    return True


# ---------------------------------------------------------------------------
# The allocator
# ---------------------------------------------------------------------------

@dataclass
class _Candidate:
    slice_state: SliceState
    placement: Placement
    order: list[Coord]
    locality: float
    score: float


class GangAllocator:
    """Pure-function fit/score/assign over SliceStates (no I/O) — the same
    testability property the reference's allocator had (SURVEY.md §5)."""

    def __init__(self, max_placements_per_shape: int = 64,
                 max_scored_per_shape: int = 8,
                 locality_weight: float = 0.6, frag_weight: float = 0.25,
                 fill_weight: float = 0.15):
        self.max_placements_per_shape = max_placements_per_shape
        self.max_scored_per_shape = max_scored_per_shape
        self.locality_weight = locality_weight
        self.frag_weight = frag_weight
        self.fill_weight = fill_weight
        # Load (and if stale, rebuild) the native core NOW, not inside
        # the first scheduling decision — the lazy path costs ms (or a
        # `make` run) and would land in the latency histogram's tail.
        from kubegpu_tpu.allocator import _native
        _native.available()

    # -- public API ------------------------------------------------------

    def find_assignment(self, slices: list[SliceState],
                        req: GangRequest) -> GangAssignment | None:
        import time as _time

        # per-call phase attribution: enumeration (per-slice shape ×
        # placement × ordering search) vs the multislice split search.
        # The extender folds these into its per-decision trace so the
        # bench can bucket the p99 tail (VERDICT r5 weak #5: a 330×
        # p50→p99 spread with no committed attribution).  Overwritten
        # every call; read it before the next one.
        t0 = _time.perf_counter()
        self.last_phase_ms = {"enumerate": 0.0, "multislice_split": 0.0}
        if req.millitpu_per_pod:
            out = self._find_fractional(slices, req)
            self.last_phase_ms["enumerate"] = \
                (_time.perf_counter() - t0) * 1e3
            return out
        best: GangAssignment | None = None
        for st in slices:
            # threading the incumbent lets a later slice's whole search
            # stop at the bound check before any ordering work when it
            # provably cannot beat an earlier slice's candidate
            cand = self._best_candidate_in_slice(
                st, req, incumbent=best.score if best else None)
            if cand and (best is None or cand.score > best.score):
                best = cand
        self.last_phase_ms["enumerate"] = \
            (_time.perf_counter() - t0) * 1e3
        if best is None and req.allow_multislice and req.num_pods > 1 \
                and req.chips_per_pod and len(slices) > 1:
            t1 = _time.perf_counter()
            best = self._multislice_candidate(slices, req)
            self.last_phase_ms["multislice_split"] = \
                (_time.perf_counter() - t1) * 1e3
        return best

    def commit(self, slices: dict[str, SliceState],
               assignment: GangAssignment) -> None:
        """TakePodResources (SURVEY.md §4.2): mutate occupancy atomically.
        Skips slices that vanished, symmetric with rollback — a multislice
        gang re-committed in a what-if trial (recovery's rollback→find→
        commit) may have lost one slice while another lives on."""
        for p in assignment.pods:
            st = slices.get(assignment.pod_slice(p))
            if st is not None:
                st.take(p.chips)

    def rollback(self, slices: dict[str, SliceState],
                 assignment: GangAssignment) -> None:
        """ReturnPodResources (SURVEY.md §4.4).  A slice that vanished
        (all hosts down) has nothing to release — skip it, free the rest
        (multislice gangs can lose one slice and keep another)."""
        for p in assignment.pods:
            st = slices.get(assignment.pod_slice(p))
            if st is not None:
                st.release(p.chips)

    # -- whole-chip path -------------------------------------------------

    def _best_candidate_in_slice(self, st: SliceState,
                                 req: GangRequest,
                                 incumbent: float | None = None
                                 ) -> GangAssignment | None:
        total = req.total_chips
        if total == 0 or total > len(st.available):
            return None
        cph = st.spec.chips_per_host
        if req.chips_per_pod > cph:
            return None  # a pod cannot span hosts
        blocked = st.blocked_for_whole(req.hbm_gib_per_chip)
        # Exact necessary condition, O(chips): fewer FREE chips than the
        # ask means no shape can ever place — skip the whole shape ×
        # placement × ordering search.  This is the failing-decision hot
        # path (the p99 tail is made of infeasible searches).
        if total > len(st.available - blocked):
            return None
        fill = st.fill_fraction()
        axes = req.mesh_axes or {"dp": total}
        # Branch-and-bound over placements: the ordering search (the
        # expensive part of scoring) is bounded above by locality=1.0,
        # so computing the CHEAP fragmentation term for every placement
        # first and visiting in descending-frag order lets us stop the
        # moment no remaining placement's bound can beat the incumbent.
        # Exact: the winner is the same as scoring everything (ties may
        # resolve to an equal-scored placement).  This is what keeps the
        # empty-cluster small-gang case (many placements) off the p99.
        ranked: list[tuple[float, int, Placement]] = []
        # ONE occupancy mask for the whole per-slice search: the shape
        # scan, frag ranking, and connected fallback all reuse it (the
        # per-call rebuild dominated 1024-chip decision times)
        from kubegpu_tpu.allocator import _native
        occ_mask = _native.occupancy_mask(st.topo, blocked)
        fscore = fragmentation_scorer(st.topo, blocked, mask=occ_mask)
        # Bound the ordering work, not just the candidate count: a
        # 256-chip placement's ring search costs ~16x a 16-chip one's,
        # and origin matters even less for big placements (fewer
        # distinct origins, homogeneous torus) — so the per-shape
        # scored-candidate budget shrinks as the ask grows, keeping
        # decision cost ~flat across gang sizes (the 1024-chip p99
        # was made of full-slice placements scoring 8 candidates each).
        k_scored = max(2, min(self.max_scored_per_shape,
                              (64 * self.max_scored_per_shape)
                              // max(total, 1)))
        for si, shape in enumerate(subslice_shapes(
                total, st.spec.mesh_shape)):
            # Only the top-frag few per shape get the expensive ordering
            # search: on a homogeneous torus, locality depends on the
            # shape far more than the origin, so the frag ranking is the
            # score ranking to within ties — every shape stays
            # represented, and the global bound below still applies.
            # The enumerate+rank+truncate runs fused in C when the
            # library is up (top-K only ever crosses back into Python).
            native_ranked = _native.rank_free_placements_native(
                st.topo, blocked, shape,
                self.max_placements_per_shape,
                k_scored, mask=occ_mask)
            if native_ranked is not None:
                ranked.extend((f, si, pl) for f, pl in native_ranked)
                continue
            shape_ranked = [
                (fscore(pl), si, pl)
                for pl in find_free_placements(
                    st.topo, blocked, shape,
                    limit=self.max_placements_per_shape,
                    mask=occ_mask)]
            shape_ranked.sort(key=lambda t: -t[0])
            ranked.extend(shape_ranked[:k_scored])
        # stable: frag desc, then the shape-compactness preference order
        ranked.sort(key=lambda t: (-t[0], t[1]))
        best: _Candidate | None = None
        # Bounding out at <= is exact for the RECTANGULAR search: a tie
        # against the cross-slice incumbent also loses (strict > in
        # find_assignment).
        floor = incumbent if incumbent is not None else float("-inf")
        # `rect_scored` settles connected-fallback eligibility: it flips
        # the moment ANY rectangular placement passes _score_placement.
        # While it is still False the loop keeps scoring BELOW the
        # incumbent floor (candidates there can't beat the incumbent —
        # score <= bound <= floor — so this only settles eligibility,
        # never changes the winner), which makes eligibility a pure
        # function of (slice occupancy, request), independent of the
        # cross-slice incumbent and hence of slice iteration order
        # (ADVICE r3: the r3 `not ranked` gate silently declared a slice
        # unschedulable when rectangles enumerated but every ordering
        # failed the host-chunking filter).
        rect_scored = False
        for frag, _, pl in ranked:
            bound = 10.0 * (self.locality_weight
                            + self.frag_weight * frag
                            + self.fill_weight * fill)
            if best is not None and bound <= best.score:
                break
            if bound <= floor:
                if rect_scored:
                    break
                # Below the incumbent floor a candidate can't win
                # (score <= bound <= floor, strict > cross-slice), so
                # settle eligibility with the cheap host-chunking probe
                # instead of the full ordering-locality search — the
                # losing-slice hot path stays near its r3 cost.
                if self._rect_feasible(st, pl, req, axes):
                    rect_scored = True
                    break
                continue
            cand = self._score_placement(st, pl, req, axes, blocked, fill,
                                         frag=frag)
            if cand:
                rect_scored = True
                if best is None or cand.score > best.score:
                    best = cand
        if not rect_scored:   # also covers `not ranked` (loop never ran)
            # Non-rectangular totals (e.g. 3 chips in a 2x2 mesh) — or
            # slices where every rectangular ordering fails the
            # host-chunking filter — fall back to a connected free set;
            # the reference's group allocator had the same flexibility
            # since groups weren't geometric.
            cand = self._connected_candidate(st, req, blocked, axes,
                                             mask=occ_mask)
            if cand is not None:
                best = cand
        if best is None:
            return None
        return self._to_assignment(best, req)

    def _connected_candidate(self, st: SliceState, req: GangRequest,
                             blocked: set[Coord],
                             axes: dict[str, int],
                             mask=None) -> _Candidate | None:
        """BFS-grow a connected set of free chips, chunked host-locally."""
        from kubegpu_tpu.allocator import _native

        total = req.total_chips
        c = req.chips_per_pod
        res = _native.connected_order_native(st.topo, blocked, total, c,
                                             req.num_pods, mask=mask)
        if res is not None:
            found, order = res
            if not found:
                return None
            loc = evaluate_order(st.topo, order, axes, req.axis_weights,
                                 st.bad_links)
            pl = Placement(origin=min(order), shape=(0, 0, 0),
                           coords=tuple(order))
            frag = fragmentation_score(st.topo, blocked, pl)
            score = 10.0 * (self.locality_weight * loc
                            + self.frag_weight * frag
                            + self.fill_weight * st.fill_fraction())
            return _Candidate(slice_state=st, placement=pl, order=order,
                              locality=loc, score=score)
        free = sorted({ch.coord for ch in st.topo.chips} - blocked)
        for start in free:
            seen = {start}
            frontier = [start]
            region: list[Coord] = []
            # min-heap pop == the old frontier.sort(); pop(0) order
            # (smallest coord each iteration) at O(log n) per pop —
            # the native port's sorted-frontier BFS matches this too
            while frontier and len(region) + len(frontier) <= len(free):
                nxt = heapq.heappop(frontier)
                region.append(nxt)
                if len(region) >= total:
                    break
                for nb in st.topo.neighbors(nxt):
                    if nb not in seen and nb not in blocked:
                        seen.add(nb)
                        heapq.heappush(frontier, nb)
            if len(region) < total:
                continue
            # chunk host-locally: pods take chips host by host
            by_host: dict[int, list[Coord]] = {}
            for x in region:
                by_host.setdefault(st.topo.chip_at(x).host_id, []).append(x)
            order: list[Coord] = []
            chunks_formed = 0
            for hid in sorted(by_host):
                chips = sorted(by_host[hid])
                usable = (len(chips) // c) * c
                take = min(usable, total - len(order))
                order.extend(chips[:take])
                chunks_formed += take // c
                if len(order) >= total:
                    break
            if len(order) != total or chunks_formed != req.num_pods:
                continue
            loc = evaluate_order(st.topo, order, axes, req.axis_weights,
                                 st.bad_links)
            pl = Placement(origin=min(order), shape=(0, 0, 0),
                           coords=tuple(order))
            frag = fragmentation_score(st.topo, blocked, pl)
            score = 10.0 * (self.locality_weight * loc
                            + self.frag_weight * frag
                            + self.fill_weight * st.fill_fraction())
            return _Candidate(slice_state=st, placement=pl, order=order,
                              locality=loc, score=score)
        return None

    def _feasible_orders(self, st: SliceState, pl: Placement,
                         req: GangRequest,
                         axes: dict[str, int]):
        """Candidate orderings of ``pl`` that chunk host-locally — THE
        order set both the scorer and the below-floor eligibility probe
        consume (one generator, so the probe can never drift from
        ``_score_placement(...) is not None``).  Lazy: the probe stops
        at the first hit without paying ``evaluate_order``."""
        c = req.chips_per_pod
        ring_span = list(axes.values())[-1] if axes else None
        for o in candidate_orders(pl):
            if _chunks_host_local(st.topo, o, c):
                yield o
        for o in _block_orders(st.topo, pl, ring_span):
            if _chunks_host_local(st.topo, o, c):
                yield o

    def _rect_feasible(self, st: SliceState, pl: Placement,
                       req: GangRequest, axes: dict[str, int]) -> bool:
        return next(
            iter(self._feasible_orders(st, pl, req, axes)), None) is not None

    def _score_placement(self, st: SliceState, pl: Placement,
                         req: GangRequest, axes: dict[str, int],
                         blocked: set[Coord],
                         fill: float,
                         frag: float | None = None) -> _Candidate | None:
        orders = list(self._feasible_orders(st, pl, req, axes))
        if not orders:
            return None
        best_order, best_loc = None, -1.0
        for o in orders:
            loc = evaluate_order(st.topo, o, axes, req.axis_weights,
                                 st.bad_links)
            if loc > best_loc:
                best_order, best_loc = o, loc
        if frag is None:
            frag = fragmentation_score(st.topo, blocked, pl)
        score = 10.0 * (self.locality_weight * best_loc
                        + self.frag_weight * frag
                        + self.fill_weight * fill)
        return _Candidate(slice_state=st, placement=pl, order=best_order,
                          locality=best_loc, score=score)

    def _to_assignment(self, cand: _Candidate,
                       req: GangRequest) -> GangAssignment:
        st = cand.slice_state
        c = req.chips_per_pod
        pods: list[PodAssignment] = []
        for k in range(req.num_pods):
            chunk = cand.order[k * c:(k + 1) * c]
            host_id = st.topo.chip_at(chunk[0]).host_id
            pods.append(PodAssignment(
                pod_index=k,
                node_name=st.node_of_host.get(host_id, f"host-{host_id}"),
                host_id=host_id,
                chips=[st._alloc_chip(x, MILLICHIPS_PER_CHIP)
                       for x in chunk]))
        return GangAssignment(
            slice_id=st.slice_id, pods=pods, locality=cand.locality,
            score=cand.score, placement=cand.placement,
            logical_order=cand.order)

    # -- multislice path (DCN-spanning gangs) -----------------------------

    def _multislice_candidate(self, slices: list[SliceState],
                              req: GangRequest) -> GangAssignment | None:
        """Split the gang across slices when no single slice fits
        (SURVEY.md §6 comm-backend row: collectives ride ICI intra-slice,
        DCN across slices — the Cloud-TPU-multislice shape).

        The FIRST (outermost) mesh axis partitions: n_parts contiguous
        worker groups land on n_parts distinct slices, so only that axis's
        rings cross slices.  Fewest parts wins (fewest DCN crossings);
        reported locality counts every cross-slice traffic pair as
        non-local — the honest number the ≥90% north-star is judged on.
        """
        axes = req.mesh_axes or {"dp": req.total_chips}
        outer_name = next(iter(axes))
        outer = axes[outer_name]
        by_id = {st.slice_id: st for st in slices}
        max_parts = min(outer, len(slices), req.num_pods)
        for n_parts in range(2, max_parts + 1):
            if outer % n_parts or req.num_pods % n_parts:
                continue
            sub_axes = dict(axes)
            sub_axes[outer_name] = outer // n_parts
            sub_req = GangRequest(
                gang_name=req.gang_name,
                num_pods=req.num_pods // n_parts,
                chips_per_pod=req.chips_per_pod,
                hbm_gib_per_chip=req.hbm_gib_per_chip,
                mesh_axes=sub_axes,
                axis_weights=req.axis_weights)
            cands = []
            for st in slices:
                c = self._best_candidate_in_slice(st, sub_req)
                if c is not None:
                    cands.append(c)
            if len(cands) < n_parts:
                continue
            cands.sort(key=lambda a: (-a.score, a.slice_id))
            parts = cands[:n_parts]
            m = req.num_pods // n_parts
            pods: list[PodAssignment] = []
            for k, pa in enumerate(parts):
                for p in pa.pods:
                    pods.append(PodAssignment(
                        pod_index=k * m + p.pod_index,
                        node_name=p.node_name,
                        host_id=p.host_id,
                        chips=p.chips,
                        slice_id=pa.slice_id))
            loc = _multislice_locality(
                [(by_id[pa.slice_id], pa.logical_order) for pa in parts],
                axes, req.axis_weights)
            # parts' scores blend their (closed-subring) locality; swap in
            # the honest global figure, keep their frag/fill terms
            score = (10.0 * self.locality_weight * loc
                     + sum(pa.score - 10.0 * self.locality_weight
                           * pa.locality for pa in parts) / n_parts)
            return GangAssignment(
                slice_id=parts[0].slice_id, pods=pods, locality=loc,
                score=score, placement=None,
                logical_order=[c for pa in parts
                               for c in pa.logical_order])
        return None

    # -- fractional path -------------------------------------------------

    def _find_fractional(self, slices: list[SliceState],
                         req: GangRequest) -> GangAssignment | None:
        """Best-fit-decreasing: prefer the most-used chip that still fits,
        keeping whole chips free for slice placements (BASELINE config 5).

        Tie-breaks (in order) minimize damage to future *gang* placements:
        pick the smallest slice (keep big contiguous meshes whole), then a
        corner chip (fragment an edge, not the middle), then stable coord.
        """
        need = req.millitpu_per_pod
        best: tuple[tuple, SliceState, Coord] | None = None
        for st in slices:
            mx, my, mz = st.spec.mesh_shape
            for coord in st.available:  # key's coord tie-break = determinism
                free = st.free_millichips(coord)
                used = st.used_millichips.get(coord, 0)
                if free < need:
                    continue
                if req.hbm_gib_per_chip > 0 and \
                        st.hbm_gib.get(coord, 0.0) < req.hbm_gib_per_chip:
                    continue
                corner_dist = (min(coord[0], mx - 1 - coord[0])
                               + min(coord[1], my - 1 - coord[1])
                               + min(coord[2], mz - 1 - coord[2]))
                key = (-used, len(st.available), corner_dist, coord)
                if best is None or key < best[0]:
                    best = (key, st, coord)
        if best is None:
            return None
        _, st, coord = best
        used = st.used_millichips.get(coord, 0)
        host_id = st.topo.chip_at(coord).host_id
        pod = PodAssignment(
            pod_index=0,
            node_name=st.node_of_host.get(host_id, f"host-{host_id}"),
            host_id=host_id,
            chips=[st._alloc_chip(coord, need)])
        return GangAssignment(
            slice_id=st.slice_id, pods=[pod], locality=1.0,
            score=5.0 + 5.0 * (used / MILLICHIPS_PER_CHIP))

    # -- helpers for the scheduler --------------------------------------

    @staticmethod
    def coordinator_for(assignment: GangAssignment,
                        slices: dict[str, SliceState],
                        port: int = COORDINATOR_PORT) -> tuple[str, list[str]]:
        """(coordinator address, worker hostnames in worker order).  Each
        pod resolves against its own slice (multislice gangs span
        several); the coordinator is worker 0's host."""
        names = []
        for p in assignment.pods:
            st = slices[assignment.pod_slice(p)]
            names.append(st.node_of_host.get(p.host_id,
                                             f"host-{p.host_id}"))
        st0 = slices[assignment.pod_slice(assignment.pods[0])]
        ip0 = st0.ip_of_host.get(assignment.pods[0].host_id, "127.0.0.1")
        return f"{ip0}:{port}", names

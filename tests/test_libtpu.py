"""LibtpuBackend unit tests against a faked ``jax.local_devices()`` —
the L0 hardware seam, testable without hardware (VERDICT r1 #4; the
reference's NVML paths had no such coverage, SURVEY.md §5 calls that a
gap to close)."""

import jax
import pytest

from kubegpu_tpu.allocator import SliceState
from kubegpu_tpu.tpuplugin.libtpu import (
    LibtpuBackend,
    slice_type_from_accelerator,
)


class FakeDev:
    platform = "tpu"

    def __init__(self, coords, process_index=0, stats="default"):
        self.coords = coords
        self.process_index = process_index
        self._stats = stats

    def memory_stats(self):
        if self._stats == "default":
            return {"bytes_limit": 16 * (1 << 30)}
        if self._stats is None:
            raise RuntimeError("no stats on this runtime")
        return self._stats


@pytest.fixture()
def fake_devices(monkeypatch):
    """Install a device list; returns a setter."""
    holder = {"devs": []}
    monkeypatch.setattr(jax, "local_devices", lambda: holder["devs"])

    def set_devs(devs):
        holder["devs"] = devs
    return set_devs


class TestAcceleratorTypeMap:
    def test_known_types(self):
        assert slice_type_from_accelerator("v5litepod-16") == "v5e-16"
        assert slice_type_from_accelerator("v5litepod-64") == "v5e-64"
        assert slice_type_from_accelerator("v4-8") == "v4-8"
        assert slice_type_from_accelerator("v5p-128") == "v5p-128"

    def test_unknown_types(self):
        assert slice_type_from_accelerator(None) is None
        assert slice_type_from_accelerator("") is None
        assert slice_type_from_accelerator("tpu7x-9000") is None
        assert slice_type_from_accelerator("v5litepod-12345") is None


class TestLocalDiscovery:
    def test_megacore_dedup_and_chip_local_index(self, fake_devices):
        """v4 megacore: 2 cores share one chip coord; TPU_VISIBLE_CHIPS
        indexes CHIPS, so local_index must count deduped chips."""
        fake_devices([
            FakeDev((0, 0, 0)), FakeDev((0, 0, 0)),   # chip 0, 2 cores
            FakeDev((1, 0, 0)), FakeDev((1, 0, 0)),   # chip 1
        ])
        adv = LibtpuBackend().discover()
        assert adv.num_chips == 2
        assert [c.local_index for c in adv.chips] == [0, 1]
        assert [c.coord for c in adv.chips] == [(0, 0, 0), (1, 0, 0)]

    def test_2d_coords_get_z0(self, fake_devices):
        fake_devices([FakeDev((0, 0)), FakeDev((0, 1)),
                      FakeDev((1, 0)), FakeDev((1, 1))])
        adv = LibtpuBackend().discover()
        assert {c.coord for c in adv.chips} == {
            (0, 0, 0), (0, 1, 0), (1, 0, 0), (1, 1, 0)}
        assert adv.mesh_shape == (2, 2, 1)

    def test_coords_normalized_to_origin(self, fake_devices):
        """A lone host deep inside a larger pod still forms a valid
        standalone mesh."""
        fake_devices([FakeDev((4, 6, 0)), FakeDev((5, 6, 0)),
                      FakeDev((4, 7, 0)), FakeDev((5, 7, 0))])
        adv = LibtpuBackend().discover()
        assert {c.coord for c in adv.chips} == {
            (0, 0, 0), (1, 0, 0), (0, 1, 0), (1, 1, 0)}
        assert adv.mesh_shape == (2, 2, 1)
        assert adv.host_block == (2, 2, 1)

    def test_hbm_from_memory_stats_with_fallback(self, fake_devices):
        fake_devices([
            FakeDev((0, 0, 0), stats={"bytes_limit": 32 * (1 << 30)}),
            FakeDev((1, 0, 0), stats=None),           # stats raise
            FakeDev((2, 0, 0), stats={}),             # no bytes_limit
        ])
        adv = LibtpuBackend().discover()
        assert [round(c.hbm_gib) for c in adv.chips] == [32, 16, 16]

    def test_no_tpus_raises(self, fake_devices):
        class Cpu:
            platform = "cpu"
        fake_devices([Cpu()])
        with pytest.raises(RuntimeError, match="no TPU devices"):
            LibtpuBackend().discover()

    def test_devices_without_coords_enumerate_linearly(self, fake_devices):
        class BareDev:
            platform = "tpu"
            process_index = 0
        fake_devices([BareDev(), BareDev()])
        adv = LibtpuBackend().discover()
        assert adv.num_chips == 2
        assert adv.mesh_shape == (2, 1, 1)


class TestHealthHooks:
    def test_unhealthy_chip_and_health_check(self, fake_devices):
        fake_devices([FakeDev((0, 0, 0)), FakeDev((1, 0, 0))])
        be = LibtpuBackend(health_check=lambda li, d: li != 1)
        adv = be.discover()
        assert [c.healthy for c in adv.chips] == [True, False]
        be.mark_chip_unhealthy(0)
        adv = be.discover()
        assert [c.healthy for c in adv.chips] == [False, False]
        be.heal_chip(0)
        assert [c.healthy for c in be.discover().chips] == [True, False]

    def test_bad_link_reported_when_incident(self, fake_devices):
        fake_devices([FakeDev((0, 0, 0)), FakeDev((1, 0, 0))])
        be = LibtpuBackend()
        be.report_bad_link((1, 0, 0), (2, 0, 0))   # incident to local
        be.report_bad_link((5, 5, 0), (6, 5, 0))   # remote: not ours
        adv = be.discover()
        assert adv.bad_links == (((1, 0, 0), (2, 0, 0)),)
        be.heal_link((1, 0, 0), (2, 0, 0))
        assert be.discover().bad_links == ()


class TestRegistryDiscovery:
    def _host_devs(self, host_id):
        """The 4 chips of v5e-16 host ``host_id`` (2x2 blocks tiling a
        4x4 mesh in row-major origin order)."""
        ox, oy = [(0, 0), (0, 2), (2, 0), (2, 2)][host_id]
        return [FakeDev((ox + dx, oy + dy), process_index=host_id)
                for dx in range(2) for dy in range(2)]

    def test_one_host_of_v5e16(self, fake_devices, monkeypatch):
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5litepod-16")
        monkeypatch.setenv("TPU_WORKER_ID", "2")
        fake_devices(self._host_devs(2))
        adv = LibtpuBackend(node_name="host-2").discover()
        assert adv.slice_type == "v5e-16"
        assert adv.host_id == 2
        assert adv.mesh_shape == (4, 4, 1)
        assert adv.host_block == (2, 2, 1)
        assert adv.slice_id == "v5e-16-slice"
        assert {c.coord for c in adv.chips} == {
            (2, 0, 0), (2, 1, 0), (3, 0, 0), (3, 1, 0)}

    def test_worker_id_mismatch_refused(self, fake_devices, monkeypatch):
        """Host 0's chips advertised as worker 3 would corrupt worker
        ordering — must raise, not advertise garbage."""
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5litepod-16")
        monkeypatch.setenv("TPU_WORKER_ID", "3")
        fake_devices(self._host_devs(0))
        with pytest.raises(ValueError, match="host_block tiling"):
            LibtpuBackend().discover()

    def test_worker_id_out_of_range_refused(self, fake_devices,
                                            monkeypatch):
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5litepod-16")
        monkeypatch.setenv("TPU_WORKER_ID", "9")
        fake_devices(self._host_devs(0))
        with pytest.raises(ValueError, match="out of range"):
            LibtpuBackend().discover()

    def test_four_hosts_assemble_into_v5e16_slice(self, fake_devices,
                                                  monkeypatch):
        """The multi-host path end-to-end: 4 per-host advertisements →
        one SliceState with the full 16-chip mesh (what VERDICT r1 #3
        said round 1 could not do)."""
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5litepod-16")
        advs = []
        for hid in range(4):
            monkeypatch.setenv("TPU_WORKER_ID", str(hid))
            fake_devices(self._host_devs(hid))
            advs.append(
                LibtpuBackend(node_name=f"host-{hid}").discover())
        assert len({a.slice_id for a in advs}) == 1
        st = SliceState.from_advertisements(advs)
        assert len(st.available) == 16
        assert st.spec.mesh_shape == (4, 4, 1)
        assert sorted(st.node_of_host) == [0, 1, 2, 3]
        # worker-identity wiring: host 2's chips really are host 2's
        assert st.topo.chip_at((2, 0, 0)).host_id == 2

    def test_unknown_accelerator_type_falls_back_local(self, fake_devices,
                                                       monkeypatch):
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "tpu9-weird")
        fake_devices([FakeDev((0, 0, 0))])
        adv = LibtpuBackend().discover()
        assert adv.slice_type == "local-1chip"

"""HBM-aware admission (VERDICT r1 #5): the per-chip memory capacity is
a scheduling dimension — a gang whose model doesn't fit a chip's HBM must
not schedule there (reference tracked per-device memory in its capacity
lists, SURVEY.md §3 core types)."""

from kubegpu_tpu.cluster import SimCluster, tpu_pod
from kubegpu_tpu.kubemeta import GangSpec, PodPhase
from kubegpu_tpu.kubemeta.objects import ResourceRequests


class TestHbmAdmission:
    def test_oversized_ask_unschedulable_on_small_hbm(self):
        """A 95 GiB/chip ask fits v5p (95 GiB chips) but must be
        unschedulable on v5e-16 (16 GiB chips)."""
        cl = SimCluster(["v5e-16"])
        cl.submit(tpu_pod("big", chips=4, hbm_gib=95.0, command=["x"]))
        result, _ = cl.step()
        assert "big" in result.unschedulable
        cl.close()

    def test_oversized_ask_lands_on_v5p(self):
        cl = SimCluster(["v5e-16", "v5p-128"])
        cl.submit(tpu_pod("big", chips=4, hbm_gib=95.0, command=["x"]))
        result, _ = cl.step()
        assert "big" in result.scheduled
        pod = cl.api.get("Pod", "big")
        assert pod.spec.node_name.startswith("v5p-128")
        cl.close()

    def test_small_ask_unconstrained(self):
        """No hbm_gib declared → schedules anywhere (back-compat)."""
        cl = SimCluster(["v5e-16"])
        cl.submit(tpu_pod("ok", chips=4, command=["x"]))
        result, _ = cl.step()
        assert "ok" in result.scheduled
        cl.close()

    def test_gang_hbm_floor_applies_to_every_member(self):
        cl = SimCluster(["v5e-16", "v5p-128"])
        cl.submit(*[
            tpu_pod(f"g-{i}", chips=4, hbm_gib=40.0,
                    gang=GangSpec(name="g", size=4, index=i),
                    command=["x"])
            for i in range(4)
        ])
        result, _ = cl.step()
        assert len(result.scheduled) == 4
        for i in range(4):
            pod = cl.api.get("Pod", f"g-{i}")
            assert pod.spec.node_name.startswith("v5p-128")
        cl.close()

    def test_fractional_ask_respects_hbm(self):
        cl = SimCluster(["v5e-16"])
        cl.submit(tpu_pod("frac", millitpu=500, hbm_gib=95.0,
                          command=["x"]))
        result, _ = cl.step()
        assert "frac" in result.unschedulable
        cl.submit(tpu_pod("frac-ok", millitpu=500, hbm_gib=8.0,
                          command=["x"]))
        result, _ = cl.step()
        assert "frac-ok" in result.scheduled
        cl.close()

    def test_hbm_survives_resource_dict_roundtrip(self):
        r = ResourceRequests(tpu_chips=2, hbm_gib=24.5)
        assert ResourceRequests.from_dict(r.to_dict()) == r

    def test_preemption_only_frees_chips_that_help(self):
        """A high-priority 95 GiB ask on a v5e-only cluster must stay
        unschedulable WITHOUT evicting the low-priority tenant — no chip
        in the cluster can ever satisfy it, so eviction buys nothing."""
        cl = SimCluster(["v5e-16"])
        cl.submit(*[
            tpu_pod(f"low-{i}", chips=4,
                    gang=GangSpec(name="low", size=4, index=i),
                    command=["x"], priority=0)
            for i in range(4)
        ])
        result, _ = cl.step()
        assert len(result.scheduled) == 4
        cl.submit(tpu_pod("big", chips=4, hbm_gib=95.0, command=["x"],
                          priority=10))
        result, _ = cl.step()
        assert "big" in result.unschedulable
        for i in range(4):
            low = cl.api.get("Pod", f"low-{i}")
            assert low.status.phase != PodPhase.PENDING  # not thrash-evicted
        cl.close()

"""Shared finding model + report rendering for the KTP-Audit passes.

Both prongs (the AST lint engine and the jaxpr/HLO auditor) reduce to
a flat list of :class:`Finding`; the CLI renders them as a human
report (one ``CODE path:line message`` row per finding, grouped by
rule) or a JSON document, and exits nonzero iff any finding survived
the blessed-site allowlist.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field


@dataclass
class Finding:
    """One rule violation.

    ``code``    stable rule id (``KTP001``.. for lints, ``JXA00x`` for
                jaxpr-audit findings, ``CEN001`` for the compile census)
    ``path``    repo-relative file (lints) or ``<executable>`` (audit)
    ``line``    1-indexed line, 0 when the finding has no source anchor
    ``message`` human sentence; carries the offending shape diff for
                census findings
    ``blessed`` True when an allowlist entry (TOML or inline comment)
                covers the site — blessed findings are reported in the
                JSON document but never fail the run
    """

    code: str
    path: str
    line: int
    message: str
    blessed: bool = False
    reason: str = ""   # blessing reason, when blessed

    def key(self) -> tuple:
        return (self.code, self.path, self.line)


@dataclass
class Report:
    """Aggregated result of one analysis run."""

    findings: list[Finding] = field(default_factory=list)
    # pass name → summary payload (census signature sets, executable
    # walk stats, ...) carried into the JSON document
    summaries: dict = field(default_factory=dict)

    def extend(self, fs) -> None:
        self.findings.extend(fs)

    @property
    def violations(self) -> list[Finding]:
        return [f for f in self.findings if not f.blessed]

    @property
    def blessed(self) -> list[Finding]:
        return [f for f in self.findings if f.blessed]

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_doc(self) -> dict:
        return {
            "ok": self.ok,
            "violations": [asdict(f) for f in self.violations],
            "blessed": [asdict(f) for f in self.blessed],
            "summaries": self.summaries,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_doc(), indent=2, sort_keys=True)

    def render(self) -> str:
        """Human report: violations grouped by rule code, blessed
        sites as a one-line tally."""
        lines: list[str] = []
        by_code: dict[str, list[Finding]] = {}
        for f in self.violations:
            by_code.setdefault(f.code, []).append(f)
        for code in sorted(by_code):
            for f in sorted(by_code[code], key=lambda f: f.key()):
                loc = f"{f.path}:{f.line}" if f.line else f.path
                lines.append(f"{code} {loc}  {f.message}")
        if self.blessed:
            lines.append(
                f"[blessed] {len(self.blessed)} allowlisted site(s) "
                "suppressed (see --json for the list)")
        for name, summary in sorted(self.summaries.items()):
            brief = summary.get("brief") if isinstance(summary, dict) \
                else None
            if brief:
                lines.append(f"[{name}] {brief}")
        lines.append("ANALYSIS " + ("CLEAN" if self.ok else
                                    f"FAILED ({len(self.violations)} "
                                    "violation(s))"))
        return "\n".join(lines)

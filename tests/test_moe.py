"""MoE model tests: routing algebra invariants, forward/causality,
training, and expert-parallel (ep) sharded execution on the 8-device CPU
mesh — the ep leg of the driver's multi-chip dryrun."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from kubegpu_tpu.models import (
    MoEConfig, moe_forward, moe_init, moe_param_specs,
)
from kubegpu_tpu.models.moe import (
    make_moe_train_step, moe_next_token_loss, route_tokens,
)
from kubegpu_tpu.parallel import make_mesh, named_sharding_tree


@pytest.fixture(scope="module")
def tiny():
    cfg = MoEConfig.tiny()
    params = moe_init(jax.random.PRNGKey(0), cfg)
    return cfg, params


class TestRouting:
    def _random_logits(self, g=2, t=16, e=4, seed=0):
        return jax.random.normal(jax.random.PRNGKey(seed), (g, t, e))

    def test_dispatch_is_valid_onehot(self):
        logits = self._random_logits()
        cap = 16  # ample: nothing dropped
        dispatch, combine, _ = route_tokens(logits, top_k=2, capacity=cap)
        d = np.asarray(dispatch)
        # each token occupies exactly top_k slots
        np.testing.assert_allclose(d.sum(axis=(2, 3)), 2.0)
        # each (expert, slot) holds at most one token
        assert d.sum(axis=1).max() <= 1.0 + 1e-6

    def test_combine_weights_normalized(self):
        logits = self._random_logits(seed=3)
        _, combine, _ = route_tokens(logits, top_k=2, capacity=16)
        c = np.asarray(combine).sum(axis=(2, 3))
        np.testing.assert_allclose(c, 1.0, atol=1e-5)

    def test_capacity_drops_overflow(self):
        # all tokens prefer expert 0 → only `cap` survive per group
        logits = jnp.zeros((1, 8, 4)).at[:, :, 0].set(10.0)
        dispatch, _, _ = route_tokens(logits, top_k=1, capacity=3)
        d = np.asarray(dispatch)
        assert d.sum() == 3.0                    # 3 kept of 8
        assert d[0, :, 0].sum() == 3.0           # all on expert 0
        # kept tokens are the earliest by position (GShard convention)
        assert d[0, :3].sum() == 3.0

    def test_aux_loss_uniform_is_one(self):
        # perfectly uniform router → aux loss == 1 (its minimum)
        logits = jnp.zeros((2, 32, 4))
        _, _, aux = route_tokens(logits, top_k=2, capacity=32)
        assert abs(float(aux) - 1.0) < 1e-5

    def test_aux_loss_collapsed_is_high(self):
        logits = jnp.zeros((2, 32, 4)).at[:, :, 1].set(20.0)
        _, _, aux = route_tokens(logits, top_k=2, capacity=32)
        assert float(aux) > 3.5  # collapse → ≈ E


class TestForward:
    def test_shapes(self, tiny):
        cfg, params = tiny
        tokens = jnp.zeros((2, 16), jnp.int32)
        logits, aux = moe_forward(params, tokens, cfg)
        assert logits.shape == (2, 16, cfg.base.vocab_size)
        assert logits.dtype == jnp.float32
        assert np.isfinite(float(aux))

    def test_causality(self, tiny):
        # capacity_factor = E/top_k guarantees zero drops (each token
        # assigns to an expert at most once, so per-expert load <= T);
        # with drops possible, capacity contention is non-causal — the
        # standard GShard/Switch training behavior.
        cfg = MoEConfig.tiny(capacity_factor=2.0)
        _, params = tiny
        key = jax.random.PRNGKey(1)
        tok1 = jax.random.randint(key, (1, 16), 0, cfg.base.vocab_size)
        tok2 = tok1.at[0, 12:].set(5)
        l1, _ = moe_forward(params, tok1, cfg)
        l2, _ = moe_forward(params, tok2, cfg)
        np.testing.assert_allclose(np.asarray(l1[0, :12]),
                                   np.asarray(l2[0, :12]), atol=1e-5)

    def test_loss_decreases(self, tiny):
        cfg, params = tiny
        opt = optax.adam(1e-2)
        step = jax.jit(make_moe_train_step(cfg, opt))
        opt_state = opt.init(params)
        tokens = (jnp.arange(64, dtype=jnp.int32).reshape(2, 32) * 3
                  ) % cfg.base.vocab_size
        first = None
        for _ in range(10):
            params, opt_state, loss = step(params, opt_state, tokens)
            first = first if first is not None else float(loss)
        assert float(loss) < first


class TestExpertParallel:
    def test_ep_sharded_forward_matches_single(self, tiny):
        """dp2 × ep4 over 8 CPU devices: same numbers as unsharded."""
        cfg, params = tiny
        mesh = make_mesh({"dp": 2, "ep": 4})
        specs = named_sharding_tree(mesh, moe_param_specs(cfg))
        sharded = jax.device_put(params, specs)
        tokens = (jnp.arange(64, dtype=jnp.int32).reshape(4, 16) * 5
                  ) % cfg.base.vocab_size
        tokens_s = jax.device_put(
            tokens, NamedSharding(mesh, P(("dp",), None)))
        ref, aux_ref = moe_forward(params, tokens, cfg)
        out, aux = jax.jit(
            lambda p, t: moe_forward(p, t, cfg, mesh))(sharded, tokens_s)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=3e-4, rtol=3e-4)
        assert abs(float(aux) - float(aux_ref)) < 1e-4

    def test_ep_tp_train_step(self, tiny):
        """Full train step on dp2 × ep2 × tp2 executes, finite loss."""
        cfg, _ = tiny
        mesh = make_mesh({"dp": 2, "ep": 2, "tp": 2})
        params = moe_init(jax.random.PRNGKey(0), cfg)
        specs = named_sharding_tree(mesh, moe_param_specs(cfg))
        params = jax.device_put(params, specs)
        opt = optax.adamw(1e-3)
        opt_state = opt.init(params)
        step = jax.jit(make_moe_train_step(cfg, opt, mesh),
                       donate_argnums=(0, 1))
        tokens = (jnp.arange(4 * 17, dtype=jnp.int32).reshape(4, 17)
                  ) % cfg.base.vocab_size
        tokens = jax.device_put(
            tokens, NamedSharding(mesh, P(("dp",), None)))
        params, opt_state, loss = step(params, opt_state, tokens)
        assert np.isfinite(float(loss))

    def test_loss_agrees_across_shardings(self, tiny):
        cfg, params = tiny
        mesh = make_mesh({"dp": 2, "ep": 4})
        tokens = (jnp.arange(4 * 16, dtype=jnp.int32).reshape(4, 16)
                  ) % cfg.base.vocab_size
        ref = moe_next_token_loss(params, tokens, cfg)
        specs = named_sharding_tree(mesh, moe_param_specs(cfg))
        sharded = jax.device_put(params, specs)
        out = jax.jit(
            lambda p, t: moe_next_token_loss(p, t, cfg, mesh))(
                sharded, tokens)
        assert abs(float(out) - float(ref)) < 1e-3


class TestMoEServing:
    """KV-cache decode with routed experts (the ffn hook into
    decode._forward_with_cache)."""

    def _setup(self):
        from kubegpu_tpu.models.moe import MoEConfig, moe_init
        # capacity_factor high enough that NO token is ever dropped:
        # capacity drops depend on the routing GROUP (full sequence in
        # training vs one step in decode), so exact parity between the
        # two only holds in the no-drop regime — which is also how MoE
        # serving is run in practice (dropping at inference is lossy)
        cfg = MoEConfig.tiny(n_experts=4, top_k=2, n_layers=2,
                             n_heads=4, n_kv_heads=2, max_seq_len=64,
                             capacity_factor=8.0)
        params = moe_init(jax.random.PRNGKey(0), cfg)
        return cfg, params

    def test_decode_matches_forward(self):
        """Prefill + stepwise decode must reproduce moe_forward logits
        at every position (the same parity contract the Llama decode
        path has)."""
        from kubegpu_tpu.models.moe import (
            moe_decode_step, moe_forward, moe_prefill,
        )
        cfg, params = self._setup()
        seq = (jnp.arange(10, dtype=jnp.int32)[None, :] * 5
               ) % cfg.base.vocab_size
        ref, _ = moe_forward(params, seq, cfg)
        logits, cache = moe_prefill(params, seq[:, :4], cfg)
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(ref[:, 3]),
                                   atol=3e-4, rtol=3e-4)
        for pos in range(4, 10):
            logits, cache = moe_decode_step(params, cache, seq[:, pos],
                                            pos, cfg)
            np.testing.assert_allclose(
                np.asarray(logits), np.asarray(ref[:, pos]),
                atol=5e-4, rtol=5e-4, err_msg=f"position {pos}")

    def test_greedy_generate_matches_naive(self):
        from kubegpu_tpu.models.moe import moe_forward, moe_greedy_generate
        cfg, params = self._setup()
        prompt = (jnp.arange(2 * 5, dtype=jnp.int32).reshape(2, 5) * 3
                  ) % cfg.base.vocab_size
        n = 5
        got = moe_greedy_generate(params, prompt, n, cfg)
        seq = prompt
        for _ in range(n):
            logits, _ = moe_forward(params, seq, cfg)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(seq.dtype)
            seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(seq[:, 5:]))

    def test_kv_int8_runs(self):
        from kubegpu_tpu.models.moe import moe_greedy_generate
        cfg, params = self._setup()
        prompt = (jnp.arange(2 * 5, dtype=jnp.int32).reshape(2, 5)
                  ) % cfg.base.vocab_size
        out = moe_greedy_generate(params, prompt, 3, cfg, kv_int8=True)
        assert out.shape == (2, 3)

"""Logical-device ordering: map allocated chips to mesh positions.

Placement alone doesn't determine collective bandwidth — the *order* in
which chips are assigned to logical mesh coordinates does (SURVEY.md §8
"Worker identity wiring": ordering must match mesh coords or pjit layouts
silently degrade).  This module picks, for a placement and a workload's
logical axes, the chip order that maximizes weighted ring locality; it is
KubeTPU's counterpart of ``jax.experimental.mesh_utils.create_device_mesh``
run at *schedule time*, so TPU_WORKER_ID assignment already reflects it.

Strategies tried (cheap, exact evaluation over each):
- grid: logical axes mapped straight onto physical axes (row-major)
- snake folds: fold one logical axis through two or more physical rows so
  its ring closes into a physical cycle even on unwrapped meshes
All candidates are scored with the same honest traffic model the scheduler
reports, and the argmax wins.
"""

from __future__ import annotations

import functools
import itertools
import os

from kubegpu_tpu.topology.locality import (
    TrafficModel,
    ici_locality,
    resolve_axis_weights,
    traffic_pairs_for_mesh_axes,
)
from kubegpu_tpu.topology.mesh import Coord, TpuTopology
from kubegpu_tpu.topology.slices import Placement


_eval_order_memo: dict = {}


def evaluate_order(
    topo: TpuTopology,
    order: list[Coord],
    axes: dict[str, int],
    axis_weights: dict[str, float] | None = None,
    bad_links: set[tuple[Coord, Coord]] | None = None,
) -> float:
    """Weighted ICI locality of a candidate logical order.

    ``bad_links`` (failed ICI links) force the slow Python path — faults
    are rare, and correctness of avoiding a dead link beats the native
    fast path's speed.  The fault-free path is pure geometry and
    memoized (same orders recur across slices and passes); the
    native-path flag keys the memo so parity tests compare real runs.
    """
    from kubegpu_tpu.allocator import _native

    axis_weights = resolve_axis_weights(axes, axis_weights)
    if not bad_links:
        key = (topo.spec.name, topo.spec.mesh_shape, topo.spec.wrap,
               tuple(order), tuple(axes.items()),
               tuple(sorted(axis_weights.items())),
               bool(os.environ.get("KUBETPU_NO_NATIVE")))
        hit = _eval_order_memo.get(key)
        if hit is not None:
            return hit
        native = _native.eval_order_native(topo, order, axes, axis_weights)
        if native is None:
            native = ici_locality(
                topo, traffic_pairs_for_mesh_axes(order, axes,
                                                  axis_weights))
        if len(_eval_order_memo) >= 16384:
            _eval_order_memo.clear()
        _eval_order_memo[key] = native
        return native
    tm = traffic_pairs_for_mesh_axes(order, axes, axis_weights)
    return ici_locality(topo, tm, bad_links)


def _grid_orders(placement: Placement) -> list[list[Coord]]:
    """Row-major orders over each permutation of the placement's axes."""
    sx, sy, sz = placement.shape
    ox, oy, oz = placement.origin
    coords = placement.coords  # row-major (z fastest) already
    orders = []
    dims = [sx, sy, sz]
    for perm in set(itertools.permutations((0, 1, 2))):
        order = []
        ranges = [range(dims[perm[0]]), range(dims[perm[1]]),
                  range(dims[perm[2]])]
        for i in ranges[0]:
            for j in ranges[1]:
                for k in ranges[2]:
                    off = [0, 0, 0]
                    off[perm[0]], off[perm[1]], off[perm[2]] = i, j, k
                    order.append(coords[
                        off[0] * sy * sz + off[1] * sz + off[2]])
        orders.append(order)
    return orders


def _snake_orders(placement: Placement) -> list[list[Coord]]:
    """Boustrophedon folds: reverse every other row along one axis so
    consecutive logical indices stay physically adjacent, and the full
    sequence forms a closed cycle when the folded axis has even length."""
    sx, sy, sz = placement.shape
    coords = placement.coords
    orders = []
    if sz == 1:  # 2D cases (v5e): snake over x with rows of y, and transpose
        grid = [[coords[x * sy * sz + y * sz] for y in range(sy)]
                for x in range(sx)]
        snake_xy = []
        for x in range(sx):
            row = grid[x] if x % 2 == 0 else list(reversed(grid[x]))
            snake_xy.extend(row)
        orders.append(snake_xy)
        snake_yx = []
        for y in range(sy):
            col = [grid[x][y] for x in range(sx)]
            if y % 2 == 1:
                col.reverse()
            snake_yx.extend(col)
        orders.append(snake_yx)
    return orders


def _closed_cycle_orders(placement: Placement) -> list[list[Coord]]:
    """Hamiltonian *cycles* over 2D placements (exists when either
    dimension is even): boustrophedon through columns 1..n-1 then return up
    column 0.  Closes the all-chips ring (pure-DP default) at 100% ICI
    locality even on unwrapped meshes — a snake alone leaves the wrap pair
    several hops apart."""
    sx, sy, sz = placement.shape
    if sz != 1:
        return []
    coords = placement.coords

    def at(x: int, y: int) -> Coord:
        return coords[x * sy + y]

    orders = []
    if sx >= 2 and sy >= 2 and sx % 2 == 0:
        # rows 0..sx-1 snake within columns 1..sy-1, return up column 0
        o = [at(0, y) for y in range(sy)]  # row 0: col 0..sy-1
        for x in range(1, sx):
            ys = range(sy - 1, 0, -1) if x % 2 == 1 else range(1, sy)
            o.extend(at(x, y) for y in ys)
        o.extend(at(x, 0) for x in range(sx - 1, 0, -1))
        orders.append(o)
    if sx >= 2 and sy >= 2 and sy % 2 == 0:  # transpose variant
        o = [at(x, 0) for x in range(sx)]
        for y in range(1, sy):
            xs = range(sx - 1, 0, -1) if y % 2 == 1 else range(1, sx)
            o.extend(at(x, y) for x in xs)
        o.extend(at(0, y) for y in range(sy - 1, 0, -1))
        orders.append(o)
    return orders


@functools.lru_cache(maxsize=4096)
def candidate_orders(placement: Placement) -> list[list[Coord]]:
    """Pure geometry of a (frozen, hashable) placement — memoized because
    the same placements recur across slices and scheduling passes.
    Callers must not mutate the returned orders."""
    seen: set[tuple] = set()
    out: list[list[Coord]] = []
    for o in (_grid_orders(placement) + _snake_orders(placement)
              + _closed_cycle_orders(placement)):
        key = tuple(o)
        if key not in seen:
            seen.add(key)
            out.append(o)
    return out


def best_logical_order(
    topo: TpuTopology,
    placement: Placement,
    axes: dict[str, int] | None,
    axis_weights: dict[str, float] | None = None,
) -> tuple[list[Coord], float]:
    """Best (order, locality) for the placement under the workload's axes.

    With no declared axes, models the default: one allreduce ring over all
    chips (pure DP), which snake orders close into a physical cycle.
    """
    if axes is None:
        axes = {"dp": placement.num_chips}
    best, best_score = None, -1.0
    for order in candidate_orders(placement):
        s = evaluate_order(topo, order, axes, axis_weights)
        if s > best_score:
            best, best_score = order, s
    assert best is not None
    return best, best_score

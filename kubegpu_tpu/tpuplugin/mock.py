"""Mock backend: deterministic coordinate tables for tests/simulation.

First-class citizen by design (SURVEY.md §5: the reference's NVML paths had
no automated coverage because they needed real GPUs — a gap this closes).
Ships the v4-8 / v5e-16 / v5e-64 tables BASELINE.json's configs need.
"""

from __future__ import annotations

from kubegpu_tpu.topology.mesh import TOPOLOGY_REGISTRY, TpuTopology
from kubegpu_tpu.tpuplugin.backend import (
    MILLICHIPS_PER_CHIP,
    ChipAdvertisement,
    DeviceBackend,
    NodeAdvertisement,
)


class MockBackend(DeviceBackend):
    """Pretends to be host ``host_id`` of a ``slice_type`` slice.

    Carries mutable fault state (bad chips / bad incident ICI links) so
    tests and the SimCluster can inject faults mid-run and the advertiser
    re-enumeration picks them up — the fault-injection hooks SURVEY.md §6
    calls for (kill a chip, flap a link) driving recovery tests.
    """

    def __init__(self, slice_type: str, host_id: int = 0,
                 slice_id: str | None = None, node_name: str | None = None,
                 unhealthy_chips: set[int] | None = None):
        if slice_type not in TOPOLOGY_REGISTRY:
            raise KeyError(f"unknown slice type {slice_type!r}")
        self.spec = TOPOLOGY_REGISTRY[slice_type]
        if not 0 <= host_id < self.spec.num_hosts:
            raise ValueError(
                f"host_id {host_id} out of range for {slice_type} "
                f"({self.spec.num_hosts} hosts)")
        self.slice_type = slice_type
        self.host_id = host_id
        self.slice_id = slice_id or f"{slice_type}-slice-0"
        self.node_name = node_name or f"{self.slice_id}-host-{host_id}"
        self.unhealthy_chips: set[int] = set(unhealthy_chips or set())
        self.bad_links: set[tuple] = set()   # normalized coord pairs
        self.topo = TpuTopology.build(self.spec)

    # -- fault injection (mutable health state) -------------------------

    def _local_coords(self) -> set:
        host = self.topo.hosts[self.host_id]
        return {self.topo.chips[i].coord for i in host.chip_indices}

    def fail_chip(self, local_index: int) -> None:
        if not 0 <= local_index < self.spec.chips_per_host:
            raise ValueError(f"no local chip {local_index}")
        self.unhealthy_chips.add(local_index)

    def heal_chip(self, local_index: int) -> None:
        self.unhealthy_chips.discard(local_index)

    def fail_link(self, a, b) -> bool:
        """Mark the ICI link a↔b bad if one endpoint is local; returns
        whether this host owns (and therefore advertises) the link."""
        a, b = tuple(a), tuple(b)
        if not self.topo.are_ici_adjacent(a, b):
            raise ValueError(f"{a}–{b} is not an ICI link")
        if not ({a, b} & self._local_coords()):
            return False
        self.bad_links.add((min(a, b), max(a, b)))
        return True

    def heal_link(self, a, b) -> None:
        a, b = tuple(a), tuple(b)
        self.bad_links.discard((min(a, b), max(a, b)))

    def discover(self) -> NodeAdvertisement:
        topo = self.topo
        host = topo.hosts[self.host_id]
        chips = tuple(
            ChipAdvertisement(
                coord=topo.chips[idx].coord,
                local_index=li,
                millichips=MILLICHIPS_PER_CHIP,
                hbm_gib=self.spec.hbm_gib_per_chip,
                healthy=li not in self.unhealthy_chips,
            )
            for li, idx in enumerate(host.chip_indices)
        )
        return NodeAdvertisement(
            node_name=self.node_name,
            slice_id=self.slice_id,
            slice_type=self.slice_type,
            host_id=self.host_id,
            mesh_shape=self.spec.mesh_shape,
            wrap=self.spec.wrap,
            host_block=self.spec.host_block,
            chips=chips,
            bad_links=tuple(sorted(self.bad_links)),
        )

    def allocate_env(self, chips, worker_id, num_workers,
                     coordinator_address, worker_hostnames):
        return build_tpu_env(self.spec.host_block, chips, worker_id,
                             num_workers, coordinator_address,
                             worker_hostnames)


def build_tpu_env(host_block, chips, worker_id, num_workers,
                  coordinator_address, worker_hostnames) -> dict[str, str]:
    """The injection payload — reference parity: the crishim's env rewrite
    set ``NVIDIA_VISIBLE_DEVICES=<uuids>`` (SURVEY.md §4.3); the TPU
    translation sets chip visibility + worker identity + the coordinator
    bootstrap ``jax.distributed.initialize`` consumes.
    """
    hb = host_block
    return {
        "TPU_VISIBLE_CHIPS": ",".join(str(c.local_index) for c in chips),
        "TPU_WORKER_ID": str(worker_id),
        "TPU_WORKER_HOSTNAMES": ",".join(worker_hostnames),
        "TPU_CHIPS_PER_HOST_BOUNDS": f"{hb[0]},{hb[1]},{hb[2]}",
        "JAX_COORDINATOR_ADDRESS": coordinator_address,
        "JAX_NUM_PROCESSES": str(num_workers),
        "JAX_PROCESS_ID": str(worker_id),
    }


def mock_cluster(slice_types: list[str]) -> list[MockBackend]:
    """One backend per host for a cluster of slices.

    ``mock_cluster(["v5e-16", "v4-8"])`` → 4 + 1 = 5 node backends, each
    slice getting a distinct ``slice_id``.
    """
    backends: list[MockBackend] = []
    for i, st in enumerate(slice_types):
        spec = TOPOLOGY_REGISTRY[st]
        slice_id = f"{st}-slice-{i}"
        for hid in range(spec.num_hosts):
            backends.append(MockBackend(st, host_id=hid, slice_id=slice_id))
    return backends

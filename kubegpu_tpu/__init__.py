"""KubeTPU — a TPU-native cluster scheduling & runtime-injection framework.

Reimplements the capability surface of Microsoft/KubeGPU (reference:
Bhaskers-Blu-Org2/KubeGPU — a Go k8s extension stack for topology-aware GPU
scheduling; see SURVEY.md for the full structural analysis) as an idiomatic
TPU-first design:

- ``topology``  — explicit ICI torus-mesh model (reference: the hierarchical
  ``gpugrpN/...`` grouped-resource tree, SURVEY.md §3 "Core types").
- ``tpuplugin`` — chip enumeration / advertisement backends (reference:
  ``plugins/nvidiagpuplugin``, NVML-backed, SURVEY.md §3).
- ``allocator`` — gang/contiguous-slice allocator (reference: ``grpalloc`` +
  ``plugins/gpuschedulerplugin``, SURVEY.md §3).
- ``scheduler`` — extender-shaped filter/prioritize/bind service (reference:
  ``device-scheduler``, SURVEY.md §3).
- ``kubemeta``  — annotation codec + fake control plane (reference:
  ``kubeinterface`` + the k8s apiserver, SURVEY.md §3).
- ``crishim``   — runtime-injection layer (reference: ``crishim``, which
  injected ``NVIDIA_VISIBLE_DEVICES``; here ``TPU_VISIBLE_CHIPS`` /
  ``TPU_WORKER_ID``, SURVEY.md §4.3).
- ``models`` / ``parallel`` / ``ops`` / ``workloads`` — the JAX/XLA workload
  layer exercising the full path (reference: ``example/`` pod specs).
"""

__version__ = "0.1.0"

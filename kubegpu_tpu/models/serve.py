"""Continuous batching — arrival-driven serving over the cached forward.

The r2 serving stack is batch-static: every sequence in a
``greedy_generate`` call starts and ends together.  Real serving is
arrival-driven; the structural piece this module adds (VERDICT r2 next
item #7) is the SLOT engine:

- the KV cache is ``n_slots`` independent batch rows with PER-SLOT
  positions — a slot is admitted, decodes, retires, and is re-admitted
  without disturbing its neighbors;
- an arriving request is prefilled at batch 1 (prompt right-padded to a
  compile bucket) and its K/V panel is scattered into a free slot's
  rows — admission never re-traces the decode executable;
- decode advances ALL slots in one executable with per-row positions:
  rope takes a [B, 1] position matrix, the cache write is a vmapped
  ``dynamic_update_slice`` (one row offset per slot, lowered to a
  scatter), and the causal/unwritten mask compares each row's own
  position;
- host interaction is STRIDE-amortized: ``lax.scan`` runs N decode
  steps per dispatch and the host fetches one [stride, B] token block
  — under the async TPU tunnel a per-step fetch costs ~100× the step
  itself (the r2 speculative host loop measured exactly that), and
  even locally it serializes dispatch.  Admission/retirement granularity
  is the stride.

Correctness contract: slots are independent batch rows — a request's
attention/FFN math never mixes with its neighbors'.  Tokens are
bit-identical to a solo ``greedy_generate`` at the tested
configurations (f32, small slot counts, asserted with staggered
arrivals); at other batch sizes XLA may choose different reduction
orders, which can flip a near-degenerate argmax tie (observed once at
n_slots=4 on an untrained f32 model — the same chunked-vs-stepwise
caveat spec decoding documents).  Right-pad garbage is never
attended: pad rows sit at positions ≥ the row's true length, the
per-row mask hides ``k_pos > q_pos``, and generation overwrites each
row before its position becomes visible (the same
overwrite-before-attend invariant the speculative verifier relies
on).
"""

from __future__ import annotations

import functools
from collections import deque
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kubegpu_tpu.models.decode import (
    _attn_finish,
    _dense_ffn,
    _project_qkv,
    init_kv_cache,
)
from kubegpu_tpu.models.llama import LlamaConfig, _rmsnorm
from kubegpu_tpu.ops.flash_attention import NEG_INF


# ---------------------------------------------------------------------------
# Per-row-position forward (the continuous-batching decode step)
# ---------------------------------------------------------------------------

def _attend_rows_buffered(q: jax.Array, ck: jax.Array, cv: jax.Array,
                          bk: jax.Array, bv: jax.Array,
                          flush_pos: jax.Array, j: jax.Array) -> jax.Array:
    """Grouped cached attention with PER-ROW positions over a dense
    cache PLUS the in-block write buffer.

    q: [B, Hq, 1, D]; cache [B, Hkv, S, D], valid where
    ``k_pos < flush_pos[b]`` (everything flushed before this block);
    buffer [B, Hkv, stride, D] holding this block's keys, valid at
    buffer index ``j' <= j`` (the SHARED in-block step — buffer entry
    j' is row b's logical position ``flush_pos[b] + j'``).  Softmax is
    permutation-invariant over the key set, so splitting the keys
    between cache and buffer changes nothing semantically; the point is
    that buffer writes land at the shared index j (one
    dynamic_update_slice, no scatter)."""
    b, hq, t, d = q.shape
    hkv, s = ck.shape[1], ck.shape[2]
    stride = bk.shape[2]
    qg = q.reshape(b, hkv, hq // hkv, t, d)
    scale = d ** -0.5
    sc = jnp.einsum("bkgtd,bksd->bkgts", qg, ck,
                    preferred_element_type=jnp.float32)
    sb = jnp.einsum("bkgtd,bksd->bkgts", qg, bk,
                    preferred_element_type=jnp.float32)
    scores = jnp.concatenate([sc, sb], axis=-1) * scale
    k_pos = jnp.arange(s)
    mask = jnp.concatenate(
        [k_pos[None, :] < flush_pos[:, None],              # [B, S]
         jnp.broadcast_to(jnp.arange(stride)[None, :] <= j,
                          (b, stride))], axis=-1)
    scores = jnp.where(mask[:, None, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = (jnp.einsum("bkgts,bksd->bkgtd", probs[..., :s], cv,
                      preferred_element_type=jnp.float32)
           + jnp.einsum("bkgts,bksd->bkgtd", probs[..., s:], bv,
                        preferred_element_type=jnp.float32))
    return out.reshape(b, hq, t, d).astype(q.dtype)


def _row_step_buffered(params: dict, tokens: jax.Array, cache: dict,
                       buf: dict, flush_pos: jax.Array, pos: jax.Array,
                       j: jax.Array, cfg: LlamaConfig
                       ) -> tuple[jax.Array, dict]:
    """One decode step for every slot at its OWN position, writing new
    K/V into the block buffer at the SHARED index ``j`` instead of
    scattering into the cache at per-row offsets.

    The r3 engine's vmapped per-slot ``dynamic_update_slice`` lowered
    to a scatter that cost 21% of the step (1.56 vs 1.23 ms measured,
    BASELINE.md r3); the buffer write is a plain shared-offset update,
    and the scatter happens ONCE per stride-block at flush time.
    tokens: [B]; pos: [B] each row's global position (rope);
    flush_pos: [B] positions at block start (cache validity).
    Returns (next-token logits [B, V] f32, updated buffer)."""
    x = jnp.take(params["embed"], tokens, axis=0)[:, None, :]   # [B,1,D]
    positions = pos[:, None]                                    # [B,1]

    def layer(x, xs):
        lp, ck, cv, bk, bv = xs
        h = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q, k, v = _project_qkv(h, lp, cfg, positions)   # [B,H,1,D]
        bk = lax.dynamic_update_slice(bk, k.astype(bk.dtype),
                                      (0, 0, j, 0))
        bv = lax.dynamic_update_slice(bv, v.astype(bv.dtype),
                                      (0, 0, j, 0))
        o = _attend_rows_buffered(q, ck, cv, bk, bv, flush_pos, j)
        return _attn_finish(
            x, o, lp, cfg,
            lambda x_, lp_: _dense_ffn(x_, lp_, cfg)), (bk, bv)

    x, (bk_new, bv_new) = lax.scan(
        layer, x, (params["layers"], cache["k"], cache["v"],
                   buf["k"], buf["v"]))
    x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits[:, 0], {"k": bk_new, "v": bv_new}


def _flush_buffer(cache: dict, buf: dict, flush_pos: jax.Array) -> dict:
    """Scatter the block buffer into the dense cache — the ONE per-row
    write of a stride-block.  cache [L, B, Hkv, S, D]; buf
    [L, B, Hkv, stride, D]; row b's segment lands at ``flush_pos[b]``."""

    def write_seg(c, seg, p):     # [Hkv, S, D] ← [Hkv, stride, D] at p
        return lax.dynamic_update_slice(c, seg.astype(c.dtype),
                                        (0, p, 0))

    write = jax.vmap(jax.vmap(write_seg, in_axes=(0, 0, 0)),
                     in_axes=(0, 0, None))          # over L, then B
    return {"k": write(cache["k"], buf["k"], flush_pos),
            "v": write(cache["v"], buf["v"], flush_pos)}


@functools.lru_cache(maxsize=32)
def _engine_fns(cfg: LlamaConfig, n_slots: int, max_len: int,
                stride: int, top_k: int = 0, sampling: bool = False):
    """Jitted engine pieces, cached per static signature.  ``top_k``
    is the engine-wide truncation for sampled slots (static: per-slot
    k would be shape-dynamic); per-REQUEST temperature rides a [B]
    vector — 0 means greedy for that slot.  ``sampling`` is STATIC:
    a greedy-only engine traces pure argmax steps — temps is a
    runtime input, so XLA could never dead-code the full-vocab
    categorical draw out of the hot scan on its own."""

    def _pick(logits, temps, k_):
        """Per-slot token selection: greedy where temps == 0, else the
        shared :func:`decode._sample_token` draw (temperature-scaled,
        top-k-truncated) — the truncation math exists exactly once;
        only the per-row greedy/sampled blend is this engine's."""
        greedy = jnp.argmax(logits, axis=-1)
        if not sampling:
            return greedy
        from kubegpu_tpu.models.decode import _sample_token
        sampled = _sample_token(logits, k_, temps[:, None],
                                jnp.float32(1.0), top_k, nucleus=False)
        return jnp.where(temps > 0, sampled, greedy)

    @jax.jit
    def decode_block(params, cache, tokens, pos, active, temps,
                     base_key, tick):
        """``stride`` decode steps for all slots in ONE dispatch.
        Per-slot greedy/sampled feedback; inactive slots hold position
        (their garbage output is never emitted and their rows never
        advance).  New K/V rides the write buffer at the shared step
        index and is flushed to the cache once at block end — the
        per-row scatter is paid 1/stride as often as the r3 engine
        paid it.  The tick folds into the key INSIDE the jit (an
        eager fold_in would cost dispatches on an engine built to
        avoid them).  Returns (token block [stride, B], last tokens,
        pos', cache)."""
        keys = jax.random.split(
            jax.random.fold_in(jax.random.fold_in(base_key, 0), tick),
            stride)
        flush_pos = pos                     # block-start positions [B]
        shape = cache["k"].shape            # [L, B, Hkv, S, D]
        buf = {n: jnp.zeros(shape[:3] + (stride,) + shape[4:],
                            cache[n].dtype) for n in ("k", "v")}

        def step(carry, xs):
            tokens, pos, buf = carry
            j, k_ = xs
            logits, buf = _row_step_buffered(
                params, tokens, cache, buf, flush_pos, pos, j, cfg)
            nxt = _pick(logits, temps, k_).astype(tokens.dtype)
            nxt = jnp.where(active, nxt, tokens)
            pos = jnp.where(active, pos + 1, pos)
            return (nxt, pos, buf), nxt

        (tokens, pos, buf), block = lax.scan(
            step, (tokens, pos, buf), (jnp.arange(stride), keys))
        cache = _flush_buffer(cache, buf, flush_pos)
        return block, tokens, pos, cache

    @jax.jit
    def prefill_wave(params, padded_prompts, true_lens, temps_w,
                     base_key, rid0):
        """Batch-k prefill on right-padded prompts [k, bucket] (the
        padded SHAPE — both k and bucket — keys the compile cache).
        Returns (first tokens [k], batch-k cache); each row's first
        token is picked at ITS true last prompt position (pad logits
        ignored), greedy or sampled per-row.  The wave's first rid
        folds into the key inside the jit (separate domain from the
        block keys via the leading 1); rows draw independently from
        the one key via the batched categorical."""
        from kubegpu_tpu.models.decode import _forward_with_cache
        k = padded_prompts.shape[0]
        cache_w = init_kv_cache(cfg, k, max_len)
        logits, cache_w = _forward_with_cache(
            params, padded_prompts, cache_w, jnp.int32(0), cfg)
        last = jnp.take_along_axis(
            logits, (true_lens - 1)[:, None, None], axis=1)[:, 0]
        key = jax.random.fold_in(jax.random.fold_in(base_key, 1), rid0)
        return _pick(last, temps_w, key).astype(jnp.int32), cache_w

    @functools.partial(jax.jit, static_argnames=("k",))
    def adopt_wave(cache, cache_w, slots, firsts, plens, temps_w,
                   first_toks, tokens, pos, temps, k):
        """Admit a whole wave in ONE dispatch: scatter the batch-k
        cache's rows into (possibly non-contiguous) slots and update
        every per-slot device vector.  (Eager ``.at[].set`` ops per
        admission each cost a dispatch — under the tunnel that
        overhead rivaled the decode itself.)"""
        for i in range(k):   # k is static: unrolled slice-updates
            cache = jax.tree.map(
                lambda big, w: lax.dynamic_update_slice(
                    big, lax.dynamic_slice_in_dim(
                        w, i, 1, axis=1).astype(big.dtype),
                    (0, slots[i], 0, 0, 0)),
                cache, cache_w)
            first_toks = lax.dynamic_update_slice(
                first_toks, firsts[i:i + 1], (slots[i],))
            tokens = lax.dynamic_update_slice(
                tokens, firsts[i:i + 1], (slots[i],))
            pos = lax.dynamic_update_slice(
                pos, plens[i:i + 1], (slots[i],))
            temps = lax.dynamic_update_slice(
                temps, temps_w[i:i + 1], (slots[i],))
        return cache, first_toks, tokens, pos, temps

    return decode_block, prefill_wave, adopt_wave


# ---------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------

@dataclass
class _Request:
    rid: int
    prompt_len: int
    max_new_tokens: int
    temperature: float = 0.0     # 0 = greedy
    tokens: list[int] = field(default_factory=list)   # generated so far
    done: bool = False


class ContinuousBatcher:
    """Slot-based continuous-batching engine.

    ``submit()`` enqueues a request (greedy by default; a positive
    ``temperature`` samples that request with the engine's static
    ``top_k`` truncation, deterministically per ``seed``); ``step()``
    admits pending requests into free slots (batch-1 prefill + cache
    scatter), runs ONE stride-block of decode steps for every slot,
    and returns the requests that finished.  ``prompt_buckets`` are
    the padded prompt lengths prefill compiles for (one executable per
    bucket)."""

    def __init__(self, params: dict, cfg: LlamaConfig, n_slots: int = 8,
                 max_len: int | None = None, stride: int = 16,
                 prompt_buckets: tuple[int, ...] = (128, 512, 1024),
                 sampling: bool = False, top_k: int = 0, seed: int = 0,
                 max_wave: int = 1):
        if not 0 <= top_k <= cfg.vocab_size:
            raise ValueError(
                f"top_k {top_k} not in [0, vocab_size={cfg.vocab_size}]")
        self.sampling = sampling
        # Wave-size cap, DEFAULT 1.  Batched admission (k requests in
        # one [k, bucket] prefill + one adopt) is implemented and
        # parity-tested, but on-chip A/B runs were inconclusive: the
        # tunnel's throughput swung 5x between measurement windows,
        # and within one window k=1 was never slower (per-request
        # prefill cost measured flat across k — prefill is
        # compute-bound at these shapes — while each wave holds a
        # [k, max_len] cache transient alive).  Raise only with a
        # trustworthy measurement setup.
        self.max_wave = max(1, max_wave)
        self.params = params
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_len = max_len or cfg.max_seq_len
        self.stride = stride
        self.prompt_buckets = tuple(sorted(prompt_buckets))
        if self.prompt_buckets[-1] >= self.max_len:
            raise ValueError("largest prompt bucket must be < max_len")
        self._fns = _engine_fns(cfg, n_slots, self.max_len, stride,
                                top_k, sampling)
        self.cache = init_kv_cache(cfg, n_slots, self.max_len)
        self.tokens = jnp.zeros((n_slots,), jnp.int32)
        self.pos = jnp.zeros((n_slots,), jnp.int32)
        self.temps = jnp.zeros((n_slots,), jnp.float32)
        # deterministic sampling: prefill keys derive from the rid,
        # block keys from the tick counter — no device-side key state
        self._base_key = jax.random.PRNGKey(seed)
        self._tick = 0
        # the active mask lives HOST-side (numpy) and uploads with the
        # block dispatch — mutating it at retirement must not cost a
        # device op per request
        self.active = np.zeros((n_slots,), bool)
        # per-slot prefill-produced first token, kept ON DEVICE until
        # the next tick's single fused fetch — admissions must add zero
        # host round trips (under the TPU tunnel one fetch costs ~100
        # decode steps; the naive per-admission int() sync dominated
        # the first on-chip measurement)
        self.first_toks = jnp.zeros((n_slots,), jnp.int32)
        self.slot_req: dict[int, _Request] = {}
        self.queue: deque[tuple[_Request, jax.Array]] = deque()
        self._inflight: jax.Array | None = None   # fused (block, firsts)
        self._next_rid = 0
        # generated-token bookkeeping (totals; the bench's numerator)
        self.emitted_tokens = 0      # all generated tokens (incl. the
        #                              prefill-produced first token)
        self._decode_tokens = 0      # tokens produced BY decode steps
        self.slot_steps = 0          # decode slot-steps spent

    def warmup(self) -> None:
        """Compile every executable this engine can hit — the decode
        block and each power-of-two wave size per prompt bucket —
        WITHOUT touching engine state (all calls are functional and
        their outputs are discarded; counters stay at zero).  Benches
        and serving pods call this before the timed window: the first
        full-slot wave otherwise compiles a [n_slots, bucket] prefill
        mid-measurement (observed eating ~95% of a flagship run)."""
        decode_block, prefill_wave, adopt_wave = self._fns
        outs = []
        for bucket in self.prompt_buckets:
            k = 1
            while k <= min(self.n_slots, self.max_wave):
                padded = jnp.zeros((k, bucket), jnp.int32)
                lens = jnp.ones((k,), jnp.int32)
                temps = jnp.zeros((k,), jnp.float32)
                firsts, cache_w = prefill_wave(
                    self.params, padded, lens, temps, self._base_key,
                    jnp.int32(0))
                outs.append(adopt_wave(
                    self.cache, cache_w,
                    jnp.arange(k, dtype=jnp.int32), firsts, lens,
                    temps, self.first_toks, self.tokens, self.pos,
                    self.temps, k)[1])
                k *= 2
        outs.append(decode_block(
            self.params, self.cache, self.tokens, self.pos,
            jnp.asarray(self.active), self.temps, self._base_key,
            jnp.int32(0))[0])
        for o in outs:   # block until every compile finished
            np.asarray(o)

    # -- submission -----------------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               temperature: float = 0.0) -> int:
        """Enqueue a request.  ``prompt``: 1-D int sequence;
        ``temperature`` 0 decodes greedily, > 0 samples."""
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if temperature < 0:
            raise ValueError(
                f"temperature must be >= 0, got {temperature}")
        if temperature > 0 and not self.sampling:
            raise ValueError(
                "temperature > 0 needs a sampling-enabled engine "
                "(ContinuousBatcher(..., sampling=True)) — greedy-only "
                "engines compile argmax-only decode steps")
        prompt = jnp.asarray(prompt, jnp.int32)
        t = int(prompt.shape[0])
        if t < 1:
            # an empty prompt would index prefill logits at -1, which
            # dynamic_index clamps to 0 — silent garbage, not an error
            raise ValueError("prompt must have at least one token")
        bucket = next((b for b in self.prompt_buckets if b >= t), None)
        if bucket is None:
            raise ValueError(
                f"prompt length {t} exceeds largest bucket "
                f"{self.prompt_buckets[-1]}")
        if t + max_new_tokens + self.stride > self.max_len:
            raise ValueError(
                f"prompt {t} + max_new {max_new_tokens} + stride "
                f"{self.stride} > max_len {self.max_len}")
        padded = jnp.zeros((1, bucket), jnp.int32).at[0, :t].set(prompt)
        req = _Request(rid=self._next_rid, prompt_len=t,
                       max_new_tokens=max_new_tokens,
                       temperature=float(temperature))
        self._next_rid += 1
        self.queue.append((req, padded))
        return req.rid

    # -- the engine tick ------------------------------------------------

    def _admit(self) -> None:
        decode_block, prefill_wave, adopt_wave = self._fns
        free = [s for s in range(self.n_slots)
                if s not in self.slot_req]
        while free and self.queue:
            # WAVE admission: consecutive queue-front requests sharing
            # one prompt bucket prefill as a single [k, bucket] batch
            # (one prefill + one adopt dispatch instead of 2k, and the
            # batched prompt matmuls beat k batch-1 passes).  k rounds
            # down to a power of two so the per-(k, bucket) executable
            # count stays at log2(n_slots) per bucket; FIFO order is
            # preserved — a different-bucket request at the front just
            # bounds this wave, never gets jumped.
            bucket = self.queue[0][1].shape[1]
            n_same = 1
            for r, p in list(self.queue)[1:min(len(self.queue),
                                               len(free))]:
                if p.shape[1] != bucket:
                    break
                n_same += 1
            k = 1
            while k * 2 <= min(n_same, len(free), self.max_wave):
                k *= 2
            wave = [self.queue.popleft() for _ in range(k)]
            slots = [free.pop(0) for _ in range(k)]
            padded = jnp.concatenate([p for _, p in wave], axis=0)
            true_lens = jnp.asarray(
                [r.prompt_len for r, _ in wave], jnp.int32)
            temps_w = jnp.asarray(
                [r.temperature for r, _ in wave], jnp.float32)
            firsts, cache_w = prefill_wave(
                self.params, padded, true_lens, temps_w,
                self._base_key, jnp.int32(wave[0][0].rid))
            # two dispatches per WAVE, zero host fetches: first-token
            # values reach req.tokens at the next tick's fused fetch
            (self.cache, self.first_toks, self.tokens,
             self.pos, self.temps) = adopt_wave(
                self.cache, cache_w, jnp.asarray(slots, jnp.int32),
                firsts, true_lens, temps_w, self.first_toks,
                self.tokens, self.pos, self.temps, k)
            for slot, (req, _) in zip(slots, wave):
                self.active[slot] = req.max_new_tokens > 1
                self.slot_req[slot] = req
                self.emitted_tokens += 1
                if req.max_new_tokens <= 1:
                    req.done = True

    def step(self) -> list[_Request]:
        """One engine tick: collect the previous tick's in-flight block,
        retire its finishers, admit into the freed slots, then dispatch
        the next block and return WITHOUT waiting for it.  One fused
        host round trip per tick (token block + every pending first
        token).  Because the dispatch is asynchronous, the block
        computes during whatever the caller does between ticks (e.g. an
        async server accepting submissions) — and since collection
        precedes dispatch, membership is always current: a finisher
        retires before the next block runs.  Returns the requests that
        FINISHED (from the block dispatched last tick)."""
        decode_block, _, _ = self._fns
        finished = self._collect()
        self._admit()
        if self.slot_req:
            block, self.tokens, self.pos, self.cache = decode_block(
                self.params, self.cache, self.tokens, self.pos,
                jnp.asarray(self.active), self.temps, self._base_key,
                jnp.int32(self._tick))
            self._tick += 1
            # fuse NOW (after admissions): newly admitted requests'
            # first tokens ride this block's fetch
            self._inflight = jnp.concatenate(
                [block.reshape(-1), self.first_toks])
        return finished

    def _collect(self) -> list[_Request]:
        """Fetch + account the in-flight block, if any."""
        finished: list[_Request] = []
        if self._inflight is None:
            return finished
        fused = np.asarray(self._inflight)    # THE host sync
        self._inflight = None
        nb = self.stride * self.n_slots
        block_np = fused[:nb].reshape(self.stride, self.n_slots)
        firsts_np = fused[nb:]
        self.slot_steps += self.stride * self.n_slots
        for slot, req in list(self.slot_req.items()):
            if not req.tokens:   # first token materializes on fetch
                req.tokens.append(int(firsts_np[slot]))
            if req.done:   # single-token request: retires without decode
                finished.append(req)
                del self.slot_req[slot]
                self.active[slot] = False
                continue
            want = req.max_new_tokens - len(req.tokens)
            take = min(self.stride, want)
            req.tokens.extend(int(x) for x in block_np[:take, slot])
            self.emitted_tokens += take
            self._decode_tokens += take
            if len(req.tokens) >= req.max_new_tokens:
                req.done = True
                finished.append(req)
                del self.slot_req[slot]
                self.active[slot] = False
        return finished

    def drain(self, max_ticks: int = 10_000) -> list[_Request]:
        """Run until queue and slots are empty; returns every finished
        request in completion order."""
        out: list[_Request] = []
        for _ in range(max_ticks):
            if not self.queue and not self.slot_req:
                return out
            out.extend(self.step())
        raise RuntimeError("drain did not converge")

    @property
    def occupancy(self) -> float:
        """Fraction of decode slot-steps whose token was consumed by a
        request (the prefill-produced first token is throughput but not
        a decode step, so it does not count here)."""
        return (self._decode_tokens / self.slot_steps
                if self.slot_steps else 0.0)

"""Runtime-injection layer — reference: ``crishim`` (SURVEY.md §3, §4.3).

The reference interposed a gRPC CRI server between kubelet and the real
container runtime, rewriting ``CreateContainer`` with device env/mounts.
KubeTPU keeps the exact seam: ``CriShim.create_container`` reads the pod's
allocation annotation, asks the device backend for the TPU env
(``TPU_VISIBLE_CHIPS``/``TPU_WORKER_ID``/coordinator bootstrap), rewrites
the container spec, and forwards to a runtime.  ``SubprocessRuntime``
actually launches workload processes with that env; ``FakeRuntime`` records
calls for scheduler-side tests.  ``NodeAgent`` plays kubelet+advertiser:
periodic Node advertisement patches and reacting to pods bound here.
"""

from kubegpu_tpu.crishim.runtime import (
    ContainerHandle,
    ContainerRuntime,
    FakeRuntime,
    SubprocessRuntime,
)
from kubegpu_tpu.crishim.shim import CriShim
from kubegpu_tpu.crishim.agent import NodeAgent
from kubegpu_tpu.crishim.criserver import (
    CriClient,
    CriError,
    CriServer,
    RemoteCriShim,
)

__all__ = [
    "ContainerHandle", "ContainerRuntime", "FakeRuntime",
    "SubprocessRuntime", "CriShim", "NodeAgent",
    "CriServer", "CriClient", "CriError", "RemoteCriShim",
]

"""Llama model tests: shapes, causality, training, sharded execution on
the 8-device CPU mesh (the same path the driver's dryrun compiles)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from kubegpu_tpu.models import (
    LlamaConfig,
    llama_forward,
    llama_init,
    llama_param_specs,
)
from kubegpu_tpu.models.llama import make_train_step, next_token_loss
from kubegpu_tpu.parallel import make_mesh, named_sharding_tree


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny()
    params = llama_init(jax.random.PRNGKey(0), cfg)
    return cfg, params


class TestForward:
    def test_logit_shape_and_dtype(self, tiny):
        cfg, params = tiny
        tokens = jnp.zeros((2, 16), jnp.int32)
        logits = llama_forward(params, tokens, cfg)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert logits.dtype == jnp.float32

    def test_causality(self, tiny):
        """Future-token edits must not affect earlier logits."""
        cfg, params = tiny
        key = jax.random.PRNGKey(1)
        tok1 = jax.random.randint(key, (1, 16), 0, cfg.vocab_size)
        tok2 = tok1.at[0, 10:].set(7)
        l1 = llama_forward(params, tok1, cfg)
        l2 = llama_forward(params, tok2, cfg)
        np.testing.assert_allclose(np.asarray(l1[0, :10]),
                                   np.asarray(l2[0, :10]), atol=1e-5)

    def test_remat_matches(self, tiny):
        cfg, params = tiny
        cfg_r = LlamaConfig.tiny(remat=True)
        tokens = jnp.arange(32, dtype=jnp.int32).reshape(2, 16) % cfg.vocab_size
        np.testing.assert_allclose(
            np.asarray(llama_forward(params, tokens, cfg)),
            np.asarray(llama_forward(params, tokens, cfg_r)),
            atol=1e-5)

    def test_loss_decreases(self, tiny):
        cfg, params = tiny
        opt = optax.adam(1e-2)
        step = jax.jit(make_train_step(cfg, opt))
        opt_state = opt.init(params)
        tokens = (jnp.arange(64, dtype=jnp.int32).reshape(2, 32) * 3
                  ) % cfg.vocab_size
        first = None
        for _ in range(10):
            params, opt_state, loss = step(params, opt_state, tokens)
            first = first if first is not None else float(loss)
        assert float(loss) < first


class TestShardedExecution:
    def test_tp_dp_sharded_forward_matches_single(self, tiny):
        """dp2 x tp4 over 8 CPU devices: same numbers as unsharded."""
        cfg, params = tiny
        mesh = make_mesh({"dp": 2, "tp": 4})
        specs = named_sharding_tree(mesh, llama_param_specs(cfg))
        sharded = jax.device_put(params, specs)
        tokens = (jnp.arange(64, dtype=jnp.int32).reshape(4, 16) * 5
                  ) % cfg.vocab_size
        tok_sharding = NamedSharding(mesh, P(("dp",), None))
        tokens_s = jax.device_put(tokens, tok_sharding)
        ref = llama_forward(params, tokens, cfg)
        out = jax.jit(
            lambda p, t: llama_forward(p, t, cfg, mesh)
        )(sharded, tokens_s)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=3e-4, rtol=3e-4)

    def test_full_train_step_on_mesh(self, tiny):
        """jitted train step with dp/fsdp/tp shardings executes and the
        loss is finite — the dryrun_multichip path."""
        cfg, _ = tiny
        mesh = make_mesh({"dp": 2, "fsdp": 2, "tp": 2})
        params = llama_init(jax.random.PRNGKey(0), cfg)
        specs = named_sharding_tree(mesh, llama_param_specs(cfg))
        params = jax.device_put(params, specs)
        opt = optax.adamw(1e-3)
        opt_state = opt.init(params)
        step = jax.jit(make_train_step(cfg, opt, mesh), donate_argnums=(0, 1))
        tokens = (jnp.arange(4 * 17, dtype=jnp.int32).reshape(4, 17)
                  ) % cfg.vocab_size
        tokens = jax.device_put(
            tokens, NamedSharding(mesh, P(("dp", "fsdp"), None)))
        params, opt_state, loss = step(params, opt_state, tokens)
        assert np.isfinite(float(loss))

    def test_ring_attention_model_matches(self):
        """sp-sharded model (ring attention) == local-attention model."""
        cfg = LlamaConfig.tiny(attn_impl="xla")
        cfg_ring = LlamaConfig.tiny(attn_impl="ring")
        params = llama_init(jax.random.PRNGKey(0), cfg)
        mesh = make_mesh({"dp": 1, "sp": 8})
        tokens = (jnp.arange(32, dtype=jnp.int32).reshape(1, 32) * 7
                  ) % cfg.vocab_size
        ref = llama_forward(params, tokens, cfg)
        out = jax.jit(
            lambda p, t: llama_forward(p, t, cfg_ring, mesh)
        )(params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=3e-4, rtol=3e-4)

    def test_loss_agrees_across_shardings(self, tiny):
        cfg, params = tiny
        mesh = make_mesh({"dp": 4, "tp": 2})
        tokens = (jnp.arange(4 * 16, dtype=jnp.int32).reshape(4, 16)
                  ) % cfg.vocab_size
        ref = next_token_loss(params, tokens, cfg)
        specs = named_sharding_tree(mesh, llama_param_specs(cfg))
        sharded = jax.device_put(params, specs)
        out = jax.jit(
            lambda p, t: next_token_loss(p, t, cfg, mesh))(sharded, tokens)
        assert abs(float(out) - float(ref)) < 1e-3


class TestGradAccumulation:
    def test_accumulated_matches_full_batch(self):
        """accum_steps=4 over a batch of 8 must produce the same update
        as one full-batch step (equal microbatches => identical mean
        grads, modulo f32 accumulation order)."""
        import optax

        from kubegpu_tpu.models.llama import make_train_step

        cfg = LlamaConfig.tiny()
        params = llama_init(jax.random.PRNGKey(0), cfg)
        opt = optax.sgd(1e-2)   # stateless-ish: isolates the grads
        tokens = (jnp.arange(8 * 17, dtype=jnp.int32).reshape(8, 17) * 3
                  ) % cfg.vocab_size
        full = jax.jit(make_train_step(cfg, opt))
        accu = jax.jit(make_train_step(cfg, opt, accum_steps=4))
        p1, _, l1 = full(params, opt.init(params), tokens)
        p2, _, l2 = accu(params, opt.init(params), tokens)
        assert float(l1) == pytest.approx(float(l2), rel=1e-5)
        for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-5)

    def test_validation(self):
        import optax

        from kubegpu_tpu.models.llama import make_train_step

        cfg = LlamaConfig.tiny()
        with pytest.raises(ValueError, match="accum_steps"):
            make_train_step(cfg, optax.sgd(1e-2), accum_steps=0)
        step = jax.jit(make_train_step(cfg, optax.sgd(1e-2),
                                       accum_steps=3))
        params = llama_init(jax.random.PRNGKey(0), cfg)
        tokens = jnp.zeros((8, 17), jnp.int32)   # 8 % 3 != 0
        with pytest.raises(ValueError, match="divisible"):
            step(params, optax.sgd(1e-2).init(params), tokens)

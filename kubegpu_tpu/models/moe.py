"""Mixtral-style mixture-of-experts decoder — the expert-parallel (``ep``)
workload (extends the flagship Llama family; reference example/ has no MoE,
this is a TPU-native addition the driver's multi-chip dryrun exercises).

TPU-first design choices (GShard/Switch lineage, per the scaling-book
recipe):
- routing is expressed as **one-hot dispatch/combine einsums** so the
  whole MoE layer is dense matmuls on the MXU — no gather/scatter, no
  dynamic shapes;
- experts are stored stacked ``[E, ...]`` and sharded on the ``ep`` mesh
  axis; the dispatch einsum's output is constrained to ``ep`` so GSPMD
  inserts the canonical all-to-all (token shuffle) over ICI;
- fixed **expert capacity** (static shapes under jit): tokens over
  capacity are dropped by position, the standard TPU MoE contract;
- aux load-balancing loss (Switch §2.2 form: E · Σ_e f_e · p_e) keeps
  routing from collapsing; returned alongside logits so train steps can
  weight it.

Note on causality: capacity contention is position-ordered but not
strictly causal (a later token's earlier-round choice can displace an
earlier token's later-round slot) — the standard behavior of
capacity-based MoE training; ``capacity_factor >= n_experts/top_k``
guarantees zero drops and exact causality.


Shares the attention stack with :mod:`kubegpu_tpu.models.llama` — only
the FFN is replaced by the routed expert FFN.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kubegpu_tpu.models.llama import (
    LlamaConfig, _rmsnorm, attention_sublayer, embed_lookup,
    make_train_step, select_attend,
)
from kubegpu_tpu.models import decode
from kubegpu_tpu.parallel.sharding import constrain


@dataclass(frozen=True)
class MoEConfig:
    """Llama backbone + routed-expert FFN."""
    base: LlamaConfig = field(default_factory=LlamaConfig)
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    router_aux_weight: float = 0.01

    @classmethod
    def mixtral_8x7b_shaped(cls) -> "MoEConfig":
        return cls(base=LlamaConfig(
            vocab_size=32000, d_model=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, d_ff=14336, max_seq_len=8192,
            rope_theta=1e6), n_experts=8, top_k=2)

    @classmethod
    def tiny(cls, n_experts: int = 4, top_k: int = 2,
             capacity_factor: float = 1.25, **base_kw) -> "MoEConfig":
        return cls(base=LlamaConfig.tiny(**base_kw), n_experts=n_experts,
                   top_k=top_k, capacity_factor=capacity_factor)

    def capacity(self, tokens_per_group: int) -> int:
        """Per-expert token capacity for a routing group of that size."""
        cap = math.ceil(
            self.top_k * tokens_per_group * self.capacity_factor
            / self.n_experts)
        return max(cap, self.top_k)


# ---------------------------------------------------------------------------
# Init / sharding rules
# ---------------------------------------------------------------------------

def moe_init(key: jax.Array, cfg: MoEConfig) -> dict:
    """Stacked-layer pytree; expert FFNs carry an extra leading E dim."""
    b = cfg.base
    hd = b.head_dim
    k_emb, k_layers, k_out = jax.random.split(key, 3)

    def dense_init(k, shape, scale_dim):
        return (jax.random.normal(k, shape, jnp.float32)
                * (scale_dim ** -0.5)).astype(b.jdtype)

    ks = jax.random.split(k_layers, 8)
    L, E = b.n_layers, cfg.n_experts
    layers = {
        "attn_norm": jnp.ones((L, b.d_model), b.jdtype),
        "wq": dense_init(ks[0], (L, b.d_model, b.n_heads * hd), b.d_model),
        "wk": dense_init(ks[1], (L, b.d_model, b.n_kv_heads * hd), b.d_model),
        "wv": dense_init(ks[2], (L, b.d_model, b.n_kv_heads * hd), b.d_model),
        "wo": dense_init(ks[3], (L, b.n_heads * hd, b.d_model),
                         b.n_heads * hd),
        "mlp_norm": jnp.ones((L, b.d_model), b.jdtype),
        # router in f32: tiny matmul, routing decisions are precision-critical
        "w_router": (jax.random.normal(ks[4], (L, b.d_model, E), jnp.float32)
                     * (b.d_model ** -0.5)),
        "w_gate": dense_init(ks[5], (L, E, b.d_model, b.d_ff), b.d_model),
        "w_up": dense_init(ks[6], (L, E, b.d_model, b.d_ff), b.d_model),
        "w_down": dense_init(ks[7], (L, E, b.d_ff, b.d_model), b.d_ff),
    }
    return {
        "embed": dense_init(k_emb, (b.vocab_size, b.d_model), b.d_model),
        "layers": layers,
        "final_norm": jnp.ones((b.d_model,), b.jdtype),
        "lm_head": dense_init(k_out, (b.d_model, b.vocab_size), b.d_model),
    }


def moe_param_specs(cfg: MoEConfig) -> dict:
    """Sharding rules: attention as Llama (fsdp/tp); experts sharded on
    ``ep`` with tp on the ffn dim — each ep rank holds E/ep whole experts,
    so expert matmuls need no cross-expert communication at all."""
    return {
        "embed": P("tp", "fsdp"),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, "fsdp", "tp"),
            "wk": P(None, "fsdp", "tp"),
            "wv": P(None, "fsdp", "tp"),
            "wo": P(None, "tp", "fsdp"),
            "mlp_norm": P(None, None),
            "w_router": P(None, "fsdp", None),
            "w_gate": P(None, "ep", "fsdp", "tp"),
            "w_up": P(None, "ep", "fsdp", "tp"),
            "w_down": P(None, "ep", "tp", "fsdp"),
        },
        "final_norm": P(None),
        "lm_head": P("fsdp", "tp"),
    }


# ---------------------------------------------------------------------------
# Routed FFN
# ---------------------------------------------------------------------------

def route_tokens(router_logits: jax.Array, top_k: int, capacity: int
                 ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Top-k routing with fixed capacity.

    router_logits: [G, T, E] (G = routing groups, here the batch dim).
    Returns (dispatch [G,T,E,C] one-hot float, combine [G,T,E,C] gate
    weights, aux_loss scalar).  Position-in-expert is assigned by token
    order (GShard convention); tokens past capacity get zero rows — they
    fall through the residual connection untouched.
    """
    g, t, e = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)

    # iterative top-k (k is small and static; avoids sort on [G,T,E])
    dispatch = jnp.zeros((g, t, e, capacity), jnp.float32)
    combine = jnp.zeros((g, t, e, capacity), jnp.float32)
    remaining = probs
    # running count of tokens already assigned to each expert: [G, E]
    fill = jnp.zeros((g, e), jnp.int32)
    for _ in range(top_k):
        gate = remaining.max(axis=-1)                       # [G, T]
        choice = remaining.argmax(axis=-1)                  # [G, T]
        onehot = jax.nn.one_hot(choice, e, dtype=jnp.float32)  # [G,T,E]
        # position of each token within its chosen expert's buffer:
        # cumulative count of earlier tokens choosing the same expert
        # this round, plus what previous rounds already filled.
        pos_in_round = (jnp.cumsum(onehot, axis=1) - onehot)  # [G,T,E]
        pos = (pos_in_round + fill[:, None, :])               # [G,T,E]
        pos_tok = jnp.einsum("gte,gte->gt", pos, onehot)      # [G,T]
        keep = pos_tok < capacity
        pos_oh = jax.nn.one_hot(pos_tok.astype(jnp.int32), capacity,
                                dtype=jnp.float32)            # [G,T,C]
        slot = (onehot[..., None] * pos_oh[:, :, None, :]
                * keep[:, :, None, None])                     # [G,T,E,C]
        dispatch = dispatch + slot
        combine = combine + slot * gate[:, :, None, None]
        fill = fill + (onehot * keep[..., None]).sum(axis=1).astype(jnp.int32)
        remaining = remaining * (1.0 - onehot)  # mask this round's choice

    # Switch-style aux loss on the FIRST-choice distribution:
    # E * sum_e (fraction of tokens whose argmax is e) * (mean prob of e)
    first = jax.nn.one_hot(probs.argmax(axis=-1), e, dtype=jnp.float32)
    frac = first.mean(axis=(0, 1))
    mean_p = probs.mean(axis=(0, 1))
    aux = e * jnp.sum(frac * mean_p)

    # renormalize kept gates so each token's surviving weights sum to 1
    denom = combine.sum(axis=(2, 3), keepdims=True)
    combine = combine / jnp.maximum(denom, 1e-9)
    return dispatch, combine, aux


def moe_ffn(x: jax.Array, lp: dict, cfg: MoEConfig,
            mesh: Mesh | None = None) -> tuple[jax.Array, jax.Array]:
    """Routed SwiGLU FFN.  x: [B, T, d] → (out [B, T, d], aux_loss).

    Dense one-hot algebra end to end: dispatch/combine are einsums, the
    expert matmuls are a single batched ``[E, cap', d] @ [E, d, f]``
    (vmapped over the stacked expert dim) — all MXU work.  The ``ep``
    constraint on the dispatched tensor makes GSPMD materialize the
    all-to-all token shuffle.
    """
    b_, t, d = x.shape
    cap = cfg.capacity(t)
    logits = x.astype(jnp.float32) @ lp["w_router"]          # [B,T,E]
    dispatch, combine, aux = route_tokens(logits, cfg.top_k, cap)

    # [B,T,E,C] × [B,T,d] → [E, B·C, d]: tokens grouped per expert
    xd = jnp.einsum("gtec,gtd->egcd", dispatch.astype(x.dtype), x)
    xd = xd.reshape(cfg.n_experts, b_ * cap, d)
    xd = constrain(xd, mesh, "ep", ("dp", "fsdp"), None)

    def expert(xe, wg, wu, wd):
        h = jax.nn.silu(xe @ wg) * (xe @ wu)
        return h @ wd

    out = jax.vmap(expert)(xd, lp["w_gate"], lp["w_up"], lp["w_down"])
    out = constrain(out, mesh, "ep", ("dp", "fsdp"), None)
    out = out.reshape(cfg.n_experts, b_, cap, d)
    y = jnp.einsum("egcd,gtec->gtd", out.astype(jnp.float32),
                   combine).astype(x.dtype)
    return y, aux


# ---------------------------------------------------------------------------
# Forward / loss
# ---------------------------------------------------------------------------

def moe_forward(params: dict, tokens: jax.Array, cfg: MoEConfig,
                mesh: Mesh | None = None) -> tuple[jax.Array, jax.Array]:
    """tokens [B,T] → (logits [B,T,V] f32, total aux loss)."""
    b = cfg.base
    bs, t = tokens.shape
    x = embed_lookup(params["embed"], tokens, mesh)
    x = constrain(x, mesh, ("dp", "fsdp"), "sp", None)
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (bs, t))
    attend = select_attend(b, mesh)

    def layer(carry, lp):
        x, aux_sum = carry
        x = attention_sublayer(x, lp, b, positions, attend, mesh)
        h = _rmsnorm(x, lp["mlp_norm"], b.norm_eps)
        y, aux = moe_ffn(h, lp, cfg, mesh)
        x = x + y
        x = constrain(x, mesh, ("dp", "fsdp"), "sp", None)
        return (x, aux_sum + aux), None

    layer_fn = jax.checkpoint(layer) if b.remat else layer
    (x, aux_sum), _ = jax.lax.scan(layer_fn, (x, jnp.float32(0.0)),
                                   params["layers"])
    x = _rmsnorm(x, params["final_norm"], b.norm_eps)
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return constrain(logits, mesh, ("dp", "fsdp"), "sp", "tp"), aux_sum


def moe_next_token_loss(params: dict, tokens: jax.Array, cfg: MoEConfig,
                        mesh: Mesh | None = None) -> jax.Array:
    # forward ALL T tokens and drop the last logit (same contract as
    # llama's next_token_loss r4 fix): a T-1 forward breaks kernel
    # block alignment and silently fell back to O(T^2) XLA attention
    logits, aux = moe_forward(params, tokens, cfg, mesh)
    logits = logits[:, :-1]
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -ll.mean() + cfg.router_aux_weight * aux / cfg.base.n_layers


def make_moe_train_step(cfg: MoEConfig, optimizer,
                        mesh: Mesh | None = None):
    """(params, opt_state, tokens) → (params, opt_state, loss); the shared
    llama train-step machinery with the MoE (lm + aux) loss."""
    return make_train_step(cfg, optimizer, mesh,
                           loss_fn=moe_next_token_loss)


# ---------------------------------------------------------------------------
# Serving: KV-cache decode with routed experts
# ---------------------------------------------------------------------------

def _moe_decode_ffn(cfg: MoEConfig):
    """The routed-FFN hook for the cached forward (decode.py): same
    moe_ffn as training, aux loss discarded (serving doesn't train the
    router), no mesh constraints (single-host serving; GSPMD shardings
    still flow from the params when present).

    Capacity semantics: routing groups are per-call (the whole prompt
    at prefill, ONE token per decode step), so capacity-overflow drops
    differ from training's full-sequence grouping.  In the no-drop
    regime (generous capacity_factor — how MoE serving is run in
    practice, since dropping at inference is lossy) decode matches
    moe_forward exactly; with tight capacity the decode path drops
    LESS than training would."""
    def ffn(x, lp):
        h = _rmsnorm(x, lp["mlp_norm"], cfg.base.norm_eps)
        y, _ = moe_ffn(h, lp, cfg, mesh=None)
        return x + y
    return ffn


def moe_prefill(params: dict, prompt, cfg: MoEConfig,
                max_len: int | None = None, kv_int8: bool = False):
    """MoE counterpart of decode.prefill: (last logits, primed cache)."""
    return decode.prefill(params, prompt, cfg.base, max_len,
                          kv_int8=kv_int8, ffn=_moe_decode_ffn(cfg))


def moe_decode_step(params: dict, cache: dict, token, pos,
                    cfg: MoEConfig):
    """One routed decode step: token [B], pos scalar → (logits, cache)."""
    return decode.decode_step(params, cache, token, pos, cfg.base,
                              ffn=_moe_decode_ffn(cfg))


def moe_greedy_generate(params: dict, prompt, n_steps: int,
                        cfg: MoEConfig, max_len: int | None = None,
                        kv_int8: bool = False):
    """Greedy decode for the MoE family — decode's public
    :func:`kubegpu_tpu.models.decode.generate` with the routed-expert
    FFN swapped in via the hashable (factory, cfg) pair; per-step
    routing runs over each step's single token (capacity top_k at
    T=1)."""
    return decode.generate(params, prompt, n_steps, cfg.base,
                           max_len=max_len, kv_int8=kv_int8,
                           ffn_factory=_moe_decode_ffn, ffn_cfg=cfg)

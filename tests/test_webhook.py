"""HTTP scheduler-extender webhook: the kube-scheduler wire contract
(SURVEY.md §3 extender service / §4.2 filter→prioritize)."""

import json
import urllib.request

import pytest

from kubegpu_tpu.cluster import SimCluster, tpu_pod
from kubegpu_tpu.kubemeta import GangSpec
from kubegpu_tpu.scheduler.webhook import (
    ExtenderHTTPServer,
    pod_from_doc,
    pod_to_doc,
    policy_config,
)


def post(url: str, payload) -> object:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())


@pytest.fixture()
def cluster_and_server():
    cl = SimCluster(["v5e-16"])
    srv = ExtenderHTTPServer(cl.scheduler).start()
    yield cl, srv
    srv.close()
    cl.close()


class TestPodDocRoundTrip:
    def test_round_trip_preserves_scheduler_fields(self):
        pod = tpu_pod("p", chips=4, mesh_axes={"dp": 2, "tp": 2},
                      gang=GangSpec(name="g", size=2, index=0),
                      priority=7, multislice=True, command=["x"])
        back = pod_from_doc(pod_to_doc(pod))
        assert back.name == "p"
        assert back.spec.total_chips == 4
        assert back.spec.priority == 7
        assert back.metadata.annotations == pod.metadata.annotations


class TestExtenderHTTP:
    def test_filter_over_http(self, cluster_and_server):
        cl, srv = cluster_and_server
        nodes = [n.name for n in cl.api.list("Node")]
        pod_doc = pod_to_doc(tpu_pod("p", chips=4, command=["x"]))
        out = post(f"{srv.address}/kubetpu/filter",
                   {"Pod": pod_doc, "NodeNames": nodes})
        assert out["Error"] == ""
        assert set(out["NodeNames"]) == set(nodes)
        assert out["FailedNodes"] == {}

    def test_filter_reports_infeasible_nodes(self, cluster_and_server):
        cl, srv = cluster_and_server
        # occupy one host's block, then ask for a full-host pod
        cl.submit(tpu_pod("warm", chips=4, command=["x"]))
        cl.step()
        warm_node = cl.api.get("Pod", "warm").spec.node_name
        nodes = [n.name for n in cl.api.list("Node")]
        pod_doc = pod_to_doc(tpu_pod("p", chips=4, command=["x"]))
        out = post(f"{srv.address}/kubetpu/filter",
                   {"Pod": pod_doc, "NodeNames": nodes})
        assert warm_node not in out["NodeNames"]
        assert warm_node in out["FailedNodes"]

    def test_prioritize_over_http(self, cluster_and_server):
        cl, srv = cluster_and_server
        nodes = [n.name for n in cl.api.list("Node")]
        pod_doc = pod_to_doc(tpu_pod("p", chips=1, command=["x"]))
        out = post(f"{srv.address}/kubetpu/prioritize",
                   {"Pod": pod_doc, "NodeNames": nodes})
        assert isinstance(out, list) and len(out) == len(nodes)
        for entry in out:
            assert entry["Host"] in nodes
            assert 0 <= entry["Score"] <= 10

    def test_unknown_verb_404(self, cluster_and_server):
        _, srv = cluster_and_server
        req = urllib.request.Request(
            f"{srv.address}/kubetpu/nope", data=b"{}", method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 404

    def test_malformed_body_reports_error_field(self, cluster_and_server):
        _, srv = cluster_and_server
        req = urllib.request.Request(
            f"{srv.address}/kubetpu/filter", data=b"not json",
            method="POST")
        with urllib.request.urlopen(req, timeout=10) as resp:
            out = json.loads(resp.read())
        assert out["Error"]
        assert out["NodeNames"] == []

    def test_malformed_prioritize_returns_500(self, cluster_and_server):
        """prioritize's contract is a bare HostPriorityList with no Error
        slot — failures must surface at the HTTP level, not as an object
        the client can't unmarshal."""
        _, srv = cluster_and_server
        req = urllib.request.Request(
            f"{srv.address}/kubetpu/prioritize", data=b"not json",
            method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 500


class TestPolicyConfig:
    def test_stanza_shape(self):
        cfg = policy_config("http://1.2.3.4:8900")
        ext = cfg["extenders"][0]
        assert ext["urlPrefix"] == "http://1.2.3.4:8900/kubetpu"
        assert ext["filterVerb"] == "filter"
        assert ext["prioritizeVerb"] == "prioritize"

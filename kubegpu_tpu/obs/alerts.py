"""Multi-window burn-rate alerting over the flight recorder
(ISSUE 20).

The SRE-workbook shape, tick-denominated: an :class:`AlertRule` pairs
a FAST window (default 8 ticks — catches a cliff quickly) with a SLOW
window (default 64 ticks — suppresses blips), and fires only when
BOTH breach, with hysteresis (``hold_ticks`` consecutive breaching
evaluations, like ``AutoscalePolicy``'s hold) and a per-rule cooldown
so one sustained incident is one alert, not one per tick.  Rules are
evaluated each tick from a :class:`~kubegpu_tpu.obs.tsdb.SeriesStore`
— METRICS ONLY, no privileged peek at the injector — which is the
point the ``cb_obs_fleet`` bench gates: a ``DomainChaosInjector``
domain kill must be detected from the series within a bounded tick
count while the fault-free twin fires ZERO alerts.

Determinism: windows, thresholds, and series are all tick-indexed, so
the fired-alert list is a pure function of the seed — two runs of the
same trace produce identical ``(tick, rule)`` sequences.

ALERT TABLE — the default rule set (mirrored in the README
observability section):

======================  ====  ========================================
rule                    kind  fires when (fast AND slow windows)
======================  ====  ========================================
``alert_failover_burn``  rate  ``serve_failover_total`` deltas exceed
                               0.25/tick over 8 ticks and 0.02/tick
                               over 64 — correlated replica loss
                               (a domain kill trips this in ~2 ticks)
``alert_shed_burn``      rate  ``serve_requests_shed`` deltas exceed
                               0.5/tick fast and 0.1/tick slow —
                               sustained admission-control pressure
``alert_slo_burn``       burn  ``serve_slo_attainment`` burn
                               (objective − windowed mean, objective
                               0.95) exceeds 0.35 fast and 0.15 slow
                               — the error budget is burning
======================  ====  ========================================

:class:`FlightRecorder` is the one-stop wiring: a ``controller(tick,
stats)`` callable (the exact hook ``run_load`` / ``run_fleet``
already expose) that refreshes the attainment gauge, samples the
store, and evaluates the rules — so recording+alerting bolts onto any
existing driver with zero driver changes, and the engine outcomes
stay bit-identical with it on or off (it only ever READS the run).
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass

from kubegpu_tpu.obs.tsdb import SeriesStore

__all__ = ["AlertRule", "Alert", "AlertEngine", "FlightRecorder",
           "default_rules"]

RATE = "rate"    # windowed per-tick rate of a (delta) series
BURN = "burn"    # objective minus windowed mean of a ratio series
KINDS = (RATE, BURN)


@dataclass(frozen=True)
class AlertRule:
    """One multi-window rule.  ``kind=RATE`` measures
    ``rate(series, window)`` (counter-delta series ⇒ events/tick);
    ``kind=BURN`` measures ``max(0, objective − avg(series, window))``
    — and an EMPTY window measures 0 (no data is not an incident)."""
    name: str
    series: str
    kind: str = RATE
    objective: float = 1.0          # BURN only
    fast_window: int = 8
    slow_window: int = 64
    fast_threshold: float = 0.25
    slow_threshold: float = 0.05
    hold_ticks: int = 2             # consecutive breaches before firing
    cooldown_ticks: int = 32        # re-fire lockout after an alert

    def __post_init__(self) -> None:
        if self.kind not in KINDS:
            raise ValueError(f"unknown alert kind {self.kind!r}")
        if self.fast_window < 1 or self.slow_window < self.fast_window:
            raise ValueError(
                f"windows must satisfy 1 <= fast <= slow, got "
                f"{self.fast_window}/{self.slow_window}")


@dataclass(frozen=True)
class Alert:
    """One fired alert — deterministic: (tick, rule) sequences are
    identical run to run for a fixed seed."""
    tick: int
    rule: str
    series: str
    fast: float
    slow: float


def default_rules() -> tuple[AlertRule, ...]:
    """The stock rule set of the ALERT TABLE above."""
    return (
        AlertRule(name="alert_failover_burn",
                  series="serve_failover_total", kind=RATE,
                  fast_threshold=0.25, slow_threshold=0.02),
        AlertRule(name="alert_shed_burn",
                  series="serve_requests_shed", kind=RATE,
                  fast_threshold=0.5, slow_threshold=0.1),
        AlertRule(name="alert_slo_burn",
                  series="serve_slo_attainment", kind=BURN,
                  objective=0.95,
                  fast_threshold=0.35, slow_threshold=0.15),
    )


class AlertEngine:
    """Evaluate a rule set each tick against a
    :class:`SeriesStore`; fired alerts append to :attr:`alerts`,
    count on ``serve_alerts_fired``, and mark the trace with an
    ``alert.fired`` instant so incidents land on the same timeline as
    the spans and counter tracks."""

    def __init__(self, store: SeriesStore, rules=None, metrics=None,
                 tracer=None, capacity: int = 4096):
        self.store = store
        self.rules = tuple(rules) if rules is not None \
            else default_rules()
        self.metrics = metrics
        self.tracer = tracer
        # cooldown bounds the fire RATE; capacity bounds the log in a
        # long-lived daemon (a smoke run never comes near either)
        self.alerts: deque[Alert] = deque(maxlen=int(capacity))
        self._streak: dict[str, int] = {}
        self._cooldown_until: dict[str, int] = {}

    def _measure(self, rule: AlertRule) -> tuple[float, float]:
        if rule.kind == RATE:
            return (self.store.rate(rule.series, rule.fast_window),
                    self.store.rate(rule.series, rule.slow_window))
        out = []
        for w in (rule.fast_window, rule.slow_window):
            vals = self.store.values(rule.series, w)
            out.append(max(0.0, rule.objective - sum(vals) / len(vals))
                       if vals else 0.0)
        return out[0], out[1]

    def evaluate(self, tick: int) -> list[Alert]:
        """One evaluation pass; returns the alerts fired THIS tick."""
        tick = int(tick)
        fired: list[Alert] = []
        for rule in self.rules:
            fast, slow = self._measure(rule)
            breach = (fast > rule.fast_threshold
                      and slow > rule.slow_threshold)
            streak = self._streak.get(rule.name, 0) + 1 if breach else 0
            self._streak[rule.name] = streak   # ktp: allow(KTP005) keyed by fixed rule set
            if not breach or streak < rule.hold_ticks:
                continue
            if tick < self._cooldown_until.get(rule.name, -1 << 62):
                continue
            alert = Alert(tick=tick, rule=rule.name,
                          series=rule.series, fast=fast, slow=slow)
            self.alerts.append(alert)
            fired.append(alert)
            # ktp: allow(KTP005) keyed by fixed rule set
            self._cooldown_until[rule.name] = tick + rule.cooldown_ticks
            if self.metrics is not None:
                self.metrics.inc("serve_alerts_fired")
            if self.tracer is not None:
                self.tracer.instant("alert.fired", attrs={
                    "rule": rule.name, "series": rule.series,
                    "tick": tick, "fast": round(fast, 4),
                    "slow": round(slow, 4)})
        return fired


class FlightRecorder:
    """Controller-shaped recorder: ``recorder(tick, stats)`` plugs
    straight into ``run_load``/``run_fleet``'s ``controller=`` seam
    (chain an existing controller via ``inner=``).  Each tick it sets
    the running ``serve_slo_attainment`` gauge from the driver's
    stats, samples the registry into the store, and evaluates the
    alert rules.  ``obs_wall_s`` accumulates the recorder's own wall
    cost — the ≤ 5 % sampling-overhead number the bench reports."""

    def __init__(self, metrics, rules=None, tracer=None,
                 capacity: int = 4096, inner=None):
        self.metrics = metrics
        self.store = SeriesStore(metrics, capacity=capacity)
        self.alert_engine = AlertEngine(self.store, rules=rules,
                                        metrics=metrics, tracer=tracer,
                                        capacity=capacity)
        self.inner = inner
        self.ticks = 0
        self.obs_wall_s = 0.0

    @property
    def alerts(self) -> list[Alert]:
        return list(self.alert_engine.alerts)

    def alert_log(self) -> list[tuple[int, str]]:
        """The determinism digest two twin runs must agree on."""
        return [(a.tick, a.rule) for a in self.alerts]

    def __call__(self, tick: int, stats: dict) -> None:
        if self.inner is not None:
            self.inner(tick, stats)
        t0 = time.perf_counter()
        att = stats.get("attainment")
        if att is not None:
            self.metrics.set_gauge("serve_slo_attainment", float(att))
        self.store.sample(tick)
        self.alert_engine.evaluate(tick)
        self.ticks += 1
        self.obs_wall_s += time.perf_counter() - t0

    @property
    def overhead_per_tick_s(self) -> float:
        return self.obs_wall_s / self.ticks if self.ticks else 0.0


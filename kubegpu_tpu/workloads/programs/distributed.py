"""Shared bootstrap for distributed workloads: consume the injected env.

This is the workload-side half of the injection contract (SURVEY.md §4.5
last line): the crishim set ``TPU_WORKER_ID`` / ``JAX_COORDINATOR_ADDRESS``
/ ``JAX_NUM_PROCESSES``; ``init_from_env()`` turns them into a live
``jax.distributed`` runtime so collectives ride the allocated slice.
"""

from __future__ import annotations

import os
from dataclasses import dataclass


@dataclass
class WorkerEnv:
    worker_id: int
    num_workers: int
    coordinator: str
    visible_chips: list[int]
    hostnames: list[str]
    millitpu: int | None
    hbm_gib: float | None = None   # allocated HBM (crishim-injected)
    slice_id: str = ""             # ICI domain this worker sits in


def read_env() -> WorkerEnv:
    chips = os.environ.get("TPU_VISIBLE_CHIPS", "")
    milli = os.environ.get("KUBETPU_MILLITPU")
    hbm = os.environ.get("KUBETPU_HBM_GIB")
    return WorkerEnv(
        worker_id=int(os.environ.get("TPU_WORKER_ID", "0")),
        num_workers=int(os.environ.get("JAX_NUM_PROCESSES", "1")),
        coordinator=os.environ.get("JAX_COORDINATOR_ADDRESS", ""),
        visible_chips=[int(c) for c in chips.split(",") if c != ""],
        hostnames=[h for h in os.environ.get(
            "TPU_WORKER_HOSTNAMES", "").split(",") if h],
        millitpu=int(milli) if milli else None,
        hbm_gib=float(hbm) if hbm else None,
        slice_id=os.environ.get("KUBETPU_SLICE_ID", ""),
    )


def init_from_env() -> WorkerEnv:
    """jax.distributed.initialize from the injected env (no-op for
    single-worker pods)."""
    env = read_env()
    if env.num_workers > 1:
        import jax
        jax.distributed.initialize(
            coordinator_address=env.coordinator,
            num_processes=env.num_workers,
            process_id=env.worker_id)
    return env

"""ResNet-50 (flax) — BASELINE config 2 workload (single-chip JAX
ResNet-50).  bfloat16 conv/matmul path for the MXU, f32 batch norm.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


class Bottleneck(nn.Module):
    filters: int
    strides: int = 1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=jnp.float32)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3), strides=(self.strides,) * 2)(y)
        y = nn.relu(norm()(y))
        y = conv(self.filters * 4, (1, 1))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1),
                            strides=(self.strides,) * 2)(residual)
            residual = norm()(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int] = (3, 4, 6, 3)   # ResNet-50
    num_classes: int = 1000
    width: int = 64
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(self.width, (7, 7), strides=(2, 2), use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.relu(nn.BatchNorm(use_running_average=not train,
                                 momentum=0.9, dtype=jnp.float32)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                x = Bottleneck(self.width * 2 ** i,
                               strides=2 if j == 0 and i > 0 else 1,
                               dtype=self.dtype)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def resnet50(num_classes: int = 1000) -> ResNet:
    return ResNet(num_classes=num_classes)


def resnet_tiny(num_classes: int = 10) -> ResNet:
    """Structure-preserving test-scale variant."""
    return ResNet(stage_sizes=(1, 1), num_classes=num_classes, width=8,
                  dtype=jnp.float32)


def make_resnet_train_step(model: ResNet, optimizer):
    import optax

    def loss_fn(params, batch_stats, images, labels):
        logits, updates = model.apply(
            {"params": params, "batch_stats": batch_stats}, images,
            train=True, mutable=["batch_stats"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()
        return loss, updates["batch_stats"]

    def step(params, batch_stats, opt_state, images, labels):
        (loss, batch_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch_stats, images, labels)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, batch_stats, opt_state, loss

    return step

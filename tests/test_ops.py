"""Flash-attention kernel numerics (pallas interpret mode vs XLA ref)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubegpu_tpu.ops import flash_attention, xla_attention


def rand_qkv(key, b=2, hq=4, hkv=4, t=128, s=128, d=64, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    return (jax.random.normal(kq, (b, hq, t, d), dtype),
            jax.random.normal(kk, (b, hkv, s, d), dtype),
            jax.random.normal(kv, (b, hkv, s, d), dtype))


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_xla_reference(self, causal):
        q, k, v = rand_qkv(jax.random.PRNGKey(0))
        ref = xla_attention(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal, block_q=64,
                              block_k=64, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_gqa_heads(self):
        q, k, v = rand_qkv(jax.random.PRNGKey(1), hq=8, hkv=2)
        ref = xla_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, block_q=64,
                              block_k=64, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_multi_kv_block_accumulation(self):
        q, k, v = rand_qkv(jax.random.PRNGKey(2), t=128, s=256)
        ref = xla_attention(q, k, v, causal=False)
        out = flash_attention(q, k, v, causal=False, block_q=32,
                              block_k=32, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_mismatched_block_sizes(self):
        """Regression (review): block_q > block_k must not drop K blocks
        near the causal diagonal."""
        q, k, v = rand_qkv(jax.random.PRNGKey(7), t=128, s=128)
        ref = xla_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, block_q=64,
                              block_k=32, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)
        out2 = flash_attention(q, k, v, causal=True, block_q=32,
                               block_k=64, interpret=True)
        np.testing.assert_allclose(np.asarray(out2), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_causal_alignment_t_lt_s(self):
        """Regression (review): t < s causal must be end-aligned in both
        implementations (decode/suffix convention)."""
        q, k, v = rand_qkv(jax.random.PRNGKey(8), t=64, s=128)
        ref = xla_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, block_q=32,
                              block_k=32, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_causal_t_gt_s_rejected(self):
        """Regression (review): t > s causal is ill-defined — both
        implementations must refuse rather than return garbage."""
        q, k, v = rand_qkv(jax.random.PRNGKey(9), t=128, s=64)
        with pytest.raises(ValueError):
            xla_attention(q, k, v, causal=True)
        with pytest.raises(ValueError):
            flash_attention(q, k, v, causal=True, interpret=True)

    def test_odd_shapes_fall_back(self):
        q, k, v = rand_qkv(jax.random.PRNGKey(3), t=100, s=100)
        out = flash_attention(q, k, v, causal=True, interpret=True)
        ref = xla_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_causal_masks_future(self):
        """Changing future tokens must not change past outputs."""
        q, k, v = rand_qkv(jax.random.PRNGKey(4), t=64, s=64)
        out1 = xla_attention(q, k, v, causal=True)
        k2 = k.at[:, :, 32:, :].set(0.0)
        v2 = v.at[:, :, 32:, :].set(0.0)
        out2 = xla_attention(q, k2, v2, causal=True)
        np.testing.assert_allclose(np.asarray(out1[:, :, :32]),
                                   np.asarray(out2[:, :, :32]),
                                   atol=1e-6)

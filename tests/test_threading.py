"""Threading-stress on the scheduling stack (SURVEY.md §6 race-detection
row): the scheduler loop runs in its own thread, as in a real deployment,
while other threads churn pods and flip node health through the apiserver.
Everything coordinates through FakeApiServer (thread-safe); the invariants
checked are the allocator's no-double-booking guarantees."""

import random
import threading

from kubegpu_tpu.cluster import SimCluster, tpu_pod
from kubegpu_tpu.kubemeta import GangSpec, NotFound, PodPhase
from kubegpu_tpu.tpuplugin.backend import MILLICHIPS_PER_CHIP


def test_scheduler_loop_vs_churn_and_faults():
    cl = SimCluster(["v5e-16", "v4-8"])
    stop = threading.Event()
    errors: list[BaseException] = []

    def guard(fn):
        def run():
            try:
                fn()
            except BaseException as e:  # surfaced after join
                errors.append(e)
                stop.set()
        return run

    def scheduler_loop():
        while not stop.is_set():
            cl.step()
            cl.reap(timeout=0)

    def submitter():
        rng = random.Random(1)
        i = 0
        while not stop.is_set() and i < 60:
            i += 1
            size = rng.choice([1, 2, 4])
            chips = rng.choice([1, 2])
            if size == 1:
                cl.submit(tpu_pod(f"s{i}", chips=chips, command=["x"]))
            else:
                cl.submit(*[
                    tpu_pod(f"g{i}-{k}", chips=chips,
                            gang=GangSpec(name=f"g{i}", size=size, index=k),
                            command=["x"])
                    for k in range(size)])

    def reaper():
        rng = random.Random(2)
        while not stop.is_set():
            pods = [p for p in cl.api.list("Pod")
                    if p.status.phase != PodPhase.PENDING]
            if pods:
                victim = rng.choice(pods)
                try:
                    cl.api.delete("Pod", victim.name,
                                  namespace=victim.metadata.namespace)
                except NotFound:
                    pass

    def health_flipper():
        rng = random.Random(3)
        nodes = [a.node_name for a in cl.agents]
        while not stop.is_set():
            n = rng.choice(nodes)
            try:
                cl.api.set_node_ready(n, rng.random() < 0.7)
            except NotFound:
                pass

    threads = [threading.Thread(target=guard(f), daemon=True)
               for f in (scheduler_loop, submitter, reaper, health_flipper)]
    for t in threads:
        t.start()
    # let them contend, then stop
    threads[1].join(timeout=20)  # submitter finishes its 60 gangs
    stop.set()
    for t in threads:
        t.join(timeout=20)
    assert not errors, errors[0]

    # restore every node, settle, and check invariants against truth
    for a in cl.agents:
        cl.api.set_node_ready(a.node_name, True)
        a.advertise()
    cl.step()
    for st in cl.scheduler.slices.values():
        for coord, used in st.used_millichips.items():
            assert 0 <= used <= MILLICHIPS_PER_CHIP, (coord, used)
    seen = {}
    for gang, asg in cl.scheduler._committed.items():
        for p in asg.pods:
            for ch in p.chips:
                if ch.millichips == MILLICHIPS_PER_CHIP:
                    key = (asg.slice_id, ch.coord)
                    assert key not in seen, (key, gang, seen[key])
                    seen[key] = gang
    # annotation truth agrees with the cache after a full re-sync
    cl.scheduler.sync()
    for st in cl.scheduler.slices.values():
        for coord, used in st.used_millichips.items():
            assert 0 <= used <= MILLICHIPS_PER_CHIP, (coord, used)
    cl.close()


def test_webhook_bind_vs_pod_churn_no_deadlock():
    """Review r2 regression (ABBA deadlock): webhook threads hold the
    scheduler lock and call into the apiserver, while apiserver watch
    callbacks call back into the scheduler.  Delivery outside the
    apiserver lock must keep these from deadlocking."""
    import threading

    from kubegpu_tpu.cluster import SimCluster, tpu_pod
    from kubegpu_tpu.kubemeta import Conflict, NotFound

    cl = SimCluster(["v5e-16"])
    stop = threading.Event()
    errors = []

    def guard(fn):
        def run():
            try:
                fn()
            except Exception as e:   # pragma: no cover - failure path
                errors.append(e)
                stop.set()
        return run

    def binder():
        # hammer the wire verbs: filter + bind of short-lived singles
        i = 0
        while not stop.is_set() and i < 200:
            name = f"wire-{i}"
            i += 1
            try:
                cl.api.create("Pod", tpu_pod(name, chips=1,
                                             command=["x"]))
            except Conflict:
                continue
            nodes = [n.name for n in cl.api.list("Node")]
            pod = cl.api.get("Pod", name)
            feasible, _ = cl.scheduler.filter(pod, nodes)
            if feasible:
                cl.scheduler.bind(name, feasible[0])
            try:
                cl.api.delete("Pod", name)   # fires watch → release
            except NotFound:
                pass

    def churner():
        # create/delete pods from another thread: every delete delivers
        # a watch event that re-enters the scheduler
        i = 0
        while not stop.is_set() and i < 200:
            name = f"churn-{i}"
            i += 1
            try:
                cl.api.create("Pod", tpu_pod(name, chips=1,
                                             command=["x"]))
                cl.scheduler.run_once()
                cl.api.delete("Pod", name)
            except (Conflict, NotFound):
                pass

    threads = [threading.Thread(target=guard(f), daemon=True)
               for f in (binder, churner)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    alive = [t for t in threads if t.is_alive()]
    stop.set()
    assert not alive, "deadlock: threads still blocked after 60s"
    assert not errors, errors[0]
    cl.close()

"""Scheduler-specific tests: extender verbs, resilience, recovery.

Several cases here are regressions from the round-1 code review:
re-sync must not orphan committed gangs; malformed pods must not abort
the scheduling pass; fractional gang pods must not silently become CPU
pods; filter() must answer per-node.
"""

from kubegpu_tpu.cluster import SimCluster, tpu_pod
from kubegpu_tpu.kubemeta import GangSpec, PodPhase
from kubegpu_tpu.kubemeta.codec import pod_allocation
from kubegpu_tpu.scheduler import DeviceScheduler


class TestExtenderVerbs:
    def test_filter_per_node_feasibility(self):
        """Every host with enough free chips is feasible — not just the
        argmax host (review finding #4)."""
        cl = SimCluster(["v5e-16"])
        pod = tpu_pod("p", chips=4, command=["x"])
        cl.api.create("Pod", pod)
        nodes = [n.name for n in cl.api.list("Node")]
        feasible, reasons = cl.scheduler.filter(pod, nodes)
        assert set(feasible) == set(nodes), reasons

    def test_filter_rejects_busy_node(self):
        cl = SimCluster(["v5e-16"])
        # fill host 0's block via a 4-chip pod pinned by scheduling
        cl.submit(tpu_pod("warm", chips=4, command=["x"]))
        cl.step()
        warm_node = cl.api.get("Pod", "warm").spec.node_name
        pod = tpu_pod("p", chips=4, command=["x"])
        cl.api.create("Pod", pod)
        feasible, reasons = cl.scheduler.filter(
            pod, [n.name for n in cl.api.list("Node")])
        assert warm_node not in feasible
        assert len(feasible) == 3

    def test_prioritize_scores_per_node(self):
        cl = SimCluster(["v5e-16"])
        pod = tpu_pod("p", chips=1, command=["x"])
        cl.api.create("Pod", pod)
        scores = cl.scheduler.prioritize(
            pod, [n.name for n in cl.api.list("Node")])
        assert all(0.0 <= s <= 10.0 for s in scores.values())
        assert any(s > 0 for s in scores.values())

    def test_filter_zero_device_pod_fits_everywhere(self):
        cl = SimCluster(["v4-8"])
        pod = tpu_pod("p", chips=0, command=["x"])
        cl.api.create("Pod", pod)
        feasible, _ = cl.scheduler.filter(
            pod, [n.name for n in cl.api.list("Node")])
        assert feasible


class TestResilience:
    def test_bad_mesh_axes_does_not_abort_pass(self):
        """Review finding #2: one malformed pod must not starve the rest.
        A mismatched mesh-axes hint is dropped, not fatal."""
        cl = SimCluster(["v4-8"])
        bad = tpu_pod("bad", chips=2, mesh_axes={"dp": 3, "tp": 5},
                      command=["x"])
        good = tpu_pod("good", chips=1, command=["x"])
        cl.submit(bad, good)
        result, _ = cl.step()
        assert "good" in result.scheduled
        assert "bad" in result.scheduled  # hint dropped, pod still placed

    def test_fractional_gang_pod_gets_allocation(self):
        """Review finding #3: a gang-annotated fractional pod must be a
        fractional allocation, not a silent CPU fallback."""
        cl = SimCluster(["v4-8"])
        cl.submit(tpu_pod("f0", millitpu=500,
                          gang=GangSpec(name="fg", size=1, index=0),
                          command=["x"]))
        result, _ = cl.step()
        assert result.scheduled == ["f0"]
        alloc = pod_allocation(cl.api.get("Pod", "f0"))
        assert alloc is not None
        assert alloc.chips[0].millichips == 500

    def test_heterogeneous_gang_rejected_not_fatal(self):
        cl = SimCluster(["v4-8"])
        cl.submit(tpu_pod("h0", chips=1,
                          gang=GangSpec(name="het", size=2, index=0),
                          command=["x"]))
        cl.submit(tpu_pod("h1", chips=2,
                          gang=GangSpec(name="het", size=2, index=1),
                          command=["x"]))
        cl.submit(tpu_pod("ok", chips=1, command=["x"]))
        result, _ = cl.step()
        assert "ok" in result.scheduled
        assert {"h0", "h1"} <= set(result.unschedulable)

    def test_resync_preserves_release_path(self):
        """Review finding #1 (critical): after observe_node_change(), a
        completing pod must still release its chips."""
        cl = SimCluster(["v4-8"])
        cl.submit(tpu_pod("a", chips=4, command=["x"]))
        cl.step()
        cl.scheduler.observe_node_change()  # re-sync wipes in-memory state
        st = next(iter(cl.scheduler.slices.values()))
        assert sum(st.used_millichips.values()) == 4000  # still accounted
        cl.reap()  # FakeRuntime → Succeeded → release
        st = next(iter(cl.scheduler.slices.values()))
        assert sum(st.used_millichips.values()) == 0
        cl.submit(tpu_pod("b", chips=4, command=["x"]))
        result, _ = cl.step()
        assert result.scheduled == ["b"]

    def test_restarted_scheduler_releases_on_completion(self):
        """Full restart: a fresh DeviceScheduler must release a gang it
        never scheduled itself (annotation truth only)."""
        cl = SimCluster(["v5e-16"])
        for i in range(2):
            cl.submit(tpu_pod(f"g-{i}", chips=4,
                              gang=GangSpec(name="g", size=2, index=i),
                              command=["x"]))
        cl.step()
        fresh = DeviceScheduler(cl.api)
        used = sum(sum(st.used_millichips.values())
                   for st in fresh.slices.values())
        assert used == 8000
        fresh.return_pod_resources("g-0", "default")
        # gang partially alive → not yet released
        used = sum(sum(st.used_millichips.values())
                   for st in fresh.slices.values())
        assert used == 8000
        fresh.return_pod_resources("g-1", "default")
        used = sum(sum(st.used_millichips.values())
                   for st in fresh.slices.values())
        assert used == 0


class TestFifoFairness:
    def test_gang_queued_first_beats_later_single(self):
        """FIFO across unit kinds: a whole-slice gang submitted BEFORE a
        fractional single must win the slice — previously singles were
        always scheduled first and a 300-millitpu pod could permanently
        starve a 16-chip gang (observed via kubetpu apply)."""
        cl = SimCluster(["v5e-16"])
        cl.submit(*[
            tpu_pod(f"g-{i}", chips=4,
                    gang=GangSpec(name="g", size=4, index=i),
                    command=["x"])
            for i in range(4)
        ])
        cl.submit(tpu_pod("frac", millitpu=300, command=["x"]))
        result, _ = cl.step()
        assert set(result.scheduled) == {f"g-{i}" for i in range(4)}
        assert cl.pod_phase("frac") == PodPhase.PENDING
        cl.close()

    def test_single_queued_first_still_wins(self):
        cl = SimCluster(["v5e-16"])
        cl.submit(tpu_pod("frac", millitpu=300, command=["x"]))
        cl.submit(*[
            tpu_pod(f"g-{i}", chips=4,
                    gang=GangSpec(name="g", size=4, index=i),
                    command=["x"])
            for i in range(4)
        ])
        result, _ = cl.step()
        assert "frac" in result.scheduled
        assert set(result.unschedulable) == {f"g-{i}" for i in range(4)}
        cl.close()

    def test_incomplete_gang_blocks_later_single_within_grace(self):
        """An incomplete gang at the queue head holds later units back
        during its arrival grace — the straggler member must not find the
        slice fragmented by a single that arrived after the gang."""
        cl = SimCluster(["v5e-16"])
        cl.submit(*[
            tpu_pod(f"g-{i}", chips=4,
                    gang=GangSpec(name="g", size=4, index=i),
                    command=["x"])
            for i in range(3)  # member 3 is late
        ])
        cl.submit(tpu_pod("frac", millitpu=300, command=["x"]))
        result, _ = cl.step()
        assert result.scheduled == []
        assert "frac" in result.held
        # straggler arrives → gang gets the whole slice, then frac pends
        cl.submit(tpu_pod("g-3", chips=4,
                          gang=GangSpec(name="g", size=4, index=3),
                          command=["x"]))
        result, _ = cl.step()
        assert set(result.scheduled) == {f"g-{i}" for i in range(4)}
        assert cl.pod_phase("frac") == PodPhase.PENDING
        cl.close()

    def test_grace_expiry_unblocks_queue(self):
        """Grace 0: an incomplete gang never blocks — no deadlock when a
        gang member never shows up."""
        from kubegpu_tpu.config import KubeTpuConfig
        cfg = KubeTpuConfig.load(overrides=[
            "backend.slice_types=v5e-16", "scheduler.gang_grace_s=0"])
        cl = SimCluster.from_config(cfg)
        cl.submit(tpu_pod("g-0", chips=4,
                          gang=GangSpec(name="g", size=4, index=0),
                          command=["x"]))
        cl.submit(tpu_pod("solo", chips=1, command=["x"]))
        result, _ = cl.step()
        assert "solo" in result.scheduled      # flowed past the held gang
        assert "g-0" in result.held
        cl.close()


class TestPriorityPreemptionBackfill:
    def test_priority_orders_queue(self):
        """Higher priority schedules first even when submitted later."""
        cl = SimCluster(["v4-8"])
        cl.submit(tpu_pod("low", chips=4, command=["x"], priority=0))
        cl.submit(tpu_pod("high", chips=4, command=["x"], priority=5))
        result, _ = cl.step()
        # v4-8 has 4 chips — only one of the two fits
        assert result.scheduled == ["high"]
        assert "low" in result.unschedulable
        cl.close()

    def test_preemption_evicts_lower_priority_gang(self):
        cl = SimCluster(["v4-8"])
        cl.submit(*[
            tpu_pod(f"low-{i}", chips=1,
                    gang=GangSpec(name="low", size=4, index=i),
                    command=["x"], priority=0)
            for i in range(4)
        ])
        result, _ = cl.step()
        assert len(result.scheduled) == 4
        # high-priority gang needs the whole slice → must preempt
        cl.submit(*[
            tpu_pod(f"hi-{i}", chips=2,
                    gang=GangSpec(name="hi", size=2, index=i),
                    command=["x"], priority=10)
            for i in range(2)
        ])
        result, _ = cl.step()
        assert set(result.scheduled) == {"hi-0", "hi-1"}
        # victims were requeued whole as fresh PENDING pods
        for i in range(4):
            assert cl.pod_phase(f"low-{i}") == PodPhase.PENDING
        assert cl.metrics.snapshot()["counters"]["gangs_preempted"] == 1.0
        cl.close()

    def test_no_preemption_among_equal_priority(self):
        cl = SimCluster(["v4-8"])
        cl.submit(tpu_pod("first", chips=4, command=["x"], priority=3))
        cl.step()
        cl.submit(tpu_pod("second", chips=4, command=["x"], priority=3))
        result, _ = cl.step()
        assert "second" in result.unschedulable
        assert cl.pod_phase("first") != PodPhase.PENDING
        cl.close()

    def test_preemption_minimizes_victims(self):
        """Evict exactly as many victims as the fit needs, no more."""
        cl = SimCluster(["v5e-16"])
        cl.submit(tpu_pod("a", chips=4, command=["x"], priority=0))
        cl.submit(tpu_pod("b", chips=4, command=["x"], priority=0))
        result, _ = cl.step()
        assert len(result.scheduled) == 2
        # 8 free; asking 12 (3 host-local pods) ⇒ exactly one victim goes
        cl.submit(*[
            tpu_pod(f"big-{i}", chips=4,
                    gang=GangSpec(name="big", size=3, index=i),
                    command=["x"], priority=7)
            for i in range(3)
        ])
        result, _ = cl.step()
        assert set(result.scheduled) == {f"big-{i}" for i in range(3)}
        phases = {n: cl.pod_phase(n) for n in ("a", "b")}
        assert sorted(p == PodPhase.PENDING for p in phases.values()) \
            == [False, True], phases
        cl.close()

    def test_backfill_past_incomplete_gang(self):
        """A later single schedules during the barrier grace when the
        what-if trial shows the gang still fits afterwards."""
        cl = SimCluster(["v5e-16"])
        cl.submit(*[
            tpu_pod(f"g-{i}", chips=2,
                    gang=GangSpec(name="g", size=4, index=i),
                    command=["x"])
            for i in range(3)  # 8 chips once complete; member 3 late
        ])
        cl.submit(tpu_pod("solo", chips=4, command=["x"]))
        result, _ = cl.step()
        assert "solo" in result.scheduled          # backfilled
        assert "g-0" in result.held
        cl.submit(tpu_pod("g-3", chips=2,
                          gang=GangSpec(name="g", size=4, index=3),
                          command=["x"]))
        result, _ = cl.step()
        assert set(result.scheduled) == {f"g-{i}" for i in range(4)}
        cl.close()

    def test_backfill_denied_when_it_would_hurt_the_gang(self):
        """The conservative check: a single whose placement would break
        the blocked gang's fit stays held (the pre-backfill behavior)."""
        cl = SimCluster(["v5e-16"])
        cl.submit(*[
            tpu_pod(f"g-{i}", chips=4,
                    gang=GangSpec(name="g", size=4, index=i),
                    command=["x"])
            for i in range(3)  # whole slice once complete
        ])
        cl.submit(tpu_pod("solo", chips=1, command=["x"]))
        result, _ = cl.step()
        assert result.scheduled == []
        assert "solo" in result.held
        cl.close()

    def test_high_priority_bypasses_barrier(self):
        """Priority ordering puts a high-priority unit ahead of the
        in-grace incomplete gang entirely."""
        cl = SimCluster(["v5e-16"])
        cl.submit(*[
            tpu_pod(f"g-{i}", chips=4,
                    gang=GangSpec(name="g", size=4, index=i),
                    command=["x"], priority=0)
            for i in range(3)
        ])
        # would be denied backfill (takes the whole slice) but outranks
        cl.submit(*[
            tpu_pod(f"urgent-{i}", chips=4,
                    gang=GangSpec(name="urgent", size=4, index=i),
                    command=["x"], priority=9)
            for i in range(4)
        ])
        result, _ = cl.step()
        assert set(result.scheduled) == {f"urgent-{i}" for i in range(4)}
        cl.close()

    def test_preempted_gang_comes_back_after_release(self):
        """The full cycle: preempted → pending → high-pri job finishes →
        victim reschedules."""
        cl = SimCluster(["v4-8"])
        cl.submit(tpu_pod("low", chips=4, command=["x"], priority=0))
        cl.step()
        cl.submit(tpu_pod("hi", chips=4, command=["x"], priority=5))
        cl.step()
        assert cl.pod_phase("low") == PodPhase.PENDING
        # hi's container finishes (FakeRuntime exits 0 immediately on reap)
        cl.reap(timeout=0)
        result, _ = cl.step()
        assert "low" in result.scheduled
        cl.close()

    def test_backfill_protects_all_held_units_not_just_barrier(self):
        """Review regression: with gang A (barrier, small ask) and gang B
        (second in-grace gang, big ask) both held, a later single must
        not steal the chips B needs just because A's fit survives."""
        cl = SimCluster(["v5e-16"])
        # A: incomplete, projected 2 pods x 2 chips = 4 chips
        cl.submit(tpu_pod("a-0", chips=2,
                          gang=GangSpec(name="a", size=2, index=0),
                          command=["x"]))
        # B: incomplete, projected 3 pods x 4 chips = 12 chips
        cl.submit(*[
            tpu_pod(f"b-{i}", chips=4,
                    gang=GangSpec(name="b", size=3, index=i),
                    command=["x"])
            for i in range(2)
        ])
        # C: later single asking 4 chips — A (4) still fits after C (4),
        # but A + B (16) would not; C must be held
        cl.submit(tpu_pod("c", chips=4, command=["x"]))
        result, _ = cl.step()
        assert result.scheduled == []
        assert "c" in result.held
        # stragglers arrive: both gangs schedule, then C fails (full)
        cl.submit(tpu_pod("a-1", chips=2,
                          gang=GangSpec(name="a", size=2, index=1),
                          command=["x"]))
        cl.submit(tpu_pod("b-2", chips=4,
                          gang=GangSpec(name="b", size=3, index=2),
                          command=["x"]))
        result, _ = cl.step()
        scheduled = set(result.scheduled)
        assert {"a-0", "a-1", "b-0", "b-1", "b-2"} <= scheduled
        cl.close()


class TestLatencyAccounting:
    def test_failed_decisions_enter_latency_histogram(self):
        """VERDICT r1 #3: unschedulable decisions are the most expensive
        code paths and must be counted in the p50/p99 metric, not only
        the successes."""
        cl = SimCluster(["v4-8"])
        cl.submit(tpu_pod("fits", chips=2, command=["x"]))
        cl.step()
        count_after_ok = cl.metrics.snapshot()[
            "histograms"]["schedule_latency_ms"]["count"]
        assert count_after_ok == 1
        # 4 pods x 4 chips = 16 chips > the slice's 8 → unschedulable
        cl.submit(*[
            tpu_pod(f"big-{i}", chips=4,
                    gang=GangSpec(name="big", size=4, index=i),
                    command=["x"])
            for i in range(4)
        ])
        result, _ = cl.step()
        assert len(result.unschedulable) == 4
        snap = cl.metrics.snapshot()
        assert snap["histograms"]["schedule_latency_ms"]["count"] == 2
        assert snap["counters"]["gangs_failed"] == 1.0
        cl.close()

    def test_quota_denied_counts_as_decision(self):
        cl = SimCluster(["v4-8"])
        cl.set_quota("team-a", chips=1)
        cl.submit(tpu_pod("over", chips=2, namespace="team-a",
                          command=["x"]))
        cl.step()
        snap = cl.metrics.snapshot()
        assert snap["histograms"]["schedule_latency_ms"]["count"] == 1
        assert snap["counters"]["gangs_failed"] == 1.0
        cl.close()


class TestServingTrafficModel:
    """Serving gangs carry the tp degree AND the serving workload kind,
    so topology scoring sees a serving slice: tp rings stay the hot
    axis while dp-replica hops are nearly free (no collective ever
    crosses replica boundaries)."""

    def test_serving_gang_request_carries_serving_weights(self):
        cl = SimCluster(["v5e-16"])
        pods = [
            tpu_pod(f"s{i}", chips=4,
                    gang=GangSpec(name="tp-serve", size=2, index=i),
                    mesh_axes={"dp": 2, "tp": 4},
                    workload="serving", command=["x"])
            for i in range(2)
        ]
        req = cl.scheduler._request_for_gang("tp-serve", pods)
        assert req.mesh_axes == {"dp": 2, "tp": 4}
        assert req.axis_weights == {"dp": 0.05, "tp": 8.0}
        cl.close()

    def test_training_gang_keeps_default_weights(self):
        cl = SimCluster(["v5e-16"])
        pods = [
            tpu_pod(f"t{i}", chips=4,
                    gang=GangSpec(name="train", size=2, index=i),
                    mesh_axes={"dp": 2, "tp": 4}, command=["x"])
            for i in range(2)
        ]
        req = cl.scheduler._request_for_gang("train", pods)
        assert req.axis_weights is None   # resolver falls back to
        #                                   the training defaults
        cl.close()

    def test_tp_serving_single_pod_schedules(self):
        """The tp_serving workload spec (one pod, dp x tp chips)
        places end-to-end and its allocation covers the whole ask."""
        from kubegpu_tpu.workloads.specs import tp_serving
        pods, slice_types = tp_serving(tp=4, dp=1)
        cl = SimCluster(slice_types)
        for p in pods:
            p.spec.containers[0].command = ["x"]   # don't exec
            cl.submit(p)
        cl.step()
        alloc = pod_allocation(cl.api.get("Pod", "tp-serve"))
        assert alloc is not None and len(alloc.chips) == 4
        cl.close()

    def test_serving_axis_weights_resolver(self):
        from kubegpu_tpu.topology.locality import (
            resolve_axis_weights,
            serving_axis_weights,
        )
        w = serving_axis_weights({"dp": 2, "tp": 4})
        assert w["tp"] > 100 * w["dp"]    # replicas are nearly free
        # explicit weights still win over both default tables
        assert resolve_axis_weights({"tp": 2}, w)["tp"] == w["tp"]

    def test_serving_metrics_surfaces_spec_acceptance(self):
        """Harvested serving-pod metric lines (incl. the speculative
        engine's acceptance echo) surface through the scheduler's
        serving_metrics() view, and acceptance lands as the
        serving_spec_acceptance gauge on the scrape surface."""
        import json as _json

        from kubegpu_tpu.crishim.agent import harvest_workload_metrics
        cl = SimCluster(["v4-8"])
        stdout = "\n".join(_json.dumps({"metric": m, "value": v}) for
                           m, v in (
            ("serve_engine_tokens_per_s", 1234.5),
            ("serve_engine_cfg_spec_gamma", 4),
            ("serve_engine_cfg_draft_layers", 8),
            ("serve_engine_spec_accept_rate", 0.625),
            ("serve_engine_spec_tokens_per_tick", 3.5),
            # fault-tolerance echo (ISSUE 4): a serving pod that
            # failed over reports it; the scheduler mirrors it onto
            # the scrape surface next to gang evictions
            ("serve_failover_total", 2),
            ("serve_requests_retried", 3),
            ("serve_slots_quarantined", 1),
        ))
        seen = harvest_workload_metrics(stdout, cl.metrics, "serve-0")
        assert "serve_engine_spec_accept_rate" in seen
        out = cl.scheduler.serving_metrics()
        assert out["serve_engine_spec_accept_rate"] == 0.625
        assert out["serve_engine_cfg_spec_gamma"] == 4
        assert out["serve_engine_spec_tokens_per_tick"] == 3.5
        assert cl.metrics.gauge("serving_spec_acceptance") == 0.625
        assert out["serve_failover_total"] == 2
        assert cl.metrics.gauge("serving_failover_total") == 2
        assert cl.metrics.gauge("serving_requests_retried") == 3
        assert cl.metrics.gauge("serving_slots_quarantined") == 1
        cl.close()

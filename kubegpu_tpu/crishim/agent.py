"""Node agent: advertiser + kubelet-ish pod lifecycle.

Reference parity: the ``kubeadvertise`` loop PATCHing the Node object
(SURVEY.md §4.1) plus the kubelet role in §4.3 (seeing pods bound to this
node and calling the CRI).  One agent per (simulated) TPU host VM.
"""

from __future__ import annotations

import json
import math

from kubegpu_tpu.crishim.criserver import CriError
from kubegpu_tpu.crishim.runtime import ContainerHandle, ContainerRuntime
from kubegpu_tpu.crishim.shim import CriShim
from kubegpu_tpu.kubemeta import (
    FakeApiServer,
    Node,
    NotFound,
    ObjectMeta,
    PodPhase,
)
from kubegpu_tpu.kubemeta.codec import (
    DEVICE_INFO_KEY,
    node_advertisement_to_annotation,
)
from kubegpu_tpu.obs import MetricsRegistry, get_logger
from kubegpu_tpu.tpuplugin.backend import DeviceBackend

log = get_logger("nodeagent")


def harvest_workload_metrics(stdout: str, metrics: MetricsRegistry,
                             pod_name: str = "") -> list[str]:
    """Scan a finished container's stdout for metric lines — any line
    that parses as JSON with numeric ``metric``/``value`` fields (the
    convention the workload programs print, e.g. the allreduce
    microbenchmark's ``allreduce_algo_bandwidth``) — and feed them into
    the cluster metrics registry as ``workload_<metric>`` observations
    + gauges.  This is how north-star metric #2 lands in
    ``metrics.snapshot()`` instead of dying in a process log."""
    seen: list[str] = []
    for line in stdout.splitlines():
        line = line.strip()
        if not (line.startswith("{") and '"metric"' in line):
            continue
        try:
            doc = json.loads(line)
            name = str(doc["metric"])
            value = float(doc["value"])
        except (ValueError, KeyError, TypeError):
            continue
        if not math.isfinite(value):
            continue   # a NaN would poison the whole histogram

        metrics.observe(f"workload_{name}", value)
        metrics.set_gauge(f"workload_{name}", value)
        seen.append(name)
    return seen


class NodeAgent:
    def __init__(self, api: FakeApiServer, backend: DeviceBackend,
                 runtime: ContainerRuntime,
                 metrics: MetricsRegistry | None = None,
                 shim=None):
        self.api = api
        self.backend = backend
        self.adv = backend.discover()
        self.node_name = self.adv.node_name
        self.runtime = runtime
        self.metrics = metrics
        # shim override: a RemoteCriShim here sends every container call
        # over the CRI unix socket (criserver.py) instead of in-process —
        # the kubelet→crishim transport of the reference (SURVEY.md §4.3)
        self.shim = shim if shim is not None else CriShim(
            api, backend, self.node_name, runtime)
        self.handles: dict[str, ContainerHandle] = {}  # pod name → handle
        self._uids: dict[str, str] = {}  # pod name → uid of the incarnation
        self._ns: dict[str, str] = {}    # pod name → namespace
        self.down = False  # host failure: agent stops heartbeating/acting

    # -- advertisement (SURVEY.md §4.1) ---------------------------------

    def register(self) -> None:
        """Create the Node object if needed, then advertise capacity +
        topology as an annotation."""
        try:
            self.api.get("Node", self.node_name)
        except NotFound:
            self.api.create("Node", Node(
                metadata=ObjectMeta(name=self.node_name)))
        self.advertise()

    def advertise(self) -> None:
        self.adv = self.backend.discover()  # re-enumerate (health may change)
        self.api.patch_annotations(
            "Node", self.node_name,
            {DEVICE_INFO_KEY: node_advertisement_to_annotation(self.adv)})

    # -- pod lifecycle (SURVEY.md §4.3) ---------------------------------

    # -- failure injection (simulated machine death) --------------------

    def fail(self) -> None:
        """The host dies: containers are gone, the agent stops acting.
        (The node controller flips Node.ready separately, as in k8s.)"""
        self.down = True
        for h in self.handles.values():
            h.kill()
        self.handles.clear()
        self._uids.clear()
        self._ns.clear()

    def restore(self) -> None:
        """Host comes back: re-register + re-advertise fresh health."""
        self.down = False
        self.register()

    # -- reconcile ------------------------------------------------------

    def reconcile(self) -> None:
        """Kill containers whose pod was deleted/evicted (kubelet's
        pod-worker teardown when the apiserver drops a pod it runs).
        Incarnations are matched by uid, not name: an evicted gang member
        recreated with the same name and re-bound to this node is a NEW
        pod — the old container (stale chip set/coordinator env) must die
        or the recovered gang can never form its jax.distributed barrier."""
        for pod_name in list(self.handles):
            try:
                pod = self.api.get("Pod", pod_name,
                                   namespace=self._ns.get(pod_name, "default"))
                gone = (pod.spec.node_name != self.node_name
                        or pod.metadata.uid != self._uids.get(pod_name))
            except NotFound:
                gone = True
            if gone:
                self.handles.pop(pod_name).kill()
                self._uids.pop(pod_name, None)
                self._ns.pop(pod_name, None)

    def run_once(self) -> list[ContainerHandle]:
        """Start containers for pods newly bound to this node."""
        if self.down:
            return []
        self.reconcile()
        started: list[ContainerHandle] = []
        for pod in self.api.list("Pod", node_name=self.node_name,
                                 phase=PodPhase.SCHEDULED):
            if pod.name not in self.handles:
                try:
                    handle = self.shim.create_container(pod)
                except CriError as e:
                    # over the CRI wire the server re-fetches the pod, so
                    # a delete/evict+recreate racing this pass surfaces
                    # here (pod gone / uid mismatch): skip this pod — the
                    # next pass sees the new incarnation — and never abort
                    # the other pods' starts (mirrors the NotFound catch
                    # on the phase write below).  Logged loudly because
                    # the same frame also carries non-transient server
                    # errors (e.g. wrong-node allocation): a pod stuck
                    # SCHEDULED shows why here instead of failing silently.
                    log.warning("create_container_failed", pod=pod.name,
                                node=self.node_name, error=str(e))
                    continue
                self.handles[pod.name] = handle
                self._uids[pod.name] = pod.metadata.uid
                self._ns[pod.name] = pod.metadata.namespace
                try:
                    self.api.set_pod_phase(pod.name, PodPhase.RUNNING,
                                           namespace=pod.metadata.namespace,
                                           expect_uid=pod.metadata.uid)
                except NotFound:
                    # pod deleted (or evicted+recreated) between our list
                    # and the phase write: this container must not outlive
                    # its incarnation
                    self.handles.pop(pod.name).kill()
                    self._uids.pop(pod.name, None)
                    self._ns.pop(pod.name, None)
                    continue
                started.append(handle)
        return started

    def reap(self, timeout: float | None = None) -> dict[str, int]:
        """Wait for running containers; report exit codes and update pod
        phases (Succeeded/Failed)."""
        results: dict[str, int] = {}
        if self.down:
            return results
        for pod_name, handle in list(self.handles.items()):
            code = handle.wait(timeout=timeout)
            if code is None:
                continue
            results[pod_name] = code
            phase = PodPhase.SUCCEEDED if code == 0 else PodPhase.FAILED
            ns = self._ns.get(pod_name, "default")
            if code == 0 and self.metrics is not None:
                harvest_workload_metrics(handle.stdout, self.metrics,
                                         pod_name=pod_name)
            try:
                # only report for the incarnation this container belongs to
                self.api.set_pod_phase(
                    pod_name, phase,
                    message=handle.stderr[-2000:] if code else "",
                    exit_code=code, namespace=ns,
                    expect_uid=self._uids.get(pod_name))
            except NotFound:
                pass
            del self.handles[pod_name]
            self._uids.pop(pod_name, None)
            self._ns.pop(pod_name, None)
        return results

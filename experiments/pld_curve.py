"""Probe prompt-noise rate → PLD acceptance on the chip (r5 item #6).

The bench's PLD row trains the flagship bench model to continue a
cyclic pattern (acceptance 1.0).  To chart the acceptance curve's
MIDDLE, the prompt's history is corrupted at rate r: lookup matches in
noisy history propose wrong continuations while the model still emits
the clean cycle, so acceptance falls with r.  This script measures
acceptance + speedup at several r so the bench can bake in rates that
land ≈ 0.3/0.5/0.7 (VERDICT r5 item #6)."""

import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

import jax                                        # noqa: E402
import jax.numpy as jnp                           # noqa: E402
import optax                                      # noqa: E402

from kubegpu_tpu.benchmark import (               # noqa: E402
    _time_calls,
    llama_bench_config,
)
from kubegpu_tpu.models.decode import (           # noqa: E402
    _pld_fused_fn,
    greedy_generate,
    pld_generate_fused,
)
from kubegpu_tpu.models.llama import llama_init, make_train_step  # noqa: E402
from kubegpu_tpu.models.quant import quantize_llama  # noqa: E402

PLD_STEPS, PAT, BATCH, SEQ = 120, 128, 4, 1024
SPEC_T, SPEC_STEPS, GAMMA, NGRAM = 1024, 128, 8, 3


def main():
    cfg = llama_bench_config()
    rng = np.random.default_rng(7)
    pattern = rng.integers(2, cfg.vocab_size, PAT)
    data = np.tile(pattern, SEQ * 2 // PAT + 2)
    params = llama_init(jax.random.PRNGKey(7), cfg)
    opt = optax.adamw(3e-4)
    state = opt.init(params)
    step = jax.jit(make_train_step(cfg, opt), donate_argnums=(0, 1))
    t0 = time.perf_counter()
    for i in range(PLD_STEPS):
        off = int(rng.integers(0, PAT))
        batch = np.stack([data[off + j:off + j + SEQ]
                          for j in range(BATCH)])
        params, state, loss = step(params, state,
                                   jnp.asarray(batch, jnp.int32))
    print(f"trained {PLD_STEPS} steps in {time.perf_counter()-t0:.1f}s "
          f"loss={float(loss):.4f}", flush=True)
    tq = quantize_llama(params)

    spec_len = SPEC_T + SPEC_STEPS
    base = np.tile(pattern, SPEC_T // PAT + 1)[:SPEC_T]
    run = _pld_fused_fn(cfg, SPEC_T, SPEC_STEPS, spec_len, GAMMA,
                        NGRAM, True)
    clean_prompt = jnp.asarray(
        np.broadcast_to(base, (BATCH, SPEC_T)).copy(), jnp.int32)
    tg_s = _time_calls(
        lambda: greedy_generate(tq, clean_prompt, SPEC_STEPS, cfg,
                                max_len=spec_len, kv_int8=True),
        lambda o: o, 2)
    print(f"greedy e2e: {tg_s*1e3:.1f} ms", flush=True)

    for rate in (0.0, 0.05, 0.1, 0.2, 0.35, 0.5, 0.7):
        nrng = np.random.default_rng(int(rate * 1000) + 1)
        noisy = np.broadcast_to(base, (BATCH, SPEC_T)).copy()
        mask = nrng.random((BATCH, SPEC_T)) < rate
        mask[:, -NGRAM:] = False   # generation starts on-cycle
        noisy[mask] = nrng.integers(2, cfg.vocab_size, mask.sum())
        prompt = jnp.asarray(noisy, jnp.int32)
        _, stats = pld_generate_fused(
            tq, prompt, SPEC_STEPS, cfg, gamma=GAMMA, ngram=NGRAM,
            max_len=spec_len, kv_int8=True)
        pld_s = _time_calls(lambda: run(tq, prompt)[0], lambda o: o, 2)
        print(f"rate {rate:4.2f}: acceptance "
              f"{stats['acceptance_rate']:.3f} iters "
              f"{stats['iterations']:3d} pld {pld_s*1e3:7.1f} ms "
              f"speedup {tg_s/pld_s:5.2f}x", flush=True)


if __name__ == "__main__":
    main()

"""Vision Transformer — the image-classification family beyond ResNet
(TPU-native addition; the reference's example/ hosts workloads, it ships
no models — SURVEY.md §3).

Same TPU-first construction as the Llama decoder:
- encoder blocks stored *stacked* ``[L, ...]`` and run with ``lax.scan``
  (one traced block, O(1) compile time at any depth);
- patch embedding as a single reshape+matmul (the conv is a matmul over
  flattened patches — MXU-friendly, no conv lowering needed);
- bidirectional attention through the shared flash/XLA kernel
  (``causal=False``);
- megatron-style PartitionSpec tree (dp/fsdp batch, tp on heads/mlp), so
  the same pjit wiring the Llama workload uses serves ViT unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kubegpu_tpu.ops import attention
from kubegpu_tpu.parallel.sharding import constrain


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    n_classes: int = 1000
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    dtype: str = "bfloat16"
    attn_impl: str = "auto"   # auto | pallas | xla

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @classmethod
    def base_16(cls) -> "ViTConfig":
        """ViT-B/16."""
        return cls()

    @classmethod
    def tiny(cls, **kw) -> "ViTConfig":
        base = cls(image_size=32, patch_size=8, n_classes=10, d_model=64,
                   n_layers=2, n_heads=4, d_ff=128, dtype="float32",
                   attn_impl="xla")
        return replace(base, **kw)

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)


def vit_init(key: jax.Array, cfg: ViTConfig) -> dict:
    patch_dim = cfg.patch_size * cfg.patch_size * 3
    ks = jax.random.split(key, 8)

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(cfg.jdtype)

    L = cfg.n_layers
    return {
        "patch_embed": dense(ks[0], (patch_dim, cfg.d_model), patch_dim),
        "cls_token": jnp.zeros((1, 1, cfg.d_model), cfg.jdtype),
        "pos_embed": (jax.random.normal(
            ks[1], (1, cfg.n_patches + 1, cfg.d_model), jnp.float32)
            * 0.02).astype(cfg.jdtype),
        "layers": {
            "ln1_scale": jnp.ones((L, cfg.d_model), cfg.jdtype),
            "ln1_bias": jnp.zeros((L, cfg.d_model), cfg.jdtype),
            "wqkv": dense(ks[2], (L, cfg.d_model, 3 * cfg.d_model),
                          cfg.d_model),
            "wo": dense(ks[3], (L, cfg.d_model, cfg.d_model), cfg.d_model),
            "ln2_scale": jnp.ones((L, cfg.d_model), cfg.jdtype),
            "ln2_bias": jnp.zeros((L, cfg.d_model), cfg.jdtype),
            "w_up": dense(ks[4], (L, cfg.d_model, cfg.d_ff), cfg.d_model),
            "b_up": jnp.zeros((L, cfg.d_ff), cfg.jdtype),
            "w_down": dense(ks[5], (L, cfg.d_ff, cfg.d_model), cfg.d_ff),
            "b_down": jnp.zeros((L, cfg.d_model), cfg.jdtype),
        },
        "final_ln_scale": jnp.ones((cfg.d_model,), cfg.jdtype),
        "final_ln_bias": jnp.zeros((cfg.d_model,), cfg.jdtype),
        "head": dense(ks[6], (cfg.d_model, cfg.n_classes), cfg.d_model),
    }


def vit_param_specs(cfg: ViTConfig) -> dict:
    """dp/fsdp on batch (activations), tp on heads/mlp dims."""
    return {
        "patch_embed": P(None, "tp"),
        "cls_token": P(None, None, None),
        "pos_embed": P(None, None, None),
        "layers": {
            "ln1_scale": P(None, None),
            "ln1_bias": P(None, None),
            "wqkv": P(None, "fsdp", "tp"),
            "wo": P(None, "tp", "fsdp"),
            "ln2_scale": P(None, None),
            "ln2_bias": P(None, None),
            "w_up": P(None, "fsdp", "tp"),
            "b_up": P(None, "tp"),
            "w_down": P(None, "tp", "fsdp"),
            "b_down": P(None, None),
        },
        "final_ln_scale": P(None),
        "final_ln_bias": P(None),
        "head": P("fsdp", "tp"),
    }


def _layernorm(x: jax.Array, scale: jax.Array, bias: jax.Array,
               eps: float = 1e-6) -> jax.Array:
    xf = x.astype(jnp.float32)
    mu = xf.mean(axis=-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(axis=-1, keepdims=True)
    out = (xf - mu) * jax.lax.rsqrt(var + eps)
    return out.astype(x.dtype) * scale + bias


def patchify(images: jax.Array, patch: int) -> jax.Array:
    """[B, H, W, 3] → [B, N, patch*patch*3] row-major patches."""
    b, h, w, c = images.shape
    gh, gw = h // patch, w // patch
    x = images.reshape(b, gh, patch, gw, patch, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, gh * gw, patch * patch * c)


def vit_forward(params: dict, images: jax.Array, cfg: ViTConfig,
                mesh: Mesh | None = None) -> jax.Array:
    """images [B, H, W, 3] → class logits [B, n_classes] (f32)."""
    b = images.shape[0]
    hd = cfg.head_dim
    x = patchify(images.astype(cfg.jdtype), cfg.patch_size) \
        @ params["patch_embed"]
    cls = jnp.broadcast_to(params["cls_token"], (b, 1, cfg.d_model))
    x = jnp.concatenate([cls, x], axis=1) + params["pos_embed"]
    x = constrain(x, mesh, ("dp", "fsdp"), None, None)
    t = x.shape[1]

    def block(x, lp):
        h = _layernorm(x, lp["ln1_scale"], lp["ln1_bias"])
        qkv = (h @ lp["wqkv"]).reshape(b, t, 3, cfg.n_heads, hd)
        q, k, v = (qkv[:, :, i].transpose(0, 2, 1, 3) for i in range(3))
        o = attention(q, k, v, causal=False, impl=cfg.attn_impl)
        o = o.transpose(0, 2, 1, 3).reshape(b, t, cfg.d_model)
        o = constrain(o, mesh, ("dp", "fsdp"), None, "tp")
        x = x + (o @ lp["wo"]).astype(x.dtype)
        h = _layernorm(x, lp["ln2_scale"], lp["ln2_bias"])
        up = jax.nn.gelu(h @ lp["w_up"] + lp["b_up"])
        up = constrain(up, mesh, ("dp", "fsdp"), None, "tp")
        x = x + (up @ lp["w_down"] + lp["b_down"]).astype(x.dtype)
        return x, None

    x, _ = jax.lax.scan(block, x, params["layers"])
    x = _layernorm(x[:, 0], params["final_ln_scale"],
                   params["final_ln_bias"])
    return (x @ params["head"]).astype(jnp.float32)


def vit_loss(params: dict, images: jax.Array, labels: jax.Array,
             cfg: ViTConfig, mesh: Mesh | None = None) -> jax.Array:
    logits = vit_forward(params, images, cfg, mesh)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()


def make_vit_train_step(cfg: ViTConfig, optimizer,
                        mesh: Mesh | None = None):
    """(params, opt_state, images, labels) → (params, opt_state, loss).
    Reuses the shared train-step machinery (grad/update/apply — the same
    hook the MoE step plugs its loss into)."""
    from kubegpu_tpu.models.llama import make_train_step

    def loss_fn(params, batch, _cfg, _mesh):
        images, labels = batch
        return vit_loss(params, images, labels, _cfg, _mesh)

    base = make_train_step(cfg, optimizer, mesh, loss_fn=loss_fn)

    def step(params, opt_state, images, labels):
        return base(params, opt_state, (images, labels))

    return step

"""ISSUE 6 observability primitives: span tracer, bounded histograms,
Prometheus exposition, and the metric/span-name census.

The census tests are the tier-1 gate the ``obs/metrics.py`` docstring
promises: every literal metric name the package observes (and every
literal span name it records) must appear in the METRICS TABLE, so a
new metric without a table row fails here, before review.
"""

import json
import pathlib

import pytest

from kubegpu_tpu.obs.metrics import (
    _RESERVOIR,
    MetricsRegistry,
    _Histogram,
    parse_prometheus,
    percentiles,
)
from kubegpu_tpu.obs.spans import (
    SpanContext,
    Tracer,
    validate_chrome_trace,
)
from kubegpu_tpu.obs.trace import ScheduleTrace

PKG_ROOT = pathlib.Path(__file__).resolve().parent.parent / "kubegpu_tpu"


# ---------------------------------------------------------------------------
# SpanContext: the wire token
# ---------------------------------------------------------------------------

def test_span_context_roundtrip():
    ctx = SpanContext("abc123", "def456")
    assert ctx.encode() == "abc123:def456"
    back = SpanContext.decode(ctx.encode())
    assert back == ctx
    assert back.trace_id == "abc123" and back.span_id == "def456"


@pytest.mark.parametrize("junk", [None, "", "nocolon", ":orphan",
                                  "orphan:", ":"])
def test_span_context_junk_decodes_to_none(junk):
    # junk in the annotation/env must disable tracing, not crash the pod
    assert SpanContext.decode(junk) is None


# ---------------------------------------------------------------------------
# Tracer: trees, cross-process parenting, capacity, export
# ---------------------------------------------------------------------------

def test_tracer_parent_child_same_trace():
    tr = Tracer()
    with tr.span("root") as root:
        with tr.span("child", parent=root) as child:
            pass
    assert root.parent_id == ""
    assert child.trace_id == root.trace_id
    assert child.parent_id == root.span_id
    assert len(tr.trace_ids()) == 1


def test_tracer_cross_process_parenting_via_token():
    upstream = Tracer()
    with upstream.span("sched.bind") as bind:
        token = bind.context.encode()
    # a different process decodes the token and parents under it
    downstream = Tracer()
    ctx = SpanContext.decode(token)
    with downstream.span("crishim.inject", parent=ctx) as inj:
        pass
    assert inj.trace_id == bind.trace_id
    assert inj.parent_id == bind.span_id


def test_tracer_add_span_backdates():
    tr = Tracer()
    sp = tr.add_span("engine.tick", 10.0, 10.5, attrs={"tick": 3})
    assert sp.t0 == 10.0 and sp.t1 == 10.5
    assert tr.spans(name="engine.tick")[0].attrs["tick"] == 3


def test_tracer_capacity_evicts_oldest():
    tr = Tracer(capacity=4)
    for i in range(10):
        tr.add_span(f"s{i}", float(i), float(i) + 0.5)
    got = tr.spans()
    assert len(got) == 4
    assert [s.name for s in got] == ["s6", "s7", "s8", "s9"]


def test_tracer_span_tree_connectivity():
    tr = Tracer()
    root = tr.start_span("request")
    a = tr.start_span("request.admit_span", parent=root)
    b = tr.start_span("engine.tick", parent=root)
    c = tr.start_span("engine.dispatch", parent=b)
    for s in (c, b, a, root):
        s.end()
    tree = tr.span_tree(root.trace_id)
    assert {s.name for s in tree[""]} == {"request"}
    assert {s.name for s in tree[root.span_id]} == {"request.admit_span",
                                                    "engine.tick"}
    assert {s.name for s in tree[b.span_id]} == {"engine.dispatch"}
    # every non-root parent id resolves to a recorded span
    ids = {s.span_id for s in tr.spans(root.trace_id)}
    dangling = [s for s in tr.spans(root.trace_id)
                if s.parent_id and s.parent_id not in ids]
    assert dangling == []


def test_tracer_chrome_export_and_validation():
    tr = Tracer()
    with tr.span("request", attrs={"rid": 1}) as req:
        tr.instant("request.admit", req, attrs={"slot": 0})
        with tr.span("engine.tick", parent=req):
            pass
    text = tr.to_chrome_trace()
    events = validate_chrome_trace(text)
    by_ph = {}
    for e in events:
        by_ph.setdefault(e["ph"], []).append(e)
    assert {e["name"] for e in by_ph["X"]} == {"request", "engine.tick"}
    assert {e["name"] for e in by_ph["i"]} == {"request.admit"}
    # ids ride in args so the tree is reconstructible from the export
    req_ev = next(e for e in by_ph["X"] if e["name"] == "request")
    tick_ev = next(e for e in by_ph["X"] if e["name"] == "engine.tick")
    assert tick_ev["args"]["parent_id"] == req_ev["args"]["span_id"]
    assert req_ev["args"]["rid"] == 1
    # events are time-sorted
    ts = [e["ts"] for e in events]
    assert ts == sorted(ts)


def test_tracer_chrome_export_trace_filter():
    tr = Tracer()
    with tr.span("a") as a:
        pass
    with tr.span("b"):
        pass
    events = validate_chrome_trace(tr.to_chrome_trace(a.trace_id))
    assert [e["name"] for e in events] == ["a"]


@pytest.mark.parametrize("doc", [
    {"notTraceEvents": []},
    {"traceEvents": [{"ph": "Z", "ts": 0}]},
    {"traceEvents": [{"ph": "X", "ts": "soon", "dur": 1}]},
    {"traceEvents": [{"ph": "X", "ts": 0.0}]},          # X without dur
    # ph:"C" counter events (ISSUE 20) need a numeric args.value
    {"traceEvents": [{"ph": "C", "ts": 0.0, "name": "g"}]},
    {"traceEvents": [{"ph": "C", "ts": 0.0, "name": "g",
                      "args": {"value": "high"}}]},
])
def test_validate_chrome_trace_rejects_bad_shapes(doc):
    with pytest.raises(ValueError):
        validate_chrome_trace(json.dumps(doc))


def test_validate_chrome_trace_accepts_counter_events():
    doc = {"traceEvents": [
        {"ph": "C", "ts": 1.0, "name": "serve_queue_depth", "pid": 1,
         "tid": 0, "args": {"value": 3.0}}]}
    events = validate_chrome_trace(json.dumps(doc))
    assert events[0]["args"]["value"] == 3.0


# ---------------------------------------------------------------------------
# ScheduleTrace: bounded ring + tracer forwarding
# ---------------------------------------------------------------------------

def test_schedule_trace_bounded_eviction():
    st = ScheduleTrace(capacity=8)
    for i in range(20):
        st.record("schedule", gang=f"g{i}")
    evs = st.events()
    assert len(evs) == 8
    assert [e.gang for e in evs] == [f"g{i}" for i in range(12, 20)]


def test_schedule_trace_forwards_linked_gangs_only():
    tr = Tracer()
    st = ScheduleTrace(tracer=tr)
    with tr.span("sched.schedule") as root:
        tr.link_gang("ns/linked", root)
    st.record("schedule", gang="ns/linked", node="n0", score=0.5,
              candidates=["n0", "n1"])           # list attr filtered out
    st.record("schedule", gang="ns/unlinked", node="n1")
    st.record("heartbeat")                        # gangless, dropped
    events = validate_chrome_trace(tr.to_chrome_trace(root.trace_id))
    instants = [e for e in events if e["ph"] == "i"]
    assert len(instants) == 1
    ev = instants[0]
    assert ev["name"] == "sched.schedule"
    assert ev["args"]["gang"] == "ns/linked"
    assert ev["args"]["node"] == "n0" and ev["args"]["score"] == 0.5
    assert "candidates" not in ev["args"]
    assert tr.gang_context("ns/unlinked") is None


# ---------------------------------------------------------------------------
# Bounded histogram + Prometheus exposition
# ---------------------------------------------------------------------------

def test_histogram_exact_percentiles_below_cap():
    h = _Histogram()
    for v in range(100):
        h.observe(float(v))
    assert h.count == 100
    assert h.percentile(0) == 0.0
    assert h.percentile(50) == pytest.approx(50.0, abs=1.0)
    assert h.percentile(100) == 99.0
    assert h.mean == pytest.approx(49.5)


def test_histogram_memory_bounded_at_scale():
    h = _Histogram()
    n = 100_000
    for i in range(n):
        h.observe(float(i % 1000))
    assert h.count == n
    assert len(h._reservoir) <= _RESERVOIR
    # reservoir percentiles stay a sane estimate of the population
    assert 350.0 <= h.percentile(50) <= 650.0
    # deterministic: the seeded reservoir replays identically
    h2 = _Histogram()
    for i in range(n):
        h2.observe(float(i % 1000))
    assert h2.percentile(50) == h.percentile(50)
    assert h2.percentile(99) == h.percentile(99)


def test_histogram_buckets_cumulative_monotone():
    h = _Histogram()
    for v in (0.05, 0.3, 0.7, 3.0, 30.0, 3000.0, 99999.0):
        h.observe(v)
    buckets = h.buckets()
    counts = [c for _, c in buckets]
    assert counts == sorted(counts)
    assert buckets[-1][0] == float("inf")
    assert buckets[-1][1] == h.count
    # an out-of-range observation lands only in +Inf
    les = dict(buckets)
    assert les[10000.0] == h.count - 1


def test_registry_prometheus_roundtrip():
    reg = MetricsRegistry()
    reg.inc("gangs_scheduled", 3)
    reg.set_gauge("allocation_locality", 0.75)
    for v in (1.0, 2.0, 40.0):
        reg.observe("schedule_latency_ms", v)
    text = reg.to_prometheus()
    fams = parse_prometheus(text)
    assert fams["kubetpu_gangs_scheduled"]["type"] == "counter"
    assert fams["kubetpu_gangs_scheduled"]["samples"][
        "kubetpu_gangs_scheduled"] == 3.0
    assert fams["kubetpu_allocation_locality"]["type"] == "gauge"
    hist = fams["kubetpu_schedule_latency_ms"]
    assert hist["type"] == "histogram"
    assert hist["samples"]["kubetpu_schedule_latency_ms_count"] == 3.0
    assert hist["samples"]["kubetpu_schedule_latency_ms_sum"] == 43.0
    assert hist["samples"][
        'kubetpu_schedule_latency_ms_bucket{le="+Inf"}'] == 3.0
    assert hist["samples"][
        'kubetpu_schedule_latency_ms_bucket{le="1"}'] == 1.0


def test_help_lines_ride_from_the_metrics_table():
    # ISSUE 20 satellite: /metrics carries # HELP from the METRICS
    # TABLE doc text; names without a table row get an explicit stub
    reg = MetricsRegistry()
    reg.inc("gangs_scheduled", 1)
    reg.set_gauge("allocation_locality", 0.5)
    reg.observe("schedule_latency_ms", 2.0)
    reg.set_gauge("some_adhoc_gauge", 1.0)
    text = reg.to_prometheus()
    fams = parse_prometheus(text)
    for fam in ("kubetpu_gangs_scheduled", "kubetpu_allocation_locality",
                "kubetpu_schedule_latency_ms"):
        h = fams[fam]["help"]
        assert h and "undocumented" not in h, (fam, h)
    stub = fams["kubetpu_some_adhoc_gauge"]["help"]
    assert "undocumented metric some_adhoc_gauge" in stub
    # every family in the exposition leads with its HELP line
    lines = text.splitlines()
    for i, ln in enumerate(lines):
        if ln.startswith("# TYPE "):
            fam = ln.split()[2]
            assert lines[i - 1].startswith(f"# HELP {fam} "), fam


def test_registry_gauge_histogram_collision_exports_last():
    # harvest_workload_metrics registers serve names as BOTH gauge and
    # histogram; a duplicate family is a hard Prometheus parse error
    reg = MetricsRegistry()
    reg.observe("serve_ttft_ms", 12.0)
    reg.set_gauge("serve_ttft_ms", 12.0)
    fams = parse_prometheus(reg.to_prometheus())
    assert fams["kubetpu_serve_ttft_ms"]["type"] == "histogram"
    assert fams["kubetpu_serve_ttft_ms_last"]["type"] == "gauge"


def test_parse_prometheus_rejects_malformed():
    with pytest.raises(ValueError):
        parse_prometheus("# TYPE a counter\n# TYPE a counter\na 1\n")
    with pytest.raises(ValueError):
        parse_prometheus("orphan_sample 1\n")
    with pytest.raises(ValueError):
        parse_prometheus("# TYPE h histogram\n"
                         'h_bucket{le="1"} 5\n'
                         'h_bucket{le="2"} 3\n'
                         "h_count 5\nh_sum 9\n")


def test_percentiles_helper_matches_histogram():
    vals = [float(v) for v in range(200)]
    out = percentiles(vals)
    h = _Histogram()
    for v in vals:
        h.observe(v)
    assert out["count"] == 200
    assert out["p50"] == h.percentile(50)
    assert out["p99"] == h.percentile(99)


# ---------------------------------------------------------------------------
# Name census: code ↔ METRICS TABLE — delegated to the KTP004 lint
# pass (kubegpu_tpu/analysis/lint.py), which owns the call-site
# regexes and reads the registry via obs.metrics.documented_names().
# ---------------------------------------------------------------------------

def test_every_observed_name_is_in_the_table():
    from kubegpu_tpu.analysis.blessed import Blessings
    from kubegpu_tpu.analysis.lint import lint_metric_names
    findings = [f for f in lint_metric_names(PKG_ROOT, Blessings.load())
                if not f.blessed]
    assert not findings, "\n".join(
        f"{f.path}:{f.line} {f.message}" for f in findings)


def test_documented_names_parses_the_table():
    from kubegpu_tpu.obs.metrics import documented_names
    docs = documented_names()
    # spot-check both kinds: a metric the engine observes every tick
    # and the root span every request trace hangs from
    assert "serve_decode_stall_ms" in docs["metrics"]
    assert "request" in docs["spans"]
    assert all("." in s or s == "request" for s in docs["spans"])

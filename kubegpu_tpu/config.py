"""Unified config tree: dataclasses + file + CLI-style overrides.

Reference parity (SURVEY.md §6 "Config / flag system"): the reference used
Go flag/pflag per binary plus the kube-scheduler JSON policy file, with
device plugins selected by ``.so`` path.  Here the whole stack reads one
dataclass tree; the backend field mirrors the reference's plugin seam
(``mock`` ⇄ ``libtpu`` instead of ``nvidiagpuplugin.so``).

Load order (later wins): built-in defaults → config file (JSON or YAML)
→ dotted CLI overrides (``scheduler.locality_weight=0.7``).
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field, fields


@dataclass
class SchedulerConfig:
    """Tuning for the gang allocator + extender service."""

    locality_weight: float = 0.6
    frag_weight: float = 0.25
    fill_weight: float = 0.15
    max_placements_per_shape: int = 64
    coordinator_port: int = 0  # 0 = auto (rotate per cluster)
    # incomplete-gang arrival grace: how long the queue head blocks
    # later-arrived units while a gang's members trickle in
    gang_grace_s: float = 30.0


@dataclass
class BackendConfig:
    """Device-backend selection — the reference's plugin seam."""

    type: str = "mock"                # "mock" | "libtpu"
    slice_types: list[str] = field(default_factory=lambda: ["v4-8"])

    def __post_init__(self) -> None:
        if self.type not in ("mock", "libtpu"):
            raise ValueError(f"unknown backend type {self.type!r}")


@dataclass
class RuntimeConfig:
    """Node-runtime behavior (the crishim's launch path)."""

    real_processes: bool = False
    extra_env: dict[str, str] = field(default_factory=dict)
    # route agent→shim container calls over a CRI-shaped unix socket
    # (criserver.py) instead of in-process — the reference's transport
    wire_cri: bool = False


@dataclass
class ObsConfig:
    trace_capacity: int = 4096
    # emit structured JSON log lines (obs/logging.py) to stderr
    json_logs: bool = False
    log_level: str = "info"


@dataclass
class KubeTpuConfig:
    scheduler: SchedulerConfig = field(default_factory=SchedulerConfig)
    backend: BackendConfig = field(default_factory=BackendConfig)
    runtime: RuntimeConfig = field(default_factory=RuntimeConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)

    # ------------------------------------------------------------------

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "KubeTpuConfig":
        cfg = cls()
        _merge_into(cfg, d, path="")
        return cfg

    @classmethod
    def load(cls, path: str | None = None,
             overrides: list[str] | None = None) -> "KubeTpuConfig":
        """Defaults → ``path`` (JSON/YAML by extension) → dotted overrides
        like ``scheduler.locality_weight=0.7`` or ``backend.type=mock``."""
        cfg = cls()
        if path:
            _merge_into(cfg, load_structured_file(path), path="")
        for ov in overrides or []:
            _apply_override(cfg, ov)
        return cfg


def load_structured_file(path: str) -> dict:
    """Read a JSON or YAML mapping by extension (shared by config and the
    CLI's workload-spec loader)."""
    with open(path) as f:
        text = f.read()
    if path.endswith((".yaml", ".yml")):
        try:
            import yaml
        except ImportError as e:
            raise ValueError(
                f"{path}: reading YAML requires pyyaml (pip install "
                f"pyyaml) — or use JSON") from e
        return yaml.safe_load(text) or {}
    return json.loads(text or "{}")


def _merge_into(obj, d: dict, path: str) -> None:
    if not isinstance(d, dict):
        raise ValueError(f"config section {path or '<root>'} must be a "
                         f"mapping, got {type(d).__name__}")
    valid = {f.name: f for f in fields(obj)}
    for key, val in d.items():
        if key not in valid:
            raise ValueError(f"unknown config key {path}{key}")
        cur = getattr(obj, key)
        if dataclasses.is_dataclass(cur):
            _merge_into(cur, val, path=f"{path}{key}.")
        else:
            setattr(obj, key, _coerce(cur, val, f"{path}{key}"))
    _revalidate(obj)


def _apply_override(cfg, override: str) -> None:
    if "=" not in override:
        raise ValueError(f"override {override!r} must be key.path=value")
    dotted, _, raw = override.partition("=")
    parts = dotted.strip().split(".")
    obj = cfg
    for p in parts[:-1]:
        if not hasattr(obj, p) or not dataclasses.is_dataclass(getattr(obj, p)):
            raise ValueError(f"unknown config section {p!r} in {dotted}")
        obj = getattr(obj, p)
    leaf = parts[-1]
    if leaf not in {f.name for f in fields(obj)}:
        raise ValueError(f"unknown config key {dotted}")
    cur = getattr(obj, leaf)
    if dataclasses.is_dataclass(cur):
        raise ValueError(
            f"{dotted} is a config section, not a value — set one of its "
            f"fields (e.g. {dotted}.{fields(cur)[0].name}=...)")
    # parse the raw string by the current value's type
    if isinstance(cur, bool):
        val = raw.strip().lower() in ("1", "true", "yes", "on")
    elif isinstance(cur, int):
        val = int(raw)
    elif isinstance(cur, float):
        val = float(raw)
    elif isinstance(cur, list):
        val = [x.strip() for x in raw.split(",") if x.strip()]
    elif isinstance(cur, dict):
        val = dict(kv.split(":", 1) for kv in raw.split(",") if kv)
    else:
        val = raw
    setattr(obj, leaf, val)
    _revalidate(obj)


def _coerce(cur, val, where: str):
    """Light type coercion with a clear error, so a YAML '0.7' string or a
    JSON int-for-float round-trips instead of poisoning the tree."""
    if isinstance(cur, bool):
        if isinstance(val, bool):
            return val
        raise ValueError(f"{where}: expected bool, got {val!r}")
    # bool is a subclass of int: reject it explicitly in numeric slots so
    # YAML 1.1 scalars like `on`/`yes` don't silently become 1.0
    if isinstance(cur, float) and isinstance(val, (int, float)) \
            and not isinstance(val, bool):
        return float(val)
    if isinstance(cur, int) and isinstance(val, int) \
            and not isinstance(val, bool):
        return val
    if isinstance(cur, str) and isinstance(val, str):
        return val
    if isinstance(cur, list) and isinstance(val, list):
        return list(val)
    if isinstance(cur, dict) and isinstance(val, dict):
        return {str(k): str(v) for k, v in val.items()}
    raise ValueError(f"{where}: expected {type(cur).__name__}, got {val!r}")


def _revalidate(obj) -> None:
    post = getattr(obj, "__post_init__", None)
    if post is not None:
        post()

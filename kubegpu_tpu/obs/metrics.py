"""Counter / gauge / histogram registry with JSON export.

Feeds the driver-defined metrics (BASELINE.md): ``schedule_latency_ms``
histogram (p50 is north-star #1), ``allocation_locality`` gauge per gang,
plus scheduler throughput counters.  Thread-safe; structured-JSON export.

Serving-engine histograms (observed by ``ContinuousBatcher`` when a
registry is passed): ``serve_decode_stall_ms`` (per-tick admission work
decode slots waited behind), ``serve_spec_accept`` (per-slot per-tick
draft match fraction of the speculative engine), ``serve_spec_tokens_
per_tick`` (tokens banked per slot per verify tick — accepted drafts +
correction), and ``serve_collect_overlap_ms`` (host readout wall hidden
behind the double-buffered next tick when ``collect_overlap`` is on).

Serving fault-tolerance metrics (ISSUE 4 — observed by the engine and
``DataParallelServePool`` when a registry is passed; the serve pod
echoes the same names so ``DeviceScheduler.serving_metrics()`` carries
them as scheduler-visible gauges):

===========================  ==========  ================================
name                         kind        meaning
===========================  ==========  ================================
``serve_failover_total``     counter     dp replicas declared dead and
                                         failed over (kill, watchdog
                                         stall, or control-plane gang
                                         eviction)
``serve_replay_ms``          histogram   wall time of one failover's
                                         re-admission sweep (harvest +
                                         replay submits)
``serve_requests_retried``   counter     requests re-admitted via
                                         bit-exact replay (engine
                                         quarantine + pool failover)
``serve_slots_quarantined``  counter     slots pulled from the batch on
                                         non-finite logits
``serve_requests_shed``      counter     admissions failed by
                                         backpressure instead of
                                         deadlocking the queue
``serve_dispatch_failures``  counter     transient dispatch failures
                                         retried in place
``serve_tick_stalls``        counter     watchdog deadline trips
``serve_replica_deaths``     counter     engine deaths (any cause)
``serve_spec_degraded``      counter     engines that fell back to γ=0
                                         on repeated zero-acceptance
                                         verify ticks
===========================  ==========  ================================
"""

from __future__ import annotations

import json
import threading
from bisect import insort


class _Histogram:
    def __init__(self) -> None:
        self._sorted: list[float] = []

    def observe(self, v: float) -> None:
        insort(self._sorted, v)

    def percentile(self, p: float) -> float:
        if not self._sorted:
            return 0.0
        k = min(len(self._sorted) - 1,
                max(0, int(round(p / 100.0 * (len(self._sorted) - 1)))))
        return self._sorted[k]

    @property
    def count(self) -> int:
        return len(self._sorted)

    @property
    def mean(self) -> float:
        return sum(self._sorted) / len(self._sorted) if self._sorted else 0.0

    def snapshot(self) -> dict:
        return {
            "count": self.count,
            "mean": self.mean,
            "p50": self.percentile(50),
            "p90": self.percentile(90),
            "p99": self.percentile(99),
        }


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._hists: dict[str, _Histogram] = {}

    def inc(self, name: str, delta: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + delta

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            self._hists.setdefault(name, _Histogram()).observe(value)

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def gauge(self, name: str) -> float:
        with self._lock:
            return self._gauges.get(name, 0.0)

    def histogram(self, name: str) -> _Histogram:
        with self._lock:
            return self._hists.setdefault(name, _Histogram())

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: h.snapshot()
                               for k, h in self._hists.items()},
            }

    def to_json(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)

    def to_prometheus(self) -> str:
        """Prometheus text exposition (the observability surface a
        k8s-era deployment scrapes; served at GET /metrics on the
        extender webhook).  Histograms export as summaries with
        p50/p90/p99 quantiles plus _count and _sum.  A name registered
        as BOTH gauge and histogram (harvest_workload_metrics does
        this) exports the gauge as ``<name>_last`` — a duplicate metric
        family is a hard parse error that would fail the whole scrape.
        One locked pass, reusing _Histogram's own percentile math."""
        def sanitize(name: str) -> str:
            return "kubetpu_" + "".join(
                c if c.isalnum() or c == "_" else "_" for c in name)

        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            hist_names = set(self._hists)
            hist_stats = [
                (k, h.percentile(50), h.percentile(90), h.percentile(99),
                 h.count, h.mean * h.count)
                for k, h in sorted(self._hists.items())]
        lines: list[str] = []
        for name, v in counters:
            m = sanitize(name)
            lines += [f"# TYPE {m} counter", f"{m} {v}"]
        for name, v in gauges:
            m = sanitize(name + "_last" if name in hist_names else name)
            lines += [f"# TYPE {m} gauge", f"{m} {v}"]
        for name, p50, p90, p99, n, total in hist_stats:
            m = sanitize(name)
            lines.append(f"# TYPE {m} summary")
            lines.append(f'{m}{{quantile="0.5"}} {p50}')
            lines.append(f'{m}{{quantile="0.9"}} {p90}')
            lines.append(f'{m}{{quantile="0.99"}} {p99}')
            lines.append(f"{m}_count {n}")
            lines.append(f"{m}_sum {total}")
        return "\n".join(lines) + "\n"


def percentiles(values, ps=(50, 90, 99)) -> dict:
    """Percentile summary of a plain value list without registering a
    histogram — same index math as :class:`_Histogram`.  Used by the
    serving engine's per-tick decode-stall list
    (``ContinuousBatcher.stall_ms``) and the bench's device-anchored
    stall distributions, so engine and bench quantiles can never
    disagree on method."""
    h = _Histogram()
    for v in values:
        h.observe(float(v))
    out = {"count": h.count, "mean": h.mean}
    for p in ps:
        out[f"p{int(p)}"] = h.percentile(p)
    return out


global_registry = MetricsRegistry()


def serve_prometheus(registry: MetricsRegistry, host: str = "127.0.0.1",
                     port: int = 0):
    """Standalone Prometheus scrape endpoint (GET /metrics) for daemon
    processes that have no other HTTP server — the extender webhook
    integrates the same surface into its own dispatch; this is the
    scheduler daemon's.  ``host`` matters in a container netns (a
    loopback-only bind is unreachable from an off-host scraper).
    Returns the started ThreadingHTTPServer; call ``shutdown()`` +
    ``server_close()`` to stop."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if self.path.split("?", 1)[0] != "/metrics":
                self.send_response(404)
                self.end_headers()
                return
            body = registry.to_prometheus().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = ThreadingHTTPServer((host, port), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv

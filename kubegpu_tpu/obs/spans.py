"""Cross-layer request tracing (ISSUE 6 tentpole).

One request, one trace: a :class:`Tracer` records parent/child spans
whose *propagation token* travels the same road as ``TPU_VISIBLE_CHIPS``
— extender decision → gang bind (pod annotation) → crishim env
injection (``KUBETPU_TRACE_CONTEXT``) → serve pod → the engine — so a
slow request can be attributed phase by phase: queue wait, admission,
each prefill chunk, each decode/verify tick it rode, quarantine /
replay / failover hops, the prefill→decode page-chain migration
(``request.migrate``, with page count and hand-off wall under
disaggregated serving), TTFT and per-output-token time as span
attributes.

Three deliberate properties:

- **Near-free when absent.**  Every instrumented component takes
  ``tracer=None`` and guards each record with a single ``is not None``
  check; tracing never touches device math, so tokens are bit-exact
  on/off (the ``cb_trace_overhead`` bench row asserts both).
- **Process-local storage, wire-friendly identity.**  Spans live in a
  bounded in-process ring; only the tiny ``trace_id:span_id`` token
  crosses process boundaries (annotation → env var), exactly like
  W3C ``traceparent``.  A downstream process starts its own spans as
  children of the decoded token.
- **Drop-in visualization.**  :meth:`Tracer.to_chrome_trace` exports
  the Chrome/Perfetto trace-event JSON format (``ph:"X"`` complete
  events in µs, instants for point events), loadable in
  ``chrome://tracing`` / ui.perfetto.dev with zero tooling.

``ScheduleTrace`` linkage: the extender registers each gang's trace
root via :meth:`Tracer.link_gang`; a :class:`ScheduleTrace` constructed
with ``tracer=`` forwards every recorded decision whose gang is linked
as an instant event on that gang's trace — control-plane decisions and
engine ticks land on one timeline.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
import uuid
from collections import deque

# The road the token travels: the extender writes the annotation at
# bind time (next to ALLOCATE_FROM_KEY), the crishim copies it into the
# container env (next to TPU_VISIBLE_CHIPS), the serve pod decodes the
# env var and parents its engine spans under it.
TRACE_ANNOTATION = "pod.alpha.kubetpu/trace-context"
TRACE_ENV = "KUBETPU_TRACE_CONTEXT"

_SPAN_CAPACITY = 65536
_GANG_LINK_CAP = 4096   # gang → trace-root links kept (FIFO evicted)


class SpanContext:
    """Immutable (trace_id, span_id) pair — the propagation token."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def encode(self) -> str:
        """Wire form, annotation/env-safe: ``trace_id:span_id``."""
        return f"{self.trace_id}:{self.span_id}"

    @classmethod
    def decode(cls, token: str | None) -> "SpanContext | None":
        """Parse a wire token; junk decodes to None (tracing simply
        stays off downstream rather than crashing the pod)."""
        if not token or ":" not in token:
            return None
        trace_id, _, span_id = token.partition(":")
        if not trace_id or not span_id:
            return None
        return cls(trace_id, span_id)

    def __eq__(self, other) -> bool:
        return (isinstance(other, SpanContext)
                and other.trace_id == self.trace_id
                and other.span_id == self.span_id)

    def __repr__(self) -> str:
        return f"SpanContext({self.encode()!r})"


class Span:
    """One timed operation.  Context-manager: ``with tracer.span(...)``
    ends it on exit; or call :meth:`end` explicitly for spans whose
    lifetime crosses function boundaries (request spans)."""

    __slots__ = ("_tracer", "name", "trace_id", "span_id", "parent_id",
                 "t0", "t1", "attrs", "tid")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 span_id: str, parent_id: str, t0: float,
                 attrs: dict | None, tid: int):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0 = t0
        self.t1: float | None = None
        self.attrs = dict(attrs) if attrs else {}
        self.tid = tid

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def end(self, t: float | None = None) -> None:
        if self.t1 is None:
            self.t1 = self._tracer._now() if t is None else t
            self._tracer._finish(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.end()


class Tracer:
    """Thread-safe span recorder with bounded memory.

    ``capacity`` bounds BOTH finished spans and instant events (each a
    ``deque(maxlen=...)``) so a long-lived daemon can trace forever;
    eviction drops the oldest spans, which is the right bias for a
    profiler (recent window matters)."""

    def __init__(self, capacity: int = _SPAN_CAPACITY):
        self._lock = threading.Lock()
        self._spans: deque[Span] = deque(maxlen=capacity)
        self._instants: deque[tuple] = deque(maxlen=capacity)
        self._gangs: dict[str, SpanContext] = {}
        # one uuid per tracer + a counter: unique ids at ~ns cost,
        # instead of a uuid4 per span (measurable at tick rate)
        self._prefix = uuid.uuid4().hex[:10]
        self._ctr = itertools.count(1)
        self._tids: dict[int, int] = {}
        # chrome trace ts is absolute µs; anchor perf_counter to wall
        # clock once so separate tracers' exports line up roughly
        self._wall0 = time.time()
        self._perf0 = time.perf_counter()

    # -- time / ids -----------------------------------------------------

    def _now(self) -> float:
        return time.perf_counter()

    def _new_id(self) -> str:
        return f"{self._prefix}{next(self._ctr):x}"

    def _tid(self) -> int:
        ident = threading.get_ident()
        with self._lock:
            return self._tids.setdefault(ident, len(self._tids) + 1)

    # -- span API -------------------------------------------------------

    def start_span(self, name: str,
                   parent: "Span | SpanContext | None" = None,
                   attrs: dict | None = None) -> Span:
        """Start a span.  ``parent=None`` roots a NEW trace; a
        :class:`Span` or decoded :class:`SpanContext` parents into an
        existing one (possibly from another process via the token)."""
        if parent is None:
            trace_id, parent_id = self._new_id(), ""
        else:
            trace_id, parent_id = parent.trace_id, parent.span_id
        return Span(self, name, trace_id, self._new_id(), parent_id,
                    self._now(), attrs, self._tid())

    def span(self, name: str,
             parent: "Span | SpanContext | None" = None,
             attrs: dict | None = None) -> Span:
        """Alias for :meth:`start_span`; reads naturally as
        ``with tracer.span("engine.tick"):``."""
        return self.start_span(name, parent, attrs)

    def add_span(self, name: str, t0: float, t1: float,
                 parent: "Span | SpanContext | None" = None,
                 attrs: dict | None = None) -> Span:
        """Record an ALREADY-TIMED operation as a finished span.  The
        engine's tick profiler reuses the phase timestamps it measures
        anyway (``t_adm``, stall, dispatch wall) rather than paying a
        context manager per phase per tick."""
        sp = self.start_span(name, parent, attrs)
        sp.t0 = t0
        sp.end(t1)
        return sp

    def instant(self, name: str,
                ctx: "Span | SpanContext | None" = None,
                attrs: dict | None = None) -> None:
        """Record a zero-duration point event (chrome ``ph:"i"``)."""
        trace_id = ctx.trace_id if ctx is not None else ""
        with self._lock:
            self._instants.append(
                (self._now(), name, trace_id,
                 dict(attrs) if attrs else {}, self._tid_locked()))

    def _tid_locked(self) -> int:
        return self._tids.setdefault(threading.get_ident(),
                                     len(self._tids) + 1)

    def _finish(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)

    # -- gang linkage (ScheduleTrace → request traces) ------------------

    def link_gang(self, gang: str, ctx: "Span | SpanContext") -> None:
        """Register gang → trace root, so later schedule-trace events
        for that gang land on the request trace."""
        if isinstance(ctx, Span):
            ctx = ctx.context
        with self._lock:
            self._gangs[gang] = ctx
            # bounded like the span deques: gangs churn forever in a
            # long-lived daemon; drop the oldest links past capacity
            while len(self._gangs) > _GANG_LINK_CAP:
                self._gangs.pop(next(iter(self._gangs)))

    def gang_context(self, gang: str) -> SpanContext | None:
        with self._lock:
            return self._gangs.get(gang)

    def ingest_schedule_event(self, kind: str, gang: str,
                              detail: dict) -> None:
        """Sink for :class:`ScheduleTrace` (constructed with
        ``tracer=``): decisions for a linked gang become instant events
        on that gang's trace; unlinked gangs are dropped (they have no
        request trace to join)."""
        ctx = self.gang_context(gang)
        if ctx is None:
            return
        self.instant(f"sched.{kind}", ctx,
                     {"gang": gang, **{k: v for k, v in detail.items()
                                       if isinstance(v, (int, float,
                                                         str, bool))}})

    # -- read side ------------------------------------------------------

    def spans(self, trace_id: str | None = None,
              name: str | None = None) -> list[Span]:
        """Snapshot of FINISHED spans, optionally filtered."""
        with self._lock:
            out = list(self._spans)
        if trace_id is not None:
            out = [s for s in out if s.trace_id == trace_id]
        if name is not None:
            out = [s for s in out if s.name == name]
        return out

    def count(self, name: str, trace_id: str | None = None) -> int:
        """Number of FINISHED spans with ``name`` (optionally within
        one trace) — the cheap cardinality check the fused-decode tests
        lean on (one ``engine.tick`` span per fused BLOCK, not per
        device tick) without materializing span lists."""
        with self._lock:
            return sum(1 for s in self._spans
                       if s.name == name
                       and (trace_id is None or s.trace_id == trace_id))

    def trace_ids(self) -> list[str]:
        with self._lock:
            seen: dict[str, None] = {}
            for s in self._spans:
                seen.setdefault(s.trace_id)
        return list(seen)

    def span_tree(self, trace_id: str) -> dict[str, list[Span]]:
        """parent span_id → children, for connectivity checks."""
        tree: dict[str, list[Span]] = {}
        for s in self.spans(trace_id):
            tree.setdefault(s.parent_id, []).append(s)
        return tree

    # -- export ---------------------------------------------------------

    def _ts_us(self, t_perf: float) -> float:
        return (self._wall0 + (t_perf - self._perf0)) * 1e6

    def to_chrome_trace(self, trace_id: str | None = None) -> str:
        """Chrome/Perfetto trace-event JSON: ``ph:"X"`` complete events
        for spans (ts/dur in µs), ``ph:"i"`` for instants; trace/span
        ids ride in ``args`` so the tree is reconstructible from the
        export alone.  Load in chrome://tracing or ui.perfetto.dev."""
        with self._lock:
            spans = list(self._spans)
            instants = list(self._instants)
        events: list[dict] = []
        for s in spans:
            if trace_id is not None and s.trace_id != trace_id:
                continue
            events.append({
                "ph": "X", "name": s.name, "cat": s.name.split(".")[0],
                "ts": self._ts_us(s.t0),
                "dur": max((s.t1 - s.t0) * 1e6, 0.0),
                "pid": 1, "tid": s.tid,
                "args": {"trace_id": s.trace_id, "span_id": s.span_id,
                         "parent_id": s.parent_id, **s.attrs},
            })
        for t, name, tid_trace, attrs, tid in instants:
            if trace_id is not None and tid_trace != trace_id:
                continue
            events.append({
                "ph": "i", "name": name, "cat": name.split(".")[0],
                "ts": self._ts_us(t), "s": "g", "pid": 1, "tid": tid,
                "args": {"trace_id": tid_trace, **attrs},
            })
        events.sort(key=lambda e: e["ts"])
        return json.dumps({"traceEvents": events,
                           "displayTimeUnit": "ms"})


def validate_chrome_trace(text: str) -> list[dict]:
    """Parse + shape-check a chrome trace export (the trace-smoke
    gate): returns the event list or raises ValueError."""
    doc = json.loads(text)
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        raise ValueError("traceEvents missing or not a list")
    for e in events:
        if e.get("ph") not in ("X", "i", "B", "E", "M", "C"):
            raise ValueError(f"bad phase {e.get('ph')!r}")
        if not isinstance(e.get("ts"), (int, float)):
            raise ValueError(f"bad ts in {e.get('name')!r}")
        if e["ph"] == "X" and not isinstance(e.get("dur"),
                                             (int, float)):
            raise ValueError(f"X event without dur: {e.get('name')!r}")
        if e["ph"] == "C" and not isinstance(
                (e.get("args") or {}).get("value"), (int, float)):
            raise ValueError(
                f"C event without numeric value: {e.get('name')!r}")
    return events

"""ViT family: shapes, patchify exactness, sharded-vs-single parity,
training progress."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from kubegpu_tpu.models.vit import (
    ViTConfig,
    make_vit_train_step,
    patchify,
    vit_forward,
    vit_init,
    vit_loss,
    vit_param_specs,
)
from kubegpu_tpu.parallel import make_mesh, named_sharding_tree
from kubegpu_tpu.parallel.sharding import fit_spec


@pytest.fixture(scope="module")
def tiny():
    cfg = ViTConfig.tiny()
    params = vit_init(jax.random.PRNGKey(0), cfg)
    return cfg, params


def images_for(cfg, batch, seed=0):
    return jax.random.uniform(
        jax.random.PRNGKey(seed),
        (batch, cfg.image_size, cfg.image_size, 3), jnp.float32)


class TestViT:
    def test_patchify_reassembles(self, tiny):
        cfg, _ = tiny
        img = images_for(cfg, 2)
        patches = patchify(img, cfg.patch_size)
        assert patches.shape == (2, cfg.n_patches,
                                 cfg.patch_size ** 2 * 3)
        # first patch == top-left corner, row-major
        corner = img[0, :cfg.patch_size, :cfg.patch_size, :]
        np.testing.assert_array_equal(
            np.asarray(patches[0, 0]), np.asarray(corner).reshape(-1))

    def test_forward_shapes(self, tiny):
        cfg, params = tiny
        logits = vit_forward(params, images_for(cfg, 3), cfg)
        assert logits.shape == (3, cfg.n_classes)
        assert logits.dtype == jnp.float32
        assert np.isfinite(np.asarray(logits)).all()

    def test_sharded_matches_single(self, tiny):
        cfg, params = tiny
        mesh = make_mesh({"dp": 2, "fsdp": 2, "tp": 2})
        img = images_for(cfg, 4)
        ref = vit_forward(params, img, cfg)
        sharded = jax.device_put(
            params, named_sharding_tree(mesh, vit_param_specs(cfg)))
        img_s = jax.device_put(img, NamedSharding(
            mesh, fit_spec(mesh, P(("dp", "fsdp"), None, None, None))))
        got = jax.jit(lambda p, x: vit_forward(p, x, cfg, mesh))(
            sharded, img_s)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)

    def test_training_reduces_loss(self, tiny):
        cfg, params = tiny
        # donation below consumes the buffers — keep the fixture's intact
        params = jax.tree.map(jnp.copy, params)
        opt = optax.adamw(1e-3)
        opt_state = opt.init(params)
        step = jax.jit(make_vit_train_step(cfg, opt),
                       donate_argnums=(0, 1))
        img = images_for(cfg, 8)
        labels = jnp.arange(8, dtype=jnp.int32) % cfg.n_classes
        first = None
        for _ in range(8):
            params, opt_state, loss = step(params, opt_state, img, labels)
            first = first if first is not None else float(loss)
        assert float(loss) < first

    def test_loss_agrees_across_shardings(self, tiny):
        cfg, params = tiny
        mesh = make_mesh({"dp": 4, "tp": 2})
        img = images_for(cfg, 4)
        labels = jnp.array([0, 1, 2, 3], jnp.int32)
        ref = float(vit_loss(params, img, labels, cfg))
        sharded = jax.device_put(
            params, named_sharding_tree(mesh, vit_param_specs(cfg)))
        got = float(jax.jit(
            lambda p, x, y: vit_loss(p, x, y, cfg, mesh))(
                sharded, img, labels))
        assert got == pytest.approx(ref, abs=1e-5)

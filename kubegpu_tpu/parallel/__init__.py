"""Parallelism layer: device meshes, sharding rules, ring attention.

The reference contained no parallelism code (SURVEY.md §3: it *placed*
workloads; NCCL ran inside user containers).  KubeTPU's workload layer is
TPU-native: explicit ``jax.sharding.Mesh`` axes (dp/fsdp/tp/sp), GSPMD
sharding rules for the model families, and sequence parallelism via
shard_map + ppermute ring attention — the collectives the scheduler's
locality model optimizes placement for.
"""

from kubegpu_tpu.parallel.mesh import MeshAxes, make_mesh, mesh_axis_sizes
from kubegpu_tpu.parallel.pipeline import (
    make_pp_loss,
    make_pp_train_step,
    spmd_pipeline,
)
from kubegpu_tpu.parallel.ringattention import ring_attention
from kubegpu_tpu.parallel.sharding import (
    constrain,
    named_sharding_tree,
)

__all__ = [
    "MeshAxes", "make_mesh", "mesh_axis_sizes",
    "ring_attention", "constrain", "named_sharding_tree",
    "spmd_pipeline", "make_pp_loss", "make_pp_train_step",
]

"""T5 seq2seq training workload — the encoder-decoder family, single-
or multi-worker via the injected TPU env (dp × tp mesh when the
allocation's mesh axes say so).

Env knobs:
  T5_STEPS   train steps (default 4)
  T5_TP      tensor-parallel width (default 1)
"""

from __future__ import annotations

import os
import sys


def main() -> int:
    from kubegpu_tpu.workloads.programs.distributed import init_from_env

    env = init_from_env()
    import jax
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kubegpu_tpu.models.t5 import (
        T5Config, make_t5_train_step, t5_init, t5_param_specs,
    )
    from kubegpu_tpu.parallel import make_mesh, named_sharding_tree
    from kubegpu_tpu.parallel.sharding import fit_spec

    steps = max(1, int(os.environ.get("T5_STEPS", "4")))
    tp = max(1, int(os.environ.get("T5_TP", "1")))
    cfg = T5Config.tiny()
    n = jax.device_count()
    mesh = make_mesh({"dp": n // tp, "tp": tp})

    params = jax.device_put(
        t5_init(jax.random.PRNGKey(0), cfg),
        named_sharding_tree(mesh, t5_param_specs(cfg)))
    opt = optax.adamw(1e-3)
    opt_state = opt.init(params)
    step = jax.jit(make_t5_train_step(cfg, opt, mesh),
                   donate_argnums=(0, 1))
    dp = n // tp
    batch = dp * max(1, 8 // dp)   # always divisible by the dp axis
    sh = NamedSharding(mesh, fit_spec(mesh, P("dp", None)))
    # one FIXED batch so the loss-decrease gate measures the same data
    enc = jax.device_put(jax.random.randint(
        jax.random.PRNGKey(1), (batch, 16), 0, cfg.vocab_size), sh)
    dec = jax.device_put(jax.random.randint(
        jax.random.PRNGKey(2), (batch, 12), 0, cfg.vocab_size), sh)
    losses = []
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, enc, dec)
        losses.append(float(loss))

    if env.worker_id == 0:
        print(f"t5: devices={n} tp={tp} "
              f"losses={[round(l, 4) for l in losses]}")
    if not all(np.isfinite(losses)) or (
            len(losses) > 1 and not losses[-1] < losses[0]):
        print("FAIL: loss not improving", file=sys.stderr)
        return 3
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

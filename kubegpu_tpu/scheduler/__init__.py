"""Cluster scheduler — reference: ``device-scheduler`` (SURVEY.md §3).

An extender-shaped service: ``filter`` (feasibility over candidate nodes),
``prioritize`` (0–10 scores), and a gang-aware scheduling loop that holds a
gang's pods until the whole gang fits a contiguous slice, then atomically
commits, writes allocation annotations, and binds (SURVEY.md §4.2).  All
cluster state is rebuilt from annotations on restart (§4.4 subtlety).
"""

from kubegpu_tpu.scheduler.extender import DeviceScheduler, ScheduleResult
from kubegpu_tpu.scheduler.health import (
    FaultRecoveryController,
    RecoveryResult,
)
from kubegpu_tpu.scheduler.webhook import (
    ExtenderHTTPServer,
    ExtenderService,
    policy_config,
)

__all__ = ["DeviceScheduler", "ScheduleResult", "FaultRecoveryController",
           "RecoveryResult", "ExtenderHTTPServer", "ExtenderService",
           "policy_config"]

"""Counter / gauge / histogram registry with JSON + Prometheus export.

Feeds the driver-defined metrics (BASELINE.md): ``schedule_latency_ms``
histogram (p50 is north-star #1), ``allocation_locality`` gauge per gang,
plus scheduler throughput counters.  Thread-safe; structured-JSON export
and Prometheus text exposition (0.0.4) with cumulative-bucket
histograms, served from GET /metrics on the extender webhook, the
scheduler daemon (``serve_prometheus``), and the kubemeta apiserver.

METRICS TABLE — every metric name the code observes.  tier-1
(``tests/test_obs_spans.py``) greps the source for literal
``observe/inc/set_gauge`` names and asserts each appears below, so a
new metric without a table row fails before review, not after.

Scheduler (DeviceScheduler / allocator):

==============================  =========  ============================
name                            kind       meaning
==============================  =========  ============================
``schedule_latency_ms``         histogram  one gang-schedule decision
                                           wall (p50 = north-star #1)
``allocation_locality``         gauge      locality score of the last
                                           placed gang
``last_allocation_locality``    gauge      alias kept for dashboards
``gangs_scheduled``             counter    gangs placed
``gangs_failed``                counter    gangs that found no placement
``gangs_preempted``             counter    victim gangs evicted by
                                           priority preemption
``gangs_migrated``              counter    gangs moved by defrag
``gangs_evicted``               counter    gangs evicted on device fault
``schedule_unschedulable``      counter    decisions ending unplaceable
``schedule_invalid``            counter    malformed/oversized asks
``schedule_quota_denied``       counter    namespace quota rejections
``bind_conflict_retries``       counter    bind-time rv conflicts
                                           retried
``bind_conflict_requeued``      counter    binds requeued after retry
                                           budget
``serving_spec_acceptance``     gauge      cluster-mean draft
                                           acceptance harvested from
                                           serve pods
``serving_goodput_tokens_per_s``  gauge    pod-harvested goodput under
                                           SLO, mirrored from
                                           ``serve_goodput_tokens_per_s``
                                           (ISSUE 13)
``serving_slo_attainment``      gauge      pod-harvested SLO attainment
                                           mirror (ISSUE 13)
``serving_requests_shed``       gauge      pod-harvested shed-count
                                           mirror (ISSUE 13)
``serving_requests_preempted``  gauge      pod-harvested preemption
                                           mirror (ISSUE 13)
``serving_deadline_miss``       gauge      pod-harvested deadline-miss
                                           mirror (ISSUE 13)
``serving_kv_bits``             gauge      pod-harvested KV element
                                           width mirror, from
                                           ``serve_kv_bits`` (ISSUE 15)
``serving_pages_evicted_total``  gauge     pod-harvested context-
                                           eviction mirror (ISSUE 15)
``serving_kv_quality_delta``    gauge      pod-harvested kv-compression
                                           quality-delta mirror
                                           (ISSUE 15)
``serving_chip_ticks_total``    gauge      pod-harvested chip-tick
                                           spend mirror, from
                                           ``serve_chip_ticks_total``
                                           (ISSUE 20)
==============================  =========  ============================

Serving engine (observed by ``ContinuousBatcher`` /
``DataParallelServePool`` when a registry is passed; the serve pod
echoes the same names so ``DeviceScheduler.serving_metrics()`` carries
them as scheduler-visible gauges):

==============================  =========  ============================
name                            kind       meaning
==============================  =========  ============================
``serve_decode_stall_ms``       histogram  per-tick admission work
                                           decode slots waited behind
``serve_spec_accept``           histogram  per-slot per-tick draft
                                           match fraction
``serve_spec_tokens_per_tick``  histogram  tokens banked per slot per
                                           verify tick
``serve_collect_overlap_ms``    histogram  host readout wall hidden
                                           behind the next tick
``serve_ttft_ms``               histogram  submit → first output token
                                           (queue wait + admission +
                                           prefill; ISSUE 6)
``serve_token_ms``              histogram  per-output-token decode
                                           latency after the first
                                           token (ISSUE 6)
``serve_queue_wait_ms``         histogram  submit → admission onto a
                                           slot (ISSUE 6)
``serve_failover_total``        counter    dp replicas declared dead
                                           and failed over
``serve_replay_ms``             histogram  wall of one failover's
                                           re-admission sweep
``serve_requests_retried``      counter    requests re-admitted via
                                           bit-exact replay
``serve_slots_quarantined``     counter    slots pulled on non-finite
                                           logits
``serve_requests_shed``         counter    admissions failed by
                                           backpressure; suffixed
                                           ``_pressure`` / ``_quota`` /
                                           ``_deadline`` per shed
                                           reason and ``_t<k>`` per
                                           tier (ISSUE 13)
``serve_dispatch_failures``     counter    transient dispatch failures
                                           retried in place
``serve_tick_stalls``           counter    watchdog deadline trips
``serve_replica_deaths``        counter    engine deaths (any cause)
``serve_spec_degraded``         counter    engines that fell back to
                                           γ=0 on zero-acceptance
``serve_fused_block_ms``        histogram  host sync wall of one fused
                                           K-tick block (ISSUE 8)
``serve_host_overhead_pct``     gauge      share of a step's wall spent
                                           OUTSIDE the device sync —
                                           the cost fused ticks
                                           amortize (ISSUE 8)
``serve_hbm_pool_bytes``        gauge      live pool + slot-mirror
                                           bytes at the last dispatch
                                           boundary (~1× the pool with
                                           buffer donation on, ~2×
                                           with it off; ISSUE 10)
``serve_hbm_peak_bytes``        gauge      lifetime peak of the live
                                           pool bytes — the number
                                           capacity planning budgets
                                           ``max_pages``/``n_slots``
                                           against (ISSUE 10)
``serve_migrated_pages_total``  counter    KV pages migrated from
                                           prefill-specialist to
                                           decode-specialist replicas
                                           (ISSUE 11)
``serve_migration_ms``          histogram  wall of one page-chain
                                           import: digest check +
                                           scatter + slot activation
                                           (ISSUE 11)
``serve_replica_queue_depth``   gauge      per-replica admission queue
                                           depth (suffixed ``_r<i>``
                                           per replica; the pool
                                           router's own signal,
                                           ISSUE 11)
``serve_queue_wait_ticks``      histogram  submit → admission in engine
                                           service rounds — the
                                           deterministic twin of
                                           ``serve_queue_wait_ms``
                                           (schedule-pure; the CPU
                                           smoke A/B gates on it,
                                           ISSUE 11); suffixed
                                           ``_t<k>`` per tier under
                                           tiered admission
                                           (ISSUE 13)
``serve_ttft_ticks``            histogram  submit → first token in
                                           engine service rounds — the
                                           deterministic twin of
                                           ``serve_ttft_ms`` (ISSUE 11)
``serve_decode_stall_work``     histogram  admission + chunk work UNITS
                                           decode-phase slots waited
                                           behind in one tick — the
                                           structural twin of
                                           ``serve_decode_stall_ms``
                                           (ISSUE 11)
``serve_goodput_tokens_per_s``  gauge      tokens/s from requests that
                                           met their tier's SLO — the
                                           hardware (weather) claim of
                                           goodput under overload
                                           (ISSUE 13)
``serve_goodput_tokens_per_tick``  gauge   goodput in tokens per engine
                                           tick — the deterministic
                                           twin the SLO smoke gates on
                                           (ISSUE 13)
``serve_slo_attainment``        gauge      fraction of offered requests
                                           that met their tier's SLO;
                                           suffixed ``_t<k>`` per tier
                                           — the degradation story is
                                           that ``_t0`` stays pinned
                                           while lower tiers absorb
                                           the overload (ISSUE 13)
``serve_requests_preempted``    counter    low-priority decoding slots
                                           parked host-side (pages
                                           released) to serve a higher
                                           tier; suffixed ``_t<k>`` by
                                           the victim's tier
                                           (ISSUE 13)
``serve_requests_resumed``      counter    parked requests re-admitted
                                           via the bit-exact greedy
                                           replay path — converges to
                                           the preempted counter at
                                           drain (ISSUE 13)
``serve_deadline_miss``         counter    requests expired by wall or
                                           tick deadline (pre-prefill
                                           prunes AND resident
                                           cancels); suffixed
                                           ``_t<k>`` per tier
                                           (ISSUE 13)
``serve_routing_affinity_hits``  counter   pool submits routed to a
                                           replica already holding ≥1
                                           page of the prompt's chain
                                           (prefix-affinity routing,
                                           ISSUE 14)
``serve_autoscale_events``      counter    replica-pool scale actions
                                           (up = gang spawn + fresh
                                           replica, down = graceful
                                           drain through the replay
                                           parking; ISSUE 14)
``serve_replicas_active``       gauge      live replicas in the pool
                                           after deaths, retires, and
                                           scale-ups (ISSUE 14)
``serve_kv_bits``               gauge      KV-pool element width in
                                           bits (16 = bf16, 8 = per-
                                           token int8, 4 = grouped
                                           packed int4; ISSUE 15)
``serve_pages_evicted_total``   counter    resident KV pages dropped by
                                           the context-eviction policy
                                           (window or attention-mass;
                                           ISSUE 15)
``serve_kv_quality_delta``      gauge      measured greedy-token
                                           disagreement vs the bf16
                                           reference for the active
                                           kv format (set by the
                                           ``cb_kv_capacity`` bench /
                                           serve harness via
                                           ``note_kv_quality``;
                                           ISSUE 15)
``serve_fleet_replicas``        gauge      live simulated replicas in
                                           the discrete-event fleet
                                           harness (ISSUE 19)
``serve_domain_kills_total``    counter    whole failure domains
                                           (slice/rack/zone) killed in
                                           one tick by the domain
                                           chaos injector (ISSUE 19)
``serve_ctrl_recoveries_total``  counter   control-plane crashes
                                           recovered from the append-
                                           only journal with every
                                           in-flight request re-driven
                                           exactly-once (ISSUE 19)
``serve_upgrade_waves_total``   counter    rolling-upgrade drain waves
                                           completed (one failure
                                           domain retired through
                                           replay parking and
                                           backfilled; ISSUE 19)
``serve_chip_ticks_total``      gauge      chip-ticks charged to
                                           resident work by the cost
                                           ledger (one chip busy one
                                           engine tick); suffixed
                                           ``_<tenant>_t<k>`` per
                                           (tenant, tier) key, exact
                                           integer conservation vs
                                           the engines' busy ticks
                                           (ISSUE 20)
``serve_alerts_fired``          counter    burn-rate alerts fired by
                                           the flight recorder's
                                           multi-window rules
                                           (ISSUE 20)
==============================  =========  ============================

Alert RULE names (ISSUE 20 — ``obs/alerts.py`` burn-rate rules over
flight-recorder series; the KTP004 census checks ``AlertRule`` name
and series literals against this registry): ``alert_failover_burn``
(failure-domain loss via the ``serve_failover_total`` delta series),
``alert_shed_burn`` (sustained admission-control shed pressure via
``serve_requests_shed`` deltas), ``alert_slo_burn`` (SLO
error-budget burn via the ``serve_slo_attainment`` series).
Histogram series sampled through ``obs/tsdb.SeriesStore`` appear as
``_p50``/``_p99``-suffixed tracks of their documented base name.

Trace spans (ISSUE 6 — recorded by ``obs/spans.Tracer``, exported as
Chrome/Perfetto JSON, not scraped): ``sched.schedule``, ``sched.bind``,
``crishim.inject``, ``engine.start``, ``request`` (attrs:
``queue_wait_ms``, ``ttft_ms``, ``token_ms``, ``tokens``),
``request.admit``, ``request.prefill_chunk``, ``request.replay``,
``request.migrate`` (attrs: ``rid``, ``pages``, ``to_replica``,
``outcome``, ``ms`` — the prefill→decode page-chain hand-off),
``request.preempt`` / ``request.resume`` (attrs: ``rid``, ``slot``,
``tier``, ``preemptions`` — the park/replay handshake of low-priority
preemption, ISSUE 13),
``request.quarantine``, ``pool.failover``,
``request.route`` (attrs: ``rid``, ``replica``, ``affinity_pages``,
``load`` — the prefix-affinity routing decision, ISSUE 14),
``pool.scale`` (attrs: ``direction``, ``replica``,
``replicas_active``, ``drain_replays`` — one autoscale action,
ISSUE 14), ``engine.tick``,
``engine.dispatch``, ``engine.verify``, ``engine.collect``,
``engine.admit``, ``alert.fired`` (attrs: ``rule``, ``series``,
``tick``, ``fast``, ``slow`` — one burn-rate alert landing on the
flame+counter timeline, ISSUE 20), plus ``sched.<kind>`` instants
forwarded from ScheduleTrace for linked gangs.  The serve pod echoes the span census
as the ``serve_trace_spans`` metric line.  The ``cb_trace_overhead``
bench row asserts tracing on/off is bit-exact with bounded overhead.
"""

from __future__ import annotations

import json
import random
import threading
from bisect import bisect_left

# Cumulative-bucket upper bounds (ms-scale latencies — the registry's
# histograms are all milliseconds or small ratios).  Matches the
# Prometheus convention: each bucket counts observations <= le, and
# +Inf is implicit (== _count).
DEFAULT_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                   100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0)

# Reservoir size for percentile estimation: exact below this many
# observations, uniform reservoir sample above (seeded — a given
# observation sequence always yields the same percentiles).
_RESERVOIR = 1024


class _Histogram:
    """Bounded-memory histogram: cumulative buckets (Prometheus
    exposition) + a seeded reservoir serving ``percentile()``.

    The old implementation kept EVERY observation in a sorted list
    (``insort`` = O(n) per observe, unbounded memory) — at engine tick
    rate that is both a CPU tax in the serving loop and a leak in a
    long-lived daemon.  Here ``observe`` is O(log buckets) and memory
    is capped at ``_RESERVOIR`` floats; percentiles stay EXACT until
    the cap, then degrade to a uniform sample (seeded, so
    deterministic for a fixed observation sequence)."""

    __slots__ = ("_bounds", "_bucket_counts", "_count", "_sum",
                 "_reservoir", "_rng", "_sorted_cache")

    def __init__(self, bounds: tuple = DEFAULT_BUCKETS) -> None:
        self._bounds = bounds
        self._bucket_counts = [0] * (len(bounds) + 1)   # last = +Inf
        self._count = 0
        self._sum = 0.0
        self._reservoir: list[float] = []
        self._rng = random.Random(0x5EED)
        self._sorted_cache: list[float] | None = None

    def observe(self, v: float) -> None:
        v = float(v)
        self._count += 1
        self._sum += v
        # bisect_left: v exactly on a bound belongs to THAT bucket
        # (Prometheus buckets count observations <= le)
        self._bucket_counts[bisect_left(self._bounds, v)] += 1
        if len(self._reservoir) < _RESERVOIR:
            self._reservoir.append(v)
            self._sorted_cache = None
        else:
            j = self._rng.randrange(self._count)
            if j < _RESERVOIR:
                self._reservoir[j] = v
                self._sorted_cache = None

    def percentile(self, p: float) -> float:
        vals = self._sorted_cache
        if vals is None:
            vals = self._sorted_cache = sorted(self._reservoir)
        if not vals:
            return 0.0
        k = min(len(vals) - 1,
                max(0, int(round(p / 100.0 * (len(vals) - 1)))))
        return vals[k]

    @property
    def count(self) -> int:
        return self._count

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0

    @property
    def sum(self) -> float:
        return self._sum

    def buckets(self) -> list[tuple[float, int]]:
        """Cumulative (le, count) pairs, +Inf last — the Prometheus
        histogram exposition shape."""
        out: list[tuple[float, int]] = []
        acc = 0
        for le, c in zip(self._bounds, self._bucket_counts):
            acc += c
            out.append((le, acc))
        out.append((float("inf"), self._count))
        return out

    def snapshot(self) -> dict:
        return {
            "count": self.count,
            "mean": self.mean,
            "p50": self.percentile(50),
            "p90": self.percentile(90),
            "p99": self.percentile(99),
        }


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._hists: dict[str, _Histogram] = {}
        self._gauge_del_hooks: list = []

    def inc(self, name: str, delta: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + delta

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def add_gauge_delete_hook(self, fn) -> None:
        """Register an observer called (outside the lock) with each
        gauge name that :meth:`delete_gauge` actually removes — the
        seam ``obs/tsdb.SeriesStore`` uses to END a per-instance
        series at the same choke point that drops its gauge
        (ISSUE 20)."""
        with self._lock:
            # ktp: allow(KTP005) one hook per attached SeriesStore
            self._gauge_del_hooks.append(fn)

    def delete_gauge(self, name: str) -> None:
        """Drop a gauge from the scrape surface entirely (idempotent).
        Per-instance gauges (``serve_replica_queue_depth_r<i>``) use
        this when the instance goes away — a drained replica must
        vanish from ``/metrics``, not freeze at its last depth.
        Delete hooks fire only on an ACTUAL removal, so the pool's
        idempotent re-deletes at the harvest choke point stay
        no-ops."""
        with self._lock:
            existed = self._gauges.pop(name, None) is not None
            hooks = list(self._gauge_del_hooks) if existed else []
        for fn in hooks:
            fn(name)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            self._hists.setdefault(name, _Histogram()).observe(value)

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def gauge(self, name: str) -> float:
        with self._lock:
            return self._gauges.get(name, 0.0)

    def histogram(self, name: str) -> _Histogram:
        with self._lock:
            return self._hists.setdefault(name, _Histogram())

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: h.snapshot()
                               for k, h in self._hists.items()},
            }

    def to_json(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)

    def to_prometheus(self) -> str:
        """Prometheus text exposition 0.0.4 (the observability surface
        a k8s-era deployment scrapes; served at GET /metrics on the
        extender webhook, the scheduler daemon, and the kubemeta
        apiserver).  Histograms export as CUMULATIVE BUCKETS
        (``_bucket{le="..."}`` + ``_count`` + ``_sum`` — ISSUE 6), so
        quantiles aggregate across scrape targets server-side
        (histogram_quantile), which summaries cannot.  A name
        registered as BOTH gauge and histogram
        (harvest_workload_metrics does this) exports the gauge as
        ``<name>_last`` — a duplicate metric family is a hard parse
        error that would fail the whole scrape.  Every family gets a
        ``# HELP`` line sourced from the METRICS TABLE docstring
        (ISSUE 20); undocumented names carry an explicit stub so the
        gap is visible in the scrape itself.  One locked pass."""
        docs = documented_names()["docs"]

        def sanitize(name: str) -> str:
            return "kubetpu_" + "".join(
                c if c.isalnum() or c == "_" else "_" for c in name)

        def help_line(m: str, name: str) -> str:
            text = docs.get(name) or (
                f"undocumented metric {name} (no METRICS TABLE row)")
            return f"# HELP {m} " + text.replace("\\", "\\\\")

        def fmt_le(le: float) -> str:
            if le == float("inf"):
                return "+Inf"
            return repr(le) if le != int(le) else str(int(le))

        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            hist_names = set(self._hists)
            hist_rows = [(k, h.buckets(), h.count, h.sum)
                         for k, h in sorted(self._hists.items())]
        lines: list[str] = []
        for name, v in counters:
            m = sanitize(name)
            lines += [help_line(m, name), f"# TYPE {m} counter",
                      f"{m} {v}"]
        for name, v in gauges:
            m = sanitize(name + "_last" if name in hist_names else name)
            lines += [help_line(m, name), f"# TYPE {m} gauge",
                      f"{m} {v}"]
        for name, buckets, n, total in hist_rows:
            m = sanitize(name)
            lines += [help_line(m, name), f"# TYPE {m} histogram"]
            for le, c in buckets:
                lines.append(f'{m}_bucket{{le="{fmt_le(le)}"}} {c}')
            lines.append(f"{m}_count {n}")
            lines.append(f"{m}_sum {total}")
        return "\n".join(lines) + "\n"


class LiveBytesTracker:
    """Live-array byte accounting for the serving engine (ISSUE 10).

    The engine calls :meth:`sample` at every dispatch boundary with the
    bytes of its still-referenced device state (pool/cache leaves plus
    the slot mirrors, plus any stale pre-dispatch handles the backend
    has not yet deleted).  With buffer donation on, XLA writes each
    tick's outputs into the inputs' buffers and deletes the inputs, so
    the sample sits at ~1× the pool; without donation the old handles
    stay live until the host drops them — ~2×.  The on/off ratio is
    exactly what the ``cb_hbm_donation`` bench row asserts, and the two
    gauges below are what capacity planning budgets ``max_pages`` /
    ``n_slots`` against."""

    def __init__(self, registry: MetricsRegistry | None = None) -> None:
        self.registry = registry
        self.live = 0
        self.peak = 0
        self.samples = 0

    def sample(self, live_bytes: int) -> None:
        self.live = int(live_bytes)
        self.peak = max(self.peak, self.live)
        self.samples += 1
        if self.registry is not None:
            self.registry.set_gauge("serve_hbm_pool_bytes", self.live)
            self.registry.set_gauge("serve_hbm_peak_bytes", self.peak)


def parse_prometheus(text: str) -> dict[str, dict]:
    """Minimal 0.0.4 parser for the trace-smoke gate: returns
    family → {"type", "help", "samples": {name+labels: value}} and
    raises ValueError on malformed lines, duplicate families, or
    non-monotonic histogram buckets.  ``# HELP`` text round-trips
    (ISSUE 20): the help recorded before a family's TYPE line rides
    on the family."""
    families: dict[str, dict] = {}
    help_pending: dict[str, str] = {}
    for ln in text.splitlines():
        if not ln.strip():
            continue
        if ln.startswith("# HELP "):
            rest = ln[len("# HELP "):]
            name, _, help_text = rest.partition(" ")
            help_pending[name] = help_text.replace("\\\\", "\\")
            continue
        if ln.startswith("# TYPE "):
            _, _, rest = ln.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            if name in families:
                raise ValueError(f"duplicate family {name}")
            if kind not in ("counter", "gauge", "histogram", "summary"):
                raise ValueError(f"bad type {kind!r} for {name}")
            families[name] = {"type": kind,
                              "help": help_pending.get(name),
                              "samples": {}}
            continue
        if ln.startswith("#"):
            continue
        key, _, val = ln.rpartition(" ")
        if not key:
            raise ValueError(f"malformed sample line {ln!r}")
        float(val)   # must parse
        base = key.split("{", 1)[0]
        fam = base
        for suffix in ("_bucket", "_count", "_sum"):
            if base.endswith(suffix) and base[:-len(suffix)] in families:
                fam = base[:-len(suffix)]
                break
        if fam not in families:
            raise ValueError(f"sample {key!r} without TYPE line")
        families[fam]["samples"][key] = float(val)
    for name, fam in families.items():
        if fam["type"] != "histogram":
            continue
        pairs = []
        for key, v in fam["samples"].items():
            if key.startswith(name + "_bucket{le=\""):
                le = key.split('le="', 1)[1].rstrip('"}')
                pairs.append((float("inf") if le == "+Inf"
                              else float(le), v))
        pairs.sort()
        if any(b[1] < a[1] for a, b in zip(pairs, pairs[1:])):
            raise ValueError(f"non-monotonic buckets in {name}")
    return families


def percentiles(values, ps=(50, 90, 99)) -> dict:
    """Percentile summary of a plain value list without registering a
    histogram — same index math as :class:`_Histogram`.  Used by the
    serving engine's per-tick decode-stall list
    (``ContinuousBatcher.stall_ms``) and the bench's device-anchored
    stall distributions, so engine and bench quantiles can never
    disagree on method."""
    h = _Histogram()
    for v in values:
        h.observe(float(v))
    out = {"count": h.count, "mean": h.mean}
    for p in ps:
        out[f"p{int(p)}"] = h.percentile(p)
    return out


def documented_names() -> dict[str, frozenset]:
    """The documented-name REGISTRY: every metric and span name the
    METRICS TABLE above declares, parsed from this module's docstring
    (the table is the single source of truth — KTP004 in
    ``kubegpu_tpu/analysis/lint.py`` and the tier-1 census in
    ``tests/test_obs_spans.py`` both consume this instead of keeping
    their own hand-maintained copies).

    A *metric* row is any ````name```` literal of plain snake_case; a
    *span* name additionally contains a dot (``engine.tick``) or is
    the bare ``request`` root.  Returns
    ``{"metrics": frozenset, "spans": frozenset, "docs": dict}``;
    span names are also valid ``add_span`` targets so both sets
    include the dotted names.  ``docs`` maps each TABLE-ROW name to
    its one-line meaning (continuation lines folded in) — the source
    of :meth:`MetricsRegistry.to_prometheus`'s ``# HELP`` text
    (ISSUE 20)."""
    import re
    doc = __doc__ or ""
    names = set(re.findall(r"``([a-z0-9_][a-z0-9_.]*)``", doc))
    spans = frozenset(n for n in names if "." in n) | {"request"}
    metrics = frozenset(n for n in names if "." not in n)
    # help text: a table ROW opens with ``name`` at column 0 plus a
    # kind and meaning; deeply-indented follow-up lines continue the
    # meaning, and anything else (borders, prose, blanks) closes it
    docs: dict[str, str] = {}
    cur: str | None = None
    for line in doc.splitlines():
        m = re.match(r"``([a-z0-9_][a-z0-9_.]*)``\s+(\S+)\s+(\S.*)",
                     line)
        if m:
            cur = m.group(1)
            docs[cur] = m.group(3).strip()
            continue
        if cur is not None and re.match(r"\s{8,}\S", line):
            docs[cur] = docs[cur] + " " + line.strip()
            continue
        cur = None
    return {"metrics": metrics, "spans": frozenset(spans),
            "docs": docs}


global_registry = MetricsRegistry()


def serve_prometheus(registry: MetricsRegistry, host: str = "127.0.0.1",
                     port: int = 0):
    """Standalone Prometheus scrape endpoint (GET /metrics) for daemon
    processes that have no other HTTP server — the extender webhook
    and the kubemeta apiserver integrate the same surface into their
    own dispatch; this is the scheduler daemon's.  ``host`` matters in
    a container netns (a loopback-only bind is unreachable from an
    off-host scraper).  Returns the started ThreadingHTTPServer; call
    ``shutdown()`` + ``server_close()`` to stop."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if self.path.split("?", 1)[0] != "/metrics":
                self.send_response(404)
                self.end_headers()
                return
            body = registry.to_prometheus().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = ThreadingHTTPServer((host, port), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv

"""KubeTPU benchmarks — package home for both bench surfaces.

1. :func:`run_bench` — gang-schedule latency (north-star metric #1):
   drives the real scheduler end-to-end on a simulated multi-slice
   cluster (2× v5e-64 + v4-8) with a churning stream of mixed gang
   workloads.  ``vs_baseline`` compares against the stand-in baseline
   BASELINE.md defines (the reference publishes no numbers): 50 ms p50.
2. :func:`run_model_bench` — the HARDWARE perf figure (VERDICT r1 #1):
   jits the flagship Llama train step on the default backend and
   reports tokens/s + MFU against the chip's peak bf16 FLOPs, plus a
   pallas-vs-XLA flash-attention microbenchmark.  On the driver's real
   TPU chip this produces the recorded MFU; on CPU (tests) it runs a
   tiny config so the code path stays covered.

The repo-root ``bench.py`` (the driver's entry point) calls
:func:`run_full_bench` and prints ONE JSON line with the model results
embedded under ``details.model``; ``kubetpu bench`` runs the scheduler
half by default and includes the model half with ``--model``.
"""

from __future__ import annotations

import os
import random
import time

BASELINE_P50_MS = 50.0

# Peak dense bf16 TFLOP/s per chip by device kind (public spec sheets).
_PEAK_TFLOPS = [
    ("v6e", 918.0), ("trillium", 918.0),
    ("v5p", 459.0),
    ("v5e", 197.0), ("v5litepod", 197.0), ("v5 lite", 197.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 46.0),
]


def chip_peak_tflops(device) -> float:
    env = os.environ.get("KUBETPU_PEAK_TFLOPS")
    if env:
        return float(env)
    kind = str(getattr(device, "device_kind", "")).lower()
    for prefix, peak in _PEAK_TFLOPS:
        if prefix in kind:
            return peak
    return 197.0   # assume v5e (the BASELINE target platform)


def llama_bench_config():
    """Llama-3-8B structure scaled to one v5e chip's HBM: same layer
    math, fewer layers/width (shared with ``__graft_entry__.entry``).
    Heads keep Llama-3's actual geometry — head_dim 128, GQA group 4 —
    which is also the MXU-friendly layout (a 64-wide contraction runs
    the 128x128 systolic array half-empty; measured 2.3x slower); width
    is the largest that trains remat-free in 16 GiB with its adamw state
    (d_model sweep on the chip: 1024 -> 0.54 MFU, 2048 -> 0.64).
    scan_unroll=8 (full): the r5 same-window bracket measured the
    unrolled layer loop at 206.8 ms/step vs 229.2 for the scanned one
    (MFU 0.707 vs 0.637 in that window) — XLA fuses/overlaps across
    layer boundaries once the while-loop barrier is gone."""
    from kubegpu_tpu.models import LlamaConfig
    return LlamaConfig(
        vocab_size=32000, d_model=2048, n_layers=8, n_heads=16,
        n_kv_heads=4, d_ff=8192, max_seq_len=2048, dtype="bfloat16",
        remat=False, scan_unroll=8)


def train_flops_per_step(cfg, batch: int, seq: int) -> float:
    """Model FLOPs for one train step (fwd + bwd ≈ 3× fwd), the MFU
    numerator.  Matmul fwd = 2·params_in_matmuls·tokens; causal
    attention fwd = 2·B·Hq·T²·hd per layer (half the full T² score/PV
    work); backward doubles the forward."""
    hd = cfg.head_dim
    per_layer_matmul = (
        cfg.d_model * cfg.n_heads * hd          # wq
        + 2 * cfg.d_model * cfg.n_kv_heads * hd  # wk, wv
        + cfg.n_heads * hd * cfg.d_model        # wo
        + 3 * cfg.d_model * cfg.d_ff)           # gate, up, down
    matmul_params = cfg.n_layers * per_layer_matmul \
        + cfg.d_model * cfg.vocab_size          # lm_head
    tokens = batch * seq
    fwd = 2.0 * matmul_params * tokens \
        + cfg.n_layers * 2.0 * batch * cfg.n_heads * seq * seq * hd
    return 3.0 * fwd


def _fetch_scalar(x) -> float:
    """Force completion by pulling one element to the host.  Under the
    axon TPU tunnel ``block_until_ready`` ACKs at dispatch time, so a
    host fetch is the only reliable synchronization barrier."""
    import jax
    import numpy as np

    return float(np.asarray(jax.device_get(jnp_ravel0(x))))


def jnp_ravel0(x):
    import jax.numpy as jnp

    return jnp.ravel(x)[0].astype(jnp.float32)


def _fetch_rtt_s(x) -> float:
    """Host-fetch round-trip latency (to subtract from chained timings);
    median of 3 on an already-computed array."""
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        _fetch_scalar(x)
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[1]


def _time_chained(step_fn, state, iters: int,
                  bursts: int = 2) -> tuple[float, object]:
    """Seconds per iteration of ``state = step_fn(state)``, timed as
    chained bursts with a single host fetch at the end of each (minus
    the fetch RTT) — the only honest timing under an async tunnel where
    per-call blocking is a no-op and every fetch pays a network round
    trip.  Best of ``bursts`` (the tunnel adds noise spikes, never
    negative noise)."""
    def leaf(st):
        return st[-1] if isinstance(st, tuple) else st

    state = step_fn(state)            # compile + warm
    _fetch_scalar(leaf(state))
    rtt = _fetch_rtt_s(leaf(state))
    best = float("inf")
    for _ in range(bursts):
        t0 = time.perf_counter()
        for _ in range(iters):
            state = step_fn(state)
        _fetch_scalar(leaf(state))
        elapsed = time.perf_counter() - t0
        best = min(best, max(elapsed - rtt, 1e-9) / iters)
    return best, state


def _attention_bench(batch, heads, seq, hd, dtype, on_tpu) -> dict | None:
    """pallas flash attention vs the XLA fallback on the bench shape."""
    import jax
    import jax.numpy as jnp

    from kubegpu_tpu.ops.flash_attention import (
        flash_attention,
        xla_attention,
    )

    if not on_tpu:
        return None   # interpret-mode pallas on CPU measures nothing real
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (batch, heads, seq, hd), dtype)
    k = jax.random.normal(kk, (batch, heads, seq, hd), dtype)
    v = jax.random.normal(kv, (batch, heads, seq, hd), dtype)
    # chain through q (same shape as the output) so iterations depend on
    # each other and one end fetch times the whole burst
    pallas_s, _ = _time_chained(
        lambda q_: flash_attention(q_, k, v), q, iters=100)
    xla_jit = jax.jit(lambda q_: xla_attention(q_, k, v))
    xla_s, _ = _time_chained(xla_jit, q, iters=100)
    return {
        "shape": [batch, heads, seq, hd],
        "pallas_ms": round(pallas_s * 1e3, 3),
        "xla_ms": round(xla_s * 1e3, 3),
        "pallas_speedup": round(xla_s / pallas_s, 3) if pallas_s else 0.0,
    }


def _serving_bench(cfg, params, on_tpu) -> dict:
    """Prefill latency + KV-cache decode throughput on the same params
    the train bench just produced (models/decode.py scanned greedy
    loop).  Timed as repeated whole-call dispatches with one end fetch:
    device execution is serial, so N calls / elapsed is throughput even
    when per-call blocking is a no-op under the async tunnel."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubegpu_tpu.models import greedy_generate
    from kubegpu_tpu.models.decode import prefill

    if on_tpu:
        batch, prompt_t, steps, iters = 8, 1024, 128, 3
    else:
        batch, prompt_t, steps, iters = 2, 8, 4, 2
    max_len = prompt_t + steps
    timeit = _time_calls   # ONE timing protocol for every bench row

    def measure(p, b, n, kv_int8=False):
        """(prefill_s, decode_s) for params ``p`` at batch ``b`` — ONE
        timing protocol for every configuration reported below, so the
        batch-32 methodology cannot diverge from the batch-8 one.  The
        prefill subtracted is always the SAME configuration's prefill
        (an int8 dequant-epilogue or int8-cache prefill differs by tens
        of ms and must not be booked to decode)."""
        pf = jax.jit(lambda pp, tk: prefill(
            pp, tk, cfg, max_len, kv_int8=kv_int8)[0])
        pr = jnp.asarray(
            np.arange(b * prompt_t).reshape(b, prompt_t)
            % cfg.vocab_size, jnp.int32)
        pre_s = timeit(lambda: pf(p, pr), lambda o: o, n)
        gen_s = timeit(
            lambda: greedy_generate(p, pr, steps, cfg, max_len,
                                    kv_int8=kv_int8),
            lambda o: o, n)
        return pre_s, max(gen_s - pre_s, 1e-9), gen_s

    def tps(b, decode_s):
        return round(b * (steps - 1) / decode_s, 1)

    prefill_s, decode_s, gen_s = measure(params, batch, iters)
    # int8 weight-only serving (models/quant.py): decode is weight-read
    # bound, so halved weight bytes show up directly
    from kubegpu_tpu.models.quant import quantize_llama
    qparams = quantize_llama(params)
    _, qdecode_s, _ = measure(qparams, batch, iters)
    # + int8 KV cache: at wide batches the cache out-reads the weights
    _, qkv_decode_s, _ = measure(qparams, batch, iters, kv_int8=True)
    # throughput-optimal serving: wider batch, both quantizations on
    _, qkv_b4x_s, _ = measure(qparams, batch * 4, max(iters - 1, 1),
                              kv_int8=True)
    return {
        "batch": batch,
        "prompt_len": prompt_t,
        "decode_steps": steps,
        "prefill_ms": round(prefill_s * 1e3, 2),
        "e2e_ms": round(gen_s * 1e3, 2),
        "decode_tokens_per_s": tps(batch, decode_s),
        "prefill_tokens_per_s": round(batch * prompt_t / prefill_s, 1),
        "int8_decode_tokens_per_s": tps(batch, qdecode_s),
        "int8_decode_speedup": round(decode_s / qdecode_s, 2),
        "int8_kv_decode_tokens_per_s": tps(batch, qkv_decode_s),
        "int8_kv_decode_b4x_tokens_per_s": tps(batch * 4, qkv_b4x_s),
    }


def moe_bench_config():
    """MoE bench scale for one v5e chip: the flagship's attention
    geometry (head_dim 128, GQA) at half width, 8 routed experts top-2
    (~390M params — experts dominate)."""
    from kubegpu_tpu.models import LlamaConfig
    from kubegpu_tpu.models.moe import MoEConfig
    return MoEConfig(
        base=LlamaConfig(
            vocab_size=32000, d_model=1024, n_layers=8, n_heads=8,
            n_kv_heads=2, d_ff=1536, max_seq_len=1024,
            dtype="bfloat16", remat=False),
        n_experts=8, top_k=2)


def t5_bench_config():
    """Encoder-decoder bench scale (~340M): t5-large-ish width, 8+8
    layers."""
    from kubegpu_tpu.models.t5 import T5Config
    return T5Config(vocab_size=32000, d_model=1024, n_enc_layers=8,
                    n_dec_layers=8, n_heads=16, d_ff=2816)


def _time_calls(fn, fetch, n: int) -> float:
    """Seconds per call of ``fn`` timed as n serial dispatches with one
    end fetch (device execution is serial; per-call blocking is a no-op
    under the async tunnel), best of 2 bursts, RTT subtracted."""
    out = fn()
    _fetch_scalar(fetch(out))
    rtt = _fetch_rtt_s(fetch(out))
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn()
        _fetch_scalar(fetch(out))
        best = min(best, max(time.perf_counter() - t0 - rtt, 1e-9))
    return best / n


def _probe_block_cost(probe, iters: int) -> float:
    """Chained per-dispatch cost of a probe engine's decode block on
    its LIVE state (caller fills the probe's slots and steps once
    first, so the paged kernel walks realistic page counts).

    THE donate-the-pool protocol, shared by every probe below: the
    engine's executables DONATE their pool/cache and mutated slot
    mirrors (``donating_jit``, ISSUE 10), so a probe must chain every
    donated argument through the measurement state — re-passing a live
    handle after its first dispatch reads a deleted buffer.  Probes are
    throwaway; consuming their state is the point."""
    import jax.numpy as jnp

    act = jnp.asarray(probe.active)
    if probe.paged:
        st0 = (probe.pool, probe.tokens, probe.pos)

        def chain(st):
            pool, tok, pos = st
            _, tok, pos, pool, _ = probe._fns[0](
                probe.params, pool, probe._pt_dev, probe._tvec_dev,
                probe._tpad_dev, tok, pos, act, probe.temps,
                probe._base_key, jnp.int32(0))
            return pool, tok, pos
    else:
        st0 = (probe.cache, probe.tokens, probe.pos)

        def chain(st):
            cache, tok, pos = st
            _, tok, pos, cache, _ = probe._fns[0](
                probe.params, cache, tok, pos, act, probe.temps,
                probe._base_key, jnp.int32(0))
            return cache, tok, pos

    s, _ = _time_chained(chain, st0, iters=iters)
    return s


def _probe_wave_cost(probe, kwave: int, bucket: int, iters: int) -> float:
    """Per-dispatch admission cost (prefill + adopt) at one
    (k, bucket), chained in this window on the probe's executables
    per the donate-the-pool protocol (see ``_probe_block_cost``): the
    adopt donates its big pool/cache AND the four slot mirrors, so
    the measurement chains all five through scratch copies — each
    mirror gets its OWN buffer (donating one array through two
    parameters is an aliasing error)."""
    import jax
    import jax.numpy as jnp

    qparams = probe.params
    paged = probe.paged
    pf = probe._fns[1]
    slots = probe.n_slots
    padded = jnp.zeros((kwave, bucket), jnp.int32)
    lens = jnp.ones((kwave,), jnp.int32)
    temps_w = jnp.zeros((kwave,), jnp.float32)
    pf_s = _time_calls(
        lambda: pf(qparams, padded, lens, temps_w,
                   probe._base_key, jnp.int32(0))[0],
        lambda o: o, max((iters * 10) // kwave, 8))
    firsts1, cache_w1 = pf(qparams, padded, lens, temps_w,
                           probe._base_key, jnp.int32(0))
    slotsk = jnp.arange(kwave, dtype=jnp.int32)
    big0 = jax.tree.map(jnp.zeros_like,
                        probe.pool if paged else probe.cache)
    st_big = (big0,
              jnp.zeros((slots,), jnp.int32),
              jnp.zeros((slots,), jnp.int32),
              jnp.zeros((slots,), jnp.int32),
              jnp.zeros((slots,), jnp.float32))
    if paged:
        pdst = jnp.zeros((kwave, bucket // probe.page_size), jnp.int32)

        def adopt_chain(st):
            pool, ft, tok, pos, tmp = st
            return probe._fns[2](
                pool, cache_w1, pdst, slotsk, firsts1, lens,
                temps_w, ft, tok, pos, tmp, kwave)
    else:
        def adopt_chain(st):
            cache, ft, tok, pos, tmp = st
            return probe._fns[2](
                cache, cache_w1, slotsk, firsts1, lens,
                temps_w, ft, tok, pos, tmp, kwave)

    adopt_s, _ = _time_chained(adopt_chain, st_big,
                               iters=max(iters * 20, 20))
    return pf_s + adopt_s


def _probe_chunk_cost(probe, bucket: int, iters: int) -> float:
    """Per-dispatch cost of one prefill chunk at near-max history (the
    last chunk of a ``bucket``-long prompt — the conservative upper
    bound for the anchored stall figure).  Chains a scratch pool per
    the donate-the-pool protocol (see ``_probe_block_cost``); the
    chunk donates ONLY its pool, so the probe's live slot-0 page
    table may be re-passed."""
    import jax
    import jax.numpy as jnp

    quant = "k_scale" in probe.pool
    c = probe.prefill_chunk
    s0 = max(bucket - c, 0)
    ck = jnp.zeros((1, c), jnp.int32)
    ptr = jnp.asarray(probe._pt[0:1])
    tlen = jnp.full((1,), bucket, jnp.int32)
    t1 = jnp.zeros((1,), jnp.float32)
    fn = probe._fns[3]

    def chain(st):
        pool = {"k": st[0], "v": st[1],
                **({"k_scale": st[2], "v_scale": st[3]}
                   if quant else {})}
        _, pool = fn(probe.params, pool, ck, ptr, jnp.int32(s0), tlen,
                     t1, probe._base_key, jnp.int32(0))
        return ((pool["k"], pool["v"], pool["k_scale"],
                 pool["v_scale"]) if quant
                else (pool["k"], pool["v"]))

    big0 = jax.tree.map(jnp.zeros_like, probe.pool)
    st0 = ((big0["k"], big0["v"], big0["k_scale"], big0["v_scale"])
           if quant else (big0["k"], big0["v"]))
    s, _ = _time_chained(chain, st0, iters=max(iters * 10, 10))
    return s


def _probe_spec_cost(probe, iters: int) -> float:
    """Chained per-dispatch cost of one SPECULATIVE verify tick (γ
    batched draft steps + the [n_slots, γ+1] full-model verify) on a
    spec-enabled probe engine's live state — the spec analog of
    ``_probe_block_cost``.  Chaining advances pos, so later iterations
    walk a few extra (owned or trash) pages; at probe iteration counts
    that bias is small and CONSERVATIVE for the spec-on leg."""
    import jax.numpy as jnp

    act = jnp.asarray(probe.active)
    gcap = jnp.asarray(probe._gcap)
    st0 = (probe.pool, probe.tokens, probe.pos)

    def chain(st):
        pool, tok, pos = st
        _, _, _, _, tok, pos, pool = probe._fns[5](
            probe.params, probe._draft_params, pool, probe._pt_dev,
            probe._tvec_dev, probe._tpad_dev, tok, pos, act, gcap)
        return pool, tok, pos

    s, _ = _time_chained(chain, st0, iters=max(iters * 4, 8))
    return s


def _train_draft_model(cfg, steps: int, pat_len: int, batch: int,
                       seq: int, seed: int = 7):
    """Train a fresh model of ``cfg``'s shape on a short cyclic pattern
    so its first layers (the early-exit self-draft) have actually
    learned the task — the r6 honesty treatment every self-draft row
    gets: acceptance measured on random-init weights was ~0 for four
    rounds straight and proved nothing.  Returns (params, pattern,
    final_loss); prompts built by tiling/rotating ``pattern`` keep the
    generation on-cycle so draft acceptance is attainable."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from kubegpu_tpu.models.llama import llama_init, make_train_step
    from kubegpu_tpu.parallel.sharding import donating_jit

    rng = np.random.default_rng(seed)
    pattern = rng.integers(2, cfg.vocab_size, pat_len)
    data = np.tile(pattern, seq * 2 // pat_len + 2)
    params = llama_init(jax.random.PRNGKey(seed), cfg)
    opt = optax.adamw(3e-4)
    state = opt.init(params)
    step = donating_jit(make_train_step(cfg, opt),
                        donate=("params", "opt_state"))
    loss = None
    for _ in range(steps):
        off = int(rng.integers(0, pat_len))
        batch_np = np.stack([data[off + j:off + j + seq]
                             for j in range(batch)])
        params, state, loss = step(params, state,
                                   jnp.asarray(batch_np, jnp.int32))
    return params, pattern, float(loss)


def _cb_spec_bench(params, cfg, slots: int, prompt: int, new: int,
                   stride: int, page: int, reqs: int, iters: int,
                   draft_layers: int, gammas: tuple = (2, 4),
                   degrees: tuple = (1, 2), prompts=None) -> dict:
    """Engine-INTEGRATED speculative decoding (ISSUE 3 tentpole row):
    the same request window drained by the spec-off paged engine and by
    spec-on engines at each γ, at tp=1 and tp=2.  ``params`` should be
    in-bench-TRAINED weights (see ``_train_draft_model``) so acceptance
    is a measurement, not noise.  Reports, per tp: anchored engine
    tok/s off vs per-γ on (deterministic tick counts × chained
    per-dispatch costs — ticks shrink with acceptance, which is the
    whole win), acceptance rate, mean tokens banked per verify tick,
    and ``parity_vs_off`` — token-for-token equality of every request
    against the spec-off leg (the greedy bit-exact contract; also
    asserted in tier-1)."""
    import jax
    import numpy as np

    from kubegpu_tpu.models.serve import ContinuousBatcher, make_serve_mesh

    n_dev = len(jax.devices())
    cb_len = prompt + new + max(stride, max(gammas) + 1) + 8
    if prompts is None:
        base = np.arange(prompt) % cfg.vocab_size
        prompts = [(base + i) % cfg.vocab_size for i in range(reqs)]
    stream = [(np.asarray(p, np.int32), new) for p in prompts[:reqs]]
    out = {"n_slots": slots, "prompt_len": prompt, "new_tokens": new,
           "stride": stride, "requests": len(stream),
           "draft_layers": draft_layers, "gammas": list(gammas),
           "by_tp": {}}

    for tp in degrees:
        name = f"tp{tp}"
        if tp > n_dev or cfg.n_kv_heads % tp:
            out["by_tp"][name] = {
                "skipped": f"needs {tp} devices and "
                           f"tp | n_kv_heads={cfg.n_kv_heads}"}
            continue

        def mk(**kw):
            return ContinuousBatcher(
                params, cfg, n_slots=slots, max_len=cb_len,
                stride=stride, prompt_buckets=(prompt,), paged=True,
                page_size=page,
                mesh=make_serve_mesh(tp) if tp > 1 else None, **kw)

        def drain_leg(**kw):
            eng = mk(**kw)
            eng.warmup()
            t0 = time.perf_counter()
            rids = [eng.submit(p, n) for p, n in stream]
            done = {r.rid: r.tokens for r in eng.drain()}
            wall = time.perf_counter() - t0
            return eng, [done[r] for r in rids], wall

        def probe_of(**kw):
            pr = mk(**kw)
            for p, n in stream[:slots]:
                pr.submit(p, n)
            pr.step()
            return pr

        # -- spec-off leg: today's engine on the same window ----------
        eng, off_tokens, off_wall = drain_leg()
        off_ticks = eng.slot_steps // (stride * slots)
        off_waves = list(eng.wave_log)
        total = sum(len(t) for t in off_tokens)
        del eng
        pr = probe_of()
        blk_s = _probe_block_cost(pr, max(iters * 8, 8))
        wcost = {kb: _probe_wave_cost(pr, kb[0], kb[1], iters)
                 for kb in sorted(set(off_waves))}
        del pr
        off_anchored = off_ticks * blk_s + sum(
            wcost[kb] for kb in off_waves)
        off_tps = total / off_anchored
        row = {"off": {
            "ticks": off_ticks, "tokens": total,
            "block_ms": round(blk_s * 1e3, 3),
            "e2e_ms_raw_weather": round(off_wall * 1e3, 1),
            "engine_tokens_per_s_anchored": round(off_tps, 1),
        }}

        # -- spec-on legs: one engine per γ, same window --------------
        parity_all = True
        best = (0.0, None, 0.0)          # (speedup, gamma, acceptance)
        for g in gammas:
            eng, on_tokens, on_wall = drain_leg(
                spec_gamma=g, draft_layers=draft_layers)
            spec_ticks = eng.spec_ticks
            acc = eng.spec_acceptance_rate
            tpt = eng.spec_tokens_per_tick
            on_waves = list(eng.wave_log)
            del eng
            pr = probe_of(spec_gamma=g, draft_layers=draft_layers)
            tick_s = _probe_spec_cost(pr, iters)
            wcost_g = {kb: _probe_wave_cost(pr, kb[0], kb[1], iters)
                       for kb in sorted(set(on_waves))}
            del pr
            on_anchored = spec_ticks * tick_s + sum(
                wcost_g[kb] for kb in on_waves)
            on_tps = total / on_anchored
            parity = on_tokens == off_tokens
            parity_all = parity_all and parity
            speedup = on_tps / off_tps if off_tps else 0.0
            if speedup > best[0]:
                best = (speedup, g, acc)
            row[f"gamma{g}"] = {
                "verify_ticks": spec_ticks,
                "tick_ms": round(tick_s * 1e3, 3),
                "acceptance_rate": round(acc, 3),
                "tokens_per_tick": round(tpt, 3),
                "e2e_ms_raw_weather": round(on_wall * 1e3, 1),
                "engine_tokens_per_s_anchored": round(on_tps, 1),
                "speedup_vs_off": round(speedup, 3),
                "parity_vs_off": parity,
            }
        row["parity_all"] = parity_all
        row["best_speedup_vs_off"] = round(best[0], 3)
        row["best_gamma"] = best[1]
        row["best_acceptance"] = round(best[2], 3)
        out["by_tp"][name] = row
    return out


def _cb_fused_bench(params, cfg, slots: int, prompt: int, new: int,
                    stride: int, page: int, reqs: int,
                    ks: tuple = (1, 2, 4, 8), prompts=None,
                    repeats: int = 2) -> dict:
    """Fused multi-tick decode A/B (ISSUE 8 tentpole row): the SAME
    request window drained by paged engines at each fused depth K —
    K=1 is today's one-host-sync-per-tick engine, K>1 runs K complete
    decode ticks inside one ``lax.scan`` and fetches one concatenated
    block.  Reports, per K: token parity vs the K=1 leg (the greedy
    bit-exact contract, also asserted in tier-1), fused dispatch/stall
    counters, wall tok/s, and the headline ``host_ms_per_token`` — the
    per-token host-side overhead (step wall MINUS device sync) that
    fused ticks exist to amortize.  Best-of-``repeats`` by
    host_ms_per_token so one GC pause doesn't decide the row."""
    import numpy as np

    from kubegpu_tpu.models.serve import ContinuousBatcher

    cb_len = prompt + new + stride + 8
    if prompts is None:
        rng = np.random.default_rng(8)
        prompts = [rng.integers(1, cfg.vocab_size, size=prompt)
                   for _ in range(reqs)]
    stream = [(np.asarray(p, np.int32), new) for p in prompts[:reqs]]
    out = {"protocol": "same_window_fused_k_sweep", "ks": list(ks),
           "requests": len(stream), "new_tokens": new, "stride": stride,
           "by_k": {}}

    def leg(k):
        eng = ContinuousBatcher(
            params, cfg, n_slots=slots, max_len=cb_len, stride=stride,
            prompt_buckets=(prompt,), paged=True, page_size=page,
            fused_ticks=k)
        eng.warmup()
        t0 = time.perf_counter()
        rids = [eng.submit(p, n) for p, n in stream]
        done = {r.rid: r.tokens for r in eng.drain()}
        wall = time.perf_counter() - t0
        return eng, [done[r] for r in rids], wall

    base_tokens = None
    parity_all = True
    for k in ks:
        best = None
        for _ in range(repeats):
            eng, tokens, wall = leg(k)
            n_tok = sum(len(t) for t in tokens)
            host_ms = sum(eng.host_overhead_ms)
            hpt = host_ms / n_tok if n_tok else float("inf")
            cand = {
                "tokens": n_tok,
                "ticks": eng._tick,
                "steps": len(eng.host_overhead_ms),
                "fused_dispatches": eng.fused_dispatches,
                "fused_ticks_run": eng.fused_ticks_run,
                "fused_stalls": eng.fused_stalls,
                "host_ms_per_token": round(hpt, 4),
                "tokens_per_s_wall": round(n_tok / wall, 1),
                "fused_block_ms": round(
                    float(np.mean(eng.fused_block_ms)), 3)
                if eng.fused_block_ms else None,
            }
            del eng
            if best is None or hpt < best[0]:
                best = (hpt, cand, tokens)
        hpt, row, tokens = best
        if base_tokens is None:
            base_tokens = tokens        # first K in ks must be 1
        row["parity_vs_k1"] = tokens == base_tokens
        parity_all = parity_all and row["parity_vs_k1"]
        out["by_k"][f"k{k}"] = row
    out["parity_all"] = parity_all
    k1 = out["by_k"].get("k1", {}).get("host_ms_per_token")
    k4 = out["by_k"].get("k4", {}).get("host_ms_per_token")
    out["host_ms_per_token_k1"] = k1
    out["host_ms_per_token_k4"] = k4
    out["host_overhead_reduction_x"] = (
        round(k1 / k4, 3) if k1 and k4 else None)
    return out


def _cb_chaos_bench(params, cfg, slots: int, prompt: int, new: int,
                    stride: int, page: int, reqs: int,
                    seed: int = 0) -> dict:
    """Chaos-hardened serving row (ISSUE 4 tentpole): the SAME request
    window drained fault-free and under a seeded injected-fault matrix
    (replica kill, transient dispatch failure, NaN-logit poisoning,
    watchdog tick stall), asserting the recovery contract the issue
    demands — zero lost requests, zero duplicated completions, and
    BIT-EXACT tokens for every replayed stream (greedy replay re-
    conditions on the accepted prefix) — while reporting failover and
    replay timings next to the fault-free baseline.  Throughput here
    is raw wall ("weather"): the row's claim is exactly-once + parity
    under faults plus the recovery cost, not a kernel speedup."""
    import jax
    import numpy as np

    from kubegpu_tpu.models.serve import (
        ContinuousBatcher,
        DataParallelServePool,
    )
    from kubegpu_tpu.obs.chaos import ChaosEvent, ChaosInjector
    from kubegpu_tpu.obs.metrics import MetricsRegistry, percentiles

    # replay prompts grow by the accepted tokens, so the bucket ladder
    # must cover prompt + new (page-aligned)
    buckets = (prompt, prompt + ((new + page - 1) // page) * page)
    cb_len = buckets[-1] + new + stride + 8
    base = np.arange(prompt) % cfg.vocab_size
    stream = [((base + 3 * i) % cfg.vocab_size, new)
              for i in range(reqs)]
    n_dev = len(jax.devices())

    def pool_kw():
        return dict(n_slots=slots, max_len=cb_len, stride=stride,
                    prompt_buckets=buckets, paged=True, page_size=page,
                    prefix_cache=True)

    def run(make, warm=False):
        obj = make()
        if warm:
            # the watchdog scenario must not count compile time as a
            # stall — warmup() compiles every (bucket, wave) the run
            # AND its replays can hit, so steady ticks are compile-free
            obj.warmup()
        t0 = time.perf_counter()
        rids = [obj.submit(p, n) for p, n in stream]
        seen: dict[int, list[int]] = {}
        dup = 0
        for r in obj.drain():
            if r.rid in seen:
                dup += 1
            seen[r.rid] = (None if r.error is not None
                           else list(r.tokens))
        wall = time.perf_counter() - t0
        lost = len([r for r in rids if r not in seen])
        return obj, [seen.get(r) for r in rids], wall, lost, dup

    # -- fault-free baseline (dp=2 when devices allow, else dp=1) ----
    dp = 2 if n_dev >= 2 else 1
    eng0, base_tokens, base_wall, lost0, dup0 = run(
        lambda: DataParallelServePool(params, cfg, dp=dp, tp=1,
                                      **pool_kw()))
    total = sum(len(t) for t in base_tokens if t)
    out = {
        "protocol": "seeded_chaos_matrix",
        "seed": seed, "requests": reqs, "new_tokens": new,
        "dp": dp, "n_slots": slots,
        "fault_free": {
            "completed": len([t for t in base_tokens if t is not None]),
            "lost": lost0, "duplicated": dup0, "tokens": total,
            "wall_ms_raw_weather": round(base_wall * 1e3, 1),
            "tokens_per_s_raw_weather": round(total / base_wall, 1),
        },
        "scenarios": {},
    }

    def scenario(name, make, wall_extra_s=0.0, warm=False):
        reg = MetricsRegistry()
        obj, toks, wall, lost, dup = run(lambda: make(reg), warm=warm)
        exact = toks == base_tokens
        row = {
            "completed": len([t for t in toks if t is not None]),
            "lost": lost, "duplicated": dup,
            "bit_exact_vs_fault_free": exact,
            "wall_ms_raw_weather": round(wall * 1e3, 1),
            "tokens_per_s_raw_weather": round(
                total / max(wall - wall_extra_s, 1e-9), 1),
            "failovers": getattr(obj, "failovers", 0),
            "requests_retried": int(
                reg.counter("serve_requests_retried")),
            "slots_quarantined": int(
                reg.counter("serve_slots_quarantined")),
            "dispatch_failures": int(
                reg.counter("serve_dispatch_failures")),
            "replay_ms": {k: round(v, 3) for k, v in percentiles(
                getattr(obj, "replay_ms", [])).items()},
        }
        out["scenarios"][name] = row

    # replica kill at a seeded tick — dp failover + replay
    kill_tick = 2 + seed % 3
    if dp >= 2:
        scenario("replica_kill", lambda reg: DataParallelServePool(
            params, cfg, dp=dp, tp=1, metrics=reg,
            chaos={0: ChaosInjector(
                [ChaosEvent(tick=kill_tick, kind="kill_replica")])},
            **pool_kw()))
    else:
        out["scenarios"]["replica_kill"] = {"skipped": "needs 2 devices"}

    # one transient dispatch failure — retried in place, no failover
    scenario("dispatch_failure", lambda reg: DataParallelServePool(
        params, cfg, dp=1, tp=1, metrics=reg,
        chaos={0: ChaosInjector(
            [ChaosEvent(tick=1, kind="fail_dispatch")])},
        **pool_kw()))

    # NaN-logit poisoning — slot quarantine + engine-level replay
    scenario("nan_logits", lambda reg: DataParallelServePool(
        params, cfg, dp=1, tp=1, metrics=reg,
        chaos={0: ChaosInjector(
            [ChaosEvent(tick=2 + seed % 2, kind="nan_logits")])},
        **pool_kw()))

    # watchdog tick stall — declared dead, pool fails over.  The
    # injected sleep is subtracted from the throughput figure (it is
    # scenario cost, not engine cost); completions/parity are the row.
    stall_s = 1.2
    if dp >= 2:
        scenario("tick_stall", lambda reg: DataParallelServePool(
            params, cfg, dp=dp, tp=1, metrics=reg,
            tick_deadline_s=stall_s / 2,
            chaos={1: ChaosInjector(
                [ChaosEvent(tick=1, kind="stall_tick",
                            stall_s=stall_s)])},
            **pool_kw()), wall_extra_s=stall_s, warm=True)
    else:
        out["scenarios"]["tick_stall"] = {"skipped": "needs 2 devices"}

    live = [r for r in out["scenarios"].values() if "skipped" not in r]
    out["all_bit_exact"] = all(r["bit_exact_vs_fault_free"]
                               for r in live)
    out["total_lost"] = sum(r["lost"] for r in live)
    out["total_duplicated"] = sum(r["duplicated"] for r in live)
    return out


def _cb_trace_overhead_bench(params, cfg, slots: int, prompt: int,
                             new: int, stride: int, page: int,
                             reqs: int, iters: int = 2) -> dict:
    """Tracing-overhead row (ISSUE 6): the SAME request window drained
    untraced and with a Tracer + MetricsRegistry attached, asserting
    the disabled path's core contract — tracing never touches device
    math, so tokens are BIT-EXACT on/off — and reporting the host-side
    cost (best-of-``iters`` walls; the raw ratio is weather-prone, the
    per-tick delta is the honest figure) plus the span census and a
    shape-validated Perfetto export."""
    import json

    import numpy as np

    from kubegpu_tpu.models.serve import ContinuousBatcher
    from kubegpu_tpu.obs.metrics import MetricsRegistry
    from kubegpu_tpu.obs.spans import Tracer, validate_chrome_trace

    cb_len = prompt + new + stride + 8
    base = np.arange(prompt) % cfg.vocab_size
    stream = [((base + 3 * i) % cfg.vocab_size, new)
              for i in range(reqs)]

    def make(tracer=None, ctx=None, reg=None):
        return ContinuousBatcher(
            params, cfg, n_slots=slots, max_len=cb_len, stride=stride,
            prompt_buckets=(prompt,), paged=True, page_size=page,
            prefix_cache=True, metrics=reg, tracer=tracer,
            trace_ctx=ctx)

    def run(eng):
        eng.warmup()
        t0 = time.perf_counter()
        for p, n in stream:
            eng.submit(p, n)
        done = sorted(eng.drain(), key=lambda r: r.rid)
        return [list(r.tokens) for r in done], time.perf_counter() - t0

    off_tokens, off_walls = None, []
    for _ in range(iters):
        toks, w = run(make())
        off_walls.append(w)
        off_tokens = off_tokens or toks
    on_tokens, on_walls, tracer0, tid = None, [], None, ""
    for _ in range(iters):
        tr = Tracer()
        # stand-in for the crishim-injected parent: the export below
        # is the exact artifact a traced serve pod would dump
        root = tr.start_span("crishim.inject")
        root.end()
        toks, w = run(make(tr, root.context, MetricsRegistry()))
        on_walls.append(w)
        if on_tokens is None:
            on_tokens, tracer0, tid = toks, tr, root.trace_id
    spans = tracer0.spans(tid)
    trace_json = tracer0.to_chrome_trace(tid)
    try:
        validate_chrome_trace(trace_json)
        trace_valid = True
    except ValueError:
        trace_valid = False
    off_w, on_w = min(off_walls), min(on_walls)
    n_ticks = len(tracer0.spans(tid, "engine.tick"))
    return {
        "protocol": "same_window_traced_vs_untraced_best_of",
        "iters": iters, "requests": reqs, "new_tokens": new,
        "bit_exact": on_tokens == off_tokens,
        "untraced_wall_ms": round(off_w * 1e3, 2),
        "traced_wall_ms": round(on_w * 1e3, 2),
        "overhead_x_raw_weather": round(on_w / off_w, 3),
        "trace_overhead_us_per_tick": round(
            max(on_w - off_w, 0.0) / max(n_ticks, 1) * 1e6, 1),
        "spans": len(spans),
        "engine_ticks_traced": n_ticks,
        "span_names": sorted({s.name for s in spans}),
        "chrome_trace_valid": trace_valid,
        "chrome_trace_events": len(
            json.loads(trace_json)["traceEvents"]),
    }


def _cb_prefix_bench(qparams, cfg, slots: int, prompt: int, new: int,
                     stride: int, page: int, n_way: int) -> dict:
    """Shared-prefix serving workload on the refcounted page pool: one
    leader pays the full prefill; ``n_way - 1`` followers share every
    cacheable prompt page (identical prompts except the last page,
    which is never cacheable) and prefill only their tails through the
    pool-history chunk path.  Reports the prefill work actually done
    vs the naive N × full cost, and the pool pages aliasing saved —
    the driver-recorded row VERDICT r5 next-item #2 demanded."""
    import numpy as np

    from kubegpu_tpu.models.serve import ContinuousBatcher

    cb_len = prompt + new + stride + 8
    base = np.arange(prompt) % cfg.vocab_size

    def variant(j):
        p = base.copy()
        p[-1] = (p[-1] + j) % cfg.vocab_size   # last page differs
        return p

    eng = ContinuousBatcher(
        qparams, cfg, n_slots=slots, max_len=cb_len, stride=stride,
        prompt_buckets=(prompt,), paged=True, page_size=page,
        prefix_cache=True, prefill_chunk=2 * page)
    eng.warmup()
    t0 = time.perf_counter()
    eng.submit(variant(0), new)
    eng.step()                     # leader admits + registers
    for j in range(1, n_way):
        eng.submit(variant(j), new)
    done = []
    peak_pages = 0
    ticks = 0
    while (eng.queue or eng.slot_req) and ticks < 10_000:
        done.extend(eng.step())
        peak_pages = max(peak_pages, sum(
            1 for r in eng._page_refs.values() if r > 0))
        ticks += 1
    elapsed = time.perf_counter() - t0
    naive_tokens = n_way * prompt
    naive_pages = n_way * eng._pages_needed(new, prompt)
    return {
        "n_way": n_way,
        "prompt_len": prompt,
        "new_tokens": new,
        "requests_completed": len(done),
        "prefill_tokens_naive": naive_tokens,
        "prefill_tokens_actual": eng.prefill_tokens,
        "prefill_reduction_x": round(
            naive_tokens / max(eng.prefill_tokens, 1), 3),
        "prefill_tokens_saved": eng.prefill_tokens_saved,
        "pages_aliased": eng.pages_aliased,
        "pages_naive": naive_pages,
        "peak_pages_in_use": peak_pages,
        "pages_saved_at_peak": naive_pages - peak_pages,
        "prefix_hits": eng.prefix_hits,
        "chunks_run": eng.chunks_run,
        "e2e_ms_raw_weather": round(elapsed * 1e3, 1),
    }


def _cb_stall_bench(qparams, cfg, slots: int, prompt: int, new: int,
                    stride: int, reqs: int, page: int, chunk: int,
                    iters: int) -> dict:
    """Per-tick decode stall, chunked prefill ON vs OFF, at one shape.

    The stall of a tick is the admission work its decode slots waited
    behind: with chunking off that is whole [k, prompt] prefill waves;
    with chunking on it is page-aligned chunks.  The figure of record
    is DEVICE-ANCHORED (the engine's host-wall ``stall_ms`` is a
    dispatch-time proxy): per-dispatch wave and chunk costs are
    chained-measured in this window and folded over each tick's actual
    admission log, so the p50/p99 reflect device time, not tunnel
    weather (chunk cost is taken at near-max history — conservative
    for the reduction claim)."""
    import numpy as np

    from kubegpu_tpu.models.serve import ContinuousBatcher
    from kubegpu_tpu.obs.metrics import percentiles

    cb_len = prompt + new + stride + 8
    base = np.arange(prompt) % cfg.vocab_size

    def leg(chunked: bool) -> dict:
        mk = lambda: ContinuousBatcher(   # noqa: E731
            qparams, cfg, n_slots=slots, max_len=cb_len, stride=stride,
            prompt_buckets=(prompt,), paged=True, page_size=page,
            chunked_prefill=chunked, prefill_chunk=chunk)
        eng = mk()
        eng.warmup()
        for i in range(reqs):
            eng.submit((base + i) % cfg.vocab_size, new)
        eng.drain()
        tick_log = list(eng._tick_log)
        host = percentiles(eng.stall_ms)
        occ = eng.occupancy
        del eng
        probe = mk()
        for i in range(slots):
            probe.submit((base + i) % cfg.vocab_size, new)
        probe.step()
        wave_kinds = sorted({(w[1], w[2]) for t_ in tick_log
                             for w in t_["work"] if w[0] == "wave"})
        wave_cost = {kb: _probe_wave_cost(probe, kb[0], kb[1], iters)
                     for kb in wave_kinds}
        any_chunks = any(w[0] == "chunk" for t_ in tick_log
                         for w in t_["work"])
        chunk_s = (_probe_chunk_cost(probe, prompt, iters)
                   if any_chunks else 0.0)
        stalls = []
        for t_ in tick_log:
            s_ = 0.0
            for w in t_["work"]:
                s_ += wave_cost[(w[1], w[2])] if w[0] == "wave" \
                    else chunk_s
            stalls.append(s_ * 1e3)
        anchored = percentiles(stalls)
        return {
            "chunked_prefill": chunked,
            "ticks": len(tick_log),
            "occupancy": round(occ, 3),
            "stall_ms_anchored": {k: round(v, 3)
                                  for k, v in anchored.items()},
            "stall_ms_host_proxy": {k: round(v, 3)
                                    for k, v in host.items()},
            "wave_cost_ms": {f"{k}x{b}": round(v * 1e3, 3)
                             for (k, b), v in wave_cost.items()},
            "chunk_cost_ms": round(chunk_s * 1e3, 3),
        }

    off = leg(False)
    on = leg(True)
    off_p99 = off["stall_ms_anchored"].get("p99", 0.0)
    on_p99 = on["stall_ms_anchored"].get("p99", 0.0)
    return {
        "n_slots": slots, "prompt_len": prompt, "new_tokens": new,
        "stride": stride, "requests": reqs, "prefill_chunk": chunk,
        "off": off, "on": on,
        "stall_p99_ms_off": off_p99,
        "stall_p99_ms_on": on_p99,
        "stall_p99_reduction_x": round(off_p99 / on_p99, 3)
        if on_p99 else 0.0,
    }


def _cb_equal_hbm_bench(qparams, cfg, dense_slots: int, paged_slots: int,
                        buckets: tuple, mix: list, reqs: int,
                        stride: int, page: int, iters: int) -> dict:
    """Equal-HBM, mixed-length paged-vs-dense A/B (VERDICT r5 next-item
    #1): both engines get the SAME KV byte budget.  Dense spends it on
    ``dense_slots`` full ``max_len`` rows; paged spends the identical
    budget on a shared pool serving ``paged_slots`` slots, so short
    requests decode in the pages long rows aren't using — the
    structural advantage the uniform full-fill A/B could never
    express.  Anchored exactly like ``_cb_ab_bench``: deterministic
    tick/wave counts × per-dispatch costs chained in this window."""
    import numpy as np

    from kubegpu_tpu.models.serve import ContinuousBatcher

    max_bucket = max(buckets)
    max_new = max(n for _, n in mix)
    cb_len = max_bucket + max_new + stride + 8
    total_pages = (dense_slots * cb_len) // page   # dense's byte budget
    stream = [mix[i % len(mix)] for i in range(reqs)]

    def leg(paged: bool) -> dict:
        n_slots = paged_slots if paged else dense_slots

        def mk():
            return ContinuousBatcher(
                qparams, cfg, n_slots=n_slots, max_len=cb_len,
                stride=stride, prompt_buckets=buckets, paged=paged,
                page_size=page,
                total_pages=total_pages if paged else None)

        eng = mk()
        eng.warmup()
        t0 = time.perf_counter()
        for plen, n in stream:
            eng.submit(np.arange(plen) % cfg.vocab_size, n)
        done = eng.drain()
        elapsed = time.perf_counter() - t0
        ticks = eng.slot_steps // (stride * n_slots)
        total = sum(len(r.tokens) for r in done)
        wave_log = list(eng.wave_log)
        occ = eng.occupancy
        del eng
        probe = mk()
        for plen, n in stream[:n_slots]:
            probe.submit(np.arange(plen) % cfg.vocab_size, n)
        probe.step()
        blk_s = _probe_block_cost(probe, max(iters * 8, 8))
        wave_kinds = sorted(set(wave_log))
        wcost = {kb: _probe_wave_cost(probe, kb[0], kb[1], iters)
                 for kb in wave_kinds}
        anchored_s = ticks * blk_s + sum(wcost[kb] for kb in wave_log)
        return {
            "n_slots": n_slots,
            "ticks": ticks, "waves": len(wave_log), "tokens": total,
            "occupancy": round(occ, 3),
            "block_ms": round(blk_s * 1e3, 3),
            "e2e_ms_raw_weather": round(elapsed * 1e3, 1),
            "e2e_tokens_per_s_anchored": round(total / anchored_s, 1),
        }

    dense = leg(False)
    paged = leg(True)
    return {
        "protocol": "equal_hbm_mixed_length",
        "kv_budget_tokens": dense_slots * cb_len,
        "total_pages": total_pages,
        "dense_slots": dense_slots, "paged_slots": paged_slots,
        "buckets": list(buckets),
        "mix": [list(m) for m in mix],
        "requests": reqs,
        "dense": dense,
        "paged": paged,
        "paged_vs_dense_equal_hbm": round(
            paged["e2e_tokens_per_s_anchored"]
            / dense["e2e_tokens_per_s_anchored"], 3)
        if dense["e2e_tokens_per_s_anchored"] else 0.0,
    }


def _cb_tp_bench(qparams, cfg, slots: int, prompt: int, new: int,
                 stride: int, reqs: int, page: int, iters: int,
                 degrees: tuple = (1, 2, 4),
                 equal_chips: int = 4) -> dict:
    """Mesh-native serving scaling: engine throughput at tp=1/2/4 with
    per-phase timings, plus the EQUAL-CHIP question — the same
    ``equal_chips`` devices spent as ONE tp=N engine vs N independent
    dp replicas behind one admission queue, on the SAME request
    stream.  Anchored like every cb row: deterministic tick/wave
    counts x per-dispatch costs chained in this window (for the dp
    leg, replicas run on disjoint chips, so the anchored model is the
    MAX over replicas of their per-replica anchored time — host wall
    on virtual CPU devices would serialize what real chips overlap).
    Rows skip (with a reason) when the window has too few devices or
    tp doesn't divide the KV heads."""
    import jax
    import numpy as np

    from kubegpu_tpu.models.serve import (
        ContinuousBatcher,
        DataParallelServePool,
        make_serve_mesh,
    )

    n_dev = len(jax.devices())
    cb_len = prompt + new + stride + 8
    base = np.arange(prompt) % cfg.vocab_size
    stream = [((base + i) % cfg.vocab_size, new) for i in range(reqs)]

    def mk(mesh):
        return ContinuousBatcher(
            qparams, cfg, n_slots=slots, max_len=cb_len, stride=stride,
            prompt_buckets=(prompt,), paged=True, page_size=page,
            mesh=mesh)

    def anchored_leg(eng_ticks, eng_wave_log, probe):
        blk_s = _probe_block_cost(probe, max(iters * 8, 8))
        wcost = {kb: _probe_wave_cost(probe, kb[0], kb[1], iters)
                 for kb in sorted(set(eng_wave_log))}
        return blk_s, wcost, (eng_ticks * blk_s
                              + sum(wcost[kb] for kb in eng_wave_log))

    out = {"devices": n_dev, "n_slots": slots, "prompt_len": prompt,
           "new_tokens": new, "stride": stride, "requests": reqs,
           "scaling": {}}
    tp1_tps = None
    for tp in degrees:
        name = f"tp{tp}"
        if tp > n_dev or cfg.n_kv_heads % tp:
            out["scaling"][name] = {
                "skipped": f"needs {tp} devices and "
                           f"tp | n_kv_heads={cfg.n_kv_heads}"}
            continue
        eng = mk(make_serve_mesh(tp))
        eng.warmup()
        t0 = time.perf_counter()
        for p, n in stream:
            eng.submit(p, n)
        done = eng.drain()
        elapsed = time.perf_counter() - t0
        ticks = eng.slot_steps // (stride * slots)
        total = sum(len(r.tokens) for r in done)
        wave_log = list(eng.wave_log)
        del eng
        probe = mk(make_serve_mesh(tp))
        for p, n in stream[:slots]:
            probe.submit(p, n)
        probe.step()
        blk_s, wcost, anchored_s = anchored_leg(ticks, wave_log, probe)
        tps = total / anchored_s
        if tp == 1:
            tp1_tps = tps
        out["scaling"][name] = {
            "ticks": ticks, "waves": len(wave_log), "tokens": total,
            "e2e_ms_raw_weather": round(elapsed * 1e3, 1),
            "engine_tokens_per_s_anchored": round(tps, 1),
            "speedup_vs_tp1": round(tps / tp1_tps, 3) if tp1_tps
            else None,
            # per-phase: the stride-amortized decode block and each
            # admission wave shape (prefill + adopt per dispatch)
            "phase_decode_block_ms": round(blk_s * 1e3, 3),
            "phase_admission_ms_by_wave": {
                f"{k}x{b}": round(s * 1e3, 3)
                for (k, b), s in wcost.items()},
        }

    # -- equal-chip A/B: tp=equal_chips vs dp=equal_chips replicas ----
    dp = tp_deg = equal_chips
    if n_dev < equal_chips or cfg.n_kv_heads % tp_deg:
        out["equal_chip_ab"] = {
            "skipped": f"needs {equal_chips} devices and tp | "
                       f"n_kv_heads={cfg.n_kv_heads}"}
        return out
    # tp leg: one engine over equal_chips devices
    eng = mk(make_serve_mesh(tp_deg))
    eng.warmup()
    t0 = time.perf_counter()
    for p, n in stream:
        eng.submit(p, n)
    done = eng.drain()
    tp_wall = time.perf_counter() - t0
    tp_ticks = eng.slot_steps // (stride * slots)
    tp_tokens = sum(len(r.tokens) for r in done)
    tp_wave_log = list(eng.wave_log)
    del eng
    probe = mk(make_serve_mesh(tp_deg))
    for p, n in stream[:slots]:
        probe.submit(p, n)
    probe.step()
    _, _, tp_anchored = anchored_leg(tp_ticks, tp_wave_log, probe)
    del probe
    # dp leg: equal_chips single-chip replicas, one admission queue,
    # SAME stream
    pool = DataParallelServePool(
        qparams, cfg, dp=dp, tp=1, n_slots=slots, max_len=cb_len,
        stride=stride, prompt_buckets=(prompt,), page_size=page)
    pool.warmup()
    t0 = time.perf_counter()
    for p, n in stream:
        pool.submit(p, n)
    done = pool.drain()
    dp_wall = time.perf_counter() - t0
    dp_tokens = sum(len(r.tokens) for r in done)
    per_replica = [(e.slot_steps // (stride * slots), list(e.wave_log))
                   for e in pool.replicas]
    del pool
    probe = mk(make_serve_mesh(1))
    for p, n in stream[:slots]:
        probe.submit(p, n)
    probe.step()
    blk_s = _probe_block_cost(probe, max(iters * 8, 8))
    all_kinds = sorted({kb for _, wl in per_replica for kb in wl})
    wcost = {kb: _probe_wave_cost(probe, kb[0], kb[1], iters)
             for kb in all_kinds}
    dp_anchored = max(
        (t_ * blk_s + sum(wcost[kb] for kb in wl)
         for t_, wl in per_replica), default=1e-9)
    tp_tps = tp_tokens / tp_anchored
    dp_tps = dp_tokens / dp_anchored
    out["equal_chip_ab"] = {
        "chips": equal_chips,
        "tp": {"tokens": tp_tokens, "ticks": tp_ticks,
               "e2e_ms_raw_weather": round(tp_wall * 1e3, 1),
               "engine_tokens_per_s_anchored": round(tp_tps, 1)},
        "dp": {"tokens": dp_tokens,
               "replica_ticks": [t_ for t_, _ in per_replica],
               "e2e_ms_raw_weather": round(dp_wall * 1e3, 1),
               "engine_tokens_per_s_anchored": round(dp_tps, 1)},
        "tp_vs_dp": round(tp_tps / dp_tps, 3) if dp_tps else 0.0,
        # the documented default for this regime: whichever leg the
        # driver-recorded number favors (tp shards the KV read and
        # wins when a single stream is latency/HBM-bound; dp wins on
        # abundant independent traffic — the README states the rule)
        "winner": "tp" if tp_tps >= dp_tps else "dp",
    }
    return out


def _cb_ab_bench(qparams, cfg, slots: int, prompt: int, new: int,
                 stride: int, reqs: int, page: int, kv_int8: bool,
                 iters: int) -> dict:
    """Three-way continuous-batching A/B at one shape: the static
    formulation, the dense-cache slot engine, and the PAGED engine
    (``kv_int8`` pages when the shape sits past llama_serve's
    n_slots x prompt >= 16k crossover).  The e2e figure of record is
    DEVICE-ANCHORED: deterministic dispatch counts x per-dispatch costs
    chained-measured in the same window — the r3 raw-wall number swung
    10x with tunnel weather because ~480 ms of device work hid under
    seconds of fluctuating dispatch overhead.  Raw wall time is still
    reported, labeled as weather."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubegpu_tpu.models import greedy_generate
    from kubegpu_tpu.models.serve import ContinuousBatcher

    cb_len = prompt + new + stride + 8
    cb_p = np.arange(prompt) % cfg.vocab_size
    # static comparator at the same shape/params/cache dtype
    cb_sp = jnp.asarray(
        np.arange(slots * prompt).reshape(slots, prompt)
        % cfg.vocab_size, jnp.int32)
    static_s = _time_calls(
        lambda: greedy_generate(qparams, cb_sp, new, cfg,
                                max_len=cb_len),
        lambda o: o, iters)
    static_tps = slots * new / static_s

    def run_engine(paged: bool) -> dict:
        quant = paged and kv_int8
        eng = ContinuousBatcher(
            qparams, cfg, n_slots=slots, max_len=cb_len,
            stride=stride, prompt_buckets=(prompt,),
            paged=paged, page_size=page, kv_int8=quant)
        eng.warmup()   # state-free: compiles every wave size + block
        t0 = time.perf_counter()
        for i in range(reqs):
            eng.submit((cb_p + i) % cfg.vocab_size, new)
        done = eng.drain()
        elapsed = time.perf_counter() - t0
        ticks = eng.slot_steps // (stride * slots)
        total = sum(len(r.tokens) for r in done)
        # per-dispatch costs, chained in THIS window, on the engine's
        # own executables and a throwaway engine state
        probe = ContinuousBatcher(
            qparams, cfg, n_slots=slots, max_len=cb_len,
            stride=stride, prompt_buckets=(prompt,),
            paged=paged, page_size=page, kv_int8=quant)
        # fill EVERY probe slot before chaining: the paged kernel's
        # work scales with the pages active rows actually hold, so a
        # 1-of-8-slots probe would undercount the block cost ~8x and
        # flatter the anchored e2e (r4 review catch)
        for i in range(slots):
            probe.submit((cb_p + i) % cfg.vocab_size, new)
        probe.step()
        assert probe.active.all(), "probe must run at full occupancy"
        occ_scalars = dict(occupancy=round(eng.occupancy, 3),
                           waves=eng.prefill_waves,
                           wave_sizes=list(eng.wave_sizes))
        del eng  # its pool/cache is dead weight during the probe
        # chained block rate on the probe's jitted decode_block, then
        # per-wave admission cost (prefill + adopt) — both via the
        # shared probe helpers, which own the donate-the-pool chaining
        # protocol (see _probe_block_cost).  Admission is measured at
        # each WAVE SIZE the drain actually dispatched (max_wave
        # defaults to 8, so waves are usually [k=8, k=8, ...]) —
        # probing only k=1 would undercount the admission term ~7x.
        blk_s = _probe_block_cost(probe, max(iters * 8, 8))
        wave_cost_s = {
            kwave: _probe_wave_cost(probe, kwave, prompt, iters)
            for kwave in sorted(set(occ_scalars["wave_sizes"]))}
        anchored_s = ticks * blk_s + sum(
            wave_cost_s[k_] for k_ in occ_scalars["wave_sizes"])
        return {
            "occupancy": occ_scalars["occupancy"],
            "ticks": ticks, "waves": occ_scalars["waves"],
            "tokens": total,
            "e2e_ms_raw_weather": round(elapsed * 1e3, 1),
            "block_ms": round(blk_s * 1e3, 3),
            "decode_tokens_per_s": round(slots * stride / blk_s, 1),
            "e2e_tokens_per_s_anchored": round(total / anchored_s, 1),
            "vs_static_e2e_anchored": round(
                (total / anchored_s) / static_tps, 3),
        }

    dense = run_engine(paged=False)
    paged = run_engine(paged=True)
    return {
        "n_slots": slots, "prompt_len": prompt,
        "new_tokens": new, "stride": stride,
        "requests": reqs,
        "pooled_tokens": slots * prompt,
        "kv_int8_pages": kv_int8,
        "static_e2e_tokens_per_s": round(static_tps, 1),
        "dense": dense,
        "paged": paged,
        "paged_vs_dense": round(
            paged["e2e_tokens_per_s_anchored"]
            / dense["e2e_tokens_per_s_anchored"], 3)
        if dense["e2e_tokens_per_s_anchored"] else 0.0,
        # headline figures = the paged engine (the serving default)
        "occupancy": paged["occupancy"],
        "decode_tokens_per_s": paged["decode_tokens_per_s"],
        "e2e_tokens_per_s_anchored": paged["e2e_tokens_per_s_anchored"],
        "vs_static_e2e": paged["vs_static_e2e_anchored"],
    }


def _families_bench(cfg, params, on_tpu) -> dict:
    """Reproducible rows for every non-flagship BASELINE.md hardware
    figure (VERDICT r2 weak #2: those numbers were session anecdotes no
    committed harness could regenerate): MoE serving, T5 serving, LoRA
    fine-tune step, beam search, speculative decode.  ``cfg``/``params``
    are the flagship train bench's (the Llama-based rows reuse them).
    On CPU the same code runs at tiny scale so tests cover the paths."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from kubegpu_tpu.models import LlamaConfig
    from kubegpu_tpu.models.decode import (
        beam_generate,
        draft_view,
        greedy_generate,
        spec_generate_fused,
    )
    from kubegpu_tpu.models.lora import (
        LoRAConfig,
        lora_init,
        lora_n_params,
        make_lora_train_step,
    )
    from kubegpu_tpu.models.moe import MoEConfig, moe_greedy_generate, moe_init
    from kubegpu_tpu.models.quant import quantize_llama
    from kubegpu_tpu.models.t5 import t5_greedy_generate, t5_init
    from kubegpu_tpu.models.t5 import T5Config
    from kubegpu_tpu.parallel.sharding import donating_jit

    if on_tpu:
        moe_cfg = moe_bench_config()
        t5_cfg = t5_bench_config()
        moe_b, moe_t, moe_steps = 8, 512, 64
        t5_b, t5_t, t5_steps = 8, 512, 64
        beam_b, beam_t, beam_steps, beams = 4, 512, 32, 4
        spec_b, spec_t, spec_steps = 8, 1024, 128
        lora_batch, iters = 4, 2
    else:
        moe_cfg = MoEConfig.tiny()
        t5_cfg = T5Config.tiny()
        moe_b, moe_t, moe_steps = 2, 8, 4
        t5_b, t5_t, t5_steps = 2, 8, 4
        beam_b, beam_t, beam_steps, beams = 2, 8, 3, 2
        spec_b, spec_t, spec_steps = 2, 8, 6
        lora_batch, iters = 2, 2
    seq = cfg.max_seq_len

    def prompt_of(b, t, vocab):
        return jnp.asarray(
            np.arange(b * t).reshape(b, t) % vocab, jnp.int32)

    out = {}

    # --- MoE serving: routed-expert decode, int8 KV cache; int8
    # weights are the big lever here — top-2-of-8 routing still
    # streams ALL expert weights every step, so halving their bytes
    # is ~2x (measured 1.9x) ---
    from kubegpu_tpu.models.quant import quantize_moe
    moe_params = moe_init(jax.random.PRNGKey(1), moe_cfg)
    mp = prompt_of(moe_b, moe_t, moe_cfg.base.vocab_size)
    moe_len = moe_t + moe_steps
    moe_s = _time_calls(
        lambda: moe_greedy_generate(moe_params, mp, moe_steps, moe_cfg,
                                    max_len=moe_len, kv_int8=True),
        lambda o: o, iters)
    moe_q = quantize_moe(moe_params)
    moe_qs = _time_calls(
        lambda: moe_greedy_generate(moe_q, mp, moe_steps, moe_cfg,
                                    max_len=moe_len, kv_int8=True),
        lambda o: o, iters)
    out["moe_serving"] = {
        "params_m": round(sum(
            x.size for x in jax.tree.leaves(moe_params)) / 1e6, 1),
        "batch": moe_b, "prompt_len": moe_t, "steps": moe_steps,
        "e2e_ms": round(moe_s * 1e3, 2),
        "gen_tokens_per_s_e2e": round(moe_b * moe_steps / moe_s, 1),
        "int8_gen_tokens_per_s_e2e": round(
            moe_b * moe_steps / moe_qs, 1),
        "int8_speedup": round(moe_s / moe_qs, 2),
    }
    # MoE decode ON THE PAGE POOL vs the dense slot engine, same
    # protocol (chained block cost on a full-occupancy probe) — the
    # MoE-on-pool chip row VERDICT r5 item #5 asked for.  The routed
    # FFN rides the engine's ffn hook; only the attention/KV side
    # changes between the legs.
    from kubegpu_tpu.models.serve import ContinuousBatcher
    if on_tpu:
        m_slots, m_prompt, m_new, m_stride, m_page = 8, 512, 32, 16, 128
    else:
        m_slots, m_prompt, m_new, m_stride, m_page = 2, 8, 4, 2, 8
    moe_pool_row = {"n_slots": m_slots, "prompt_len": m_prompt,
                    "stride": m_stride}
    for leg, paged_ in (("dense", False), ("paged", True)):
        probe = ContinuousBatcher(
            moe_params, moe_cfg, n_slots=m_slots,
            max_len=m_prompt + m_new + m_stride + 8, stride=m_stride,
            prompt_buckets=(m_prompt,), paged=paged_, page_size=m_page)
        mpb = np.arange(m_prompt) % moe_cfg.base.vocab_size
        for i in range(m_slots):
            probe.submit((mpb + i) % moe_cfg.base.vocab_size, m_new)
        probe.step()
        blk_s = _probe_block_cost(probe, max(iters * 4, 4))
        moe_pool_row[leg] = {
            "block_ms": round(blk_s * 1e3, 3),
            "decode_tokens_per_s": round(
                m_slots * m_stride / blk_s, 1),
        }
        del probe
    moe_pool_row["paged_vs_dense"] = round(
        moe_pool_row["paged"]["decode_tokens_per_s"]
        / moe_pool_row["dense"]["decode_tokens_per_s"], 3)
    out["moe_paged_engine"] = moe_pool_row
    del moe_params, moe_q

    # --- T5 serving: encode once + cached decode (bf16 and int8) ---
    from kubegpu_tpu.models.quant import quantize_t5
    t5_params = t5_init(jax.random.PRNGKey(2), t5_cfg)
    tp = prompt_of(t5_b, t5_t, t5_cfg.vocab_size)
    t5_s = _time_calls(
        lambda: t5_greedy_generate(t5_params, tp, t5_steps, t5_cfg),
        lambda o: o, iters)
    t5_q = quantize_t5(t5_params)
    t5_qs = _time_calls(
        lambda: t5_greedy_generate(t5_q, tp, t5_steps, t5_cfg),
        lambda o: o, iters)
    # T5 decoder self-attn on the PAGE POOL (the biased paged kernel)
    # vs the dense cache, same window + protocol — a paged_vs_dense
    # below ~1 here is explained by the bias-table one-hot lookup the
    # paged kernel pays in-kernel; anything beyond that is a
    # regression against the dense row above.
    from kubegpu_tpu.models.t5 import t5_greedy_generate_paged
    t5_page = 128 if on_tpu else 8
    t5_pps = _time_calls(
        lambda: t5_greedy_generate_paged(t5_params, tp, t5_steps,
                                         t5_cfg, page_size=t5_page),
        lambda o: o, iters)
    out["t5_serving"] = {
        "params_m": round(sum(
            x.size for x in jax.tree.leaves(t5_params)) / 1e6, 1),
        "batch": t5_b, "enc_len": t5_t, "steps": t5_steps,
        "e2e_ms": round(t5_s * 1e3, 2),
        "gen_tokens_per_s_e2e": round(t5_b * t5_steps / t5_s, 1),
        "int8_gen_tokens_per_s_e2e": round(
            t5_b * t5_steps / t5_qs, 1),
        "int8_speedup": round(t5_s / t5_qs, 2),
        "paged": {
            "page_size": t5_page,
            "e2e_ms": round(t5_pps * 1e3, 2),
            "gen_tokens_per_s_e2e": round(
                t5_b * t5_steps / t5_pps, 1),
            "paged_vs_dense": round(t5_s / t5_pps, 3),
        },
    }
    del t5_params, t5_q

    # --- LoRA fine-tune step on the flagship params ---
    lcfg = LoRAConfig(rank=8)
    adapters = lora_init(jax.random.PRNGKey(3), params, lcfg)
    opt = optax.adamw(1e-3)
    lora_opt_state = opt.init(adapters)
    lora_step = donating_jit(make_lora_train_step(cfg, lcfg, opt),
                             donate=("adapters", "opt_state"))
    toks = jnp.asarray(
        np.arange(lora_batch * seq).reshape(lora_batch, seq)
        % cfg.vocab_size, jnp.int32)
    lora_s, _ = _time_chained(
        lambda s: lora_step(s[0], s[1], params, toks),
        (adapters, lora_opt_state), iters=max(iters * 3, 4))
    out["lora"] = {
        "rank": lcfg.rank,
        "trainable_params_k": round(lora_n_params(adapters) / 1e3, 1),
        "step_ms": round(lora_s * 1e3, 2),
    }

    # --- int8 + int8-KV llama serving variants: beam + speculative ---
    qparams = quantize_llama(params)
    bp = prompt_of(beam_b, beam_t, cfg.vocab_size)
    beam_len = beam_t + beam_steps
    beam_s = _time_calls(
        lambda: beam_generate(qparams, bp, beam_steps, cfg, beams=beams,
                              max_len=beam_len, kv_int8=True)[0],
        lambda o: o, iters)
    # beam search with the PROMPT segment on the page pool (beams
    # alias their sequence's pages — the kernel reads each prompt page
    # once per sequence, not once per beam), same window + protocol
    from kubegpu_tpu.models.decode import beam_generate_paged
    beam_page = 128 if on_tpu else 8
    beam_ps = _time_calls(
        lambda: beam_generate_paged(qparams, bp, beam_steps, cfg,
                                    beams=beams, page_size=beam_page,
                                    max_len=beam_len)[0],
        lambda o: o, iters)
    out["beam"] = {
        "beams": beams, "batch": beam_b, "prompt_len": beam_t,
        "steps": beam_steps, "e2e_ms": round(beam_s * 1e3, 2),
        "paged": {
            "page_size": beam_page,
            "e2e_ms": round(beam_ps * 1e3, 2),
            "paged_vs_dense": round(beam_s / beam_ps, 3),
        },
    }

    # --- continuous batching: arrival-driven serving (models/serve.py) ---
    # Same-window three-way A/B (VERDICT r3 next-item #2): the static
    # formulation, the dense-cache slot engine, and the PAGED engine
    # (pallas paged-attention pool) are measured inside this one bench
    # invocation with one protocol — TWICE: at the historical 8 x 512
    # shape (where dense wins — the small-scale fast path) and at the
    # FLAGSHIP serving scale 32 slots x 1024 prompt (32k pooled tokens,
    # >= the 16k crossover where llama_serve auto-enables int8 pages),
    # where the paged pool's wins live.  VERDICT r4 weak #4: the paged
    # win existed only in builder-written BASELINE.md because the bench
    # only measured the shape where paged loses.
    if on_tpu:
        out["continuous_batching"] = _cb_ab_bench(
            qparams, cfg, slots=8, prompt=512, new=64, stride=16,
            reqs=24, page=128, kv_int8=False, iters=iters)
        out["continuous_batching_flagship"] = _cb_ab_bench(
            qparams, cfg, slots=32, prompt=1024, new=64, stride=16,
            reqs=48, page=128, kv_int8=True, iters=iters)
        # serving fast path: prefix caching, chunked-prefill stall,
        # and the equal-HBM mixed-length A/B (VERDICT r5 items 1/2/8)
        out["cb_prefix_cache"] = _cb_prefix_bench(
            qparams, cfg, slots=8, prompt=1024, new=64, stride=16,
            page=128, n_way=8)
        out["cb_chunked_stall"] = _cb_stall_bench(
            qparams, cfg, slots=32, prompt=1024, new=64, stride=16,
            reqs=48, page=128, chunk=256, iters=iters)
        out["cb_equal_hbm"] = _cb_equal_hbm_bench(
            qparams, cfg, dense_slots=8, paged_slots=24,
            buckets=(128, 1024),
            mix=[(128, 64), (128, 64), (128, 64), (1024, 64)],
            reqs=48, stride=16, page=128, iters=iters)
        # mesh-native serving: tp=1/2/4 scaling + the equal-chip
        # tp-vs-dp A/B (rows self-skip on a 1-chip window; the
        # 8-device multichip dryrun records the populated rows)
        out["cb_tp_serving"] = _cb_tp_bench(
            qparams, cfg, slots=8, prompt=512, new=64, stride=16,
            reqs=24, page=128, iters=iters)
        # fused multi-tick decode (ISSUE 8): same-window K sweep —
        # host ms/token is the metric fused ticks exist to shrink
        out["cb_fused_ticks"] = _cb_fused_bench(
            qparams, cfg, slots=8, prompt=512, new=64, stride=16,
            reqs=24, page=128)
        # grouped int4 KV + attention-aware eviction (ISSUE 15): the
        # equal-budget capacity A/B at flagship serving scale — the
        # 1024-token prompts span 8 pages, so the eviction legs ride
        # the same shape
        out["cb_kv_capacity"] = _cb_kv_capacity_bench(
            qparams, cfg, slots=8, prompt=1024, new=64, stride=16,
            page=128, reqs=16)
    else:
        out["continuous_batching"] = _cb_ab_bench(
            qparams, cfg, slots=2, prompt=8, new=4, stride=2,
            reqs=4, page=8, kv_int8=False, iters=iters)
        # tiny flagship-shaped row keeps the int8-paged path covered
        out["continuous_batching_flagship"] = _cb_ab_bench(
            qparams, cfg, slots=2, prompt=8, new=4, stride=2,
            reqs=4, page=8, kv_int8=True, iters=iters)
        out["cb_prefix_cache"] = _cb_prefix_bench(
            qparams, cfg, slots=2, prompt=16, new=4, stride=2,
            page=8, n_way=3)
        out["cb_chunked_stall"] = _cb_stall_bench(
            qparams, cfg, slots=2, prompt=16, new=4, stride=2,
            reqs=4, page=8, chunk=8, iters=iters)
        out["cb_equal_hbm"] = _cb_equal_hbm_bench(
            qparams, cfg, dense_slots=2, paged_slots=4,
            buckets=(8, 16), mix=[(8, 4), (8, 4), (16, 4)],
            reqs=5, stride=2, page=8, iters=iters)
        # cb_fused_ticks and cb_kv_capacity ride the on_tpu branch +
        # the bench smoke (like cb_tp_serving): the tiny tier-1 path
        # already pays for the full fused K sweep and the int4
        # capacity A/B in run_serving_bench_smoke

    # fleet-scale robustness matrix (ISSUE 19) — entirely host-side
    # discrete-event simulation, so the same full-size run rides both
    # branches in well under a second
    out["cb_fleet_chaos"] = _cb_fleet_chaos_bench()
    # flight-recorder loop (ISSUE 20) rides the same host-side harness
    out["cb_obs_fleet"] = _cb_obs_fleet_bench()

    # --- train the bench model on a cyclic pattern --------------------
    # One training pays for TWO honest speculative rows: the PLD
    # (prompt-lookup) row below, and the self-draft row — which for
    # four rounds measured acceptance ~0 on random-init weights
    # (VERDICT r5 weak #3: re-confirming a known nothing).  On the
    # trained model the first draft_layers have actually learned the
    # task, so the self-draft row finally records REAL acceptance.
    from kubegpu_tpu.models.decode import pld_generate_fused
    from kubegpu_tpu.models.llama import llama_init, make_train_step
    if on_tpu:
        pld_steps, pld_pat, pld_batch, pld_seq = 120, 128, 4, 1024
    else:
        pld_steps, pld_pat, pld_batch, pld_seq = 3, 8, 2, 16
    rng = np.random.default_rng(7)
    pattern = rng.integers(2, cfg.vocab_size, pld_pat)
    data = np.tile(pattern, pld_seq * 2 // pld_pat + 2)
    tparams = llama_init(jax.random.PRNGKey(7), cfg)
    opt = optax.adamw(3e-4)
    tstate = opt.init(tparams)
    tstep = donating_jit(make_train_step(cfg, opt),
                         donate=("params", "opt_state"))
    t_train0 = time.perf_counter()
    loss = None
    for i in range(pld_steps):
        off = int(rng.integers(0, pld_pat))
        batch = np.stack([data[off + j:off + j + pld_seq]
                          for j in range(pld_batch)])
        tparams, tstate, loss = tstep(
            tparams, tstate, jnp.asarray(batch, jnp.int32))
    final_loss = float(loss)
    train_s = time.perf_counter() - t_train0
    pld_prompt = jnp.asarray(
        np.tile(pattern, spec_t // pld_pat + 1)[None, :spec_t]
        .repeat(spec_b, 0), jnp.int32)
    tq = quantize_llama(tparams)
    spec_len = spec_t + spec_steps

    # --- self-draft speculative decode, on the TRAINED model ----------
    # (the "PLD honesty treatment" VERDICT r5 next-item #7 demanded:
    # the early-exit draft is sliced from a model that has learned the
    # task, so its acceptance is a real measurement, not noise)
    dl = max(1, cfg.n_layers // 4)
    dview = draft_view(tq, dl)
    _, spec_stats = spec_generate_fused(
        tq, pld_prompt, spec_steps, cfg, dl, gamma=4, max_len=spec_len,
        kv_int8=True, dparams=dview)
    # time the RAW fused executable (tokens only): the wrapper's
    # stats fetch costs host round trips that belong to reporting,
    # not generation (r4: they dwarfed the loop itself)
    from kubegpu_tpu.models.decode import _spec_fused_fn
    spec_run = _spec_fused_fn(cfg, spec_t, spec_steps, spec_len, dl,
                              4, True)
    spec_s = _time_calls(
        lambda: spec_run(tq, dview, pld_prompt)[0], lambda o: o, iters)
    tg_s = _time_calls(
        lambda: greedy_generate(tq, pld_prompt, spec_steps, cfg,
                                max_len=spec_len, kv_int8=True),
        lambda o: o, iters)
    out["spec_decode"] = {
        "draft_layers": dl, "gamma": 4, "batch": spec_b,
        "prompt_len": spec_t, "steps": spec_steps,
        "trained_draft": True,
        "train_steps": pld_steps, "train_loss": round(final_loss, 4),
        "fused_e2e_ms": round(spec_s * 1e3, 2),
        "greedy_e2e_ms": round(tg_s * 1e3, 2),
        # honest headline: > 1.0 only when draft acceptance pays for
        # the draft+verify overhead — now measured on weights where
        # acceptance is attainable
        "speedup_vs_greedy": round(tg_s / spec_s, 3),
        "acceptance_rate": round(spec_stats["acceptance_rate"], 3),
        "iterations": spec_stats["iterations"],
    }

    # --- ENGINE-INTEGRATED speculation (ISSUE 3): the cb_spec row -----
    # Same trained weights (the training above already paid for honest
    # acceptance), but measured where production serves: inside the
    # paged ContinuousBatcher, spec-on vs spec-off on one request
    # window, at tp=1 and tp=2, with per-request bit parity asserted.
    # Prompts tile/rotate the learned pattern so generation stays
    # on-cycle and the sliced draft has something real to accept.
    if on_tpu:
        sp_prompt, sp_reqs = 512, 16
        cyc = np.tile(pattern, sp_prompt // pld_pat + 2)
        out["cb_spec"] = _cb_spec_bench(
            tq, cfg, slots=8, prompt=sp_prompt, new=64, stride=16,
            page=128, reqs=sp_reqs, iters=iters, draft_layers=dl,
            gammas=(2, 4), degrees=(1, 2),
            prompts=[cyc[i % pld_pat:][:sp_prompt]
                     for i in range(sp_reqs)])
    else:
        sp_prompt, sp_reqs = 16, 3
        cyc = np.tile(pattern, sp_prompt // pld_pat + 2)
        out["cb_spec"] = _cb_spec_bench(
            tq, cfg, slots=2, prompt=sp_prompt, new=4, stride=2,
            page=8, reqs=sp_reqs, iters=2, draft_layers=dl,
            gammas=(2,), degrees=(1, 2),
            prompts=[cyc[i % pld_pat:][:sp_prompt]
                     for i in range(sp_reqs)])

    # --- prompt-lookup (n-gram) speculative decoding ------------------
    # VERDICT r3 next-item #3: draft-model-free prompt-lookup decoding
    # on the in-bench-trained model — drafts are the tokens that
    # followed the last occurrence of the trailing n-gram, the shape
    # real serving exploits on templated/repetitive text.  Both
    # numbers measured in this window; training cost reported too.
    _, pld_stats = pld_generate_fused(
        tq, pld_prompt, spec_steps, cfg, gamma=8, ngram=3,
        max_len=spec_len, kv_int8=True)
    from kubegpu_tpu.models.decode import _pld_fused_fn
    pld_run = _pld_fused_fn(cfg, spec_t, spec_steps, spec_len, 8, 3,
                            True)
    pld_s = _time_calls(
        lambda: pld_run(tq, pld_prompt)[0], lambda o: o, iters)
    # tg_s (greedy on the trained model, same window) measured above
    # for the self-draft row — one protocol, one number, both rows
    out["spec_decode_pld"] = {
        "gamma": 8, "ngram": 3, "batch": spec_b,
        "prompt_len": spec_t, "steps": spec_steps,
        "train_steps": pld_steps, "train_s": round(train_s, 1),
        "train_loss": round(final_loss, 4),
        "fused_e2e_ms": round(pld_s * 1e3, 2),
        "greedy_e2e_ms": round(tg_s * 1e3, 2),
        "speedup_vs_greedy": round(tg_s / pld_s, 3),
        "acceptance_rate": round(pld_stats["acceptance_rate"], 3),
        "iterations": pld_stats["iterations"],
    }

    # --- PLD acceptance curve: the MIDDLE, not just the endpoints ----
    # VERDICT r4 weak #5: 2.49x at acceptance 1.0 and 0.48x at 0.0
    # bracketed but never established the production claim.  Noise
    # injected into the prompt HISTORY poisons the n-gram lookup
    # (matches propose the noisy continuation; the trained model still
    # emits the clean cycle) — the r5 chip probe mapped noise 0.05/
    # 0.1/0.2/0.5 to acceptance ~0.53/0.23/0.10/0.00 with speedups
    # 2.9/2.0/1.5/0.99x, so the curve's knee AND the break-even are
    # both measured, not extrapolated.  The last ngram tokens stay
    # clean so generation starts on-cycle.
    curve = []
    for rate in (0.05, 0.1, 0.2, 0.5):
        nrng = np.random.default_rng(int(rate * 1000) + 1)
        base_np = np.tile(pattern, spec_t // pld_pat + 1)[:spec_t]
        noisy = np.broadcast_to(base_np, (spec_b, spec_t)).copy()
        mask = nrng.random((spec_b, spec_t)) < rate
        mask[:, -3:] = False
        noisy[mask] = nrng.integers(2, cfg.vocab_size, mask.sum())
        pr = jnp.asarray(noisy, jnp.int32)
        _, st = pld_generate_fused(
            tq, pr, spec_steps, cfg, gamma=8, ngram=3,
            max_len=spec_len, kv_int8=True)
        s = _time_calls(lambda: pld_run(tq, pr)[0], lambda o: o, iters)
        curve.append({
            "noise_rate": rate,
            "acceptance_rate": round(st["acceptance_rate"], 3),
            "iterations": st["iterations"],
            "speedup_vs_greedy": round(tg_s / s, 3),
        })
    # break-even acceptance for gamma=8: interpolate where the curve
    # crosses 1.0 (the chunk forward is weight-read bound, so the
    # zero-acceptance penalty is only a few % and break-even is tiny)
    pts = sorted(curve, key=lambda p: p["acceptance_rate"])
    break_even = None
    for lo, hi in zip(pts, pts[1:]):
        a, b = lo["speedup_vs_greedy"], hi["speedup_vs_greedy"]
        if a < 1.0 <= b:
            frac = (1.0 - a) / (b - a)
            break_even = round(
                lo["acceptance_rate"] + frac
                * (hi["acceptance_rate"] - lo["acceptance_rate"]), 4)
            break
    if break_even is None and pts and \
            pts[0]["speedup_vs_greedy"] >= 1.0:
        break_even = 0.0   # never dips below greedy in measured range
    out["spec_decode_pld_curve"] = curve
    out["spec_decode_pld_break_even_acceptance"] = break_even
    return out


def run_model_bench(steps: int = 12) -> dict:
    """Flagship-model step-time/MFU on the default backend (one chip)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from kubegpu_tpu.models import LlamaConfig, llama_init
    from kubegpu_tpu.models.llama import make_train_step
    from kubegpu_tpu.parallel.sharding import donating_jit

    dev = jax.devices()[0]
    on_tpu = dev.platform.startswith(("tpu", "axon"))
    if on_tpu:
        cfg = llama_bench_config()
        batch, seq = 4, 2048
    else:
        cfg = LlamaConfig.tiny(n_layers=2, n_heads=4, n_kv_heads=2)
        batch, seq = 2, 64
    params = llama_init(jax.random.PRNGKey(0), cfg)
    opt = optax.adamw(1e-3)
    opt_state = opt.init(params)
    # donate the train state: without aliasing, XLA keeps input AND
    # output copies of params+adamw moments live across the step — at
    # this model size that alone OOMs a 16 GiB chip
    step = donating_jit(make_train_step(cfg, opt),
                        donate=("params", "opt_state"))
    tokens = jnp.asarray(
        (np.arange(batch * seq).reshape(batch, seq))
        % cfg.vocab_size, jnp.int32)

    # timed as one chained burst (params flow step-to-step, so nothing
    # can be elided) with a single host fetch at the end — see
    # _time_chained for why per-step blocking is meaningless here
    step_s, state = _time_chained(
        lambda s: step(s[0], s[1], tokens), (params, opt_state),
        iters=steps)
    params, opt_state, loss = state
    loss = _fetch_scalar(loss)
    flops = train_flops_per_step(cfg, batch, seq)
    peak = chip_peak_tflops(dev)
    mfu = flops / step_s / (peak * 1e12)
    out = {
        "device_kind": str(getattr(dev, "device_kind", dev.platform)),
        "platform": dev.platform,
        "on_tpu": on_tpu,
        "batch": batch,
        "seq": seq,
        "params_m": round(sum(
            x.size for x in jax.tree.leaves(params)) / 1e6, 1),
        "step_ms": round(step_s * 1e3, 2),
        "tokens_per_s": round(batch * seq / step_s, 1),
        "model_tflops_per_s": round(flops / step_s / 1e12, 2),
        "peak_tflops": peak,
        "mfu": round(mfu, 4),
        "loss": round(loss, 4),
        # same shape as the train step times, so the speedup and the
        # MFU figure in BASELINE.md describe one configuration
        "attention": _attention_bench(
            batch, cfg.n_heads, seq, cfg.head_dim, cfg.jdtype, on_tpu),
        # serving-side numbers on the just-trained params: prefill
        # latency + scanned KV-cache greedy decode throughput
        # (KUBETPU_BENCH_SERVING=0 skips — ~4 of bench.py's ~6.5 min)
        "serving": (_serving_bench(cfg, params, on_tpu)
                    if os.environ.get("KUBETPU_BENCH_SERVING", "1") != "0"
                    else None),
        # every remaining BASELINE.md hardware row, reproducibly
        # (KUBETPU_BENCH_FAMILIES=0 skips)
        "families": (_families_bench(cfg, params, on_tpu)
                     if os.environ.get(
                         "KUBETPU_BENCH_FAMILIES", "1") != "0"
                     else None),
    }
    return out


def _cb_hbm_bench(params, cfg, slots: int, prompt: int, new: int,
                  stride: int, page: int, reqs: int) -> dict:
    """Donation-on/off HBM A/B in one window (ISSUE 10): the same
    request mix through two otherwise-identical paged engines, one
    with buffer donation (the default), one without.  Asserts nothing
    itself — reports what the tier-1 smoke asserts: bit-exact tokens,
    the steady-state live-pool byte ratio (donation halves it: the
    non-donating engine keeps input AND output pool buffers live
    across each tick), compile-time ``input_output_alias`` coverage
    for every mutated pool/cache/mirror argument of every executable
    on BOTH audit engines (bf16 spec + int8-KV — the int8 check is
    what proves QTensor scales alias alongside values), and the
    capacity headroom: the larger ``max_pages``/``n_slots`` that now
    fits the byte budget the non-donating engine needed, demonstrated
    by actually running the bigger engine inside that budget."""
    import jax
    import numpy as np

    from kubegpu_tpu.analysis.jaxpr_audit import (
        build_audit_engine,
        donation_report,
    )
    from kubegpu_tpu.models.serve import ContinuousBatcher

    cb_p = np.arange(prompt) % cfg.vocab_size

    def run(donate: bool, n_slots=slots, total_pages=None,
            n_reqs=reqs):
        eng = ContinuousBatcher(
            params, cfg, n_slots=n_slots, stride=stride,
            prompt_buckets=(prompt,), paged=True, page_size=page,
            total_pages=total_pages, donate=donate)
        for i in range(n_reqs):
            eng.submit((cb_p + i) % cfg.vocab_size, new)
        done = eng.drain()
        toks = {r.rid: list(r.tokens) for r in done}
        return toks, eng

    on_toks, on_eng = run(True)
    off_toks, off_eng = run(False)
    pool_bytes = sum(h.nbytes for h in jax.tree.leaves(on_eng.pool))
    ratio = off_eng.hbm_peak_bytes / max(on_eng.hbm_peak_bytes, 1)

    # compile-time aliasing proof — per executable, per engine flavor
    aliases = {}
    for label, kw in (("bf16", dict(spec=True)),
                      ("int8", dict(kv_int8=True)),
                      ("int4", dict(kv_bits=4))):
        rep = donation_report(build_audit_engine(**kw))
        aliases[label] = {
            name: {"aliased_params": r["aliased_params"],
                   "covered": r["covered"],
                   "args": {a: f"{d['aliased']}/{d['leaves']}"
                            for a, d in r["args"].items()}}
            for name, r in rep.items()}

    # capacity-headroom sweep: the byte budget is what the NON-donating
    # engine peaked at for this shape; donation frees the input-copy
    # half, so ~ratio× the pages (and another slot's mirrors) fit back
    # in.  Run the bigger engine for real — a projection alone would
    # hide a pool-layout bug that breaks at the larger shape.
    budget = off_eng.hbm_peak_bytes
    big_pages = int(on_eng.total_pages * ratio)
    big_toks, big_eng = run(True, n_slots=slots + 1,
                            total_pages=big_pages, n_reqs=reqs + 1)
    return {
        "bit_exact": on_toks == off_toks,
        "tokens": sum(len(t) for t in on_toks.values()),
        "pool_bytes": pool_bytes,
        "donation_on": {"live_bytes": on_eng.hbm_pool_bytes,
                        "peak_bytes": on_eng.hbm_peak_bytes,
                        "samples": on_eng.hbm.samples},
        "donation_off": {"live_bytes": off_eng.hbm_pool_bytes,
                         "peak_bytes": off_eng.hbm_peak_bytes},
        "pool_bytes_ratio": round(ratio, 3),
        "input_output_aliases": aliases,
        "aliases_covered": all(
            r["covered"] and r["aliased_params"] > 0
            for rep_ in aliases.values() for r in rep_.values()),
        "capacity_headroom": {
            "byte_budget": budget,
            "total_pages_no_donation": on_eng.total_pages,
            "total_pages_donation": big_pages,
            "n_slots_no_donation": slots,
            "n_slots_donation": slots + 1,
            "bigger_engine_peak_bytes": big_eng.hbm_peak_bytes,
            "fits_budget": big_eng.hbm_peak_bytes <= budget,
            "tokens": sum(len(t) for t in big_toks.values()),
        },
    }


def _cb_kv_capacity_bench(params, cfg, slots: int, prompt: int,
                          new: int, stride: int, page: int,
                          reqs: int) -> dict:
    """Spend the reclaimed HBM twice (ISSUE 15): the grouped-int4 KV
    pool must fit >= 1.5x the concurrent slots inside the byte budget
    the DONATION-OFF int8 engine needed for the same request mix, at a
    bounded, MEASURED quality delta — plus the attention-aware page
    eviction legs (window + mass) with their own measured deltas.

    Method: one bf16 reference run pins the greedy token streams;
    the donation-off int8 run at ``slots`` slots sets ``byte_budget``
    (its lifetime HBM peak); the int4 engine then runs >= 1.5x the
    slots (and proportionally more requests) and must PEAK inside that
    budget while completing every request.  Quality deltas are
    greedy-token disagreement vs the bf16 reference per request —
    reported, pushed through ``note_kv_quality`` (so the
    ``serve_kv_quality_delta`` gauge carries the measured number, not
    a guess), and gated against ``quality_bound``.  The bound is loose
    (0.8) because the tiny random-weight smoke model has near-tied
    logits everywhere, so 4-bit noise cascades at the first flipped
    token; directed pool-byte checks (tests/test_page_pool.py) pin the
    actual dequantization error to int4 tolerance.

    ``prompt`` must span >= 4 pages: every leg (capacity AND eviction)
    shares the same request mix so the ONE bf16 reference prices them
    all, and the eviction rails refuse to evict below 3 live prompt
    pages."""
    import numpy as np

    from kubegpu_tpu.models.serve import ContinuousBatcher

    def run(pr_len, n_slots, n_reqs, n_new, **kw):
        eng = ContinuousBatcher(
            params, cfg, n_slots=n_slots, stride=stride,
            prompt_buckets=(pr_len,), paged=True, page_size=page, **kw)
        base = np.arange(pr_len) % cfg.vocab_size
        for i in range(n_reqs):
            eng.submit((base + i) % cfg.vocab_size, n_new)
        done = eng.drain()
        eng.check_page_invariants()
        return {r.rid: list(r.tokens) for r in done}, eng

    def delta_vs(toks, ref):
        # greedy-token disagreement on the rid set BOTH runs served
        # (rids are submit-ordered, so rid i is the same prompt in
        # every leg); 0.0 == bit-exact streams
        pairs = [(t, r) for rid in ref for t, r in
                 zip(toks[rid], ref[rid])]
        return 1.0 - sum(t == r for t, r in pairs) / max(len(pairs), 1)

    ref_toks, _ = run(prompt, slots, reqs, new)
    off8_toks, off8_eng = run(prompt, slots, reqs, new,
                              kv_int8=True, donate=False)
    budget = off8_eng.hbm_peak_bytes
    # the acceptance floor is 1.5x; the packed pool (half the int8
    # bytes) plus donation (no second transient copy) delivers 2x
    # comfortably, so claim it and let fits_budget prove it
    slots_hi = slots * 2
    hi_reqs = reqs * slots_hi // slots
    hi_toks, hi_eng = run(prompt, slots_hi, hi_reqs, new, kv_bits=4)
    delta4 = delta_vs(hi_toks, ref_toks)
    hi_eng.note_kv_quality(delta4)
    fits = hi_eng.hbm_peak_bytes <= budget

    # eviction legs: same shapes, same bf16 reference
    ev = {}
    for policy, param in (("window", 2.0 * page), ("mass", 0.25)):
        toks, eng = run(prompt, slots, reqs, new,
                        kv_bits=4, evict_policy=policy,
                        evict_param=param)
        d = delta_vs(toks, ref_toks)
        eng.note_kv_quality(d)
        ev[policy] = {
            "evict_param": param,
            "pages_evicted": eng.pages_evicted,
            "quality_delta": round(d, 4),
            "completed": len(toks),
            "tokens": sum(len(t) for t in toks.values()),
        }

    return {
        "protocol": "equal_budget_capacity_ab",
        "byte_budget": budget,
        "budget_engine": {
            "kv_bits": 8, "donate": False, "n_slots": slots,
            "requests": reqs,
            "peak_bytes": off8_eng.hbm_peak_bytes,
            "pool_bytes": off8_eng.hbm_pool_bytes,
            "quality_delta": round(delta_vs(off8_toks, ref_toks), 4),
        },
        "int4_engine": {
            "kv_bits": 4, "donate": True, "n_slots": slots_hi,
            "kv_group": hi_eng.kv_group, "requests": hi_reqs,
            "peak_bytes": hi_eng.hbm_peak_bytes,
            "pool_bytes": hi_eng.hbm_pool_bytes,
            "completed": len(hi_toks),
            "tokens": sum(len(t) for t in hi_toks.values()),
        },
        "slots_ratio": round(slots_hi / slots, 3),
        "fits_budget": fits,
        "capacity_ok": fits and slots_hi / slots >= 1.5,
        "quality_delta_int4": round(delta4, 4),
        "quality_bound": 0.8,
        "quality_ok": delta4 <= 0.8,
        "eviction": ev,
    }


def _cb_disagg_bench(params, cfg, slots: int, prompt: int, new: int,
                     stride: int, page: int, chunk: int,
                     reqs: int) -> dict:
    """Disaggregated prefill/decode A/B (ISSUE 11 tentpole): the SAME
    request window through a symmetric ``DataParallelServePool(dp=2)``
    and a ``DisaggServePool(prefill=1, decode=1)`` at EQUAL chip count
    (2 chips each), chunked prefill + prefix cache on both.  The row's
    claim is the tail contract the issue gates on: TTFT p99 AND decode-
    stall p99 both drop on the role-split pool (an arriving prompt
    never queues behind a replica's decode residents; a decoding slot
    never shares its engine with a prefill chunk), with BIT-EXACT
    greedy tokens — migrated page chains are exact pool bytes, so the
    decode replica continues from bit-identical state.  Wall clocks
    here are raw ("weather"); the tails come from each leg's own
    ``MetricsRegistry`` histograms so bench and engine can never
    disagree on method."""
    import jax
    import numpy as np

    from kubegpu_tpu.models.serve import (
        DataParallelServePool,
        DisaggServePool,
    )
    from kubegpu_tpu.obs.metrics import MetricsRegistry, percentiles

    if len(jax.devices()) < 2:
        return {"skipped": "needs 2 devices"}

    cb_len = prompt + new + stride + 8
    base = np.arange(prompt) % cfg.vocab_size
    stream = [((base + 3 * i) % cfg.vocab_size, new)
              for i in range(reqs)]
    pool_kw = dict(n_slots=slots, max_len=cb_len, stride=stride,
                   prompt_buckets=(prompt,), paged=True,
                   page_size=page, prefix_cache=True,
                   chunked_prefill=True, prefill_chunk=chunk)
    TAILS = {"ttft_p99_ms": "serve_ttft_ms",
             "decode_stall_p99_ms": "serve_decode_stall_ms",
             "queue_wait_p99_ms": "serve_queue_wait_ms",
             # deterministic twins: engine service rounds / work units
             # instead of host wall — a pure function of the admission
             # schedule, so the CPU smoke can gate on them while the
             # ms tails above stay the hardware numbers
             "ttft_p99_ticks": "serve_ttft_ticks",
             "queue_wait_p99_ticks": "serve_queue_wait_ticks",
             "decode_stall_work_p99": "serve_decode_stall_work"}

    def run(make):
        reg = MetricsRegistry()
        pool = make(reg)
        pool.warmup()   # compile outside the timed window
        t0 = time.perf_counter()
        rids = [pool.submit(p, n) for p, n in stream]
        seen: dict[int, list[int] | None] = {}
        for r in pool.drain():
            seen[r.rid] = (None if r.error is not None
                           else list(r.tokens))
        wall = time.perf_counter() - t0
        hists = reg.snapshot()["histograms"]
        tails = {k: (round(hists[m]["p99"], 3) if m in hists
                     else None)
                 for k, m in TAILS.items()}
        return pool, [seen.get(r) for r in rids], wall, tails

    sym, sym_toks, sym_wall, sym_tails = run(
        lambda reg: DataParallelServePool(
            params, cfg, dp=2, tp=1, metrics=reg, **pool_kw))
    dis, dis_toks, dis_wall, dis_tails = run(
        lambda reg: DisaggServePool(
            params, cfg, prefill=1, decode=1, tp=1, metrics=reg,
            **pool_kw))
    total = sum(len(t) for t in sym_toks if t)

    def reduction(key):
        a, b = sym_tails[key], dis_tails[key]
        if not a or not b:
            return None
        return round(a / b, 3)

    return {
        "protocol": "equal_chip_ab",
        "chips_per_leg": 2, "requests": reqs, "new_tokens": new,
        "n_slots": slots, "prefill_chunk": chunk,
        "bit_exact": sym_toks == dis_toks,
        "tokens": total,
        "symmetric": {
            "shape": "dp=2 tp=1", **sym_tails,
            "wall_ms_raw_weather": round(sym_wall * 1e3, 1),
        },
        "disagg": {
            "shape": "prefill=1 decode=1 tp=1", **dis_tails,
            "wall_ms_raw_weather": round(dis_wall * 1e3, 1),
            "migrations": dis.migrations,
            "migrated_pages": dis.migrated_pages,
            "migration_ms": {k: round(v, 3) for k, v in
                             percentiles(dis.migration_ms).items()},
        },
        "ttft_p99_reduction_x": reduction("ttft_p99_ms"),
        "stall_p99_reduction_x": reduction("decode_stall_p99_ms"),
        "queue_wait_p99_reduction_x": reduction("queue_wait_p99_ms"),
        # deterministic (schedule-pure) reductions — what tier-1 and
        # ``make disagg-smoke`` assert on; the ms reductions above are
        # the hardware claim and read as weather on a loaded CPU host
        "ttft_ticks_reduction_x": reduction("ttft_p99_ticks"),
        "queue_wait_ticks_reduction_x": reduction(
            "queue_wait_p99_ticks"),
    }


def _cb_slo_goodput_bench(params, cfg) -> dict:
    """SLO-guarded overload A/B (ISSUE 13 tentpole): the SAME seeded
    open-loop overload trace (bursty Poisson arrivals, long-tail
    lengths, shared prefixes, 3 priority tiers) through one engine
    twice at equal chips — once with every request submitted FIFO at
    tier 0 (shedding is the only overload control), once with tiered
    admission (strict across tiers, EDF within) + low-priority decode
    preemption.  The gate is the headline degradation story: the
    tiered leg's TOP-TIER goodput-under-SLO (tokens/tick from
    requests that met their tier's TTFT + per-token tick SLOs) must
    be >= 1.3x the FIFO leg's, with zero lost/duplicated requests
    and every completed request BIT-EXACT against an unloaded
    reference run — preempt/park/resume is token-identical by the
    greedy-replay construction.  Tick-denominated numbers gate
    (deterministic twins, PR 9); wall clocks ride along as weather."""
    from kubegpu_tpu.loadgen import (
        LoadSpec,
        TierSpec,
        run_load,
        synth_trace,
    )
    from kubegpu_tpu.models.serve import ContinuousBatcher
    from kubegpu_tpu.obs.metrics import MetricsRegistry

    TIERS = (TierSpec("gold", ttft_slo_ticks=8, token_slo_ticks=4.0,
                      share=0.3),
             TierSpec("std", ttft_slo_ticks=30, token_slo_ticks=8.0,
                      share=0.4),
             TierSpec("batch", ttft_slo_ticks=10 ** 6,
                      token_slo_ticks=10 ** 6, share=0.3))
    spec = LoadSpec(seed=7, n_requests=36, mean_iat_ticks=0.9,
                    burst=True, prompt_len_max=8, out_len_min=2,
                    out_len_max=10, prefix_share=0.25, prefix_len=4,
                    vocab=min(48, cfg.vocab_size), tiers=TIERS)
    trace = synth_trace(spec)
    TAILS = {"ttft_p99_ms": "serve_ttft_ms",
             "queue_wait_p99_ms": "serve_queue_wait_ms",
             "ttft_p99_ticks": "serve_ttft_ticks",
             "queue_wait_p99_ticks": "serve_queue_wait_ticks"}
    eng_kw = dict(n_slots=2, stride=2, prompt_buckets=(8,),
                  paged=True, page_size=8, total_pages=8,
                  prefix_cache=True)

    def leg(tiered):
        reg = MetricsRegistry()
        eng = ContinuousBatcher(params, cfg, metrics=reg, **eng_kw)
        eng.warmup()   # compile outside the measured window
        rep = run_load(eng, trace, TIERS, tiered=tiered, metrics=reg)
        hists = reg.snapshot()["histograms"]
        tails = {k: (round(hists[m]["p99"], 3) if m in hists
                     else None)
                 for k, m in TAILS.items()}
        return eng, rep, tails

    fifo_eng, fifo, fifo_tails = leg(tiered=False)
    tier_eng, tiered, tier_tails = leg(tiered=True)

    # unloaded reference: every unique (prompt, budget) alone on a
    # fresh engine — the bit-exact-survivor contract's ground truth
    ref_eng = ContinuousBatcher(params, cfg, **eng_kw)
    ref: dict = {}
    for item in trace:
        key = (item["prompt"].tobytes(), item["max_new"])
        if key in ref:
            continue
        rid = ref_eng.submit(item["prompt"], item["max_new"])
        ref[key] = {r.rid: list(r.tokens)
                    for r in ref_eng.drain()}[rid]
    bit_exact = all(
        rec["tokens"] == ref[(rec["prompt"].tobytes(),
                              rec["max_new"])]
        for rep_ in (fifo, tiered) for rec in rep_.records
        if rec["completed"])

    def leg_dict(rep, eng, tails):
        return {
            "goodput_tokens_per_tick":
                round(rep.goodput_tokens_per_tick, 4),
            "goodput_tokens_per_s_weather":
                round(rep.goodput_tokens_per_s, 1),
            "slo_attainment": round(rep.slo_attainment, 4),
            "top_tier": {
                "attainment": rep.per_tier[0]["attainment"],
                "goodput_tokens": rep.per_tier[0]["goodput_tokens"],
            },
            "per_tier_attainment": [rep.per_tier[k]["attainment"]
                                    for k in range(len(TIERS))],
            "ticks": rep.ticks,
            "completed": rep.completed, "failed": rep.failed,
            "preempted": eng.requests_preempted,
            "resumed": eng.requests_resumed,
            "deadline_misses": eng.deadline_misses,
            "shed_by_reason": dict(eng.shed_by_reason),
            **tails,
            "wall_ms_raw_weather": round(rep.wall_s * 1e3, 1),
        }

    fifo_top = fifo.per_tier[0]["goodput_tokens"] / max(fifo.ticks, 1)
    tier_top = tiered.per_tier[0]["goodput_tokens"] \
        / max(tiered.ticks, 1)
    return {
        "protocol": "same_trace_ab",
        "requests": len(trace),
        "tiers": [{"name": t.name,
                   "ttft_slo_ticks": t.ttft_slo_ticks,
                   "token_slo_ticks": t.token_slo_ticks}
                  for t in TIERS],
        "fifo": leg_dict(fifo, fifo_eng, fifo_tails),
        "tiered": leg_dict(tiered, tier_eng, tier_tails),
        # deterministic (tick-denominated) gate: tiered admission +
        # preemption must buy the top tier >= 1.3x goodput-under-SLO
        "top_tier_goodput_ratio_x":
            round(tier_top / fifo_top, 3) if fifo_top else None,
        "bit_exact": bit_exact,
        "lost": fifo.lost + tiered.lost,
        "duplicated": fifo.duplicated + tiered.duplicated,
    }


def _cb_prefix_affinity_bench(params, cfg) -> dict:
    """Prefix-affinity routing A/B (ISSUE 14 tentpole, routing half):
    the SAME seeded bursty shared-prefix trace through a
    ``DataParallelServePool(dp=2)`` twice at EQUAL chips — once with
    ``routing="affinity"`` (each replica's chain-hash digest scores
    placement: resident pages of this prompt's chain minus the
    least-loaded penalty), once with pure least-loaded.  Affinity
    keeps each shared prefix on ONE replica, so its requests alias
    the registry pages instead of re-prefilling the chain on whichever
    replica happened to be emptiest — fewer prefill chunks before the
    first token AND fewer pages claimed per admit under a tight pool.
    The gate is tick-pure: the affinity leg's TOP-TIER
    goodput-under-SLO must be >= 1.3x the least-loaded leg's, with
    BIT-EXACT tokens against an unloaded reference (routing never
    touches a device buffer — the digest is host arithmetic riding
    the metric-echo path) and zero lost/duplicated requests.  Wall
    clocks ride along as weather."""
    import jax

    from kubegpu_tpu.loadgen import (
        LoadSpec,
        TierSpec,
        run_load,
        synth_trace,
    )
    from kubegpu_tpu.models.serve import (
        ContinuousBatcher,
        DataParallelServePool,
    )
    from kubegpu_tpu.obs.metrics import MetricsRegistry

    if len(jax.devices()) < 2:
        return {"skipped": "needs 2 devices"}

    TIERS = (TierSpec("gold", ttft_slo_ticks=8, token_slo_ticks=4.0,
                      share=0.4),
             TierSpec("std", ttft_slo_ticks=40, token_slo_ticks=8.0,
                      share=0.3),
             TierSpec("batch", ttft_slo_ticks=10 ** 6,
                      token_slo_ticks=10 ** 6, share=0.3))
    # long prompts dominated by 3-page (24-token) shared prefixes and
    # SHORT decodes — prefill is the workload, so a chain hit (admit
    # at chunk 3 of 4 instead of chunk 0, alias 3 pages instead of
    # allocating them) is most of a request's cost.  THREE prefixes
    # against a pool that holds at most two chains per replica is the
    # interference the router exists for: least-loaded interleaves all
    # three chains onto both replicas and the registries thrash, while
    # affinity parks each chain on one home replica where residents
    # keep re-referencing it.  (One affinity page only TIES against an
    # idle replica — the load penalty of one queued request cancels it
    # — so short-prefix traffic would show nothing.)
    spec = LoadSpec(seed=7, n_requests=48, mean_iat_ticks=0.5,
                    burst=True, prompt_len_mean=3.4,
                    prompt_len_sigma=0.1, prompt_len_max=32,
                    out_len_min=2, out_len_max=6, prefix_share=0.95,
                    n_shared_prefixes=3, prefix_len=24,
                    vocab=min(48, cfg.vocab_size), tiers=TIERS)
    trace = synth_trace(spec)
    pool_kw = dict(n_slots=2, stride=2, prompt_buckets=(32,),
                   paged=True, page_size=8, total_pages=11,
                   prefix_cache=True, chunked_prefill=True,
                   prefill_chunk=8)
    TAILS = {"ttft_p99_ms": "serve_ttft_ms",
             "queue_wait_p99_ms": "serve_queue_wait_ms",
             "ttft_p99_ticks": "serve_ttft_ticks",
             "queue_wait_p99_ticks": "serve_queue_wait_ticks"}

    def leg(routing):
        reg = MetricsRegistry()
        pool = DataParallelServePool(params, cfg, dp=2, tp=1,
                                     metrics=reg, routing=routing,
                                     **pool_kw)
        pool.warmup()   # compile outside the measured window
        rep = run_load(pool, trace, TIERS, metrics=reg)
        hists = reg.snapshot()["histograms"]
        tails = {k: (round(hists[m]["p99"], 3) if m in hists
                     else None)
                 for k, m in TAILS.items()}
        return pool, rep, tails

    ll_pool, ll, ll_tails = leg("least_loaded")
    af_pool, aff, af_tails = leg("affinity")

    # unloaded reference: every unique (prompt, budget) alone on a
    # fresh engine — placement must never change a token
    ref_eng = ContinuousBatcher(params, cfg, **pool_kw)
    ref: dict = {}
    for item in trace:
        key = (item["prompt"].tobytes(), item["max_new"])
        if key in ref:
            continue
        rid = ref_eng.submit(item["prompt"], item["max_new"])
        ref[key] = {r.rid: list(r.tokens)
                    for r in ref_eng.drain()}[rid]
    bit_exact = all(
        rec["tokens"] == ref[(rec["prompt"].tobytes(),
                              rec["max_new"])]
        for rep_ in (ll, aff) for rec in rep_.records
        if rec["completed"])

    def leg_dict(pool, rep, tails):
        return {
            "goodput_tokens_per_tick":
                round(rep.goodput_tokens_per_tick, 4),
            "slo_attainment": round(rep.slo_attainment, 4),
            "top_tier": {
                "attainment": rep.per_tier[0]["attainment"],
                "goodput_tokens": rep.per_tier[0]["goodput_tokens"],
            },
            "per_tier_attainment": [rep.per_tier[k]["attainment"]
                                    for k in range(len(TIERS))],
            "ticks": rep.ticks,
            "completed": rep.completed, "failed": rep.failed,
            "affinity_hits": pool.routing_affinity_hits,
            "affinity_hit_rate":
                round(pool.routing_affinity_hit_rate, 4),
            **tails,
            "wall_ms_raw_weather": round(rep.wall_s * 1e3, 1),
        }

    ll_top = ll.per_tier[0]["goodput_tokens"] / max(ll.ticks, 1)
    af_top = aff.per_tier[0]["goodput_tokens"] / max(aff.ticks, 1)
    return {
        "protocol": "same_trace_equal_chip_ab",
        "chips_per_leg": 2,
        "requests": len(trace),
        "shared_prefix_pages": spec.prefix_len // 8,
        "least_loaded": leg_dict(ll_pool, ll, ll_tails),
        "affinity": leg_dict(af_pool, aff, af_tails),
        # deterministic (tick-denominated) gate: chain-aware placement
        # must buy the top tier >= 1.3x goodput-under-SLO at equal chips
        "top_tier_goodput_ratio_x":
            round(af_top / ll_top, 3) if ll_top else None,
        "routing_affinity_hit_rate":
            round(af_pool.routing_affinity_hit_rate, 4),
        "bit_exact": bit_exact,
        "lost": ll.lost + aff.lost,
        "duplicated": ll.duplicated + aff.duplicated,
    }


def _cb_autoscale_bench(params, cfg) -> dict:
    """SLO-driven autoscaling through the control plane (ISSUE 14
    tentpole, scaling half): one seeded burst-then-trickle trace
    drives a ``DataParallelServePool`` whose ``run_load`` controller
    is a :class:`ServingAutoscaler` bound to a live ``SimCluster``.
    The burst pushes queue wait over the watermark → the policy holds,
    then scales UP through the extender gang path
    (``spawn_serving_gang`` → ``add_replica(gang=...)``); the trickle
    tail calms the signals → the policy scales DOWN
    (``retire_replica`` → drain via the bit-exact replay parking →
    ``evict_gang(requeue=False)``, whose watch-delivered death the
    pool sees as already-drained).  Gates: at least one up AND one
    down event, replicas max > min, exactly-once completion (zero
    lost/duplicated), BIT-EXACT tokens vs an unloaded reference, and
    the compile census unchanged (asserted by the census leg — the
    whole loop is host-side)."""
    import jax

    from kubegpu_tpu.cluster import SimCluster
    from kubegpu_tpu.loadgen import (
        LoadSpec,
        TierSpec,
        run_load,
        synth_trace,
    )
    from kubegpu_tpu.models.serve import (
        ContinuousBatcher,
        DataParallelServePool,
    )
    from kubegpu_tpu.obs.metrics import MetricsRegistry
    from kubegpu_tpu.scheduler.serve import (
        AutoscaleConfig,
        AutoscalePolicy,
        ServingAutoscaler,
    )

    if len(jax.devices()) < 2:
        return {"skipped": "needs 2 devices"}

    TIERS = (TierSpec("std", ttft_slo_ticks=20,
                      token_slo_ticks=8.0),)
    vocab = min(48, cfg.vocab_size)
    # burst head (tight arrivals pile the queue) + trickle tail (light
    # traffic keeps flowing while the pool calms back down, so the
    # scale-down drain happens mid-traffic, not on an idle pool).  The
    # tail shares ONE 1-page prefix: affinity homes its chain on the
    # scaled-up replica — the emptiest when the first tail request
    # lands — so the highest-index victim the autoscaler retires still
    # holds trickle residents, and the drain's replay parking is
    # exercised for real, not vacuously on an empty engine.
    head = synth_trace(LoadSpec(
        seed=5, n_requests=20, mean_iat_ticks=0.4, burst=True,
        prompt_len_max=8, out_len_min=2, out_len_max=8, vocab=vocab,
        tiers=TIERS))
    tail = synth_trace(LoadSpec(
        seed=6, n_requests=12, mean_iat_ticks=3.0,
        prompt_len_mean=2.4, prompt_len_sigma=0.2, prompt_len_max=16,
        prefix_share=0.95, n_shared_prefixes=1, prefix_len=8,
        out_len_min=4, out_len_max=8, vocab=vocab, tiers=TIERS))
    shift = max(e["arrival_tick"] for e in head) + 4
    for e in tail:
        e["arrival_tick"] += shift
    trace = head + tail
    eng_kw = dict(n_slots=2, stride=2, prompt_buckets=(8, 16),
                  paged=True, page_size=8, total_pages=8,
                  prefix_cache=True)

    reg = MetricsRegistry()
    cl = SimCluster(["v5e-16"])
    try:
        # the base replica's gang goes through the SAME extender path
        # the autoscaler uses, so the health watch covers both alike
        cl.scheduler.spawn_serving_gang("serve-base", chips=1)
        pool = DataParallelServePool(
            params, cfg, dp=1, tp=1, devices=jax.devices(),
            metrics=reg, **eng_kw)
        pool.warmup()
        pool.bind_replica_gang(0, "serve-base")
        pool.watch_health(cl.api)
        policy = AutoscalePolicy(AutoscaleConfig(
            min_replicas=1, max_replicas=2,
            queue_wait_high_ticks=3.0, attainment_low=0.5,
            hold_ticks=2, idle_ticks=6, cooldown_ticks=8))
        scaler = ServingAutoscaler(pool, policy,
                                   scheduler=cl.scheduler,
                                   cluster=cl, chips_per_replica=1)
        rep = run_load(pool, trace, TIERS, metrics=reg,
                       controller=scaler)
    finally:
        cl.close()

    # unloaded reference: placement AND scaling must never change a
    # token — drained residents replay bit-exactly on survivors
    ref_eng = ContinuousBatcher(params, cfg, **eng_kw)
    ref: dict = {}
    for item in trace:
        key = (item["prompt"].tobytes(), item["max_new"])
        if key in ref:
            continue
        rid = ref_eng.submit(item["prompt"], item["max_new"])
        ref[key] = {r.rid: list(r.tokens)
                    for r in ref_eng.drain()}[rid]
    bit_exact = all(
        rec["tokens"] == ref[(rec["prompt"].tobytes(),
                              rec["max_new"])]
        for rec in rep.records if rec["completed"])

    return {
        "protocol": "closed_loop_autoscale",
        "requests": len(trace),
        "ticks": rep.ticks,
        "completed": rep.completed, "failed": rep.failed,
        "scale_ups": scaler.scale_ups,
        "scale_downs": scaler.scale_downs,
        "events": [[t, d, r] for t, d, r in scaler.events],
        "decisions": [[t, a] for t, a in policy.decisions],
        "replicas_min": pool.replicas_active_min,
        "replicas_max": pool.replicas_active_max,
        "autoscale_events": pool.autoscale_events,
        "drains": pool.drains,
        "drain_replays": pool.drain_replays,
        "failovers": pool.failovers,
        "exactly_once": rep.lost == 0 and rep.duplicated == 0,
        "lost": rep.lost, "duplicated": rep.duplicated,
        "bit_exact": bit_exact,
        "goodput_tokens_per_tick":
            round(rep.goodput_tokens_per_tick, 4),
        "slo_attainment": round(rep.slo_attainment, 4),
        "wall_ms_raw_weather": round(rep.wall_s * 1e3, 1),
    }


def _cb_fleet_chaos_bench(replicas: int = 64, domains: int = 4,
                          requests: int = 192) -> dict:
    """Fleet-scale robustness matrix (ISSUE 19 tentpole): ONE seeded
    diurnal/flash-crowd trace drives the REAL pool code over
    ``replicas`` bench-calibrated simulated engines, four times —

    - **twin**: uninterrupted reference run;
    - **domain_kill**: a whole failure domain (≥ 25% of the fleet)
      dies in ONE tick while the health-watch channel duplicates and
      delays its eviction deliveries;
    - **upgrade**: a rolling drain-wave across EVERY domain under a
      surge budget that must hold the capacity floor;
    - **crash_recovery**: the control plane is killed mid-trace and
      rebuilt from its append-only journal, re-driving every in-flight
      request exactly-once.

    Gates (asserted by tier-1 via this row): zero lost, zero
    duplicated, tier ordering never inverted, per-request outcomes of
    every scenario leg IDENTICAL to the twin, and the whole matrix
    deterministic by seed."""
    import time

    from kubegpu_tpu.fleet import (
        ControlPlaneJournal,
        FleetConfig,
        ReplicaCosts,
        compare_outcomes,
        run_fleet,
    )
    from kubegpu_tpu.loadgen import LoadSpec, TierSpec, synth_trace
    from kubegpu_tpu.obs.chaos import (
        DOMAIN_KILL,
        WATCH_DELAY,
        WATCH_DUP,
        DomainChaosEvent,
        DomainChaosInjector,
    )
    from kubegpu_tpu.obs.metrics import MetricsRegistry

    TIERS = (TierSpec("gold", ttft_slo_ticks=40,
                      token_slo_ticks=40.0, share=0.2),
             TierSpec("silver", ttft_slo_ticks=80,
                      token_slo_ticks=80.0, share=0.3),
             TierSpec("bronze", ttft_slo_ticks=10**6,
                      token_slo_ticks=1e6, share=0.5))
    trace = synth_trace(LoadSpec(
        seed=1907, n_requests=requests, mean_iat_ticks=0.25,
        tiers=TIERS, diurnal=True, flash_at=(10.0,),
        flash_rate_x=4.0, flash_len_ticks=8.0))
    costs = ReplicaCosts.from_bench()
    cfg = FleetConfig(costs=costs)
    reg = MetricsRegistry()

    def _leg(**kw):
        return run_fleet(trace, TIERS, cfg=cfg, replicas=replicas,
                         domains=domains, metrics=reg, **kw)

    def _weather():
        # watch-channel weather around the kill: each eviction
        # delivery arrives 3× and 4 ticks late — recovery must
        # tolerate both without double-failover
        return DomainChaosInjector(events=[
            DomainChaosEvent(tick=18, kind=WATCH_DUP, dup=3,
                             duration_ticks=6),
            DomainChaosEvent(tick=18, kind=WATCH_DELAY,
                             delay_ticks=4, duration_ticks=6),
            DomainChaosEvent(tick=20, kind=DOMAIN_KILL,
                             domain="rack1"),
        ])

    t0 = time.perf_counter()
    twin = _leg()
    kill = _leg(chaos=_weather())
    kill2 = _leg(chaos=_weather())       # seed-determinism re-run
    # floor HALF a domain above the post-kill worst case: the first
    # drain batch lands exactly on the floor, so the wave only
    # completes if the controller backfills mid-wave
    floor = replicas - (replicas // domains) // 2
    upg = _leg(upgrade=True, upgrade_floor=floor, upgrade_surge=4,
               upgrade_start=8)
    crash = _leg(journal=ControlPlaneJournal(), crash_at=25)
    wall_ms = (time.perf_counter() - t0) * 1e3

    legs = {"domain_kill": kill, "upgrade": upg,
            "crash_recovery": crash}
    cmp_ = {name: compare_outcomes(twin.load, r.load)
            for name, r in legs.items()}
    exactly_once = all(r.load.lost == 0 and r.load.duplicated == 0
                       for r in [twin, *legs.values()])
    identical = all(c["identical"] for c in cmp_.values())
    recovered = (crash.recoveries == 1 and crash.load.lost == 0
                 and crash.load.duplicated == 0
                 and cmp_["crash_recovery"]["identical"])

    def _row(r, c=None):
        out = {"completed": r.load.completed, "lost": r.load.lost,
               "duplicated": r.load.duplicated, "ticks": r.load.ticks,
               "tier_inversions": r.tier_inversions,
               "failovers": r.failovers, "min_alive": r.min_alive,
               "sim_ms": round(r.sim_ms, 1)}
        if c is not None:
            out["outcomes_identical"] = c["identical"]
        return out

    return {
        "protocol": "fleet_discrete_event",
        "fleet_replicas": replicas,
        "domains": domains,
        "requests": len(trace),
        "costs_ms": {"block": round(costs.block_ms, 4),
                     "prefill_per_token":
                         round(costs.prefill_ms_per_token, 5),
                     "migration": round(costs.migration_ms, 4)},
        "twin": _row(twin),
        "domain_kill": {
            **_row(kill, cmp_["domain_kill"]),
            "killed_replicas": kill.killed_replicas,
            "kill_fraction": round(
                kill.killed_replicas / replicas, 3),
            "watch_delivered": kill.watch_delivered,
        },
        "upgrade": {
            **_row(upg, cmp_["upgrade"]),
            "waves": upg.upgrade_waves,
            "upgraded_replicas": upg.upgraded_replicas,
            "floor": floor,
        },
        "crash_recovery": {
            **_row(crash, cmp_["crash_recovery"]),
            "recoveries": crash.recoveries,
            "redriven": crash.redriven,
            "journal_records": crash.journal_records,
        },
        # headline gates (the tier-1 smoke asserts these)
        "domains_killed": kill.domain_kills,
        "exactly_once": exactly_once,
        "outcomes_identical": identical,
        "tier_inversions": sum(r.tier_inversions
                               for r in [twin, *legs.values()]),
        "upgrade_waves": upg.upgrade_waves,
        "recovered_exactly_once": recovered,
        "deterministic": compare_outcomes(
            kill.load, kill2.load)["identical"],
        "wall_ms_raw_weather": round(wall_ms, 1),
    }


def _cb_obs_fleet_bench(replicas: int = 32, domains: int = 4,
                        requests: int = 192) -> dict:
    """Flight-recorder closed loop (ISSUE 20 tentpole): the fleet
    harness runs a seeded multi-tenant trace four times —

    - **twin**: fault-free, FlightRecorder on → MUST fire zero alerts
      (the burn windows never breach on healthy traffic);
    - **kill**: ``rack1`` (25% of the fleet) dies at tick 20 with the
      recorder watching — the failover burn-rate rule must page from
      metrics alone within 16 ticks of the kill;
    - **kill2**: identical re-run — alert log AND per-request outcomes
      must be bit-identical (alerting is tick-deterministic);
    - **off**: same kill with NO metrics/recorder — outcomes must be
      identical to the recorded kill (observation never steers).

    Every leg also proves exact integer chip-tick conservation
    (Σ per-(tenant,tier) attribution == Σ replica busy chip-ticks) and
    the twin reports the recorder's per-tick sampling overhead, gated
    at ≤ 5% of leg wall (tick-denominated outcomes are the contract;
    the wall numbers are weather)."""
    import time

    from kubegpu_tpu.fleet import (
        FleetConfig,
        ReplicaCosts,
        compare_outcomes,
        run_fleet,
    )
    from kubegpu_tpu.loadgen import LoadSpec, TierSpec, synth_trace
    from kubegpu_tpu.obs.alerts import FlightRecorder
    from kubegpu_tpu.obs.chaos import (
        DOMAIN_KILL,
        DomainChaosEvent,
        DomainChaosInjector,
    )
    from kubegpu_tpu.obs.metrics import MetricsRegistry
    from kubegpu_tpu.obs.spans import Tracer, validate_chrome_trace

    KILL_TICK = 20
    ALERT_BOUND_TICKS = 16
    TIERS = (TierSpec("gold", ttft_slo_ticks=40,
                      token_slo_ticks=40.0, share=0.2),
             TierSpec("silver", ttft_slo_ticks=80,
                      token_slo_ticks=80.0, share=0.3),
             TierSpec("bronze", ttft_slo_ticks=10**6,
                      token_slo_ticks=1e6, share=0.5))
    trace = synth_trace(LoadSpec(
        seed=1907, n_requests=requests, mean_iat_ticks=0.25,
        tiers=TIERS, tenants=("acme", "blue", "coral"),
        diurnal=True, flash_at=(10.0,), flash_rate_x=4.0,
        flash_len_ticks=8.0))
    cfg = FleetConfig(costs=ReplicaCosts.from_bench())

    def _weather():
        return DomainChaosInjector(events=[DomainChaosEvent(
            tick=KILL_TICK, kind=DOMAIN_KILL, domain="rack1")])

    def _leg(recorder=None, metrics=None, **kw):
        t0 = time.perf_counter()
        rep = run_fleet(trace, TIERS, cfg=cfg, replicas=replicas,
                        domains=domains, controller=recorder,
                        metrics=metrics, **kw)
        return rep, time.perf_counter() - t0

    # warmup twin: pays interpreter cold-start so the measured twin
    # doesn't bill it to the sampling-overhead number; it is ALSO a
    # second overhead sample — the reported steady-state figure is the
    # min of the two (best-of-N, the standard defense against a CPU-
    # contention spike landing on exactly one leg)
    warm_reg = MetricsRegistry()
    warm_rec = FlightRecorder(warm_reg)
    _, warm_wall = _leg(warm_rec, warm_reg)

    twin_reg = MetricsRegistry()
    twin_rec = FlightRecorder(twin_reg)
    twin, twin_wall = _leg(twin_rec, twin_reg)

    tracer = Tracer()
    kill_reg = MetricsRegistry()
    kill_rec = FlightRecorder(kill_reg, tracer=tracer)
    kill, _ = _leg(kill_rec, kill_reg, chaos=_weather())

    kill2_reg = MetricsRegistry()
    kill2_rec = FlightRecorder(kill2_reg)
    kill2, _ = _leg(kill2_rec, kill2_reg, chaos=_weather())

    off, _ = _leg(chaos=_weather())

    conserved = all(
        r.busy_chip_ticks == sum(r.cost_by_key.values()) == r.busy_ticks
        for r in (twin, kill, kill2, off))
    fired = kill_rec.alert_log()
    first_alert_tick = fired[0][0] if fired else None
    latency = (first_alert_tick - KILL_TICK
               if first_alert_tick is not None else None)

    # Perfetto proof: the kill leg's counter tracks merge into the
    # (possibly empty) span trace and the result still validates
    merged = kill_rec.store.merge_chrome_trace(tracer.to_chrome_trace())
    events = validate_chrome_trace(merged)
    counter_events = sum(1 for e in events if e["ph"] == "C")

    pcts = [100.0 * rec.obs_wall_s / wall
            for rec, wall in ((warm_rec, warm_wall),
                              (twin_rec, twin_wall)) if wall > 0]
    overhead_pct = min(pcts) if pcts else 0.0
    overhead_tick_us = min(
        warm_rec.overhead_per_tick_s, twin_rec.overhead_per_tick_s) * 1e6
    return {
        "protocol": "fleet_flight_recorder",
        "fleet_replicas": replicas,
        "domains": domains,
        "domains_killed": kill.domain_kills,
        "requests": len(trace),
        "kill_tick": KILL_TICK,
        "alert_bound_ticks": ALERT_BOUND_TICKS,
        # headline gates (tier-1 asserts these)
        "twin_alerts": len(twin_rec.alert_log()),
        "alerts_fired": len(fired),
        "first_alert_tick": first_alert_tick,
        "alert_latency_ticks": latency,
        "alert_within_bound": (latency is not None
                               and latency <= ALERT_BOUND_TICKS),
        "alert_log": [list(t) for t in fired],
        "deterministic": (
            kill_rec.alert_log() == kill2_rec.alert_log()
            and compare_outcomes(kill.load, kill2.load)["identical"]),
        "outcomes_identical_obs_off": compare_outcomes(
            kill.load, off.load)["identical"],
        "chip_ticks_conserved": conserved,
        "busy_chip_ticks": kill.busy_chip_ticks,
        "cost_summary": kill.cost_summary(),
        "goodput_per_chip_tick":
            kill.cost_summary()["goodput_per_chip_tick"],
        "series_sampled": len(kill_rec.store.names()),
        "counter_events": counter_events,
        "trace_validates": True,
        "overhead_per_tick_us_raw": round(overhead_tick_us, 2),
        "overhead_pct_raw": round(overhead_pct, 3),
        "overhead_pct_legs_raw": [round(p, 3) for p in pcts],
        "overhead_ok": overhead_pct <= 5.0,
    }


def run_serving_bench_smoke(legs=None) -> dict:
    """Tiny-config run of ONLY the serving fast-path bench legs
    (prefix cache, chunked-prefill stall, equal-HBM mixed-length A/B,
    HBM donation A/B) — seconds on CPU.  ``make bench-smoke`` and the
    tier-1 smoke test drive this to assert the bench JSON parses and
    carries the new keys without waiting for a full hardware bench.
    ``legs`` filters to a subset by row name (``make hbm-smoke`` runs
    just ``cb_hbm_donation``)."""
    import jax

    from kubegpu_tpu.models import LlamaConfig, llama_init

    import numpy as np

    cfg = LlamaConfig.tiny(n_heads=4, n_kv_heads=2, max_seq_len=64)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    # the tp leg needs tp | n_kv_heads up to 4 (the tp=1/2/4 ladder
    # plus the 4-chip equal-chip A/B)
    tp_cfg = LlamaConfig.tiny(n_heads=4, n_kv_heads=4, max_seq_len=64)
    tp_params = llama_init(jax.random.PRNGKey(1), tp_cfg)
    # the spec leg trains its tiny model on a short cycle (seconds on
    # CPU) so the smoke's acceptance number is a real measurement of
    # the trained-draft machinery, not random-weight noise.  4 layers
    # with a 2-layer draft keeps the flagship's draft-cost shape; at
    # the measured acceptance (1.0 on the learned cycle) the spec
    # engine drains the window in FEWER verify ticks than the off
    # engine's decode blocks — deterministic, so tier-1 asserts it.
    def spec_leg():
        sp_cfg = LlamaConfig.tiny(n_heads=4, n_kv_heads=2, n_layers=4,
                                  max_seq_len=64)
        sp_params, sp_pattern, _ = _train_draft_model(
            sp_cfg, steps=100, pat_len=8, batch=2, seq=16)
        sp_cyc = np.tile(sp_pattern, 6)
        return _cb_spec_bench(
            sp_params, sp_cfg, slots=2, prompt=16, new=8, stride=2,
            page=8, reqs=4, iters=2, draft_layers=2, gammas=(3,),
            degrees=(1, 2),
            prompts=[sp_cyc[i % 8:][:16] for i in range(4)])

    rows = {
        "cb_prefix_cache": lambda: _cb_prefix_bench(
            params, cfg, slots=2, prompt=16, new=4, stride=2, page=8,
            n_way=3),
        "cb_chunked_stall": lambda: _cb_stall_bench(
            params, cfg, slots=2, prompt=16, new=4, stride=2, reqs=3,
            page=8, chunk=8, iters=2),
        "cb_equal_hbm": lambda: _cb_equal_hbm_bench(
            params, cfg, dense_slots=2, paged_slots=3, buckets=(8, 16),
            mix=[(8, 3), (16, 3)], reqs=4, stride=2, page=8, iters=2),
        "cb_tp_scaling": lambda: _cb_tp_bench(
            tp_params, tp_cfg, slots=2, prompt=16, new=4, stride=2,
            reqs=6, page=8, iters=2),
        "cb_spec": spec_leg,
        "cb_chaos": lambda: _cb_chaos_bench(
            params, cfg, slots=2, prompt=16, new=8, stride=2, page=8,
            reqs=6),
        "cb_trace_overhead": lambda: _cb_trace_overhead_bench(
            params, cfg, slots=2, prompt=16, new=8, stride=2, page=8,
            reqs=6),
        "cb_fused_ticks": lambda: _cb_fused_bench(
            params, cfg, slots=3, prompt=16, new=24, stride=2, page=8,
            reqs=3, ks=(1, 4)),
        "cb_hbm_donation": lambda: _cb_hbm_bench(
            params, cfg, slots=2, prompt=16, new=8, stride=2, page=8,
            reqs=4),
        "cb_kv_capacity": lambda: _cb_kv_capacity_bench(
            params, cfg, slots=2, prompt=32, new=8, stride=2, page=8,
            reqs=4),
        "cb_disagg": lambda: _cb_disagg_bench(
            params, cfg, slots=2, prompt=16, new=24, stride=2, page=8,
            chunk=8, reqs=8),
        "cb_slo_goodput": lambda: _cb_slo_goodput_bench(params, cfg),
        "cb_prefix_affinity": lambda: _cb_prefix_affinity_bench(
            params, cfg),
        "cb_autoscale": lambda: _cb_autoscale_bench(params, cfg),
        "cb_fleet_chaos": _cb_fleet_chaos_bench,
        "cb_obs_fleet": _cb_obs_fleet_bench,
        "cb_compile_census": _cb_compile_census_bench,
    }
    if legs is not None:
        unknown = set(legs) - set(rows)
        if unknown:
            raise ValueError(f"unknown bench legs: {sorted(unknown)}")
        rows = {k: rows[k] for k in rows if k in set(legs)}
    return {name: fn() for name, fn in rows.items()}


def _cb_compile_census_bench() -> dict:
    """The KTP-Audit compile-signature census as a bench row: how many
    distinct lowering signatures the scripted serving workload
    (admission wave → chunked prefill → spec ticks → fused K∈{1,4} →
    quarantine replay) compiles, and the first-compile wall per
    executable.  ``violations`` MUST be 0 — a nonzero count means a
    dispatch shape drifted off the enumerated expected set in
    kubegpu_tpu/analysis/jaxpr_audit.py (a recompilation hazard in
    production)."""
    from kubegpu_tpu.analysis.jaxpr_audit import compile_census
    findings, summary = compile_census()
    return {
        "violations": len(findings),
        "violation_messages": [f.message for f in findings],
        "signatures_total": summary["signatures_total"],
        "per_executable": summary["per_executable"],
        "engines": summary["engines"],
    }


def _p99_phase_attribution(trace) -> dict:
    """Bucket what the slowest 1% of scheduling decisions spent their
    time on (VERDICT r5 weak #5 / next-item #6).  Every schedule/fail
    decision now carries per-phase timings (enumeration incl. ordering,
    multislice split search, preemption planning, migration planning)
    in its trace record; this aggregates the tail so the p99 story is
    attributed in the bench JSON instead of being a bare number."""
    def payload(e):
        # ScheduleTrace.record(kind, gang=..., detail={...}) nests the
        # caller's dict under the "detail" key of TraceEvent.detail
        return e.detail.get("detail", e.detail)

    evs = [(e.kind, payload(e)) for e in trace.events()
           if e.kind in ("schedule", "fail")
           and "total_ms" in payload(e)]
    if not evs:
        return {"decisions": 0}
    evs.sort(key=lambda kd: kd[1]["total_ms"], reverse=True)
    n_tail = max(1, len(evs) // 100)
    tail = evs[:n_tail]
    tail_total = sum(d["total_ms"] for _, d in tail)
    phases = sorted({k for _, d in tail
                     for k in d.get("phase_ms", {})})
    agg = {}
    for name in phases:
        vals = [d.get("phase_ms", {}).get(name, 0.0) for _, d in tail]
        agg[name] = {
            "mean_ms": round(sum(vals) / len(vals), 3),
            "max_ms": round(max(vals), 3),
            "share": round(sum(vals) / tail_total, 3)
            if tail_total else 0.0,
        }
    return {
        "decisions": len(evs),
        "tail_count": n_tail,
        "tail_threshold_ms": round(tail[-1][1]["total_ms"], 3),
        "tail_mean_ms": round(tail_total / n_tail, 3),
        "tail_kinds": {k: sum(1 for kk, _ in tail if kk == k)
                       for k in ("schedule", "fail")},
        "phases": agg,
    }


def run_bench(n_gangs: int = 60, seed: int = 0,
              slice_types: list[str] | None = None,
              shapes: list[dict] | None = None,
              metric_name: str = "gang_schedule_p50_latency") -> dict:
    from kubegpu_tpu.cluster import SimCluster, tpu_pod
    from kubegpu_tpu.kubemeta import GangSpec, NotFound, PodPhase
    from kubegpu_tpu.kubemeta.codec import pod_allocation

    rng = random.Random(seed)
    cl = SimCluster(slice_types or ["v5e-64", "v5e-64", "v4-8"])
    # mixed workload: DP gangs, tp-heavy llama-style gangs, single chips,
    # fractional co-tenants — with completion churn so the allocator works
    # against fragmentation, not an empty cluster.
    shapes = shapes or [
        dict(pods=4, chips=1, axes={"dp": 4}),
        dict(pods=4, chips=4, axes={"dp": 4, "tp": 4}),
        dict(pods=16, chips=4, axes={"dp": 4, "tp": 16}),
        dict(pods=8, chips=4, axes={"dp": 2, "tp": 16}),
        dict(pods=1, chips=1, axes=None),
        dict(pods=1, chips=4, axes={"dp": 1, "tp": 4}),
        dict(pods=1, chips=0, axes=None, millitpu=500),
    ]

    def finish_one(live_list):
        """Complete one random live gang: delete its pods → watch event →
        the scheduler releases its slice."""
        for name in live_list.pop(rng.randrange(len(live_list))):
            try:
                cl.api.delete("Pod", name)
            except NotFound:
                pass

    def gang_placed(names):
        return all(
            cl.api.get("Pod", n).status.phase != PodPhase.PENDING
            for n in names)

    live: list[list[str]] = []
    gangs_placed_total = 0
    gangs_multislice = 0
    for g in range(n_gangs):
        spec = rng.choice(shapes)
        names = []
        if spec.get("millitpu"):
            names.append(f"frac-{g}")
            cl.submit(tpu_pod(f"frac-{g}", millitpu=spec["millitpu"],
                              command=["x"]))
        elif spec["pods"] == 1:
            names.append(f"pod-{g}")
            cl.submit(tpu_pod(f"pod-{g}", chips=spec["chips"],
                              mesh_axes=spec["axes"], command=["x"]))
        else:
            for i in range(spec["pods"]):
                name = f"gang{g}-{i}"
                names.append(name)
                cl.submit(tpu_pod(
                    name, chips=spec["chips"],
                    gang=GangSpec(name=f"gang{g}", size=spec["pods"],
                                  index=i),
                    mesh_axes=spec["axes"],
                    multislice=spec.get("multislice", False),
                    command=["x"]))
        cl.step()
        # queue-drain model: if the gang didn't fit, complete live gangs
        # one at a time until it does — the allocator always works
        # against a fragmented, partially-occupied cluster, and every
        # successful placement latency lands in the histogram.
        while not gang_placed(names) and live:
            finish_one(live)
            cl.step()
        if gang_placed(names):
            live.append(names)
            # multislice accounting: a gang whose pods landed on >1
            # slice crossed DCN (its first-axis rings split)
            sids = set()
            for n in names:
                alloc = pod_allocation(cl.api.get("Pod", n))
                if alloc is not None:
                    sids.add(alloc.slice_id)
            gangs_placed_total += 1
            if len(sids) > 1:
                gangs_multislice += 1
        # background churn keeps occupancy realistic (~40% completion)
        if len(live) > 4 and rng.random() < 0.4:
            finish_one(live)
    cl.reap()
    snap = cl.metrics.snapshot()
    hist = snap["histograms"].get("schedule_latency_ms", {})
    loc = snap["histograms"].get("allocation_locality", {})
    p50 = hist.get("p50", 0.0)
    return {
        "metric": metric_name,
        "value": round(p50, 3),
        "unit": "ms",
        # 0.0 (not inf) when nothing scheduled: a broken run must not
        # read as a record win
        "vs_baseline": round(BASELINE_P50_MS / p50, 2) if p50 > 0 else 0.0,
        "details": {
            "p90_ms": round(hist.get("p90", 0.0), 3),
            "p99_ms": round(hist.get("p99", 0.0), 3),
            # the histogram covers EVERY decision, failed ones included —
            # the expensive infeasible searches are in the percentiles
            "decisions": hist.get("count", 0),
            "gangs_scheduled": snap["counters"].get("gangs_scheduled", 0),
            "decisions_failed": snap["counters"].get("gangs_failed", 0),
            "unschedulable": snap["counters"].get(
                "schedule_unschedulable", 0),
            "mean_allocation_locality": round(loc.get("mean", 0.0), 4),
            "gangs_multislice": gangs_multislice,
            "multislice_fraction": round(
                gangs_multislice / gangs_placed_total, 3)
            if gangs_placed_total else 0.0,
            "baseline_p50_ms": BASELINE_P50_MS,
            # what the slowest 1% of decisions actually spent time on
            "p99_phase_attribution": _p99_phase_attribution(cl.trace),
        },
    }


def run_scale_bench(n_gangs: int = 500, seed: int = 0) -> dict:
    """Pod-scale scenario (VERDICT r2 weak #5: the p50/p99 story was
    untested past 136 chips / 60 gangs): 4 x v5e-256 = 1024 chips over
    256 nodes, 500-gang churn, gang sizes up to a full 256-chip slice.
    Same queue-drain/churn model as :func:`run_bench`."""
    shapes = [
        dict(pods=4, chips=1, axes={"dp": 4}),
        dict(pods=4, chips=4, axes={"dp": 4, "tp": 4}),
        dict(pods=16, chips=4, axes={"dp": 4, "tp": 16}),      # 64 chips
        dict(pods=32, chips=4, axes={"dp": 2, "tp": 64}),      # 128 chips
        dict(pods=64, chips=4, axes={"dp": 4, "tp": 64}),      # full slice
        dict(pods=1, chips=1, axes=None),
        dict(pods=1, chips=4, axes={"dp": 1, "tp": 4}),
        dict(pods=1, chips=0, axes=None, millitpu=500),
    ]
    return run_bench(
        n_gangs=n_gangs, seed=seed,
        slice_types=["v5e-256"] * 4, shapes=shapes,
        metric_name="gang_schedule_p50_latency_1024chip")


def run_multislice_bench(n_gangs: int = 120, seed: int = 0) -> dict:
    """Multislice-at-scale scenario (VERDICT r3 next-item #8): 4 x
    v5e-256, but a fraction of gangs EXCEED any single slice (320- and
    512-chip asks with ``allow_multislice``) so the allocator must
    split them across DCN — the Cloud-TPU multislice shape.  Reports
    the usual latency percentiles + locality, plus how many placed
    gangs actually crossed slices (``multislice_fraction``)."""
    shapes = [
        dict(pods=16, chips=4, axes={"dp": 4, "tp": 16}),       # 64
        dict(pods=64, chips=4, axes={"dp": 4, "tp": 64}),       # 256
        dict(pods=80, chips=4, axes={"dp": 5, "tp": 64},        # 320:
             multislice=True),                # > one slice, splits dp
        dict(pods=128, chips=4, axes={"dp": 8, "tp": 64},       # 512:
             multislice=True),                # spans >= 2 slices
        dict(pods=1, chips=4, axes={"dp": 1, "tp": 4}),
        dict(pods=1, chips=1, axes=None),
    ]
    return run_bench(
        n_gangs=n_gangs, seed=seed,
        slice_types=["v5e-256"] * 4, shapes=shapes,
        metric_name="gang_schedule_p50_latency_multislice")


def run_wire_bench(n_pods: int = 40, slice_type: str = "v5e-64") -> dict:
    """Scheduler-over-HTTP decision latency (VERDICT r2 item #2's
    'done' bar: record the wire p50).  Topology: apiserver façade in
    this process, the SCHEDULER as an external
    ``kubegpu_tpu.scheduler.daemon`` process reading through its watch
    cache and binding over HTTP; node agents register in-process (their
    wire path has its own daemon + tests — the scheduler is the wire
    under test).  Per-pod latency = Pod create → SCHEDULED watch event
    at this client, i.e. decision time plus the bind POST plus watch
    delivery; pods churn (delete after bind) so the slice never fills."""
    import statistics
    import subprocess
    import sys as _sys
    import threading

    from kubegpu_tpu.cluster import tpu_pod
    from kubegpu_tpu.crishim.agent import NodeAgent
    from kubegpu_tpu.crishim.runtime import FakeRuntime
    from kubegpu_tpu.kubemeta import FakeApiServer, PodPhase
    from kubegpu_tpu.kubemeta.apiserver_http import ApiServerHTTP
    from kubegpu_tpu.tpuplugin.mock import mock_cluster

    api = FakeApiServer()
    srv = ApiServerHTTP(api).start()
    for backend in mock_cluster([slice_type]):
        NodeAgent(api, backend, FakeRuntime()).register()

    scheduled = {}          # pod name → event arrival time
    seen = threading.Condition()

    def on_event(ev):
        if ev.kind == "Pod" and ev.type == "MODIFIED" \
                and ev.obj.status.phase == PodPhase.SCHEDULED:
            with seen:
                scheduled[ev.obj.metadata.name] = time.perf_counter()
                seen.notify_all()

    unsub = api.watch(on_event)
    proc = subprocess.Popen(
        [_sys.executable, "-m", "kubegpu_tpu.scheduler.daemon",
         "--apiserver", srv.address, "--tick", "0.5"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    # Reader threads drain BOTH pipes for the daemon's whole life
    # (ADVICE r3: a blocking readline here could hang past the deadline
    # if the daemon filled the 64KB stderr pipe without ever printing
    # the readiness line).
    ready = threading.Event()
    out_lines: list = []
    err_lines: list = []

    def _pump(stream, sink, needle=None):
        for line in stream:
            sink.append(line)
            if needle and line.startswith(needle):
                ready.set()

    threading.Thread(target=_pump,
                     args=(proc.stdout, out_lines, "scheduler: connected"),
                     daemon=True).start()
    err_pump = threading.Thread(target=_pump, args=(proc.stderr, err_lines),
                                daemon=True)
    err_pump.start()

    def _stderr_tail() -> str:
        # Let the pump reach EOF so a crash traceback is fully captured
        # before we format the error (racing it can report '' instead).
        err_pump.join(timeout=2.0)
        return "".join(err_lines)[-500:]

    lat_ms = []
    try:
        deadline = time.monotonic() + 30
        while not ready.is_set():
            if proc.poll() is not None:
                raise RuntimeError(
                    "scheduler daemon died at startup "
                    f"(rc={proc.poll()}): {_stderr_tail()}")
            if time.monotonic() > deadline:
                raise RuntimeError(
                    "scheduler daemon never printed readiness within 30s; "
                    f"stderr: {''.join(err_lines)[-500:]}")
            ready.wait(0.05)
        for i in range(n_pods):
            name = f"wire-{i}"
            t0 = time.perf_counter()
            api.create("Pod", tpu_pod(name, chips=1, command=["x"]))
            with seen:
                ok = seen.wait_for(lambda: name in scheduled,
                                   timeout=20.0)
            if not ok:
                raise RuntimeError(
                    f"pod {name} never scheduled over the wire; "
                    f"daemon rc={proc.poll()}")
            lat_ms.append((scheduled[name] - t0) * 1e3)
            api.delete("Pod", name)   # churn: keep the slice free
        lat_ms.sort()
        return {
            "n_pods": n_pods,
            "slice": slice_type,
            "p50_ms": round(statistics.median(lat_ms), 3),
            "p90_ms": round(lat_ms[int(0.9 * (len(lat_ms) - 1))], 3),
            "p99_ms": round(lat_ms[int(0.99 * (len(lat_ms) - 1))], 3),
            "max_ms": round(lat_ms[-1], 3),
        }
    finally:
        unsub()
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
        srv.close()


def run_serve_pod_bench(timeout_s: float = 600.0) -> dict:
    """Serving as a SCHEDULABLE workload, measured end-to-end through
    the cluster (VERDICT r2 weak #4: r2 only ever served the tiny
    config from a pod): schedule the ``serve`` spec onto a SimCluster
    whose crishim launches a REAL subprocess that inherits the real
    TPU, let the annotation-driven config selection pick the flagship
    (the node advertises a whole 16 GiB chip), and read the tokens/s
    the node agent harvested into the cluster metrics registry.  The
    number reported here came from a pod, not a library call."""
    import jax

    from kubegpu_tpu.cluster import SimCluster
    from kubegpu_tpu.workloads.specs import ALL_CONFIGS

    on_tpu = jax.devices()[0].platform.startswith(("tpu", "axon"))
    # the pod must see the real TPU: no JAX_PLATFORMS=cpu override —
    # but the subprocess whitelist needs PJRT tunnel vars passed through
    extra = {k: v for k, v in os.environ.items()
             if k.startswith(("JAX_", "TPU_", "PJRT_", "LIBTPU"))
             and k not in ("JAX_PLATFORMS",)}
    # the strict fence rides into the pod: the flagship serving
    # workload must abort on a silent paged→dense degradation too
    if os.environ.get("KUBETPU_REQUIRE_PALLAS"):
        extra["KUBETPU_REQUIRE_PALLAS"] = \
            os.environ["KUBETPU_REQUIRE_PALLAS"]
    cl = SimCluster(["v4-8"], real_processes=True, extra_env=extra)
    pods, _ = ALL_CONFIGS["serve"]()
    for p in pods:
        # flagship serving needs the full decode budget; drop the spec's
        # CPU-sim-friendly step override so the bench config defaults
        # (b32 x 1024 prompt x 128 steps, int8) apply on hardware
        if on_tpu:
            p.spec.containers[0].env.pop("SERVE_STEPS", None)
        cl.submit(p)
    codes = cl.run_to_completion(timeout_s=timeout_s)
    snap = cl.metrics.snapshot()
    pod_decode = snap["gauges"].get(
        "workload_serve_decode_tokens_per_s")
    # pod-path attribution (VERDICT r5 next-item #3): the pod now
    # echoes its exact config and per-phase timings into the registry
    # the agent harvests — surface every serve_* gauge it reported
    pod_detail = {
        k.removeprefix("workload_"): v
        for k, v in snap["gauges"].items()
        if k.startswith("workload_serve_")}
    out = {
        "exit_codes": codes,
        "decode_tokens_per_s": pod_decode,
        "e2e_tokens_per_s": snap["gauges"].get(
            "workload_serve_e2e_tokens_per_s"),
        "pod_detail": pod_detail,
    }
    # library A/B in the SAME window: run the identical static decode
    # measurement in-process (the pod's own protocol — prefill
    # subtracted, int8 weights + int8 KV) so the pod tax is a
    # like-for-like ratio, not a cross-round comparison
    if on_tpu and pod_decode:
        try:
            import jax.numpy as jnp
            import numpy as np

            from kubegpu_tpu.models import (
                greedy_generate,
                llama_init,
                quantize_llama,
            )
            from kubegpu_tpu.models.decode import prefill as _prefill

            import jax as _jax
            cfg = llama_bench_config()
            batch, prompt_t, steps = (
                int(pod_detail.get("serve_cfg_batch", 32)),
                int(pod_detail.get("serve_cfg_prompt", 1024)),
                int(pod_detail.get("serve_cfg_steps", 128)))
            max_len = prompt_t + steps
            params = quantize_llama(
                llama_init(_jax.random.PRNGKey(0), cfg))
            pr = jnp.asarray(
                np.arange(batch * prompt_t).reshape(batch, prompt_t)
                % cfg.vocab_size, jnp.int32)
            pf = _jax.jit(lambda p, tk: _prefill(
                p, tk, cfg, max_len, kv_int8=True)[0])
            pre_s = _time_calls(lambda: pf(params, pr), lambda o: o, 2)
            gen_s = _time_calls(
                lambda: greedy_generate(params, pr, steps, cfg,
                                        max_len, kv_int8=True),
                lambda o: o, 2)
            lib_decode = round(
                batch * (steps - 1) / max(gen_s - pre_s, 1e-9), 1)
            out["library_decode_tokens_per_s"] = lib_decode
            out["pod_vs_library"] = round(pod_decode / lib_decode, 3)
        except Exception as e:   # the A/B must not hide the pod figure
            out["library_error"] = str(e)
    return out


def summarize_bench(out: dict) -> dict:
    """Compact headline summary — the driver-captured line of record.

    VERDICT r4 weak #1: BENCH_r0{3,4}.json had ``parsed: null`` and a
    2000-char tail that truncated the one giant JSON line mid-document,
    so the round's flagship numbers (MFU, flash speedup, decode ladder)
    existed in no driver artifact.  This summary is guaranteed small
    (< ~1500 bytes) and is printed as the FINAL stdout line so it always
    lands whole inside the driver's tail window and parses on its own.
    Keys abbreviate but stay self-describing; the full document goes to
    the first stdout line + BENCH_DETAILS.json."""
    d = out.get("details", {})
    s = {
        "metric": out.get("metric"),
        "value": out.get("value"),
        "unit": out.get("unit"),
        "vs_baseline": out.get("vs_baseline"),
        "p99_ms": d.get("p99_ms"),
        "locality": d.get("mean_allocation_locality"),
    }

    def err_or(node, fn):
        if not isinstance(node, dict):
            return None
        if "error" in node:
            return {"error": str(node["error"])[:120]}
        return fn(node)

    m = d.get("model")
    if isinstance(m, dict) and "error" not in m:
        s["mfu"] = m.get("mfu")
        s["train_step_ms"] = m.get("step_ms")
        s["train_tok_s"] = m.get("tokens_per_s")
        att = m.get("attention") or {}
        s["flash_speedup"] = att.get("pallas_speedup")
        sv = m.get("serving") or {}
        s["decode_tok_s"] = {
            "bf16": sv.get("decode_tokens_per_s"),
            "int8": sv.get("int8_decode_tokens_per_s"),
            "int8_kv": sv.get("int8_kv_decode_tokens_per_s"),
            "int8_kv_b4x": sv.get("int8_kv_decode_b4x_tokens_per_s"),
        }
        fam = m.get("families") or {}
        cb = fam.get("continuous_batching") or {}
        s["cb"] = {
            "static": cb.get("static_e2e_tokens_per_s"),
            "dense_x": (cb.get("dense") or {}).get(
                "vs_static_e2e_anchored"),
            "paged_x": (cb.get("paged") or {}).get(
                "vs_static_e2e_anchored"),
            "paged_tok_s": cb.get("decode_tokens_per_s"),
        }
        cbf = fam.get("continuous_batching_flagship") or {}
        if cbf:
            s["cb_flagship"] = {
                "static": cbf.get("static_e2e_tokens_per_s"),
                "dense_x": (cbf.get("dense") or {}).get(
                    "vs_static_e2e_anchored"),
                "paged_x": (cbf.get("paged") or {}).get(
                    "vs_static_e2e_anchored"),
                "paged_tok_s": cbf.get("decode_tokens_per_s"),
            }
        pc = fam.get("cb_prefix_cache") or {}
        if pc:
            s["cb_prefix"] = {"x": pc.get("prefill_reduction_x"),
                              "pages": pc.get("pages_aliased")}
        stl = fam.get("cb_chunked_stall") or {}
        if stl:
            s["cb_stall_p99"] = {"off": stl.get("stall_p99_ms_off"),
                                 "on": stl.get("stall_p99_ms_on"),
                                 "x": stl.get("stall_p99_reduction_x")}
        ehbm = fam.get("cb_equal_hbm") or {}
        if ehbm:
            s["cb_hbm_x"] = ehbm.get("paged_vs_dense_equal_hbm")
        tps = fam.get("cb_tp_serving") or {}
        if tps:
            scal = tps.get("scaling") or {}
            s["cb_tp"] = {
                name: row.get("engine_tokens_per_s_anchored")
                for name, row in scal.items()}
            ab = tps.get("equal_chip_ab") or {}
            if "skipped" not in ab:
                s["cb_tp"]["tp_vs_dp"] = ab.get("tp_vs_dp")
                s["cb_tp"]["winner"] = ab.get("winner")
        pld = fam.get("spec_decode_pld") or {}
        s["pld"] = {"x": pld.get("speedup_vs_greedy"),
                    "acc": pld.get("acceptance_rate")}
        curve = fam.get("spec_decode_pld_curve")
        if curve:
            s["pld_curve"] = [
                [p.get("acceptance_rate"), p.get("speedup_vs_greedy")]
                for p in curve]
        spec = fam.get("spec_decode") or {}
        s["spec_self_x"] = spec.get("speedup_vs_greedy")
        s["spec_self_acc"] = spec.get("acceptance_rate")
        cbs = fam.get("cb_spec") or {}
        if cbs:
            s["cb_spec"] = {
                name: {"x": row.get("best_speedup_vs_off"),
                       "g": row.get("best_gamma"),
                       "acc": row.get("best_acceptance"),
                       "parity": row.get("parity_all")}
                for name, row in (cbs.get("by_tp") or {}).items()
                if "skipped" not in row}
        dis = fam.get("cb_disagg") or {}
        if dis and "skipped" not in dis:
            s["cb_disagg"] = {
                "ttft_x": dis.get("ttft_p99_reduction_x"),
                "stall_x": dis.get("stall_p99_reduction_x"),
                "ttft_ticks_x": dis.get("ttft_ticks_reduction_x"),
                "exact": dis.get("bit_exact"),
                "migrations": (dis.get("disagg") or {}).get(
                    "migrations"),
            }
        # serving-tail columns — [TTFT p99, decode-stall p99,
        # queue-wait p99] ms for EVERY serving row (ISSUE 11 sat.):
        # a row reports the tails at top level or one leg-dict deep;
        # rows that don't measure a tail print null, so the table's
        # shape is stable as rows learn to measure them
        TAIL_KEYS = ("ttft_p99_ms", "decode_stall_p99_ms",
                     "queue_wait_p99_ms")

        def _tail_cols(row):
            legs = {name: node for name, node in row.items()
                    if isinstance(node, dict)
                    and any(t in node for t in TAIL_KEYS)}
            if legs:
                return {name: [node.get(t) for t in TAIL_KEYS]
                        for name, node in legs.items()}
            return [row.get(t) for t in TAIL_KEYS]

        tails = {
            name: _tail_cols(row)
            for name, row in list(fam.items()) + [("serving", sv)]
            if isinstance(row, dict) and "skipped" not in row
            and "error" not in row
            and (name == "serving" or name.startswith(
                ("cb", "continuous_batching", "spec_decode")))}
        if tails:
            s["serving_tails"] = tails
        # goodput / SLO-attainment columns (ISSUE 13 sat.) — same
        # probing as the tail table: [goodput tokens/tick,
        # SLO attainment] per serving row (or per leg).  Sparse by
        # design: rows that never drove the load harness are omitted
        # (an all-null column would burn the driver line's byte
        # budget saying nothing)
        GOOD_KEYS = ("goodput_tokens_per_tick", "slo_attainment")

        def _goodput_cols(row):
            legs = {name: node for name, node in row.items()
                    if isinstance(node, dict)
                    and any(g in node for g in GOOD_KEYS)}
            if legs:
                return {name: [node.get(g) for g in GOOD_KEYS]
                        for name, node in legs.items()}
            if any(g in row for g in GOOD_KEYS):
                return [row.get(g) for g in GOOD_KEYS]
            return None

        goodput = {
            name: cols
            for name, row in list(fam.items()) + [("serving", sv)]
            if isinstance(row, dict) and "skipped" not in row
            and "error" not in row
            and (name == "serving" or name.startswith(
                ("cb", "continuous_batching", "spec_decode")))
            and (cols := _goodput_cols(row)) is not None}
        if goodput:
            s["serving_goodput"] = goodput
        # routing / autoscale columns (ISSUE 14 sat.) — sparse like
        # the goodput table: [affinity hit-rate, replicas min→max]
        # for rows that routed traffic through the pool or scaled it

        def _routing_cols(row):
            hit = row.get("routing_affinity_hit_rate")
            if hit is None and isinstance(row.get("affinity"), dict):
                hit = row["affinity"].get("affinity_hit_rate")
            lo, hi = row.get("replicas_min"), row.get("replicas_max")
            if hit is None and lo is None:
                return None
            return [hit, f"{lo}→{hi}" if lo is not None else None]

        routing = {
            name: cols
            for name, row in list(fam.items()) + [("serving", sv)]
            if isinstance(row, dict) and "skipped" not in row
            and "error" not in row
            and (name == "serving" or name.startswith(
                ("cb", "continuous_batching")))
            and (cols := _routing_cols(row)) is not None}
        if routing:
            s["serving_routing"] = routing
        # kv-capacity columns (ISSUE 15 sat.) — sparse like the
        # routing table: [slots-at-budget, measured quality delta]
        # for rows that ran the compressed-pool capacity A/B; the
        # slots column flags a budget bust loudly instead of hiding
        # it behind a bare ratio

        def _capacity_cols(row):
            ratio = row.get("slots_ratio")
            delta = row.get("quality_delta_int4",
                            row.get("quality_delta"))
            if ratio is None and delta is None:
                return None
            slots_at = None
            if ratio is not None:
                slots_at = f"{ratio}x" + (
                    "" if row.get("fits_budget", True) else "!budget")
            return [slots_at, delta]

        capacity = {
            name: cols
            for name, row in list(fam.items()) + [("serving", sv)]
            if isinstance(row, dict) and "skipped" not in row
            and "error" not in row
            and (name == "serving" or name.startswith(
                ("cb", "continuous_batching")))
            and (cols := _capacity_cols(row)) is not None}
        if capacity:
            s["serving_capacity"] = capacity
        # fleet columns (ISSUE 19 sat.) — sparse like the others:
        # [replicas, domains_killed, recovered_exactly_once] for rows
        # that drove the discrete-event fleet harness

        def _fleet_cols(row):
            n = row.get("fleet_replicas")
            if n is None:
                return None
            return [n, row.get("domains_killed"),
                    row.get("recovered_exactly_once")]

        fleet = {
            name: cols
            for name, row in list(fam.items()) + [("serving", sv)]
            if isinstance(row, dict) and "skipped" not in row
            and "error" not in row
            and (cols := _fleet_cols(row)) is not None}
        if fleet:
            s["serving_fleet"] = fleet
        # chip-tick cost columns (ISSUE 20 tentpole) — sparse:
        # [busy_chip_ticks, goodput_per_chip_tick, alert_latency_ticks]
        # for rows that ran the flight-recorder loop

        def _cost_cols(row):
            n = row.get("busy_chip_ticks")
            if n is None:
                return None
            return [n, row.get("goodput_per_chip_tick"),
                    row.get("alert_latency_ticks")]

        cost = {
            name: cols
            for name, row in list(fam.items()) + [("serving", sv)]
            if isinstance(row, dict) and "skipped" not in row
            and "error" not in row
            and (cols := _cost_cols(row)) is not None}
        if cost:
            s["serving_cost"] = cost
    elif isinstance(m, dict):
        s["model"] = {"error": str(m["error"])[:120]}

    sc = err_or(d.get("scheduler_scale_1024chip"), lambda n: {
        "cold_p50": n.get("cold", {}).get("p50_ms"),
        "steady_p50": n.get("steady_state", {}).get("p50_ms"),
        "loc": n.get("steady_state", {}).get("mean_allocation_locality"),
    })
    if sc:
        s["sched_1024"] = sc
    ms = err_or(d.get("scheduler_scale_multislice"), lambda n: {
        "p99": n.get("p99_ms"), "frac": n.get("multislice_fraction"),
        "loc": n.get("mean_allocation_locality"),
        # dominant tail phase, so the p99 headline carries its cause
        "p99_top": max(
            ((n.get("p99_phase_attribution") or {}).get("phases")
             or {}).items(),
            key=lambda kv: kv[1].get("share", 0.0), default=(None,))[0],
    })
    if ms:
        s["multislice"] = ms
    w = err_or(d.get("scheduler_wire"),
               lambda n: {"p50": n.get("p50_ms"), "max": n.get("max_ms")})
    if w:
        s["wire_ms"] = w
    sp = err_or(d.get("serve_pod"),
                lambda n: {"decode_tok_s": n.get("decode_tokens_per_s"),
                           "vs_lib": n.get("pod_vs_library")})
    if sp:
        s["serve_pod"] = sp
    return s


def run_full_bench(n_gangs: int = 60, seed: int = 0) -> dict:
    """The driver entry: scheduler bench + hardware model bench in one
    JSON document (details.model carries the MFU figure recorded in
    BASELINE.md).  KUBETPU_BENCH_MODEL=0 skips the model half;
    KUBETPU_BENCH_SERVE_POD=0 skips the scheduled-serving measurement
    (it is skipped off-TPU automatically — the CPU path is covered by
    the workload tests)."""
    out = run_bench(n_gangs=n_gangs, seed=seed)
    if os.environ.get("KUBETPU_BENCH_MODEL", "1") != "0":
        try:
            out["details"]["model"] = run_model_bench()
        except Exception as e:   # a broken chip must not hide metric #1
            out["details"]["model"] = {"error": str(e)}
    if os.environ.get("KUBETPU_BENCH_SCALE", "1") != "0":
        try:
            # cold = fresh process (ring-orientation memo empty: the
            # first 128/256-chip placements pay the geometry search);
            # steady = a second 500-gang run with warm geometry, the
            # regime a long-lived scheduler daemon actually operates in
            cold = run_scale_bench()
            steady = run_scale_bench(seed=1)
            out["details"]["scheduler_scale_1024chip"] = {
                "cold": {"p50_ms": cold["value"], **{
                    k: cold["details"][k] for k in
                    ("p90_ms", "p99_ms", "decisions",
                     "mean_allocation_locality")}},
                "steady_state": {"p50_ms": steady["value"], **{
                    k: steady["details"][k] for k in
                    ("p90_ms", "p99_ms", "decisions",
                     "mean_allocation_locality")}},
                "p99_phase_attribution": steady["details"].get(
                    "p99_phase_attribution"),
            }
        except Exception as e:
            out["details"]["scheduler_scale_1024chip"] = {"error": str(e)}
    if os.environ.get("KUBETPU_BENCH_MULTISLICE", "1") != "0":
        try:
            ms = run_multislice_bench()
            out["details"]["scheduler_scale_multislice"] = {
                "p50_ms": ms["value"], **{
                    k: ms["details"][k] for k in
                    ("p90_ms", "p99_ms", "decisions",
                     "mean_allocation_locality", "gangs_multislice",
                     "multislice_fraction", "p99_phase_attribution")}}
        except Exception as e:
            out["details"]["scheduler_scale_multislice"] = {
                "error": str(e)}
    if os.environ.get("KUBETPU_BENCH_WIRE", "1") != "0":
        try:
            out["details"]["scheduler_wire"] = run_wire_bench()
        except Exception as e:
            out["details"]["scheduler_wire"] = {"error": str(e)}
    if os.environ.get("KUBETPU_BENCH_SERVE_POD", "1") != "0":
        # a broken backend must not hide metric #1 either — the TPU
        # probe itself stays inside the guard (and JAX stays
        # uninitialized for scheduler-only runs)
        try:
            import jax

            if jax.devices()[0].platform.startswith(("tpu", "axon")):
                out["details"]["serve_pod"] = run_serve_pod_bench()
        except Exception as e:
            out["details"]["serve_pod"] = {"error": str(e)}
    return out

"""Standalone crishim node daemon: ``python -m kubegpu_tpu.crishim.serve``.

The reference's ``crishim main()`` (SURVEY.md §4.1): parse flags → load
the device plugin → start the CRI server on a unix socket → run the
kubeadvertise loop against the apiserver.  This is that binary for the
TPU stack: it connects to the HTTP apiserver façade
(``kubemeta/apiserver_http.py``), registers the node, serves the
CRI-shaped socket, and runs the kubelet-ish pod lifecycle — in its own
process, talking to the control plane over nothing but HTTP + the unix
socket, exactly like the reference deployment.

    python -m kubegpu_tpu.crishim.serve \
        --apiserver http://127.0.0.1:8901 \
        --backend mock --slice v4-8 \
        --cri-socket /tmp/kubetpu-cri.sock \
        --real-processes
"""

from __future__ import annotations

import argparse
import sys
import time

from kubegpu_tpu.kubemeta.controlplane import Conflict, NotFound


def build_agent(args):
    """Construct (api client, CRI server, node agent) from flags —
    split from main() so tests can drive the daemon in-process."""
    from kubegpu_tpu.crishim.agent import NodeAgent
    from kubegpu_tpu.crishim.criserver import CriServer, RemoteCriShim
    from kubegpu_tpu.crishim.runtime import FakeRuntime, SubprocessRuntime
    from kubegpu_tpu.kubemeta.apiserver_http import HttpApiClient
    from kubegpu_tpu.obs import global_registry
    from kubegpu_tpu.tpuplugin import LibtpuBackend, MockBackend

    api = HttpApiClient(args.apiserver)
    if args.backend == "mock":
        backend = MockBackend(args.slice, host_id=args.host_id)
    elif args.backend == "libtpu":
        backend = LibtpuBackend()
    else:
        raise ValueError(f"unknown backend {args.backend!r}")
    if args.real_processes:
        extra = dict(kv.split("=", 1) for kv in (args.env or []))
        runtime = SubprocessRuntime(extra_env=extra)
    else:
        runtime = FakeRuntime()
    node_name = backend.discover().node_name
    transport = getattr(args, "transport", "json")
    if transport.startswith("grpc"):
        from kubegpu_tpu.crishim.grpcserver import (
            GrpcCriServer,
            GrpcRemoteCriShim,
        )
        # "grpc" = runtime.v1 protobuf bodies (kubelet-compatible);
        # "grpc-json" keeps the r3 JSON-body behavior
        codec = "json" if transport == "grpc-json" else "proto"
        server = GrpcCriServer(api, backend, node_name, runtime,
                               socket_path=args.cri_socket,
                               codec=codec).start()
        shim = GrpcRemoteCriShim(server.socket_path, codec=codec)
    else:
        server = CriServer(api, backend, node_name, runtime,
                           socket_path=args.cri_socket).start()
        shim = RemoteCriShim(server.socket_path)
    agent = NodeAgent(api, backend, runtime,
                      metrics=global_registry, shim=shim)
    return api, server, agent


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="kubetpu-crishim",
        description="node daemon: CRI-shaped runtime socket + device "
        "advertiser + pod lifecycle (reference: crishim main())")
    ap.add_argument("--apiserver", required=True,
                    help="HTTP apiserver URL (kubemeta.apiserver_http)")
    ap.add_argument("--backend", default="mock",
                    choices=["mock", "libtpu"])
    ap.add_argument("--slice", default="v4-8",
                    help="mock backend slice type")
    ap.add_argument("--host-id", type=int, default=0,
                    help="mock backend host index within the slice")
    ap.add_argument("--transport", default="json",
                    choices=("json", "grpc", "grpc-json"),
                    help="CRI wire transport: length-prefixed JSON "
                         "frames, real gRPC with runtime.v1 protobuf "
                         "bodies, or gRPC with JSON bodies (fallback)")
    ap.add_argument("--cri-socket", default=None,
                    help="unix socket path for the CRI server "
                    "(default: a fresh temp path, printed at startup)")
    ap.add_argument("--real-processes", action="store_true",
                    help="launch real workload subprocesses")
    ap.add_argument("--env", action="append", metavar="K=V",
                    help="extra env for launched workloads, repeatable")
    ap.add_argument("--advertise-interval", type=float, default=5.0,
                    help="seconds between Node advertisement patches")
    ap.add_argument("--tick", type=float, default=0.2,
                    help="pod-lifecycle reconcile interval (seconds)")
    args = ap.parse_args(argv)

    api, server, agent = build_agent(args)
    backoff = args.tick
    while True:   # registration retries too: the apiserver may still be
        try:      # coming up when the daemon starts (concurrent boot)
            agent.register()
            break
        except (OSError, ValueError, Conflict, NotFound) as e:
            print(f"crishim: cannot register with {args.apiserver}, "
                  f"retrying in {backoff:.1f}s: {e}", file=sys.stderr)
            time.sleep(backoff)
            backoff = min(backoff * 2, 10.0)
    print(f"crishim: node {agent.node_name} registered; "
          f"CRI socket {server.socket_path}", file=sys.stderr)

    last_advertise = time.monotonic()
    backoff = args.tick
    try:
        while True:
            try:
                agent.run_once()
                agent.reap(timeout=0)
                now = time.monotonic()
                if now - last_advertise >= args.advertise_interval:
                    agent.advertise()
                    last_advertise = now
                backoff = args.tick
            except (OSError, ValueError, NotFound, Conflict) as e:
                # transient control-plane failure (apiserver restart,
                # connection reset, our Node object wiped): a
                # kubelet-shaped daemon backs off and retries — it must
                # NOT die and orphan its containers and registration
                print(f"crishim: control-plane error, retrying in "
                      f"{backoff:.1f}s: {e}", file=sys.stderr)
                time.sleep(backoff)
                backoff = min(backoff * 2, 10.0)
                if isinstance(e, NotFound):
                    try:   # Node object gone (apiserver state reset)
                        agent.register()
                    except Exception:
                        pass
                continue
            time.sleep(args.tick)
    except KeyboardInterrupt:
        pass
    finally:
        server.close()
        api.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())

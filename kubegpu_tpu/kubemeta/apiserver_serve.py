"""Standalone apiserver daemon: ``python -m kubegpu_tpu.kubemeta.apiserver_serve``.

The control-plane hub as its own process — the role kube-apiserver plays
in the reference deployment (SURVEY.md §2: scheduler and node agent
never talk directly; ALL coordination flows through here).  State is the
in-memory FakeApiServer behind the HTTP façade; scheduler daemon
(``scheduler/serve.py``) and node daemon (``crishim/serve.py``) connect
over nothing but this wire.

    python -m kubegpu_tpu.kubemeta.apiserver_serve --port 8901
"""

from __future__ import annotations

import argparse
import sys
import time

from kubegpu_tpu.kubemeta.apiserver_http import ApiServerHTTP
from kubegpu_tpu.kubemeta.controlplane import FakeApiServer


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="kubetpu-apiserver",
        description="HTTP apiserver façade as a standalone process")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8901)
    args = ap.parse_args(argv)

    server = ApiServerHTTP(FakeApiServer(), host=args.host,
                           port=args.port).start()
    # machine-greppable readiness line (tests/scripts wait for it)
    print(f"apiserver: listening on {server.address}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        server.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())

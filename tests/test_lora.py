"""LoRA adapters: zero-delta init, frozen base, training, serving merge,
GSPMD sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kubegpu_tpu.models import LlamaConfig, greedy_generate, llama_init
from kubegpu_tpu.models.llama import next_token_loss
from kubegpu_tpu.models.lora import (
    LoRAConfig,
    lora_init,
    lora_merge,
    lora_n_params,
    lora_param_specs,
    make_lora_train_step,
)


@pytest.fixture(scope="module")
def base():
    cfg = LlamaConfig.tiny()
    params = llama_init(jax.random.PRNGKey(0), cfg)
    return cfg, params


class TestLoRA:
    def test_zero_delta_at_init(self, base):
        cfg, params = base
        lcfg = LoRAConfig(rank=4)
        adapters = lora_init(jax.random.PRNGKey(1), params, lcfg)
        merged = lora_merge(params, adapters, lcfg)
        tokens = (jnp.arange(2 * 17, dtype=jnp.int32).reshape(2, 17)
                  ) % cfg.vocab_size
        l0 = float(next_token_loss(params, tokens, cfg))
        l1 = float(next_token_loss(merged, tokens, cfg))
        assert l0 == pytest.approx(l1, abs=1e-6)

    def test_adapters_are_tiny(self, base):
        cfg, params = base
        lcfg = LoRAConfig(rank=4)
        adapters = lora_init(jax.random.PRNGKey(1), params, lcfg)
        n_base = sum(x.size for x in jax.tree.leaves(params))
        assert lora_n_params(adapters) < 0.1 * n_base

    def test_training_moves_only_adapters(self, base):
        cfg, params = base
        lcfg = LoRAConfig(rank=4, targets=("wq", "wv", "w_down"))
        adapters = lora_init(jax.random.PRNGKey(2), params, lcfg)
        opt = optax.adam(1e-2)
        opt_state = opt.init(adapters)
        step = jax.jit(make_lora_train_step(cfg, lcfg, opt))
        tokens = (jnp.arange(4 * 17, dtype=jnp.int32).reshape(4, 17) * 5
                  ) % cfg.vocab_size
        first = None
        base_before = jax.tree.map(lambda x: np.asarray(x), params)
        for _ in range(6):
            adapters, opt_state, loss = step(adapters, opt_state,
                                             params, tokens)
            first = first if first is not None else float(loss)
        assert float(loss) < first          # it actually learns
        # the base never moved (frozen by construction)
        for a, b in zip(jax.tree.leaves(base_before),
                        jax.tree.leaves(params)):
            np.testing.assert_array_equal(a, np.asarray(b))
        # and the adapters did
        assert float(jnp.abs(adapters["wq"]["b"]).max()) > 0

    def test_merge_serves(self, base):
        """Merged adapters drop into the KV-cache decode unchanged."""
        cfg, params = base
        lcfg = LoRAConfig(rank=2)
        adapters = lora_init(jax.random.PRNGKey(3), params, lcfg)
        adapters = jax.tree.map(lambda x: x + 0.01, adapters)  # nonzero
        merged = lora_merge(params, adapters, lcfg)
        prompt = (jnp.arange(2 * 5, dtype=jnp.int32).reshape(2, 5)
                  ) % cfg.vocab_size
        out = greedy_generate(merged, prompt, 4, cfg)
        assert out.shape == (2, 4)

    def test_validation(self, base):
        with pytest.raises(ValueError, match="rank"):
            LoRAConfig(rank=0)
        with pytest.raises(ValueError, match="unknown LoRA targets"):
            LoRAConfig(targets=("wq", "nope"))

    def test_gspmd_sharded_step(self, base):
        """Adapters sharded on the 8-device mesh next to sharded base
        params: one jitted LoRA step, finite loss."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from kubegpu_tpu.models import llama_param_specs
        from kubegpu_tpu.parallel import make_mesh, named_sharding_tree
        from kubegpu_tpu.parallel.sharding import fit_spec

        cfg, params = base
        lcfg = LoRAConfig(rank=4)
        mesh = make_mesh({"dp": 2, "tp": 4})
        sharded_base = jax.device_put(
            params, named_sharding_tree(mesh, llama_param_specs(cfg)))
        adapters = jax.device_put(
            lora_init(jax.random.PRNGKey(4), params, lcfg),
            named_sharding_tree(mesh, lora_param_specs(lcfg)))
        opt = optax.adam(1e-2)
        opt_state = opt.init(adapters)
        step = jax.jit(make_lora_train_step(cfg, lcfg, opt, mesh),
                       donate_argnums=(0, 1))
        tokens = jax.device_put(
            (jnp.arange(4 * 17, dtype=jnp.int32).reshape(4, 17)
             ) % cfg.vocab_size,
            NamedSharding(mesh, fit_spec(mesh, P(("dp", "fsdp"), None))))
        adapters, opt_state, loss = step(adapters, opt_state,
                                         sharded_base, tokens)
        assert np.isfinite(float(loss))

    def test_specs_match_base_layout_for_row_parallel(self):
        """wo/w_down are megatron row-parallel (tp on the INPUT dim):
        their adapters must shard the same axes as the base weight or
        every step pays resharding collectives."""
        from jax.sharding import PartitionSpec as P
        lcfg = LoRAConfig(targets=("wq", "wo", "w_down"))
        specs = lora_param_specs(lcfg)
        assert specs["wq"]["a"] == P(None, "fsdp", None)
        assert specs["wq"]["b"] == P(None, None, "tp")
        assert specs["wo"]["a"] == P(None, "tp", None)
        assert specs["wo"]["b"] == P(None, None, "fsdp")
        assert specs["w_down"]["a"] == P(None, "tp", None)
        assert specs["w_down"]["b"] == P(None, None, "fsdp")

"""Deterministic fault injection for the serving stack (ISSUE 4).

The training side already treats hardware loss as routine (the health
controller evicts and re-places whole gangs); this module gives the
SERVING stack the same discipline by making failures reproducible: a
:class:`ChaosInjector` is a seeded schedule of :class:`ChaosEvent`\\ s
that an engine consults at every tick boundary.  Four fault kinds cover
the failure modes production TPU serving actually sees:

- ``kill_replica`` — the whole engine dies mid-tick (host preemption,
  slice revocation).  The engine raises :class:`ReplicaDeadError`;
  :class:`~kubegpu_tpu.models.serve.DataParallelServePool` catches it
  and re-admits every resident request onto healthy replicas via
  prefix-cache-accelerated replay.
- ``fail_dispatch`` — ONE dispatch fails transiently
  (:class:`DispatchFailure`); the engine retries it in place (the
  dispatch is functional, so a retry re-runs identical math) and only
  escalates to replica death after repeated failures.
- ``nan_logits`` — a slot's pool pages are poisoned with NaN, so that
  slot's logits go non-finite while its neighbors stay exact (slots
  are independent batch rows).  The engine's per-tick invalid-logit
  detector quarantines the slot and replays its request instead of
  letting the poison ride the batch.
- ``stall_tick`` — the tick sleeps past the engine's watchdog deadline
  (``tick_deadline_s``); the watchdog declares the replica stalled
  (:class:`TickStallError`, a :class:`ReplicaDeadError`) and the pool
  fails over exactly as for a kill.

Determinism contract: an injector is a pure function of its events (or
of ``from_seed``'s arguments), and every downstream recovery action is
greedy-replay bit-exact — so a chaos run must emit EXACTLY the
fault-free run's tokens, which is what ``tests/test_serve_chaos.py``
and the ``cb_chaos`` bench row assert.
"""

from __future__ import annotations

from dataclasses import dataclass, field


class ChaosError(RuntimeError):
    """Base class for injected serving faults."""


class ReplicaDeadError(ChaosError):
    """The engine is dead (killed, or declared dead by its watchdog);
    every subsequent ``step()`` re-raises.  The pool's failover path
    catches this, harvests the engine's host-side request state, and
    replays survivors on healthy replicas."""


class TickStallError(ReplicaDeadError):
    """Watchdog verdict: a tick exceeded ``tick_deadline_s``.  A
    subclass of :class:`ReplicaDeadError` because the recovery policy
    is identical — a replica that can stall once can wedge ``drain()``
    forever, so the pool fails over rather than waiting."""


class DispatchFailure(ChaosError):
    """A single dispatch failed transiently; the engine retries the
    same dispatch (safe: dispatches are functional) with a bounded
    budget before escalating to replica death."""


KILL = "kill_replica"
FAIL_DISPATCH = "fail_dispatch"
NAN_LOGITS = "nan_logits"
STALL = "stall_tick"
KINDS = (KILL, FAIL_DISPATCH, NAN_LOGITS, STALL)


@dataclass(frozen=True)
class ChaosEvent:
    tick: int            # engine tick (dispatch counter) to fire at
    kind: str            # one of KINDS
    stall_s: float = 0.0  # sleep injected for STALL events


@dataclass
class ChaosInjector:
    """Seeded, replayable fault schedule for ONE engine.

    ``take(tick)`` pops every event due at or before ``tick`` (events
    fire once); ``defer(ev, tick)`` re-queues an event the engine could
    not apply yet (e.g. a NaN injection with no eligible slot).  The
    ``fired`` log is the audit trail the bench row reports."""

    events: list = field(default_factory=list)
    fired: list = field(default_factory=list)

    def __post_init__(self) -> None:
        for ev in self.events:
            if ev.kind not in KINDS:
                raise ValueError(f"unknown chaos kind {ev.kind!r}")
        self.events = sorted(self.events, key=lambda e: e.tick)

    @classmethod
    def from_seed(cls, seed: int, ticks: int,
                  kinds: tuple = KINDS,
                  n_events: int = 1,
                  stall_s: float = 0.0) -> "ChaosInjector":
        """Draw ``n_events`` events uniformly over ``[1, ticks]`` from a
        seeded generator — the scenario-matrix entry point (same seed ⇒
        same schedule ⇒ same recovery sequence)."""
        import numpy as np
        rng = np.random.default_rng(seed)
        evs = [ChaosEvent(tick=int(rng.integers(1, max(ticks, 2))),
                          kind=str(rng.choice(list(kinds))),
                          stall_s=stall_s)
               for _ in range(n_events)]
        return cls(events=evs)

    def take(self, tick: int) -> list:
        due = [e for e in self.events if e.tick <= tick]
        if due:
            self.events = [e for e in self.events if e.tick > tick]
            self.fired.extend(due)
        return due

    def defer(self, ev: ChaosEvent, tick: int) -> None:
        self.fired.remove(ev)
        self.events.append(ChaosEvent(tick=tick, kind=ev.kind,
                                      stall_s=ev.stall_s))
        self.events.sort(key=lambda e: e.tick)

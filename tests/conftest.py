"""Test harness config: force an 8-device virtual CPU mesh BEFORE jax import.

Multi-chip TPU hardware is not available in CI; all sharding/pjit tests run
against ``xla_force_host_platform_device_count=8`` virtual CPU devices (the
same mechanism the driver's dryrun uses).  Must run before anything imports
jax, hence top of conftest.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

"""The CreateContainer interposition — reference: SURVEY.md §4.3.

Reference flow: kubelet → crishim → (read allocation annotation → device
manager → env/devices/mounts → rewrite ContainerConfig) → real runtime.
Identical here, with the TPU env payload in place of NVIDIA's.
"""

from __future__ import annotations

import json

from kubegpu_tpu.crishim.runtime import ContainerHandle, ContainerRuntime
from kubegpu_tpu.kubemeta import FakeApiServer, Pod
from kubegpu_tpu.kubemeta.codec import pod_allocation, pod_mesh_axes
from kubegpu_tpu.obs import get_logger
from kubegpu_tpu.obs.spans import TRACE_ANNOTATION, TRACE_ENV, SpanContext
from kubegpu_tpu.tpuplugin.backend import DeviceBackend

log = get_logger("crishim")


class CriShim:
    def __init__(self, api: FakeApiServer, backend: DeviceBackend,
                 node_name: str, runtime: ContainerRuntime,
                 tracer=None):
        self.api = api
        self.backend = backend
        self.node_name = node_name
        self.runtime = runtime
        # ISSUE 6: with a Tracer attached the shim records its env
        # injection as a span and re-parents the propagated token under
        # it, so engine spans hang off crishim.inject; without one the
        # annotation token passes through untouched
        self.tracer = tracer

    def _propagate_trace(self, pod: Pod, env: dict) -> None:
        """Copy the bind-time trace token from the pod annotation into
        the container env — the same road TPU_VISIBLE_CHIPS travels."""
        token = pod.metadata.annotations.get(TRACE_ANNOTATION)
        ctx = SpanContext.decode(token)
        if ctx is None:
            return
        if self.tracer is not None:
            with self.tracer.span(
                    "crishim.inject", parent=ctx,
                    attrs={"pod": pod.name,
                           "node": self.node_name}) as sp:
                token = sp.context.encode()
        env[TRACE_ENV] = token

    def create_container(self, pod: Pod,
                         container_index: int = 0) -> ContainerHandle:
        """Rewrite the container spec with the allocation's TPU env and
        forward to the runtime.  Pods with no allocation (0-device CPU
        fallback, BASELINE config 1) pass through with TPU visibility
        explicitly cleared."""
        spec = pod.spec.containers[container_index]
        alloc = pod_allocation(pod)
        env = dict(spec.env)
        if alloc is None or not alloc.chips:
            env["TPU_VISIBLE_CHIPS"] = ""
            env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
        else:
            if alloc.node_name != self.node_name:
                raise ValueError(
                    f"pod {pod.name} allocated to {alloc.node_name}, "
                    f"but this shim serves {self.node_name}")
            adv = self.backend.discover()
            by_local = {c.local_index: c for c in adv.chips}
            chips = [by_local[c.local_index] for c in alloc.chips]
            env.update(self.backend.allocate_env(
                chips,
                worker_id=alloc.worker_id,
                num_workers=alloc.num_workers,
                coordinator_address=alloc.coordinator_address,
                worker_hostnames=alloc.worker_hostnames,
            ))
            millis = {c.millichips for c in alloc.chips}
            if millis != {1000}:
                # fractional co-tenancy: the workload self-limits HBM use
                env["KUBETPU_MILLITPU"] = str(sum(c.millichips
                                                 for c in alloc.chips))
            # advertised capacity flows to the workload: serving picks
            # its model scale from the allocation, not from guesswork
            # (fractional grants scale the figure by their chip share)
            env["KUBETPU_HBM_GIB"] = str(round(sum(
                by_local[c.local_index].hbm_gib * c.millichips / 1000
                for c in alloc.chips), 3))
            # slice identity: a multislice gang's workers learn which
            # ICI domain they sit in (dp spans slices over DCN; the
            # slice id is the boundary a MEGASCALE-style runtime needs)
            env["KUBETPU_SLICE_ID"] = alloc.slice_id
            axes = pod_mesh_axes(pod)
            if axes:
                # close the loop: the mesh the allocator optimized
                # placement for IS the mesh the workload builds
                env["KUBETPU_MESH_AXES"] = json.dumps(list(axes.items()))
        self._propagate_trace(pod, env)
        log.info("create_container", pod=pod.name, node=self.node_name,
                 chips=len(alloc.chips) if alloc else 0,
                 worker_id=alloc.worker_id if alloc else None)
        return self.runtime.create_container(
            pod.name, spec.name, spec.command, env)

"""Discrete-event FLEET harness (ISSUE 19): the real serving control
plane over simulated cost-model replicas.

Every chaos guarantee so far (exactly-once failover, preempt/drain
bit-exactness, SLO-driven autoscaling) was proven at 1–4 real engines
— too small for the failure modes that actually dominate a fleet:
correlated loss of a whole slice/rack/zone, rolling upgrade waves, and
the control plane itself dying mid-trace.  This module scales the
PROOF without scaling the hardware:

- :class:`SimReplicaEngine` is a cost model with the FULL
  ``ContinuousBatcher`` surface the pool layer touches (admission
  queue, slot residency, paged-pool accounting, prefix registry,
  chaos consult, orphan stash, export/import for disagg migration).
  Costs are calibrated from real bench rows
  (:meth:`ReplicaCosts.from_bench` reads ``BENCH_r0x.json``).  Tokens
  are a pure function of the full token sequence so far — a running
  ``zlib.crc32`` over the int32 byte stream — so a failover replay
  submitted as ``prompt ++ accepted`` continues BIT-EXACTLY, which is
  the property every exactly-once gate leans on.
- :class:`FleetPool` / :class:`FleetDisaggPool` are the REAL
  :class:`~kubegpu_tpu.models.serve.DataParallelServePool` /
  ``DisaggServePool`` with ONLY the engine factory overridden: every
  routing, admission, failover, drain, and autoscale line above the
  engine runs unmodified over 100+ simulated replicas.
- :func:`run_fleet` drives seeded diurnal/flash-crowd traces
  (extended ``loadgen``) through three robustness layers: correlated
  failure-domain chaos (``DomainChaosInjector`` — whole-domain kills,
  watch-delivery delay/duplication/reorder/partition with stale
  reads), :class:`UpgradeWaveController` rolling upgrades (drain-wave
  retires through the standing replay parking, surge budget holds a
  capacity floor), and :class:`ControlPlaneJournal` crash recovery
  (append-only host-state log; a mid-trace control-plane kill rebuilds
  the pool and re-drives every in-flight request through the standing
  replay machinery in strict tier order — no lost, no duplicated, no
  tier inversion, outcomes identical to an uninterrupted twin).

Determinism: the trace, the chaos schedule, and every token are pure
functions of seeds; wall-clock never orders anything.  The
``cb_fleet_chaos`` bench row gates on exactly that.
"""
from __future__ import annotations

import glob
import json
import os
import time
import zlib
from collections import OrderedDict, deque
from dataclasses import dataclass, field

import numpy as np

from kubegpu_tpu.loadgen import (LoadReport, TierSpec, _busy,
                                 _slo_met, score_run)
from kubegpu_tpu.models.serve import (DataParallelServePool,
                                      DisaggServePool,
                                      _AdmissionQueue, _Request)
from kubegpu_tpu.obs.chaos import (DOMAIN_EVICT, DOMAIN_KILL,
                                   FAIL_DISPATCH, KILL, NAN_LOGITS,
                                   STALL, WATCH_DELAY, WATCH_DUP,
                                   WATCH_PARTITION, WATCH_REORDER,
                                   ChaosEvent, ChaosInjector,
                                   ReplicaDeadError, TickStallError)
from kubegpu_tpu.obs.cost import CostLedger

__all__ = ["ReplicaCosts", "FleetConfig", "SimReplicaEngine",
           "FleetPool", "FleetDisaggPool", "FleetTopology",
           "UpgradeWaveController", "ControlPlaneJournal",
           "FleetReport", "run_fleet", "compare_outcomes"]


# -- calibration --------------------------------------------------------

@dataclass(frozen=True)
class ReplicaCosts:
    """Per-replica cost model, calibrated from REAL bench rows: one
    decode stride-block's wall time, prefill throughput, and the
    page-chain migration handoff.  These drive the simulated wall
    clock (``sim_ms`` — reported as weather) and the prefill tick
    count (deterministic, and what affinity routing saves)."""
    block_ms: float = 2.0
    prefill_ms_per_token: float = 0.01
    migration_ms: float = 0.5

    @classmethod
    def from_bench(cls, root: str = ".") -> "ReplicaCosts":
        """Best-effort calibration from ``BENCH_r0x.json`` serving
        rows (``prefill_ms`` / ``prefill_tokens_per_s`` /
        ``decode_tokens_per_s`` at a known batch); missing files or
        keys fall back to the defaults — calibration changes the
        weather numbers, never the deterministic schedule."""
        block_ms = cls.block_ms
        prefill = cls.prefill_ms_per_token
        for path in sorted(glob.glob(os.path.join(root,
                                                  "BENCH_r0*.json"))):
            try:
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            sv = ((((doc.get("parsed") or {}).get("details") or {})
                   .get("model") or {}).get("serving") or {})
            tps = sv.get("decode_tokens_per_s")
            batch = sv.get("batch")
            if tps and batch:
                block_ms = 1000.0 * float(batch) / float(tps)
            ptps = sv.get("prefill_tokens_per_s")
            if ptps:
                prefill = 1000.0 / float(ptps)
        return cls(block_ms=block_ms, prefill_ms_per_token=prefill,
                   migration_ms=cls.migration_ms)


@dataclass(frozen=True)
class FleetConfig:
    """Simulated replica shape — the knobs the pool layer reads
    (``page_size``/``total_pages`` feed routing and autoscale
    headroom) plus the cost model."""
    vocab: int = 64
    n_slots: int = 4
    page_size: int = 4
    total_pages: int = 96
    max_len: int = 96
    registry_cap: int = 64
    page_bytes: int = 2048
    prefill_tokens_per_tick: int = 8
    costs: ReplicaCosts = ReplicaCosts()


def _next_token(crc: int, vocab: int) -> int:
    """The simulated model: next token = f(running crc32 of the full
    int32 byte stream so far).  ``crc32(b, crc32(a)) == crc32(a+b)``,
    so a replay submitted as ``prompt ++ accepted`` resumes the SAME
    running state a fault interrupted — greedy replay is bit-exact by
    construction, exactly like the real engine."""
    return crc % (vocab - 1) + 1


# -- the simulated replica ---------------------------------------------

class SimReplicaEngine:
    """Cost-model replica with the ``ContinuousBatcher`` surface the
    pool/autoscaler/loadgen layers touch.  Admission is strict-tier
    (FIFO within a tier via ``seq``) from a sorted
    ``_AdmissionQueue``; prefill costs ticks proportional to
    NON-CACHED prompt tokens (prefix-registry hits shorten it — the
    effect affinity routing exploits); decode emits one token per
    resident slot per tick.  The engine consults its per-replica
    :class:`~kubegpu_tpu.obs.chaos.ChaosInjector` at every tick
    boundary with the real engine's contract: kills raise
    :class:`ReplicaDeadError` AFTER the tick's finishers moved to the
    orphan stash (exactly-once), NaN quarantine re-queues the victim
    as prompt + accepted, dispatch failures retry in place."""

    def __init__(self, cfg: FleetConfig, metrics=None, chaos=None):
        self.cfg = cfg
        self.paged = True
        self.prefix_cache_enabled = True
        self.page_size = cfg.page_size
        self.total_pages = cfg.total_pages
        self.n_slots = cfg.n_slots
        self.max_len = cfg.max_len
        self.spec_gamma = 0
        self.eos_id = None
        self.dead: str | None = None
        self.chaos = chaos
        self._metrics = metrics
        self._engine_anchor = None
        self.queue = _AdmissionQueue()
        self.slot_req: dict[int, object] = {}      # slot → _Request
        self._prefill_left: dict[int, int] = {}    # slot → ticks left
        self._slot_pages: dict[int, int] = {}
        self._crc: dict[int, int] = {}             # local rid → state
        self._prefix_cache: OrderedDict = OrderedDict()
        self._prefilling: dict = {}                # loadgen._busy probe
        self._failed: list = []
        self._orphans: list = []
        self._exports: dict[int, dict] = {}
        self._migrate_out: set[int] = set()
        self._next_rid = 0
        self._seq = 0
        self._tick = 0
        self._step_count = 0
        # accounting surface the pool aggregates
        self.emitted_tokens = 0
        self.prefill_waves = 0
        self.slot_steps = 0
        self._decode_tokens = 0
        self.stall_ms: list[float] = []
        self.slots_quarantined = 0
        self.dispatch_failures = 0
        self.requests_retried = 0
        self.requests_shed = 0
        self.requests_preempted = 0
        self.requests_resumed = 0
        self.deadline_misses = 0
        self.shed_by_reason: dict[str, int] = {}
        self.spec_drafts_proposed = 0
        self.spec_drafts_accepted = 0
        self.hbm_peak_bytes = 0
        self.sim_ms = 0.0           # cost-model wall clock (weather)
        # chip-tick attribution (ISSUE 20): one chip-tick per busy
        # engine tick (tp=1 in the sim), charged pro-rata by work
        # units to the resident (tenant, tier) keys; busy_ticks is
        # the independent counter the conservation law checks against
        self.cost = CostLedger()
        self.busy_ticks = 0
        # audit trail for the tier-ordering gate: (tick, tier, seq)
        # per admission, plus a counter that trips if an admission
        # ever jumps a strictly-more-critical queued request
        self.admission_log: list[tuple[int, int, int]] = []
        self.tier_inversions = 0

    # -- capacity ------------------------------------------------------

    def _pages_for(self, t: int, remaining: int) -> int:
        return -(-(t + remaining) // self.page_size)

    def _available_pages(self) -> int:
        return self.total_pages - sum(self._slot_pages.values())

    @property
    def hbm_pool_bytes(self) -> int:
        return ((self.total_pages - self._available_pages())
                * self.cfg.page_bytes)

    def warmup(self) -> None:
        return None

    # -- submit --------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               temperature: float = 0.0,
               deadline_s: float | None = None,
               migrate_out: bool = False, tier: int = 0,
               tenant: str = "",
               deadline_ticks: int | None = None) -> int:
        if self.dead is not None:
            raise ReplicaDeadError(self.dead)
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if tier < 0:
            raise ValueError(f"tier must be >= 0, got {tier}")
        prompt_np = np.asarray(prompt, np.int32)
        t = int(prompt_np.shape[0])
        if t < 1:
            raise ValueError("prompt must have at least one token")
        if t + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt {t} + max_new {max_new_tokens} > "
                f"max_len {self.max_len}")
        if self._pages_for(t, max_new_tokens) > self.total_pages:
            raise ValueError(
                f"request needs {self._pages_for(t, max_new_tokens)} "
                f"pages but the pool has only {self.total_pages}")
        # SAME chain-hash scheme as the real engine/pool router
        n_cacheable = (t - 1) // self.page_size
        keys = tuple(
            hash(prompt_np[:(i + 1) * self.page_size].tobytes())
            for i in range(n_cacheable))
        req = _Request(rid=self._next_rid, prompt_len=t,
                       max_new_tokens=max_new_tokens,
                       temperature=float(temperature),
                       prefix_keys=keys, prompt=prompt_np,
                       admit_len=t, tier=int(tier),
                       tenant=str(tenant), seq=self._seq)
        req.submit_tick = self._tick
        if deadline_ticks is not None:
            req.deadline_tick = self._step_count + int(deadline_ticks)
        self._next_rid += 1
        self._seq += 1
        if migrate_out:
            self._migrate_out.add(req.rid)
        self.queue.append((req, prompt_np))
        return req.rid

    # -- cancel / orphan / export surface ------------------------------

    def _release(self, slot: int, req) -> None:
        self._slot_pages.pop(slot, None)
        self._prefill_left.pop(slot, None)
        self._crc.pop(req.rid, None)

    def cancel(self, rid: int, reason: str = "canceled"):
        for i, (r, _) in enumerate(self.queue):
            if r.rid == rid:
                del self.queue[i]
                r.done, r.error = True, reason
                return r
        for slot, r in list(self.slot_req.items()):
            if r.rid == rid:
                self.slot_req.pop(slot)
                self._release(slot, r)
                r.done, r.error = True, reason
                return r
        return None

    def take_orphans(self) -> list:
        out, self._orphans = self._orphans, []
        return out

    def take_export(self, rid: int) -> dict | None:
        return self._exports.pop(rid, None)

    def import_chain(self, export: dict, max_new_tokens: int,
                     temperature: float = 0.0, tier: int = 0,
                     tenant: str = "") -> int | None:
        """Adopt a migrated chain (sim format: running crc travels
        with the first token, so decode resumes bit-exactly).  Returns
        the local rid or None when no slot/pages are free."""
        if self.dead is not None:
            raise ReplicaDeadError(f"replica dead: {self.dead}")
        if max_new_tokens < 2:
            raise ValueError(
                "import_chain needs max_new_tokens >= 2 — a satisfied "
                "request retires at its prefill replica")
        if int(export["page_size"]) != self.page_size:
            raise ValueError(
                f"page-size mismatch: chain {export['page_size']} vs "
                f"pool {self.page_size}")
        t = int(export["t"])
        need = self._pages_for(t, max_new_tokens)
        slot = next((s for s in range(self.n_slots)
                     if s not in self.slot_req), None)
        if slot is None or self._available_pages() < need:
            return None
        req = _Request(rid=self._next_rid, prompt_len=t,
                       max_new_tokens=max_new_tokens,
                       temperature=float(temperature),
                       prefix_keys=tuple(export["keys"]),
                       prompt=np.asarray(export["prompt_np"],
                                         np.int32),
                       admit_len=t, tier=int(tier),
                       tenant=str(tenant), seq=self._seq)
        req.tokens = list(export["tokens"])
        req.submit_tick = self._tick
        req.first_tick = self._tick
        self._next_rid += 1
        self._seq += 1
        self.slot_req[slot] = req
        self._slot_pages[slot] = need
        self._crc[req.rid] = int(export["crc"])
        self._register_keys(req.prefix_keys)
        self.sim_ms += self.cfg.costs.migration_ms
        return req.rid

    # -- the tick ------------------------------------------------------

    def _registry_hit(self, keys: tuple) -> int:
        hit = 0
        for k in keys:
            if k not in self._prefix_cache:
                break
            self._prefix_cache.move_to_end(k)
            hit += 1
        return hit

    def _register_keys(self, keys: tuple) -> None:
        for k in keys:
            self._prefix_cache[k] = True
            self._prefix_cache.move_to_end(k)
        while len(self._prefix_cache) > self.cfg.registry_cap:
            self._prefix_cache.popitem(last=False)

    def _quarantine_one(self) -> None:
        """NaN-poison response: re-queue the lowest resident slot's
        request as prompt + accepted (the engine-internal replay)."""
        if not self.slot_req:
            return
        slot = min(self.slot_req)
        req = self.slot_req.pop(slot)
        self._release(slot, req)
        replay = (np.concatenate([req.prompt,
                                  np.asarray(req.tokens, np.int32)])
                  if req.tokens else req.prompt)
        req.admit_len = int(replay.shape[0])
        req.retries += 1
        self.slots_quarantined += 1
        self.requests_retried += 1
        self.queue.append((req, replay))

    def step(self) -> list:
        if self.dead is not None:
            raise ReplicaDeadError(self.dead)
        kill_ev = None
        if self.chaos is not None:
            for ev in self.chaos.take(self._tick):
                if ev.kind == FAIL_DISPATCH:
                    # transient: the retry re-runs identical math
                    self.dispatch_failures += 1
                elif ev.kind == NAN_LOGITS:
                    if self.slot_req:
                        self._quarantine_one()
                    else:
                        self.chaos.defer(ev, self._tick + 1)
                elif ev.kind in (KILL, STALL):
                    kill_ev = ev
        finished: list = []
        # admission: strict tier, FIFO within (deadline_tick, seq) —
        # sorted rebuild keeps the _AdmissionQueue token counter exact
        if self.queue:
            items = sorted(self.queue, key=lambda it: (
                it[0].tier,
                it[0].deadline_tick if it[0].deadline_tick is not None
                else 1 << 62,
                it[0].seq))
            self.queue.clear()
            self.queue.extend(items)
        while self.queue and len(self.slot_req) < self.n_slots:
            req, pnp = self.queue[0]
            need = self._pages_for(req.admit_len, req.remaining_new)
            if need > self._available_pages():
                break   # strict head-of-line: never jump the order
            self.queue.popleft()
            if any(q.tier < req.tier for q, _ in self.queue):
                self.tier_inversions += 1   # must never happen
            # ktp: allow(KTP005) lifetime: one fleet run — engine dies with its pool
            self.admission_log.append((self._tick, req.tier, req.seq))
            slot = next(s for s in range(self.n_slots)
                        if s not in self.slot_req)
            self.slot_req[slot] = req
            self._slot_pages[slot] = need
            self._crc[req.rid] = zlib.crc32(pnp.tobytes())
            hit = self._registry_hit(req.prefix_keys)
            cold = max(1, req.admit_len - hit * self.page_size)
            self._prefill_left[slot] = -(-cold
                                         // self.cfg
                                         .prefill_tokens_per_tick)
            self.prefill_waves += 1
            self.sim_ms += cold * self.cfg.costs.prefill_ms_per_token
            self._register_keys(req.prefix_keys)
        # prefill progress + decode: one token per READY slot per tick
        if self.slot_req:
            self.sim_ms += self.cfg.costs.block_ms
            # chip-tick attribution (ISSUE 20), charged BEFORE the
            # decode loop consumes _prefill_left so a prefilling
            # slot's weight is its prefill work this tick
            self.busy_ticks += 1
            self.cost.charge(
                [(r.tenant, r.tier,
                  self.cfg.prefill_tokens_per_tick
                  if self._prefill_left.get(s, 0) > 0 else 1)
                 for s, r in sorted(self.slot_req.items())], 1)
        for slot in sorted(self.slot_req):
            req = self.slot_req[slot]
            if self._prefill_left.get(slot, 0) > 0:
                self._prefill_left[slot] -= 1
                if self._prefill_left[slot] > 0:
                    continue
                self._prefill_left.pop(slot)
                if req.first_tick < 0:
                    req.first_tick = self._tick
            crc = self._crc[req.rid]
            tok = _next_token(crc, self.cfg.vocab)
            self._crc[req.rid] = zlib.crc32(
                np.int32(tok).tobytes(), crc)
            req.tokens.append(tok)
            if req.first_tick < 0:
                req.first_tick = self._tick
            self.emitted_tokens += 1
            self._decode_tokens += 1
            self.slot_steps += 1
            if len(req.tokens) >= req.max_new_tokens:
                req.done = True
                req.finish_tick = self._tick
                self.slot_req.pop(slot)
                if req.rid in self._migrate_out:
                    self._migrate_out.discard(req.rid)
                    self._exports[req.rid] = {
                        "page_size": self.page_size,
                        "t": req.admit_len,
                        "pages": self._slot_pages.get(slot, 0),
                        "prompt_np": req.prompt,
                        "tokens": list(req.tokens),
                        "crc": self._crc[req.rid],
                        "keys": req.prefix_keys,
                    }
                self._release(slot, req)
                finished.append(req)
        self.hbm_peak_bytes = max(self.hbm_peak_bytes,
                                  self.hbm_pool_bytes)
        self._tick += 1
        self._step_count += 1
        if kill_ev is not None:
            # finishers of the dying step go to the orphan stash so
            # the pool's failover NEVER replays a completed request
            self._orphans.extend(finished)
            self.dead = f"chaos {kill_ev.kind} at tick {self._tick - 1}"
            if kill_ev.kind == STALL:
                raise TickStallError(self.dead)
            raise ReplicaDeadError(self.dead)
        return finished


# -- the fleet pools ----------------------------------------------------

class _SimEngineFactory:
    """Override of the pool's single engine-construction seam: every
    routing/admission/failover/autoscale line above runs unmodified."""

    def _build_engine(self, i: int):
        return SimReplicaEngine(self._cfg, metrics=self._metrics,
                                chaos=self._chaos.get(i))


class FleetPool(_SimEngineFactory, DataParallelServePool):
    """The REAL DataParallelServePool over simulated replicas.
    ``max_replicas`` caps total replica identities (device blocks are
    virtual ints here) so autoscale/upgrade surge has room."""

    def __init__(self, cfg: FleetConfig | None = None, dp: int = 1,
                 max_replicas: int | None = None, metrics=None,
                 chaos=None, routing: str = "affinity",
                 max_replays: int = 2):
        cap = max(max_replicas or dp, dp)
        super().__init__(params=None, cfg=cfg or FleetConfig(),
                         dp=dp, tp=1, devices=list(range(cap)),
                         metrics=metrics, max_replays=max_replays,
                         chaos=chaos, routing=routing)


class FleetDisaggPool(_SimEngineFactory, DisaggServePool):
    """The REAL DisaggServePool (prefill/decode roles, page-chain
    migration) over simulated replicas."""

    def __init__(self, cfg: FleetConfig | None = None,
                 prefill: int = 1, decode: int = 1,
                 max_replicas: int | None = None, metrics=None,
                 chaos=None, routing: str = "affinity",
                 max_replays: int = 2):
        n = prefill + decode
        cap = max(max_replicas or n, n)
        super().__init__(None, cfg or FleetConfig(),
                         prefill=prefill, decode=decode, tp=1,
                         devices=list(range(cap)),
                         metrics=metrics, max_replays=max_replays,
                         chaos=chaos, routing=routing)


# -- topology -----------------------------------------------------------

class FleetTopology:
    """Replica → failure-domain map (slice/rack/zone — one level; the
    DOMAIN is the correlated-failure unit).  Replicas added later
    (autoscale backfill, upgrade surge) are assigned via
    :meth:`assign`."""

    def __init__(self, domains: dict[str, list[int]]):
        self.domains = {name: list(m) for name, m in domains.items()}

    @classmethod
    def grid(cls, n_replicas: int, n_domains: int,
             kind: str = "rack") -> "FleetTopology":
        per = -(-n_replicas // n_domains)
        doms = {}
        for d in range(n_domains):
            members = list(range(d * per, min((d + 1) * per,
                                              n_replicas)))
            if members:
                doms[f"{kind}{d}"] = members
        return cls(doms)

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(self.domains)

    def members(self, name: str) -> list[int]:
        return list(self.domains.get(name, ()))

    def assign(self, replica: int, name: str) -> None:
        self.domains.setdefault(name, [])
        if replica not in self.domains[name]:
            self.domains[name].append(replica)

    def domain_of(self, replica: int) -> str | None:
        for name, members in self.domains.items():
            if replica in members:
                return name
        return None


# -- watch channel (health-delivery weather) ----------------------------

class _WatchChannel:
    """Health-watch delivery channel between the chaos layer and
    ``pool.observe_gang_eviction`` — the seam where watch-scope chaos
    (delay, duplication, reorder, partition/stale-reads) is injected.
    Deliveries are (due_tick, issue_seq) ordered; a partition buffers
    everything until heal — the stale-read window where routing still
    targets condemned replicas."""

    def __init__(self, pool):
        self.pool = pool
        self._pending: list[tuple[int, int, str, str]] = []
        self._issue_seq = 0
        self._windows: list[tuple[int, str, int]] = []
        self._partition_until = -1
        self.delivered = 0

    def apply(self, ev, tick: int) -> None:
        until = tick + max(1, int(ev.duration_ticks))
        if ev.kind == WATCH_DELAY:
            self._windows.append((until, "delay",
                                  max(0, int(ev.delay_ticks))))
        elif ev.kind == WATCH_DUP:
            self._windows.append((until, "dup", max(1, int(ev.dup))))
        elif ev.kind == WATCH_REORDER:
            self._windows.append((until, "reorder", 1))
        elif ev.kind == WATCH_PARTITION:
            self._partition_until = max(self._partition_until, until)

    def _active(self, tick: int, kind: str, default: int) -> int:
        vals = [v for until, k, v in self._windows
                if k == kind and tick < until]
        return max(vals) if vals else default

    def emit(self, tick: int, gang: str, reason: str) -> None:
        delay = self._active(tick, "delay", 0)
        for _ in range(self._active(tick, "dup", 1)):
            self._pending.append((tick + delay, self._issue_seq,
                                  gang, reason))
            self._issue_seq += 1

    def pump(self, tick: int) -> None:
        if tick < self._partition_until:
            return   # partitioned: stale reads until heal
        due = [p for p in self._pending if p[0] <= tick]
        if not due:
            return
        self._pending = [p for p in self._pending if p[0] > tick]
        due.sort(key=lambda p: (p[0], p[1]),
                 reverse=bool(self._active(tick, "reorder", 0)))
        for _, _, gang, reason in due:
            # duplicates / late deliveries for already-failed-over
            # replicas are idempotent no-ops inside the pool
            self.pool.observe_gang_eviction(gang, reason)
            self.delivered += 1

    @property
    def idle(self) -> bool:
        return not self._pending


# -- rolling upgrades ---------------------------------------------------

class UpgradeWaveController:
    """Drain-wave rolling upgrade: retire each failure domain's
    replicas in domain-sized batches through the pool's replay-parking
    drain, with a SURGE budget (extra new-generation replicas added
    first) so live capacity never drops below ``floor``.  Retired
    replicas are backfilled by new-generation replicas at wave end, so
    the fleet exits every wave at nominal size, fully upgraded."""

    def __init__(self, pool, topology: FleetTopology, *, floor: int,
                 surge: int = 1, start_tick: int = 0,
                 gang_namer=None, metrics=None):
        self.pool = pool
        self.topology = topology
        self.floor = int(floor)
        self.surge = int(surge)
        self.start_tick = int(start_tick)
        self._waves = deque((name, list(members))
                            for name, members in
                            topology.domains.items())
        self._phase = "idle"
        self._targets: list[int] = []
        self._retiring: list[int] = []
        self._wave_name = ""
        self._credit = 0        # surge replicas not yet consumed
        self._gen_serial = 0
        self.waves_done = 0
        self.upgraded: list[int] = []
        self.min_alive: int | None = None
        self._namer = gang_namer or (
            lambda k: f"fleet/upgrade-g{k}")
        self._metrics = metrics

    @property
    def done(self) -> bool:
        return not self._waves and self._phase == "idle"

    def _add_new_gen(self, domain: str) -> int:
        gang = self._namer(self._gen_serial)
        self._gen_serial += 1
        i = self.pool.add_replica(gang=gang)
        self.topology.assign(i, f"{domain}@gen1")
        self.upgraded.append(i)
        return i

    def on_tick(self, tick: int) -> None:
        alive = self.pool._alive()
        self.min_alive = (len(alive) if self.min_alive is None
                          else min(self.min_alive, len(alive)))
        if tick < self.start_tick or self.done:
            return
        if self._phase == "idle":
            name, members = self._waves[0]
            self._wave_name = name
            self._targets = [i for i in members
                             if i not in self.pool.dead_replicas]
            self._retiring = []
            if not self._targets:
                self._waves.popleft()
                return
            # surge FIRST: capacity may never dip below the floor
            # while a domain-sized batch drains.  The surge replicas
            # are a CREDIT against later backfill, so the wave still
            # exits at nominal fleet size.
            want = min(self.surge, len(self._targets))
            for _ in range(want):
                self._add_new_gen(name)
                self._credit += 1
            self._phase = "retire"
            return
        if self._phase == "retire":
            alive_n = len(self.pool._alive())
            budget = max(0, alive_n - self.floor)
            batch = [i for i in self._targets[:budget]]
            if not batch:
                return   # wait for drains to free budget
            for i in batch:
                self.pool.retire_replica(i)
                self._retiring.append(i)
            self._targets = self._targets[len(batch):]
            self._phase = "wait"
            return
        if self._phase == "wait":
            if any(i not in self.pool.dead_replicas
                   for i in self._retiring):
                return   # still draining through replay parking
            # backfill AS EACH BATCH DRAINS (consuming surge credit
            # first) — waiting until wave end would starve the retire
            # budget whenever a batch drains capacity down to the
            # floor exactly, wedging the wave
            drained = len(self._retiring)
            self._retiring = []
            use = min(self._credit, drained)
            self._credit -= use
            for _ in range(drained - use):
                self._add_new_gen(self._wave_name)
            if self._targets:
                self._phase = "retire"
                return
            self._waves.popleft()
            self.waves_done += 1
            if self._metrics is not None:
                self._metrics.inc("serve_upgrade_waves_total")
            self._phase = "idle"


# -- control-plane journal ---------------------------------------------

class ControlPlaneJournal:
    """Append-only control-plane log: the request ledger (submit /
    finish per global rid, with tier), routing placements, scale
    actions, and crash/recovery marks.  Recovery = rebuild the pool at
    the journaled size and re-drive every in-flight request (submitted
    minus finished) through the standing replay machinery in strict
    ``(tier, rid)`` order — tier ordering survives the crash by
    construction, and the deterministic token function makes the
    recovered outcomes identical to an uninterrupted twin."""

    def __init__(self):
        self.records: list[dict] = []

    def append(self, kind: str, **payload) -> dict:
        rec = {"kind": kind, **payload}
        self.records.append(rec)
        return rec

    def _rids(self, kind: str) -> set:
        return {r["gid"] for r in self.records
                if r["kind"] == kind and "gid" in r}

    def inflight(self) -> list[int]:
        """Submitted-but-unfinished global rids, the re-drive set."""
        return sorted(self._rids("submit") - self._rids("finish"))

    def counts(self) -> dict:
        out: dict[str, int] = {}
        for r in self.records:
            out[r["kind"]] = out.get(r["kind"], 0) + 1
        return out


# -- the fleet driver ---------------------------------------------------

@dataclass
class FleetReport:
    """One fleet run's verdict: the standard goodput/SLO scoring
    (``load`` — scored by loadgen's own predicate) plus the
    fleet-layer audit trail the robustness gates assert on."""
    load: LoadReport
    replicas: int = 0
    domains: int = 0
    domain_kills: int = 0
    domain_evictions: int = 0
    killed_replicas: int = 0
    upgrade_waves: int = 0
    upgraded_replicas: int = 0
    recoveries: int = 0
    redriven: int = 0
    tier_inversions: int = 0
    min_alive: int = 0
    watch_delivered: int = 0
    journal_records: int = 0
    failovers: int = 0
    sim_ms: float = 0.0
    # chip-tick cost attribution (ISSUE 20): the fleet-wide ledger
    # (closed pools merged in), plus the independent busy-tick count
    # the exact conservation law is checked against
    busy_chip_ticks: int = 0
    busy_ticks: int = 0
    cost_by_key: dict = field(default_factory=dict)

    def cost_summary(self) -> dict:
        """Goodput-per-chip-tick per (tenant, tier) — delegates to
        the scored :class:`LoadReport`, which carries the same ledger
        fields."""
        return self.load.cost_summary()


def compare_outcomes(a: LoadReport, b: LoadReport) -> dict:
    """Per-request outcome equality between two runs of the SAME
    trace: completion status, error-ness, and the full token stream
    must match request for request (rids are trace-stable).  SLO
    stamps are excluded on purpose — a failover replay lands later by
    design; what must never change is WHAT was generated."""
    ra = {r["rid"]: r for r in a.records}
    rb = {r["rid"]: r for r in b.records}
    mismatched = []
    for rid in sorted(set(ra) | set(rb)):
        x, y = ra.get(rid), rb.get(rid)
        if (x is None or y is None
                or x["completed"] != y["completed"]
                or (x["error"] is None) != (y["error"] is None)
                or list(x["tokens"]) != list(y["tokens"])):
            mismatched.append(rid)
    return {"identical": not mismatched,
            "mismatched": len(mismatched),
            "checked": len(set(ra) | set(rb))}


def run_fleet(trace: list[dict], tiers: tuple[TierSpec, ...], *,
              cfg: FleetConfig | None = None, replicas: int = 64,
              domains: int = 4, domain_kind: str = "rack",
              topology: FleetTopology | None = None, chaos=None,
              engine_chaos=None, upgrade: bool = False,
              upgrade_floor: int | None = None, upgrade_surge: int = 2,
              upgrade_start: int = 8, journal=None,
              crash_at: int | None = None, controller=None,
              metrics=None, routing: str = "affinity",
              max_replays: int = 4,
              max_ticks: int = 20_000) -> FleetReport:
    """Drive ``trace`` through the REAL pool code over ``replicas``
    simulated engines, open-loop, one ``pool.step()`` per tick, with
    the three ISSUE-19 robustness layers composed in:

    - ``chaos`` (a ``DomainChaosInjector``): domain kills mark every
      member engine dead in the SAME tick (the pool discovers them
      via its normal failover paths) and emit watch evictions through
      a delivery channel whose weather (delay/dup/reorder/partition)
      the injector also schedules; domain evictions travel ONLY via
      the watch — a delayed delivery is a stale-read window.
    - ``upgrade``: an :class:`UpgradeWaveController` rolls every
      domain through the replay-parking drain under a surge budget.
    - ``crash_at`` + ``journal``: at that tick the control plane dies
      — the pool object and all host state are discarded — and
      recovery rebuilds a fresh pool at the journaled size, re-driving
      every in-flight request in strict (tier, rid) order.

    Scoring goes through loadgen's own :func:`score_run`, so lost /
    duplicated / goodput mean exactly what they mean everywhere else.
    """
    cfg = cfg or FleetConfig()
    topo = topology or FleetTopology.grid(replicas, domains,
                                          domain_kind)
    gang_of: dict[int, str] = {}
    pool_gen = [0]

    def _mk_pool(dp: int):
        cap = dp + (upgrade_surge if upgrade else 0) + 8
        p = FleetPool(cfg, dp=dp, max_replicas=cap, metrics=metrics,
                      chaos=engine_chaos, routing=routing,
                      max_replays=max_replays)
        gang_of.clear()
        for i in range(dp):
            g = f"fleet/gen{pool_gen[0]}-g{i}"
            gang_of[i] = g
            p.bind_replica_gang(i, g)
        pool_gen[0] += 1
        return p

    pool = _mk_pool(replicas)
    watch = _WatchChannel(pool)
    upg = (UpgradeWaveController(pool, topo, floor=upgrade_floor
                                 or max(1, replicas - replicas
                                        // max(1, domains)),
                                 surge=upgrade_surge,
                                 start_tick=upgrade_start,
                                 metrics=metrics)
           if upgrade else None)

    meta: dict[int, dict] = {}      # global rid (trace idx) → item
    seen: dict[int, int] = {}
    done_map: dict[int, object] = {}
    rid_map: dict[int, int] = {}    # CURRENT pool rid → global rid
    rep = FleetReport(load=None, replicas=replicas,
                      domains=len(topo.names))
    min_alive = replicas
    tier_inv_closed = 0             # from pools already torn down
    failovers_closed = 0
    sim_ms_closed = 0.0
    cost_closed = CostLedger()      # chip-ticks of torn-down pools
    busy_ticks_closed = 0
    n_ok = n_fail = n_met = 0
    crashed = False
    i = 0
    tick = 0
    t0 = time.perf_counter()
    while tick < max_ticks:
        # 1. control-plane crash + journal recovery
        if (crash_at is not None and not crashed and tick >= crash_at
                and journal is not None):
            crashed = True
            journal.append("crash", tick=tick)
            alive_n = max(1, len(pool._alive()))
            tier_inv_closed += sum(e.tier_inversions
                                   for e in pool.replicas)
            failovers_closed += pool.failovers
            sim_ms_closed += sum(e.sim_ms for e in pool.replicas)
            # the chips the dead control plane's pool burned were
            # real spend: close its ledger into the run total so the
            # conservation law survives the crash boundary
            cost_closed.merge(pool.cost)
            busy_ticks_closed += pool.busy_ticks
            # the control plane is DEAD: pool, router digests, entry
            # ledger, watch channel — all host state is gone
            pool = _mk_pool(alive_n)
            watch = _WatchChannel(pool)
            topo = FleetTopology.grid(alive_n, domains, domain_kind)
            rid_map = {}
            rep.recoveries += 1
            if metrics is not None:
                metrics.inc("serve_ctrl_recoveries_total")
            # re-drive in-flight work through the STANDING submit
            # path, strict (tier, rid) order — no tier inversion
            # across the recovery boundary
            redo = sorted((g for g in meta if g not in done_map),
                          key=lambda g: (meta[g]["tier"], g))
            for g in redo:
                it = meta[g]
                prid = pool.submit(it["prompt"], it["max_new"],
                                   tier=it["tier"],
                                   tenant=it["tenant"])
                rid_map[prid] = g
                journal.append("resubmit", gid=g, tier=it["tier"],
                               tick=tick)
            rep.redriven += len(redo)
            journal.append("recovered", tick=tick,
                           replicas=alive_n, inflight=len(redo))
        # 2. correlated chaos
        if chaos is not None:
            for ev in chaos.take(tick):
                if ev.kind == DOMAIN_KILL:
                    rep.domain_kills += 1
                    if metrics is not None:
                        metrics.inc("serve_domain_kills_total")
                    for r_i in topo.members(ev.domain):
                        if (r_i < len(pool.replicas)
                                and r_i not in pool.dead_replicas
                                and pool.replicas[r_i].dead is None):
                            # schedule an engine-level kill at the
                            # member's CURRENT tick: the whole domain
                            # dies in this one pool step, but each
                            # death surfaces through the pool's
                            # normal failover discovery — exactly how
                            # a real correlated host loss lands
                            eng = pool.replicas[r_i]
                            if eng.chaos is None:
                                eng.chaos = ChaosInjector(events=[])
                            eng.chaos.events.append(ChaosEvent(
                                tick=eng._tick, kind=KILL))
                            rep.killed_replicas += 1
                            if r_i in gang_of:
                                watch.emit(tick, gang_of[r_i],
                                           f"domain {ev.domain} "
                                           f"killed")
                elif ev.kind == DOMAIN_EVICT:
                    rep.domain_evictions += 1
                    for r_i in topo.members(ev.domain):
                        if r_i in gang_of:
                            watch.emit(tick, gang_of[r_i],
                                       f"domain {ev.domain} evicted")
                else:
                    watch.apply(ev, tick)
        # 3. watch deliveries due this tick (weather applied)
        watch.pump(tick)
        # 4. arrivals — a submit that lands on a dead-but-undetected
        # replica (the stale-read window) fails like the real RPC
        # would; the arrival retries next tick, after failover
        while i < len(trace) and trace[i]["arrival_tick"] <= tick:
            item = trace[i]
            gid = i
            try:
                prid = pool.submit(item["prompt"], item["max_new"],
                                   tier=item["tier"],
                                   tenant=item["tenant"])
            except ReplicaDeadError:
                break
            rid_map[prid] = gid
            meta[gid] = item
            if journal is not None:
                journal.append("submit", gid=gid, tier=item["tier"],
                               tick=tick,
                               replica=pool._entries[prid].replica)
            i += 1
        # 5. one control-plane tick
        for r in pool.step():
            gid = rid_map.get(r.rid)
            if gid is None:
                continue
            seen[gid] = seen.get(gid, 0) + 1
            done_map[gid] = r
            if journal is not None:
                journal.append("finish", gid=gid, tick=tick,
                               error=r.error)
            if seen[gid] == 1:
                if r.error is not None:
                    n_fail += 1
                else:
                    n_ok += 1
                    if _slo_met(r, tiers[meta[gid]["tier"]]):
                        n_met += 1
        # 6. controllers
        if upg is not None:
            upg.on_tick(tick)
        if controller is not None:
            controller(tick, {
                "submitted": len(meta), "finished": n_ok,
                "failed": n_fail, "slo_met": n_met,
                "in_flight": len(meta) - len(done_map),
                "attainment": (n_met / n_ok) if n_ok else 1.0,
            })
        n_alive = len(pool._alive())
        min_alive = min(min_alive, n_alive)
        if metrics is not None:
            metrics.set_gauge("serve_fleet_replicas", float(n_alive))
        tick += 1
        if (i >= len(trace) and not _busy(pool)
                and (upg is None or upg.done) and watch.idle
                and not pool._pending_deaths
                and not pool._pending_retire):
            break
    wall = time.perf_counter() - t0
    if i < len(trace) or _busy(pool):
        raise RuntimeError(
            f"fleet run did not go idle within {max_ticks} ticks "
            f"({len(trace) - i} arrivals unsubmitted, "
            f"{len(pool._entries)} entries in flight)")
    rep.load = score_run(meta, seen, done_map, tiers, ticks=tick,
                         wall_s=wall)
    fleet_cost = cost_closed.merge(pool.cost)
    rep.busy_chip_ticks = fleet_cost.busy_chip_ticks
    rep.busy_ticks = busy_ticks_closed + pool.busy_ticks
    rep.cost_by_key = fleet_cost.as_dict()
    rep.load.busy_chip_ticks = fleet_cost.busy_chip_ticks
    rep.load.cost_by_key = dict(rep.cost_by_key)
    rep.load.publish(metrics)
    rep.tier_inversions = tier_inv_closed + sum(
        e.tier_inversions for e in pool.replicas)
    rep.failovers = failovers_closed + pool.failovers
    rep.sim_ms = sim_ms_closed + sum(e.sim_ms
                                     for e in pool.replicas)
    rep.min_alive = min_alive
    rep.watch_delivered = watch.delivered
    if upg is not None:
        rep.upgrade_waves = upg.waves_done
        rep.upgraded_replicas = len(upg.upgraded)
        if upg.min_alive is not None:
            rep.min_alive = min(rep.min_alive, upg.min_alive)
    if journal is not None:
        rep.journal_records = len(journal.records)
    return rep

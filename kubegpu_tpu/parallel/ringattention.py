"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Long-context support (SURVEY.md §6 "long-context"): Q stays put; K/V
blocks rotate around the ``sp`` ring via ``ppermute`` (ICI neighbor
exchange — exactly the traffic the allocator's ring-closure ordering makes
single-hop), with flash-style online-softmax accumulation so the full
sequence is never materialized on one chip.

Used under ``shard_map`` with sequences sharded along ``sp``; degenerates
to plain attention when the axis has size 1.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from kubegpu_tpu.ops.flash_attention import NEG_INF


def _block_attend(q, k, v, q_pos, k_pos, causal, scale):
    """One (q-block × kv-block) flash step → (o_partial, m, l).
    q: [B,H,Tq,D], k/v: [B,H,Tk,D]; positions are global token indices."""
    scores = jnp.einsum("bhtd,bhsd->bhts", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    m = scores.max(axis=-1, keepdims=True)
    # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be exp(0)
    m_safe = jnp.maximum(m, NEG_INF / 2)
    p = jnp.exp(scores - m_safe)
    p = jnp.where(scores <= NEG_INF / 2, 0.0, p)
    l = p.sum(axis=-1, keepdims=True)
    o = jnp.einsum("bhts,bhsd->bhtd", p, v.astype(jnp.float32))
    return o, m_safe, l


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = "sp", causal: bool = True) -> jax.Array:
    """Attention over a sequence sharded on ``axis_name``.

    Call under shard_map; q/k/v are the *local* sequence blocks
    [B, H, T_local, D] and the result is the local output block.  GQA via
    repeated kv heads (match head counts before sharding).
    """
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, h, t_local, d = q.shape
    scale = d ** -0.5
    q_pos = my_idx * t_local + jnp.arange(t_local)

    def step(i, carry):
        o_acc, m_acc, l_acc, k_cur, v_cur = carry
        # block currently held arrived from (my_idx + i) counter-ring-wise
        src = (my_idx - i) % axis_size
        k_pos = src * t_local + jnp.arange(t_local)
        o_p, m_p, l_p = _block_attend(q, k_cur, v_cur, q_pos, k_pos,
                                      causal, scale)
        m_new = jnp.maximum(m_acc, m_p)
        alpha = jnp.exp(m_acc - m_new)
        beta = jnp.exp(m_p - m_new)
        o_new = o_acc * alpha + o_p * beta
        l_new = l_acc * alpha + l_p * beta
        # rotate kv to the next rank (ring neighbor exchange on ICI)
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return o_new, m_new, l_new, k_nxt, v_nxt

    o0 = jnp.zeros((b, h, t_local, d), jnp.float32)
    m0 = jnp.full((b, h, t_local, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t_local, 1), jnp.float32)
    o, m, l, _, _ = jax.lax.fori_loop(
        0, axis_size, step, (o0, m0, l0, k, v))
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)


def make_sharded_ring_attention(mesh, axis_name: str = "sp",
                                causal: bool = True):
    """shard_map-wrapped ring attention: takes global [B,H,T,D] arrays
    sharded on T and returns the same."""
    from jax.sharding import PartitionSpec as P

    from kubegpu_tpu.parallel.sharding import fit_spec

    # batch stays sharded on (dp, fsdp) and heads on tp — only the
    # sequence axis rides the ring; a replicated in_spec would all-gather
    # the whole batch/heads onto every sp rank
    spec = fit_spec(mesh, P(("dp", "fsdp"), "tp", axis_name, None))
    fn = functools.partial(ring_attention, axis_name=axis_name,
                           causal=causal)
    from kubegpu_tpu.parallel.sharding import compat_shard_map
    return compat_shard_map(fn, mesh, in_specs=(spec, spec, spec),
                            out_specs=spec, check=False)

"""ICI mesh topology model — the foundation layer.

The reference (SURVEY.md §3 "Core types", expected ``types/types.go``) models
device topology as a hierarchical grouped-resource tree of path strings
(``gpugrp1/0/gpugrp0/0/gpu/0/cards``) because NVLink cliques are naturally
hierarchical.  TPU ICI is not a hierarchy — it is an explicit torus mesh — so
this layer models it as one: chip coordinates, per-axis wraparound, host
blocks, and a two-tier link graph (ICI intra-slice, DCN inter-host/inter-
slice).  Slice algebra (contiguous sub-torus enumeration) and locality scoring
(the ≥90% ICI-link-locality north-star metric, BASELINE.md) live here too.
"""

from kubegpu_tpu.topology.mesh import (
    Chip,
    Host,
    LinkTier,
    TopologySpec,
    TpuTopology,
    get_topology,
    register_topology,
    TOPOLOGY_REGISTRY,
)
from kubegpu_tpu.topology.slices import (
    Placement,
    enumerate_placements,
    find_free_placements,
    subslice_shapes,
)
from kubegpu_tpu.topology.locality import (
    TrafficModel,
    ici_locality,
    ring_order_for_axis,
    traffic_pairs_for_mesh_axes,
)

__all__ = [
    "Chip",
    "Host",
    "LinkTier",
    "TopologySpec",
    "TpuTopology",
    "get_topology",
    "register_topology",
    "TOPOLOGY_REGISTRY",
    "Placement",
    "enumerate_placements",
    "find_free_placements",
    "subslice_shapes",
    "TrafficModel",
    "ici_locality",
    "ring_order_for_axis",
    "traffic_pairs_for_mesh_axes",
]

"""Open-loop load harness (ISSUE 13): the seeded trace generator and
the goodput-under-SLO scorer.  The harness is the scenario engine every
overload claim rides on, so its own contracts get tier-1 coverage:
same seed ⇒ bit-identical trace AND report, length/prefix/tier
invariants hold for arbitrary seeds, and a run through the REAL engine
is exactly-once with a self-consistent goodput decomposition."""

import jax
import numpy as np
import pytest

from kubegpu_tpu.loadgen import LoadSpec, TierSpec, synth_trace, run_load
from kubegpu_tpu.models import LlamaConfig, llama_init
from kubegpu_tpu.models.serve import ContinuousBatcher
from kubegpu_tpu.obs.metrics import MetricsRegistry

TIERS = (TierSpec("gold", ttft_slo_ticks=8, token_slo_ticks=4.0,
                  share=0.3),
         TierSpec("std", ttft_slo_ticks=30, token_slo_ticks=8.0,
                  share=0.4),
         TierSpec("batch", ttft_slo_ticks=10 ** 6,
                  token_slo_ticks=10 ** 6.0, share=0.3))


def _spec(**kw):
    kw.setdefault("seed", 7)
    kw.setdefault("n_requests", 24)
    kw.setdefault("mean_iat_ticks", 0.9)
    kw.setdefault("burst", True)
    kw.setdefault("prompt_len_max", 8)
    kw.setdefault("out_len_min", 2)
    kw.setdefault("out_len_max", 8)
    kw.setdefault("prefix_share", 0.4)
    kw.setdefault("prefix_len", 4)
    kw.setdefault("vocab", 48)
    kw.setdefault("tiers", TIERS)
    return LoadSpec(**kw)


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny(max_seq_len=64)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _eng(params, cfg, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("stride", 2)
    kw.setdefault("prompt_buckets", (8, 16))
    kw.setdefault("paged", True)
    kw.setdefault("page_size", 8)
    kw.setdefault("total_pages", 12)
    kw.setdefault("prefix_cache", True)
    return ContinuousBatcher(params, cfg, **kw)


class TestSynthTrace:
    def test_same_seed_same_trace_bit_for_bit(self):
        a, b = synth_trace(_spec()), synth_trace(_spec())
        assert len(a) == len(b) == 24
        for x, y in zip(a, b):
            assert x["arrival_tick"] == y["arrival_tick"]
            assert x["max_new"] == y["max_new"]
            assert x["tier"] == y["tier"]
            assert x["tenant"] == y["tenant"]
            assert np.array_equal(x["prompt"], y["prompt"])

    def test_different_seed_different_trace(self):
        a = synth_trace(_spec(seed=7))
        b = synth_trace(_spec(seed=8))
        assert any(
            x["arrival_tick"] != y["arrival_tick"]
            or not np.array_equal(x["prompt"], y["prompt"])
            for x, y in zip(a, b))

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_invariants_hold_for_arbitrary_seeds(self, seed):
        spec = _spec(seed=seed, n_requests=40)
        trace = synth_trace(spec)
        assert len(trace) == 40
        ticks = [e["arrival_tick"] for e in trace]
        assert ticks == sorted(ticks)
        for e in trace:
            assert 1 <= len(e["prompt"]) <= spec.prompt_len_max
            assert spec.out_len_min <= e["max_new"] <= spec.out_len_max
            assert 0 <= e["tier"] < len(spec.tiers)
            assert e["tenant"] in spec.tenants
            assert e["prompt"].dtype == np.int32
            assert all(0 < t < spec.vocab for t in e["prompt"])

    def test_prefix_sharing_actually_shares(self):
        spec = _spec(prefix_share=1.0, n_shared_prefixes=1,
                     prompt_len_mean=2.0, n_requests=40)
        trace = synth_trace(spec)
        long_prompts = [e["prompt"] for e in trace
                        if len(e["prompt"]) > spec.prefix_len]
        assert len(long_prompts) >= 2
        heads = {p[:spec.prefix_len].tobytes() for p in long_prompts}
        assert len(heads) == 1, "prefix_share=1.0 must reuse the prefix"
        # and with sharing off, heads diverge
        off = synth_trace(_spec(prefix_share=0.0, prompt_len_mean=2.0,
                                n_requests=40))
        heads_off = {e["prompt"][:spec.prefix_len].tobytes()
                     for e in off if len(e["prompt"]) > spec.prefix_len}
        assert len(heads_off) > 1


class TestRunLoad:
    def test_exactly_once_and_goodput_decomposition(self, tiny):
        cfg, params = tiny
        reg = MetricsRegistry()
        eng = _eng(params, cfg, metrics=reg)
        trace = synth_trace(_spec())
        rep = run_load(eng, trace, TIERS, tiered=True, metrics=reg)
        assert rep.submitted == len(trace)
        assert rep.lost == 0 and rep.duplicated == 0
        assert rep.completed + rep.failed == rep.submitted
        assert 0.0 <= rep.slo_attainment <= 1.0
        assert rep.goodput_tokens <= rep.total_tokens
        assert rep.goodput_tokens == sum(
            a["goodput_tokens"] for a in rep.per_tier.values())
        assert sum(a["submitted"] for a in rep.per_tier.values()) \
            == rep.submitted
        assert rep.ticks > 0 and rep.goodput_tokens_per_tick == \
            pytest.approx(rep.goodput_tokens / rep.ticks)
        # one record per submitted request, tokens carried for the
        # bit-exactness check the bench builds on
        assert len(rep.records) == rep.submitted
        assert all(rec["tokens"] for rec in rep.records
                   if rec["completed"])
        # publish() exported the gauge surface
        g = reg.snapshot()["gauges"]
        assert g["serve_goodput_tokens_per_tick"] == \
            pytest.approx(rep.goodput_tokens_per_tick, abs=1e-3)
        assert g["serve_slo_attainment"] == \
            pytest.approx(rep.slo_attainment, abs=1e-3)
        assert "serve_slo_attainment_t0" in g
        assert g["serve_goodput_tokens_per_s"] >= 0

    def test_deterministic_twin_same_seed_same_report(self, tiny):
        """The tick-denominated surface is a pure function of the
        seed + engine schedule: two fresh engines over the same trace
        agree bit-for-bit on everything except wall clocks."""
        cfg, params = tiny
        trace = synth_trace(_spec())

        def one():
            rep = run_load(_eng(params, cfg,
                                metrics=MetricsRegistry()),
                           trace, TIERS, tiered=True)
            return rep
        a, b = one(), one()
        da, db = a.as_dict(), b.as_dict()
        da.pop("wall_s"), db.pop("wall_s")
        da.pop("goodput_tokens_per_s"), db.pop("goodput_tokens_per_s")
        assert da == db
        assert [r["tokens"] for r in a.records] == \
            [r["tokens"] for r in b.records]

    def test_fifo_leg_scores_against_intended_tier(self, tiny):
        """tiered=False submits everything at tier 0 but the report
        still buckets by the trace's intended tier, so the A/B legs
        are comparable request for request."""
        cfg, params = tiny
        trace = synth_trace(_spec())
        rep = run_load(_eng(params, cfg, metrics=MetricsRegistry()),
                       trace, TIERS, tiered=False,
                       metrics=MetricsRegistry())
        by_tier = {k: a["submitted"] for k, a in rep.per_tier.items()}
        want = {k: sum(1 for e in trace if e["tier"] == k)
                for k in range(len(TIERS))}
        assert by_tier == want
        assert sum(want.values()) == len(trace)

    def test_stuck_run_raises_not_hangs(self, tiny):
        cfg, params = tiny
        trace = synth_trace(_spec(n_requests=6))
        with pytest.raises(RuntimeError, match="did not go idle"):
            run_load(_eng(params, cfg, metrics=MetricsRegistry()),
                     trace, TIERS, tiered=True, max_ticks=2)


# -- chip-tick cost ledger + harvest (ISSUE 20) -------------------------

class TestCostLedger:
    def test_largest_remainder_conserves_exactly(self):
        from kubegpu_tpu.obs.cost import CostLedger
        import random
        rng = random.Random(20)
        led = CostLedger()
        for _ in range(300):
            n = rng.randrange(0, 5)
            led.charge([("t%d" % rng.randrange(3), rng.randrange(3),
                         rng.randrange(0, 7)) for _ in range(n)],
                       rng.randrange(0, 30))
        assert led.conserved
        assert sum(led.by_key.values()) == led.busy_chip_ticks

    def test_prorata_by_work_units(self):
        from kubegpu_tpu.obs.cost import CostLedger
        led = CostLedger()
        led.charge([("a", 0, 3), ("b", 0, 1)], 4)
        assert led.by_key == {"a:t0": 3, "b:t0": 1}

    def test_zero_work_splits_equally(self):
        from kubegpu_tpu.obs.cost import CostLedger
        led = CostLedger()
        led.charge([("a", 0, 0), ("b", 0, 0)], 5)
        assert led.busy_chip_ticks == 5
        assert sorted(led.by_key.values()) == [2, 3]

    def test_remainder_tie_break_is_stable(self):
        from kubegpu_tpu.obs.cost import CostLedger
        a = CostLedger()
        a.charge([("x", 0, 1), ("y", 0, 1), ("z", 0, 1)], 2)
        b = CostLedger()
        b.charge([("x", 0, 1), ("y", 0, 1), ("z", 0, 1)], 2)
        assert a.by_key == b.by_key
        assert sum(a.by_key.values()) == 2

    def test_merge_accumulates(self):
        from kubegpu_tpu.obs.cost import CostLedger
        a, b = CostLedger(), CostLedger()
        a.charge([("a", 0, 1)], 3)
        b.charge([("a", 0, 1), ("b", 1, 1)], 4)
        a.merge(b)
        assert a.busy_chip_ticks == 7
        assert a.conserved
        assert a.by_key["a:t0"] == 5 and a.by_key["b:t1"] == 2

    def test_publish_emits_total_and_suffixed_gauges(self):
        from kubegpu_tpu.obs.cost import CostLedger
        led = CostLedger()
        led.charge([("acme", 1, 2), ("blue", 0, 2)], 10)
        reg = MetricsRegistry()
        led.publish(reg)
        g = reg.snapshot()["gauges"]
        assert g["serve_chip_ticks_total"] == 10.0
        assert g["serve_chip_ticks_total_acme_t1"] == 5.0
        assert g["serve_chip_ticks_total_blue_t0"] == 5.0


class TestRunLoadCostHarvest:
    def test_engine_ledger_lands_in_report(self, tiny):
        cfg, params = tiny
        reg = MetricsRegistry()
        eng = _eng(params, cfg, metrics=reg)
        trace = synth_trace(_spec(n_requests=8,
                                  tenants=("acme", "blue")))
        rep = run_load(eng, trace, TIERS, max_ticks=600, metrics=reg)
        assert rep.completed == 8
        # the engine charged tp(=1) chips per busy tick, exactly
        assert rep.busy_chip_ticks == eng.busy_ticks
        assert sum(rep.cost_by_key.values()) == rep.busy_chip_ticks
        assert rep.busy_chip_ticks > 0
        cs = rep.cost_summary()
        assert cs["attributed_chip_ticks"] == rep.busy_chip_ticks
        assert {k.split(":")[0] for k in cs["per_key"]} \
            <= {"acme", "blue"}
        # publish() mirrors the grand total onto the registry
        g = reg.snapshot()["gauges"]
        assert g["serve_chip_ticks_total"] == float(rep.busy_chip_ticks)
        assert rep.as_dict()["busy_chip_ticks"] == rep.busy_chip_ticks

"""ISSUE 20 flight recorder, store half: SeriesStore ring semantics
(gauges verbatim, counters as per-tick deltas, histogram percentile
tracks), windowed queries, series END at the gauge-delete choke point,
and the Perfetto counter-track merge.
"""

import json

import pytest

from kubegpu_tpu.obs.metrics import LiveBytesTracker, MetricsRegistry
from kubegpu_tpu.obs.spans import Tracer, validate_chrome_trace
from kubegpu_tpu.obs.tsdb import SeriesStore


def test_capacity_validates():
    with pytest.raises(ValueError):
        SeriesStore(MetricsRegistry(), capacity=0)


def test_gauges_sample_verbatim_counters_as_deltas():
    reg = MetricsRegistry()
    store = SeriesStore(reg)
    for t in range(5):
        reg.set_gauge("allocation_locality", 0.1 * t)
        reg.inc("gangs_scheduled", 2)
        store.sample(t)
    assert store.series("allocation_locality") == [
        (t, pytest.approx(0.1 * t)) for t in range(5)]
    # the counter went 2,4,6,8,10 — the series stores the deltas
    assert store.series("gangs_scheduled") == [(t, 2.0) for t in range(5)]
    assert store.latest("gangs_scheduled") == 2.0


def test_histogram_percentile_tracks():
    reg = MetricsRegistry()
    store = SeriesStore(reg)
    for v in (1.0, 2.0, 100.0):
        reg.observe("serve_ttft_ms", v)
    store.sample(0)
    assert "serve_ttft_ms_p50" in store.names()
    assert "serve_ttft_ms_p99" in store.names()
    assert store.latest("serve_ttft_ms_p99") >= store.latest(
        "serve_ttft_ms_p50")


def test_percentile_tracks_deterministic_at_scale():
    # the seeded histogram reservoir replays identically, so the p50
    # TRACK two identically-driven stores record is bit-identical even
    # past the reservoir cap (determinism is what the alert gates on)
    def drive():
        reg = MetricsRegistry()
        store = SeriesStore(reg)
        for t in range(20):
            for i in range(300):
                reg.observe("serve_ttft_ms", float((t * 300 + i) % 997))
            store.sample(t)
        return store.series("serve_ttft_ms_p50"), store.series(
            "serve_ttft_ms_p99")
    assert drive() == drive()


def test_ring_capacity_bounds_history():
    reg = MetricsRegistry()
    store = SeriesStore(reg, capacity=8)
    for t in range(100):
        reg.set_gauge("allocation_locality", float(t))
        store.sample(t)
    hist = store.series("allocation_locality")
    assert len(hist) == 8
    assert hist[0] == (92, 92.0)
    assert hist[-1] == (99, 99.0)


def test_windowed_queries():
    reg = MetricsRegistry()
    store = SeriesStore(reg)
    for t in range(10):
        reg.inc("gangs_scheduled", 4 if t >= 6 else 0)
        reg.set_gauge("allocation_locality", float(t))
        store.sample(t)
    # (end-window, end] window: 4 deltas of 4 over the last 8 ticks
    assert store.rate("gangs_scheduled", 8) == pytest.approx(16 / 8)
    assert store.rate("gangs_scheduled", 4) == pytest.approx(16 / 4)
    assert store.avg("allocation_locality", 4) == pytest.approx(7.5)
    assert store.max("allocation_locality", 4) == 9.0
    # explicit end_tick rewinds the window
    assert store.rate("gangs_scheduled", 4, end_tick=5) == 0.0
    assert store.max("allocation_locality", 3, end_tick=5) == 5.0
    # unknown series measure empty, not KeyError
    assert store.values("nope", 8) == []
    assert store.rate("nope", 8) == 0.0
    assert store.avg("nope", 8) == 0.0
    assert store.max("nope", 8) == 0.0


def test_series_ends_at_gauge_delete_choke_point():
    reg = MetricsRegistry()
    store = SeriesStore(reg)
    reg.set_gauge("serve_replica_queue_depth_r0", 3.0)
    store.sample(0)
    reg.delete_gauge("serve_replica_queue_depth_r0")
    assert store.ended("serve_replica_queue_depth_r0")
    # idempotent re-delete (the pool harvest loop re-deletes) is a
    # no-op, and a LATER same-named gauge cannot resurrect the series
    reg.delete_gauge("serve_replica_queue_depth_r0")
    reg.set_gauge("serve_replica_queue_depth_r0", 99.0)
    store.sample(1)
    assert store.series("serve_replica_queue_depth_r0") == [(0, 3.0)]


def test_delete_of_unknown_gauge_does_not_end_future_series():
    reg = MetricsRegistry()
    store = SeriesStore(reg)
    # deleting a name the store never sampled must not pre-poison it
    reg.delete_gauge("serve_replica_queue_depth_r7")
    reg.set_gauge("serve_replica_queue_depth_r7", 1.0)
    store.sample(0)
    assert store.series("serve_replica_queue_depth_r7") == [(0, 1.0)]


def test_live_bytes_tracker_peak_series_matches_tracker():
    reg = MetricsRegistry()
    store = SeriesStore(reg)
    hbm = LiveBytesTracker(reg)
    for t, b in enumerate((100, 900, 400, 700)):
        hbm.sample(b)
        store.sample(t)
    peaks = [v for _, v in store.series("serve_hbm_peak_bytes")]
    assert peaks == [100.0, 900.0, 900.0, 900.0]
    assert store.latest("serve_hbm_peak_bytes") == hbm.peak
    assert store.max("serve_hbm_pool_bytes", 4) == 900.0


def test_counter_events_merge_into_chrome_trace():
    reg = MetricsRegistry()
    store = SeriesStore(reg)
    for t in range(3):
        reg.set_gauge("allocation_locality", float(t))
        store.sample(t)
    tracer = Tracer()
    with tracer.span("engine.tick"):
        pass
    merged = store.merge_chrome_trace(tracer.to_chrome_trace())
    events = validate_chrome_trace(merged)
    cs = [e for e in events if e["ph"] == "C"]
    assert len(cs) == 3
    # counters anchor at the earliest span ts and stay sorted
    span_ts = min(e["ts"] for e in events if e["ph"] != "C")
    assert min(e["ts"] for e in cs) == span_ts
    ts = [e["ts"] for e in events]
    assert ts == sorted(ts)
    assert all(isinstance(e["args"]["value"], float) for e in cs)


def test_merge_rejects_bad_trace_doc():
    store = SeriesStore(MetricsRegistry())
    with pytest.raises(ValueError):
        store.merge_chrome_trace(json.dumps({"traceEvents": "nope"}))


def test_sample_without_registry_raises():
    store = SeriesStore()
    with pytest.raises(ValueError):
        store.sample(0)

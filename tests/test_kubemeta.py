"""Annotation round-trip + fake apiserver tests (SURVEY.md §5: the
reference's ``kubeinterface`` tests were NodeInfo → annotation → NodeInfo
equality; same shape here, plus control-plane semantics)."""

import threading

import pytest

from kubegpu_tpu.kubemeta import (
    Allocation,
    AllocatedChip,
    Conflict,
    ContainerSpec,
    FakeApiServer,
    GangSpec,
    Node,
    NotFound,
    ObjectMeta,
    Pod,
    PodPhase,
    PodSpec,
    ResourceRequests,
    advertise_on_node,
    allocation_from_annotation,
    allocation_to_annotation,
    node_advertisement,
    node_advertisement_from_annotation,
    node_advertisement_to_annotation,
    pod_allocation,
    pod_gang_spec,
    pod_mesh_axes,
    set_pod_allocation,
    set_pod_gang,
    set_pod_mesh_axes,
)
from kubegpu_tpu.tpuplugin import MockBackend, mock_cluster


def make_pod(name="p0", chips=1, millitpu=0) -> Pod:
    return Pod(
        metadata=ObjectMeta(name=name),
        spec=PodSpec(containers=[ContainerSpec(
            name="main",
            resources=ResourceRequests(tpu_chips=chips, millitpu=millitpu))]),
    )


class TestMockBackend:
    def test_discover_v4_8(self):
        adv = MockBackend("v4-8").discover()
        assert adv.num_chips == 4
        assert adv.mesh_shape == (2, 2, 1)
        assert {c.coord for c in adv.chips} == {
            (0, 0, 0), (0, 1, 0), (1, 0, 0), (1, 1, 0)}

    def test_discover_v5e16_host2(self):
        adv = MockBackend("v5e-16", host_id=2).discover()
        assert adv.host_id == 2
        assert adv.num_chips == 4
        # host 2's block origin in row-major host order: (2,0)
        assert {c.coord for c in adv.chips} == {
            (2, 0, 0), (2, 1, 0), (3, 0, 0), (3, 1, 0)}

    def test_mock_cluster_node_count(self):
        backends = mock_cluster(["v5e-16", "v4-8"])
        assert len(backends) == 5  # 4 hosts + 1 host
        assert len({b.slice_id for b in backends}) == 2

    def test_bad_host_id(self):
        with pytest.raises(ValueError):
            MockBackend("v4-8", host_id=1)

    def test_allocate_env(self):
        b = MockBackend("v5e-16", host_id=1)
        adv = b.discover()
        env = b.allocate_env(list(adv.chips), worker_id=1, num_workers=4,
                             coordinator_address="10.0.0.1:8476",
                             worker_hostnames=["h0", "h1", "h2", "h3"])
        assert env["TPU_VISIBLE_CHIPS"] == "0,1,2,3"
        assert env["TPU_WORKER_ID"] == "1"
        assert env["JAX_COORDINATOR_ADDRESS"] == "10.0.0.1:8476"
        assert env["JAX_NUM_PROCESSES"] == "4"

    def test_unhealthy_chip_marked(self):
        adv = MockBackend("v4-8", unhealthy_chips={2}).discover()
        assert [c.healthy for c in adv.chips] == [True, True, False, True]


class TestCodecRoundTrips:
    def test_node_advertisement_roundtrip(self):
        adv = MockBackend("v5e-64", host_id=7).discover()
        payload = node_advertisement_to_annotation(adv)
        back = node_advertisement_from_annotation(payload)
        assert back == adv

    def test_allocation_roundtrip(self):
        alloc = Allocation(
            node_name="n0", slice_id="v5e-16-slice-0",
            chips=[AllocatedChip(coord=(1, 2, 0), local_index=3,
                                 millichips=1000)],
            worker_id=2, num_workers=4,
            coordinator_address="10.0.0.1:8476",
            worker_hostnames=["h0", "h1", "h2", "h3"],
            gang_name="job-a")
        back = allocation_from_annotation(allocation_to_annotation(alloc))
        assert back == alloc

    def test_pod_annotation_helpers(self):
        pod = make_pod()
        assert pod_allocation(pod) is None
        alloc = Allocation(node_name="n0", slice_id="s0",
                           chips=[AllocatedChip((0, 0, 0), 0, 500)])
        set_pod_allocation(pod, alloc)
        assert pod_allocation(pod) == alloc

    def test_gang_roundtrip(self):
        pod = make_pod()
        assert pod_gang_spec(pod) is None
        set_pod_gang(pod, GangSpec(name="job-a", size=4, index=3))
        g = pod_gang_spec(pod)
        assert (g.name, g.size, g.index) == ("job-a", 4, 3)

    def test_mesh_axes_roundtrip_preserves_order(self):
        pod = make_pod()
        set_pod_mesh_axes(pod, {"dp": 2, "tp": 8})
        assert list(pod_mesh_axes(pod).items()) == [("dp", 2), ("tp", 8)]

    def test_node_annotation_attach(self):
        node = Node(metadata=ObjectMeta(name="n0"))
        assert node_advertisement(node) is None
        adv = MockBackend("v4-8").discover()
        advertise_on_node(node, adv)
        assert node_advertisement(node) == adv


class TestFakeApiServer:
    def test_create_get_list(self):
        api = FakeApiServer()
        api.create("Pod", make_pod("a"))
        api.create("Pod", make_pod("b"))
        assert api.get("Pod", "a").name == "a"
        assert {p.name for p in api.list("Pod")} == {"a", "b"}

    def test_create_duplicate_conflicts(self):
        api = FakeApiServer()
        api.create("Pod", make_pod("a"))
        with pytest.raises(Conflict):
            api.create("Pod", make_pod("a"))

    def test_get_missing(self):
        api = FakeApiServer()
        with pytest.raises(NotFound):
            api.get("Pod", "nope")

    def test_mutating_copy_does_not_leak(self):
        api = FakeApiServer()
        api.create("Pod", make_pod("a"))
        got = api.get("Pod", "a")
        got.metadata.annotations["x"] = "y"
        assert "x" not in api.get("Pod", "a").metadata.annotations

    def test_optimistic_concurrency(self):
        api = FakeApiServer()
        created = api.create("Pod", make_pod("a"))
        stale = api.get("Pod", "a")
        api.update("Pod", created)  # bumps rv
        with pytest.raises(Conflict):
            api.update("Pod", stale)

    def test_patch_annotations(self):
        api = FakeApiServer()
        api.create("Node", Node(metadata=ObjectMeta(name="n0")))
        api.patch_annotations("Node", "n0", {"k": "v"})
        assert api.get("Node", "n0").metadata.annotations["k"] == "v"

    def test_bind_and_phase(self):
        api = FakeApiServer()
        api.create("Pod", make_pod("a"))
        api.bind_pod("a", "n0")
        pod = api.get("Pod", "a")
        assert pod.spec.node_name == "n0"
        assert pod.status.phase == PodPhase.SCHEDULED

    def test_watch_events(self):
        api = FakeApiServer()
        events = []
        unsub = api.watch(events.append)
        api.create("Pod", make_pod("a"))
        api.bind_pod("a", "n0")
        api.delete("Pod", "a")
        assert [e.type for e in events] == ["ADDED", "MODIFIED", "DELETED"]
        unsub()
        api.create("Pod", make_pod("b"))
        assert len(events) == 3

    def test_thread_stress(self):
        """SURVEY.md §6 race-detection requirement: concurrent patchers must
        not lose updates or corrupt state."""
        api = FakeApiServer()
        api.create("Node", Node(metadata=ObjectMeta(name="n0")))
        n_threads, n_iters = 8, 50
        def worker(tid):
            for i in range(n_iters):
                api.patch_annotations("Node", "n0", {f"t{tid}-{i}": "1"})
        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        ann = api.get("Node", "n0").metadata.annotations
        assert len(ann) == n_threads * n_iters

    def test_resource_requests_validation(self):
        with pytest.raises(ValueError):
            ResourceRequests(tpu_chips=1, millitpu=500)
        with pytest.raises(ValueError):
            GangSpec(name="g", size=2, index=2)

"""Sharding utilities: PartitionSpec trees → NamedShardings, activation
constraints that degrade gracefully off-mesh."""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _fix_axis(a, names: set[str]):
    if a is None:
        return None
    if isinstance(a, (tuple, list)):
        kept = tuple(x for x in a if x in names)
        return kept if kept else None
    return a if a in names else None


def fit_spec(mesh: Mesh, spec: P) -> P:
    """Drop axis names ``mesh`` doesn't have, so one rule set serves
    dp-only and dp×fsdp×tp meshes alike."""
    names = set(mesh.axis_names)
    return P(*(_fix_axis(a, names) for a in spec))


def named_sharding_tree(mesh: Mesh, spec_tree):
    """Map a pytree of PartitionSpec to NamedSharding over ``mesh``."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, fit_spec(mesh, s)), spec_tree,
        is_leaf=lambda x: isinstance(x, P))


def constrain(x: jax.Array, mesh: Mesh | None, *spec) -> jax.Array:
    """``with_sharding_constraint`` against ``mesh``; identity when no mesh
    is in play (single-device tests, the driver's single-chip entry)."""
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, fit_spec(mesh, P(*spec))))


def device_put_tree(mesh: Mesh, tree, spec_tree):
    """``device_put`` a pytree against a matching PartitionSpec tree.

    The serving engine lays out its big state ONCE at construction (the
    page pool over KV heads, full and draft weights megatron-style per
    ``_serve_param_specs``) so every per-tick executable sees inputs
    already placed per its ``in_specs`` — no per-dispatch resharding.
    QTensor-style container leaves work transparently: both ``tree``
    and ``spec_tree`` carry them as pytree nodes, so values and scales
    pick up their own specs in lockstep."""
    sharding = jax.tree.map(
        lambda s: NamedSharding(mesh, fit_spec(mesh, s)), spec_tree,
        is_leaf=lambda x: isinstance(x, P))
    return jax.device_put(tree, sharding)


def sharded_jit(f, mesh: Mesh, in_specs, out_specs, donate=()):
    """``compat_shard_map`` + ``jax.jit`` with buffer donation, in one
    call — the wrapping every mesh-native serving executable repeats by
    hand (an explicit jitted def whose only job is naming the donated
    argument).  ``donate`` names arguments of ``f`` whose buffers the
    caller rebinds every dispatch (the page pool); jit resolves the
    names against ``f``'s own signature through ``__wrapped__``."""
    import functools

    mapped = compat_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check=False)

    @functools.wraps(f)
    def call(*args):
        return mapped(*args)

    return jax.jit(call, donate_argnames=tuple(donate))


def compat_shard_map(f, mesh: Mesh, in_specs, out_specs, check=False):
    """shard_map across the jax API generations this repo meets: the
    driver's image has ``jax.shard_map`` (replication checking spelled
    ``check_vma``), older images only ``jax.experimental.shard_map``
    (spelled ``check_rep``).  ``check=False`` is required wherever a
    pallas_call runs inside the mapped body — pallas has no
    replication rule on either generation."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        try:
            return sm(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=check)
        except TypeError:   # jax.shard_map without the vma keyword
            return sm(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check)
